#!/usr/bin/env python
"""Compare a fresh bench.py run against the newest committed BENCH_r*.json.

    python bench.py > /tmp/fresh.json
    python scripts/bench_compare.py /tmp/fresh.json

Flags a regression when a named lane moves more than ``--threshold``
(default 10%) in its bad direction — throughput/utilization lanes down,
latency/waste lanes up — and exits nonzero so a CI step can gate on it.

Input formats (both sides accept either):
  * a plain bench.py result dict, or
  * a committed driver artifact ``{n, cmd, rc, tail, parsed}`` — the
    result is ``parsed`` when the driver captured it, else lane values
    are recovered from the ``tail`` text (the tail may truncate the
    JSON's head, so this regexes ``"lane": number`` pairs rather than
    parsing).

Renamed lanes are followed through ``ALIASES`` (e.g. the honest
``adaptive_batch16_pipeline_util`` reads old baselines' mislabelled
``adaptive_batch16_mfu``), so a rename never fakes a vanished lane.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, Optional, Tuple

#: named lanes -> direction: +1 higher-is-better, -1 lower-is-better.
#: Curated, not exhaustive: these are the headline lanes CHANGES/ROADMAP
#: quote; one-off diagnostic fields move too much run-to-run to gate on.
LANES: Dict[str, int] = {
    # headline lanes (present since the earliest artifacts)
    "fps_median": +1,
    "mfu": +1,
    "vs_baseline": +1,
    "p50_invoke_us": -1,
    "composite_lstm_query_fps_median": +1,
    "adaptive_batch16_fps_median": +1,
    "adaptive_batch16_pipeline_util": +1,
    "transformer_prefill_b64_tokens_per_s": +1,
    "transformer_roofline_tokens_per_s": +1,
    "transformer_roofline_mfu": +1,
    "transformer_roofline_w8a8_tokens_per_s": +1,
    "transformer_roofline_w8a8_int8_util": +1,
    "lm_serving_continuous_tokens_per_s": +1,
    "lm_serving_speedup": +1,
    "lm_serving_spec_tokens_per_s": +1,
    "composite_roundtrip_p50_us": -1,
    "transformer_roofline_step_s_median": -1,
    "lm_serving_continuous_waste_frac": -1,
    "multiplex_fps_median": +1,
    "multiplex_pipeline_util": +1,
    # per-tenant goodput under the 8-tenant mix (obs.slo accounting):
    # deadline-met work as a fraction of all work, overall and for the
    # deadline-tight tenant — a scheduler "win" that starves the tight
    # tenant regresses here even when occupancy improves
    "multiplex_goodput_ratio": +1,
    "multiplex_goodput_tight_ratio": +1,
    # disaggregated prefill/decode serving (serving/disagg.py): the
    # absolute rate, the cost of the wire hop against the same engine
    # unified, and the prefix reuse the radix digest router exists for
    "disagg_serving_tokens_per_s": +1,
    "disagg_serving_relative": +1,
    "disagg_serving_prefix_hit_rate": +1,
    "lm_serving_paged_prefix_hit_rate": +1,
    # epilogue fusion (ops/epilogue.py): post-filter chains compiled into
    # the filter's jit — fewer dispatches per frame is the tentpole claim
    "epilogue_fusion_fps_median": +1,
    "epilogue_fusion_speedup": +1,
    "epilogue_fusion_dispatches_per_frame": -1,
    "epilogue_fusion_dispatch_ratio": +1,
    # autotuner (tune/): a warm store must answer without sweeping
    # (0 is the contract, any growth is a persistence regression), and
    # the tuner's flash-block pick must match or beat the FLASH_TUNE_r05
    # hand sweep it replaces (ratio >= 1)
    "autotune_warm_sweeps": -1,
    "autotune_flash_vs_hand": +1,
    "autotune_flash_tuned_ms": -1,
    # fleet autoscaling (fleet/): live session migration must stay
    # cheap (wall seconds per migrated session, end to end including
    # the KV-page ship), and goodput after halving the fleet under
    # load must hold against the unhalved run (ratio >= the SLO floor
    # — streams surviving a scale-in is the tentpole claim)
    "fleet_migration_seconds": -1,
    "fleet_halved_goodput_ratio": +1,
    # crash restore (fleet/checkpoint.py): restoring a killed worker's
    # sessions must stay fast (re-pin + checkpoint_send + page splice,
    # end to end) and warm (post-restore prompt tokens served from the
    # restored prefix pages — a re-prefill fallback scores ~0 here)
    "fleet_restore_seconds": -1,
    "fleet_restore_warm_ratio": +1,
    "fleet_checkpoint_overhead_ratio": +1,
    # incident diagnostics (obs/diag/): freezing a full debug bundle
    # must stay cheap enough to fire from a burn alert in production,
    # and the critical-path sweep must keep attributing root-span time
    # to real segments (a coverage drop means the taps stopped seeing
    # the latency they are supposed to explain)
    "diag_capture_seconds": -1,
    "diag_critpath_coverage_ratio": +1,
    # data-plane quality (obs/quality/): the instrumented pipeline must
    # keep >= 95% of the uninstrumented rate (the <= 5% overhead
    # acceptance gate rides this ratio), and a frozen-baseline
    # distribution shift must breach both drift windows quickly
    "quality_overhead_ratio": +1,
    "quality_drift_detect_seconds": -1,
}

#: absolute floors, gated on the FRESH run independently of the
#: baseline — a drifting baseline must never grandfather a breach.
#: fleet_checkpoint_overhead_ratio is the checkpoint daemon's
#: acceptance gate: serving throughput with a checkpoint pass per
#: request holds >= 95% of the uncheckpointed rate.
FLOORS: Dict[str, float] = {
    "fleet_checkpoint_overhead_ratio": 0.95,
}

#: current lane name -> names it may carry in OLDER baselines
ALIASES: Dict[str, Tuple[str, ...]] = {
    "adaptive_batch16_pipeline_util": ("adaptive_batch16_mfu",),
    # the multi-tenant scheduler lane supersedes the serial utilization
    # number: older baselines carry only the 1-pipeline figure, and the
    # whole point of sched.DeviceEngine is the delta against it
    "multiplex_pipeline_util": ("adaptive_batch16_pipeline_util",
                                "adaptive_batch16_mfu"),
}

_NUM_RE = re.compile(r'"([A-Za-z0-9_]+)":\s*(-?\d+(?:\.\d+)?(?:[eE][-+]?\d+)?)')


def _lanes_from_tail(tail: str) -> Dict[str, float]:
    """Recover scalar lanes from a (possibly head-truncated) result
    tail. Last occurrence wins — matches dict-update semantics."""
    return {k: float(v) for k, v in _NUM_RE.findall(tail or "")}


def load_lanes(path: str) -> Dict[str, float]:
    """Scalar lane values from a bench result file (plain or wrapped)."""
    with open(path, "r", encoding="utf-8") as fp:
        doc = json.load(fp)
    if isinstance(doc, dict) and "tail" in doc and "rc" in doc:  # wrapped
        parsed = doc.get("parsed")
        doc = parsed if isinstance(parsed, dict) \
            else _lanes_from_tail(doc.get("tail", ""))
    if not isinstance(doc, dict):
        raise ValueError(f"{path}: not a bench result dict")
    return {k: float(v) for k, v in doc.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)}


def newest_baseline(root: str) -> Optional[str]:
    """Newest committed BENCH_r*.json by round number (name sort is the
    commit order: BENCH_r01 < BENCH_r02 < ...)."""
    paths = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    return paths[-1] if paths else None


def lane_value(lanes: Dict[str, float], name: str) -> Optional[float]:
    if name in lanes:
        return lanes[name]
    for alias in ALIASES.get(name, ()):
        if alias in lanes:
            return lanes[alias]
    return None


def compare(fresh: Dict[str, float], base: Dict[str, float],
            threshold: float, lane_names) -> Tuple[list, list, list]:
    """-> (regressions, ok, skipped) rows of (lane, base, fresh, delta)."""
    regressions, ok, skipped = [], [], []
    for name in lane_names:
        sign = LANES.get(name, +1)
        # aliases resolve the BASELINE side only: a fresh artifact may
        # legitimately carry both a lane and the older lane it
        # supersedes (multiplex_pipeline_util next to
        # adaptive_batch16_pipeline_util) — the old value must never
        # stand in for a missing new reading
        b, f = lane_value(base, name), fresh.get(name)
        if b is None or f is None or b == 0:
            skipped.append((name, b, f, None))
            continue
        delta = (f - b) / abs(b)
        row = (name, b, f, delta)
        # bad direction: down for higher-is-better, up for lower-is-better
        if sign * delta < -threshold if sign > 0 else delta > threshold:
            regressions.append(row)
        else:
            ok.append(row)
    return regressions, ok, skipped


def main(argv=None) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ap = argparse.ArgumentParser(
        description="flag >threshold regressions vs the newest committed "
                    "BENCH_r*.json")
    ap.add_argument("fresh", help="fresh bench result JSON (plain bench.py "
                                  "stdout or a wrapped driver artifact)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON (default: newest BENCH_r*.json in "
                         "the repo root)")
    ap.add_argument("--threshold", type=float, default=0.10, metavar="FRAC",
                    help="regression threshold as a fraction (default 0.10)")
    ap.add_argument("--lanes", default=None,
                    help="comma-separated lane names (default: the curated "
                         "named-lane set)")
    args = ap.parse_args(argv)

    baseline = args.baseline or newest_baseline(repo_root)
    if baseline is None:
        print("bench_compare: no BENCH_r*.json baseline found", file=sys.stderr)
        return 2
    try:
        fresh = load_lanes(args.fresh)
        base = load_lanes(baseline)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"bench_compare: {e}", file=sys.stderr)
        return 2
    lane_names = [ln.strip() for ln in args.lanes.split(",") if ln.strip()] \
        if args.lanes else list(LANES)
    regressions, ok, skipped = compare(fresh, base, args.threshold, lane_names)
    floor_breaches = [(name, FLOORS[name], fresh[name])
                      for name in sorted(FLOORS)
                      if name in fresh and fresh[name] < FLOORS[name]]

    print(f"baseline: {baseline}")
    for name, b, f, d in ok:
        arrow = "+" if d >= 0 else ""
        print(f"  ok        {name}: {b:g} -> {f:g} ({arrow}{d * 100:.1f}%)")
    for name, b, f, _ in skipped:
        which = "both" if b is None and f is None else \
            ("baseline" if b is None else "fresh")
        print(f"  skipped   {name}: missing in {which}")
    for name, b, f, d in regressions:
        print(f"  REGRESSED {name}: {b:g} -> {f:g} ({d * 100:+.1f}%, "
              f"threshold {args.threshold * 100:.0f}%)")
    for name, fl, f in floor_breaches:
        print(f"  FLOOR     {name}: {f:g} below absolute floor {fl:g}")
    if regressions or floor_breaches:
        print(f"bench_compare: {len(regressions)} lane(s) regressed, "
              f"{len(floor_breaches)} floor breach(es)", file=sys.stderr)
        return 1
    print(f"bench_compare: {len(ok)} lane(s) within threshold, "
          f"{len(skipped)} skipped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
