#!/usr/bin/env python
"""Compatibility shim: the metric/span/event/placement naming lint now
lives in the nnslint registry (scripts/nnslint/naming_compat.py, run
as the ``naming/*`` rule family by ``python -m scripts.nnslint``).

This path keeps the original module API — ``check``, ``check_names``,
``check_labels``, ``check_spans``, ``check_events``,
``check_resilience``, ``check_kv``, ``check_router``, the ``iter_*``
helpers, the convention constants, and ``main`` — so
tests/test_metric_names.py and any external callers keep working
unchanged.
"""

from __future__ import annotations

import sys
from pathlib import Path

# the shim is imported both as a bare module (tests put scripts/ on
# sys.path) and run as a script; either way the repo root must be
# importable for the scripts.nnslint package
_REPO_ROOT = str(Path(__file__).resolve().parents[1])
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

from scripts.nnslint.naming_compat import *  # noqa: F401,F403,E402
from scripts.nnslint.naming_compat import (  # noqa: F401,E402 — underscore + explicit names star-import misses
    _CALL_RE, _EVENT_CALL_RE, _EVENT_NAME_RE, _NAME_RE, _SPAN_CALL_RE,
    _SPAN_NAME_RE, _where, main)

if __name__ == "__main__":
    sys.exit(main())
