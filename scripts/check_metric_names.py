#!/usr/bin/env python
"""Lint registered metric names against the repo naming convention.

Convention (docs/observability.md): every metric is
``nnstpu_<layer>_<name>_<unit>`` with

  * layer  in {pipeline, query, serving},
  * counters    ending in ``_total``,
  * histograms  ending in ``_seconds``,
  * gauges      ending in one of ``_depth`` / ``_slots`` / ``_bytes``.

The check greps source for literal first arguments of
``.counter(...)`` / ``.gauge(...)`` / ``.histogram(...)`` registry
calls, so drift fails CI (wired as a tier-1 test:
tests/test_metric_names.py) the moment an off-convention name lands.
Registrations built from non-literal names are invisible to this lint
— keep names literal.

Exit 0 when clean; exit 1 listing every violation.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[1]
SOURCE_ROOT = REPO_ROOT / "nnstreamer_tpu"

LAYERS = ("pipeline", "query", "serving")
UNIT_BY_TYPE = {
    "counter": ("total",),
    "histogram": ("seconds",),
    "gauge": ("depth", "slots", "bytes"),
}

#: reg.counter("name"... — dotted call so plain functions named e.g.
#: ``gauge()`` elsewhere don't false-positive
_CALL_RE = re.compile(
    r"\.(counter|gauge|histogram)\(\s*[\"']([^\"']+)[\"']")

_NAME_RE = re.compile(
    r"^nnstpu_(?P<layer>[a-z0-9]+)_(?P<body>[a-z0-9_]+)_(?P<unit>[a-z0-9]+)$")


def iter_registrations(root: Path = SOURCE_ROOT):
    """Yield (path, lineno, metric_type, name) for every literal-name
    registry call under ``root``."""
    for path in sorted(root.rglob("*.py")):
        text = path.read_text(encoding="utf-8")
        # whole-file scan: registrations routinely wrap the name onto
        # the line after the open paren (\s* spans newlines)
        for m in _CALL_RE.finditer(text):
            lineno = text.count("\n", 0, m.start()) + 1
            yield path, lineno, m.group(1), m.group(2)


def check(root: Path = SOURCE_ROOT):
    """Return a list of violation strings (empty = clean)."""
    problems = []
    found = 0
    for path, lineno, mtype, name in iter_registrations(root):
        found += 1
        rel = path.relative_to(REPO_ROOT) if REPO_ROOT in path.parents \
            else path
        where = f"{rel}:{lineno}"
        m = _NAME_RE.match(name)
        if m is None:
            problems.append(
                f"{where}: {name!r} does not match "
                "nnstpu_<layer>_<name>_<unit>")
            continue
        if m.group("layer") not in LAYERS:
            problems.append(
                f"{where}: {name!r} layer {m.group('layer')!r} not in "
                f"{LAYERS}")
        units = UNIT_BY_TYPE[mtype]
        if m.group("unit") not in units:
            problems.append(
                f"{where}: {name!r} is a {mtype} but unit "
                f"{m.group('unit')!r} not in {units}")
    if found == 0:
        problems.append(
            f"no metric registrations found under {root} — "
            "lint regex out of sync with the registry API?")
    return problems


def main() -> int:
    problems = check()
    if problems:
        for p in problems:
            print(p, file=sys.stderr)
        print(f"{len(problems)} metric naming violation(s)",
              file=sys.stderr)
        return 1
    n = sum(1 for _ in iter_registrations())
    print(f"metric names OK ({n} registrations checked)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
