"""Repo tooling package — makes ``python -m scripts.nnslint`` work."""
