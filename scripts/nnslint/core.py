"""nnslint core: findings, rule registry, suppression parsing, engine.

The codebase grew a set of invariants that were, until this module,
enforced only by convention: lock-guarded attributes, daemon/joined
worker threads, never-raise wire boundaries, zero-overhead hook gates,
JAX tracing purity, wire-protocol completeness, and telemetry naming.
nnslint turns each into a registered :class:`Rule` that runs over the
parsed AST of every source file, so the invariant fails tier-1 CI the
moment a violation lands instead of waiting for a reviewer (or an
outage) to notice.

Vocabulary:

* **Finding** — one violation: ``(rule, path, line, message, anchor)``.
  The ``anchor`` is a short, line-number-free symbol (attribute name,
  function name, format string) so baseline entries survive unrelated
  line drift.
* **Rule** — a checker registered under ``<family>/<name>``. Per-file
  rules implement ``visit_file(ctx)``; cross-file rules (wire
  completeness, naming placement) implement ``finalize(ctxs)`` which
  runs once after every file has been parsed.
* **Suppression** — ``# nnslint: disable=<rule>[,<rule>…]`` on the
  finding line or the line directly above it. ``<rule>`` may be a full
  id, a bare family (``concurrency``), or ``all``. Suppressions are
  for *reviewed* exceptions (happens-before init, parsing a foreign
  protocol); new code should not need them.
* **Baseline** — grandfathered findings committed in
  ``scripts/nnslint/baseline.json`` (see baseline.py); the engine
  subtracts them so the tree lints clean while the debt is paid down.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

REPO_ROOT = Path(__file__).resolve().parents[2]
#: the tree linted by default (and by the tier-1 test)
DEFAULT_ROOT = REPO_ROOT / "nnstreamer_tpu"

_SUPPRESS_RE = re.compile(r"#\s*nnslint:\s*disable=([A-Za-z0-9_/,\- ]+)")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str  # repo-relative, posix separators
    line: int
    message: str
    #: stable symbol for baseline matching (never a line number)
    anchor: str = ""

    @property
    def key(self) -> str:
        """Baseline identity: survives line drift, not symbol renames."""
        return f"{self.rule}::{self.path}::{self.anchor or self.message}"

    def to_json(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "anchor": self.anchor}

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class FileContext:
    """One parsed source file: text, AST, and suppression map."""

    def __init__(self, path: Path, root: Path = REPO_ROOT):
        self.path = path
        try:
            self.rel = path.resolve().relative_to(root).as_posix()
        except ValueError:
            self.rel = path.as_posix()
        self.text = path.read_text(encoding="utf-8")
        self.lines = self.text.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(self.text, filename=str(path))
        except SyntaxError as e:
            self.parse_error = e
        #: line -> frozenset of suppressed rule tokens on that line
        self.suppressions: Dict[int, frozenset] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                toks = frozenset(
                    t.strip() for t in m.group(1).split(",") if t.strip())
                self.suppressions[i] = toks

    def suppressed(self, rule: str, line: int) -> bool:
        """True when ``rule`` is disabled on ``line`` — by a trailing
        comment on the line itself or a comment-only line directly
        above (the two shapes reviewers actually write)."""
        for ln in (line, line - 1):
            toks = self.suppressions.get(ln)
            if not toks:
                continue
            if ln == line - 1 and not self.lines[ln - 1].lstrip().startswith("#"):
                continue  # code line above: its suppression is its own
            family = rule.split("/", 1)[0]
            if "all" in toks or rule in toks or family in toks:
                return True
        return False


class Rule:
    """Base rule. Subclasses set ``id`` (``family/name``) and
    ``description`` and override ``visit_file`` and/or ``finalize``."""

    id: str = ""
    description: str = ""

    def visit_file(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def finalize(self, ctxs: Sequence[FileContext]) -> Iterable[Finding]:
        return ()


_RULES: Dict[str, Rule] = {}


def register_rule(cls):
    """Class decorator: instantiate and register under ``cls.id``."""
    if not cls.id or "/" not in cls.id:
        raise ValueError(f"rule id must be family/name, got {cls.id!r}")
    if cls.id in _RULES:
        raise ValueError(f"duplicate rule id {cls.id!r}")
    _RULES[cls.id] = cls()
    return cls


def all_rules() -> Dict[str, Rule]:
    from . import rules  # noqa: F401 — importing registers the families

    return dict(_RULES)


def iter_py_files(roots: Sequence[Path]) -> List[Path]:
    out: List[Path] = []
    for root in roots:
        if root.is_file():
            out.append(root)
        else:
            out.extend(sorted(root.rglob("*.py")))
    return out


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    suppressed: int
    files: int
    rules: int


def run_lint(roots: Optional[Sequence[Path]] = None,
             select: Optional[Sequence[str]] = None) -> LintResult:
    """Run every registered rule (or the ``select`` id/family prefixes)
    over ``roots`` and return surviving findings, sorted by location.
    Suppressed findings are counted, not returned."""
    roots = [Path(r) for r in (roots or [DEFAULT_ROOT])]
    rules = all_rules()
    if select:
        rules = {rid: r for rid, r in rules.items()
                 if any(rid == s or rid.startswith(s.rstrip("/") + "/")
                        or rid.split("/")[0] == s for s in select)}
    ctxs = [FileContext(p) for p in iter_py_files(roots)]
    by_rel = {c.rel: c for c in ctxs}
    raw: List[Finding] = []
    for rule in rules.values():
        for ctx in ctxs:
            if ctx.tree is None:
                continue
            raw.extend(rule.visit_file(ctx))
        raw.extend(rule.finalize(ctxs))
    findings: List[Finding] = []
    suppressed = 0
    for f in raw:
        ctx = by_rel.get(f.path)
        if ctx is not None and ctx.suppressed(f.rule, f.line):
            suppressed += 1
        else:
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return LintResult(findings=findings, suppressed=suppressed,
                      files=len(ctxs), rules=len(rules))


# --------------------------------------------------------------------------- #
# shared AST helpers used by several rule families
# --------------------------------------------------------------------------- #

def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def ancestors(node: ast.AST, parents: Dict[ast.AST, ast.AST]
              ) -> Iterable[ast.AST]:
    while node in parents:
        node = parents[node]
        yield node


def is_self_attr(node: ast.AST, attr: Optional[str] = None
                 ) -> Optional[str]:
    """Return the attribute name when ``node`` is ``self.<attr>``
    (matching ``attr`` if given), else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and (attr is None or node.attr == attr)):
        return node.attr
    return None


def func_docstring(node: ast.AST) -> str:
    try:
        return ast.get_docstring(node) or ""
    except TypeError:
        return ""
