"""nnslint — project-wide static analysis for concurrency discipline,
hot-path contracts, JAX tracing hazards, wire-protocol completeness,
and telemetry naming. See docs/analysis.md.

Entry points:

* CLI: ``python -m scripts.nnslint [--json] [--update-baseline]``
* API: :func:`run_lint` returning :class:`LintResult`
* tier-1: ``tests/test_nnslint.py`` fails on any non-baselined finding
"""

from .core import (DEFAULT_ROOT, REPO_ROOT, FileContext, Finding,  # noqa: F401
                   LintResult, Rule, all_rules, register_rule, run_lint)
from .baseline import DEFAULT_BASELINE  # noqa: F401
