"""Baseline handling: grandfathered findings committed next to the
engine so the tree lints clean while the debt is paid down.

The file is a sorted JSON list of finding keys plus the human-readable
context that produced them (rule/path/anchor/message). Matching is by
:attr:`Finding.key` — rule + path + anchor — deliberately excluding
line numbers so unrelated edits don't churn the baseline. Workflow:

* a *new* finding (not in the baseline) fails the lint;
* a baselined finding that disappears is reported as stale by
  ``--update-baseline`` (run it and commit the shrunken file — the
  diff is the review);
* ``--update-baseline`` rewrites the file from the current findings.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Sequence, Set, Tuple

from .core import Finding

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"


def load(path: Path = DEFAULT_BASELINE) -> Set[str]:
    """The set of grandfathered finding keys (empty when no file)."""
    if not path.exists():
        return set()
    entries = json.loads(path.read_text(encoding="utf-8"))
    return {e["key"] for e in entries}


def save(findings: Sequence[Finding], path: Path = DEFAULT_BASELINE) -> int:
    """Rewrite the baseline from ``findings``; returns the entry count.
    Entries carry the message/line for reviewers — only ``key`` is
    matched."""
    entries: List[Dict[str, object]] = [
        {"key": f.key, "rule": f.rule, "path": f.path, "line": f.line,
         "message": f.message}
        for f in sorted(findings, key=lambda f: f.key)
    ]
    path.write_text(json.dumps(entries, indent=1) + "\n", encoding="utf-8")
    return len(entries)


def split(findings: Sequence[Finding], baseline_keys: Set[str]
          ) -> Tuple[List[Finding], List[Finding], Set[str]]:
    """Partition into (new, grandfathered, stale_keys)."""
    new: List[Finding] = []
    old: List[Finding] = []
    seen: Set[str] = set()
    for f in findings:
        if f.key in baseline_keys:
            old.append(f)
            seen.add(f.key)
        else:
            new.append(f)
    return new, old, baseline_keys - seen
