"""Wire-protocol completeness rules (cross-file).

* ``wire/cmd-dispatch`` — every member of a wire-command enum (an
  ``enum.IntEnum`` subclass named ``Cmd``) is referenced by at least
  one dispatch site outside the enum definition. An unreferenced
  member is a command one side can legally send and the other side
  routes to the generic "unexpected cmd" arm — protocol drift that
  only shows up as a live incident (the reference NNStreamer hit
  exactly this with TRANSFER_* handling).
* ``wire/struct-format`` — within one subpackage, every literal
  ``struct.pack`` format string has a matching ``struct.unpack`` /
  ``unpack_from`` of the same format somewhere, and vice versa
  (``struct.Struct`` instances count for both directions: the object
  is the send/recv pair). A one-sided format is a framing mismatch
  waiting for the first peer running older code. Packages that only
  ever read foreign formats (model file parsers) have no pack sites
  and are skipped; single sites that parse a *foreign* wire format
  inside a paired package carry an inline suppression naming the
  protocol.
"""

from __future__ import annotations

import ast
from collections import defaultdict
from typing import Dict, Iterable, List, Sequence, Set, Tuple

from ..core import FileContext, Finding, Rule, dotted_name, register_rule

#: wire-command enum class names subject to the dispatch check
_CMD_CLASS_NAMES = frozenset({"Cmd"})


def _is_enum_base(base: ast.AST) -> bool:
    name = dotted_name(base) or ""
    return name.split(".")[-1].endswith("Enum")


@register_rule
class CmdDispatchRule(Rule):
    id = "wire/cmd-dispatch"
    description = ("every wire-command enum member has a dispatch branch "
                   "referencing it outside the enum definition")

    def finalize(self, ctxs: Sequence[FileContext]) -> Iterable[Finding]:
        # pass 1: enum members, remembering the defining class span
        members: Dict[str, Dict[str, Tuple[str, int]]] = {}
        spans: Dict[str, List[Tuple[str, int, int]]] = defaultdict(list)
        for ctx in ctxs:
            if ctx.tree is None:
                continue
            for cls in ast.walk(ctx.tree):
                if not (isinstance(cls, ast.ClassDef)
                        and cls.name in _CMD_CLASS_NAMES
                        and any(_is_enum_base(b) for b in cls.bases)):
                    continue
                end = max((n.lineno for n in ast.walk(cls)
                           if hasattr(n, "lineno")), default=cls.lineno)
                spans[cls.name].append((ctx.rel, cls.lineno, end))
                for stmt in cls.body:
                    if isinstance(stmt, ast.Assign):
                        for tgt in stmt.targets:
                            if isinstance(tgt, ast.Name) \
                                    and tgt.id.isupper():
                                members.setdefault(cls.name, {})[tgt.id] = \
                                    (ctx.rel, stmt.lineno)
        if not members:
            return
        # pass 2: Cmd.<member> references outside the defining class
        referenced: Dict[str, Set[str]] = defaultdict(set)
        for ctx in ctxs:
            if ctx.tree is None:
                continue
            for node in ast.walk(ctx.tree):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Name)
                        and node.value.id in members):
                    continue
                cls_name = node.value.id
                if any(rel == ctx.rel and lo <= node.lineno <= hi
                       for rel, lo, hi in spans[cls_name]):
                    continue  # inside the enum body itself
                referenced[cls_name].add(node.attr)
        for cls_name, mems in members.items():
            for member, (rel, line) in mems.items():
                if member in referenced[cls_name]:
                    continue
                yield Finding(
                    rule=self.id, path=rel, line=line,
                    anchor=f"{cls_name}.{member}",
                    message=(f"{cls_name}.{member} has no dispatch branch "
                             f"anywhere — a peer sending it is routed to "
                             f"the generic error arm (protocol drift)"))


def _fmt(node: ast.Call) -> str:
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value.replace(" ", "")
    return ""


def _package_of(rel: str) -> str:
    parts = rel.split("/")
    return "/".join(parts[:2]) if len(parts) > 2 else parts[0]


@register_rule
class StructFormatRule(Rule):
    id = "wire/struct-format"
    description = ("struct pack/unpack format strings agree across "
                   "send/recv pairs within a subpackage")

    def finalize(self, ctxs: Sequence[FileContext]) -> Iterable[Finding]:
        # pkg -> fmt -> first (rel, line) per direction
        packs: Dict[str, Dict[str, Tuple[str, int]]] = defaultdict(dict)
        unpacks: Dict[str, Dict[str, Tuple[str, int]]] = defaultdict(dict)
        for ctx in ctxs:
            if ctx.tree is None:
                continue
            pkg = _package_of(ctx.rel)
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func) or ""
                fmt = _fmt(node)
                if not fmt:
                    continue
                at = (ctx.rel, node.lineno)
                if name in ("struct.pack", "struct.pack_into"):
                    packs[pkg].setdefault(fmt, at)
                elif name in ("struct.unpack", "struct.unpack_from"):
                    unpacks[pkg].setdefault(fmt, at)
                elif name == "struct.Struct":
                    # the Struct object is its own send/recv pair
                    packs[pkg].setdefault(fmt, at)
                    unpacks[pkg].setdefault(fmt, at)
        for pkg in set(packs) | set(unpacks):
            if not packs[pkg] or not unpacks[pkg]:
                continue  # read-only (or write-only) package: a parser
            for fmt, (rel, line) in sorted(packs[pkg].items()):
                if fmt not in unpacks[pkg]:
                    yield Finding(
                        rule=self.id, path=rel, line=line,
                        anchor=f"pack:{fmt}",
                        message=(f"struct format {fmt!r} is packed in "
                                 f"{pkg} but never unpacked there — "
                                 f"send/recv framing mismatch"))
            for fmt, (rel, line) in sorted(unpacks[pkg].items()):
                if fmt not in packs[pkg]:
                    yield Finding(
                        rule=self.id, path=rel, line=line,
                        anchor=f"unpack:{fmt}",
                        message=(f"struct format {fmt!r} is unpacked in "
                                 f"{pkg} but never packed there — "
                                 f"send/recv framing mismatch (foreign "
                                 f"protocols: suppress inline, naming "
                                 f"the protocol)"))
