"""Hot-path contract rules.

* ``contracts/never-raise`` — a function whose docstring declares a
  never-raise boundary ("never raises", "never raise", "never an
  exception", "must not raise") must actually contain a broad
  ``except Exception``/bare ``except`` handler somewhere. These
  boundaries sit where telemetry or peer input meets a data stream
  (``ingest_wire``, flight-recorder logging, OBS_PUSH fire-and-forget);
  a narrow except list silently converts "never raises" into "raises
  on the one type nobody enumerated".
* ``contracts/hook-gate`` — module-global hot-path hooks (names
  matching ``*_HOOK``) are consumed behind an ``is None`` gate —
  either ``if X is not None: X(...)`` (including the and-chain form
  ``if X is not None and X(...)``) or an early ``if X is None:
  return`` guard. The disabled path must stay one global load + one
  None check; an unguarded call turns "zero overhead when off" into a
  TypeError when off.
* ``contracts/hook-default`` — the module defining a ``*_HOOK`` global
  initializes it to ``None``: installed-by-default hooks silently
  repeal the zero-overhead contract.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Set

from ..core import (FileContext, Finding, Rule, ancestors, func_docstring,
                    parent_map, register_rule)

_NEVER_RAISE_RE = re.compile(
    r"never[\s-]+raise[sd]?\b|never\s+an\s+exception|must\s+not\s+raise",
    re.IGNORECASE)

_HOOK_NAME_RE = re.compile(r"^[A-Z][A-Z0-9_]*_HOOK$")


def _has_broad_except(func: ast.AST) -> bool:
    # manual stack instead of ast.walk: nested defs guard their own
    # bodies, so their handlers must not satisfy the outer boundary
    stack: List[ast.AST] = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            return True
        t = node.type
        if isinstance(t, ast.Tuple):
            names = [getattr(e, "id", getattr(e, "attr", "")) for e in t.elts]
        else:
            names = [getattr(t, "id", getattr(t, "attr", ""))]
        if "Exception" in names or "BaseException" in names:
            return True
    return False


@register_rule
class NeverRaiseRule(Rule):
    id = "contracts/never-raise"
    description = ("functions declaring a never-raise boundary contain "
                   "a broad except")

    def visit_file(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            doc = func_docstring(node)
            if not doc or not _NEVER_RAISE_RE.search(doc):
                continue
            if _has_broad_except(node):
                continue
            yield Finding(
                rule=self.id, path=ctx.rel, line=node.lineno,
                anchor=node.name,
                message=(f"{node.name}() declares a never-raise boundary "
                         f"in its docstring but has no broad 'except "
                         f"Exception' — the contract leaks every type "
                         f"outside its narrow except list"))


def _gated_by(node: ast.AST, hook: str, parents) -> bool:
    """True when a hook *call site* is behind an ``is None`` gate."""
    for anc in ancestors(node, parents):
        if isinstance(anc, ast.If) and _test_checks(anc.test, hook):
            return True
        if isinstance(anc, ast.IfExp) and _test_checks(anc.test, hook):
            return True
        # early-guard form: a preceding `if X is None: return/raise` in
        # the same statement list
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for stmt in anc.body:
                if stmt.lineno >= node.lineno:
                    break
                if (isinstance(stmt, ast.If)
                        and _is_none_bailout(stmt, hook)):
                    return True
            return False
    return False


def _test_checks(test: ast.AST, hook: str) -> bool:
    """Does ``test`` contain ``<hook> is not None``? (Direct compare or
    any value of an and-chain.)"""
    for node in ast.walk(test):
        if (isinstance(node, ast.Compare)
                and isinstance(node.left, ast.Name)
                and node.left.id == hook
                and len(node.ops) == 1
                and isinstance(node.ops[0], ast.IsNot)
                and isinstance(node.comparators[0], ast.Constant)
                and node.comparators[0].value is None):
            return True
    return False


def _is_none_bailout(stmt: ast.If, hook: str) -> bool:
    test = stmt.test
    is_none = (isinstance(test, ast.Compare)
               and isinstance(test.left, ast.Name)
               and test.left.id == hook
               and len(test.ops) == 1
               and isinstance(test.ops[0], ast.Is)
               and isinstance(test.comparators[0], ast.Constant)
               and test.comparators[0].value is None)
    if not is_none or not stmt.body:
        return False
    last = stmt.body[-1]
    return isinstance(last, (ast.Return, ast.Raise, ast.Continue))


@register_rule
class HookGateRule(Rule):
    id = "contracts/hook-gate"
    description = ("*_HOOK globals are called behind a single "
                   "'is None' gate")

    def visit_file(self, ctx: FileContext) -> Iterable[Finding]:
        hooks: Set[str] = {
            n.id for node in ast.walk(ctx.tree)
            for n in ast.walk(node)
            if isinstance(n, ast.Name) and _HOOK_NAME_RE.match(n.id)}
        if not hooks:
            return
        parents = parent_map(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in hooks):
                continue
            if _gated_by(node, node.func.id, parents):
                continue
            yield Finding(
                rule=self.id, path=ctx.rel, line=node.lineno,
                anchor=node.func.id,
                message=(f"{node.func.id}(...) called without an "
                         f"'is None' gate — the zero-overhead-when-off "
                         f"contract requires 'if {node.func.id} is not "
                         f"None' around every consumption"))


@register_rule
class HookDefaultRule(Rule):
    id = "contracts/hook-default"
    description = "module-global *_HOOK defaults are None"

    def visit_file(self, ctx: FileContext) -> Iterable[Finding]:
        for stmt in ctx.tree.body:
            if not isinstance(stmt, ast.Assign):
                continue
            for tgt in stmt.targets:
                if not (isinstance(tgt, ast.Name)
                        and _HOOK_NAME_RE.match(tgt.id)):
                    continue
                if isinstance(stmt.value, ast.Constant) \
                        and stmt.value.value is None:
                    continue
                yield Finding(
                    rule=self.id, path=ctx.rel, line=stmt.lineno,
                    anchor=tgt.id,
                    message=(f"{tgt.id} defaults to a non-None value at "
                             f"module scope — hooks are installed at "
                             f"runtime; the import-time default must be "
                             f"None so the disabled path stays free"))
