"""Telemetry naming rules — the original check_metric_names checks,
registered as the fifth nnslint family so there is one lint engine.

The implementation stays in :mod:`scripts.nnslint.naming_compat`
(moved verbatim; ``scripts/check_metric_names.py`` is now a shim over
it) because its string-returning API is public: tests and external
callers drive ``check()``/``check_labels()``/… directly. The wrappers
here parse those ``path:line: message`` strings into Findings, keyed
for the baseline by the message body (naming violations are about a
literal name, which IS the stable symbol).
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Callable, Iterable, List, Sequence

from .. import naming_compat as _compat
from ..core import REPO_ROOT, FileContext, Finding, Rule, register_rule

_LOC_RE = re.compile(r"^(?P<path>[^:]+):(?P<line>\d+): (?P<msg>.*)$",
                     re.DOTALL)


def _to_findings(rule_id: str, problems: Sequence[str]) -> List[Finding]:
    out: List[Finding] = []
    for p in problems:
        m = _LOC_RE.match(p)
        if m:
            out.append(Finding(
                rule=rule_id, path=Path(m.group("path")).as_posix(),
                line=int(m.group("line")), message=m.group("msg"),
                anchor=m.group("msg")))
        else:
            # tree-level problems ("no registrations found") anchor on
            # the whole tree
            out.append(Finding(rule=rule_id, path="nnstreamer_tpu",
                               line=0, message=p, anchor=p))
    return out


def _root_of(ctxs: Sequence[FileContext]) -> Path:
    """The common directory the engine is scanning — naming_compat
    iterates files itself, so hand it the same root."""
    if not ctxs:
        return _compat.SOURCE_ROOT
    paths = [ctx.path.resolve() for ctx in ctxs]
    root = paths[0] if paths[0].is_dir() else paths[0].parent
    for p in paths[1:]:
        while root not in p.parents and root != p:
            root = root.parent
    return root


class _NamingRule(Rule):
    checks: Sequence[Callable[[Path], List[str]]] = ()

    def finalize(self, ctxs: Sequence[FileContext]) -> Iterable[Finding]:
        root = _root_of(ctxs)
        problems: List[str] = []
        for chk in type(self).checks:
            problems.extend(chk(root))
        return _to_findings(self.id, problems)


@register_rule
class MetricNameRule(_NamingRule):
    id = "naming/metric-name"
    description = "metric names follow nnstpu_<layer>_<name>_<unit>"
    checks = (_compat.check_names,)


@register_rule
class MetricLabelRule(_NamingRule):
    id = "naming/metric-labels"
    description = ("label keys are legal, non-reserved, and at most "
                   f"{_compat.MAX_LABEL_KEYS} per family")
    checks = (_compat.check_labels,)


@register_rule
class SpanNameRule(_NamingRule):
    id = "naming/span-name"
    description = "span names are lowercase <layer>.<operation>"
    checks = (_compat.check_spans,)


@register_rule
class EventNameRule(_NamingRule):
    id = "naming/event-name"
    description = "flight-recorder event types are lowercase <layer>.<event>"
    checks = (_compat.check_events,)


@register_rule
class PlacementRule(_NamingRule):
    id = "naming/placement"
    description = ("resilience/chaos, kv_*, router, and sched telemetry "
                   "are registered in their owning packages")
    checks = (_compat.check_resilience, _compat.check_kv,
              _compat.check_router, _compat.check_sched)


@register_rule
class ProfileRule(_NamingRule):
    id = "naming/profile"
    description = ("profile telemetry is registered in obs/profile.py "
                   "and owns the ratio/flops gauge units")
    checks = (_compat.check_profile,)


@register_rule
class DisaggRule(_NamingRule):
    id = "naming/disagg"
    description = ("disagg telemetry is registered in "
                   "serving/disagg.py alone")
    checks = (_compat.check_disagg,)


@register_rule
class EpilogueRule(_NamingRule):
    id = "naming/epilogue"
    description = ("Pallas kernel labels are pallas.<snake_case> owned by "
                   "ops/pallas/; EPILOGUE_SELECT_HOOK is assigned only by "
                   "its definition and profile.enable()/disable()")
    checks = (_compat.check_epilogue,)


@register_rule
class SloRule(_NamingRule):
    id = "naming/slo"
    description = ("slo telemetry is registered in obs/slo.py and the "
                   "tenant label stays in obs/slo.py + sched/")
    checks = (_compat.check_slo,)


@register_rule
class TuneRule(_NamingRule):
    id = "naming/tune"
    description = ("tune telemetry and tune.* events live in tune/; "
                   "TUNE_HOOK is assigned only by tune.enable()/"
                   "disable() and obs/profile.py")
    checks = (_compat.check_tune,)


@register_rule
class DiagRule(_NamingRule):
    id = "naming/diag"
    description = ("diag telemetry, diag.* synthetic spans, and diag.* "
                   "events live in obs/diag/; nnstpu_build_info is "
                   "registered only in obs/exporter.py; DIAG_HOOK is "
                   "assigned only by diag.enable()/disable()")
    checks = (_compat.check_diag,)


@register_rule
class QualityRule(_NamingRule):
    id = "naming/quality"
    description = ("quality telemetry, quality.* spans, and quality.* "
                   "events live in obs/quality/; the psi gauge unit is "
                   "quality-only; QUALITY_HOOK is assigned only by "
                   "quality.enable()/disable()")
    checks = (_compat.check_quality,)


@register_rule
class FleetRule(_NamingRule):
    id = "naming/fleet"
    description = ("nnstpu_fleet_* metrics, fleet.* spans, and the "
                   "fleet.scale_*/migrate_* event subfamilies live in "
                   "fleet/; the replicas gauge unit is fleet-only; "
                   "AUTOSCALE_HOOK is assigned only by "
                   "fleet.enable()/disable()")
    checks = (_compat.check_fleet,)


@register_rule
class CheckpointRule(_NamingRule):
    id = "naming/checkpoint"
    description = ("nnstpu_fleet_checkpoint_*/restore_*/restored_* "
                   "metrics and the fleet.checkpoint_*/restore_* event "
                   "subfamilies live in fleet/; CHECKPOINT_HOOK is "
                   "assigned only by the checkpoint daemon's "
                   "install_hook()/uninstall_hook()")
    checks = (_compat.check_checkpoint,)
