"""JAX tracing hazard rules.

``jax.jit`` / ``pl.pallas_call`` bodies execute at *trace* time; host
side effects inside them either burn in a stale value (``time.time``,
``random``) or silently force a device sync per call
(``np.asarray`` on a tracer, ``.block_until_ready``, ``.item()``).
Before the Pallas/autotuner arc adds more kernels, these rules make
the boundary mechanical (docs/analysis.md "JAX tracing"):

* ``jax/host-call-in-jit`` — no wall-clock reads, stdlib ``random``,
  host numpy materialization, or explicit device syncs inside a traced
  function. Traced = decorated with ``jax.jit``/``jit``/``pmap``/
  ``pjit`` (directly or via ``partial(jax.jit, ...)``), wrapped as
  ``g = jax.jit(f)``, or passed as the kernel to ``pl.pallas_call``.
  Constant setup that legitimately runs once at trace time carries an
  inline ``# nnslint: disable=jax/host-call-in-jit`` with a reason.
* ``jax/mutable-default`` — no mutable defaults holding arrays:
  ``def f(x, buf=np.zeros(8))`` evaluates once at import and every
  call shares (and in-place ops mutate) the same array; in a traced
  signature it additionally bakes one constant into the compiled
  executable.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from ..core import FileContext, Finding, Rule, dotted_name, register_rule

#: decorator / wrapper spellings that make a function traced
_JIT_NAMES = frozenset({
    "jax.jit", "jit", "jax.pmap", "pmap", "pjit", "jax.pjit",
})
_PALLAS_NAMES = frozenset({"pl.pallas_call", "pallas_call"})

#: host calls banned under trace: (dotted prefix, reason)
_BANNED_CALLS = {
    "time.time": "wall-clock read burns in the trace-time value",
    "time.time_ns": "wall-clock read burns in the trace-time value",
    "time.monotonic": "clock read burns in the trace-time value",
    "time.monotonic_ns": "clock read burns in the trace-time value",
    "time.perf_counter": "clock read burns in the trace-time value",
    "time.sleep": "host sleep has no effect in the compiled function",
    "np.asarray": "host materialization forces a device sync per call",
    "np.array": "host materialization forces a device sync per call",
    "numpy.asarray": "host materialization forces a device sync per call",
    "numpy.array": "host materialization forces a device sync per call",
    "jax.device_get": "explicit device sync inside the traced body",
    "print": "traces once, not per call — use jax.debug.print",
}
#: stdlib random module (jax.random is fine and spelled jrandom/jax.random)
_BANNED_MODULES = ("random.",)
#: method calls that force a host sync on a traced value
_BANNED_METHODS = frozenset({"block_until_ready", "item"})

#: default-value constructors that allocate an array at def time
_ARRAY_CTORS = ("np.", "numpy.", "jnp.", "jax.numpy.")


def _decorator_is_jit(dec: ast.AST) -> bool:
    name = dotted_name(dec)
    if name in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        fname = dotted_name(dec.func)
        if fname in _JIT_NAMES:
            return True  # @jax.jit(static_argnums=...)
        if fname in {"partial", "functools.partial"} and dec.args:
            return dotted_name(dec.args[0]) in _JIT_NAMES
    return False


def _collect_traced(tree: ast.Module) -> Set[ast.AST]:
    """Function nodes whose bodies run under JAX tracing."""
    by_name = {}
    bindings = {}  # local/module name -> last assigned value expr
    traced: Set[ast.AST] = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, node)
            if any(_decorator_is_jit(d) for d in node.decorator_list):
                traced.add(node)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            bindings[node.targets[0].id] = node.value
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fname = dotted_name(node.func)
        ref: Optional[ast.AST] = None
        if fname in _JIT_NAMES and node.args:
            ref = node.args[0]       # g = jax.jit(f)
        elif fname in _PALLAS_NAMES and node.args:
            ref = node.args[0]       # pl.pallas_call(kernel, ...)
        elif fname in _PALLAS_NAMES:
            for kw in node.keywords:
                if kw.arg == "kernel":
                    ref = kw.value
        if ref is not None:
            for name in _resolve_func_names(ref, bindings):
                if name in by_name:
                    traced.add(by_name[name])
    return traced


def _resolve_func_names(ref: ast.AST, bindings, depth: int = 0) -> Set[str]:
    """Function names a kernel/jit argument can resolve to, through
    the spellings the tree actually uses: a bare Name, a wrapper call
    whose first positional arg is the function (``functools.partial``,
    ``_shard_map``), an either-or ``IfExp``, and one level of local
    rebinding (``kernel = partial(kfn, ...)``)."""
    if depth > 4:
        return set()
    if isinstance(ref, ast.Name):
        bound = bindings.get(ref.id)
        if bound is not None and not isinstance(bound, ast.Name):
            resolved = _resolve_func_names(bound, bindings, depth + 1)
            if resolved:
                return resolved
        return {ref.id}
    if isinstance(ref, ast.Call) and ref.args:
        return _resolve_func_names(ref.args[0], bindings, depth + 1)
    if isinstance(ref, ast.IfExp):
        return (_resolve_func_names(ref.body, bindings, depth + 1)
                | _resolve_func_names(ref.orelse, bindings, depth + 1))
    return set()


@register_rule
class HostCallInJitRule(Rule):
    id = "jax/host-call-in-jit"
    description = ("no wall-clock, stdlib random, host numpy, or device "
                   "syncs inside jit/pallas-traced functions")

    def visit_file(self, ctx: FileContext) -> Iterable[Finding]:
        if "jit" not in ctx.text and "pallas_call" not in ctx.text:
            return
        traced = _collect_traced(ctx.tree)
        for func in traced:
            for node in ast.walk(func):
                if not isinstance(node, ast.Call):
                    continue
                reason = self._banned(node)
                if reason is None:
                    continue
                yield Finding(
                    rule=self.id, path=ctx.rel, line=node.lineno,
                    anchor=f"{func.name}:{dotted_name(node.func) or 'call'}",
                    message=(f"{dotted_name(node.func) or 'host call'} "
                             f"inside traced function {func.name}(): "
                             f"{reason}"))

    @staticmethod
    def _banned(node: ast.Call) -> Optional[str]:
        name = dotted_name(node.func)
        if name is not None:
            if name in _BANNED_CALLS:
                return _BANNED_CALLS[name]
            if any(name.startswith(p) for p in _BANNED_MODULES):
                return ("stdlib random draws at trace time — use "
                        "jax.random with an explicit key")
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in _BANNED_METHODS):
            return (f".{node.func.attr}() forces a host sync inside the "
                    f"traced body")
        return None


@register_rule
class MutableDefaultRule(Rule):
    id = "jax/mutable-default"
    description = ("no mutable default arguments holding arrays "
                   "(np/jnp constructors, or containers of them)")

    def visit_file(self, ctx: FileContext) -> Iterable[Finding]:
        for func in ast.walk(ctx.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            args = func.args
            for default in list(args.defaults) + [
                    d for d in args.kw_defaults if d is not None]:
                if self._array_valued(default):
                    yield Finding(
                        rule=self.id, path=ctx.rel, line=default.lineno,
                        anchor=func.name,
                        message=(f"{func.name}() has an array-valued "
                                 f"mutable default — it is allocated "
                                 f"once at import and shared by every "
                                 f"call; build it inside the body "
                                 f"(default None) instead"))

    @staticmethod
    def _array_valued(node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            return bool(name) and name.startswith(_ARRAY_CTORS)
        if isinstance(node, (ast.List, ast.Set, ast.Tuple)):
            return any(MutableDefaultRule._array_valued(e)
                       for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(v is not None
                       and MutableDefaultRule._array_valued(v)
                       for v in node.values)
        return False
