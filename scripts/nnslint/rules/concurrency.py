"""Concurrency discipline rules.

The pipeline/query/obs layers run ~30 worker threads against
lock-guarded shared state; these rules mechanize the two conventions
that keep that safe (docs/analysis.md "Concurrency"):

* ``concurrency/guarded-by`` — an attribute whose declaration line
  carries ``# guarded-by: <lock>`` may only be *mutated* inside a
  ``with self.<lock>:`` block (Condition objects count — ``with
  self._cv:`` acquires the underlying lock). The declaring method
  (normally ``__init__``, before the object is shared) is exempt.
* ``concurrency/thread-daemon`` — every ``threading.Thread(...)`` sets
  ``daemon=`` explicitly: the flag decides whether a leaked worker can
  hang interpreter exit, so it must be a reviewed decision, never the
  inherited default.
* ``concurrency/thread-join`` — a Thread stored on ``self`` (directly
  or appended to a ``self.<list>``) must be joined somewhere in its
  class (``join_or_warn(...)`` or ``.join(...)``), i.e. reachable from
  a stop()/close() path; a worker nobody joins keeps element state
  alive past stop and can wake on a reused port or queue.
* ``concurrency/join-or-warn`` — in modules that import
  ``join_or_warn``, thread joins go through it (bounded wait + leak
  telemetry) instead of a bare ``.join()`` whose timeout expiry is
  silent.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import (FileContext, Finding, Rule, ancestors, dotted_name,
                    is_self_attr, parent_map, register_rule)

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")

#: method calls that mutate their receiver in place
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "extendleft", "insert", "pop",
    "popleft", "popitem", "remove", "clear", "add", "discard", "update",
    "setdefault", "sort", "reverse",
})


def _thread_ctor(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    return name in {"threading.Thread", "Thread"}


def _enclosing_funcs(node: ast.AST, parents) -> List[ast.AST]:
    return [a for a in ancestors(node, parents)
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _func_holds_lock(ctx: FileContext, func: ast.AST, lock: str) -> bool:
    """Caller-holds-lock helpers: a method named ``*_locked`` (the
    repo's convention — e.g. SpanStore._evict_locked) is exempt for
    every lock; a def line carrying ``# guarded-by: <lock>`` documents
    which lock its callers hold."""
    if func.name.endswith("_locked"):
        return True
    line = ctx.lines[func.lineno - 1] if func.lineno <= len(ctx.lines) else ""
    m = _GUARDED_RE.search(line)
    return bool(m) and m.group(1) == lock


def _with_locks(node: ast.AST, parents) -> Set[str]:
    """Names X for every enclosing ``with self.X`` block."""
    locks: Set[str] = set()
    for a in ancestors(node, parents):
        if isinstance(a, ast.With):
            for item in a.items:
                attr = is_self_attr(item.context_expr)
                if attr:
                    locks.add(attr)
    return locks


@register_rule
class GuardedByRule(Rule):
    id = "concurrency/guarded-by"
    description = ("attributes annotated '# guarded-by: <lock>' are only "
                   "mutated inside 'with self.<lock>'")

    def visit_file(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        parents = parent_map(ctx.tree)
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            guarded: Dict[str, str] = {}       # attr -> lock name
            declared_in: Dict[str, ast.AST] = {}  # attr -> declaring func
            for node in ast.walk(cls):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    attr = is_self_attr(tgt)
                    if attr is None:
                        continue
                    line = ctx.lines[node.lineno - 1] \
                        if node.lineno <= len(ctx.lines) else ""
                    m = _GUARDED_RE.search(line)
                    if m:
                        guarded[attr] = m.group(1)
                        funcs = _enclosing_funcs(node, parents)
                        if funcs:
                            declared_in[attr] = funcs[0]
            if not guarded:
                continue
            findings.extend(self._check_class(ctx, cls, parents, guarded,
                                              declared_in))
        return findings

    def _check_class(self, ctx, cls, parents, guarded, declared_in
                     ) -> Iterable[Finding]:
        for node in ast.walk(cls):
            attr = mutation = None
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for tgt in targets:
                    attr = is_self_attr(tgt)
                    if attr is None and isinstance(tgt, ast.Subscript):
                        attr = is_self_attr(tgt.value)
                    if attr in guarded:
                        mutation = "assignment"
                        break
                    attr = None
            elif isinstance(node, ast.Delete):
                for tgt in node.targets:
                    a = is_self_attr(tgt)
                    if a is None and isinstance(tgt, ast.Subscript):
                        a = is_self_attr(tgt.value)
                    if a in guarded:
                        attr, mutation = a, "del"
                        break
            elif (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _MUTATORS):
                a = is_self_attr(node.func.value)
                if a in guarded:
                    attr, mutation = a, f".{node.func.attr}()"
            if attr is None:
                continue
            funcs = _enclosing_funcs(node, parents)
            if funcs and funcs[0] is declared_in.get(attr):
                continue  # declaring method: object not shared yet
            lock = guarded[attr]
            if lock in _with_locks(node, parents):
                continue
            if funcs and _func_holds_lock(ctx, funcs[0], lock):
                continue  # caller-holds-lock helper
            yield Finding(
                rule=self.id, path=ctx.rel, line=node.lineno,
                anchor=f"{cls.name}.{attr}",
                message=(f"{cls.name}.{attr} is guarded by self.{lock} "
                         f"but this {mutation} is outside any "
                         f"'with self.{lock}' block"))


@register_rule
class ThreadDaemonRule(Rule):
    id = "concurrency/thread-daemon"
    description = "threading.Thread(...) must pass daemon= explicitly"

    def visit_file(self, ctx: FileContext) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call) and _thread_ctor(node)):
                continue
            if any(kw.arg == "daemon" for kw in node.keywords):
                continue
            yield Finding(
                rule=self.id, path=ctx.rel, line=node.lineno,
                anchor=f"L:{_thread_anchor(node)}",
                message=("threading.Thread(...) without an explicit "
                         "daemon= — whether a leaked worker may hang "
                         "interpreter exit is a reviewed decision"))


def _thread_anchor(node: ast.Call) -> str:
    """Stable-ish anchor: the thread's target/name kwarg if literal."""
    for kw in node.keywords:
        if kw.arg == "target":
            return dotted_name(kw.value) or "thread"
        if kw.arg == "name" and isinstance(kw.value, ast.Constant):
            return str(kw.value.value)
    return "thread"


class _ClassThreads(ast.NodeVisitor):
    """Per-class collection of thread-holding attrs and join evidence."""

    def __init__(self):
        #: attr -> lineno of the Thread() (direct ``self.X = Thread()``)
        self.direct: Dict[str, int] = {}
        #: list attrs that received a Thread via .append()
        self.lists: Dict[str, int] = {}
        #: attrs with any join evidence (join_or_warn or .join)
        self.joined: Set[str] = set()
        #: attrs joined ONLY via bare .join (never join_or_warn)
        self.bare_join_lines: Dict[str, int] = {}
        self.join_or_warn_attrs: Set[str] = set()


def _analyze_class(cls: ast.ClassDef) -> _ClassThreads:
    info = _ClassThreads()
    for func in (n for n in ast.walk(cls)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))):
        #: local name -> self attr it aliases (w = self._worker;
        #: for t in self._threads)
        alias: Dict[str, str] = {}
        thread_locals: Set[str] = set()
        # ast.walk is breadth-first, so aliases nested deeper than their
        # use site (workers = list(self._threads) inside a with-block,
        # consumed by a sibling for-loop) would be missed in one pass:
        # collect Assign aliases first, then For-target aliases on top
        for node in ast.walk(func):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt, val = node.targets[0], node.value
                attr = is_self_attr(tgt)
                if attr and isinstance(val, ast.Call) and _thread_ctor(val):
                    info.direct.setdefault(attr, node.lineno)
                elif isinstance(tgt, ast.Name):
                    src = is_self_attr(val)
                    if src is None and isinstance(val, ast.Call) \
                            and isinstance(val.func, ast.Name) \
                            and val.func.id in ("list", "tuple", "sorted",
                                                "reversed") \
                            and len(val.args) == 1:
                        # snapshot copy: workers = list(self._threads)
                        src = is_self_attr(val.args[0])
                    if src:
                        alias[tgt.id] = src
                    elif isinstance(val, ast.Call) and _thread_ctor(val):
                        thread_locals.add(tgt.id)
        for node in ast.walk(func):
            if isinstance(node, ast.For):
                src = _resolve_attr(node.iter, alias)
                if src and isinstance(node.target, ast.Name):
                    alias[node.target.id] = src
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            # self.<list>.append(<thread local>)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "append"
                    and node.args and isinstance(node.args[0], ast.Name)
                    and node.args[0].id in thread_locals):
                attr = is_self_attr(node.func.value)
                if attr:
                    info.lists.setdefault(attr, node.lineno)
            # join_or_warn(X, ...)
            if (isinstance(node.func, ast.Name)
                    and node.func.id == "join_or_warn" and node.args):
                attr = _resolve_attr(node.args[0], alias)
                if attr:
                    info.joined.add(attr)
                    info.join_or_warn_attrs.add(attr)
            # X.join(...)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "join"):
                attr = _resolve_attr(node.func.value, alias)
                if attr:
                    info.joined.add(attr)
                    info.bare_join_lines.setdefault(attr, node.lineno)
    return info


def _resolve_attr(node: ast.AST, alias: Dict[str, str]) -> Optional[str]:
    attr = is_self_attr(node)
    if attr:
        return attr
    if isinstance(node, ast.Name):
        return alias.get(node.id)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id in ("list", "tuple", "sorted", "reversed") \
            and len(node.args) == 1:
        # snapshot copy in iter position: for t in list(self._threads)
        return _resolve_attr(node.args[0], alias)
    return None


@register_rule
class ThreadJoinRule(Rule):
    id = "concurrency/thread-join"
    description = ("threads stored on self must be joined (join_or_warn "
                   "or .join) from some method of their class")

    def visit_file(self, ctx: FileContext) -> Iterable[Finding]:
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            info = _analyze_class(cls)
            holders: List[Tuple[str, int]] = (
                list(info.direct.items()) + list(info.lists.items()))
            for attr, line in holders:
                if attr in info.joined:
                    continue
                yield Finding(
                    rule=self.id, path=ctx.rel, line=line,
                    anchor=f"{cls.name}.{attr}",
                    message=(f"{cls.name}.{attr} holds a worker thread "
                             f"that no method of the class ever joins — "
                             f"stop()/close() must reach it via "
                             f"join_or_warn"))


@register_rule
class JoinOrWarnRule(Rule):
    id = "concurrency/join-or-warn"
    description = ("modules importing join_or_warn join their threads "
                   "through it, not a silent bare .join()")

    def visit_file(self, ctx: FileContext) -> Iterable[Finding]:
        if "join_or_warn" not in ctx.text:
            return
        imports_it = any(
            isinstance(n, ast.ImportFrom)
            and any(a.name == "join_or_warn" for a in n.names)
            for n in ast.walk(ctx.tree))
        if not imports_it:
            return
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            info = _analyze_class(cls)
            held = set(info.direct) | set(info.lists)
            for attr, line in info.bare_join_lines.items():
                if attr not in held or attr in info.join_or_warn_attrs:
                    continue
                yield Finding(
                    rule=self.id, path=ctx.rel, line=line,
                    anchor=f"{cls.name}.{attr}",
                    message=(f"{cls.name}.{attr} is joined with a bare "
                             f".join() although this module imports "
                             f"join_or_warn — a timed-out join here "
                             f"leaks the worker silently"))
