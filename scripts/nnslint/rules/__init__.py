"""Rule families. Importing this package registers every rule."""

from . import concurrency  # noqa: F401
from . import contracts  # noqa: F401
from . import jax_rules  # noqa: F401
from . import naming  # noqa: F401
from . import wire  # noqa: F401
