"""nnslint naming family implementation (moved verbatim from
scripts/check_metric_names.py, which remains as a compatibility shim).

Lint registered metric names, span names AND flight-recorder event
types against the repo naming conventions.

Metric convention (docs/observability.md): every metric is
``nnstpu_<layer>_<name>_<unit>`` with

  * layer  in {pipeline, query, serving, resilience, chaos, router,
    profile},
  * counters    ending in ``_total``,
  * histograms  ending in ``_seconds``,
  * gauges      ending in one of ``_depth`` / ``_slots`` / ``_bytes`` /
    ``_state`` / ``_pages`` / ``_ratio`` / ``_flops``,
  * label keys matching ``[a-z_][a-z0-9_]*``, never the reserved
    ``instance``/``role`` (appended by fleet federation) or ``le``
    (histogram encoder), and at most 8 keys per family (cardinality
    guard).

Span convention (docs/observability.md "Tracing"): every span name is
a literal lowercase dotted ``<layer>.<operation>`` with layer in
{pipeline, query, serving, device} — e.g. ``serving.prefill``.

Event convention (docs/observability.md "Health & flight recorder"):
every flight-recorder event type is the same lowercase dotted
``<layer>.<event>`` shape, with layer additionally allowing {core, obs}
(the log bridge and the obs subsystem itself emit events) — e.g.
``pipeline.stall``, ``query.reconnect_storm``, ``core.log``.

KV-cache placement (docs/performance.md "Paged KV cache"): every
``serving`` metric whose body starts with ``kv_`` belongs to the paged
KV cache and is registered in nnstreamer_tpu/serving/ — no other
package invents ``kv_*`` serving series, and the ``pages`` gauge unit
is reserved for those bodies (a ``_pages`` gauge outside the kv family
is a naming drift, not a new convention). check_kv enforces both
directions, mirroring check_resilience.

Resilience placement (docs/resilience.md): the ``resilience``/``chaos``
metric + event layers belong to nnstreamer_tpu/resilience/ — every
CircuitBreaker/RetryPolicy/FaultPlan series is registered there (other
modules record through its helpers), and conversely the resilience
package never registers under another layer's name. check_resilience
enforces both directions so policy telemetry can't drift into ad-hoc
per-module names.

Profile placement (docs/observability.md "Profiling"): the
``profile`` metric + event layer belongs to nnstreamer_tpu/obs/
profile.py — dispatch timing, jit-cache/compile telemetry, and the
MFU/roofline gauges are registered there only (other modules feed them
through the profiler hooks, never by minting profile.* names), and the
dimensionless ``ratio`` and ``flops`` gauge units are reserved to that
layer (an efficiency ratio elsewhere should be a profile gauge, not a
convention fork). check_profile enforces both directions, mirroring
check_resilience.

SLO placement (docs/observability.md "SLO & tenant accounting"): the
``slo`` metric + event layer belongs to nnstreamer_tpu/obs/slo.py —
per-tenant cost attribution, goodput counters, and burn-rate gauges
are registered there only (dispatch sites feed the accountant through
its hooks, never by minting slo.* names), and the ``tenant`` label is
reserved to obs/slo.py and nnstreamer_tpu/sched/ (everywhere else a
tenant-keyed series is an unbounded-cardinality bug — route it through
the SLO registry, which folds overflow tenants). The ``ratio`` gauge
unit reservation is shared with the profile layer
(``nnstpu_slo_burn_ratio``). check_slo enforces all three directions,
mirroring check_profile.

Fleet placement (docs/autoscale.md): ``nnstpu_fleet_*`` metric series,
``fleet.*`` spans, and the ``fleet.scale_*``/``fleet.migrate_*`` event
subfamilies belong to nnstreamer_tpu/fleet/ — the autoscale controller
and session migrator own the scaling/migration audit trail, while
obs/fleet.py keeps the pre-existing federation events (``fleet.push``,
``fleet.expire``, ...), which is why the fleet *event layer* as a whole
is not package-confined, only those two verb subfamilies. The
``replicas`` gauge unit is reserved to the fleet layer, and
``AUTOSCALE_HOOK`` is assigned only inside nnstreamer_tpu/fleet/ (its
None default plus enable()/disable()) — every other module reads it
behind a single None check, which is what keeps the scheduler's
occupancy tap zero-overhead while autoscaling is off. check_fleet
enforces all of it, mirroring check_tune.

Router placement (docs/resilience.md "Fleet routing & failover"): the
``router`` metric/span/event layer belongs to
nnstreamer_tpu/query/router.py — the multi-backend dispatch telemetry
(placement, failover, backend lifecycle) is registered there only.
check_router enforces it, mirroring check_resilience. Cardinality note:
the ``backend`` label on router series carries configured ``host:port``
endpoints — bounded by fleet size, NEVER per-request/session values.

Diag placement (docs/observability.md "Diagnostics & debug bundles"):
the ``diag`` metric/span/event layer belongs to nnstreamer_tpu/obs/
diag/ — the incident-diagnostics engine back-fills its synthetic
``diag.sched_wait``/``diag.sched_run`` spans (via SpanStore.add_span,
which this lint greps next to start_span) and emits its trigger/bundle
audit events there only, and ``DIAG_HOOK`` is assigned only inside
that package (None default plus enable()/disable()) — consumers read
it behind a single None check, keeping the sched/serving taps
zero-overhead while diagnostics are off. The Prometheus-conventional
``nnstpu_build_info`` identity gauge is exempt from the
<layer>_<name>_<unit> shape and pinned to obs/exporter.py. check_diag
enforces all of it, mirroring check_fleet.

Quality placement (docs/observability.md "Data-plane quality"): the
``quality`` metric/span/event layer belongs to nnstreamer_tpu/obs/
quality/ — per-tap tensor stats, drift gauges, and the anomaly audit
events are registered there only (the element/filter/decoder/serving
taps feed the engine through the None-gated ``QUALITY_HOOK``, never by
minting quality.* names), the ``psi`` gauge unit (population-stability
drift scores) is reserved to that layer, and ``QUALITY_HOOK`` is
assigned only inside that package (None default plus
enable()/disable()) — consumers read it behind a single None check,
which is what keeps the data-plane taps zero-overhead while quality
telemetry is off. check_quality enforces all of it, mirroring
check_diag.

The check greps source for literal first arguments of
``.counter(...)`` / ``.gauge(...)`` / ``.histogram(...)`` registry
calls, ``.start_span(...)`` / ``start_span(...)`` tracing calls, and
``events.record(...)`` / ``_events.record(...)`` / bare ``record(...)``
flight-recorder calls, so drift fails CI (wired as a tier-1 test:
tests/test_metric_names.py) the moment an off-convention name lands.
Registrations built from non-literal names are invisible to this lint —
keep names literal.

Exit 0 when clean; exit 1 listing every violation.
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parents[2]
SOURCE_ROOT = REPO_ROOT / "nnstreamer_tpu"

LAYERS = ("pipeline", "query", "serving", "resilience", "chaos",
          "router", "profile", "sched", "slo", "disagg", "tune",
          "fleet", "diag", "quality")

#: families exempt from the nnstpu_<layer>_<name>_<unit> shape: the
#: Prometheus-conventional ``<prefix>_build_info`` identity gauge has
#: no unit by design (value is constantly 1; the labels carry the
#: payload) — check_diag pins its one registration to obs/exporter.py
EXEMPT_NAMES = frozenset({"nnstpu_build_info"})
UNIT_BY_TYPE = {
    "counter": ("total",),
    "histogram": ("seconds",),
    # _state: enumerated-condition gauges (e.g. breaker 0/1/2);
    # _pages: KV-page pool occupancy (serving kv_ family only);
    # _ratio/_flops: utilization + roofline gauges (profile layer only);
    # _replicas: live-backend census (fleet controller only);
    # _psi: population-stability drift scores (quality layer only)
    "gauge": ("depth", "slots", "bytes", "state", "pages", "ratio",
              "flops", "replicas", "psi"),
}
#: span layers add "device" — device.xprof has no metric series —
#: and "router" (the dispatch span, query/router.py) and "disagg"
#: (the KV-page transfer span, serving/disagg.py) and "fleet" (the
#: live-migration span, fleet/migrate.py)
#: and "diag" (the synthetic queue-wait/batch-run spans the diag
#: engine back-fills into request traces via SpanStore.add_span,
#: obs/diag/)
SPAN_LAYERS = ("pipeline", "query", "serving", "device", "router",
               "disagg", "fleet", "diag", "quality")
#: event layers additionally allow "core" (the core/log.py bridge),
#: "obs" (the obs subsystem's own events), "fleet" (cross-process
#: federation: push/expiry/merge-conflict audit trail, obs/fleet.py),
#: "resilience"/"chaos" (fault-policy decisions + injected faults,
#: nnstreamer_tpu/resilience/), "router" (multi-backend placement:
#: failover/drain/spill audit trail, query/router.py), and "profile"
#: (capture start/stop audit trail, obs/profile.py), and "sched" (the
#: multi-tenant device scheduler: tenant lifecycle, bucket misses,
#: starvation reliefs — nnstreamer_tpu/sched/), and "slo" (per-tenant
#: SLO burn alerts/recoveries — obs/slo.py), and "disagg" (the
#: prefill/decode split: re-prefill fallbacks + page spills,
#: serving/disagg.py), and "tune" (the autotuner's sweep/adoption
#: audit trail, nnstreamer_tpu/tune/)
#: and "diag" (the incident-diagnostics subsystem: trigger fires and
#: bundle captures — obs/diag/)
EVENT_LAYERS = ("pipeline", "query", "serving", "device", "core", "obs",
                "fleet", "resilience", "chaos", "router", "profile",
                "sched", "slo", "disagg", "tune", "diag", "quality")

#: layers OWNED by the resilience package: registrations under these
#: names must live in RESILIENCE_DIR and vice versa (see module doc)
RESILIENCE_LAYERS = frozenset({"resilience", "chaos"})
RESILIENCE_DIR = "resilience"

#: the paged KV cache owns the ``kv_``-prefixed serving bodies and the
#: ``pages`` gauge unit: both must stay inside KV_DIR (see module doc)
KV_BODY_PREFIX = "kv_"
KV_DIR = "serving"

#: the ``router`` metric/span/event layer is owned by the query
#: router module alone (see module doc); the path is matched on its
#: final two parts so the lint follows the file, not an absolute root
ROUTER_LAYER = "router"
ROUTER_FILE = ("query", "router.py")

#: the ``profile`` metric/event layer is owned by the profiler module
#: alone, and the ``ratio``/``flops`` gauge units are reserved to it
#: (see module doc); matched like ROUTER_FILE
PROFILE_LAYER = "profile"
PROFILE_FILE = ("obs", "profile.py")
PROFILE_UNITS = frozenset({"ratio", "flops"})

#: the ``slo`` metric/event layer is owned by the per-tenant SLO
#: accountant alone (see module doc); matched like PROFILE_FILE. The
#: ``tenant`` label is bounded there (overflow folding) and in the
#: scheduler's registered-tenant series — anywhere else it is an
#: unbounded-cardinality drift
SLO_LAYER = "slo"
SLO_FILE = ("obs", "slo.py")
TENANT_LABEL = "tenant"

#: the ``sched`` metric/event layer is owned by the multi-tenant device
#: scheduler package (sched/telemetry.py centralizes every
#: registration; engine code and the xla bucket counters go through its
#: helpers — see module doc); matched on the package dir like
#: RESILIENCE_DIR
SCHED_LAYER = "sched"
SCHED_DIR = "sched"

#: the ``disagg`` metric/span/event layer is owned by the
#: disaggregated-serving module alone (see module doc); matched like
#: ROUTER_FILE
DISAGG_LAYER = "disagg"
DISAGG_FILE = ("serving", "disagg.py")

#: label names must be legal Prometheus label identifiers
LABEL_NAME_RE = re.compile(r"^[a-z_][a-z0-9_]*$")
#: labels the fleet layer/format owns: ``instance``/``role`` are
#: appended by the aggregator to every federated series, ``le`` by the
#: histogram encoder — a user metric declaring them would collide
RESERVED_LABELS = frozenset({"instance", "role", "le"})
#: cardinality guard: a family declaring more label keys than this is
#: a combinatorial-explosion bug, not a schema
MAX_LABEL_KEYS = 8

#: reg.counter("name"... — dotted call so plain functions named e.g.
#: ``gauge()`` elsewhere don't false-positive
_CALL_RE = re.compile(
    r"\.(counter|gauge|histogram)\(\s*[\"']([^\"']+)[\"']")

_NAME_RE = re.compile(
    r"^nnstpu_(?P<layer>[a-z0-9]+)_(?P<body>[a-z0-9_]+)_(?P<unit>[a-z0-9]+)$")

#: start_span("name"... — both module-level and store-method calls —
#: plus add_span("name"... (the diag engine's synthetic-span insertion
#: path takes the same literal first argument); \b keeps e.g.
#: ``restart_spanner(`` from matching
_SPAN_CALL_RE = re.compile(
    r"\b(?:start_span|add_span)\(\s*[\"']([^\"']+)[\"']")

_SPAN_NAME_RE = re.compile(
    r"^(?P<layer>[a-z]+)\.(?P<op>[a-z][a-z0-9_]*)$")

#: events.record("type"... / _events.record("type"... / a bare
#: record("type"... (module-internal call in obs/events.py). The
#: lookbehind keeps method calls on OTHER objects — ``stats.record(``,
#: ``._record(`` — from matching; those take no literal name anyway.
_EVENT_CALL_RE = re.compile(
    r"(?:(?<![\w.])record|\b(?:events|_events)\.record)"
    r"\(\s*[\"']([^\"']+)[\"']")

_EVENT_NAME_RE = re.compile(
    r"^(?P<layer>[a-z]+)\.(?P<event>[a-z][a-z0-9_]*)$")


def iter_registrations(root: Path = SOURCE_ROOT):
    """Yield (path, lineno, metric_type, name) for every literal-name
    registry call under ``root``."""
    for path in sorted(root.rglob("*.py")):
        text = path.read_text(encoding="utf-8")
        # whole-file scan: registrations routinely wrap the name onto
        # the line after the open paren (\s* spans newlines)
        for m in _CALL_RE.finditer(text):
            lineno = text.count("\n", 0, m.start()) + 1
            yield path, lineno, m.group(1), m.group(2)


def iter_label_decls(root: Path = SOURCE_ROOT):
    """Yield (path, lineno, name, labelnames) for every registry call
    whose label tuple/list is written as literals. AST-based (unlike
    the name greps) because label tuples routinely share lines with
    help strings containing parens; only literal elements are visible —
    keep label schemas literal, same rule as names."""
    for path in sorted(root.rglob("*.py")):
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"))
        except SyntaxError:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in UNIT_BY_TYPE):
                continue
            name = None
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                name = node.args[0].value
            if name is None:
                continue
            labels_node = node.args[2] if len(node.args) > 2 else None
            if labels_node is None:
                for kw in node.keywords:
                    if kw.arg == "labelnames":
                        labels_node = kw.value
            if not isinstance(labels_node, (ast.Tuple, ast.List)):
                continue
            labels = [e.value for e in labels_node.elts
                      if isinstance(e, ast.Constant)
                      and isinstance(e.value, str)]
            yield path, node.lineno, name, labels


def check_labels(root: Path = SOURCE_ROOT):
    """Label-name violations: illegal identifiers, reserved names, and
    families declaring more than MAX_LABEL_KEYS keys."""
    problems = []
    for path, lineno, name, labels in iter_label_decls(root):
        where = _where(path, lineno)
        for lbl in labels:
            if not LABEL_NAME_RE.match(lbl):
                problems.append(
                    f"{where}: {name!r} label {lbl!r} does not match "
                    f"{LABEL_NAME_RE.pattern}")
            elif lbl in RESERVED_LABELS:
                problems.append(
                    f"{where}: {name!r} label {lbl!r} is reserved "
                    f"(fleet federation appends instance/role; the "
                    f"histogram encoder owns le)")
        if len(labels) > MAX_LABEL_KEYS:
            problems.append(
                f"{where}: {name!r} declares {len(labels)} label keys "
                f"(> {MAX_LABEL_KEYS}) — cardinality guard")
    return problems


def iter_span_sites(root: Path = SOURCE_ROOT):
    """Yield (path, lineno, span_name) for every literal-name
    ``start_span`` call under ``root``."""
    for path in sorted(root.rglob("*.py")):
        text = path.read_text(encoding="utf-8")
        for m in _SPAN_CALL_RE.finditer(text):
            lineno = text.count("\n", 0, m.start()) + 1
            yield path, lineno, m.group(1)


def iter_event_sites(root: Path = SOURCE_ROOT):
    """Yield (path, lineno, event_type) for every literal-type
    flight-recorder ``record`` call under ``root``."""
    for path in sorted(root.rglob("*.py")):
        text = path.read_text(encoding="utf-8")
        for m in _EVENT_CALL_RE.finditer(text):
            lineno = text.count("\n", 0, m.start()) + 1
            yield path, lineno, m.group(1)


def _where(path: Path, lineno: int) -> str:
    rel = path.relative_to(REPO_ROOT) if REPO_ROOT in path.parents else path
    return f"{rel}:{lineno}"


def check_names(root: Path = SOURCE_ROOT):
    """Metric-name shape/layer/unit violations only (the original
    inline body of check(); split out so the nnslint registry can run
    it as its own rule)."""
    problems = []
    found = 0
    for path, lineno, mtype, name in iter_registrations(root):
        found += 1
        if name in EXEMPT_NAMES:
            continue  # identity gauges; ownership pinned by check_diag
        where = _where(path, lineno)
        m = _NAME_RE.match(name)
        if m is None:
            problems.append(
                f"{where}: {name!r} does not match "
                "nnstpu_<layer>_<name>_<unit>")
            continue
        if m.group("layer") not in LAYERS:
            problems.append(
                f"{where}: {name!r} layer {m.group('layer')!r} not in "
                f"{LAYERS}")
        units = UNIT_BY_TYPE[mtype]
        if m.group("unit") not in units:
            problems.append(
                f"{where}: {name!r} is a {mtype} but unit "
                f"{m.group('unit')!r} not in {units}")
    if found == 0:
        problems.append(
            f"no metric registrations found under {root} — "
            "lint regex out of sync with the registry API?")
    return problems


def check(root: Path = SOURCE_ROOT):
    """Return a list of violation strings (empty = clean)."""
    problems = check_names(root)
    problems += check_labels(root)
    problems += check_spans(root)
    problems += check_events(root)
    problems += check_resilience(root)
    problems += check_kv(root)
    problems += check_router(root)
    problems += check_profile(root)
    problems += check_sched(root)
    problems += check_slo(root)
    problems += check_quality(root)
    return problems


def _is_profile_file(path: Path) -> bool:
    return tuple(path.parts[-2:]) == PROFILE_FILE


def check_profile(root: Path = SOURCE_ROOT):
    """Placement lint for the profiler telemetry: every ``profile``-
    layer metric and event is emitted from nnstreamer_tpu/obs/
    profile.py (dispatch sites feed the profiler through its hooks,
    never by minting profile.* names), the profiler module registers
    under no other layer, and the dimensionless ``ratio``/``flops``
    gauge units stay reserved to the profile layer. Mirrors
    check_resilience + the check_kv unit reservation."""
    problems = []
    for path, lineno, _mtype, name in iter_registrations(root):
        m = _NAME_RE.match(name)
        if m is None:
            continue  # shape violations already reported by check()
        layer = m.group("layer")
        in_file = _is_profile_file(path)
        if layer == PROFILE_LAYER and not in_file:
            problems.append(
                f"{_where(path, lineno)}: {name!r} uses the "
                f"{PROFILE_LAYER!r} layer outside "
                f"nnstreamer_tpu/obs/profile.py — feed the profiler "
                f"through its hooks instead")
        elif in_file and layer != PROFILE_LAYER:
            problems.append(
                f"{_where(path, lineno)}: {name!r} registered inside "
                f"nnstreamer_tpu/obs/profile.py must use the "
                f"{PROFILE_LAYER!r} layer, not {layer!r}")
        elif m.group("unit") in PROFILE_UNITS \
                and layer not in (PROFILE_LAYER, SLO_LAYER):
            # the slo layer shares the dimensionless ``ratio`` unit
            # (burn rate is budget-normalized); check_slo pins those
            # registrations to obs/slo.py
            problems.append(
                f"{_where(path, lineno)}: {name!r} uses the "
                f"{m.group('unit')!r} gauge unit reserved for the "
                f"{PROFILE_LAYER!r}/{SLO_LAYER!r} layers")
    for path, lineno, name in iter_event_sites(root):
        m = _EVENT_NAME_RE.match(name)
        if m is None:
            continue
        if m.group("layer") == PROFILE_LAYER and not _is_profile_file(path):
            problems.append(
                f"{_where(path, lineno)}: event {name!r} uses the "
                f"{PROFILE_LAYER!r} layer outside "
                f"nnstreamer_tpu/obs/profile.py")
    return problems


def _is_router_file(path: Path) -> bool:
    return tuple(path.parts[-2:]) == ROUTER_FILE


def check_router(root: Path = SOURCE_ROOT):
    """Placement lint for the multi-backend routing telemetry: every
    ``router``-layer metric, span, and event is emitted from
    nnstreamer_tpu/query/router.py (other modules reach routing through
    QueryRouter, never by minting router.* names). The reverse
    direction stays loose on purpose — router.py legitimately emits
    under ``resilience`` via the policy helpers."""
    problems = []
    for path, lineno, _mtype, name in iter_registrations(root):
        m = _NAME_RE.match(name)
        if m is None:
            continue  # shape violations already reported by check()
        if m.group("layer") == ROUTER_LAYER and not _is_router_file(path):
            problems.append(
                f"{_where(path, lineno)}: {name!r} uses the "
                f"{ROUTER_LAYER!r} layer outside "
                f"nnstreamer_tpu/query/router.py — routing telemetry "
                f"lives with the router")
    for path, lineno, name in iter_span_sites(root):
        m = _SPAN_NAME_RE.match(name)
        if m is None:
            continue
        if m.group("layer") == ROUTER_LAYER and not _is_router_file(path):
            problems.append(
                f"{_where(path, lineno)}: span {name!r} uses the "
                f"{ROUTER_LAYER!r} layer outside "
                f"nnstreamer_tpu/query/router.py")
    for path, lineno, name in iter_event_sites(root):
        m = _EVENT_NAME_RE.match(name)
        if m is None:
            continue
        if m.group("layer") == ROUTER_LAYER and not _is_router_file(path):
            problems.append(
                f"{_where(path, lineno)}: event {name!r} uses the "
                f"{ROUTER_LAYER!r} layer outside "
                f"nnstreamer_tpu/query/router.py")
    return problems


def _is_disagg_file(path: Path) -> bool:
    return tuple(path.parts[-2:]) == DISAGG_FILE


def check_disagg(root: Path = SOURCE_ROOT):
    """Placement lint for the disaggregated-serving telemetry: every
    ``disagg``-layer metric, span, and event is emitted from
    nnstreamer_tpu/serving/disagg.py (engines and the router reach the
    split through DisaggClient/DisaggWorker, never by minting disagg.*
    names). The reverse direction stays loose on purpose — disagg.py
    legitimately rides the ``router`` and ``serving`` layers via the
    QueryRouter and kv_cache helpers it builds on. Mirrors
    check_router."""
    problems = []
    for path, lineno, _mtype, name in iter_registrations(root):
        m = _NAME_RE.match(name)
        if m is None:
            continue  # shape violations already reported by check()
        if m.group("layer") == DISAGG_LAYER and not _is_disagg_file(path):
            problems.append(
                f"{_where(path, lineno)}: {name!r} uses the "
                f"{DISAGG_LAYER!r} layer outside "
                f"nnstreamer_tpu/serving/disagg.py — disaggregation "
                f"telemetry lives with the split")
    for path, lineno, name in iter_span_sites(root):
        m = _SPAN_NAME_RE.match(name)
        if m is None:
            continue
        if m.group("layer") == DISAGG_LAYER and not _is_disagg_file(path):
            problems.append(
                f"{_where(path, lineno)}: span {name!r} uses the "
                f"{DISAGG_LAYER!r} layer outside "
                f"nnstreamer_tpu/serving/disagg.py")
    for path, lineno, name in iter_event_sites(root):
        m = _EVENT_NAME_RE.match(name)
        if m is None:
            continue
        if m.group("layer") == DISAGG_LAYER and not _is_disagg_file(path):
            problems.append(
                f"{_where(path, lineno)}: event {name!r} uses the "
                f"{DISAGG_LAYER!r} layer outside "
                f"nnstreamer_tpu/serving/disagg.py")
    return problems


def check_kv(root: Path = SOURCE_ROOT):
    """Placement lint for the paged-KV-cache telemetry: every
    ``serving`` metric with a ``kv_``-prefixed body is registered under
    nnstreamer_tpu/serving/ (the cache records its own pool/prefix
    series — other modules read them through the registry), and the
    ``pages`` gauge unit never appears outside that family."""
    problems = []
    for path, lineno, _mtype, name in iter_registrations(root):
        m = _NAME_RE.match(name)
        if m is None:
            continue  # shape violations already reported by check()
        is_kv = (m.group("layer") == "serving"
                 and m.group("body").startswith(KV_BODY_PREFIX))
        in_pkg = KV_DIR in path.parts
        if is_kv and not in_pkg:
            problems.append(
                f"{_where(path, lineno)}: {name!r} uses the serving "
                f"{KV_BODY_PREFIX}* body outside "
                f"nnstreamer_tpu/{KV_DIR}/ — the paged KV cache owns "
                f"that family")
        elif m.group("unit") == "pages" and not is_kv:
            problems.append(
                f"{_where(path, lineno)}: {name!r} uses the 'pages' "
                f"gauge unit reserved for serving "
                f"{KV_BODY_PREFIX}* bodies")
    return problems


def check_resilience(root: Path = SOURCE_ROOT):
    """Placement lint for the fault-policy telemetry: every metric in
    the ``resilience``/``chaos`` layers is registered under
    nnstreamer_tpu/resilience/ (breaker/retry/shed/fallback series are
    the policy objects' own — other modules go through their helpers),
    and the resilience package registers under no other layer."""
    problems = []
    for path, lineno, _mtype, name in iter_registrations(root):
        m = _NAME_RE.match(name)
        if m is None:
            continue  # shape violations already reported by check()
        layer = m.group("layer")
        in_pkg = RESILIENCE_DIR in path.parts
        if layer in RESILIENCE_LAYERS and not in_pkg:
            problems.append(
                f"{_where(path, lineno)}: {name!r} uses the {layer!r} "
                f"layer outside nnstreamer_tpu/{RESILIENCE_DIR}/ — "
                f"record through resilience.policy/chaos helpers instead")
        elif in_pkg and layer not in RESILIENCE_LAYERS:
            problems.append(
                f"{_where(path, lineno)}: {name!r} registered inside "
                f"nnstreamer_tpu/{RESILIENCE_DIR}/ must use a layer in "
                f"{sorted(RESILIENCE_LAYERS)}, not {layer!r}")
    return problems


def check_sched(root: Path = SOURCE_ROOT):
    """Placement lint for the device-scheduler telemetry: every metric
    and event in the ``sched`` layer is emitted from
    nnstreamer_tpu/sched/ (sched/telemetry.py centralizes the
    registrations; the xla bucket counters and engine events go through
    its helper functions, never by minting sched.* names elsewhere),
    and the sched package registers under no other layer. Mirrors
    check_resilience."""
    problems = []
    for path, lineno, _mtype, name in iter_registrations(root):
        m = _NAME_RE.match(name)
        if m is None:
            continue  # shape violations already reported by check()
        layer = m.group("layer")
        in_pkg = SCHED_DIR in path.parts
        if layer == SCHED_LAYER and not in_pkg:
            problems.append(
                f"{_where(path, lineno)}: {name!r} uses the "
                f"{SCHED_LAYER!r} layer outside nnstreamer_tpu/"
                f"{SCHED_DIR}/ — record through sched.telemetry "
                f"helpers instead")
        elif in_pkg and layer != SCHED_LAYER:
            problems.append(
                f"{_where(path, lineno)}: {name!r} registered inside "
                f"nnstreamer_tpu/{SCHED_DIR}/ must use the "
                f"{SCHED_LAYER!r} layer, not {layer!r}")
    for path, lineno, name in iter_event_sites(root):
        m = _EVENT_NAME_RE.match(name)
        if m is None:
            continue
        if m.group("layer") == SCHED_LAYER and SCHED_DIR not in path.parts:
            problems.append(
                f"{_where(path, lineno)}: event {name!r} uses the "
                f"{SCHED_LAYER!r} layer outside nnstreamer_tpu/"
                f"{SCHED_DIR}/")
    return problems


def _is_slo_file(path: Path) -> bool:
    return tuple(path.parts[-2:]) == SLO_FILE


def check_slo(root: Path = SOURCE_ROOT):
    """Placement lint for the per-tenant SLO accountant: every
    ``slo``-layer metric and event is emitted from
    nnstreamer_tpu/obs/slo.py (the scheduler, serving engines, and
    router feed it through the None-gated hooks, never by minting
    slo.* names), the accountant registers under no other layer, and
    the ``tenant`` label stays inside obs/slo.py + nnstreamer_tpu/
    sched/ — the two places that bound it (overflow folding / the
    registered-tenant set). Mirrors check_profile + the check_kv
    reservation, but for a label key instead of a unit."""
    problems = []
    for path, lineno, _mtype, name in iter_registrations(root):
        m = _NAME_RE.match(name)
        if m is None:
            continue  # shape violations already reported by check()
        layer = m.group("layer")
        in_file = _is_slo_file(path)
        if layer == SLO_LAYER and not in_file:
            problems.append(
                f"{_where(path, lineno)}: {name!r} uses the "
                f"{SLO_LAYER!r} layer outside nnstreamer_tpu/obs/"
                f"slo.py — feed the SLO accountant through its hooks "
                f"instead")
        elif in_file and layer != SLO_LAYER:
            problems.append(
                f"{_where(path, lineno)}: {name!r} registered inside "
                f"nnstreamer_tpu/obs/slo.py must use the "
                f"{SLO_LAYER!r} layer, not {layer!r}")
    for path, lineno, name, labels in iter_label_decls(root):
        if TENANT_LABEL in labels and not _is_slo_file(path) \
                and SCHED_DIR not in path.parts:
            problems.append(
                f"{_where(path, lineno)}: {name!r} declares the "
                f"{TENANT_LABEL!r} label outside nnstreamer_tpu/obs/"
                f"slo.py and nnstreamer_tpu/{SCHED_DIR}/ — per-tenant "
                f"series are bounded only there (cardinality guard)")
    for path, lineno, name in iter_event_sites(root):
        m = _EVENT_NAME_RE.match(name)
        if m is None:
            continue
        if m.group("layer") == SLO_LAYER and not _is_slo_file(path):
            problems.append(
                f"{_where(path, lineno)}: event {name!r} uses the "
                f"{SLO_LAYER!r} layer outside nnstreamer_tpu/obs/"
                f"slo.py")
    return problems


def check_spans(root: Path = SOURCE_ROOT):
    """Span-name violations under ``root``. Zero span sites is only a
    problem for the real source tree (the metric check already guards
    arbitrary roots; the tracing API might legitimately be absent from
    a tree under test)."""
    problems = []
    found = 0
    for path, lineno, name in iter_span_sites(root):
        found += 1
        where = _where(path, lineno)
        m = _SPAN_NAME_RE.match(name)
        if m is None:
            problems.append(
                f"{where}: span {name!r} does not match lowercase "
                "<layer>.<operation>")
            continue
        if m.group("layer") not in SPAN_LAYERS:
            problems.append(
                f"{where}: span {name!r} layer {m.group('layer')!r} "
                f"not in {SPAN_LAYERS}")
    if found == 0 and root == SOURCE_ROOT:
        problems.append(
            f"no start_span call sites found under {root} — "
            "lint regex out of sync with the tracing API?")
    return problems


def check_events(root: Path = SOURCE_ROOT):
    """Event-type violations under ``root``. Mirrors check_spans: zero
    event sites only flags the real source tree."""
    problems = []
    found = 0
    for path, lineno, name in iter_event_sites(root):
        found += 1
        where = _where(path, lineno)
        m = _EVENT_NAME_RE.match(name)
        if m is None:
            problems.append(
                f"{where}: event {name!r} does not match lowercase "
                "<layer>.<event>")
            continue
        if m.group("layer") not in EVENT_LAYERS:
            problems.append(
                f"{where}: event {name!r} layer {m.group('layer')!r} "
                f"not in {EVENT_LAYERS}")
    if found == 0 and root == SOURCE_ROOT:
        problems.append(
            f"no event record call sites found under {root} — "
            "lint regex out of sync with the events API?")
    return problems


#: literal-label KERNEL_HOOK call (ops/pallas entry points announce the
#: kernels baked into a compiled program); labels are the device-lane
#: vocabulary, so their shape and owner are pinned like metric names
_KERNEL_LABEL_RE = re.compile(r"KERNEL_HOOK\(\s*[\"']([^\"']+)[\"']")
_KERNEL_NAME_RE = re.compile(r"^pallas\.[a-z][a-z0-9_]*$")
PALLAS_DIR = ("ops", "pallas")

#: module-level assignment to the epilogue-fusion selection hook;
#: matches ``EPILOGUE_SELECT_HOOK = ...`` and ``_epi.EPILOGUE_SELECT_HOOK
#: = ...`` alike
_EPILOGUE_HOOK_ASSIGN_RE = re.compile(
    r"^\s*(?:\w+\s*\.\s*)*EPILOGUE_SELECT_HOOK\s*=[^=]", re.MULTILINE)
#: the hook's definition site and its installer (profile.enable/disable)
EPILOGUE_HOOK_OWNERS = (("ops", "epilogue.py"), ("obs", "profile.py"))


def check_epilogue(root: Path = SOURCE_ROOT):
    """Epilogue-fusion naming/placement lint.

    * Pallas kernel labels (literal ``KERNEL_HOOK("...")`` calls) match
      ``pallas.<snake_case>`` and are emitted only from
      nnstreamer_tpu/ops/pallas/ — the device-lane label vocabulary has
      one owner, like metric registrations (check_profile).
    * ``EPILOGUE_SELECT_HOOK`` is assigned only in ops/epilogue.py (its
      None default) and obs/profile.py (enable()/disable() install and
      clear) — every other module may only *read* it behind a single
      None check, which is what keeps the fusion pass zero-overhead
      while profiling is off.
    """
    problems = []
    for path in sorted(root.rglob("*.py")):
        text = path.read_text(encoding="utf-8")
        for m in _KERNEL_LABEL_RE.finditer(text):
            lineno = text.count("\n", 0, m.start()) + 1
            label = m.group(1)
            where = _where(path, lineno)
            if not _KERNEL_NAME_RE.match(label):
                problems.append(
                    f"{where}: Pallas kernel label {label!r} does not "
                    f"match {_KERNEL_NAME_RE.pattern}")
            elif tuple(path.parts[-3:-1]) != PALLAS_DIR:
                problems.append(
                    f"{where}: Pallas kernel label {label!r} emitted "
                    f"outside nnstreamer_tpu/ops/pallas/ — kernel entry "
                    f"points own their labels")
        for m in _EPILOGUE_HOOK_ASSIGN_RE.finditer(text):
            if tuple(path.parts[-2:]) in EPILOGUE_HOOK_OWNERS:
                continue
            lineno = text.count("\n", 0, m.start()) + 1
            problems.append(
                f"{_where(path, lineno)}: EPILOGUE_SELECT_HOOK assigned "
                f"outside ops/epilogue.py + obs/profile.py — consumers "
                f"read the hook behind one None check; only "
                f"profile.enable()/disable() install and clear it")
    return problems


#: the ``tune`` metric/event layer is owned by the autotuner package:
#: knob sites feed the tuner through the None-gated TUNE_HOOK; only the
#: tuner itself counts picks/trials/adoptions (see module doc)
TUNE_LAYER = "tune"
TUNE_DIR = "tune"
#: module-level assignment to the autotuner hook; matches
#: ``TUNE_HOOK = ...`` and ``_tune.TUNE_HOOK = ...`` alike (but not the
#: distinct fleet-side TUNE_PUSH_HOOK/TUNE_ADOPT_HOOK names)
_TUNE_HOOK_ASSIGN_RE = re.compile(
    r"^\s*(?:\w+\s*\.\s*)*TUNE_HOOK\s*=[^=]", re.MULTILINE)
#: the hook's definition site (tune/__init__.py enable()/disable()) and
#: the profiler, which may install/clear it the way it owns
#: EPILOGUE_SELECT_HOOK
TUNE_HOOK_OWNER_DIR = TUNE_DIR
TUNE_HOOK_OWNER_FILES = (("obs", "profile.py"),)


def _is_tune_pkg(path: Path) -> bool:
    return path.parts[-2] == TUNE_DIR


def check_tune(root: Path = SOURCE_ROOT):
    """Autotuner naming/placement lint.

    * ``tune``-layer metrics (``nnstpu_tune_*``) are registered only
      under nnstreamer_tpu/tune/, and registrations inside that package
      use no other layer — the tuner counts its own picks/sweeps/
      adoptions; knob call sites ship no telemetry of their own.
    * ``tune.*`` events are emitted only from nnstreamer_tpu/tune/.
    * ``TUNE_HOOK`` is assigned only inside nnstreamer_tpu/tune/ (the
      None default plus enable()/disable()) and obs/profile.py — every
      other module may only *read* it behind a single None check, which
      is what keeps every wired knob site zero-overhead while tuning
      is off. Mirrors check_epilogue's EPILOGUE_SELECT_HOOK rule.
    """
    problems = []
    for path, lineno, _mtype, name in iter_registrations(root):
        m = _NAME_RE.match(name)
        if m is None:
            continue  # shape violations already reported by check()
        layer = m.group("layer")
        in_pkg = _is_tune_pkg(path)
        if layer == TUNE_LAYER and not in_pkg:
            problems.append(
                f"{_where(path, lineno)}: {name!r} uses the "
                f"{TUNE_LAYER!r} layer outside nnstreamer_tpu/tune/ — "
                f"knob sites feed the tuner through TUNE_HOOK; only "
                f"the tuner counts its own resolutions")
        elif in_pkg and layer != TUNE_LAYER:
            problems.append(
                f"{_where(path, lineno)}: {name!r} registered inside "
                f"nnstreamer_tpu/tune/ must use the {TUNE_LAYER!r} "
                f"layer, not {layer!r}")
    for path, lineno, name in iter_event_sites(root):
        m = _EVENT_NAME_RE.match(name)
        if m is None:
            continue
        if m.group("layer") == TUNE_LAYER and not _is_tune_pkg(path):
            problems.append(
                f"{_where(path, lineno)}: event {name!r} uses the "
                f"{TUNE_LAYER!r} layer outside nnstreamer_tpu/tune/")
    for path in sorted(root.rglob("*.py")):
        text = path.read_text(encoding="utf-8")
        for m in _TUNE_HOOK_ASSIGN_RE.finditer(text):
            if _is_tune_pkg(path) \
                    or tuple(path.parts[-2:]) in TUNE_HOOK_OWNER_FILES:
                continue
            lineno = text.count("\n", 0, m.start()) + 1
            problems.append(
                f"{_where(path, lineno)}: TUNE_HOOK assigned outside "
                f"nnstreamer_tpu/tune/ + obs/profile.py — consumers "
                f"read the hook behind one None check; only "
                f"tune.enable()/disable() install and clear it")
    return problems


#: the ``fleet`` *metric* layer and ``fleet.*`` spans are owned by the
#: autoscale package; the fleet *event* layer is shared with obs/
#: fleet.py (federation audit trail predates the controller), so only
#: the controller's verb subfamilies are package-confined
FLEET_LAYER = "fleet"
FLEET_DIR = "fleet"
#: event subfamilies the controller/migrator own: fleet.scale_up,
#: fleet.scale_in, fleet.migrate_start/done/abandon — obs/fleet.py
#: keeps fleet.push/expire/merge_conflict/drain_confirmed/...
FLEET_EVENT_PREFIXES = ("scale_", "migrate_")
#: the ``replicas`` gauge unit is the controller's census vocabulary
FLEET_UNITS = frozenset({"replicas"})
#: module-level assignment to the autoscale hook; matches
#: ``AUTOSCALE_HOOK = ...`` and ``_fleet.AUTOSCALE_HOOK = ...`` alike
_FLEET_HOOK_ASSIGN_RE = re.compile(
    r"^\s*(?:\w+\s*\.\s*)*AUTOSCALE_HOOK\s*=[^=]", re.MULTILINE)


def _is_fleet_pkg(path: Path) -> bool:
    return path.parts[-2] == FLEET_DIR


def check_fleet(root: Path = SOURCE_ROOT):
    """Autoscaler naming/placement lint.

    * ``fleet``-layer metrics (``nnstpu_fleet_*``) are registered only
      under nnstreamer_tpu/fleet/, and registrations inside that
      package use no other layer — the controller counts its own
      scale actions and migrations; obs/fleet.py (the federation
      aggregator) registers nothing.
    * the ``replicas`` gauge unit stays reserved to the fleet layer
      (a replica census elsewhere should route through the
      controller, not fork the convention).
    * ``fleet.*`` spans are emitted only from nnstreamer_tpu/fleet/.
    * ``fleet.scale_*`` / ``fleet.migrate_*`` events are emitted only
      from nnstreamer_tpu/fleet/ — the fleet event layer itself stays
      open because obs/fleet.py owns the federation subfamily
      (fleet.push, fleet.expire, fleet.drain_confirmed, ...).
    * ``AUTOSCALE_HOOK`` is assigned only inside nnstreamer_tpu/fleet/
      (the None default plus enable()/disable()) — every other module
      may only *read* it behind a single None check, which keeps the
      scheduler's occupancy tap zero-overhead while autoscaling is
      off. Mirrors check_tune's TUNE_HOOK rule.
    """
    problems = []
    for path, lineno, _mtype, name in iter_registrations(root):
        m = _NAME_RE.match(name)
        if m is None:
            continue  # shape violations already reported by check()
        layer = m.group("layer")
        in_pkg = _is_fleet_pkg(path)
        if layer == FLEET_LAYER and not in_pkg:
            problems.append(
                f"{_where(path, lineno)}: {name!r} uses the "
                f"{FLEET_LAYER!r} layer outside nnstreamer_tpu/fleet/ "
                f"— scaling telemetry lives with the controller")
        elif in_pkg and layer != FLEET_LAYER:
            problems.append(
                f"{_where(path, lineno)}: {name!r} registered inside "
                f"nnstreamer_tpu/fleet/ must use the {FLEET_LAYER!r} "
                f"layer, not {layer!r}")
        elif m.group("unit") in FLEET_UNITS and layer != FLEET_LAYER:
            problems.append(
                f"{_where(path, lineno)}: {name!r} uses the "
                f"{m.group('unit')!r} gauge unit reserved for the "
                f"{FLEET_LAYER!r} layer")
    for path, lineno, name in iter_span_sites(root):
        m = _SPAN_NAME_RE.match(name)
        if m is None:
            continue
        if m.group("layer") == FLEET_LAYER and not _is_fleet_pkg(path):
            problems.append(
                f"{_where(path, lineno)}: span {name!r} uses the "
                f"{FLEET_LAYER!r} layer outside nnstreamer_tpu/fleet/")
    for path, lineno, name in iter_event_sites(root):
        m = _EVENT_NAME_RE.match(name)
        if m is None:
            continue
        if m.group("layer") == FLEET_LAYER \
                and m.group("event").startswith(FLEET_EVENT_PREFIXES) \
                and not _is_fleet_pkg(path):
            problems.append(
                f"{_where(path, lineno)}: event {name!r} uses a fleet "
                f"scale_*/migrate_* subfamily outside nnstreamer_tpu/"
                f"fleet/ — the controller owns the scaling audit trail")
    for path in sorted(root.rglob("*.py")):
        text = path.read_text(encoding="utf-8")
        for m in _FLEET_HOOK_ASSIGN_RE.finditer(text):
            if _is_fleet_pkg(path):
                continue
            lineno = text.count("\n", 0, m.start()) + 1
            problems.append(
                f"{_where(path, lineno)}: AUTOSCALE_HOOK assigned "
                f"outside nnstreamer_tpu/fleet/ — consumers read the "
                f"hook behind one None check; only fleet.enable()/"
                f"disable() install and clear it")
    return problems


#: fleet/checkpoint.py's vocabulary: the crash-checkpoint metric
#: families, the fleet.checkpoint_*/restore_* event subfamilies, and
#: the CHECKPOINT_HOOK push-doc tap
CHECKPOINT_METRIC_PREFIXES = ("nnstpu_fleet_checkpoint_",
                              "nnstpu_fleet_restore_",
                              "nnstpu_fleet_restored_")
CHECKPOINT_EVENT_PREFIXES = ("checkpoint_", "restore_")
#: module-level assignment to the checkpoint watermark hook; matches
#: ``CHECKPOINT_HOOK = ...`` and ``_obsfleet.CHECKPOINT_HOOK = ...``
_CKPT_HOOK_ASSIGN_RE = re.compile(
    r"^[ \t]*(?:\w+[ \t]*\.[ \t]*)*CHECKPOINT_HOOK[ \t]*=[^=]",
    re.MULTILINE)
#: the hook's None default lives on the push-doc schema owner,
#: obs/fleet.py — the one assignment allowed outside fleet/
CKPT_HOOK_HOME = ("obs", "fleet.py")


def check_checkpoint(root: Path = SOURCE_ROOT):
    """Crash-checkpoint naming/placement lint (check_fleet's sibling).

    * the ``nnstpu_fleet_checkpoint_*`` / ``nnstpu_fleet_restore_*`` /
      ``nnstpu_fleet_restored_*`` metric families are registered only
      under nnstreamer_tpu/fleet/ — snapshot and restore accounting
      lives with the daemon and restorer, not scattered across the
      serving wire that merely carries the blobs.
    * ``fleet.checkpoint_*`` / ``fleet.restore_*`` events are emitted
      only from nnstreamer_tpu/fleet/ — the scale_*/migrate_* rule's
      sibling: one audit trail per subsystem owner.
    * ``CHECKPOINT_HOOK`` is assigned only inside nnstreamer_tpu/
      fleet/ (the daemon's install_hook()/uninstall_hook()), plus the
      ``= None`` default on obs/fleet.py where the hook lives —
      everything else reads it behind one None check, so push docs
      stay zero-overhead when no daemon runs.
    """
    problems = []
    for path, lineno, _mtype, name in iter_registrations(root):
        if name.startswith(CHECKPOINT_METRIC_PREFIXES) \
                and not _is_fleet_pkg(path):
            problems.append(
                f"{_where(path, lineno)}: {name!r} uses the fleet "
                f"checkpoint/restore metric family outside "
                f"nnstreamer_tpu/fleet/ — snapshot accounting lives "
                f"with the checkpoint daemon")
    for path, lineno, name in iter_event_sites(root):
        m = _EVENT_NAME_RE.match(name)
        if m is None:
            continue
        if m.group("layer") == FLEET_LAYER \
                and m.group("event").startswith(CHECKPOINT_EVENT_PREFIXES) \
                and not _is_fleet_pkg(path):
            problems.append(
                f"{_where(path, lineno)}: event {name!r} uses the fleet "
                f"checkpoint_*/restore_* subfamily outside "
                f"nnstreamer_tpu/fleet/ — the daemon and restorer own "
                f"the crash audit trail")
    for path in sorted(root.rglob("*.py")):
        text = path.read_text(encoding="utf-8")
        for m in _CKPT_HOOK_ASSIGN_RE.finditer(text):
            if _is_fleet_pkg(path):
                continue
            line = text[m.start():].splitlines()[0]
            if tuple(path.parts[-2:]) == CKPT_HOOK_HOME \
                    and line.split("=", 1)[1].strip() == "None":
                continue  # the hook's None default on its home module
            lineno = text.count("\n", 0, m.start()) + 1
            problems.append(
                f"{_where(path, lineno)}: CHECKPOINT_HOOK assigned "
                f"outside nnstreamer_tpu/fleet/ — consumers read the "
                f"hook behind one None check; only the daemon's "
                f"install_hook()/uninstall_hook() write it")
    return problems


#: the ``diag`` metric/span/event layer is owned by the incident-
#: diagnostics package (obs/diag/): synthetic queue-wait/batch-run
#: spans, trigger/bundle events, and any diag series are emitted
#: there only. The ``nnstpu_build_info`` identity gauge is registered
#: once, in obs/exporter.py (it serves /debug/version too).
DIAG_LAYER = "diag"
DIAG_PKG = ("obs", "diag")
BUILD_INFO_NAME = "nnstpu_build_info"
BUILD_INFO_FILE = ("obs", "exporter.py")
#: module-level assignment to the diag hook; matches ``DIAG_HOOK =
#: ...`` and ``_diag.DIAG_HOOK = ...`` alike. Cannot match the
#: distinct fleet-side ``DIAG_PUSH_HOOK`` name (obs/fleet.py owns
#: that slot; diag.enable()/disable() install and clear it)
_DIAG_HOOK_ASSIGN_RE = re.compile(
    r"^\s*(?:\w+\s*\.\s*)*DIAG_HOOK\s*=[^=]", re.MULTILINE)


def _is_diag_pkg(path: Path) -> bool:
    return tuple(path.parts[-3:-1]) == DIAG_PKG


def check_diag(root: Path = SOURCE_ROOT):
    """Incident-diagnostics naming/placement lint.

    * ``diag``-layer metrics are registered only under
      nnstreamer_tpu/obs/diag/, and the ``nnstpu_build_info`` identity
      gauge (exempt from the <layer>_<name>_<unit> shape) only in
      obs/exporter.py.
    * ``diag.*`` spans — the synthetic sched_wait/sched_run spans the
      engine back-fills via ``SpanStore.add_span`` — are created only
      from nnstreamer_tpu/obs/diag/.
    * ``diag.*`` events are emitted only from nnstreamer_tpu/obs/diag/.
    * ``DIAG_HOOK`` is assigned only inside nnstreamer_tpu/obs/diag/
      (the None default plus enable()/disable()) — every other module
      may only *read* it behind a single None check, which is what
      keeps the scheduler and serving taps zero-overhead while
      diagnostics are off. Mirrors check_fleet's AUTOSCALE_HOOK rule.
    """
    problems = []
    for path, lineno, _mtype, name in iter_registrations(root):
        if name == BUILD_INFO_NAME:
            if tuple(path.parts[-2:]) != BUILD_INFO_FILE:
                problems.append(
                    f"{_where(path, lineno)}: {name!r} registered "
                    f"outside nnstreamer_tpu/obs/exporter.py — the "
                    f"build-identity gauge has one owner")
            continue
        m = _NAME_RE.match(name)
        if m is None:
            continue  # shape violations already reported by check()
        if m.group("layer") == DIAG_LAYER and not _is_diag_pkg(path):
            problems.append(
                f"{_where(path, lineno)}: {name!r} uses the "
                f"{DIAG_LAYER!r} layer outside nnstreamer_tpu/obs/"
                f"diag/ — diagnostics telemetry lives with the engine")
    for path, lineno, name in iter_span_sites(root):
        m = _SPAN_NAME_RE.match(name)
        if m is None:
            continue
        if m.group("layer") == DIAG_LAYER and not _is_diag_pkg(path):
            problems.append(
                f"{_where(path, lineno)}: span {name!r} uses the "
                f"{DIAG_LAYER!r} layer outside nnstreamer_tpu/obs/"
                f"diag/ — only the diag engine back-fills synthetic "
                f"spans")
    for path, lineno, name in iter_event_sites(root):
        m = _EVENT_NAME_RE.match(name)
        if m is None:
            continue
        if m.group("layer") == DIAG_LAYER and not _is_diag_pkg(path):
            problems.append(
                f"{_where(path, lineno)}: event {name!r} uses the "
                f"{DIAG_LAYER!r} layer outside nnstreamer_tpu/obs/"
                f"diag/")
    for path in sorted(root.rglob("*.py")):
        text = path.read_text(encoding="utf-8")
        for m in _DIAG_HOOK_ASSIGN_RE.finditer(text):
            if _is_diag_pkg(path):
                continue
            lineno = text.count("\n", 0, m.start()) + 1
            problems.append(
                f"{_where(path, lineno)}: DIAG_HOOK assigned outside "
                f"nnstreamer_tpu/obs/diag/ — consumers read the hook "
                f"behind one None check; only diag.enable()/disable() "
                f"install and clear it")
    return problems


#: the ``quality`` metric/span/event layer is owned by the data-plane
#: quality package (obs/quality/): per-tap stat/drift series and the
#: anomaly audit events are emitted there only, and the ``psi`` gauge
#: unit (population-stability drift scores) is reserved to it
QUALITY_LAYER = "quality"
QUALITY_PKG = ("obs", "quality")
QUALITY_UNITS = frozenset({"psi"})
#: module-level assignment to the quality hook; matches
#: ``QUALITY_HOOK = ...`` and ``_quality.QUALITY_HOOK = ...`` alike
_QUALITY_HOOK_ASSIGN_RE = re.compile(
    r"^\s*(?:\w+\s*\.\s*)*QUALITY_HOOK\s*=[^=]", re.MULTILINE)


def _is_quality_pkg(path: Path) -> bool:
    return tuple(path.parts[-3:-1]) == QUALITY_PKG


def check_quality(root: Path = SOURCE_ROOT):
    """Data-plane quality naming/placement lint.

    * ``quality``-layer metrics are registered only under
      nnstreamer_tpu/obs/quality/, and the ``psi`` gauge unit stays
      reserved to that layer (a drift score elsewhere should route
      through the quality engine, not fork the convention).
    * ``quality.*`` spans and events are emitted only from
      nnstreamer_tpu/obs/quality/.
    * ``QUALITY_HOOK`` is assigned only inside nnstreamer_tpu/obs/
      quality/ (the None default plus enable()/disable()) — every
      other module may only *read* it behind a single None check,
      which is what keeps the element/filter/decoder/serving taps
      zero-overhead while quality telemetry is off. Mirrors
      check_diag's DIAG_HOOK rule.
    """
    problems = []
    for path, lineno, _mtype, name in iter_registrations(root):
        m = _NAME_RE.match(name)
        if m is None:
            continue  # shape violations already reported by check()
        layer = m.group("layer")
        in_pkg = _is_quality_pkg(path)
        if layer == QUALITY_LAYER and not in_pkg:
            problems.append(
                f"{_where(path, lineno)}: {name!r} uses the "
                f"{QUALITY_LAYER!r} layer outside nnstreamer_tpu/obs/"
                f"quality/ — taps feed the engine through QUALITY_HOOK;"
                f" only it counts its own observations")
        elif m.group("unit") in QUALITY_UNITS and layer != QUALITY_LAYER:
            problems.append(
                f"{_where(path, lineno)}: {name!r} uses the "
                f"{m.group('unit')!r} gauge unit reserved for the "
                f"{QUALITY_LAYER!r} layer")
    for path, lineno, name in iter_span_sites(root):
        m = _SPAN_NAME_RE.match(name)
        if m is None:
            continue
        if m.group("layer") == QUALITY_LAYER and not _is_quality_pkg(path):
            problems.append(
                f"{_where(path, lineno)}: span {name!r} uses the "
                f"{QUALITY_LAYER!r} layer outside nnstreamer_tpu/obs/"
                f"quality/")
    for path, lineno, name in iter_event_sites(root):
        m = _EVENT_NAME_RE.match(name)
        if m is None:
            continue
        if m.group("layer") == QUALITY_LAYER and not _is_quality_pkg(path):
            problems.append(
                f"{_where(path, lineno)}: event {name!r} uses the "
                f"{QUALITY_LAYER!r} layer outside nnstreamer_tpu/obs/"
                f"quality/")
    for path in sorted(root.rglob("*.py")):
        text = path.read_text(encoding="utf-8")
        for m in _QUALITY_HOOK_ASSIGN_RE.finditer(text):
            if _is_quality_pkg(path):
                continue
            lineno = text.count("\n", 0, m.start()) + 1
            problems.append(
                f"{_where(path, lineno)}: QUALITY_HOOK assigned "
                f"outside nnstreamer_tpu/obs/quality/ — consumers read "
                f"the hook behind one None check; only "
                f"quality.enable()/disable() install and clear it")
    return problems


def main() -> int:
    problems = check()
    if problems:
        for p in problems:
            print(p, file=sys.stderr)
        print(f"{len(problems)} naming violation(s)", file=sys.stderr)
        return 1
    n = sum(1 for _ in iter_registrations())
    nl = sum(len(labels) for *_x, labels in iter_label_decls())
    ns = sum(1 for _ in iter_span_sites())
    ne = sum(1 for _ in iter_event_sites())
    print(f"metric names OK ({n} registrations checked); "
          f"labels OK ({nl} label keys checked); "
          f"span names OK ({ns} call sites checked); "
          f"event names OK ({ne} call sites checked)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
