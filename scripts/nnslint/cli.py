"""nnslint CLI — ``python -m scripts.nnslint [paths] [options]``.

Exit codes (stable, scripted against by CI):

* ``0`` — no non-baselined findings (and no stale baseline entries
  when ``--strict-baseline``);
* ``1`` — at least one new finding;
* ``2`` — usage or internal error.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import List, Optional

from . import baseline as _baseline
from .core import DEFAULT_ROOT, REPO_ROOT, all_rules, run_lint

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_ERROR = 2


def _parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m scripts.nnslint",
        description=("Project static analysis: concurrency discipline, "
                     "hot-path contracts, JAX tracing hazards, wire "
                     "completeness, telemetry naming."))
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to lint "
                        "(default: nnstreamer_tpu/)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--select", action="append", default=None,
                   metavar="RULE",
                   help="run only these rule ids or families "
                        "(repeatable)")
    p.add_argument("--baseline", type=Path,
                   default=_baseline.DEFAULT_BASELINE,
                   help="baseline file (default: %(default)s)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline: report every finding")
    p.add_argument("--update-baseline", action="store_true",
                   help="rewrite the baseline from current findings "
                        "and exit 0 (review the diff)")
    p.add_argument("--list-rules", action="store_true",
                   help="list registered rules and exit")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    try:
        args = _parser().parse_args(argv)
    except SystemExit as e:
        return EXIT_ERROR if e.code not in (0, None) else EXIT_CLEAN
    if args.list_rules:
        for rid, rule in sorted(all_rules().items()):
            print(f"{rid:28s} {rule.description}")
        return EXIT_CLEAN
    roots = [Path(p) for p in args.paths] if args.paths else [DEFAULT_ROOT]
    for r in roots:
        if not r.exists():
            print(f"nnslint: no such path: {r}", file=sys.stderr)
            return EXIT_ERROR
    try:
        result = run_lint(roots, select=args.select)
    except Exception as e:  # noqa: BLE001 — tool crash is exit 2, not a lint verdict
        print(f"nnslint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return EXIT_ERROR
    if args.update_baseline:
        n = _baseline.save(result.findings, args.baseline)
        print(f"nnslint: baseline rewritten with {n} entr"
              f"{'y' if n == 1 else 'ies'} at {args.baseline}")
        return EXIT_CLEAN
    keys = set() if args.no_baseline else _baseline.load(args.baseline)
    new, grandfathered, stale = _baseline.split(result.findings, keys)
    if args.as_json:
        print(json.dumps({
            "findings": [f.to_json() for f in new],
            "grandfathered": [f.to_json() for f in grandfathered],
            "stale_baseline_keys": sorted(stale),
            "suppressed": result.suppressed,
            "files": result.files,
            "rules": result.rules,
        }, indent=1))
    else:
        for f in new:
            print(str(f), file=sys.stderr)
        if new:
            print(f"nnslint: {len(new)} finding(s) "
                  f"({len(grandfathered)} baselined, "
                  f"{result.suppressed} suppressed)", file=sys.stderr)
        else:
            print(f"nnslint OK: {result.files} files, {result.rules} "
                  f"rules, {len(grandfathered)} baselined finding(s), "
                  f"{result.suppressed} suppressed")
            if stale:
                print(f"nnslint: note: {len(stale)} stale baseline "
                      f"entr{'y' if len(stale) == 1 else 'ies'} — run "
                      f"--update-baseline and commit the shrink")
    return EXIT_FINDINGS if new else EXIT_CLEAN
