"""tensor_trainer element + checkpoint utils tests."""

import numpy as np
import pytest

from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline
from nnstreamer_tpu.models.zoo import ModelBundle


def caps_of(dims, types, rate=30):
    return Caps.tensors(TensorsConfig(TensorsInfo.from_strings(dims, types), rate))


def linear_bundle(seed=0):
    import jax

    w = jax.random.normal(jax.random.PRNGKey(seed), (8, 4)) * 0.1
    return ModelBundle("linear", lambda p, x: x @ p, params=w)


class TestTrainerElement:
    def _data(self, n=20):
        rng = np.random.default_rng(0)
        true_w = rng.normal(size=(8, 4)).astype(np.float32)
        xs = rng.normal(size=(n, 4, 8)).astype(np.float32)
        ys = np.argmax(xs @ true_w, axis=-1).astype(np.int32)
        return [(x, y) for x, y in zip(xs, ys)]

    def test_online_training_reduces_loss(self, tmp_path):
        ckpt = tmp_path / "trained.msgpack"
        p = Pipeline()
        src = p.add_new("appsrc", caps=caps_of("8:4,4", "float32,int32"),
                        data=self._data())
        tr = p.add_new("tensor_trainer", model=linear_bundle(),
                       learning_rate=0.05, checkpoint_path=str(ckpt),
                       report_every=5)
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, tr, sink)
        p.run(timeout=60)
        losses = list(tr.losses)  # bounded deque
        assert len(losses) == 20
        assert np.mean(losses[-5:]) < np.mean(losses[:5])
        assert sink.buffers[0].meta["loss"] > 0
        assert ckpt.exists()
        # bus received progress reports
        reports = []
        while True:
            m = p.bus.pop()
            if m is None:
                break
            if m.data.get("trainer"):
                reports.append(m)
        assert any("loss" in r.data for r in reports)

    def test_trained_params_deployable(self):
        p = Pipeline()
        src = p.add_new("appsrc", caps=caps_of("8:4,4", "float32,int32"),
                        data=self._data(10))
        tr = p.add_new("tensor_trainer", model=linear_bundle(),
                       learning_rate=0.05)
        sink = p.add_new("fakesink")
        Pipeline.link(src, tr, sink)
        p.run(timeout=60)
        bundle = tr.trained_bundle()
        out = bundle.fn()(np.ones((1, 8), np.float32))
        assert np.asarray(out).shape == (1, 4)

    def test_single_tensor_frame_rejected(self):
        from nnstreamer_tpu.graph import PipelineError

        p = Pipeline()
        src = p.add_new("appsrc", caps=caps_of("8:1", "float32"),
                        data=[np.ones((1, 8), np.float32)])
        tr = p.add_new("tensor_trainer", model=linear_bundle())
        sink = p.add_new("fakesink")
        Pipeline.link(src, tr, sink)
        with pytest.raises(PipelineError, match="expects"):
            p.run(timeout=30)


class TestCheckpoints:
    def test_msgpack_roundtrip(self, tmp_path):
        from nnstreamer_tpu.utils import checkpoints

        params = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
                  "b": np.zeros(3, np.float32)}
        path = str(tmp_path / "p.msgpack")
        checkpoints.save_variables(path, params)
        loaded = checkpoints.load_variables(
            path, {"w": np.zeros((2, 3), np.float32),
                   "b": np.ones(3, np.float32)})
        np.testing.assert_array_equal(loaded["w"], params["w"])

    def test_orbax_roundtrip(self, tmp_path):
        from nnstreamer_tpu.utils import checkpoints

        params = {"w": np.ones((4, 4), np.float32)}
        path = str(tmp_path / "ckpt")
        try:
            checkpoints.save_variables(path, params)
        except Exception as e:
            pytest.skip(f"orbax unavailable in env: {e}")
        loaded = checkpoints.load_variables(path,
                                            {"w": np.zeros((4, 4), np.float32)})
        np.testing.assert_array_equal(loaded["w"], params["w"])


class TestShardedTrainer:
    def test_mesh_prop_trains_sharded(self):
        """mesh="data:4,model:2": the in-pipeline step runs over the
        8-device mesh (params sharded, loss decreasing)."""
        import jax

        rng = np.random.default_rng(0)
        true_w = rng.normal(size=(8, 4)).astype(np.float32)
        data = []
        for _ in range(16):
            x = rng.normal(size=(4, 8)).astype(np.float32)  # batch 4
            y = np.argmax(x @ true_w, axis=-1).astype(np.int32)
            data.append((x, y))
        p = Pipeline()
        src = p.add_new("appsrc", caps=caps_of("8:4,4", "float32,int32"),
                        data=data)
        tr = p.add_new("tensor_trainer", model=linear_bundle(),
                       learning_rate=0.05, mesh="data:4,model:2")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, tr, sink)
        p.run(timeout=120)
        losses = list(tr.losses)
        assert len(losses) == 16
        assert np.mean(losses[-4:]) < np.mean(losses[:4])
        # params actually live sharded on the mesh
        leaf = jax.tree_util.tree_leaves(tr.params)[0]
        assert len(leaf.sharding.device_set) == 8

    def test_mesh_prop_accepts_dict(self):
        rng = np.random.default_rng(1)
        data = [(rng.normal(size=(2, 8)).astype(np.float32),
                 np.zeros(2, np.int32)) for _ in range(3)]
        p = Pipeline()
        src = p.add_new("appsrc", caps=caps_of("8:2,2", "float32,int32"),
                        data=data)
        tr = p.add_new("tensor_trainer", model=linear_bundle(),
                       mesh={"data": 2})
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, tr, sink)
        p.run(timeout=120)
        assert len(tr.losses) == 3

    @pytest.mark.parametrize("bad", ["data", "data:", ":4", "data:x"])
    def test_malformed_mesh_string_clear_error(self, bad):
        p = Pipeline()
        src = p.add_new("appsrc", caps=caps_of("8:2,2", "float32,int32"),
                        data=[(np.zeros((2, 8), np.float32),
                               np.zeros(2, np.int32))])
        tr = p.add_new("tensor_trainer", model=linear_bundle(), mesh=bad)
        sink = p.add_new("tensor_sink")
        Pipeline.link(src, tr, sink)
        from nnstreamer_tpu.graph.pipeline import PipelineError

        with pytest.raises((PipelineError, ValueError), match="mesh"):
            p.run(timeout=30)

    def test_empty_mesh_string_is_unsharded(self):
        p = Pipeline()
        src = p.add_new("appsrc", caps=caps_of("8:2,2", "float32,int32"),
                        data=[(np.zeros((2, 8), np.float32),
                               np.zeros(2, np.int32))] * 2)
        tr = p.add_new("tensor_trainer", model=linear_bundle(), mesh="")
        sink = p.add_new("tensor_sink")
        Pipeline.link(src, tr, sink)
        p.run(timeout=60)
        assert len(tr.losses) == 2


class TestResume:
    def test_resume_restores_params_opt_state_and_counter(self, tmp_path):
        """Two runs with resume=true continue training (momentum intact);
        loss after resume starts near where the first run ended."""
        ckpt = tmp_path / "resume.msgpack"
        rng = np.random.default_rng(0)
        true_w = rng.normal(size=(8, 4)).astype(np.float32)

        def run(n):
            data = []
            for _ in range(n):
                x = rng.normal(size=(4, 8)).astype(np.float32)
                data.append((x, np.argmax(x @ true_w, -1).astype(np.int32)))
            p = Pipeline()
            src = p.add_new("appsrc", caps=caps_of("8:4,4", "float32,int32"),
                            data=data)
            tr = p.add_new("tensor_trainer", model=linear_bundle(),
                           learning_rate=0.05, optimizer="sgd",
                           checkpoint_path=str(ckpt), resume=True)
            sink = p.add_new("tensor_sink")
            Pipeline.link(src, tr, sink)
            p.run(timeout=120)
            return tr

        t1 = run(15)
        end_loss = float(np.mean(list(t1.losses)[-5:]))
        t2 = run(15)
        assert t2._n == 30  # frame counter resumed
        start_loss = float(np.mean(list(t2.losses)[:5]))
        # resumed run starts from the trained state, not from scratch
        first_run_start = float(np.mean(list(t1.losses)[:5]))
        assert start_loss < first_run_start
        assert start_loss < end_loss * 3 + 0.5

    def test_plain_checkpoint_stays_servable(self, tmp_path):
        """resume=false (default) keeps the params-only format that
        custom=\"arch=...\" deployment consumes."""
        ckpt = tmp_path / "plain.msgpack"
        rng = np.random.default_rng(1)
        data = [(rng.normal(size=(2, 8)).astype(np.float32),
                 np.zeros(2, np.int32)) for _ in range(3)]
        p = Pipeline()
        src = p.add_new("appsrc", caps=caps_of("8:2,2", "float32,int32"),
                        data=data)
        tr = p.add_new("tensor_trainer", model=linear_bundle(),
                       checkpoint_path=str(ckpt))
        sink = p.add_new("tensor_sink")
        Pipeline.link(src, tr, sink)
        p.run(timeout=60)
        from nnstreamer_tpu.utils import checkpoints
        import jax

        w = checkpoints.load_variables(
            str(ckpt), jax.numpy.zeros((8, 4)))
        assert np.asarray(w).shape == (8, 4)

    def test_resume_cycle_with_orbax_dir(self, tmp_path):
        """save->load->save with an orbax directory checkpoint (no
        .msgpack suffix) must overwrite cleanly across runs."""
        ckpt = tmp_path / "orbax_ckpt"
        rng = np.random.default_rng(2)

        def run():
            data = [(rng.normal(size=(2, 8)).astype(np.float32),
                     np.zeros(2, np.int32)) for _ in range(3)]
            p = Pipeline()
            src = p.add_new("appsrc", caps=caps_of("8:2,2", "float32,int32"),
                            data=data)
            tr = p.add_new("tensor_trainer", model=linear_bundle(),
                           checkpoint_path=str(ckpt), resume=True)
            sink = p.add_new("tensor_sink")
            Pipeline.link(src, tr, sink)
            p.run(timeout=120)
            return tr

        run()
        t2 = run()  # second EOS overwrites; second start resumed
        assert t2._n == 6

    def test_resume_against_params_only_file_clear_error(self, tmp_path):
        ckpt = tmp_path / "old.msgpack"
        from nnstreamer_tpu.utils import checkpoints
        import jax.numpy as jnp

        checkpoints.save_variables(str(ckpt), jnp.zeros((8, 4)))
        p = Pipeline()
        src = p.add_new("appsrc", caps=caps_of("8:2,2", "float32,int32"),
                        data=[(np.zeros((2, 8), np.float32),
                               np.zeros(2, np.int32))])
        tr = p.add_new("tensor_trainer", model=linear_bundle(),
                       checkpoint_path=str(ckpt), resume=True)
        sink = p.add_new("tensor_sink")
        Pipeline.link(src, tr, sink)
        from nnstreamer_tpu.graph.pipeline import PipelineError

        with pytest.raises((PipelineError, ValueError),
                           match="resume"):
            p.run(timeout=30)

    def test_mesh_resume_preserves_sharding(self, tmp_path):
        import jax

        ckpt = tmp_path / "mesh_resume.msgpack"
        rng = np.random.default_rng(3)

        def run():
            data = [(rng.normal(size=(4, 8)).astype(np.float32),
                     np.zeros(4, np.int32)) for _ in range(3)]
            p = Pipeline()
            src = p.add_new("appsrc", caps=caps_of("8:4,4", "float32,int32"),
                            data=data)
            tr = p.add_new("tensor_trainer", model=linear_bundle(),
                           optimizer="sgd", mesh="data:4,model:2",
                           checkpoint_path=str(ckpt), resume=True)
            sink = p.add_new("tensor_sink")
            Pipeline.link(src, tr, sink)
            p.run(timeout=120)
            return tr

        run()
        t2 = run()
        assert t2._n == 6
        # restored params keep their mesh placement (8 devices)
        leaf = jax.tree_util.tree_leaves(t2.params)[0]
        assert len(leaf.sharding.device_set) == 8
        # momentum state is device-resident too, not host numpy
        opt_leaves = [x for x in jax.tree_util.tree_leaves(t2._opt_state)
                      if hasattr(x, "sharding")]
        assert opt_leaves, "opt_state lost device placement on resume"
