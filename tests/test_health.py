"""obs.health + obs.events tests: the flight-recorder ring (bounds,
trace correlation, log bridge, crash hook), the component health model
and readiness semantics, each watchdog rule driven deterministically
via check_now(), the end-to-end stalled-element acceptance path, the
zero-overhead-while-disabled guarantee, and the NNS_TPU_DEBUG invalid-
level fallback."""

import json
import logging
import threading
import time
import urllib.request
from fractions import Fraction

import numpy as np
import pytest

from nnstreamer_tpu.core.types import Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.obs import events as obs_events
from nnstreamer_tpu.obs import health as obs_health
from nnstreamer_tpu.obs import metrics as obs_metrics
from nnstreamer_tpu.obs import tracing as obs_tracing
from nnstreamer_tpu.obs.events import EventRing
from nnstreamer_tpu.obs.health import HealthRegistry, Status


def _tensor_caps(dims: str, types: str, rate=Fraction(30, 1)) -> Caps:
    return Caps.tensors(TensorsConfig(
        TensorsInfo.from_strings(dims, types), rate))


_THRESHOLDS = ("stall_after_s", "queue_dwell_s", "reconnect_storm",
               "reconnect_window_s", "admission_deadline_s", "interval_s")


@pytest.fixture
def health():
    """Reset the process-global health registry around a test; stops
    any watchdog thread the test started and restores the thresholds
    (reset() keeps them — a leaked interval_s would starve the next
    test's watchdog)."""
    reg = obs_health.registry()
    was = reg.is_enabled
    saved = {k: getattr(reg, k) for k in _THRESHOLDS}
    reg.reset()
    yield obs_health
    reg.reset()
    for k, v in saved.items():
        setattr(reg, k, v)
    reg._enabled = was


@pytest.fixture
def events():
    """Reset the process-global event ring around a test; removes the
    log bridge + excepthook taps if the test installed them."""
    ring = obs_events.ring()
    was = ring.is_enabled
    ring.reset()
    yield obs_events
    obs_events.disable()
    ring.reset()
    ring._enabled = was


@pytest.fixture
def tracing_off_after():
    was = obs_tracing.enabled()
    store = obs_tracing.store() if hasattr(obs_tracing, "store") else None
    yield obs_tracing
    (obs_tracing.enable if was else obs_tracing.disable)()
    if store is not None:
        store.reset()


# --------------------------------------------------------------------------- #
# Event ring
# --------------------------------------------------------------------------- #

class TestEventRing:
    def test_disabled_records_nothing(self):
        r = EventRing(enabled=False)
        r.record("pipeline.state", "nope")
        assert len(r) == 0
        assert r.snapshot() == []

    def test_enabled_records_fields(self):
        r = EventRing(enabled=True)
        r.record("pipeline.state", "PLAYING", pipeline="p0")
        r.record("pipeline.error", "boom", severity="error")
        evs = r.snapshot()
        assert [e["seq"] for e in evs] == [0, 1]
        assert evs[0]["type"] == "pipeline.state"
        assert evs[0]["message"] == "PLAYING"
        assert evs[0]["severity"] == "info"
        assert evs[0]["attrs"] == {"pipeline": "p0"}
        assert evs[0]["trace_id"] is None
        assert evs[1]["severity"] == "error"
        assert evs[1]["ts"] == pytest.approx(time.time(), abs=30)

    def test_ring_is_bounded_and_counts_drops(self):
        r = EventRing(capacity=4, enabled=True)
        for i in range(7):
            r.record("pipeline.state", f"m{i}")
        assert len(r) == 4
        assert r.dropped == 3
        assert [e["message"] for e in r.snapshot()] == \
            ["m3", "m4", "m5", "m6"]
        assert [e["message"] for e in r.snapshot(limit=2)] == ["m5", "m6"]

    def test_trace_correlation(self, events, tracing_off_after):
        events.enable()
        obs_tracing.enable()
        with obs_tracing.start_span("pipeline.element") as span:
            events.record("pipeline.error", "inside a traced chain")
        ev = events.ring().snapshot()[-1]
        assert ev["trace_id"] == span.context.trace_id
        assert ev["span_id"] == span.context.span_id
        # explicit override beats the contextvar (watchdog verdicts)
        events.record("pipeline.stall", "verdict", trace_id="feedbeef")
        assert events.ring().snapshot()[-1]["trace_id"] == "feedbeef"

    def test_log_bridge(self, events):
        from nnstreamer_tpu.core.log import logger

        events.enable()
        logger("healthtest").warning("something smells")
        logger("healthtest").debug("too quiet to bridge")
        evs = [e for e in events.ring().snapshot()
               if e["type"] == "core.log"]
        assert len(evs) == 1
        assert evs[0]["severity"] == "warning"
        assert "something smells" in evs[0]["message"]
        events.disable()
        logger("healthtest").warning("after disable")
        assert all("after disable" not in e["message"]
                   for e in events.ring().snapshot())

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_pipeline_thread_crash_dumps_ring(self, events, capsys):
        events.enable()

        def die():
            raise RuntimeError("synthetic crash")

        t = threading.Thread(target=die, name="src:crash-test")
        t.start()
        t.join()
        evs = [e for e in events.ring().snapshot()
               if e["type"] == "pipeline.crash"]
        assert len(evs) == 1
        assert "RuntimeError" in evs[0]["message"]
        assert evs[0]["attrs"]["thread"] == "src:crash-test"
        assert "flight recorder" in capsys.readouterr().err

    @pytest.mark.filterwarnings(
        "ignore::pytest.PytestUnhandledThreadExceptionWarning")
    def test_non_pipeline_thread_crash_ignored(self, events):
        events.enable()

        def die():
            raise RuntimeError("not ours")

        t = threading.Thread(target=die, name="user-thread")
        t.start()
        t.join()
        assert not [e for e in events.ring().snapshot()
                    if e["type"] == "pipeline.crash"]

    def test_dump_jsonl(self, events, tmp_path):
        events.enable()
        events.record("pipeline.state", "PLAYING", pipeline="p0")
        path = tmp_path / "events.jsonl"
        events.dump_jsonl(str(path))
        lines = path.read_text().strip().splitlines()
        assert json.loads(lines[-1])["type"] == "pipeline.state"


# --------------------------------------------------------------------------- #
# NNS_TPU_DEBUG fallback (core/log.py)
# --------------------------------------------------------------------------- #

class TestLogLevelFallback:
    def _reconfigure(self, monkeypatch, spec):
        from nnstreamer_tpu.core import log as corelog

        monkeypatch.setenv("NNS_TPU_DEBUG", spec)
        monkeypatch.setattr(corelog, "_configured", False)
        return corelog

    def test_invalid_global_level_warns_and_falls_back(self, monkeypatch):
        root = logging.getLogger("nns_tpu")
        prev = root.level
        corelog = self._reconfigure(monkeypatch, "bogus")
        try:
            corelog.logger("filter")  # first import path: must not raise
            assert root.level == logging.WARNING
        finally:
            root.setLevel(prev)

    def test_invalid_category_level_keeps_valid_ones(self, monkeypatch):
        root = logging.getLogger("nns_tpu")
        plog = logging.getLogger("nns_tpu.pipeline")
        flog = logging.getLogger("nns_tpu.filter")
        prev = root.level, plog.level, flog.level
        corelog = self._reconfigure(
            monkeypatch, "filter:bogus,pipeline:debug")
        try:
            corelog.logger("filter")
            assert flog.level == logging.NOTSET  # invalid part dropped
            assert plog.level == logging.DEBUG   # valid part applied
        finally:
            root.setLevel(prev[0])
            plog.setLevel(prev[1])
            flog.setLevel(prev[2])


# --------------------------------------------------------------------------- #
# Health model
# --------------------------------------------------------------------------- #

class TestHealthModel:
    def test_disabled_returns_shared_noop(self, health):
        reg = health.registry()
        reg._enabled = False
        c1 = health.component("a")
        c2 = health.component("b")
        assert c1 is c2 is obs_health.NOOP_COMPONENT
        c1.beat()
        c1.set_status(Status.FAILED, "ignored")
        c1.count("x")
        assert health.snapshot() == {"status": "ok", "ok": True,
                                     "components": []}
        assert health.readiness() == (True, {})

    def test_aggregate_is_worst_component(self, health):
        health.enable()
        health.component("a").set_status(Status.OK)
        health.component("b").set_status(Status.DEGRADED, "meh")
        reg = health.registry()
        assert reg.aggregate() is Status.DEGRADED
        snap = health.snapshot()
        assert snap["status"] == "degraded" and snap["ok"] is True
        health.component("c").set_status(Status.FAILED, "dead")
        snap = health.snapshot()
        assert snap["status"] == "failing" and snap["ok"] is False
        by_name = {c["name"]: c for c in snap["components"]}
        assert by_name["c"]["detail"] == "dead"

    def test_component_get_or_create_and_beat(self, health):
        health.enable()
        c = health.component("x", kind="element")
        assert health.component("x") is c
        assert c.last_beat_ns is None
        c.beat()
        assert c.last_beat_ns is not None
        snap = c.snapshot()
        assert snap["last_beat_age_s"] < 5.0

    def test_readiness_semantics(self, health):
        health.enable()
        ready, conds = health.readiness()
        assert ready is False and conds == {}  # nothing declared: not ready
        health.add_readiness("a", lambda: True)
        health.add_readiness("b", lambda: False)
        ready, conds = health.readiness()
        assert ready is False and conds == {"a": True, "b": False}
        health.add_readiness("b", lambda: True)
        ready, _ = health.readiness()
        assert ready is True
        # a condition returning None retires itself (weakref owner died)
        health.add_readiness("c", lambda: None)
        ready, conds = health.readiness()
        assert "c" not in conds and ready is True
        assert "c" not in health.registry()._conditions

    def test_probe_retires_component(self, health):
        health.enable(interval_s=60.0)
        health.component("gone", kind="element", probe=lambda: None)
        health.component("err", kind="element",
                         probe=lambda: (_ for _ in ()).throw(RuntimeError))
        health.check_now()
        names = [c["name"] for c in health.snapshot()["components"]]
        assert "gone" not in names  # None probe: retired
        assert "err" in names       # raising probe: kept, tick skipped

    def test_watchdog_thread_starts_lazily(self, health):
        health.enable(interval_s=60.0)
        assert "obs-health-watchdog" not in \
            [t.name for t in threading.enumerate()]
        health.component("first")
        assert "obs-health-watchdog" in \
            [t.name for t in threading.enumerate()]


# --------------------------------------------------------------------------- #
# Watchdog rules, driven deterministically via check_now()
# --------------------------------------------------------------------------- #

def _stall_events(events, etype):
    return [e for e in events.ring().snapshot() if e["type"] == etype]


class TestWatchdogRules:
    def test_element_stall_and_recovery(self, health, events):
        events.enable()
        health.enable(stall_after_s=0.05, interval_s=60.0)
        c = health.component(
            "element:p:sink0", kind="element",
            probe=lambda: {"running": True, "eos": False},
            attrs={"element": "sink0"})
        c.beat()
        c.last_trace_id = "cafe1234"
        time.sleep(0.1)
        health.check_now()
        assert c.status is Status.STALLED
        evs = _stall_events(events, "pipeline.stall")
        assert len(evs) == 1
        assert evs[0]["attrs"]["element"] == "sink0"
        assert evs[0]["attrs"]["stall_s"] > 0.05
        assert evs[0]["trace_id"] == "cafe1234"
        health.check_now()  # still stalled: verdict not re-recorded
        assert len(_stall_events(events, "pipeline.stall")) == 1
        c.beat()            # fresh beat: age back under the threshold
        health.check_now()
        assert c.status is Status.OK
        assert len(_stall_events(events, "pipeline.recover")) == 1

    def test_stopped_pipeline_is_not_stalled(self, health, events):
        events.enable()
        health.enable(stall_after_s=0.0, interval_s=60.0)
        c = health.component(
            "element:p:sink0", kind="element",
            probe=lambda: {"running": False, "eos": False})
        c.beat()
        time.sleep(0.01)
        health.check_now()
        assert c.status is Status.OK
        assert not _stall_events(events, "pipeline.stall")

    def test_queue_dwell_degrades(self, health, events):
        events.enable()
        health.enable(stall_after_s=1000.0, queue_dwell_s=0.0,
                      interval_s=60.0)
        state = {"depth": 4}
        c = health.component(
            "element:p:q0", kind="element",
            probe=lambda: {"running": True, "eos": False,
                           "depth": state["depth"], "bound": 4})
        c.beat()
        health.check_now()           # arms full_since
        time.sleep(0.01)
        health.check_now()           # dwell exceeded
        assert c.status is Status.DEGRADED
        evs = _stall_events(events, "pipeline.queue_full")
        assert len(evs) == 1 and evs[0]["attrs"]["depth"] == 4
        state["depth"] = 0
        health.check_now()
        assert c.status is Status.OK
        assert _stall_events(events, "pipeline.recover")

    def test_reconnect_storm_degrades(self, health, events):
        events.enable()
        health.enable(reconnect_storm=3, reconnect_window_s=0.0,
                      interval_s=60.0)
        c = health.component("query.client:qc0", kind="query")
        health.check_now()           # opens the counting window
        c.count("reconnect", 3)
        health.check_now()
        assert c.status is Status.DEGRADED
        evs = _stall_events(events, "query.reconnect_storm")
        assert len(evs) == 1 and evs[0]["attrs"]["reconnects"] == 3
        health.check_now()           # quiet window: recovery
        assert c.status is Status.OK
        assert _stall_events(events, "query.recover")

    def test_reconnect_storm_never_masks_failed(self, health, events):
        events.enable()
        health.enable(reconnect_storm=1, reconnect_window_s=0.0,
                      interval_s=60.0)
        c = health.component("query.client:qc0", kind="query")
        health.check_now()
        c.set_status(Status.FAILED, "connect failed")
        c.count("reconnect", 5)
        health.check_now()
        assert c.status is Status.FAILED  # the softer verdict lost
        assert _stall_events(events, "query.reconnect_storm")

    def test_admission_stall(self, health, events):
        events.enable()
        health.enable(admission_deadline_s=0.01, interval_s=60.0)
        state = {"wait": 5.0}
        c = health.component(
            "serving.engine:lm", kind="serving",
            probe=lambda: {"oldest_wait_s": state["wait"]},
            attrs={"engine": "lm"})
        health.check_now()
        assert c.status is Status.STALLED
        evs = _stall_events(events, "serving.admission_stall")
        assert len(evs) == 1 and evs[0]["attrs"]["engine"] == "lm"
        state["wait"] = 0.0
        health.check_now()
        assert c.status is Status.OK
        assert _stall_events(events, "serving.recover")


# --------------------------------------------------------------------------- #
# End-to-end: injected stall caught by the real watchdog thread
# --------------------------------------------------------------------------- #

class TestStallAcceptance:
    def test_stalled_element_reported_within_2x_threshold(
            self, health, events, tracing_off_after):
        """A sink that stops emitting buffers must show up STALLED —
        with the element name, stall age, and a correlated trace id —
        in /healthz + the event ring within 2x the watchdog threshold
        of the stall onset."""
        from nnstreamer_tpu.graph import Pipeline

        threshold = 0.4
        events.enable()
        obs_tracing.enable()
        health.enable(stall_after_s=threshold)
        release = threading.Event()
        sent = []

        def feed():
            if len(sent) < 2:
                sent.append(1)
                return np.zeros((8,), np.float32)
            release.wait(15)   # wedge: emitted 2 buffers, then nothing
            return None

        p = Pipeline()
        src = p.add_new("appsrc", caps=_tensor_caps("8", "float32"),
                        callback=feed)
        sink = p.add_new("tensor_sink")
        Pipeline.link(src, sink)
        p.start()
        try:
            # detection deadline: stall threshold + one watchdog tick,
            # capped at the acceptance bound of 2x the threshold after
            # the last buffer (plus the scheduling slack of this box)
            deadline = time.monotonic() + 2 * threshold + 1.0
            stall = None
            while time.monotonic() < deadline:
                evs = [e for e in events.ring().snapshot()
                       if e["type"] == "pipeline.stall"
                       and e["attrs"].get("element") == sink.name]
                if evs:
                    stall = evs[0]
                    break
                time.sleep(0.02)
            assert stall is not None, "watchdog never flagged the stall"
            assert stall["attrs"]["stall_s"] >= threshold
            assert stall["severity"] == "warning"
            # correlated with the trace that stopped moving
            assert stall["trace_id"] is not None
            snap = health.snapshot()
            assert snap["status"] == "stalled" and snap["ok"] is False
            stalled = [c for c in snap["components"]
                       if c["status"] == "stalled"]
            assert any(c["name"].endswith(sink.name) for c in stalled)
        finally:
            release.set()
            p.stop()

    def test_zero_overhead_when_disabled(self, health, events):
        """The structural guarantee: with health (and metrics/tracing)
        off, no watchdog thread exists, nothing registers, and element
        chains stay the plain class methods."""
        from nnstreamer_tpu.graph import Pipeline

        health.registry()._enabled = False
        was_m = obs_metrics.enabled()
        was_t = obs_tracing.enabled()
        obs_metrics.disable()
        obs_tracing.disable()
        try:
            p = Pipeline()
            src = p.add_new("videotestsrc", width=8, height=8,
                            num_buffers=2)
            conv = p.add_new("tensor_converter")
            sink = p.add_new("tensor_sink")
            Pipeline.link(src, conv, sink)
            p.run(timeout=30)
            assert "_chain_entry" not in conv.__dict__
            assert "_obs_registries" not in conv.__dict__
            assert "obs-health-watchdog" not in \
                [t.name for t in threading.enumerate()]
            assert health.snapshot()["components"] == []
        finally:
            (obs_metrics.enable if was_m else obs_metrics.disable)()
            (obs_tracing.enable if was_t else obs_tracing.disable)()

    def test_debug_events_endpoint(self, health, events):
        from nnstreamer_tpu.obs.exporter import start_exporter
        from nnstreamer_tpu.obs.metrics import MetricsRegistry

        events.enable()
        events.record("pipeline.state", "PLAYING", pipeline="p0")
        events.record("pipeline.error", "boom", severity="error")
        was_m = obs_metrics.enabled()
        try:
            with start_exporter(port=0, registry=MetricsRegistry()) as exp:
                body = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{exp.port}/debug/events",
                    timeout=5).read().decode())
                assert body["events_enabled"] is True
                types = [e["type"] for e in body["events"]]
                assert "pipeline.state" in types
                assert "pipeline.error" in types
                body = json.loads(urllib.request.urlopen(
                    f"http://127.0.0.1:{exp.port}/debug/events?n=1",
                    timeout=5).read().decode())
                assert len(body["events"]) == 1
                assert body["events"][0]["type"] == "pipeline.error"
        finally:
            (obs_metrics.enable if was_m else obs_metrics.disable)()
