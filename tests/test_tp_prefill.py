"""Tensor-parallel prompt prefill (parallel/tp_prefill.py).

The TP prefill must hand `make_tp_generate` exactly what a
single-device prefill + head-major reshard would have: same greedy
continuations (float psum tolerance on logits), same cache layout, and
— for w8a8 trees — bit-exact caches (the global-grid int32 scheme).
`true_len` column masking must match `lm_prefill_masked`.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from nnstreamer_tpu.models import causal_lm
from nnstreamer_tpu.parallel.tp_decode import (
    make_tp_generate, tp_shard_cache, tp_shard_params)
from nnstreamer_tpu.parallel.tp_prefill import make_tp_prefill

V, D, H, L, MAXLEN = 71, 64, 8, 2, 64


@pytest.fixture(scope="module")
def params():
    return causal_lm.init_causal_lm(
        jax.random.PRNGKey(21), V, D, H, L, MAXLEN)


@pytest.fixture(scope="module")
def mesh():
    if len(jax.devices()) < 4:
        pytest.skip("needs virtual multi-device CPU")
    return Mesh(np.array(jax.devices()[:4]), ("model",))


def _single_generate(params, prompt, n_steps):
    logits, kc, vc, pos = causal_lm.lm_prefill(
        params, jnp.asarray(prompt), H, MAXLEN)
    first = jnp.argmax(logits, -1)[:, None].astype(jnp.int32)
    toks, tok = [], first
    for _ in range(n_steps):
        lg, kc, vc, pos = causal_lm.lm_decode_step(
            params, tok, kc, vc, pos, H)
        tok = jnp.argmax(lg, -1)[:, None].astype(jnp.int32)
        toks.append(np.asarray(tok[:, 0]))
    return np.asarray(first[:, 0]), np.stack(toks, 1)


def test_tp_prefill_logits_and_continuation(params, mesh):
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, V, (2, 9)).astype(np.int32)
    sfirst, want = _single_generate(params, prompt, 10)

    tp = tp_shard_params(params, H, mesh)
    prefill = make_tp_prefill(H, MAXLEN, mesh)
    logits, kc_tp, vc_tp, pos = prefill(tp, prompt)

    ref_logits, _, _, ref_pos = causal_lm.lm_prefill(
        params, jnp.asarray(prompt), H, MAXLEN)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=2e-4, atol=2e-5)
    assert int(np.asarray(pos)[0]) == int(np.asarray(ref_pos)[0])

    first = jnp.argmax(logits, -1)[:, None].astype(jnp.int32)
    np.testing.assert_array_equal(np.asarray(first[:, 0]), sfirst)
    gen = make_tp_generate(H, MAXLEN, mesh)
    got = np.asarray(gen(tp, first, kc_tp, vc_tp, pos, 10))
    np.testing.assert_array_equal(got, want)


def test_tp_prefill_cache_matches_resharded_single_device(params, mesh):
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, V, (1, 11)).astype(np.int32)
    _, kc, vc, _ = causal_lm.lm_prefill(
        params, jnp.asarray(prompt), H, MAXLEN)
    kc_ref, vc_ref = tp_shard_cache(kc, vc, L, 1, H, mesh)

    tp = tp_shard_params(params, H, mesh)
    _, kc_tp, vc_tp, _ = make_tp_prefill(H, MAXLEN, mesh)(tp, prompt)
    np.testing.assert_allclose(np.asarray(kc_tp), np.asarray(kc_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(vc_tp), np.asarray(vc_ref),
                               rtol=1e-5, atol=1e-6)


def test_tp_prefill_true_len_matches_masked(params, mesh):
    """A right-padded bucket prompt through the TP prefill equals
    lm_prefill_masked: same logits row, same pos, and the continuation
    from the garbage-padded cache stays exact (the overwrite-before-
    visible contract)."""
    rng = np.random.default_rng(3)
    tl = 6
    padded = np.zeros((1, 16), np.int32)
    padded[0, :tl] = rng.integers(0, V, tl)

    ref_logits, _, _, ref_pos = causal_lm.lm_prefill_masked(
        params, jnp.asarray(padded), jnp.int32(tl), H, MAXLEN)
    tp = tp_shard_params(params, H, mesh)
    logits, kc_tp, vc_tp, pos = make_tp_prefill(H, MAXLEN, mesh)(
        tp, padded, true_len=tl)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(ref_logits),
                               rtol=2e-4, atol=2e-5)
    assert int(np.asarray(pos)[0]) == tl == int(np.asarray(ref_pos)[0])


def test_tp_prefill_w8a8_bit_exact_cache_and_tokens(params, mesh):
    """Quantized TP prefill: int8 QKV codes are the single-device codes
    (column grids preserved), so the emitted cache is BIT-exact vs
    resharding a single-device quantized prefill, and the greedy
    continuation matches token-for-token."""
    qp = causal_lm.quantize_lm_params(params)
    rng = np.random.default_rng(4)
    prompt = rng.integers(0, V, (2, 8)).astype(np.int32)
    sfirst, want = _single_generate(qp, prompt, 9)

    _, kc, vc, _ = causal_lm.lm_prefill(qp, jnp.asarray(prompt), H, MAXLEN)
    kc_ref, vc_ref = tp_shard_cache(kc, vc, L, 2, H, mesh)

    tq = tp_shard_params(qp, H, mesh)
    prefill = make_tp_prefill(H, MAXLEN, mesh)
    logits, kc_tp, vc_tp, pos = prefill(tq, prompt)
    np.testing.assert_array_equal(np.asarray(kc_tp), np.asarray(kc_ref))
    np.testing.assert_array_equal(np.asarray(vc_tp), np.asarray(vc_ref))

    first = jnp.argmax(logits, -1)[:, None].astype(jnp.int32)
    np.testing.assert_array_equal(np.asarray(first[:, 0]), sfirst)
    got = np.asarray(make_tp_generate(H, MAXLEN, mesh)(
        tq, first, kc_tp, vc_tp, pos, 9))
    np.testing.assert_array_equal(got, want)


def test_tp_prefill_rejects_oversized_prompt(params, mesh):
    tp = tp_shard_params(params, H, mesh)
    prompt = np.zeros((1, MAXLEN + 1), np.int32)
    with pytest.raises(ValueError, match="exceeds"):
        make_tp_prefill(H, MAXLEN, mesh)(tp, prompt)
