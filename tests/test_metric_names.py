"""Tier-1 wiring for scripts/check_metric_names.py: every registered
metric name must follow nnstpu_<layer>_<name>_<unit>, every literal
span name lowercase <layer>.<operation>, and every flight-recorder
event type lowercase <layer>.<event>."""

import subprocess
import sys
from pathlib import Path

import pytest

pytestmark = pytest.mark.lint

REPO_ROOT = Path(__file__).resolve().parents[1]
SCRIPT = REPO_ROOT / "scripts" / "check_metric_names.py"


def test_lint_passes_on_tree():
    proc = subprocess.run(
        [sys.executable, str(SCRIPT)], capture_output=True, text=True,
        cwd=REPO_ROOT, timeout=60)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "metric names OK" in proc.stdout
    assert "labels OK" in proc.stdout
    assert "span names OK" in proc.stdout
    assert "event names OK" in proc.stdout


def test_lint_catches_violations(tmp_path):
    """The checker actually rejects off-convention names (guards against
    a regex rot that silently passes everything)."""
    sys.path.insert(0, str(REPO_ROOT / "scripts"))
    try:
        import check_metric_names as lint
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad.py"
    bad.write_text(
        'reg.counter("nnstpu_pipeline_stuff_seconds", "h")\n'   # counter unit
        'reg.gauge("nnstpu_webui_queue_depth", "h")\n'          # bad layer
        'reg.histogram("freeform_name", "h")\n')                # no convention
    problems = lint.check(tmp_path)
    assert len(problems) == 3
    assert any("not in ('total',)" in p for p in problems)
    assert any("layer 'webui'" in p for p in problems)

    empty = tmp_path / "none"
    empty.mkdir()
    assert any("no metric registrations" in p for p in lint.check(empty))


def test_lint_catches_label_violations(tmp_path):
    """Label-name lint: illegal identifiers, reserved fleet/encoder
    names, and the >8-key cardinality guard."""
    sys.path.insert(0, str(REPO_ROOT / "scripts"))
    try:
        import check_metric_names as lint
    finally:
        sys.path.pop(0)
    many = ", ".join(f'"k{i}"' for i in range(9))
    bad = tmp_path / "bad_labels.py"
    bad.write_text(
        'reg.counter("nnstpu_query_a_total", "h", ("element",))\n'  # fine
        'reg.counter("nnstpu_query_b_total", "h", ("Element",))\n'  # case
        'reg.counter("nnstpu_query_c_total", "h", ("instance",))\n' # reserved
        'reg.histogram("nnstpu_query_d_seconds", "h", ("le",))\n'   # reserved
        'reg.gauge("nnstpu_query_e_depth", "h",\n'
        f'          labelnames=[{many}])\n')                         # >8 keys
    problems = lint.check_labels(tmp_path)
    assert len(problems) == 4, problems
    assert any("'Element'" in p for p in problems)
    assert any("'instance'" in p and "reserved" in p for p in problems)
    assert any("'le'" in p and "reserved" in p for p in problems)
    assert any("cardinality guard" in p for p in problems)
    # the real tree's label schemas must stay clean
    assert lint.check_labels() == []


def test_fleet_event_layer_allowed(tmp_path):
    sys.path.insert(0, str(REPO_ROOT / "scripts"))
    try:
        import check_metric_names as lint
    finally:
        sys.path.pop(0)
    ok = tmp_path / "fleet_events.py"
    ok.write_text('_events.record("fleet.push", "m")\n'
                  '_events.record("fleet.expire", "m")\n'
                  '_events.record("fleet.merge_conflict", "m")\n')
    assert lint.check_events(tmp_path) == []


def test_lint_catches_span_violations(tmp_path):
    sys.path.insert(0, str(REPO_ROOT / "scripts"))
    try:
        import check_metric_names as lint
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad_spans.py"
    bad.write_text(
        'store.start_span("serving.prefill")\n'       # fine
        'store.start_span("webui.render")\n'          # bad layer
        'store.start_span("PipelineElement")\n'       # not dotted
        'store.start_span("query.Recv")\n')           # uppercase op
    problems = lint.check_spans(tmp_path)
    assert len(problems) == 3
    assert any("layer 'webui'" in p for p in problems)
    assert any("'PipelineElement'" in p for p in problems)
    # the real tree must contain literal span call sites — a regex that
    # stops matching the tracing API shows up as this problem
    assert lint.check_spans() == []


def test_lint_catches_event_violations(tmp_path):
    sys.path.insert(0, str(REPO_ROOT / "scripts"))
    try:
        import check_metric_names as lint
    finally:
        sys.path.pop(0)
    bad = tmp_path / "bad_events.py"
    bad.write_text(
        '_events.record("pipeline.stall", "m")\n'     # fine
        'record("query.reconnect_storm", "m")\n'      # fine (bare call)
        '_events.record("webui.boom", "m")\n'         # bad layer
        'events.record("NotDotted", "m")\n'           # not dotted
        'self.stats.record(t0)\n')                    # not an event call
    problems = lint.check_events(tmp_path)
    assert len(problems) == 2
    assert any("layer 'webui'" in p for p in problems)
    assert any("'NotDotted'" in p for p in problems)
    # the real tree must contain literal event call sites — a regex
    # that stops matching the events API shows up as this problem
    assert lint.check_events() == []
