"""Buffer, meta header, typed data, registry, config tests."""

import numpy as np
import pytest

from nnstreamer_tpu.core import (
    Buffer,
    META_SIZE,
    SubpluginType,
    TensorDType,
    TensorFormat,
    TensorInfo,
    TensorMemory,
    TensorMetaInfo,
    get_all_subplugins,
    get_subplugin,
    register_subplugin,
    unregister_subplugin,
    unwrap_flex,
    wrap_flex,
)
from nnstreamer_tpu.core import data as tdata
from nnstreamer_tpu.core.config import reset_config
from nnstreamer_tpu.core.hw import AcceleratorSpec


class TestTensorMemory:
    def test_host_roundtrip(self):
        a = np.arange(12, dtype=np.float32).reshape(3, 4)
        m = TensorMemory(a)
        assert m.info.shape == (3, 4)
        assert m.info.dims == (4, 3)
        np.testing.assert_array_equal(m.host(), a)

    def test_device_lazy(self):
        import jax

        m = TensorMemory(np.ones((2, 2), np.float32))
        assert not m.is_device
        d = m.device()
        assert isinstance(d, jax.Array)
        np.testing.assert_array_equal(np.asarray(d), m.host())

    def test_from_device(self):
        import jax.numpy as jnp

        m = TensorMemory(jnp.zeros((5,), jnp.int32))
        assert m.is_device
        assert m.host().shape == (5,)

    def test_bytes_roundtrip(self):
        a = np.arange(6, dtype=np.uint16).reshape(2, 3)
        m = TensorMemory(a)
        m2 = TensorMemory.from_bytes(m.tobytes(), m.info)
        np.testing.assert_array_equal(m2.host(), a)


class TestBuffer:
    def test_of(self):
        b = Buffer.of(np.zeros((2, 2)), np.ones(3), pts=1000)
        assert b.num_tensors == 2
        assert b.pts == 1000

    def test_with_memories_keeps_timestamps(self):
        b = Buffer.of(np.zeros(4), pts=5, duration=7, offset=2)
        b2 = b.with_memories([TensorMemory(np.ones(2))])
        assert (b2.pts, b2.duration, b2.offset) == (5, 7, 2)
        assert b2.num_tensors == 1


class TestMeta:
    def test_pack_parse(self):
        info = TensorInfo.from_strings("3:224:224", "uint8")
        meta = TensorMetaInfo(info, TensorFormat.FLEXIBLE, "video/x-raw")
        raw = meta.pack()
        assert len(raw) == META_SIZE
        meta2 = TensorMetaInfo.parse(raw)
        assert meta2.info.dims == info.dims
        assert meta2.info.dtype is TensorDType.UINT8
        assert meta2.format is TensorFormat.FLEXIBLE
        assert meta2.media_type == "video/x-raw"

    def test_wrap_unwrap(self):
        info = TensorInfo.from_strings("4", "float32")
        payload = np.arange(4, dtype=np.float32).tobytes()
        blob = wrap_flex(payload, info)
        meta, out = unwrap_flex(blob)
        assert out == payload
        assert meta.info.is_compatible(info)

    def test_truncated(self):
        with pytest.raises(ValueError):
            TensorMetaInfo.parse(b"\x00" * 10)

    def test_bad_magic(self):
        with pytest.raises(ValueError):
            TensorMetaInfo.parse(b"\xff" * META_SIZE)


class TestTypedData:
    def test_typecast_saturation_semantics(self):
        # C-style modular wrap for ints (reference gst_tensor_data_typecast)
        assert tdata.typecast_value(300, TensorDType.UINT8) == 300 % 256

    def test_typecast_float_to_int(self):
        assert tdata.typecast_value(3.9, TensorDType.INT32) == 3

    def test_average_std(self):
        a = np.array([1, 2, 3, 4], np.float32)
        assert tdata.tensor_average(a) == 2.5
        assert tdata.tensor_std(a) == pytest.approx(np.std(a))

    def test_per_channel(self):
        a = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        avg = tdata.per_channel_average(a, channel_axis=-1)
        assert avg.shape == (4,)
        np.testing.assert_allclose(avg, a.reshape(-1, 4).mean(axis=0))


class TestRegistry:
    def test_register_lookup(self):
        assert register_subplugin(SubpluginType.DECODER, "TeStDec", object())
        assert get_subplugin(SubpluginType.DECODER, "testdec") is not None
        assert "testdec" in get_all_subplugins(SubpluginType.DECODER)
        assert unregister_subplugin(SubpluginType.DECODER, "testdec")

    def test_duplicate_fails(self):
        register_subplugin(SubpluginType.DECODER, "dup", 1)
        try:
            assert not register_subplugin(SubpluginType.DECODER, "dup", 2)
            assert register_subplugin(SubpluginType.DECODER, "dup", 2, replace=True)
        finally:
            unregister_subplugin(SubpluginType.DECODER, "dup")

    def test_miss(self):
        assert get_subplugin(SubpluginType.CONVERTER, "nope-nothing") is None


class TestConfig:
    def test_ini_and_env(self, tmp_path, monkeypatch):
        ini = tmp_path / "t.ini"
        ini.write_text(
            "[common]\nenable_envvar=true\n"
            "[filter]\nframework_priority_tflite=xla-tpu,python3\n"
            "[xla-tpu]\nprecision=bf16\n")
        cfg = reset_config(str(ini))
        assert cfg.framework_priority(".tflite") == ["xla-tpu", "python3"]
        assert cfg.framework_priority("py") == ["python3"]  # default table
        assert cfg.get_custom_value("xla-tpu", "precision") == "bf16"
        monkeypatch.setenv("NNS_TPU_XLA_TPU_PRECISION", "f32")
        assert cfg.get_custom_value("xla-tpu", "precision") == "f32"
        reset_config()


class TestAccelerator:
    def test_parse(self):
        s = AcceleratorSpec.parse("true:tpu,cpu")
        assert s.enabled and s.preference == ("tpu", "cpu")
        assert not AcceleratorSpec.parse("false").enabled
        assert AcceleratorSpec.parse(None).enabled
