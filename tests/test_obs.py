"""obs subsystem unit tests: registry semantics (labels, concurrency,
histogram buckets), Prometheus text golden, the disabled no-op fast
path, and the HTTP exporter."""

import json
import threading
import urllib.request

import pytest

from nnstreamer_tpu.obs import metrics as obs_metrics
from nnstreamer_tpu.obs.exporter import MetricsExporter, start_exporter
from nnstreamer_tpu.obs.metrics import MetricsRegistry


class TestRegistry:
    def test_counter_basics(self):
        reg = MetricsRegistry()
        c = reg.counter("nnstpu_query_messages_total", "m",
                        ("direction", "cmd"))
        c.labels("sent", "DATA").inc()
        c.labels("sent", "DATA").inc(2)
        c.labels("recv", "RESULT").inc()
        assert c.labels("sent", "DATA").value == 3
        assert c.labels("recv", "RESULT").value == 1
        with pytest.raises(ValueError, match="only go up"):
            c.labels("sent", "DATA").inc(-1)

    def test_labels_by_name_and_arity(self):
        reg = MetricsRegistry()
        c = reg.counter("nnstpu_query_messages_total", "m",
                        ("direction", "cmd"))
        assert c.labels(direction="sent", cmd="DATA") is \
            c.labels("sent", "DATA")
        with pytest.raises(ValueError, match="expected labels"):
            c.labels("sent")

    def test_reregistration_idempotent_and_conflicts(self):
        reg = MetricsRegistry()
        a = reg.counter("nnstpu_query_messages_total", "m", ("cmd",))
        b = reg.counter("nnstpu_query_messages_total", "m", ("cmd",))
        assert a is b
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("nnstpu_query_messages_total", "m", ("cmd",))
        with pytest.raises(ValueError, match="already registered"):
            reg.counter("nnstpu_query_messages_total", "m", ("other",))

    def test_gauge_set_inc_and_callback(self):
        reg = MetricsRegistry()
        g = reg.gauge("nnstpu_pipeline_queue_depth", "d", ("element",))
        g.labels("q0").set(5)
        g.labels("q0").dec(2)
        assert g.labels("q0").value == 3
        state = {"depth": 7}
        g.labels("q1").set_function(lambda: state["depth"])
        assert g.labels("q1").value == 7
        state["depth"] = 9
        assert g.labels("q1").value == 9

    def test_histogram_buckets_sum_count_max(self):
        reg = MetricsRegistry()
        h = reg.histogram("nnstpu_serving_ttft_seconds", "t",
                          buckets=(0.1, 1.0, 5.0))
        for v in (0.05, 0.5, 3.0, 10.0, 1.0):  # 1.0 lands IN le="1"
            h.observe(v)
        child = h.labels()
        assert child.count == 5
        assert child.max == 10.0
        assert abs(child.sum - 14.55) < 1e-9
        snap = reg.snapshot()["nnstpu_serving_ttft_seconds"]["series"][0]
        assert snap["buckets"] == {0.1: 1, 1.0: 3, 5.0: 4}
        assert snap["count"] == 5

    def test_default_buckets_log_spaced(self):
        b = obs_metrics.DEFAULT_LATENCY_BUCKETS
        assert b == tuple(sorted(b))
        assert b[0] == 1e-5 and b[-1] == 50.0
        ratios = [b[i + 1] / b[i] for i in range(len(b) - 1)]
        assert max(ratios) <= 4.0  # no decade-sized holes

    def test_disabled_registry_noop_then_enable(self):
        reg = MetricsRegistry(enabled=False)
        c = reg.counter("nnstpu_query_messages_total", "m")
        h = reg.histogram("nnstpu_serving_ttft_seconds", "t")
        c.inc()
        h.observe(1.0)
        assert c.labels().value == 0
        assert h.labels().count == 0
        reg.enable()
        c.inc()
        assert c.labels().value == 1

    def test_concurrent_increments_exact(self):
        reg = MetricsRegistry()
        c = reg.counter("nnstpu_query_messages_total", "m", ("cmd",))
        h = reg.histogram("nnstpu_serving_ttft_seconds", "t",
                          buckets=(1.0,))
        n, per = 8, 2000

        def worker():
            for _ in range(per):
                c.labels("DATA").inc()
                h.observe(0.5)

        threads = [threading.Thread(target=worker) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.labels("DATA").value == n * per
        assert h.labels().count == n * per
        assert h.labels()._bucket_counts[0] == n * per


class TestExposition:
    def test_prometheus_text_golden(self):
        reg = MetricsRegistry()
        c = reg.counter("nnstpu_query_messages_total", "Messages",
                        ("direction", "cmd"))
        c.labels("sent", "DATA").inc(3)
        g = reg.gauge("nnstpu_pipeline_queue_depth", "Depth", ("element",))
        g.labels("q0").set(2)
        h = reg.histogram("nnstpu_serving_ttft_seconds", "TTFT",
                          buckets=(0.1, 1.0, 5.0))
        h.observe(0.05)
        h.observe(3.0)
        expected = """\
# HELP nnstpu_pipeline_queue_depth Depth
# TYPE nnstpu_pipeline_queue_depth gauge
nnstpu_pipeline_queue_depth{element="q0"} 2
# HELP nnstpu_query_messages_total Messages
# TYPE nnstpu_query_messages_total counter
nnstpu_query_messages_total{direction="sent",cmd="DATA"} 3
# HELP nnstpu_serving_ttft_seconds TTFT
# TYPE nnstpu_serving_ttft_seconds histogram
nnstpu_serving_ttft_seconds_bucket{le="0.1"} 1
nnstpu_serving_ttft_seconds_bucket{le="1"} 1
nnstpu_serving_ttft_seconds_bucket{le="5"} 2
nnstpu_serving_ttft_seconds_bucket{le="+Inf"} 2
nnstpu_serving_ttft_seconds_sum 3.05
nnstpu_serving_ttft_seconds_count 2
"""
        assert reg.exposition() == expected

    def test_label_escaping(self):
        reg = MetricsRegistry()
        c = reg.counter("nnstpu_query_messages_total", "m", ("cmd",))
        c.labels('we"ird\\x\n').inc()
        text = reg.exposition()
        assert 'cmd="we\\"ird\\\\x\\n"' in text

    def test_empty_registry_empty_exposition(self):
        assert MetricsRegistry().exposition() == ""


@pytest.fixture
def global_metrics():
    """Save/restore the process-global enabled flag around a test."""
    was = obs_metrics.enabled()
    yield obs_metrics.registry()
    (obs_metrics.enable if was else obs_metrics.disable)()


@pytest.fixture
def global_health():
    """Reset the process-global health registry around a test (and stop
    any watchdog the test started)."""
    from nnstreamer_tpu.obs import health as obs_health

    reg = obs_health.registry()
    was = reg.is_enabled
    reg.reset()
    yield obs_health
    reg.reset()
    reg._enabled = was


def _tiny_pipeline():
    from nnstreamer_tpu.graph import Pipeline

    p = Pipeline()
    src = p.add_new("videotestsrc", width=8, height=8, num_buffers=2)
    conv = p.add_new("tensor_converter")
    sink = p.add_new("tensor_sink")
    Pipeline.link(src, conv, sink)
    return p, conv


class TestNoopFastPath:
    def test_disabled_leaves_chain_entry_untouched(self, global_metrics):
        obs_metrics.disable()
        p, conv = _tiny_pipeline()
        p.run(timeout=30)
        # the structural fast path: no wrapper was installed at all —
        # _chain_entry resolves to the plain class method, zero overhead
        assert "_chain_entry" not in conv.__dict__
        assert "_obs_registries" not in conv.__dict__

    def test_enabled_wraps_and_records(self, global_metrics):
        obs_metrics.enable()
        p, conv = _tiny_pipeline()
        p.run(timeout=30)
        assert "_chain_entry" in conv.__dict__
        snap = obs_metrics.registry().snapshot()
        series = snap["nnstpu_pipeline_buffers_total"]["series"]
        by_el = {s["labels"]["element"]: s["value"] for s in series}
        assert by_el[conv.name] >= 2

    def test_restart_does_not_double_wrap(self, global_metrics):
        obs_metrics.enable()
        p, conv = _tiny_pipeline()
        p.run(timeout=30)
        wrapped = conv.__dict__["_chain_entry"]
        p.run(timeout=30)
        assert conv.__dict__["_chain_entry"] is wrapped


class TestExporter:
    def test_scrape_and_healthz(self, global_metrics):
        reg = MetricsRegistry()
        reg.counter("nnstpu_query_messages_total", "m", ("cmd",)) \
            .labels("DATA").inc(4)
        with start_exporter(port=0, registry=reg) as exp:
            text = urllib.request.urlopen(exp.url, timeout=5) \
                .read().decode()
            assert 'nnstpu_query_messages_total{cmd="DATA"} 4' in text
            health = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{exp.port}/healthz", timeout=5)
                .read().decode())
            assert health["status"] == "ok"
            assert health["families"] == 1
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{exp.port}/nope", timeout=5)

    def test_healthz_failing_component(self, global_metrics,
                                       global_health):
        """A FAILED component flips /healthz to 503 with status
        "failing" and names the component in the body."""
        obs_health = global_health
        obs_health.enable()
        c = obs_health.component("test:unit")
        c.set_status(obs_health.Status.FAILED, "boom")
        with start_exporter(port=0, registry=MetricsRegistry()) as exp:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{exp.port}/healthz", timeout=5)
            assert ei.value.code == 503
            body = json.loads(ei.value.read().decode())
            assert body["status"] == "failing"
            by_name = {comp["name"]: comp for comp in body["components"]}
            assert by_name["test:unit"]["status"] == "failing"
            assert by_name["test:unit"]["detail"] == "boom"

    def test_readyz_transitions(self, global_metrics, global_health):
        """/readyz: enabled health with zero conditions is NOT ready;
        a started pipeline registers its PLAYING condition and flips it
        ready; stopping flips it back."""
        obs_health = global_health
        obs_health.enable()
        with start_exporter(port=0) as exp:
            url = f"http://127.0.0.1:{exp.port}/readyz"
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url, timeout=5)
            assert ei.value.code == 503
            assert json.loads(ei.value.read().decode())["ready"] is False
            p, _conv = _tiny_pipeline()
            p.start()
            try:
                body = json.loads(
                    urllib.request.urlopen(url, timeout=5).read().decode())
                assert body["ready"] is True
                assert body["conditions"][f"pipeline:{p.name}"] is True
            finally:
                p.stop()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(url, timeout=5)
            assert ei.value.code == 503
            body = json.loads(ei.value.read().decode())
            assert body["conditions"][f"pipeline:{p.name}"] is False

    def test_404_hint_lists_routes(self, global_metrics):
        with start_exporter(port=0, registry=MetricsRegistry()) as exp:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{exp.port}/nope", timeout=5)
            assert ei.value.code == 404
            hint = ei.value.read().decode()
            # derived from the dispatch table — every route shows up
            for route in ("/metrics", "/healthz", "/readyz",
                          "/debug/events", "/debug/traces"):
                assert route in hint

    def test_start_exporter_enables_collection(self, global_metrics):
        obs_metrics.disable()
        exp = start_exporter(port=0)
        try:
            assert obs_metrics.enabled()
        finally:
            exp.close()

    def test_close_joins_thread_and_releases_port(self, global_metrics):
        """Satellite: close() must join the serving thread and free the
        socket promptly — a rebind of the same port right after close()
        is the observable contract."""
        exp = start_exporter(port=0, registry=MetricsRegistry())
        port = exp.port
        exp.close()
        assert not exp._thread.is_alive()
        exp2 = MetricsExporter(port=port, registry=MetricsRegistry())
        try:
            assert exp2.port == port
        finally:
            exp2.close()

    def test_close_is_idempotent(self, global_metrics):
        exp = start_exporter(port=0, registry=MetricsRegistry())
        exp.close()
        exp.close()  # second close must be a no-op, not an EBADF

    def test_bind_conflict_names_port_and_flag(self, global_metrics):
        """Satellite: EADDRINUSE surfaces as a clear error naming the
        port and the --metrics-port flag, not a raw OSError."""
        with start_exporter(port=0, registry=MetricsRegistry()) as exp:
            with pytest.raises(RuntimeError, match="--metrics-port") as ei:
                MetricsExporter(port=exp.port, registry=MetricsRegistry())
            assert str(exp.port) in str(ei.value)

    def test_help_text_escaping(self):
        """Satellite: backslashes and newlines in help text must be
        escaped on the HELP line (quotes are legal there)."""
        reg = MetricsRegistry()
        reg.counter("nnstpu_query_messages_total",
                    'messages\nby "cmd" and \\ direction').inc()
        text = reg.exposition()
        assert ("# HELP nnstpu_query_messages_total "
                'messages\\nby "cmd" and \\\\ direction') in text
        # still one line per HELP entry: the raw newline never leaks
        assert all(ln.startswith(("#", "nnstpu_"))
                   for ln in text.strip().splitlines())
