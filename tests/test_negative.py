"""Negative-path sweep: malformed inputs must fail loudly, not corrupt.

Mirrors the reference's SSAT expect-fail discipline — e.g.
tests/nnstreamer_filter_tensorflow2_lite/runTest.sh:74-80 asserts that bad
properties make the pipeline REFUSE to run (`gstTest ... expect-fail`), and
unittest_common's parser suites reject malformed dim/type strings. Every
case here asserts a specific exception type (and usually message) — a
change that silently accepts garbage breaks this suite.
"""

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.core.types import TensorDType, TensorInfo, parse_dimension
from nnstreamer_tpu.graph import Pipeline, PipelineError
from nnstreamer_tpu.graph.parse import parse_caps_string, parse_pipeline


# --------------------------------------------------------------------------- #
# type-system parsers (reference unittest_common negative cases)
# --------------------------------------------------------------------------- #

class TestTypeSystemRejects:
    @pytest.mark.parametrize("dim", [
        "", "abc", "3:abc", "3::2", "-1", "3:-2", "0", "3:0:2",
        ":".join(["2"] * 17),  # above the rank limit (8, TPU-native)
    ])
    def test_bad_dimension_strings(self, dim):
        with pytest.raises((ValueError, TypeError)):
            parse_dimension(dim)

    @pytest.mark.parametrize("t", ["", "float128", "complex64", "int7",
                                   "uint128", "bogus"])
    def test_bad_dtype_names(self, t):
        with pytest.raises((ValueError, KeyError, TypeError)):
            TensorDType.parse(t)

    def test_tensor_count_mismatch(self):
        # a single type broadcasts over N dims (convenience); a >1
        # mismatched count is an error
        with pytest.raises(ValueError, match="count mismatch"):
            TensorsInfo.from_strings("3:2,4:4", "uint8,uint8,uint8")

    def test_more_than_16_tensors_rejected(self):
        dims = ",".join(["2:2"] * 17)
        types = ",".join(["uint8"] * 17)
        with pytest.raises(ValueError):
            TensorsInfo.from_strings(dims, types)

    def test_from_bytes_wrong_size(self):
        info = TensorInfo.from_shape((2, 3), np.float32)
        from nnstreamer_tpu.core.buffer import TensorMemory

        with pytest.raises(ValueError):
            TensorMemory.from_bytes(b"\x00" * 5, info)


# --------------------------------------------------------------------------- #
# caps / pipeline-string parser
# --------------------------------------------------------------------------- #

class TestParserRejects:
    @pytest.mark.parametrize("s", [
        "video/x-raw,format",            # field without value
        "other/tensors,dims=3:2",        # static needs types too (to_config)
    ])
    def test_bad_caps_strings(self, s):
        with pytest.raises(ValueError):
            parse_caps_string(s).to_config()

    @pytest.mark.parametrize("desc", [
        "",                                     # empty pipeline
        "nosuchelement ! tensor_sink",          # unknown element
        "videotestsrc ! nosuchelement",         # unknown downstream
        "videotestsrc bogus_prop=1 ! tensor_sink",  # unknown property
        "videotestsrc !",                       # dangling link
        "! tensor_sink",                        # leading link
        "videotestsrc ! tee name=t t. ! tensor_sink t2. ! fakesink",  # bad ref
    ])
    def test_bad_pipeline_strings(self, desc):
        with pytest.raises((ValueError, KeyError)):
            parse_pipeline(desc)

    def test_unlinked_pad_refused_at_run(self):
        p = Pipeline()
        p.add_new("videotestsrc", num_buffers=1)
        p.add_new("tensor_sink")  # never linked
        with pytest.raises((PipelineError, ValueError)):
            p.run(timeout=10)


# --------------------------------------------------------------------------- #
# tensor_filter property validation
# --------------------------------------------------------------------------- #

class TestFilterRejects:
    def test_unknown_framework(self):
        p = Pipeline()
        src = p.add_new("videotestsrc", width=8, height=8, num_buffers=1)
        conv = p.add_new("tensor_converter")
        filt = p.add_new("tensor_filter", framework="tensorrt",
                         model="x.engine")
        sink = p.add_new("tensor_sink")
        Pipeline.link(src, conv, filt, sink)
        with pytest.raises((PipelineError, ValueError)):
            p.run(timeout=30)

    def test_missing_model(self):
        p = Pipeline()
        src = p.add_new("videotestsrc", width=8, height=8, num_buffers=1)
        conv = p.add_new("tensor_converter")
        filt = p.add_new("tensor_filter", framework="xla-tpu")
        sink = p.add_new("tensor_sink")
        Pipeline.link(src, conv, filt, sink)
        with pytest.raises((PipelineError, ValueError)):
            p.run(timeout=30)

    def test_nonexistent_model_file(self):
        from nnstreamer_tpu.filters.xla import resolve_model

        with pytest.raises((ValueError, FileNotFoundError)):
            resolve_model("/nonexistent/model.jaxexport")

    def test_unknown_zoo_model(self):
        from nnstreamer_tpu.models.zoo import get_model

        with pytest.raises(ValueError, match="unknown zoo model"):
            get_model("zoo://not_a_model")

    def test_accelerator_unknown_device_falls_back(self):
        # reference parse_accl_hw semantics: unknown accelerators fall back
        # to a default device rather than failing the pipeline
        # (nnstreamer_plugin_api_filter.h:547-568)
        from nnstreamer_tpu.filters.base import AcceleratorSpec

        dev = AcceleratorSpec.parse("true:gpu.9999").pick_device()
        assert dev is not None

    def test_bucket_mixed_shapes_rejected(self):
        from nnstreamer_tpu.core.buffer import TensorMemory
        from nnstreamer_tpu.filters.base import FilterProps
        from nnstreamer_tpu.filters.xla import XLAFilter

        f = XLAFilter()
        f.open(FilterProps(model="zoo://passthrough", custom="bucket=4"))
        with pytest.raises(ValueError, match="same-shape"):
            f.invoke([TensorMemory(np.zeros((2, 2), np.float32)),
                      TensorMemory(np.zeros((3, 3), np.float32))])

    def test_reload_incompatible_model_rejected(self):
        import jax.numpy as jnp

        from nnstreamer_tpu.filters.base import FilterProps
        from nnstreamer_tpu.filters.xla import XLAFilter

        f = XLAFilter()
        f.open(FilterProps(model="zoo://scaler?dims=4:1&types=float32"))
        f.set_input_info(TensorsInfo.from_strings("4:1", "float32"))
        with pytest.raises(ValueError, match="reload rejected"):
            f.reload_model(lambda x: jnp.concatenate([x, x], axis=-1))

    def test_py_model_without_make_model(self, tmp_path):
        from nnstreamer_tpu.filters.xla import resolve_model

        bad = tmp_path / "m.py"
        bad.write_text("x = 1\n")
        with pytest.raises(ValueError, match="make_model"):
            resolve_model(str(bad))


# --------------------------------------------------------------------------- #
# converter / decoder option validation
# --------------------------------------------------------------------------- #

class TestBoundaryRejects:
    def test_decoder_without_mode(self):
        p = Pipeline()
        src = p.add_new("videotestsrc", width=8, height=8, num_buffers=1)
        conv = p.add_new("tensor_converter")
        dec = p.add_new("tensor_decoder")
        sink = p.add_new("tensor_sink")
        Pipeline.link(src, conv, dec, sink)
        with pytest.raises((PipelineError, ValueError)):
            p.run(timeout=30)

    def test_decoder_unknown_mode(self):
        from nnstreamer_tpu.elements.decoder import TensorDecoder

        d = TensorDecoder(mode="not_a_decoder")
        with pytest.raises(ValueError, match="unknown mode"):
            d.start()

    def test_bounding_box_requires_priors(self):
        from nnstreamer_tpu.decoders.base import find_decoder

        d = find_decoder("bounding_box")()
        d.init({1: "mobilenet-ssd"})
        cfg = TensorsConfig(TensorsInfo.from_strings(
            "4:8:1,6:8:1", "float32,float32"))
        with pytest.raises(ValueError, match="box-priors"):
            d.decode(Buffer.of(np.zeros((1, 8, 4), np.float32),
                               np.zeros((1, 8, 6), np.float32)), cfg)

    def test_bounding_box_bad_priors_file(self, tmp_path):
        from nnstreamer_tpu.decoders.bounding_box import load_box_priors

        f = tmp_path / "p.txt"
        f.write_text("1 2 3\n")  # needs 4 rows
        with pytest.raises(ValueError, match="4 rows"):
            load_box_priors(str(f))
        with pytest.raises(FileNotFoundError):
            load_box_priors(str(tmp_path / "nope.txt"))

    def test_image_segment_unknown_scheme(self):
        from nnstreamer_tpu.decoders.base import find_decoder

        d = find_decoder("image_segment")()
        d.init({1: "bogus-scheme"})
        cfg = TensorsConfig(TensorsInfo.from_strings("5:8:8:1", "float32"))
        with pytest.raises(ValueError, match="unknown scheme"):
            d.decode(Buffer.of(np.zeros((1, 8, 8, 5), np.float32)), cfg)

    def test_labeling_missing_label_file(self):
        from nnstreamer_tpu.decoders.base import find_decoder

        d = find_decoder("image_labeling")()
        with pytest.raises(FileNotFoundError):
            d.init({1: "/nonexistent/labels.txt"})

    def test_converter_rejects_unknown_video_format(self):
        p = Pipeline()
        from fractions import Fraction

        src = p.add_new(
            "appsrc",
            caps=Caps("video/x-raw", {"format": "YUY2", "width": 4,
                                      "height": 4,
                                      "framerate": Fraction(0, 1)}),
            data=[np.zeros((4, 4, 2), np.uint8)])
        conv = p.add_new("tensor_converter")
        sink = p.add_new("tensor_sink")
        Pipeline.link(src, conv, sink)
        with pytest.raises((PipelineError, ValueError)):
            p.run(timeout=30)

    def test_transform_bad_mode_option(self):
        from nnstreamer_tpu.elements.transform import TensorTransform

        tr = TensorTransform(mode="arithmetic", option="frobnicate:9")
        with pytest.raises(ValueError):
            tr.start()

    def test_transform_unknown_mode(self):
        from nnstreamer_tpu.elements.transform import TensorTransform

        with pytest.raises(ValueError):
            tr = TensorTransform(mode="warp", option="x")
            tr.start()

    def test_flexbuf_truncated_payload(self):
        pytest.importorskip("flatbuffers")
        from nnstreamer_tpu.converters.fb_io import (
            flexbuf_to_frame, frame_to_flexbuf)

        good = frame_to_flexbuf(Buffer.of(np.arange(8, dtype=np.uint8)))
        with pytest.raises(Exception):
            flexbuf_to_frame(good[: len(good) // 2])

    def test_flatbuf_payload_size_mismatch(self):
        pytest.importorskip("flatbuffers")
        from nnstreamer_tpu.converters import fb_io

        # declare float32 2:2 (16 bytes) but ship 4 bytes
        import flatbuffers

        b = flatbuffers.Builder(256)
        name = b.CreateString("")
        data = b.CreateByteVector(b"\x00" * 4)
        b.StartVector(4, 4, 4)
        for d in reversed([2, 2, 1, 1]):
            b.PrependUint32(d)
        dims = b.EndVector()
        b.StartObject(4)
        b.PrependUOffsetTRelativeSlot(0, name, 0)
        b.PrependInt32Slot(1, 7, 10)  # NNS_FLOAT32
        b.PrependUOffsetTRelativeSlot(2, dims, 0)
        b.PrependUOffsetTRelativeSlot(3, data, 0)
        t = b.EndObject()
        b.StartVector(4, 1, 4)
        b.PrependUOffsetTRelative(t)
        tv = b.EndVector()
        b.StartObject(4)
        b.PrependInt32Slot(0, 1, 0)
        b.PrependUOffsetTRelativeSlot(2, tv, 0)
        b.Finish(b.EndObject())
        with pytest.raises(ValueError, match="payload bytes"):
            fb_io.flatbuf_to_frame(bytes(b.Output()))

    def test_sparse_decode_garbage(self):
        from nnstreamer_tpu.elements.sparse import sparse_decode

        with pytest.raises(Exception):
            sparse_decode(b"not a sparse tensor")

    def test_flex_meta_garbage(self):
        from nnstreamer_tpu.core.meta import unwrap_flex

        with pytest.raises(ValueError):
            unwrap_flex(b"\x00" * 16)  # too short for the 128-byte header


# --------------------------------------------------------------------------- #
# element property / wiring validation
# --------------------------------------------------------------------------- #

class TestElementRejects:
    def test_unknown_property(self):
        with pytest.raises((ValueError, TypeError)):
            Pipeline().add_new("videotestsrc", not_a_prop=3)

    def test_aggregator_bad_dims(self):
        from nnstreamer_tpu.elements.aggregator import TensorAggregator

        agg = TensorAggregator(frames_out=0)
        with pytest.raises(ValueError):
            agg.start()

    def test_mux_bad_sync_mode(self):
        p = Pipeline()
        mux = p.add_new("tensor_mux", sync_mode="sometimes")
        from fractions import Fraction

        src = p.add_new("appsrc",
                        caps=Caps.tensors(TensorsConfig(
                            TensorsInfo.from_strings("2:1", "float32"),
                            Fraction(30, 1))),
                        data=[np.zeros((1, 2), np.float32)])
        sink = p.add_new("tensor_sink")
        Pipeline.link(src, mux, sink)
        with pytest.raises((PipelineError, ValueError, KeyError)):
            p.run(timeout=30)

    def test_demux_bad_tensorpick(self):
        from nnstreamer_tpu.elements.mux_demux import TensorDemux

        d = TensorDemux(tensorpick="9")  # out of range for 2-tensor stream
        cfg = TensorsConfig(TensorsInfo.from_strings("2:1,2:1",
                                                     "float32,float32"))
        caps = Caps.tensors(cfg)
        with pytest.raises((ValueError, IndexError)):
            d.on_caps(d.sink_pads[0], caps)
            d.chain(d.sink_pads[0],
                    Buffer.of(np.zeros((1, 2), np.float32),
                              np.zeros((1, 2), np.float32)))

    def test_rate_bad_framerate(self):
        from nnstreamer_tpu.elements.rate import TensorRate

        with pytest.raises((ValueError, ZeroDivisionError)):
            r = TensorRate(framerate="abc")
            r.start()

    def test_crop_without_info_pad_data(self):
        # tensor_crop with only the raw pad linked must refuse negotiation
        p = Pipeline()
        from fractions import Fraction

        src = p.add_new("appsrc",
                        caps=Caps.tensors(TensorsConfig(
                            TensorsInfo.from_strings("3:8:8:1", "uint8"),
                            Fraction(30, 1))),
                        data=[np.zeros((1, 8, 8, 3), np.uint8)])
        crop = p.add_new("tensor_crop")
        sink = p.add_new("tensor_sink")
        Pipeline.link(src, crop, sink)
        with pytest.raises((PipelineError, ValueError)):
            p.run(timeout=30)
