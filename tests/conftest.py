"""Test harness config: run JAX on a virtual 8-device CPU mesh.

Must run before any jax import (pytest imports conftest first), mirroring the
driver's multi-chip dry-run environment.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # override the ambient axon/TPU tunnel
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# the axon sitecustomize force-registers the TPU tunnel regardless of
# JAX_PLATFORMS; the config update below wins over it
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def rng():
    import numpy as np

    return np.random.default_rng(42)


@pytest.fixture(autouse=True, scope="module")
def _bound_jit_state():
    """Full-suite runs (~950 tests, one process, one core) accumulate
    thousands of XLA:CPU executables; past a few GB of JIT state the
    LLVM-side compile occasionally segfaults mid-suite (observed at
    arbitrary tests ~30 min in — jax 0.9 backend_compile_and_load, not
    reproducible on the module alone). Dropping the executable caches
    between modules bounds that state; modules recompile their own
    programs, which they mostly would anyway (distinct shapes)."""
    yield
    import jax

    jax.clear_caches()
