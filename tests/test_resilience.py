"""Resilience layer tests — retry backoff + shared budgets, the circuit
breaker transition machine (injectable clock, no sleeping), deadline
wire semantics + load shedding (client-side and LMEngine admission),
fallback routing with DEGRADED health, thread-leak visibility, the EOS
drain budget, and the deterministic chaos harness (same seed ⇒ same
schedule; zero-overhead hooks when off). E2E acceptance: a server
killed and restarted mid-stream, a breaker-open run on a dead port
completing through the fallback, and a full offload run under a fault
plan with drops + a forced disconnect.
"""

import random
import socket
import threading
import time

import numpy as np
import pytest

import jax

from nnstreamer_tpu.core import Buffer, Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline
from nnstreamer_tpu.graph import element as gel
from nnstreamer_tpu.graph.element import FlowReturn
from nnstreamer_tpu.models import causal_lm
from nnstreamer_tpu.obs import events as obs_events
from nnstreamer_tpu.obs import health as obs_health
from nnstreamer_tpu.query import protocol
from nnstreamer_tpu.query.client import TensorQueryClient
from nnstreamer_tpu.query.protocol import Cmd
from nnstreamer_tpu.resilience import chaos, policy
from nnstreamer_tpu.serving import LMEngine

V, D, H, L, MAXLEN = 97, 32, 4, 2, 64


@pytest.fixture(scope="module")
def lm_params():
    return causal_lm.init_causal_lm(jax.random.PRNGKey(7), V, D, H, L, MAXLEN)


def caps_of(dims, types, rate=30):
    return Caps.tensors(TensorsConfig(
        TensorsInfo.from_strings(dims, types), rate))


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def server_pipeline(port):
    sp = Pipeline("server")
    ssrc = sp.add_new("tensor_query_serversrc", host="127.0.0.1",
                      port=port, id=0, dims="4:1", types="float32")
    filt = sp.add_new("tensor_filter", model=lambda x: x * 10)
    ssink = sp.add_new("tensor_query_serversink", id=0)
    Pipeline.link(ssrc, filt, ssink)
    return sp


_THRESHOLDS = ("stall_after_s", "queue_dwell_s", "reconnect_storm",
               "reconnect_window_s", "admission_deadline_s", "interval_s")


@pytest.fixture
def health():
    reg = obs_health.registry()
    was = reg.is_enabled
    saved = {k: getattr(reg, k) for k in _THRESHOLDS}
    reg.reset()
    yield obs_health
    reg.reset()
    for k, v in saved.items():
        setattr(reg, k, v)
    reg._enabled = was


@pytest.fixture
def events():
    ring = obs_events.ring()
    was = ring.is_enabled
    ring.reset()
    yield obs_events
    obs_events.disable()
    ring.reset()
    ring._enabled = was


def events_of(etype):
    return [e for e in obs_events.ring().snapshot() if e["type"] == etype]


# --------------------------------------------------------------------------- #
# Retry policy + budget
# --------------------------------------------------------------------------- #

class TestRetry:
    def test_cap_grows_exponentially_to_ceiling(self):
        pol = policy.RetryPolicy(base_s=0.05, max_s=0.4, multiplier=2.0)
        assert pol.cap(0) == pytest.approx(0.05)
        assert pol.cap(1) == pytest.approx(0.1)
        assert pol.cap(2) == pytest.approx(0.2)
        assert pol.cap(3) == pytest.approx(0.4)
        assert pol.cap(10) == pytest.approx(0.4)  # ceiling holds
        assert pol.cap(-3) == pytest.approx(0.05)  # clamped, not tiny

    def test_full_jitter_stays_within_window(self):
        pol = policy.RetryPolicy(base_s=0.05, max_s=0.4,
                                 rng=random.Random(3))
        for attempt in range(10):
            for _ in range(20):
                d = pol.delay(attempt)
                assert 0.0 <= d <= pol.cap(attempt)

    def test_seeded_rng_is_deterministic(self):
        a = policy.RetryPolicy(rng=random.Random(11))
        b = policy.RetryPolicy(rng=random.Random(11))
        assert [a.delay(i) for i in range(8)] == \
               [b.delay(i) for i in range(8)]

    def test_jitter_off_returns_exact_cap(self):
        pol = policy.RetryPolicy(base_s=0.1, max_s=1.0, jitter=False)
        assert pol.delay(2) == pol.cap(2)

    def test_invalid_parameters_rejected(self):
        with pytest.raises(ValueError):
            policy.RetryPolicy(base_s=0.0)
        with pytest.raises(ValueError):
            policy.RetryPolicy(multiplier=0.5)

    def test_budget_shared_across_nested_loops(self):
        # the retry² collapse: two loops drawing from ONE pool can never
        # exceed the pool size combined
        budget = policy.RetryBudget(3)
        attempts = 0
        while budget.take():  # "outer" loop
            attempts += 1
            if budget.take():  # "inner" loop draws from the same pool
                attempts += 1
        assert attempts == 3
        assert budget.exhausted and budget.remaining == 0
        assert not budget.take()

    def test_budget_floor_is_one_attempt(self):
        assert policy.RetryBudget(0).attempts == 1
        assert policy.RetryBudget(-5).take()


# --------------------------------------------------------------------------- #
# Circuit breaker
# --------------------------------------------------------------------------- #

class TestCircuitBreaker:
    def test_full_transition_sequence(self, events):
        events.enable()
        now = [100.0]
        b = policy.CircuitBreaker("t.seq", failure_threshold=3,
                                  reset_s=10.0, clock=lambda: now[0])
        assert b.state == policy.CLOSED and b.allow()
        b.record_failure()
        b.record_failure()
        assert b.state == policy.CLOSED  # below threshold
        b.record_failure()
        assert b.state == policy.OPEN
        assert not b.allow()  # cooldown running
        now[0] += 9.9
        assert not b.allow()
        now[0] += 0.2  # cooldown elapsed
        assert b.allow()  # the half-open probe
        assert b.state == policy.HALF_OPEN
        assert not b.allow()  # probe quota (1) spent
        b.record_success()
        assert b.state == policy.CLOSED and b.allow()
        types = [e["type"] for e in obs_events.ring().snapshot()]
        assert "resilience.breaker_open" in types
        assert "resilience.breaker_half_open" in types
        assert "resilience.breaker_close" in types

    def test_half_open_failure_reopens_and_restarts_cooldown(self):
        now = [0.0]
        b = policy.CircuitBreaker("t.reopen", failure_threshold=1,
                                  reset_s=5.0, clock=lambda: now[0])
        b.record_failure()
        assert b.state == policy.OPEN
        now[0] = 5.1
        assert b.allow()
        b.record_failure()  # probe failed
        assert b.state == policy.OPEN
        now[0] = 10.0  # only 4.9s into the NEW cooldown
        assert not b.allow()
        now[0] = 10.3
        assert b.allow()

    def test_success_resets_consecutive_failure_count(self):
        b = policy.CircuitBreaker("t.reset", failure_threshold=2)
        b.record_failure()
        b.record_success()
        b.record_failure()
        assert b.state == policy.CLOSED  # never 2 CONSECUTIVE failures

    def test_multiple_probes_quota(self):
        now = [0.0]
        b = policy.CircuitBreaker("t.probes", failure_threshold=1,
                                  reset_s=1.0, half_open_probes=2,
                                  clock=lambda: now[0])
        b.record_failure()
        now[0] = 1.5
        assert b.allow() and b.allow()
        assert not b.allow()

    def test_invalid_parameters_rejected(self):
        with pytest.raises(ValueError):
            policy.CircuitBreaker("t.bad", failure_threshold=0)


# --------------------------------------------------------------------------- #
# Deadlines
# --------------------------------------------------------------------------- #

class TestDeadline:
    def test_expiry_and_remaining(self):
        d = policy.Deadline.after_s(60)
        assert not d.expired()
        assert 59.0 < d.remaining_s() <= 60.0
        assert policy.Deadline.after_ms(0).expired()
        assert policy.Deadline.after_ms(-50).expired()

    def test_wire_roundtrip_carries_remaining_budget(self):
        d = policy.Deadline.after_ms(80)
        w = d.to_wire()
        assert 0.0 < w <= 80.0  # remaining ms, not an absolute stamp
        d2 = policy.Deadline.from_wire(w)
        assert abs(d2.remaining_s() - d.remaining_s()) < 0.05

    def test_expired_deadline_encodes_zero(self):
        assert policy.Deadline.after_ms(-100).to_wire() == 0.0

    def test_from_wire_rejects_garbage(self):
        assert policy.Deadline.from_wire("junk") is None
        assert policy.Deadline.from_wire(None) is None
        assert policy.Deadline.from_wire("25.0") is not None

    def test_buffer_meta_helpers(self):
        buf = Buffer.of(np.zeros((1, 4), np.float32))
        assert policy.deadline_of(buf) is None
        d = policy.Deadline.after_s(1)
        policy.set_deadline(buf, d)
        assert policy.deadline_of(buf) is d
        buf.meta[policy.DEADLINE_META_KEY] = "not-a-deadline"
        assert policy.deadline_of(buf) is None


# --------------------------------------------------------------------------- #
# Client-side shedding + EOS drain budget
# --------------------------------------------------------------------------- #

class TestClientShedAndDrain:
    def test_expired_buffer_shed_before_send(self, events):
        # legal drop: OK without pushing, no socket ever touched
        events.enable()
        qc = TensorQueryClient(name="qshed")
        buf = Buffer.of(np.zeros((1, 4), np.float32))
        policy.set_deadline(buf, policy.Deadline.after_ms(0))
        assert qc.chain(qc.sink_pad, buf) == FlowReturn.OK
        assert qc._sock is None
        shed = events_of("resilience.shed")
        assert shed and shed[0]["attrs"]["site"] == "query"

    def test_deadline_ms_prop_stamps_ingress(self, events):
        events.enable()
        # a budget small enough to be spent by the time chain() checks
        # it: the buffer gets stamped AND shed without touching a socket
        qc = TensorQueryClient(name="qstamp", deadline_ms=0.0001)
        buf = Buffer.of(np.zeros((1, 4), np.float32))
        assert qc.chain(qc.sink_pad, buf) == FlowReturn.OK
        assert isinstance(policy.deadline_of(buf), policy.Deadline)
        assert qc._last_deadline is policy.deadline_of(buf)
        # an upstream deadline always wins over the element's prop
        buf2 = Buffer.of(np.zeros((1, 4), np.float32))
        upstream = policy.Deadline.after_ms(0)
        policy.set_deadline(buf2, upstream)
        qc.chain(qc.sink_pad, buf2)
        assert qc._last_deadline is upstream

    def test_drain_abandoned_records_pending_count(self, events):
        events.enable()
        qc = TensorQueryClient(name="qdrain", drain_timeout_s=0.05)
        qc._pending.append([0, 0, 0, True, 0.0, None, None])
        qc._pending.append([0, 0, 1, True, 0.0, None, None])
        t0 = time.monotonic()
        qc._drain_pending()
        assert time.monotonic() - t0 < 2.0
        evs = events_of("query.drain_abandoned")
        assert evs and evs[0]["attrs"]["pending"] == 2

    def test_drain_honors_last_deadline(self, events):
        events.enable()
        qc = TensorQueryClient(name="qdrain2", drain_timeout_s=60.0)
        qc._pending.append([0, 0, 0, True, 0.0, None, None])
        qc._last_deadline = policy.Deadline.after_ms(30)
        t0 = time.monotonic()
        qc._drain_pending()  # waits the deadline, not the 60s prop
        assert time.monotonic() - t0 < 2.0
        assert events_of("query.drain_abandoned")


# --------------------------------------------------------------------------- #
# Thread-leak visibility
# --------------------------------------------------------------------------- #

class TestThreadLeak:
    def test_join_timeout_warns_and_records_event(self, events, caplog):
        events.enable()
        release = threading.Event()
        t = threading.Thread(target=release.wait, daemon=True,
                             name="leaky-worker")
        t.start()
        try:
            with caplog.at_level("WARNING"):
                assert gel.join_or_warn(t, "queue0", timeout=0.05) is False
        finally:
            release.set()
            t.join()
        assert any("leaked" in r.message for r in caplog.records)
        evs = events_of("pipeline.thread_leak")
        assert evs and evs[0]["attrs"]["thread"] == "leaky-worker"
        assert evs[0]["attrs"]["element"] == "queue0"

    def test_clean_exit_returns_true_silently(self, events):
        events.enable()
        t = threading.Thread(target=lambda: None)
        t.start()
        assert gel.join_or_warn(t, "queue0", timeout=5.0) is True
        assert not events_of("pipeline.thread_leak")


# --------------------------------------------------------------------------- #
# Chaos harness
# --------------------------------------------------------------------------- #

class TestChaosPlan:
    def test_nth_fires_on_exact_matching_calls(self):
        plan = chaos.FaultPlan(
            [chaos.Fault(kind="drop", target="send", cmd="DATA",
                         nth=(1, 3))], seed=0)
        fires = [bool(plan.decide("send", "DATA")) for _ in range(4)]
        assert fires == [True, False, True, False]

    def test_cmd_filter_skips_non_matching_calls(self):
        plan = chaos.FaultPlan(
            [chaos.Fault(kind="drop", target="send", cmd="DATA", nth=1)],
            seed=0)
        # the handshake never advances the DATA counter
        assert plan.decide("send", "INFO_REQ") == []
        assert plan.decide("recv", "DATA") == []
        assert plan.decide("send", "DATA") != []

    def test_chain_target_prefix_matching(self):
        plan = chaos.FaultPlan(
            [chaos.Fault(kind="drop", target="chain", nth=(1, 2)),
             chaos.Fault(kind="delay", target="chain:sinkA", nth=1)],
            seed=0)
        hits = plan.decide("chain:sinkB")
        assert [f.kind for f in hits] == ["drop"]  # bare chain matches all
        hits = plan.decide("chain:sinkA")
        assert sorted(f.kind for f in hits) == ["delay", "drop"]

    def test_max_fires_caps_without_disturbing_draws(self):
        spec = {"seed": 9, "faults": [
            {"kind": "drop", "target": "send", "p": 0.5}]}
        uncapped = chaos.FaultPlan.from_spec(spec)
        free = [bool(uncapped.decide("send", "DATA")) for _ in range(40)]
        spec["faults"][0]["max_fires"] = 2
        capped = chaos.FaultPlan.from_spec(spec)
        limited = [bool(capped.decide("send", "DATA")) for _ in range(40)]
        assert sum(limited) == 2
        # the fires it DID take are the first would-be fires of the
        # uncapped schedule: the PRNG sequence was not disturbed
        assert [i for i, f in enumerate(limited) if f] == \
               [i for i, f in enumerate(free) if f][:2]

    def test_same_seed_same_schedule(self):
        spec = {"seed": 7, "faults": [
            {"kind": "drop", "target": "send", "cmd": "DATA", "p": 0.3},
            {"kind": "delay", "target": "recv", "p": 0.2},
            {"kind": "drop", "target": "chain", "p": 0.25}]}
        a, b = chaos.FaultPlan.from_spec(spec), chaos.FaultPlan.from_spec(spec)
        calls = [("send", "DATA")] * 50 + [("recv", None)] * 30 + \
                [("chain:sink", None)] * 30
        da = [[f.kind for f in a.decide(t, c)] for t, c in calls]
        db = [[f.kind for f in b.decide(t, c)] for t, c in calls]
        assert da == db
        assert a.fired == b.fired

    def test_different_seed_different_schedule(self):
        mk = lambda seed: chaos.FaultPlan(
            [chaos.Fault(kind="drop", target="send", p=0.3)], seed=seed)
        a, b = mk(1), mk(2)
        da = [bool(a.decide("send", "DATA")) for _ in range(50)]
        db = [bool(b.decide("send", "DATA")) for _ in range(50)]
        assert da != db

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            chaos.Fault(kind="explode")

    def test_corrupt_inverts_first_byte_only(self):
        assert chaos._corrupt(b"\x00abc") == b"\xffabc"
        assert chaos._corrupt(b"") == b""

    def test_plan_from_env(self, monkeypatch):
        monkeypatch.setenv(chaos.ENV_VAR, '{"seed": 5, "faults": '
                           '[{"kind": "drop", "target": "send", "p": 0.1}]}')
        plan = chaos.plan_from_env()
        assert plan is not None and plan.seed == 5
        assert len(plan.faults) == 1
        monkeypatch.setenv(chaos.ENV_VAR, "{not json")
        assert chaos.plan_from_env() is None  # typo must never be fatal
        monkeypatch.setenv(chaos.ENV_VAR,
                           '{"faults": [{"kind": "nope"}]}')
        assert chaos.plan_from_env() is None
        monkeypatch.delenv(chaos.ENV_VAR)
        assert chaos.plan_from_env() is None


class TestChaosHooks:
    def test_hooks_are_none_when_off(self):
        # the zero-overhead contract: disabled cost is one global load
        # + `is None` in send/recv/push — nothing else to pay
        assert protocol.CHAOS_HOOK is None
        assert gel.CHAOS_CHAIN_HOOK is None
        assert chaos.active() is None

    def test_install_sets_and_uninstall_clears(self):
        plan = chaos.FaultPlan([], seed=0)
        chaos.install(plan)
        try:
            assert protocol.CHAOS_HOOK is chaos._wire_hook
            assert gel.CHAOS_CHAIN_HOOK is chaos._chain_hook
            assert chaos.active() is plan
        finally:
            chaos.uninstall()
        assert protocol.CHAOS_HOOK is None
        assert gel.CHAOS_CHAIN_HOOK is None
        assert chaos.active() is None

    def test_wire_hook_semantics(self):
        plan = chaos.FaultPlan(
            [chaos.Fault(kind="drop", target="send", cmd="DATA", nth=1),
             chaos.Fault(kind="corrupt", target="send", cmd="DATA", nth=2),
             chaos.Fault(kind="disconnect", target="send", cmd="DATA",
                         nth=3)], seed=0)
        chaos.install(plan)
        try:
            assert chaos._wire_hook("send", Cmd.DATA, {}, b"\x01x") is None
            assert chaos._wire_hook("send", Cmd.DATA, {}, b"\x01x") \
                == b"\xfex"
            with pytest.raises(ConnectionError, match="chaos"):
                chaos._wire_hook("send", Cmd.DATA, {}, b"\x01x")
            # clean call passes the payload through untouched
            assert chaos._wire_hook("send", Cmd.DATA, {}, b"\x01x") \
                == b"\x01x"
        finally:
            chaos.uninstall()
        assert [f["kind"] for f in plan.fired] == \
            ["drop", "corrupt", "disconnect"]


# --------------------------------------------------------------------------- #
# LMEngine admission shedding
# --------------------------------------------------------------------------- #

class TestEngineShedding:
    def test_expired_at_submit_finishes_empty(self, lm_params, events):
        events.enable()
        eng = LMEngine(lm_params, H, MAXLEN, n_slots=2, chunk=4)
        ok = eng.submit([1, 2, 3], max_new=6,
                        deadline=policy.Deadline.after_s(600))
        dead = eng.submit([4, 5, 6], max_new=6,
                          deadline=policy.Deadline.after_ms(0))
        res = eng.run()
        assert res[dead] == []  # shed at the door, never prefilled
        assert len(res[ok]) == 6  # live deadline generates normally
        shed = events_of("resilience.shed")
        assert shed and shed[0]["attrs"]["site"] == "serving"

    def test_expired_in_queue_shed_at_admission(self, lm_params, events):
        events.enable()
        eng = LMEngine(lm_params, H, MAXLEN, n_slots=1, chunk=4)
        r1 = eng.submit([1, 2, 3], max_new=8)
        r2 = eng.submit([4, 5], max_new=4,
                        deadline=policy.Deadline.after_ms(1))
        time.sleep(0.05)  # r2's budget expires while it waits for a slot
        res = eng.run()
        assert len(res[r1]) == 8
        assert res[r2] == []
        assert eng.stats["prefills"] == 1  # the shed request cost nothing
        assert events_of("resilience.shed")


# --------------------------------------------------------------------------- #
# E2E: reconnect, fallback degradation, chaos acceptance
# --------------------------------------------------------------------------- #

class TestEndToEnd:
    def test_server_killed_then_restarted_stream_completes(self):
        """Kill the server mid-stream, restart it on the same port: the
        client's shared retry budget + backoff must redial and finish
        the remaining frames with correct results."""
        port = free_port()
        sp = server_pipeline(port)
        sp.start()
        sp2 = None
        # the client is driven directly (no source element) so the test
        # controls exactly which frame meets the dead server
        qc = gel.make_element("tensor_query_client", host="127.0.0.1",
                              port=port, max_request_retry=60,
                              timeout_s=2.0, retry_base_s=0.02,
                              retry_max_s=0.1)
        sink = gel.make_element("tensor_sink", store=True)
        qc.src_pads[0].link(sink.sink_pads[0])
        try:
            time.sleep(0.2)
            sink.start()
            qc.start()
            qc.on_caps(qc.sink_pad, caps_of("4:1", "float32"))
            frames = [np.full((1, 4), i, np.float32) for i in range(6)]
            for i in range(3):
                buf = Buffer.of(frames[i])
                buf.offset = i
                assert qc._chain_entry(qc.sink_pad, buf) == FlowReturn.OK
            sp.stop()  # server dies with the client connection live
            sp2 = server_pipeline(port)
            sp2.start()
            time.sleep(0.2)
            for i in range(3, 6):  # first of these rides the dead socket
                buf = Buffer.of(frames[i])
                buf.offset = i
                assert qc._chain_entry(qc.sink_pad, buf) == FlowReturn.OK
            assert sink.num_buffers == 6
            for i, out in enumerate(sink.buffers):
                np.testing.assert_array_equal(out.memories[0].host(),
                                              frames[i] * 10)
                assert out.offset == i
        finally:
            qc.stop()
            sp.stop()
            if sp2 is not None:
                sp2.stop()

    def test_breaker_open_routes_fallback_and_degrades(self, events, health):
        """Nothing listening: the breaker opens after threshold failures
        and every later buffer takes the passthrough fallback — the
        pipeline COMPLETES, and health says DEGRADED (/healthz verdict
        stays ok), not failed."""
        events.enable()
        health.enable()
        port = free_port()  # never bound
        cp = Pipeline("fb-client")
        frames = [np.full((1, 4), i, np.float32) for i in range(5)]
        src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"),
                         data=frames)
        qc = cp.add_new("tensor_query_client", host="127.0.0.1", port=port,
                        max_request_retry=1, timeout_s=0.3,
                        retry_base_s=0.001, retry_max_s=0.002,
                        breaker_threshold=2, breaker_reset_s=600.0,
                        fallback="passthrough")
        sink = cp.add_new("tensor_sink", store=True)
        Pipeline.link(src, qc, sink)
        cp.run(timeout=60)  # no PipelineError: degradation, not failure
        assert sink.num_buffers == 5
        for i, out in enumerate(sink.buffers):  # passthrough = unchanged
            np.testing.assert_array_equal(out.memories[0].host(), frames[i])
        assert qc._breaker.state == policy.OPEN
        assert events_of("resilience.breaker_open")
        assert events_of("resilience.fallback")
        snap = obs_health.snapshot()
        comp = next(c for c in snap["components"]
                    if c["name"] == f"query.client:{qc.name}")
        assert comp["status"] == "degraded"
        assert snap["ok"] is True  # impaired but alive — not a 503

    def test_fallback_element_processes_locally(self, events):
        """fallback=<kind>: a local element produces the degraded
        output (here an on-host tensor_filter standing in for the
        remote one)."""
        events.enable()
        port = free_port()
        cp = Pipeline("fb-local")
        src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"),
                         data=[np.full((1, 4), 3.0, np.float32)])
        qc = cp.add_new("tensor_query_client", host="127.0.0.1", port=port,
                        max_request_retry=1, timeout_s=0.3,
                        retry_base_s=0.001, retry_max_s=0.002,
                        breaker_threshold=1,
                        fallback=lambda x: x + 1)
        sink = cp.add_new("tensor_sink", store=True)
        Pipeline.link(src, qc, sink)
        cp.run(timeout=60)
        assert sink.num_buffers == 1
        np.testing.assert_array_equal(
            sink.buffers[0].memories[0].host(),
            np.full((1, 4), 4.0, np.float32))

    @pytest.mark.chaos
    def test_offload_completes_under_fault_plan(self):
        """Acceptance: a full offload run with injected DATA drops and
        one forced disconnect still completes with correct results —
        the drop surfaces as a recv timeout, the disconnect as a raised
        ConnectionError, both absorbed by the shared retry budget."""
        port = free_port()
        sp = server_pipeline(port)
        sp.start()
        plan = chaos.FaultPlan(
            [chaos.Fault(kind="drop", target="send", cmd="DATA", nth=2),
             chaos.Fault(kind="disconnect", target="send", cmd="DATA",
                         nth=5)], seed=11)
        chaos.install(plan)
        try:
            time.sleep(0.2)
            cp = Pipeline("chaos-client")
            frames = [np.full((1, 4), i, np.float32) for i in range(6)]
            src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"),
                             data=frames)
            qc = cp.add_new("tensor_query_client", host="127.0.0.1",
                            port=port, max_request_retry=4, timeout_s=0.5,
                            retry_base_s=0.01, retry_max_s=0.03)
            sink = cp.add_new("tensor_sink", store=True)
            Pipeline.link(src, qc, sink)
            cp.run(timeout=60)
            assert sink.num_buffers == 6
            for i, out in enumerate(sink.buffers):
                np.testing.assert_array_equal(out.memories[0].host(),
                                              frames[i] * 10)
            assert [f["kind"] for f in plan.fired] == ["drop", "disconnect"]
        finally:
            chaos.uninstall()
            sp.stop()

    @pytest.mark.chaos
    def test_chain_drop_fault_drops_buffer(self):
        """chain:<element> faults drop buffers with the graph's legal
        drop semantics — downstream simply sees fewer buffers."""
        plan = chaos.FaultPlan(
            [chaos.Fault(kind="drop", target="chain:csink", nth=2)],
            seed=0)
        chaos.install(plan)
        try:
            cp = Pipeline("chain-chaos")
            src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"),
                             data=[np.full((1, 4), i, np.float32)
                                   for i in range(4)])
            sink = cp.add_new("tensor_sink", name="csink", store=True)
            Pipeline.link(src, sink)
            cp.run(timeout=60)
            assert sink.num_buffers == 3  # frame #2 vanished
            assert [f["call"] for f in plan.fired] == [2]
        finally:
            chaos.uninstall()
