"""Paged KV cache (serving/kv_cache.py + the LMEngine paged path).

Contracts pinned here:

- Allocator: reservation accounting balances, admission is gated on
  ``available()``, eviction is lazy + deterministic LRU, host offload
  round-trips page bits exactly.
- Kernels (models/causal_lm.py paged section): the gathered page view
  IS the contiguous layout, so paged decode/verify/prefill are
  bit-identical to the contiguous kernels — by construction, asserted
  with exact equality (no tolerances).
- Engine: greedy output under paging matches the contiguous engine and
  the isolated oracle token-for-token across prefix sharing, COW
  divergence, pool exhaustion, eviction, offload, speculative decoding,
  and the bounded per-slot view.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import causal_lm
from nnstreamer_tpu.serving import LMEngine, PagedKVCache, TPLMEngine
from nnstreamer_tpu.serving.kv_cache import empty_page_pool

V, D, H, L, MAXLEN = 97, 32, 4, 2, 64
PS = 8  # page size used by every engine test: 8 pages per max_len


@pytest.fixture(scope="module")
def params():
    return causal_lm.init_causal_lm(
        jax.random.PRNGKey(7), V, D, H, L, MAXLEN)


# single-bucket jitted oracle: every prompt in this file fits one padded
# prefill shape, so the whole suite pays exactly two oracle compiles
_ORACLE_BUCKET = 32
_oracle_prefill = jax.jit(causal_lm.lm_prefill_masked, static_argnums=(3, 4))
_oracle_decode = jax.jit(causal_lm.lm_decode_step, static_argnums=(5,))


def isolated_generate(params, prompt, max_new, eos=None):
    """Single-stream oracle: masked-bucket prefill + one-at-a-time decode."""
    p = np.asarray(prompt, np.int32)
    assert len(p) <= _ORACLE_BUCKET
    buf = np.zeros((1, _ORACLE_BUCKET), np.int32)
    buf[0, :len(p)] = p
    logits, kc, vc, pos = _oracle_prefill(
        params, jnp.asarray(buf), jnp.int32(len(p)), H, MAXLEN)
    out = [int(jnp.argmax(logits[0]))]
    while len(out) < max_new and not (eos is not None and out[-1] == eos):
        tok = jnp.asarray([[out[-1]]], jnp.int32)
        logits, kc, vc, pos = _oracle_decode(params, tok, kc, vc, pos, H)
        out.append(int(jnp.argmax(logits[0])))
    return out


def prompts_rng(n, lo=1, hi=24, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, V, rng.integers(lo, hi)).astype(np.int32)
            for _ in range(n)]


def run_engine(params, jobs, **kw):
    eng = LMEngine(params, H, MAXLEN, **kw)
    rids = [eng.submit(p, max_new=mn) for p, mn in jobs]
    res = eng.run()
    return [res[r] for r in rids], eng


# -- allocator units (tiny standalone pools, no model) --------------------- #


def _cache(n_pages=8, ps=4, **kw):
    return PagedKVCache(1, 1, ps, n_pages, 2, **kw)


def _toks(seed, n):
    return np.random.default_rng(seed).integers(0, 50, n).astype(np.int32)


def test_reservation_accounting_balances():
    kv = _cache()
    prompt = _toks(0, 10)
    plan = kv.lookup(prompt)
    assert plan.hit_len == 0
    lease = kv.admit(plan, b_needed=4)
    # 3 prompt pages allocated eagerly, 1 still claimable
    assert len(lease.pages) == 3 and lease.reserved == 1
    assert kv.reserved == 1 and kv.available() == 8 - 4
    kv.lease_alloc(lease)
    assert lease.reserved == 0 and kv.reserved == 0
    with pytest.raises(RuntimeError, match="reservation"):
        kv.lease_alloc(lease)
    kv.release(lease, prompt)
    assert kv.reserved == 0
    # the 2 full prompt chunks stay registered (evictable), the rest
    # returned: everything is claimable again
    assert kv.available() == 8 and len(kv._lru) == 2


def test_admissible_gates_and_lazy_eviction_reclaims():
    kv = _cache(n_pages=4)
    p1, p2, p3 = _toks(1, 8), _toks(2, 8), _toks(3, 8)
    l1 = kv.admit(kv.lookup(p1), b_needed=2)
    kv.admit(kv.lookup(p2), b_needed=2)
    plan3 = kv.lookup(p3)
    assert not kv.admissible(plan3, b_needed=2)
    kv.release(l1, p1)  # 2 registered ref-0 pages -> evictable
    assert kv.admissible(plan3, b_needed=2)
    l3 = kv.admit(plan3, b_needed=2)
    assert len(l3.pages) == 2
    # allocation was served by dropping p1's ref-0 subtree (both pages
    # free in one eviction -- the whole chain is dead without its root)
    assert kv.stats["evictions"] == 2


def test_lookup_caps_hit_at_t_minus_1_and_cow_matches():
    kv = _cache()
    prompt = _toks(4, 8)
    kv.release(kv.admit(kv.lookup(prompt), b_needed=2), prompt)
    plan = kv.lookup(prompt)
    # same 8 tokens again: only 1 FULL chunk may match ((t-1)//ps); the
    # second chunk is served as a 3-token COW partial -> hit t-1
    assert len(plan.nodes) == 1
    assert plan.cow is not None and plan.cow[1] == 3
    assert plan.hit_len == 7
    lease = kv.admit(plan, b_needed=2)
    assert kv.stats["cow_copies"] == 1
    # the COW page is owned outright, never the shared original
    assert plan.cow[0].page not in lease.own


def test_cow_copy_preserves_page_bits():
    kv = _cache()
    prompt = _toks(5, 8)
    l0 = kv.admit(kv.lookup(prompt), b_needed=2)
    marker = jnp.full_like(kv.kpool[l0.pages[0]], 1.5)
    kv.kpool = kv.kpool.at[l0.pages[0]].set(marker)
    kv.release(l0, prompt)
    # diverge inside page 0: 2 shared tokens then different ones
    other = np.concatenate([prompt[:2], _toks(6, 6)])
    plan = kv.lookup(other)
    assert plan.nodes == [] and plan.cow is not None and plan.cow[1] == 2
    lease = kv.admit(plan, b_needed=2)
    cow_pid = lease.pages[0]
    np.testing.assert_array_equal(np.asarray(kv.kpool[cow_pid]),
                                  np.asarray(marker))


def test_eviction_is_deterministic():
    def drive(kv):
        for seed in range(6):
            p = _toks(seed, 12)
            kv.release(kv.admit(kv.lookup(p), b_needed=3), p)
        return list(kv.free), dict(kv.stats)

    a, b = drive(_cache(n_pages=6)), drive(_cache(n_pages=6))
    assert a == b
    assert a[1]["evictions"] > 0


def test_host_offload_roundtrips_page_bits():
    kv = _cache(n_pages=2, host_offload=True)
    prompt = _toks(7, 8)
    lease = kv.admit(kv.lookup(prompt), b_needed=2)
    p1, p2 = lease.pages
    kv.kpool = kv.kpool.at[p1].set(1.25)
    kv.vpool = kv.vpool.at[p1].set(2.5)
    kv.kpool = kv.kpool.at[p2].set(3.75)
    kv.release(lease, prompt)
    # a second request forces both pages out: D2H once per page, nodes
    # stay in the tree page-less
    other = _toks(8, 8)
    kv.release(kv.admit(kv.lookup(other), b_needed=2), other)
    assert kv.stats["offloads"] == 2 and kv.stats["evictions"] == 2
    # re-admitting the first prompt re-uploads the matched chunk with
    # its original bits (full-chunk hit is capped at (t-1)//ps = 1;
    # offloaded chunk 2 is not a COW candidate -- device-resident only)
    plan = kv.lookup(prompt)
    assert len(plan.nodes) == 1 and plan.cow is None
    lease3 = kv.admit(plan, b_needed=2)
    assert kv.stats["reuploads"] == 1
    np.testing.assert_array_equal(
        np.asarray(kv.kpool[lease3.pages[0]]),
        np.full_like(np.asarray(kv.kpool[0]), 1.25))
    np.testing.assert_array_equal(
        np.asarray(kv.vpool[lease3.pages[0]]),
        np.full_like(np.asarray(kv.vpool[0]), 2.5))


def test_pool_validation():
    with pytest.raises(ValueError, match=">= 1"):
        PagedKVCache(1, 1, 0, 4, 2)
    with pytest.raises(ValueError, match=">= 1"):
        PagedKVCache(1, 1, 4, 0, 2)


# -- kernel bit-identity --------------------------------------------------- #


def _paged_from_flat(kc, vc, ps):
    """Scatter one flat (LH, M, hd) cache into fresh page pools; returns
    (kpool, vpool, table) with pages 1..M/ps in order."""
    lh, m, hd = kc.shape
    b = m // ps
    kpool, vpool = empty_page_pool(b, 1, lh, ps, hd)
    table = jnp.arange(1, b + 1, dtype=jnp.int32)
    kpool = kpool.at[table].set(
        kc.reshape(lh, b, ps, hd).transpose(1, 0, 2, 3))
    vpool = vpool.at[table].set(
        vc.reshape(lh, b, ps, hd).transpose(1, 0, 2, 3))
    return kpool, vpool, table


def test_paged_view_is_the_contiguous_layout(params):
    prompt = prompts_rng(1, lo=10, hi=11, seed=20)[0]
    _, kc, vc, _ = causal_lm.lm_prefill(
        params, jnp.asarray(prompt[None]), H, MAXLEN)
    kpool, _, table = _paged_from_flat(kc, vc, PS)
    view = causal_lm.paged_view_slots(kpool, table[None])[0]
    np.testing.assert_array_equal(np.asarray(view), np.asarray(kc))


def test_paged_decode_steps_bit_identical(params):
    prompt = prompts_rng(1, lo=9, hi=10, seed=21)[0]
    lg, kc, vc, pos = causal_lm.lm_prefill(
        params, jnp.asarray(prompt[None]), H, MAXLEN)
    kpool, vpool, table = _paged_from_flat(kc, vc, PS)
    tables, poss = table[None], pos[None]
    kcs, vcs = kc[None], vc[None]
    tok = jnp.argmax(lg, -1).astype(jnp.int32)[:, None][None]
    step_c = jax.jit(causal_lm.lm_decode_step_slots, static_argnums=(5,))
    step_p = jax.jit(causal_lm.lm_decode_step_paged, static_argnums=(6,))
    for _ in range(2 * PS + 3):  # cross two page boundaries
        lg_c, kcs, vcs, poss_c = step_c(params, tok, kcs, vcs, poss, H)
        lg_p, kpool, vpool, poss = step_p(
            params, tok, kpool, vpool, tables, poss, H)
        np.testing.assert_array_equal(np.asarray(lg_p), np.asarray(lg_c))
        np.testing.assert_array_equal(np.asarray(poss), np.asarray(poss_c))
        tok = jnp.argmax(lg_p, -1).astype(jnp.int32)[:, :, None]
    # every touched page carries the same bits as the contiguous cache
    view = causal_lm.paged_view_slots(kpool, tables)
    np.testing.assert_array_equal(np.asarray(view), np.asarray(kcs))


def test_paged_verify_window_bit_identical(params):
    rng = np.random.default_rng(22)
    prompt = rng.integers(0, V, (1, 12)).astype(np.int32)
    _, kc, vc, pos = causal_lm.lm_prefill(
        params, jnp.asarray(prompt), H, MAXLEN)
    window = rng.integers(0, V, (1, 5)).astype(np.int32)
    wl, kc2, vc2, wpos = causal_lm.lm_verify_window_slots(
        params, jnp.asarray(window)[None][:, 0], kc[None], vc[None],
        pos[None], H)
    kpool, vpool, table = _paged_from_flat(kc, vc, PS)
    pl, kpool, vpool, ppos = causal_lm.lm_verify_window_paged(
        params, jnp.asarray(window), kpool, vpool, table[None], pos[None], H)
    np.testing.assert_array_equal(np.asarray(pl), np.asarray(wl))
    np.testing.assert_array_equal(np.asarray(ppos), np.asarray(wpos))
    view = causal_lm.paged_view_slots(kpool, table[None])
    np.testing.assert_array_equal(np.asarray(view), np.asarray(kc2))


def test_touch_span_bounds():
    assert causal_lm.paged_touch_span(1, 8, 8) == 2
    assert causal_lm.paged_touch_span(8, 8, 8) == 2
    assert causal_lm.paged_touch_span(9, 8, 8) == 3
    assert causal_lm.paged_touch_span(64, 8, 8) == 8  # capped at table


# -- engine: exactness under paging ---------------------------------------- #


def test_paged_engine_bit_identical_to_contiguous(params):
    jobs = [(p, 6 + i % 5) for i, p in enumerate(prompts_rng(7, seed=30))]
    cont, _ = run_engine(params, jobs, n_slots=2, chunk=4)
    paged, eng = run_engine(params, jobs, n_slots=2, chunk=4,
                            kv_page_size=PS)
    assert paged == cont
    for (p, mn), got in zip(jobs, paged):
        assert got == isolated_generate(params, p, mn)
    assert eng.kv_stats is not None and eng.kv_stats["pages_peak"] > 0


def test_prefix_sharing_hits_and_stays_exact(params):
    prefix = prompts_rng(1, lo=16, hi=17, seed=31)[0]  # 2 full pages
    jobs = [(np.concatenate([prefix, s]), 8)
            for s in prompts_rng(5, lo=4, hi=12, seed=32)]
    paged, eng = run_engine(params, jobs, n_slots=2, chunk=4,
                            kv_page_size=PS)
    for (p, mn), got in zip(jobs, paged):
        assert got == isolated_generate(params, p, mn)
    kv = eng.kv_stats
    assert kv["hit_requests"] >= 3
    assert kv["hit_tokens"] >= 3 * 16


def test_cow_divergence_stays_exact(params):
    # 12 shared tokens = 1 full page + a 4-token partial: the partial
    # must be served copy-on-write, and divergent suffixes never bleed
    # into each other through the shared page
    prefix = prompts_rng(1, lo=12, hi=13, seed=33)[0]
    jobs = [(np.concatenate([prefix, s]), 7)
            for s in prompts_rng(4, lo=3, hi=10, seed=34)]
    paged, eng = run_engine(params, jobs, n_slots=2, chunk=4,
                            kv_page_size=PS)
    for (p, mn), got in zip(jobs, paged):
        assert got == isolated_generate(params, p, mn)
    assert eng.kv_stats["cow_copies"] >= 1


def test_pool_exhaustion_defers_admission_fifo(params):
    # pool of 8 pages, each request needs 4: only 2 admissible at once,
    # the rest wait their turn and every stream still completes exact
    jobs = [(p, 8) for p in prompts_rng(6, lo=20, hi=24, seed=35)]
    paged, eng = run_engine(params, jobs, n_slots=2, chunk=4,
                            kv_page_size=PS, kv_pages=8)
    for (p, mn), got in zip(jobs, paged):
        assert got == isolated_generate(params, p, mn)
    assert eng.kv_stats["pages_peak"] <= 8


def test_engine_eviction_deterministic(params):
    jobs = [(p, 8) for p in prompts_rng(6, lo=18, hi=28, seed=36)]

    def once():
        outs, eng = run_engine(params, jobs, n_slots=2, chunk=4,
                               kv_page_size=PS, kv_pages=8)
        return outs, eng.kv_stats

    (out_a, kv_a), (out_b, kv_b) = once(), once()
    assert out_a == out_b and kv_a == kv_b
    assert kv_a["evictions"] > 0
    for (p, mn), got in zip(jobs, out_a):
        assert got == isolated_generate(params, p, mn)


def test_engine_host_offload_reuploads_and_stays_exact(params):
    base = prompts_rng(1, lo=24, hi=25, seed=37)[0]
    eng = LMEngine(params, H, MAXLEN, n_slots=2, chunk=4,
                   kv_page_size=PS, kv_pages=8, kv_host_offload=True)
    r1 = eng.submit(base, max_new=8)
    assert eng.run()[r1] == isolated_generate(params, base, 8)
    # churn the pool so base's registered chunks get offloaded
    churn = prompts_rng(2, lo=22, hi=26, seed=38)
    rids = [eng.submit(p, max_new=8) for p in churn]
    res = eng.run()
    for rid, p in zip(rids, churn):
        assert res[rid] == isolated_generate(params, p, 8)
    kv = eng.kv_stats
    assert kv["offloads"] >= 1
    # the same prompt again: its offloaded prefix re-uploads, not
    # recomputes -- and the output is still exact
    r2 = eng.submit(base, max_new=8)
    assert eng.run()[r2] == isolated_generate(params, base, 8)
    kv = eng.kv_stats
    assert kv["reuploads"] >= 1 and kv["hit_tokens"] > 0


def test_mid_flight_admission_paged(params):
    prompts = prompts_rng(5, seed=39)
    eng = LMEngine(params, H, MAXLEN, n_slots=2, chunk=4, kv_page_size=PS)
    rids = [eng.submit(p, max_new=10) for p in prompts[:2]]
    eng.step_iteration()
    eng.step_iteration()
    rids += [eng.submit(p, max_new=10) for p in prompts[2:]]
    res = eng.run()
    for rid, p in zip(rids, prompts):
        assert res[rid] == isolated_generate(params, p, 10)


def test_paged_waste_invariant_and_sampling(params):
    eng = LMEngine(params, H, MAXLEN, n_slots=2, chunk=4, kv_page_size=PS)
    rids = [eng.submit(p, max_new=3 + 4 * i)
            for i, p in enumerate(prompts_rng(3, seed=40))]
    # a sampled stream rides along: determinism contract is per-seed
    rs = eng.submit(prompts_rng(1, seed=41)[0], max_new=6,
                    temperature=0.9, top_k=11, seed=3)
    res = eng.run()
    for i, (rid, p) in enumerate(zip(rids, prompts_rng(3, seed=40))):
        assert res[rid] == isolated_generate(params, p, 3 + 4 * i)
    st = eng.stats
    assert eng.n_slots * st["decode_steps"] == \
        (st["tokens_out"] - st["prefills"]) + st["wasted_slot_steps"]
    # sampled stream: batch-composition-independent (same seed alone)
    solo = LMEngine(params, H, MAXLEN, n_slots=2, chunk=4, kv_page_size=PS)
    r = solo.submit(prompts_rng(1, seed=41)[0], max_new=6,
                    temperature=0.9, top_k=11, seed=3)
    assert solo.run()[r] == res[rs]


# -- engine: speculative decoding under paging ------------------------------ #


def _repetitive(n):
    base = [5, 9, 2, 7]
    return np.array((base * (n // 4 + 1))[:n], np.int32)


def test_spec_paged_identical_and_accepting(params):
    jobs = [(_repetitive(10), 20), (_repetitive(6), 12)]
    plain, _ = run_engine(params, jobs, n_slots=2, chunk=4)
    spec, eng = run_engine(params, jobs, n_slots=2, chunk=4,
                           spec_draft=4, kv_page_size=PS)
    assert spec == plain
    assert eng.stats["spec_iterations"] > 0
    assert eng.stats["spec_accepted"] > 0


def test_bounded_slot_view_gates_spec_and_stays_exact(params):
    # kv_slot_pages=4 -> per-request capacity 32 < max_len: the spec
    # gate must use the VIEW capacity, or the last tokens would write
    # past the gathered pages and NaN-poison the stream
    prompt = _repetitive(20)
    jobs = [(prompt, 13)]  # 20 + 13 - 1 == 32 fills the view exactly
    plain, _ = run_engine(params, jobs, n_slots=1, chunk=3)
    spec, eng = run_engine(params, jobs, n_slots=1, chunk=3, spec_draft=8,
                           kv_page_size=PS, kv_slot_pages=4)
    assert spec == plain
    assert not any(np.isnan(spec[0]))


def test_bounded_slot_view_rejects_oversize(params):
    eng = LMEngine(params, H, MAXLEN, kv_page_size=PS, kv_slot_pages=4)
    with pytest.raises(ValueError, match="paged per-request capacity"):
        eng.submit(np.arange(30, dtype=np.int32) % V, max_new=8)
    # within the view but beyond the whole pool: rejected up front so
    # admission can never deadlock waiting for pages that cannot exist
    eng2 = LMEngine(params, H, MAXLEN, kv_page_size=PS, kv_pages=2)
    with pytest.raises(ValueError, match="kv_pages=2"):
        eng2.submit(np.arange(20, dtype=np.int32) % V, max_new=8)


# -- config plumbing -------------------------------------------------------- #


def test_constructor_validation(params):
    with pytest.raises(ValueError, match="divide"):
        LMEngine(params, H, MAXLEN, kv_page_size=7)
    with pytest.raises(ValueError, match="kv_slot_pages"):
        LMEngine(params, H, MAXLEN, kv_page_size=PS, kv_slot_pages=9)
    with pytest.raises(ValueError, match="kv_page_size must be >= 0"):
        LMEngine(params, H, MAXLEN, kv_page_size=-1)
    with pytest.raises(ValueError, match="spec_draft"):
        LMEngine(params, H, MAXLEN, kv_page_size=PS, kv_slot_pages=1,
                 spec_draft=8)


def test_env_transport_and_explicit_override(params, monkeypatch):
    monkeypatch.setenv("NNS_LM_KV_PAGE_SIZE", str(PS))
    monkeypatch.setenv("NNS_LM_KV_PAGES", "12")
    eng = LMEngine(params, H, MAXLEN, n_slots=2)
    assert eng._kv is not None and eng._kv.n_pages == 12
    # explicit kv_page_size=0 pins contiguous regardless of environment
    eng0 = LMEngine(params, H, MAXLEN, n_slots=2, kv_page_size=0)
    assert eng0._kv is None
    monkeypatch.setenv("NNS_LM_KV_PAGE_SIZE", "junk")
    with pytest.raises(ValueError, match="NNS_LM_KV_PAGE_SIZE"):
        LMEngine(params, H, MAXLEN, n_slots=2)


def test_env_paged_engine_stays_exact(params, monkeypatch):
    monkeypatch.setenv("NNS_LM_KV_PAGE_SIZE", str(PS))
    prompt = prompts_rng(1, lo=10, hi=11, seed=42)[0]
    eng = LMEngine(params, H, MAXLEN, n_slots=2, chunk=4)
    assert eng._kv is not None
    rid = eng.submit(prompt, max_new=9)
    assert eng.run()[rid] == isolated_generate(params, prompt, 9)


def test_tp_engine_rejects_paging(params, monkeypatch):
    from jax.sharding import Mesh

    mesh = Mesh(np.array(jax.devices()[:1]), ("model",))
    with pytest.raises(ValueError, match="paged KV cache"):
        TPLMEngine(params, H, MAXLEN, mesh, kv_page_size=PS)
    # env paging must not leak into the TP engine either
    monkeypatch.setenv("NNS_LM_KV_PAGE_SIZE", str(PS))
    eng = TPLMEngine(params, H, MAXLEN, mesh)
    assert eng._kv is None


# -- stress (excluded from tier-1) ------------------------------------------ #


@pytest.mark.slow
def test_many_requests_through_small_pool_stress(params):
    # 24 mixed requests (some sharing a prefix) through a 4x
    # oversubscribed engine: every stream exact, pool never exceeded
    prefix = prompts_rng(1, lo=16, hi=17, seed=50)[0]
    rng = np.random.default_rng(51)
    jobs = []
    for i in range(24):
        if i % 3:
            p = np.concatenate(
                [prefix, rng.integers(0, V, rng.integers(2, 14))
                 .astype(np.int32)])
        else:
            p = rng.integers(0, V, rng.integers(8, 30)).astype(np.int32)
        jobs.append((p, 4 + i % 9))
    paged, eng = run_engine(params, jobs, n_slots=8, chunk=4,
                            kv_page_size=PS, kv_pages=32)
    for (p, mn), got in zip(jobs, paged):
        assert got == isolated_generate(params, p, mn)
    kv = eng.kv_stats
    assert kv["pages_peak"] <= 32
    assert kv["hit_requests"] > 0
