"""Full model lifecycle in one flow: in-pipeline training → checkpoint →
serialized export → pipeline-string deployment → remote offload.

The integration capstone mirroring a real user journey across
tensor_trainer, utils.checkpoints, models.deploy, the textual parser,
and the query layer — each subsystem has its own suite; this pins that
they compose.
"""

import socket
import time

import numpy as np
import pytest

import jax

from nnstreamer_tpu.core import Caps
from nnstreamer_tpu.core.types import TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline
from nnstreamer_tpu.models.zoo import ModelBundle


def caps_of(dims, types, rate=30):
    return Caps.tensors(TensorsConfig(
        TensorsInfo.from_strings(dims, types), rate))


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_train_checkpoint_export_deploy_offload(tmp_path):
    rng = np.random.default_rng(0)
    true_w = rng.normal(size=(8, 4)).astype(np.float32)
    w0 = jax.random.normal(jax.random.PRNGKey(0), (8, 4)) * 0.1
    bundle = ModelBundle(
        "linear", lambda p, x: x @ p, params=w0,
        in_info=TensorsInfo.from_strings("8:4", "float32"),
        out_info=TensorsInfo.from_strings("4:4", "float32"))

    # 1. train in-pipeline --------------------------------------------------- #
    data = []
    for _ in range(30):
        x = rng.normal(size=(4, 8)).astype(np.float32)
        data.append((x, np.argmax(x @ true_w, -1).astype(np.int32)))
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps_of("8:4,4", "float32,int32"),
                    data=data)
    tr = p.add_new("tensor_trainer", model=bundle, learning_rate=0.1,
                   checkpoint_path=str(tmp_path / "trained.msgpack"))
    sink = p.add_new("tensor_sink")
    Pipeline.link(src, tr, sink)
    p.run(timeout=120)
    losses = list(tr.losses)
    assert np.mean(losses[-5:]) < np.mean(losses[:5])
    assert (tmp_path / "trained.msgpack").exists()
    trained = tr.trained_bundle()

    # 2. export the TRAINED model to a serialized artifact ------------------- #
    from nnstreamer_tpu.models.deploy import export_model

    artifact = tmp_path / "linear.jaxexport"
    export_model(str(artifact), trained)
    assert artifact.stat().st_size > 0

    # 3. deploy via a pipeline STRING (no Python model source) --------------- #
    from nnstreamer_tpu.graph.parse import parse_pipeline

    probe = rng.normal(size=(4, 8)).astype(np.float32)
    want = np.asarray(probe @ np.asarray(trained.params))
    p2 = parse_pipeline(
        f'appsrc name=in ! tensor_filter framework=auto '
        f'model="{artifact}" ! tensor_sink name=out store=true')
    p2.get_by_name("in").set_properties(
        caps=caps_of("8:4", "float32"), data=[probe])
    p2.run(timeout=120)
    out = p2.get_by_name("out").buffers[0].memories[0].host()
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)
    # the artifact serves the TRAINED weights, not the init
    assert not np.allclose(out, probe @ np.asarray(w0))

    # 4. offload the artifact behind a query server -------------------------- #
    port = free_port()
    sp = Pipeline("server")
    ssrc = sp.add_new("tensor_query_serversrc", host="127.0.0.1",
                      port=port, id=3, dims="8:4", types="float32")
    filt = sp.add_new("tensor_filter", framework="auto",
                      model=str(artifact))
    ssink = sp.add_new("tensor_query_serversink", id=3, async_depth=8)
    Pipeline.link(ssrc, filt, ssink)
    sp.start()
    try:
        time.sleep(0.2)
        cp = Pipeline("client")
        csrc = cp.add_new("appsrc", caps=caps_of("8:4", "float32"),
                          data=[probe] * 3)
        qc = cp.add_new("tensor_query_client", host="127.0.0.1", port=port,
                        async_depth=8)
        csink = cp.add_new("tensor_sink", store=True)
        Pipeline.link(csrc, qc, csink)
        cp.run(timeout=120)
        assert csink.num_buffers == 3
        np.testing.assert_allclose(csink.buffers[-1].memories[0].host(),
                                   want, rtol=1e-4, atol=1e-5)
    finally:
        sp.stop()
