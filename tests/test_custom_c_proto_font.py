"""C custom-filter ABI, protobuf serialization, font decoder tests."""

import subprocess

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline


def caps_of(dims, types, rate=0):
    return Caps.tensors(TensorsConfig(TensorsInfo.from_strings(dims, types), rate))


class TestCCustomFilter:
    @pytest.fixture(scope="class")
    def scaler_so(self, tmp_path_factory):
        out = tmp_path_factory.mktemp("cfilter") / "libscaler_filter.so"
        try:
            subprocess.run(
                ["gcc", "-O2", "-shared", "-fPIC", "-I", "native",
                 "native/examples/scaler_filter.c", "-o", str(out)],
                check=True, capture_output=True, cwd="/root/repo")
        except (subprocess.SubprocessError, FileNotFoundError):
            pytest.skip("no C toolchain")
        return str(out)

    def test_so_filter_pipeline(self, scaler_so):
        p = Pipeline()
        src = p.add_new("appsrc", caps=caps_of("4:1", "float32"),
                        data=[np.full((1, 4), 3.0, np.float32)])
        f = p.add_new("tensor_filter", framework="custom", model=scaler_so)
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, f, sink)
        p.run(timeout=30)
        np.testing.assert_array_equal(sink.buffers[0].memories[0].host(),
                                      np.full((1, 4), 6.0, np.float32))

    def test_custom_prop(self, scaler_so):
        p = Pipeline()
        src = p.add_new("appsrc", caps=caps_of("4:1", "float32"),
                        data=[np.ones((1, 4), np.float32)])
        f = p.add_new("tensor_filter", framework="custom", model=scaler_so,
                      custom="factor=5")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, f, sink)
        p.run(timeout=30)
        np.testing.assert_array_equal(sink.buffers[0].memories[0].host(),
                                      np.full((1, 4), 5.0, np.float32))

    def test_auto_detect_so_extension(self, scaler_so):
        from nnstreamer_tpu.filters import detect_framework

        assert detect_framework(scaler_so) == "custom"

    def test_missing_so_fails(self):
        from nnstreamer_tpu.single import SingleShot

        with pytest.raises(FileNotFoundError):
            SingleShot(model="/nonexistent/lib.so", framework="custom")


class TestProtobuf:
    def test_roundtrip_functions(self):
        from nnstreamer_tpu.converters.protobuf_io import (frame_to_proto,
                                                           proto_to_frame)

        buf = Buffer.of(np.arange(6, dtype=np.int32).reshape(2, 3),
                        np.ones(4, np.float32), pts=77, offset=5)
        blob = frame_to_proto(buf)
        out = proto_to_frame(blob)
        assert out.pts == 77 and out.offset == 5
        np.testing.assert_array_equal(out.memories[0].host(),
                                      buf.memories[0].host())

    def test_decoder_converter_pipeline(self):
        """tensors → protobuf blob → back to tensors through elements."""
        p = Pipeline()
        arr = np.arange(8, dtype=np.float32)
        src = p.add_new("appsrc", caps=caps_of("8", "float32"), data=[arr])
        enc = p.add_new("tensor_decoder", mode="protobuf")
        dec = p.add_new("tensor_converter", mode="custom:protobuf")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, enc, dec, sink)
        p.run(timeout=30)
        np.testing.assert_array_equal(sink.buffers[0].memories[0].host(), arr)


class TestFont:
    def test_renders_label_text(self):
        p = Pipeline()
        text = np.frombuffer(b"orange", np.uint8).copy()
        src = p.add_new("appsrc", caps=caps_of("6", "uint8"), data=[text])
        dec = p.add_new("tensor_decoder", mode="font", option1="64:16")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, dec, sink)
        p.run(timeout=30)
        b = sink.buffers[0]
        assert b.meta["text"] == "orange"
        canvas = b.memories[0].host()
        assert canvas.shape == (16, 64, 4)
        assert canvas[..., 3].max() == 255  # glyph pixels drawn
