"""Mesh/sharding tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.parallel import (
    auto_mesh_2d,
    batch_sharding,
    make_mesh,
    make_sharded_infer_step,
    make_sharded_train_step,
    shard_params,
)


def test_device_count():
    assert len(jax.devices()) == 8  # conftest forces 8 virtual CPU devices


def test_make_mesh_validates():
    with pytest.raises(ValueError, match="devices"):
        make_mesh({"data": 3})


def test_auto_mesh_2d():
    mesh = auto_mesh_2d(8)
    assert mesh.shape == {"data": 4, "model": 2}
    mesh4 = auto_mesh_2d(8, model_parallel=4)
    assert mesh4.shape == {"data": 2, "model": 4}


def test_shard_params_layout():
    mesh = auto_mesh_2d(8, model_parallel=2)
    params = {"dense": {"kernel": np.ones((16, 8), np.float32),
                        "bias": np.ones((8,), np.float32)},
              "odd": {"kernel": np.ones((5, 3), np.float32)}}
    sharded = shard_params(params, mesh)
    k = sharded["dense"]["kernel"]
    assert k.sharding.spec == jax.sharding.PartitionSpec(None, "model")
    assert sharded["odd"]["kernel"].sharding.spec == jax.sharding.PartitionSpec()


def test_sharded_infer_step():
    mesh = auto_mesh_2d(8, model_parallel=2)
    w = np.random.default_rng(0).normal(size=(16, 8)).astype(np.float32)
    fn, params = make_sharded_infer_step(lambda p, x: x @ p, w, mesh)
    x = np.random.default_rng(1).normal(size=(8, 16)).astype(np.float32)
    xs = jax.device_put(x, batch_sharding(mesh))
    out = fn(params, xs)
    np.testing.assert_allclose(np.asarray(out), x @ w, rtol=1e-5)


def test_sharded_train_step_converges():
    mesh = auto_mesh_2d(8, model_parallel=2)
    rng = np.random.default_rng(0)
    w = rng.normal(size=(8, 4)).astype(np.float32) * 0.1

    def apply_fn(p, x):
        return x @ p

    step, params, opt_state = make_sharded_train_step(apply_fn, w, mesh)
    x = rng.normal(size=(16, 8)).astype(np.float32)
    y = rng.integers(0, 4, (16,)).astype(np.int32)
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0]  # gradient flows through sharded params
    assert params.sharding.spec == jax.sharding.PartitionSpec(None, "model")


class TestShardedCheckpoint:
    """Save/restore/resume a sharded train state (parallel/checkpoint.py).

    Equivalence contract: train N steps straight through == train k
    steps, checkpoint, restore (same or RE-SHAPED mesh), train N-k more.
    """

    def _setup(self, mesh, seed=0):
        rng = np.random.default_rng(seed)
        w = {"w1": rng.normal(size=(8, 16)).astype(np.float32) * 0.1,
             "w2": rng.normal(size=(16, 4)).astype(np.float32) * 0.1}

        def apply_fn(p, x):
            return jnp.tanh(x @ p["w1"]) @ p["w2"]

        step, params, opt_state = make_sharded_train_step(
            apply_fn, w, mesh)
        x = rng.normal(size=(16, 8)).astype(np.float32)
        y = rng.integers(0, 4, (16,)).astype(np.int32)
        return apply_fn, step, params, opt_state, x, y, w

    def _run(self, step, params, opt_state, x, y, n):
        for _ in range(n):
            params, opt_state, loss = step(params, opt_state, x, y)
        return params, opt_state, float(loss)

    def test_resume_equals_straight_through(self, tmp_path):
        from nnstreamer_tpu.parallel import (
            restore_sharded_state, save_sharded_state)

        mesh = auto_mesh_2d(8, model_parallel=2)
        _, step, params, opt_state, x, y, w = self._setup(mesh)
        p_ref, _, loss_ref = self._run(step, params, opt_state, x, y, 4)

        _, step2, params2, opt_state2, x, y, _ = self._setup(mesh)
        params2, opt_state2, _ = self._run(step2, params2, opt_state2,
                                           x, y, 2)
        path = str(tmp_path / "ckpt")
        save_sharded_state(path, params2, opt_state2)
        # fresh state objects, restored direct-to-sharded
        pr, osr = restore_sharded_state(
            path, params2, mesh=mesh, opt_state_like=opt_state2)
        for leaf, ref in zip(jax.tree_util.tree_leaves(pr),
                             jax.tree_util.tree_leaves(params2)):
            assert leaf.sharding == ref.sharding
        p_res, _, loss_res = self._run(step2, pr, osr, x, y, 2)
        assert np.isclose(loss_res, loss_ref, rtol=1e-5)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            p_res, p_ref)

    def test_restore_onto_reshaped_mesh(self, tmp_path):
        # elastic resume: checkpoint under data4 x model2, restore under
        # data2 x model4 — placement follows the NEW mesh, math unchanged
        from nnstreamer_tpu.parallel import (
            restore_sharded_state, save_sharded_state)

        mesh_a = auto_mesh_2d(8, model_parallel=2)
        apply_fn, step_a, params, opt_state, x, y, w = self._setup(mesh_a)
        params, opt_state, _ = self._run(step_a, params, opt_state, x, y, 2)
        path = str(tmp_path / "ckpt")
        save_sharded_state(path, params, opt_state)
        p_ref, _, loss_ref = self._run(step_a, params, opt_state, x, y, 2)

        mesh_b = auto_mesh_2d(8, model_parallel=4)
        step_b, pb_init, ob_init = make_sharded_train_step(
            apply_fn, w, mesh_b)
        pb, ob = restore_sharded_state(
            path, pb_init, mesh=mesh_b, opt_state_like=ob_init)
        assert all(
            leaf.sharding.mesh.shape == mesh_b.shape
            for leaf in jax.tree_util.tree_leaves(pb))
        p_res, _, loss_res = self._run(step_b, pb, ob, x, y, 2)
        assert np.isclose(loss_res, loss_ref, rtol=1e-4)
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
            p_res, p_ref)

    def test_params_only_and_host_restore(self, tmp_path):
        from nnstreamer_tpu.parallel import (
            restore_sharded_state, save_sharded_state)

        mesh = auto_mesh_2d(8, model_parallel=2)
        _, _, params, _, _, _, _ = self._setup(mesh)
        path = str(tmp_path / "ckpt")
        save_sharded_state(path, params)  # params only
        pr, osr = restore_sharded_state(path, params)  # host restore
        assert osr is None
        # documented host restore: plain numpy leaves, no device pins
        assert all(isinstance(leaf, np.ndarray)
                   for leaf in jax.tree_util.tree_leaves(pr))
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), pr, params)

    def test_partial_restores_both_directions(self, tmp_path):
        from nnstreamer_tpu.parallel import (
            restore_sharded_state, save_sharded_state)

        mesh = auto_mesh_2d(8, model_parallel=2)
        _, _, params, opt_state, _, _, _ = self._setup(mesh)
        # full checkpoint, params-only restore: stored opt discarded
        full = str(tmp_path / "full")
        save_sharded_state(full, params, opt_state)
        pr, osr = restore_sharded_state(full, params, mesh=mesh)
        assert osr is None
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), pr, params)
        # params-only checkpoint, opt template offered: returns None
        ponly = str(tmp_path / "ponly")
        save_sharded_state(ponly, params)
        pr2, osr2 = restore_sharded_state(
            ponly, params, mesh=mesh, opt_state_like=opt_state)
        assert osr2 is None
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)), pr2, params)


class TestSequenceParallel:
    def _qkv(self, b=2, h=4, L=64, d=16, seed=0):
        import numpy as np

        rng = np.random.default_rng(seed)
        mk = lambda: rng.normal(size=(b, h, L, d)).astype(np.float32) * 0.3
        return mk(), mk(), mk()

    def test_ring_attention_exact(self):
        from nnstreamer_tpu.parallel.ring import reference_attention, ring_attention

        mesh = make_mesh({"sp": 8})
        q, k, v = self._qkv()
        out = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             mesh, "sp")
        ref = reference_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_ring_attention_causal(self):
        from nnstreamer_tpu.parallel.ring import reference_attention, ring_attention

        mesh = make_mesh({"sp": 8})
        q, k, v = self._qkv(seed=1)
        out = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             mesh, "sp", causal=True)
        ref = reference_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_a2a_attention_exact(self):
        from nnstreamer_tpu.parallel.ring import a2a_attention, reference_attention

        mesh = make_mesh({"sp": 8})
        q, k, v = self._qkv(h=8, seed=2)
        out = a2a_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                            mesh, "sp")
        ref = reference_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_a2a_rejects_bad_heads(self):
        from nnstreamer_tpu.parallel.ring import a2a_attention

        mesh = make_mesh({"sp": 8})
        q, k, v = self._qkv(h=4)
        with pytest.raises(ValueError, match="divisible"):
            a2a_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          mesh, "sp")

    @pytest.mark.parametrize("causal", [False, True])
    def test_ring_flash_attention_exact(self, causal):
        """Ring over devices × pallas flash within a device (the
        long-context composition): exact vs the dense oracle, partials
        merged by softmax residuals."""
        from nnstreamer_tpu.parallel.ring import (
            reference_attention,
            ring_flash_attention,
        )

        mesh = make_mesh({"sp": 8})
        q, k, v = self._qkv(seed=3)
        out = ring_flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh, "sp",
            causal=causal, block_q=8, block_k=8)
        ref = reference_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_ring_flash_via_dispatch(self):
        """sp_attention_fn('ring-flash') routes to the composed kernel."""
        from nnstreamer_tpu.parallel.ring import (
            reference_attention,
            sp_attention_fn,
        )

        mesh = make_mesh({"sp": 8})
        q, k, v = self._qkv(seed=4)
        fn = sp_attention_fn("ring-flash", mesh, "sp", causal=True)
        out = fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        ref = reference_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_ring_under_jit(self):
        import jax
        from nnstreamer_tpu.parallel.ring import ring_attention

        mesh = make_mesh({"sp": 8})
        q, k, v = self._qkv(L=32)

        @jax.jit
        def f(q, k, v):
            return ring_attention(q, k, v, mesh, "sp")

        out = f(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        assert out.shape == q.shape


def test_query_offload_to_mesh_sharded_server():
    """SURVEY §7 step 7: the query server pipeline serves with a
    MESH-SHARDED model — remote clients offload frames; the server invoke
    fans each batch over the dp axis of an 8-device mesh (the pod-slice
    offload path, TPU-native replacement for per-buffer TCP offload
    alone)."""
    import time

    import jax
    import numpy as np

    from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
    from nnstreamer_tpu.graph import Pipeline
    from nnstreamer_tpu.models.zoo import get_model
    from nnstreamer_tpu.parallel import auto_mesh_2d

    from nnstreamer_tpu.parallel import sharded_bundle

    base = get_model("zoo://mobilenet_v2?width=0.25&size=16&num_classes=8"
                     "&batch=8&dtype=float32")
    mesh = auto_mesh_2d(8)
    served = sharded_bundle(base, mesh)

    sp = Pipeline("server")
    ssrc = sp.add_new("tensor_query_serversrc", port=0, id=0,
                      dims="3:16:16:8", types="uint8")
    filt = sp.add_new("tensor_filter", model=served)
    ssink = sp.add_new("tensor_query_serversink", id=0)
    Pipeline.link(ssrc, filt, ssink)
    sp.start()
    try:
        from nnstreamer_tpu.query.server import wait_bound_port

        port = wait_bound_port(ssrc)

        cp = Pipeline("client")
        batches = [np.random.default_rng(i).integers(
            0, 255, (8, 16, 16, 3)).astype(np.uint8) for i in range(3)]
        src = cp.add_new("appsrc",
                         caps=Caps.tensors(TensorsConfig(
                             TensorsInfo.from_strings("3:16:16:8",
                                                      "uint8"), 0)),
                         data=batches)
        qc = cp.add_new("tensor_query_client", port=port)
        sink = cp.add_new("tensor_sink", store=True)
        Pipeline.link(src, qc, sink)
        cp.run(timeout=120)
        assert sink.num_buffers == 3
        # results must equal the unsharded model's outputs
        ref_fn = jax.jit(base.fn())
        for buf, x in zip(sink.buffers, batches):
            np.testing.assert_allclose(
                buf.memories[0].host(), np.asarray(ref_fn(x)),
                rtol=2e-4, atol=2e-5)
    finally:
        sp.stop()


def test_sharded_bundle_honors_fused_preprocess_and_bf16():
    """jit:False bundles must still apply a fused preprocess stage and the
    precision cast (silently dropping a transform chain's math would give
    wrong results with no error)."""
    import jax.numpy as jnp
    import numpy as np

    from nnstreamer_tpu.core.buffer import TensorMemory
    from nnstreamer_tpu.filters.base import FilterProps
    from nnstreamer_tpu.filters.xla import XLAFilter
    from nnstreamer_tpu.models.zoo import ModelBundle

    served = ModelBundle("pre_sum", lambda x: x.sum(axis=-1),
                         metadata={"jit": False})
    f = XLAFilter()
    f.open(FilterProps(model=served, custom="precision=bf16"))
    f.set_fused_preprocess(lambda x: x * 2.0 + 1.0)
    x = np.ones((2, 4), np.float32)
    out = f.invoke([TensorMemory(x)])[0].host()
    np.testing.assert_allclose(out, np.full((2,), 12.0), rtol=1e-2)


class TestPipelineParallel:
    """GPipe staged execution (parallel/stages.py): exactness vs the
    sequential single-device oracle on the 8-device CPU mesh."""

    def _stages(self, n_stages, d=8, seed=0):
        from nnstreamer_tpu.parallel import stack_stage_params

        rng = np.random.default_rng(seed)
        per_stage = [
            {"w": jnp.asarray(rng.normal(size=(d, d)).astype(np.float32)
                              / np.sqrt(d)),
             "b": jnp.asarray(rng.normal(size=(d,)).astype(np.float32))}
            for _ in range(n_stages)]
        return stack_stage_params(per_stage)

    @staticmethod
    def _stage_fn(params, h):
        return jnp.tanh(h @ params["w"] + params["b"])

    @pytest.mark.parametrize("n_micro", [None, 8, 16])
    def test_gpipe_exact(self, n_micro):
        from nnstreamer_tpu.parallel import (
            make_gpipe_apply, make_mesh, sequential_apply,
            shard_stage_params)

        mesh = make_mesh({"stage": 8})
        stacked = self._stages(8)
        x = jnp.asarray(np.random.default_rng(1).normal(
            size=(16, 8)).astype(np.float32))
        want = np.asarray(sequential_apply(self._stage_fn, stacked, x))
        pp = make_gpipe_apply(self._stage_fn, mesh, n_microbatches=n_micro)
        got = np.asarray(jax.jit(pp)(shard_stage_params(stacked, mesh), x))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)

    def test_gpipe_2x4_mixed_mesh(self):
        """pp composes with dp on a 2D mesh (stage axis only is pipelined)."""
        from nnstreamer_tpu.parallel import (
            make_gpipe_apply, make_mesh, sequential_apply,
            shard_stage_params)

        mesh = make_mesh({"stage": 4, "data": 2})
        stacked = self._stages(4)
        x = jnp.asarray(np.random.default_rng(2).normal(
            size=(8, 8)).astype(np.float32))
        want = np.asarray(sequential_apply(self._stage_fn, stacked, x))
        pp = make_gpipe_apply(self._stage_fn, mesh)
        got = np.asarray(pp(shard_stage_params(stacked, mesh), x))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)

    def test_gpipe_rejects_indivisible_batch(self):
        from nnstreamer_tpu.parallel import make_gpipe_apply, make_mesh

        mesh = make_mesh({"stage": 8})
        pp = make_gpipe_apply(self._stage_fn, mesh, n_microbatches=8)
        with pytest.raises(ValueError, match="microbatch"):
            pp(self._stages(8), jnp.zeros((12, 8)))


class TestExpertParallel:
    def _setup(self, b=2, s=16, d=8, h=16, e=4, seed=0):
        from nnstreamer_tpu.parallel import init_moe_params

        params = init_moe_params(jax.random.PRNGKey(seed), d, h, e)
        x = jnp.asarray(np.random.default_rng(seed).normal(
            size=(b, s, d)).astype(np.float32))
        return params, x

    def test_moe_sharded_equals_single_device(self):
        from nnstreamer_tpu.parallel import (
            make_expert_parallel_moe, make_mesh, moe_apply)

        params, x = self._setup()
        want, aux_want = moe_apply(params, x)
        mesh = make_mesh({"data": 2, "expert": 4})
        jitted, placed = make_expert_parallel_moe(params, mesh)
        got, aux = jitted(placed, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(np.asarray(aux["expert_counts"]),
                                   np.asarray(aux_want["expert_counts"]))

    def test_moe_routing_properties(self):
        from nnstreamer_tpu.parallel import moe_apply

        params, x = self._setup(b=4, s=32)
        out, aux = moe_apply(params, x, capacity_factor=1.25)
        n = 4 * 32
        counts = np.asarray(aux["expert_counts"])
        assert counts.sum() == n  # every token routed somewhere
        assert 0 <= float(aux["dropped"]) < n  # capacity drops bounded
        assert out.shape == x.shape
        assert float(aux["load_balance_loss"]) >= 1.0 - 1e-3  # min at uniform

    def test_moe_capacity_drops_tokens(self):
        """capacity_factor < 1 forces drops; dropped tokens contribute 0."""
        from nnstreamer_tpu.parallel import moe_apply

        params, x = self._setup(b=2, s=32)
        _, aux_tight = moe_apply(params, x, capacity_factor=0.25)
        _, aux_loose = moe_apply(params, x, capacity_factor=4.0)
        assert float(aux_tight["dropped"]) > 0
        assert float(aux_loose["dropped"]) == 0

    def test_moe_bf16_routing_exact(self):
        """Routing bookkeeping must not round in bf16: with >256 tokens on
        one expert, slot positions would collide and corrupt outputs. The
        oracle reuses the SAME bf16 routing decisions but does the
        capacity bookkeeping in exact numpy arithmetic."""
        from nnstreamer_tpu.parallel import init_moe_params, moe_apply

        d, e, cf = 8, 4, 2.0
        params = init_moe_params(jax.random.PRNGKey(0), d, 16, e,
                                 dtype=jnp.bfloat16)
        x = jnp.asarray(np.random.default_rng(0).normal(
            size=(4, 512, d)), dtype=jnp.bfloat16)  # ~512 tokens/expert
        out, aux = moe_apply(params, x, capacity_factor=cf)
        n = 4 * 512
        assert np.asarray(aux["expert_counts"]).sum() == n

        # identical routing decisions (same jax ops), exact bookkeeping
        xf = np.asarray(x, np.float64).reshape(n, d)
        logits = jnp.asarray(x.reshape(n, d)) @ params["router"]
        gates = np.asarray(jax.nn.softmax(
            logits.astype(jnp.float32), axis=-1), np.float64)
        expert = np.argmax(gates, -1)
        gate = np.max(gates, -1)
        cap = int(np.ceil(n / e * cf))
        slots = np.zeros(e, np.int64)
        want = np.zeros_like(xf)
        w1 = np.asarray(params["w1"], np.float64)
        w2 = np.asarray(params["w2"], np.float64)
        for i in range(n):
            ee = expert[i]
            if slots[ee] < cap:
                slots[ee] += 1
                h = xf[i] @ w1[ee]
                h = 0.5 * h * (1 + np.vectorize(__import__("math").erf)(
                    h / np.sqrt(2)))
                want[i] = gate[i] * (h @ w2[ee])
        got = np.asarray(out, np.float32).reshape(n, d)
        # bf16 einsum tolerance; collisions would blow past this wholesale
        np.testing.assert_allclose(got, want, rtol=0.2, atol=0.2)



def test_gpipe_rejects_stage_count_mismatch():
    """8 stacked stages on a 4-device axis must error, not silently run
    every other stage."""
    from nnstreamer_tpu.parallel import (
        make_gpipe_apply, make_mesh, stack_stage_params)

    mesh = make_mesh({"stage": 4, "data": 2})
    stacked = stack_stage_params(
        [{"w": jnp.eye(4)} for _ in range(8)])
    pp = make_gpipe_apply(lambda p, h: h @ p["w"], mesh)
    with pytest.raises(ValueError, match="stages"):
        pp(stacked, jnp.zeros((8, 4)))


def test_composite_sharded_pipeline_with_query_offload():
    """The composite topology at mesh scale (VERDICT r3 #7): a
    sharded_bundle filter served INSIDE a full Pipeline behind the query
    offload layer, concurrently with the pipeline scheduler — results
    exact vs the unsharded oracle (shared helper, same code the driver's
    dryrun_multichip runs)."""
    from nnstreamer_tpu.models.zoo import get_model
    from nnstreamer_tpu.parallel import sharded_bundle
    from nnstreamer_tpu.parallel.composite import (
        composite_sharded_query_check,
    )

    mesh = auto_mesh_2d(8)
    batch, size = 8, 16
    bundle = get_model(f"zoo://mobilenet_v2?width=0.25&size={size}"
                       f"&num_classes=8&batch={batch}&dtype=float32")
    served = sharded_bundle(bundle, mesh)
    composite_sharded_query_check(bundle, served, batch, size)


def test_sharded_uneven_final_batch():
    """batch % dp != 0 zero-pads to the next data-axis multiple inside the
    serving filter and trims outputs (the last batch of a stream is rarely
    full on real hardware)."""
    import jax

    from nnstreamer_tpu.core.buffer import TensorMemory
    from nnstreamer_tpu.filters.base import FilterProps
    from nnstreamer_tpu.filters.xla import XLAFilter
    from nnstreamer_tpu.models.zoo import get_model
    from nnstreamer_tpu.parallel import sharded_bundle

    mesh = auto_mesh_2d(8)  # data=4
    batch, size = 8, 16
    bundle = get_model(f"zoo://mobilenet_v2?width=0.25&size={size}"
                       f"&num_classes=8&batch={batch}&dtype=float32")
    filt = XLAFilter()
    filt.open(FilterProps(model=sharded_bundle(bundle, mesh)))
    rng = np.random.default_rng(0)
    oracle = jax.jit(bundle.fn())
    for uneven in (batch + 1, batch - 3, 1):
        x = rng.normal(size=(uneven, size, size, 3)).astype(np.float32)
        got = filt.invoke([TensorMemory(x)])[0].host()
        ref = np.asarray(oracle(x))
        assert got.shape == ref.shape
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_sharded_reload_reshards():
    """Hot model reload swaps the sharded program for one with fresh
    params (mesh reshard under traffic); results follow the new oracle."""
    import jax

    from nnstreamer_tpu.core.buffer import TensorMemory
    from nnstreamer_tpu.filters.base import FilterProps
    from nnstreamer_tpu.filters.xla import XLAFilter
    from nnstreamer_tpu.models.zoo import get_model
    from nnstreamer_tpu.parallel import sharded_bundle

    mesh = auto_mesh_2d(8)
    batch, size = 8, 16
    spec = (f"zoo://mobilenet_v2?width=0.25&size={size}"
            f"&num_classes=8&batch={batch}&dtype=float32")
    b1 = get_model(spec)
    b2 = get_model(spec + "&seed=7")
    filt = XLAFilter()
    filt.open(FilterProps(model=sharded_bundle(b1, mesh)))
    rng = np.random.default_rng(1)
    x = rng.normal(size=(batch, size, size, 3)).astype(np.float32)
    got1 = filt.invoke([TensorMemory(x)])[0].host()
    np.testing.assert_allclose(
        got1, np.asarray(jax.jit(b1.fn())(x)), rtol=2e-4, atol=2e-5)
    filt.reload_model(sharded_bundle(b2, mesh))
    got2 = filt.invoke([TensorMemory(x)])[0].host()
    np.testing.assert_allclose(
        got2, np.asarray(jax.jit(b2.fn())(x)), rtol=2e-4, atol=2e-5)
    assert not np.allclose(got1, got2)  # genuinely different params


def test_composite_query_failover_retry():
    """Server pod dies mid-stream, replacement binds the same port, the
    client's retry path completes the stream exactly (shared helper, same
    code the driver's dryrun_multichip runs)."""
    from nnstreamer_tpu.models.zoo import get_model
    from nnstreamer_tpu.parallel import sharded_bundle
    from nnstreamer_tpu.parallel.composite import (
        composite_query_retry_check,
    )

    mesh = auto_mesh_2d(8)
    batch, size = 8, 16
    bundle = get_model(f"zoo://mobilenet_v2?width=0.25&size={size}"
                       f"&num_classes=8&batch={batch}&dtype=float32")
    served = sharded_bundle(bundle, mesh)
    composite_query_retry_check(bundle, served, batch, size)


def test_a2a_flash_attention_exact():
    """Ulysses × flash: per-head-subset attention through the pallas
    kernel after the all_to_all re-shard — exact vs the dense oracle."""
    from nnstreamer_tpu.parallel.ring import (
        a2a_attention,
        reference_attention,
    )

    mesh = make_mesh({"sp": 8})
    rng = np.random.default_rng(6)
    q, k, v = [rng.standard_normal((1, 8, 64, 16)).astype(np.float32)
               for _ in range(3)]
    out = a2a_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                        mesh, "sp", flash=True)
    ref = reference_attention(jnp.asarray(q), jnp.asarray(k),
                              jnp.asarray(v))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)
