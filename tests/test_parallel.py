"""Mesh/sharding tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nnstreamer_tpu.parallel import (
    auto_mesh_2d,
    batch_sharding,
    make_mesh,
    make_sharded_infer_step,
    make_sharded_train_step,
    shard_params,
)


def test_device_count():
    assert len(jax.devices()) == 8  # conftest forces 8 virtual CPU devices


def test_make_mesh_validates():
    with pytest.raises(ValueError, match="devices"):
        make_mesh({"data": 3})


def test_auto_mesh_2d():
    mesh = auto_mesh_2d(8)
    assert mesh.shape == {"data": 4, "model": 2}
    mesh4 = auto_mesh_2d(8, model_parallel=4)
    assert mesh4.shape == {"data": 2, "model": 4}


def test_shard_params_layout():
    mesh = auto_mesh_2d(8, model_parallel=2)
    params = {"dense": {"kernel": np.ones((16, 8), np.float32),
                        "bias": np.ones((8,), np.float32)},
              "odd": {"kernel": np.ones((5, 3), np.float32)}}
    sharded = shard_params(params, mesh)
    k = sharded["dense"]["kernel"]
    assert k.sharding.spec == jax.sharding.PartitionSpec(None, "model")
    assert sharded["odd"]["kernel"].sharding.spec == jax.sharding.PartitionSpec()


def test_sharded_infer_step():
    mesh = auto_mesh_2d(8, model_parallel=2)
    w = np.random.default_rng(0).normal(size=(16, 8)).astype(np.float32)
    fn, params = make_sharded_infer_step(lambda p, x: x @ p, w, mesh)
    x = np.random.default_rng(1).normal(size=(8, 16)).astype(np.float32)
    xs = jax.device_put(x, batch_sharding(mesh))
    out = fn(params, xs)
    np.testing.assert_allclose(np.asarray(out), x @ w, rtol=1e-5)


def test_sharded_train_step_converges():
    mesh = auto_mesh_2d(8, model_parallel=2)
    rng = np.random.default_rng(0)
    w = rng.normal(size=(8, 4)).astype(np.float32) * 0.1

    def apply_fn(p, x):
        return x @ p

    step, params, opt_state = make_sharded_train_step(apply_fn, w, mesh)
    x = rng.normal(size=(16, 8)).astype(np.float32)
    y = rng.integers(0, 4, (16,)).astype(np.int32)
    losses = []
    for _ in range(5):
        params, opt_state, loss = step(params, opt_state, x, y)
        losses.append(float(loss))
    assert losses[-1] < losses[0]  # gradient flows through sharded params
    assert params.sharding.spec == jax.sharding.PartitionSpec(None, "model")


class TestSequenceParallel:
    def _qkv(self, b=2, h=4, L=64, d=16, seed=0):
        import numpy as np

        rng = np.random.default_rng(seed)
        mk = lambda: rng.normal(size=(b, h, L, d)).astype(np.float32) * 0.3
        return mk(), mk(), mk()

    def test_ring_attention_exact(self):
        from nnstreamer_tpu.parallel.ring import reference_attention, ring_attention

        mesh = make_mesh({"sp": 8})
        q, k, v = self._qkv()
        out = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             mesh, "sp")
        ref = reference_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_ring_attention_causal(self):
        from nnstreamer_tpu.parallel.ring import reference_attention, ring_attention

        mesh = make_mesh({"sp": 8})
        q, k, v = self._qkv(seed=1)
        out = ring_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                             mesh, "sp", causal=True)
        ref = reference_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_a2a_attention_exact(self):
        from nnstreamer_tpu.parallel.ring import a2a_attention, reference_attention

        mesh = make_mesh({"sp": 8})
        q, k, v = self._qkv(h=8, seed=2)
        out = a2a_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                            mesh, "sp")
        ref = reference_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    def test_a2a_rejects_bad_heads(self):
        from nnstreamer_tpu.parallel.ring import a2a_attention

        mesh = make_mesh({"sp": 8})
        q, k, v = self._qkv(h=4)
        with pytest.raises(ValueError, match="divisible"):
            a2a_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          mesh, "sp")

    def test_ring_under_jit(self):
        import jax
        from nnstreamer_tpu.parallel.ring import ring_attention

        mesh = make_mesh({"sp": 8})
        q, k, v = self._qkv(L=32)

        @jax.jit
        def f(q, k, v):
            return ring_attention(q, k, v, mesh, "sp")

        out = f(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        assert out.shape == q.shape


def test_query_offload_to_mesh_sharded_server():
    """SURVEY §7 step 7: the query server pipeline serves with a
    MESH-SHARDED model — remote clients offload frames; the server invoke
    fans each batch over the dp axis of an 8-device mesh (the pod-slice
    offload path, TPU-native replacement for per-buffer TCP offload
    alone)."""
    import time

    import jax
    import numpy as np

    from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
    from nnstreamer_tpu.graph import Pipeline
    from nnstreamer_tpu.models.zoo import get_model
    from nnstreamer_tpu.parallel import auto_mesh_2d

    from nnstreamer_tpu.parallel import sharded_bundle

    base = get_model("zoo://mobilenet_v2?width=0.25&size=16&num_classes=8"
                     "&batch=8&dtype=float32")
    mesh = auto_mesh_2d(8)
    served = sharded_bundle(base, mesh)

    sp = Pipeline("server")
    ssrc = sp.add_new("tensor_query_serversrc", port=0, id=0,
                      dims="3:16:16:8", types="uint8")
    filt = sp.add_new("tensor_filter", model=served)
    ssink = sp.add_new("tensor_query_serversink", id=0)
    Pipeline.link(ssrc, filt, ssink)
    sp.start()
    try:
        deadline = time.monotonic() + 10
        while not hasattr(ssrc, "bound_port") and time.monotonic() < deadline:
            time.sleep(0.05)
        assert hasattr(ssrc, "bound_port"), "server did not bind within 10s"
        port = ssrc.bound_port

        cp = Pipeline("client")
        batches = [np.random.default_rng(i).integers(
            0, 255, (8, 16, 16, 3)).astype(np.uint8) for i in range(3)]
        src = cp.add_new("appsrc",
                         caps=Caps.tensors(TensorsConfig(
                             TensorsInfo.from_strings("3:16:16:8",
                                                      "uint8"), 0)),
                         data=batches)
        qc = cp.add_new("tensor_query_client", port=port)
        sink = cp.add_new("tensor_sink", store=True)
        Pipeline.link(src, qc, sink)
        cp.run(timeout=120)
        assert sink.num_buffers == 3
        # results must equal the unsharded model's outputs
        ref_fn = jax.jit(base.fn())
        for buf, x in zip(sink.buffers, batches):
            np.testing.assert_allclose(
                buf.memories[0].host(), np.asarray(ref_fn(x)),
                rtol=2e-4, atol=2e-5)
    finally:
        sp.stop()


def test_sharded_bundle_honors_fused_preprocess_and_bf16():
    """jit:False bundles must still apply a fused preprocess stage and the
    precision cast (silently dropping a transform chain's math would give
    wrong results with no error)."""
    import jax.numpy as jnp
    import numpy as np

    from nnstreamer_tpu.core.buffer import TensorMemory
    from nnstreamer_tpu.filters.base import FilterProps
    from nnstreamer_tpu.filters.xla import XLAFilter
    from nnstreamer_tpu.models.zoo import ModelBundle

    served = ModelBundle("pre_sum", lambda x: x.sum(axis=-1),
                         metadata={"jit": False})
    f = XLAFilter()
    f.open(FilterProps(model=served, custom="precision=bf16"))
    f.set_fused_preprocess(lambda x: x * 2.0 + 1.0)
    x = np.ones((2, 4), np.float32)
    out = f.invoke([TensorMemory(x)])[0].host()
    np.testing.assert_allclose(out, np.full((2,), 12.0), rtol=1e-2)
