"""nnslint engine + rule-family tests (scripts/nnslint/).

Each rule family is exercised against a seeded fixture snippet that
must fire and a clean twin that must stay silent — the "demonstrably
catches a seeded regression" acceptance bar — plus engine-level tests
for inline suppressions, the baseline round trip, and the CLI contract
the tier-1 gate (test_repo_lints_clean) scripts against.
"""

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parents[1]
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from scripts.nnslint import baseline as nnsl_baseline  # noqa: E402
from scripts.nnslint.core import Finding, run_lint  # noqa: E402

pytestmark = pytest.mark.lint


def lint_snippet(tmp_path, code, select, name="snippet.py"):
    """Write ``code`` into an isolated tree and run the selected rules."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(code))
    res = run_lint([p], select=list(select))
    return res


def rules_fired(res):
    return sorted({f.rule for f in res.findings})


# --------------------------------------------------------------------------- #
# concurrency family
# --------------------------------------------------------------------------- #

class TestConcurrencyRules:
    def test_guarded_by_mutation_outside_lock_fires(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []  # guarded-by: _lock

                def bad(self, x):
                    self._items.append(x)
            """, ["concurrency/guarded-by"])
        assert len(res.findings) == 1
        f = res.findings[0]
        assert f.rule == "concurrency/guarded-by"
        assert "Box._items" in f.anchor
        assert "with self._lock" in f.message

    def test_guarded_by_clean_twin_silent(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []  # guarded-by: _lock
                    self._items.append(0)   # declaring method: exempt

                def good(self, x):
                    with self._lock:
                        self._items.append(x)
                        self._items = [x]
                        del self._items[0]
            """, ["concurrency/guarded-by"])
        assert res.findings == []

    def test_guarded_by_caller_holds_lock_helpers_exempt(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import threading

            class Breaker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._state = "closed"  # guarded-by: _lock

                def trip(self):
                    with self._lock:
                        self._to_open()
                        self._reset_locked()

                def _to_open(self):  # guarded-by: _lock
                    self._state = "open"

                def _reset_locked(self):
                    self._state = "closed"
            """, ["concurrency/guarded-by"])
        assert res.findings == []

    def test_guarded_by_subscript_and_augassign_fire(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import threading

            class Reg:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._map = {}  # guarded-by: _lock
                    self._n = 0  # guarded-by: _lock

                def bad(self, k, v):
                    self._map[k] = v
                    self._n += 1
            """, ["concurrency/guarded-by"])
        assert len(res.findings) == 2

    def test_thread_daemon_missing_fires(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import threading

            def spawn(fn):
                t = threading.Thread(target=fn)
                t.start()
                return t
            """, ["concurrency/thread-daemon"])
        assert rules_fired(res) == ["concurrency/thread-daemon"]

    def test_thread_daemon_explicit_silent(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import threading

            def spawn(fn):
                t = threading.Thread(target=fn, daemon=True)
                t.start()
                return t
            """, ["concurrency/thread-daemon"])
        assert res.findings == []

    def test_unjoined_held_thread_fires(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import threading

            class Owner:
                def start(self):
                    self._w = threading.Thread(target=print, daemon=True)
                    self._w.start()

                def stop(self):
                    pass
            """, ["concurrency/thread-join"])
        assert len(res.findings) == 1
        assert res.findings[0].anchor == "Owner._w"

    def test_joined_thread_silent_incl_snapshot_alias(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import threading

            class Owner:
                def start(self):
                    self._w = threading.Thread(target=print, daemon=True)
                    t = threading.Thread(target=print, daemon=True)
                    self._pool.append(t)

                def stop(self):
                    w = self._w
                    w.join(timeout=1)
                    for t in list(self._pool):
                        t.join(timeout=1)
            """, ["concurrency/thread-join"])
        assert res.findings == []

    def test_bare_join_with_join_or_warn_imported_fires(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import threading
            from nnstreamer_tpu.graph.element import join_or_warn

            class Owner:
                def start(self):
                    self._w = threading.Thread(target=print, daemon=True)
                    self._w.start()

                def stop(self):
                    self._w.join(timeout=1)
            """, ["concurrency/join-or-warn"])
        assert len(res.findings) == 1
        assert "bare .join()" in res.findings[0].message

    def test_join_or_warn_usage_silent(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import threading
            from nnstreamer_tpu.graph.element import join_or_warn

            class Owner:
                def start(self):
                    self._w = threading.Thread(target=print, daemon=True)
                    self._w.start()

                def stop(self):
                    join_or_warn(self._w, "owner", timeout=1.0)
            """, ["concurrency/join-or-warn"])
        assert res.findings == []


# --------------------------------------------------------------------------- #
# contracts family
# --------------------------------------------------------------------------- #

class TestContractRules:
    def test_leaky_never_raise_boundary_fires(self, tmp_path):
        res = lint_snippet(tmp_path, """
            def parse(x):
                '''Best-effort parse; never raises.'''
                try:
                    return int(x)
                except ValueError:
                    return None
            """, ["contracts/never-raise"])
        assert len(res.findings) == 1
        assert res.findings[0].anchor == "parse"

    def test_broad_except_satisfies_never_raise(self, tmp_path):
        res = lint_snippet(tmp_path, """
            def parse(x):
                '''Best-effort parse; never raises.'''
                try:
                    return int(x)
                except Exception:
                    return None

            def parse2(x):
                '''Must not raise.'''
                try:
                    return int(x)
                except (OSError, Exception):
                    return None
            """, ["contracts/never-raise"])
        assert res.findings == []

    def test_nested_def_broad_except_does_not_count(self, tmp_path):
        res = lint_snippet(tmp_path, """
            def outer(x):
                '''never raises'''
                def inner():
                    try:
                        return int(x)
                    except Exception:
                        return None
                return inner()
            """, ["contracts/never-raise"])
        assert len(res.findings) == 1

    def test_ungated_hook_call_fires(self, tmp_path):
        res = lint_snippet(tmp_path, """
            CHAOS_HOOK = None

            def fire(x):
                CHAOS_HOOK(x)
            """, ["contracts/hook-gate"])
        assert len(res.findings) == 1
        assert "is None" in res.findings[0].message

    def test_gated_hook_calls_silent(self, tmp_path):
        res = lint_snippet(tmp_path, """
            CHAOS_HOOK = None

            def gated(x):
                if CHAOS_HOOK is not None:
                    CHAOS_HOOK(x)

            def and_chain(x):
                if CHAOS_HOOK is not None and CHAOS_HOOK(x):
                    return True

            def early_guard(x):
                if CHAOS_HOOK is None:
                    return None
                return CHAOS_HOOK(x)
            """, ["contracts/hook-gate"])
        assert res.findings == []

    def test_non_none_hook_default_fires(self, tmp_path):
        res = lint_snippet(tmp_path, """
            BAD_HOOK = print
            GOOD_HOOK = None
            """, ["contracts/hook-default"])
        assert len(res.findings) == 1
        assert res.findings[0].anchor == "BAD_HOOK"


# --------------------------------------------------------------------------- #
# jax family
# --------------------------------------------------------------------------- #

class TestJaxRules:
    def test_host_call_in_jitted_function_fires(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import time
            import jax

            @jax.jit
            def f(x):
                t = time.time()
                return x * t
            """, ["jax/host-call-in-jit"])
        assert len(res.findings) == 1
        assert "time.time" in res.findings[0].message

    def test_wrapped_jit_and_partial_pallas_kernel_detected(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import functools
            import random
            import time
            import jax

            def _impl(x):
                return x + random.random()

            g = jax.jit(_impl)

            def _kernel(ref, n):
                time.sleep(0.1)

            kernel = functools.partial(_kernel, n=4)
            op = pl.pallas_call(kernel, out_shape=None)
            """, ["jax/host-call-in-jit"])
        assert len(res.findings) == 2
        anchors = {f.anchor for f in res.findings}
        assert any("_impl" in a for a in anchors)
        assert any("_kernel" in a for a in anchors)

    def test_host_call_outside_trace_silent(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import time
            import jax

            def setup():
                return time.time()

            @jax.jit
            def f(x):
                return x * 2
            """, ["jax/host-call-in-jit"])
        assert res.findings == []

    def test_array_valued_mutable_default_fires(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import numpy as np

            def f(x, buf=np.zeros(8)):
                return x

            def ok(x, buf=None, n=4):
                return x
            """, ["jax/mutable-default"])
        assert len(res.findings) == 1
        assert res.findings[0].anchor == "f"


# --------------------------------------------------------------------------- #
# wire family
# --------------------------------------------------------------------------- #

class TestWireRules:
    def test_enum_member_without_dispatch_fires(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import enum

            class Cmd(enum.IntEnum):
                PING = 1
                PONG = 2

            def dispatch(c):
                if c is Cmd.PING:
                    return "pong"
            """, ["wire/cmd-dispatch"])
        assert len(res.findings) == 1
        assert res.findings[0].anchor == "Cmd.PONG"

    def test_fully_dispatched_enum_silent(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import enum

            class Cmd(enum.IntEnum):
                PING = 1
                PONG = 2

            def dispatch(c):
                if c is Cmd.PING:
                    return "pong"
                if c is Cmd.PONG:
                    return "ping"
            """, ["wire/cmd-dispatch"])
        assert res.findings == []

    def test_one_sided_struct_format_fires(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import struct

            def send(sock, a, b):
                sock.sendall(struct.pack("<II", a, b))
                sock.sendall(struct.pack("<Q", a))

            def recv(data):
                return struct.unpack("<II", data)
            """, ["wire/struct-format"])
        assert len(res.findings) == 1
        assert res.findings[0].anchor == "pack:<Q"

    def test_struct_struct_counts_both_directions(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import struct

            _HDR = struct.Struct("<IBIQ")

            def send(sock, *vals):
                sock.sendall(_HDR.pack(*vals))
            """, ["wire/struct-format"])
        assert res.findings == []

    def test_kv_page_xfer_without_dispatch_fires(self, tmp_path):
        # seeded regression for the Cmd value the disaggregated-serving
        # split added: declaring KV_PAGE_XFER without a server dispatch
        # arm must fire wire/cmd-dispatch
        res = lint_snippet(tmp_path, """
            import enum

            class Cmd(enum.IntEnum):
                DATA = 5
                OBS_PUSH = 12
                KV_PAGE_XFER = 13

            def dispatch(c):
                if c is Cmd.DATA:
                    return "data"
                if c is Cmd.OBS_PUSH:
                    return "push"
            """, ["wire/cmd-dispatch"])
        assert len(res.findings) == 1
        assert res.findings[0].anchor == "Cmd.KV_PAGE_XFER"

    def test_kv_page_xfer_dispatched_silent(self, tmp_path):
        res = lint_snippet(tmp_path, """
            import enum

            class Cmd(enum.IntEnum):
                DATA = 5
                OBS_PUSH = 12
                KV_PAGE_XFER = 13

            def dispatch(c):
                if c is Cmd.DATA:
                    return "data"
                if c is Cmd.OBS_PUSH:
                    return "push"
                if c is Cmd.KV_PAGE_XFER:
                    return "xfer"
            """, ["wire/cmd-dispatch"])
        assert res.findings == []


# --------------------------------------------------------------------------- #
# naming family (the migrated check_metric_names checks)
# --------------------------------------------------------------------------- #

class TestNamingRules:
    def test_bad_metric_name_fires(self, tmp_path):
        res = lint_snippet(tmp_path, """
            def setup(reg):
                reg.counter("frames_total", "help", ())
                reg.counter("nnstpu_pipeline_frames_total", "help", ())
            """, ["naming/metric-name"])
        assert len(res.findings) == 1
        assert "nnstpu_<layer>_<name>_<unit>" in res.findings[0].message

    def test_bad_span_name_fires(self, tmp_path):
        res = lint_snippet(tmp_path, """
            def handle(store):
                with store.start_span("Query.ServerHandle"):
                    pass
                with store.start_span("query.server_handle"):
                    pass
            """, ["naming/span-name"])
        assert len(res.findings) == 1


# --------------------------------------------------------------------------- #
# sched placement (naming/placement via naming_compat.check_sched)
# --------------------------------------------------------------------------- #

class TestSchedPlacement:
    """check_sched ownership: sched-layer telemetry lives in
    nnstreamer_tpu/sched/ and the sched package mints no other layer."""

    @staticmethod
    def _tree(tmp_path, files):
        for rel, code in files.items():
            p = tmp_path / rel
            p.parent.mkdir(parents=True, exist_ok=True)
            p.write_text(textwrap.dedent(code))
        return tmp_path

    def test_sched_metric_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"serving/stray.py": """
            def setup(reg):
                reg.counter("nnstpu_sched_stray_total", "h", ())
            """})
        problems = naming_compat.check_sched(root)
        assert len(problems) == 1
        assert "sched.telemetry" in problems[0]

    def test_foreign_layer_inside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"sched/telemetry.py": """
            def setup(reg):
                reg.counter("nnstpu_pipeline_oops_total", "h", ())
            """})
        problems = naming_compat.check_sched(root)
        assert len(problems) == 1
        assert "must use the 'sched' layer" in problems[0]

    def test_sched_event_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"filters/stray.py": """
            def warn(events):
                events.record("sched.bucket_miss", "w", msg="x")
            """})
        problems = naming_compat.check_sched(root)
        assert len(problems) == 1
        assert "sched.bucket_miss" in problems[0]

    def test_clean_twin_silent(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {
            "sched/telemetry.py": """
                def setup(reg, events):
                    reg.counter("nnstpu_sched_batches_total", "h", ())
                    reg.gauge("nnstpu_sched_queue_depth", "h", ("tenant",))
                    events.record("sched.tenant_register", "info", msg="t")
                """,
            "serving/own.py": """
                def setup(reg):
                    reg.counter("nnstpu_serving_steps_total", "h", ())
                """,
        })
        assert naming_compat.check_sched(root) == []

    def test_sched_hook_globals_are_gate_checked(self, tmp_path):
        # the integration hooks the scheduler rides (SCHED_PIPELINE_HOOK
        # in graph/pipeline.py, SCHED_HOOK in obs/profile.py) match the
        # *_HOOK convention, so contracts/hook-gate covers their callers
        res = lint_snippet(tmp_path, """
            SCHED_PIPELINE_HOOK = None
            SCHED_HOOK = None

            def bad(p):
                SCHED_PIPELINE_HOOK(p)

            def good(p):
                hook = SCHED_HOOK
                if SCHED_PIPELINE_HOOK is not None:
                    SCHED_PIPELINE_HOOK(p)
            """, ["contracts/hook-gate"])
        assert len(res.findings) == 1
        assert "SCHED_PIPELINE_HOOK" in res.findings[0].message or \
            "SCHED_PIPELINE_HOOK" in res.findings[0].anchor


# --------------------------------------------------------------------------- #
# slo placement (naming/slo via naming_compat.check_slo)
# --------------------------------------------------------------------------- #

class TestSloPlacement:
    """check_slo ownership: slo-layer telemetry lives in obs/slo.py,
    the accountant mints no other layer, and the tenant label stays
    inside obs/slo.py + sched/ (cardinality guard)."""

    _tree = staticmethod(TestSchedPlacement._tree)

    def test_slo_metric_outside_file_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"serving/stray.py": """
            def setup(reg):
                reg.counter("nnstpu_slo_stray_total", "h", ())
            """})
        problems = naming_compat.check_slo(root)
        assert len(problems) == 1
        assert "hooks" in problems[0]

    def test_foreign_layer_inside_file_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"obs/slo.py": """
            def setup(reg):
                reg.counter("nnstpu_pipeline_oops_total", "h", ())
            """})
        problems = naming_compat.check_slo(root)
        assert len(problems) == 1
        assert "must use the 'slo' layer" in problems[0]

    def test_slo_event_outside_file_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"obs/health.py": """
            def warn(events):
                events.record("slo.burn_alert", "w", msg="x")
            """})
        problems = naming_compat.check_slo(root)
        assert len(problems) == 1
        assert "slo.burn_alert" in problems[0]

    def test_tenant_label_outside_owners_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"query/router.py": """
            def setup(reg):
                reg.counter("nnstpu_router_work_total", "h", ("tenant",))
            """})
        problems = naming_compat.check_slo(root)
        assert len(problems) == 1
        assert "cardinality" in problems[0]

    def test_clean_twin_silent(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {
            "obs/slo.py": """
                def setup(reg, events):
                    reg.counter("nnstpu_slo_goodput_total", "h",
                                ("tenant", "outcome"))
                    reg.gauge("nnstpu_slo_burn_ratio", "h",
                              ("tenant", "objective", "window"))
                    events.record("slo.burn_alert", "w", msg="x")
                """,
            "sched/telemetry.py": """
                def setup(reg):
                    reg.gauge("nnstpu_sched_queue_depth", "h", ("tenant",))
                """,
        })
        assert naming_compat.check_slo(root) == []

    def test_burn_ratio_shares_profile_unit_reservation(self, tmp_path):
        # the ratio unit stays reserved: profile and slo layers pass,
        # anything else still fires check_profile
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {
            "obs/slo.py": """
                def setup(reg):
                    reg.gauge("nnstpu_slo_burn_ratio", "h", ("tenant",))
                """,
            "serving/stray.py": """
                def setup(reg):
                    reg.gauge("nnstpu_serving_hit_ratio", "h", ())
                """,
        })
        problems = naming_compat.check_profile(root)
        assert len(problems) == 1
        assert "nnstpu_serving_hit_ratio" in problems[0]


# --------------------------------------------------------------------------- #
# disagg placement (naming/disagg via naming_compat.check_disagg)
# --------------------------------------------------------------------------- #

class TestDisaggPlacement:
    """check_disagg ownership: disagg-layer metrics, spans, and events
    live in nnstreamer_tpu/serving/disagg.py alone — the prefill/decode
    split's telemetry is not minted by the engines or router it rides
    on."""

    _tree = staticmethod(TestSchedPlacement._tree)

    def test_disagg_metric_outside_file_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"query/stray.py": """
            def setup(reg):
                reg.counter("nnstpu_disagg_pages_sent_total", "h", ())
            """})
        problems = naming_compat.check_disagg(root)
        assert len(problems) == 1
        assert "disaggregation" in problems[0]

    def test_disagg_span_outside_file_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"serving/lm_engine.py": """
            def ship(store):
                with store.start_span("disagg.xfer"):
                    pass
            """})
        problems = naming_compat.check_disagg(root)
        assert len(problems) == 1
        assert "disagg.xfer" in problems[0]

    def test_disagg_event_outside_file_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"query/router.py": """
            def warn(events):
                events.record("disagg.reprefill", "warning", msg="x")
            """})
        problems = naming_compat.check_disagg(root)
        assert len(problems) == 1
        assert "disagg.reprefill" in problems[0]

    def test_clean_twin_silent(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {
            "serving/disagg.py": """
                def setup(reg, events, store):
                    reg.counter("nnstpu_disagg_pages_sent_total", "h", ())
                    reg.histogram("nnstpu_disagg_xfer_seconds", "h", ())
                    events.record("disagg.reprefill", "warning", msg="r")
                    with store.start_span("disagg.xfer"):
                        pass
                """,
            "serving/kv_cache.py": """
                def setup(reg):
                    reg.counter("nnstpu_serving_kv_offloads_total", "h", ())
                """,
        })
        assert naming_compat.check_disagg(root) == []


# --------------------------------------------------------------------------- #
# suppressions
# --------------------------------------------------------------------------- #

class TestSuppressions:
    SEEDED = """
        import threading

        def spawn(fn):{trail}
            t = threading.Thread(target=fn){same}
            t.start()
            return t
        """

    def test_same_line_suppression(self, tmp_path):
        code = self.SEEDED.format(
            trail="", same="  # nnslint: disable=concurrency/thread-daemon")
        res = lint_snippet(tmp_path, code, ["concurrency/thread-daemon"])
        assert res.findings == [] and res.suppressed == 1

    def test_comment_line_above_suppression(self, tmp_path):
        code = """
            import threading

            def spawn(fn):
                # nnslint: disable=concurrency/thread-daemon
                t = threading.Thread(target=fn)
                t.start()
                return t
            """
        res = lint_snippet(tmp_path, code, ["concurrency/thread-daemon"])
        assert res.findings == [] and res.suppressed == 1

    def test_family_and_all_tokens(self, tmp_path):
        for token in ("concurrency", "all"):
            code = self.SEEDED.format(
                trail="", same=f"  # nnslint: disable={token}")
            res = lint_snippet(tmp_path, code,
                               ["concurrency/thread-daemon"])
            assert res.findings == [], token
            assert res.suppressed == 1, token

    def test_unrelated_rule_not_suppressed(self, tmp_path):
        code = self.SEEDED.format(
            trail="", same="  # nnslint: disable=wire/cmd-dispatch")
        res = lint_snippet(tmp_path, code, ["concurrency/thread-daemon"])
        assert len(res.findings) == 1 and res.suppressed == 0

    def test_code_line_above_does_not_leak_suppression(self, tmp_path):
        code = """
            import threading

            def spawn(fn):
                x = 1  # nnslint: disable=concurrency/thread-daemon
                t = threading.Thread(target=fn)
                t.start()
                return t
            """
        res = lint_snippet(tmp_path, code, ["concurrency/thread-daemon"])
        assert len(res.findings) == 1


# --------------------------------------------------------------------------- #
# baseline round trip
# --------------------------------------------------------------------------- #

class TestBaseline:
    def _finding(self, msg="m", anchor="a"):
        return Finding(rule="concurrency/thread-daemon", path="x/y.py",
                       line=10, message=msg, anchor=anchor)

    def test_save_load_round_trip(self, tmp_path):
        bl = tmp_path / "baseline.json"
        f = self._finding()
        n = nnsl_baseline.save([f], bl)
        assert n == 1
        keys = nnsl_baseline.load(bl)
        assert keys == {f.key}
        # keys are line-number free: drift must not invalidate them
        drifted = Finding(rule=f.rule, path=f.path, line=999,
                          message=f.message, anchor=f.anchor)
        new, grandfathered, stale = nnsl_baseline.split([drifted], keys)
        assert new == [] and grandfathered == [drifted] and stale == set()

    def test_split_reports_new_and_stale(self, tmp_path):
        old = self._finding(anchor="gone")
        keys = {old.key}
        fresh = self._finding(anchor="fresh")
        new, grandfathered, stale = nnsl_baseline.split([fresh], keys)
        assert new == [fresh]
        assert grandfathered == []
        assert stale == {old.key}

    def test_missing_baseline_is_empty(self, tmp_path):
        assert nnsl_baseline.load(tmp_path / "nope.json") == set()

    def test_committed_baseline_is_small(self):
        # ISSUE acceptance: the committed baseline stays <= 10 entries
        entries = json.loads(nnsl_baseline.DEFAULT_BASELINE.read_text())
        assert isinstance(entries, list) and len(entries) <= 10


# --------------------------------------------------------------------------- #
# CLI + tier-1 gate
# --------------------------------------------------------------------------- #

def _run_cli(*args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "scripts.nnslint", *args],
        cwd=str(cwd), capture_output=True, text=True, timeout=300)


@pytest.mark.slow
class TestCli:
    def test_repo_lints_clean(self):
        """Tier-1 gate: the tree has no findings beyond the committed
        baseline. A regression in any rule family fails this test."""
        proc = _run_cli("--json")
        assert proc.returncode == 0, proc.stdout + proc.stderr
        report = json.loads(proc.stdout)
        assert report["findings"] == []
        assert report["stale_baseline_keys"] == []
        assert report["files"] > 50
        assert report["rules"] >= 16

    def test_findings_exit_code_and_update_baseline(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import threading\n"
                       "t = threading.Thread(target=print)\n")
        bl = tmp_path / "bl.json"
        proc = _run_cli(str(bad), "--baseline", str(bl),
                        "--select", "concurrency/thread-daemon")
        assert proc.returncode == 1
        assert "thread-daemon" in proc.stderr
        # --update-baseline grandfathers it and flips the verdict
        proc = _run_cli(str(bad), "--baseline", str(bl),
                        "--select", "concurrency/thread-daemon",
                        "--update-baseline")
        assert proc.returncode == 0
        assert len(json.loads(bl.read_text())) == 1
        proc = _run_cli(str(bad), "--baseline", str(bl),
                        "--select", "concurrency/thread-daemon", "--json")
        assert proc.returncode == 0
        report = json.loads(proc.stdout)
        assert report["findings"] == []
        assert len(report["grandfathered"]) == 1

    def test_list_rules_covers_all_families(self):
        proc = _run_cli("--list-rules")
        assert proc.returncode == 0
        for family in ("concurrency/", "contracts/", "jax/", "wire/",
                       "naming/"):
            assert family in proc.stdout

    def test_error_exit_on_bad_path(self):
        proc = _run_cli("definitely/not/a/path.py")
        assert proc.returncode == 2


# --------------------------------------------------------------------------- #
# epilogue placement (naming/epilogue via naming_compat.check_epilogue)
# --------------------------------------------------------------------------- #

class TestEpiloguePlacement:
    """check_epilogue ownership: Pallas kernel labels are
    pallas.<snake_case> emitted only from ops/pallas/, and
    EPILOGUE_SELECT_HOOK is assigned only by its definition
    (ops/epilogue.py) and profile.enable()/disable()."""

    _tree = staticmethod(TestSchedPlacement._tree)

    def test_bad_label_shape_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"ops/pallas/epilogue.py": """
            def kern(hook):
                hook("Pallas.NMS-Sweep", (4,), "f32")

            def entry(_profile):
                if _profile.KERNEL_HOOK is not None:
                    _profile.KERNEL_HOOK("Pallas.NMS-Sweep", (4,), "f32")
            """})
        problems = naming_compat.check_epilogue(root)
        assert len(problems) == 1
        assert "does not match" in problems[0]

    def test_label_outside_pallas_dir_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"decoders/stray.py": """
            def entry(_profile):
                if _profile.KERNEL_HOOK is not None:
                    _profile.KERNEL_HOOK("pallas.stray_kernel", (4,), "f32")
            """})
        problems = naming_compat.check_epilogue(root)
        assert len(problems) == 1
        assert "outside nnstreamer_tpu/ops/pallas/" in problems[0]

    def test_hook_assignment_outside_owners_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"graph/pipeline.py": """
            from ..ops import epilogue as _epi

            def start(self):
                _epi.EPILOGUE_SELECT_HOOK = lambda f, c: True
            """})
        problems = naming_compat.check_epilogue(root)
        assert len(problems) == 1
        assert "EPILOGUE_SELECT_HOOK assigned outside" in problems[0]

    def test_clean_twin_silent(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {
            "ops/pallas/epilogue.py": """
                def entry(_profile):
                    if _profile.KERNEL_HOOK is not None:
                        _profile.KERNEL_HOOK("pallas.nms_sweep", (4,), "f32")
                """,
            "ops/epilogue.py": """
                EPILOGUE_SELECT_HOOK = None
                """,
            "obs/profile.py": """
                def enable(p):
                    from ..ops import epilogue as _epi
                    _epi.EPILOGUE_SELECT_HOOK = p.epilogue_select

                def disable():
                    from ..ops import epilogue as _epi
                    _epi.EPILOGUE_SELECT_HOOK = None
                """,
            "ops/fusion.py": """
                def consume(chain):
                    from . import epilogue as _epi
                    if _epi.EPILOGUE_SELECT_HOOK is not None:
                        return _epi.EPILOGUE_SELECT_HOOK("f", chain)
                    return True
                """,
        })
        assert naming_compat.check_epilogue(root) == []

    def test_equality_comparison_is_not_assignment(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"tests_helper/probe.py": """
            def check(epi, fn):
                return epi.EPILOGUE_SELECT_HOOK == fn
            """})
        assert naming_compat.check_epilogue(root) == []

    def test_repo_is_clean(self):
        from scripts.nnslint import naming_compat

        assert naming_compat.check_epilogue() == []


# --------------------------------------------------------------------------- #
# tune placement (naming/tune via naming_compat.check_tune)
# --------------------------------------------------------------------------- #

class TestTunePlacement:
    """check_tune ownership: tune-layer telemetry and tune.* events
    live in nnstreamer_tpu/tune/, and TUNE_HOOK is assigned only by
    tune/ itself + obs/profile.py — knob sites READ the hook behind
    one None check (the zero-overhead contract)."""

    _tree = staticmethod(TestSchedPlacement._tree)

    def test_tune_metric_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"ops/stray.py": """
            def setup(reg):
                reg.counter("nnstpu_tune_stray_total", "h", ())
            """})
        problems = naming_compat.check_tune(root)
        assert len(problems) == 1
        assert "TUNE_HOOK" in problems[0]

    def test_foreign_layer_inside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"tune/tuner.py": """
            def setup(reg):
                reg.counter("nnstpu_pipeline_oops_total", "h", ())
            """})
        problems = naming_compat.check_tune(root)
        assert len(problems) == 1
        assert "must use the 'tune' layer" in problems[0]

    def test_tune_event_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"serving/lm_engine.py": """
            def warn(events):
                events.record("tune.sweep", "w", msg="x")
            """})
        problems = naming_compat.check_tune(root)
        assert len(problems) == 1
        assert "tune.sweep" in problems[0]

    def test_hook_assignment_outside_owners_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"filters/xla.py": """
            from .. import tune as _tune

            def hijack(tn):
                _tune.TUNE_HOOK = tn
            """})
        problems = naming_compat.check_tune(root)
        assert len(problems) == 1
        assert "TUNE_HOOK assigned outside" in problems[0]

    def test_fleet_hooks_are_distinct_names(self, tmp_path):
        # the regex must not swallow the fleet-side federation hooks,
        # which ARE legitimately assigned by tune/__init__ and defined
        # in obs/fleet.py
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"obs/fleet.py": """
            TUNE_PUSH_HOOK = None
            TUNE_ADOPT_HOOK = None
            """})
        assert naming_compat.check_tune(root) == []

    def test_clean_twin_silent(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {
            "tune/__init__.py": """
                TUNE_HOOK = None

                def enable(tn):
                    global TUNE_HOOK
                    TUNE_HOOK = tn
                """,
            "tune/tuner.py": """
                def setup(reg, events):
                    reg.counter("nnstpu_tune_picks_total", "h", ("source",))
                    events.record("tune.sweep", "info", msg="x")
                """,
            "ops/pallas/flash_attention.py": """
                def blocks(_tune):
                    tn = _tune.TUNE_HOOK
                    if tn is None:
                        return (512, 1024)
                    return tn.pick()
                """,
        })
        assert naming_compat.check_tune(root) == []

    def test_equality_comparison_is_not_assignment(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"tests_helper/probe.py": """
            def check(tune, tn):
                return tune.TUNE_HOOK == tn
            """})
        assert naming_compat.check_tune(root) == []

    def test_repo_is_clean(self):
        from scripts.nnslint import naming_compat

        assert naming_compat.check_tune() == []

# --------------------------------------------------------------------------- #
# fleet placement (naming/fleet via naming_compat.check_fleet)
# --------------------------------------------------------------------------- #

class TestFleetPlacement:
    """check_fleet ownership: nnstpu_fleet_* metrics, fleet.* spans,
    and the fleet.scale_*/migrate_* event subfamilies live in
    nnstreamer_tpu/fleet/; the replicas gauge unit is fleet-only;
    AUTOSCALE_HOOK is assigned only by fleet/ itself — the scheduler
    READS it behind one None check (the zero-overhead contract)."""

    _tree = staticmethod(TestSchedPlacement._tree)

    def test_fleet_metric_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"obs/stray.py": """
            def setup(reg):
                reg.counter("nnstpu_fleet_stray_total", "h", ())
            """})
        problems = naming_compat.check_fleet(root)
        assert len(problems) == 1
        assert "lives with the controller" in problems[0]

    def test_foreign_layer_inside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"fleet/controller.py": """
            def setup(reg):
                reg.counter("nnstpu_pipeline_oops_total", "h", ())
            """})
        problems = naming_compat.check_fleet(root)
        assert len(problems) == 1
        assert "must use the 'fleet' layer" in problems[0]

    def test_replicas_unit_outside_layer_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"serving/stray.py": """
            def setup(reg):
                reg.gauge("nnstpu_serving_worker_replicas", "h", ())
            """})
        problems = naming_compat.check_fleet(root)
        assert len(problems) == 1
        assert "reserved for the 'fleet' layer" in problems[0]

    def test_fleet_span_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"query/router.py": """
            def go(tracing):
                span = tracing.start_span("fleet.migrate")
                span.end()
            """})
        problems = naming_compat.check_fleet(root)
        assert len(problems) == 1
        assert "span 'fleet.migrate'" in problems[0]

    def test_scale_event_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"obs/fleet.py": """
            def warn(events):
                events.record("fleet.scale_up", "w", msg="x")
            """})
        problems = naming_compat.check_fleet(root)
        assert len(problems) == 1
        assert "scale_*/migrate_*" in problems[0]

    def test_federation_events_stay_open(self, tmp_path):
        # obs/fleet.py owns the federation subfamily — the event layer
        # as a whole is NOT package-confined, only the controller verbs
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"obs/fleet.py": """
            def note(events):
                events.record("fleet.push", "i", msg="x")
                events.record("fleet.expire", "w", msg="x")
                events.record("fleet.drain_confirmed", "i", msg="x")
            """})
        assert naming_compat.check_fleet(root) == []

    def test_hook_assignment_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"sched/engine.py": """
            from .. import fleet as _fleet

            def hijack(ctl):
                _fleet.AUTOSCALE_HOOK = ctl
            """})
        problems = naming_compat.check_fleet(root)
        assert len(problems) == 1
        assert "AUTOSCALE_HOOK assigned outside" in problems[0]

    def test_clean_twin_silent(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {
            "fleet/__init__.py": """
                AUTOSCALE_HOOK = None

                def enable(ctl):
                    global AUTOSCALE_HOOK
                    AUTOSCALE_HOOK = ctl
                """,
            "fleet/controller.py": """
                def setup(reg, events, tracing):
                    reg.gauge("nnstpu_fleet_worker_replicas", "h",
                              ("controller",))
                    reg.counter("nnstpu_fleet_scale_actions_total", "h",
                                ("controller", "action"))
                    events.record("fleet.scale_in", "i", msg="x")
                    span = tracing.start_span("fleet.migrate")
                    span.end()
                """,
            "sched/engine.py": """
                def tap(_fleet, name, occ):
                    hook = _fleet.AUTOSCALE_HOOK
                    if hook is not None:
                        hook.observe_occupancy(name, occ)
                """,
        })
        assert naming_compat.check_fleet(root) == []

    def test_repo_is_clean(self):
        from scripts.nnslint import naming_compat

        assert naming_compat.check_fleet() == []


# --------------------------------------------------------------------------- #
# checkpoint placement (naming/checkpoint via naming_compat.check_checkpoint)
# --------------------------------------------------------------------------- #

class TestCheckpointPlacement:
    """check_checkpoint ownership: nnstpu_fleet_checkpoint_*/restore_*/
    restored_* metrics and the fleet.checkpoint_*/restore_* event
    subfamilies live in nnstreamer_tpu/fleet/; CHECKPOINT_HOOK is
    written only by the daemon's install/uninstall — except its None
    default on obs/fleet.py, where the hook lives."""

    _tree = staticmethod(TestSchedPlacement._tree)

    def test_checkpoint_metric_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"serving/disagg.py": """
            def setup(reg):
                reg.counter("nnstpu_fleet_checkpoint_bytes_total", "h",
                            ())
            """})
        problems = naming_compat.check_checkpoint(root)
        assert len(problems) == 1
        assert "snapshot accounting lives with the checkpoint daemon" \
            in problems[0]

    def test_restore_event_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"query/router.py": """
            def warn(events):
                events.record("fleet.restore_done", "i", msg="x")
            """})
        problems = naming_compat.check_checkpoint(root)
        assert len(problems) == 1
        assert "the daemon and restorer own the crash audit trail" \
            in problems[0]

    def test_hook_assignment_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"serving/disagg.py": """
            from ..obs import fleet as _obsfleet

            def hijack(fn):
                _obsfleet.CHECKPOINT_HOOK = fn
            """})
        problems = naming_compat.check_checkpoint(root)
        assert len(problems) == 1
        assert "CHECKPOINT_HOOK assigned outside" in problems[0]

    def test_hook_none_default_on_home_module_allowed(self, tmp_path):
        # obs/fleet.py hosts the hook: its `= None` default is the one
        # assignment tolerated outside fleet/ — anything else there
        # (or any non-None value) still fires
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"obs/fleet.py": """
            CHECKPOINT_HOOK = None
            """})
        assert naming_compat.check_checkpoint(root) == []

    def test_hook_non_none_on_home_module_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"obs/fleet.py": """
            CHECKPOINT_HOOK = print
            """})
        problems = naming_compat.check_checkpoint(root)
        assert len(problems) == 1
        assert "CHECKPOINT_HOOK assigned outside" in problems[0]

    def test_clean_twin_silent(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {
            "obs/fleet.py": """
                CHECKPOINT_HOOK = None
                """,
            "fleet/checkpoint.py": """
                from ..obs import fleet as _obsfleet

                def setup(reg, events):
                    reg.counter(
                        "nnstpu_fleet_restored_sessions_total", "h",
                        ("outcome",))
                    events.record("fleet.checkpoint_write", "i",
                                  msg="x")
                    events.record("fleet.restore_done", "i", msg="x")

                def install_hook(fn):
                    _obsfleet.CHECKPOINT_HOOK = fn
                """,
            "serving/disagg.py": """
                def push(_obsfleet):
                    hook = _obsfleet.CHECKPOINT_HOOK
                    return hook() if hook is not None else {}
                """,
        })
        assert naming_compat.check_checkpoint(root) == []

    def test_repo_is_clean(self):
        from scripts.nnslint import naming_compat

        assert naming_compat.check_checkpoint() == []


# --------------------------------------------------------------------------- #
# diag placement (naming/diag via naming_compat.check_diag)
# --------------------------------------------------------------------------- #

class TestDiagPlacement:
    """check_diag ownership: diag-layer telemetry, diag.* synthetic
    spans (start_span AND add_span sites), and diag.* events live in
    nnstreamer_tpu/obs/diag/; nnstpu_build_info is registered only by
    obs/exporter.py; DIAG_HOOK is assigned only by obs/diag/ itself —
    the sched/serving taps READ it behind one None check (the
    zero-overhead contract)."""

    _tree = staticmethod(TestSchedPlacement._tree)

    def test_diag_metric_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"serving/stray.py": """
            def setup(reg):
                reg.counter("nnstpu_diag_bundles_total", "h", ())
            """})
        problems = naming_compat.check_diag(root)
        assert len(problems) == 1
        assert "lives with the engine" in problems[0]

    def test_diag_span_outside_package_fires(self, tmp_path):
        # the add_span form too: synthetic back-fill is diag-only
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"sched/engine.py": """
            def go(store, ctx, t0, t1):
                store.add_span("diag.sched_run", ctx.trace_id,
                               ctx.span_id, t0, t1)
            """})
        problems = naming_compat.check_diag(root)
        assert len(problems) == 1
        assert "synthetic spans" in problems[0]

    def test_diag_event_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"obs/health.py": """
            def warn(events):
                events.record("diag.capture", "i", msg="x")
            """})
        problems = naming_compat.check_diag(root)
        assert len(problems) == 1
        assert "event 'diag.capture'" in problems[0]

    def test_build_info_outside_exporter_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"obs/metrics.py": """
            def setup(reg):
                reg.gauge("nnstpu_build_info", "h",
                          ("version", "jax", "device_kind"))
            """})
        problems = naming_compat.check_diag(root)
        assert len(problems) == 1
        assert "one owner" in problems[0]

    def test_build_info_exempt_from_name_shape(self, tmp_path):
        # the identity gauge has no unit suffix by design — check_names
        # must not flag it (check_diag pins its ownership instead)
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"obs/exporter.py": """
            def setup(reg):
                reg.gauge("nnstpu_build_info", "h",
                          ("version", "jax", "device_kind"))
            """})
        assert naming_compat.check_names(root) == []
        assert naming_compat.check_diag(root) == []

    def test_hook_assignment_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"sched/engine.py": """
            from ..obs import diag as _diag

            def hijack(eng):
                _diag.DIAG_HOOK = eng
            """})
        problems = naming_compat.check_diag(root)
        assert len(problems) == 1
        assert "DIAG_HOOK assigned outside" in problems[0]

    def test_push_hook_in_obs_fleet_stays_silent(self, tmp_path):
        # DIAG_PUSH_HOOK is a DIFFERENT slot (obs/fleet.py owns it;
        # diag.enable() installs into it) — the DIAG_HOOK assign regex
        # must not cross-match it
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"obs/fleet.py": """
            DIAG_PUSH_HOOK = None

            def build_push():
                doc = DIAG_PUSH_HOOK() if DIAG_PUSH_HOOK is not None \\
                    else None
                return doc
            """})
        assert naming_compat.check_diag(root) == []

    def test_clean_twin_silent(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {
            "obs/diag/__init__.py": """
                DIAG_HOOK = None

                def enable(eng, store, ctx):
                    global DIAG_HOOK
                    store.add_span("diag.sched_wait", ctx.trace_id,
                                   ctx.span_id, 0, 1)
                    DIAG_HOOK = eng
                """,
            "sched/engine.py": """
                def tap(_diag, name, batch, t0, t1):
                    hook = _diag.DIAG_HOOK
                    if hook is not None:
                        hook.observe_sched_batch(name, batch, t0, t1)
                """,
        })
        assert naming_compat.check_diag(root) == []

    def test_repo_is_clean(self):
        from scripts.nnslint import naming_compat

        assert naming_compat.check_diag() == []


class TestQualityPlacement:
    """check_quality ownership: quality-layer telemetry, quality.*
    events, and the psi gauge unit live in nnstreamer_tpu/obs/quality/;
    QUALITY_HOOK is assigned only by obs/quality/ itself — the
    element/filter/decoder/serving taps READ it behind one None check
    (the zero-overhead contract)."""

    _tree = staticmethod(TestSchedPlacement._tree)

    def test_quality_metric_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"serving/stray.py": """
            def setup(reg):
                reg.counter("nnstpu_quality_frames_total", "h", ())
            """})
        problems = naming_compat.check_quality(root)
        assert len(problems) == 1
        assert "QUALITY_HOOK" in problems[0]

    def test_psi_unit_outside_layer_fires(self, tmp_path):
        # the drift-score unit is quality vocabulary, like ratio/flops
        # are profile vocabulary
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"obs/slo.py": """
            def setup(reg):
                reg.gauge("nnstpu_slo_drift_psi", "h", ())
            """})
        problems = naming_compat.check_quality(root)
        assert len(problems) == 1
        assert "reserved for the 'quality' layer" in problems[0]

    def test_quality_event_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"obs/health.py": """
            def warn(events):
                events.record("quality.anomaly", "i", msg="x")
            """})
        problems = naming_compat.check_quality(root)
        assert len(problems) == 1
        assert "event 'quality.anomaly'" in problems[0]

    def test_quality_span_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"elements/filter.py": """
            def tap(tracer):
                with tracer.start_span("quality.observe"):
                    pass
            """})
        problems = naming_compat.check_quality(root)
        assert len(problems) == 1
        assert "span 'quality.observe'" in problems[0]

    def test_hook_assignment_outside_package_fires(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {"graph/element.py": """
            from ..obs import quality as _quality

            def hijack(eng):
                _quality.QUALITY_HOOK = eng
            """})
        problems = naming_compat.check_quality(root)
        assert len(problems) == 1
        assert "QUALITY_HOOK assigned outside" in problems[0]

    def test_clean_twin_silent(self, tmp_path):
        from scripts.nnslint import naming_compat

        root = self._tree(tmp_path, {
            "obs/quality/__init__.py": """
                QUALITY_HOOK = None

                def setup(reg, events):
                    reg.gauge("nnstpu_quality_drift_psi", "h",
                              ("tap", "window"))
                    events.record("quality.anomaly", "i", msg="x")

                def enable(eng):
                    global QUALITY_HOOK
                    QUALITY_HOOK = eng
                """,
            "graph/element.py": """
                def push(_quality, peer, buf):
                    qhook = _quality.QUALITY_HOOK
                    if qhook is not None:
                        qhook.observe_chain(peer, buf)
                """,
        })
        assert naming_compat.check_quality(root) == []

    def test_repo_is_clean(self):
        from scripts.nnslint import naming_compat

        assert naming_compat.check_quality() == []
