"""Streaming KV-cache decode (models/causal_lm.py).

Exactness: step-by-step decode equals the full causal forward at every
position; the pipeline-loop form (tensor_repo carrying the cache, the
reference's LSTM-loop pattern at transformer scale) produces identical
logits, with the cache staying device-resident around the loop.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nnstreamer_tpu.core import Caps
from nnstreamer_tpu.core.types import TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline
from nnstreamer_tpu.models.causal_lm import (
    empty_cache,
    lm_decode_step,
    lm_forward,
)
from nnstreamer_tpu.models.zoo import get_model

SPEC = "zoo://causal_lm?vocab=32&dim=32&heads=4&layers=2&max_len=16"


@pytest.fixture(scope="module")
def bundle():
    return get_model(SPEC)


def test_step_decode_matches_full_forward(bundle):
    meta = bundle.metadata
    rng = np.random.default_rng(0)
    T = 10
    tokens = rng.integers(0, meta["vocab"], (1, T)).astype(np.int32)
    oracle = np.asarray(lm_forward(bundle.params, jnp.asarray(tokens),
                                   meta["heads"]))
    k, v, pos = empty_cache(meta["layers"], 1, meta["heads"],
                            meta["max_len"], meta["head_dim"])
    step = jax.jit(bundle.fn())
    for t in range(T):
        logits, k, v, pos = step(tokens[:, t:t + 1], k, v, pos)
        np.testing.assert_allclose(
            np.asarray(logits), oracle[:, t], rtol=2e-4, atol=2e-5,
            err_msg=f"step {t} diverged from the full forward")
    assert int(np.asarray(pos)[0]) == T


def test_greedy_generation_deterministic(bundle):
    """Greedy continuation via repeated steps is stable and in-vocab."""
    meta = bundle.metadata
    k, v, pos = empty_cache(meta["layers"], 1, meta["heads"],
                            meta["max_len"], meta["head_dim"])
    step = jax.jit(bundle.fn())
    tok = np.array([[3]], np.int32)
    out = []
    for _ in range(8):
        logits, k, v, pos = step(tok, k, v, pos)
        tok = np.asarray(jnp.argmax(logits, -1))[:, None].astype(np.int32)
        out.append(int(tok[0, 0]))
    assert all(0 <= t < meta["vocab"] for t in out)
    # same seed → same continuation
    k2, v2, pos2 = empty_cache(meta["layers"], 1, meta["heads"],
                               meta["max_len"], meta["head_dim"])
    tok2, out2 = np.array([[3]], np.int32), []
    for _ in range(8):
        logits, k2, v2, pos2 = step(tok2, k2, v2, pos2)
        tok2 = np.asarray(jnp.argmax(logits, -1))[:, None].astype(np.int32)
        out2.append(int(tok2[0, 0]))
    assert out == out2


def test_repo_loop_streaming_decode(bundle):
    """The pipeline form: tokens + repo-held cache → mux → filter → demux;
    logits equal the oracle and the cache rides the loop device-resident."""
    from nnstreamer_tpu.elements.repo import reset_repo

    meta = bundle.metadata
    reset_repo()
    rng = np.random.default_rng(1)
    T = 6
    tokens = rng.integers(0, meta["vocab"], (T,)).astype(np.int32)
    oracle = np.asarray(lm_forward(bundle.params,
                                   jnp.asarray(tokens[None]),
                                   meta["heads"]))[0]

    flat = meta["layers"] * meta["batch"] * meta["heads"]
    hd, M = meta["head_dim"], meta["max_len"]
    p = Pipeline()
    src = p.add_new(
        "appsrc",
        caps=Caps.tensors(TensorsConfig(
            TensorsInfo.from_strings("1:1", "int32"), 30)),
        data=[t.reshape(1, 1) for t in tokens])
    state = p.add_new(
        "tensor_reposrc", slot_index=41,
        dims=f"{hd}:{M}:{flat},{hd}:{M}:{flat},1",
        types="float32,float32,int32")
    mux = p.add_new("tensor_mux", sync_mode="nosync")
    filt = p.add_new("tensor_filter", framework="xla-tpu", model=bundle)
    demux = p.add_new("tensor_demux", tensorpick="0,1:2:3")
    q_out, q_state = p.add_new("queue"), p.add_new("queue")
    sink = p.add_new("tensor_sink", store=True)
    rsink = p.add_new("tensor_reposink", slot_index=41)
    Pipeline.link(src, mux)
    Pipeline.link(state, mux)
    Pipeline.link(mux, filt, demux)
    Pipeline.link(demux, q_out, sink)
    Pipeline.link(demux, q_state, rsink)
    p.start()
    import time

    deadline = time.monotonic() + 120
    while sink.num_buffers < T and time.monotonic() < deadline:
        time.sleep(0.05)
    p.stop()
    assert sink.num_buffers == T
    for t, buf in enumerate(sink.buffers):
        np.testing.assert_allclose(buf.memories[0].host(), oracle[None, t],
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=f"loop step {t}")


def test_cache_overflow_position_is_callers_problem(bundle):
    """Decoding beyond max_len is out of contract; pos keeps counting but
    the live mask covers at most max_len — document via behavior."""
    meta = bundle.metadata
    k, v, pos = empty_cache(meta["layers"], 1, meta["heads"],
                            meta["max_len"], meta["head_dim"])
    step = jax.jit(bundle.fn())
    tok = np.array([[0]], np.int32)
    for _ in range(meta["max_len"]):
        logits, k, v, pos = step(tok, k, v, pos)
    assert int(np.asarray(pos)[0]) == meta["max_len"]
    assert np.isfinite(np.asarray(logits)).all()


def test_prefill_then_decode_matches_full_forward(bundle):
    """Prompt via one lm_prefill forward, continuation via steps: logits
    equal the full causal forward over the whole sequence."""
    from nnstreamer_tpu.models.causal_lm import lm_prefill

    meta = bundle.metadata
    rng = np.random.default_rng(4)
    P_, C = 6, 5
    tokens = rng.integers(0, meta["vocab"], (1, P_ + C)).astype(np.int32)
    oracle = np.asarray(lm_forward(bundle.params, jnp.asarray(tokens),
                                   meta["heads"]))
    logits, k, v, pos = jax.jit(
        lambda p, t: lm_prefill(p, t, meta["heads"], meta["max_len"]))(
        bundle.params, tokens[:, :P_])
    np.testing.assert_allclose(np.asarray(logits), oracle[:, P_ - 1],
                               rtol=2e-4, atol=2e-5,
                               err_msg="prefill last-logits diverged")
    assert int(np.asarray(pos)[0]) == P_
    step = jax.jit(bundle.fn())
    for t in range(P_, P_ + C):
        logits, k, v, pos = step(tokens[:, t:t + 1], k, v, pos)
        np.testing.assert_allclose(np.asarray(logits), oracle[:, t],
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=f"decode step {t} diverged")


def test_batched_decode_matches_oracle():
    """batch=2 decoding: each batch row equals its own oracle."""
    b = get_model(SPEC + "&batch=2")
    meta = b.metadata
    rng = np.random.default_rng(6)
    T = 5
    tokens = rng.integers(0, meta["vocab"], (2, T)).astype(np.int32)
    oracle = np.asarray(lm_forward(b.params, jnp.asarray(tokens),
                                   meta["heads"]))
    k, v, pos = empty_cache(meta["layers"], 2, meta["heads"],
                            meta["max_len"], meta["head_dim"])
    step = jax.jit(b.fn())
    for t in range(T):
        logits, k, v, pos = step(tokens[:, t:t + 1], k, v, pos)
        np.testing.assert_allclose(np.asarray(logits), oracle[:, t],
                                   rtol=2e-4, atol=2e-5)


def test_prefill_rejects_oversized_prompt(bundle):
    from nnstreamer_tpu.models.causal_lm import lm_prefill

    meta = bundle.metadata
    too_long = np.zeros((1, meta["max_len"] + 1), np.int32)
    with pytest.raises(ValueError, match="max_len"):
        lm_prefill(bundle.params, jnp.asarray(too_long), meta["heads"],
                   meta["max_len"])


def test_sp_prefill_then_decode_exact(bundle):
    """Long-context path: ring-attention prefill over the sp mesh, then
    single-stream decode — logits equal the dense oracle throughout."""
    from nnstreamer_tpu.models.causal_lm import lm_prefill
    from nnstreamer_tpu.parallel import make_mesh

    meta = bundle.metadata
    mesh = make_mesh({"sp": 8})
    rng = np.random.default_rng(9)
    P_, C = 8, 4  # prompt divides the sp axis
    tokens = rng.integers(0, meta["vocab"], (1, P_ + C)).astype(np.int32)
    oracle = np.asarray(lm_forward(bundle.params, jnp.asarray(tokens),
                                   meta["heads"]))
    logits, k, v, pos = lm_prefill(bundle.params,
                                   jnp.asarray(tokens[:, :P_]),
                                   meta["heads"], meta["max_len"],
                                   mesh=mesh)
    np.testing.assert_allclose(np.asarray(logits), oracle[:, P_ - 1],
                               rtol=2e-4, atol=2e-5)
    step = jax.jit(bundle.fn())
    k, v = np.asarray(k), np.asarray(v)  # cache leaves the mesh
    for t in range(P_, P_ + C):
        logits, k, v, pos = step(tokens[:, t:t + 1], k, v, pos)
        np.testing.assert_allclose(np.asarray(logits), oracle[:, t],
                                   rtol=2e-4, atol=2e-5,
                                   err_msg=f"sp-prefill decode step {t}")


def test_sp_prefill_rejects_indivisible_prompt(bundle):
    from nnstreamer_tpu.models.causal_lm import lm_prefill
    from nnstreamer_tpu.parallel import make_mesh

    meta = bundle.metadata
    mesh = make_mesh({"sp": 8})
    with pytest.raises(ValueError, match="divisible"):
        lm_prefill(bundle.params, jnp.zeros((1, 6), jnp.int32),
                   meta["heads"], meta["max_len"], mesh=mesh)


def test_sp_prefill_rejects_missing_axis(bundle):
    from nnstreamer_tpu.models.causal_lm import lm_prefill
    from nnstreamer_tpu.parallel import make_mesh

    meta = bundle.metadata
    with pytest.raises(ValueError, match="axis"):
        lm_prefill(bundle.params, jnp.zeros((1, 8), jnp.int32),
                   meta["heads"], meta["max_len"],
                   mesh=make_mesh({"data": 8}))


def test_decode_past_cache_capacity_poisons_logits(bundle):
    """pos >= max_len cannot raise inside the compiled step, so the
    overflow surfaces as NaN logits instead of silently overwriting the
    last cache slot (ADVICE r3: lm_decode_step bound guard)."""
    meta = bundle.metadata
    k, v, pos = empty_cache(meta["layers"], 1, meta["heads"],
                            meta["max_len"], meta["head_dim"])
    step = jax.jit(bundle.fn())
    tok = np.zeros((1, 1), np.int32)
    for _ in range(meta["max_len"]):
        logits, k, v, pos = step(tok, k, v, pos)
        assert np.isfinite(np.asarray(logits)).all()
    # one past capacity: poisoned, not silently wrong
    logits, k, v, pos = step(tok, k, v, pos)
    assert np.isnan(np.asarray(logits)).all()


def test_prefill_flash_conflicts_with_mesh():
    """flash=True with a mesh must error, not silently use ring attention."""
    import jax

    from nnstreamer_tpu.models.causal_lm import init_causal_lm, lm_prefill
    from nnstreamer_tpu.parallel import make_mesh

    params = init_causal_lm(jax.random.PRNGKey(0), vocab=32, d_model=16,
                            n_heads=2, n_layers=1, max_len=16)
    mesh = make_mesh({"sp": 8})
    toks = np.zeros((1, 8), np.int32)
    with pytest.raises(ValueError, match="flash"):
        lm_prefill(params, toks, n_heads=2, max_len=16, mesh=mesh,
                   flash=True)


def test_prefill_sp_ring_flash_mode(monkeypatch):
    """NNS_LM_SP_MODE=ring-flash: the sp prefill runs the pallas kernel
    inside the ring and still matches the dense forward."""
    import jax

    from nnstreamer_tpu.models.causal_lm import (
        init_causal_lm,
        lm_forward,
        lm_prefill,
    )
    from nnstreamer_tpu.parallel import make_mesh

    params = init_causal_lm(jax.random.PRNGKey(0), vocab=32, d_model=16,
                            n_heads=2, n_layers=2, max_len=32)
    mesh = make_mesh({"sp": 8})
    toks = np.asarray(
        np.random.default_rng(7).integers(0, 32, (1, 32)), np.int32)
    monkeypatch.setenv("NNS_LM_SP_MODE", "ring-flash")
    logits, _, _, _ = lm_prefill(params, toks, n_heads=2, max_len=32,
                                 mesh=mesh)
    want = lm_forward(params, toks, n_heads=2)[:, -1]
    np.testing.assert_allclose(np.asarray(logits), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
