"""Native C++ runtime component tests (allocator, sparse codec, SPSC ring)."""

import threading

import numpy as np
import pytest

from nnstreamer_tpu.utils import native


requires_native = pytest.mark.skipif(not native.native_available(),
                                     reason="g++ toolchain unavailable")


class TestAlignedAlloc:
    @requires_native
    def test_alignment(self):
        arr = native.aligned_empty((100, 100), np.float32)
        assert arr.ctypes.data % 64 == 0
        arr[:] = 1.0
        assert arr.sum() == 10000

    def test_fallback_shape(self):
        arr = native.aligned_empty((4, 4), np.uint8)
        assert arr.shape == (4, 4)


class TestSparseCodec:
    def test_roundtrip(self):
        dense = np.zeros(1000, np.float32)
        dense[[3, 500, 999]] = [1.5, -2.0, 7.0]
        idx, vals = native.sparse_encode_arrays(dense)
        np.testing.assert_array_equal(idx, [3, 500, 999])
        out = native.sparse_decode_arrays(idx, vals, 1000, np.float32)
        np.testing.assert_array_equal(out, dense)

    def test_all_dtypes(self):
        for dt in [np.uint8, np.int16, np.float32, np.float64]:
            dense = np.zeros(64, dt)
            dense[7] = 3
            idx, vals = native.sparse_encode_arrays(dense)
            assert idx.tolist() == [7]
            out = native.sparse_decode_arrays(idx, vals, 64, dt)
            np.testing.assert_array_equal(out, dense)

    @requires_native
    def test_decode_bad_index(self):
        with pytest.raises(ValueError):
            native.sparse_decode_arrays(np.array([99], np.uint32),
                                        np.array([1.0], np.float32), 10,
                                        np.float32)

    def test_matches_python_element_codec(self):
        """Native codec and the sparse element wire format must agree."""
        from nnstreamer_tpu.core import TensorInfo
        from nnstreamer_tpu.elements.sparse import sparse_decode, sparse_encode

        dense = np.zeros((8, 8), np.float32)
        dense[1, 1] = 4.0
        blob = sparse_encode(dense, TensorInfo.from_array(dense))
        out, info = sparse_decode(blob)
        np.testing.assert_array_equal(out, dense)
        idx, vals = native.sparse_encode_arrays(dense)
        assert idx.tolist() == [9]


@requires_native
class TestSpscRing:
    def test_push_pop(self):
        ring = native.SpscRing(16, 256)
        assert ring.pop() is None
        assert ring.push(b"hello")
        assert ring.push(b"world")
        assert len(ring) == 2
        assert ring.pop() == b"hello"
        assert ring.pop() == b"world"
        ring.close()

    def test_full(self):
        ring = native.SpscRing(4, 64)
        for i in range(4):
            assert ring.push(bytes([i]))
        assert not ring.push(b"x")  # full
        ring.close()

    def test_oversized_record(self):
        ring = native.SpscRing(4, 8)
        with pytest.raises(ValueError):
            ring.push(b"x" * 100)
        ring.close()

    def test_threaded_producer_consumer(self):
        ring = native.SpscRing(256, 64)
        n = 10000
        got = []

        def producer():
            for i in range(n):
                rec = i.to_bytes(4, "little")
                while not ring.push(rec):
                    pass

        def consumer():
            while len(got) < n:
                rec = ring.pop()
                if rec is not None:
                    got.append(int.from_bytes(rec, "little"))

        t1 = threading.Thread(target=producer)
        t2 = threading.Thread(target=consumer)
        t1.start()
        t2.start()
        t1.join(30)
        t2.join(30)
        assert got == list(range(n))
        ring.close()
