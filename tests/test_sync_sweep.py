"""End-to-end tensor_mux / tensor_merge sync-policy sweeps under jittered
and mismatched-rate timestamps.

Mirrors the reference's mux/merge SSAT groups
(/root/reference/tests/nnstreamer_mux, nnstreamer_merge, and
Documentation/synchronization-policies-at-mux-merge.md): two live-paced
streams at different rates flow through a mux/merge with each policy and
the emitted PTS/pairings are asserted — not just the CollectPads unit
behavior (tests/test_graph.py) but the element + threaded-pipeline path.
"""

import time
from fractions import Fraction

import numpy as np
import pytest

from nnstreamer_tpu.core import Caps
from nnstreamer_tpu.core.buffer import Buffer
from nnstreamer_tpu.core.types import TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline


def caps_of(dims, types, rate=Fraction(30, 1)):
    return Caps.tensors(TensorsConfig(
        TensorsInfo.from_strings(dims, types), rate))


def stamped(values, period_ns, jitter_ns=0, seed=0):
    """Buffers with PTS = i*period + jitter (deterministic)."""
    rng = np.random.default_rng(seed)
    out = []
    for i, v in enumerate(values):
        j = int(rng.integers(-jitter_ns, jitter_ns + 1)) if jitter_ns else 0
        out.append(Buffer.of(np.full((2,), v, np.float32),
                             pts=max(0, i * period_ns + j),
                             duration=period_ns))
    return out


def run_mux(fast, slow, sync_mode, sync_option=""):
    p = Pipeline()
    s1 = p.add_new("appsrc", caps=caps_of("2", "float32"), data=fast)
    s2 = p.add_new("appsrc", caps=caps_of("2", "float32"), data=slow)
    mux = p.add_new("tensor_mux", sync_mode=sync_mode,
                    sync_option=sync_option)
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(s1, mux)
    Pipeline.link(s2, mux)
    Pipeline.link(mux, sink)
    p.run(timeout=60)
    return sink


MS = 1_000_000


class TestMuxPolicies:
    def test_slowest_rate_mismatch(self):
        """30 Hz + 10 Hz under SLOWEST: output paced by the slow stream,
        fast-pad values are the freshest not-newer ones."""
        fast = stamped(range(12), 33 * MS)            # ~30 Hz
        slow = stamped(range(100, 104), 100 * MS)     # 10 Hz
        sink = run_mux(fast, slow, "slowest")
        assert 3 <= sink.num_buffers <= 5
        for b in sink.buffers:
            assert b.num_tensors == 2
            f, s = b.memories[0].host()[0], b.memories[1].host()[0]
            # paired fast frame: the latest with pts <= the slow frame's
            # pts (slow period = 3 fast periods, so index ~ 3*(s-100))
            assert f == pytest.approx(min(int(s - 100) * 3, 11), abs=1)

    def test_slowest_with_jitter_monotonic_pts(self):
        fast = stamped(range(30), 33 * MS, jitter_ns=5 * MS, seed=1)
        slow = stamped(range(10), 100 * MS, jitter_ns=5 * MS, seed=2)
        sink = run_mux(fast, slow, "slowest")
        pts = [b.pts for b in sink.buffers]
        assert pts == sorted(pts), "jitter must not reorder output PTS"
        assert sink.num_buffers >= 8

    def test_nosync_pairs_in_arrival_order(self):
        a = stamped(range(5), 33 * MS)
        b = stamped(range(10, 15), 100 * MS)
        sink = run_mux(a, b, "nosync")
        assert sink.num_buffers == 5
        for i, buf in enumerate(sink.buffers):
            assert buf.memories[0].host()[0] == i
            assert buf.memories[1].host()[0] == 10 + i

    def test_basepad_window_pairing(self):
        """BASEPAD on pad 0 with a 40 ms window: every output carries pad
        0's PTS; pad 1 contributes its closest in-window frame."""
        base = stamped(range(6), 100 * MS)
        other = stamped(range(50, 68), 33 * MS)
        sink = run_mux(base, other, "basepad", sync_option="0:40000000")
        assert sink.num_buffers >= 4
        base_pts = {b.pts for b in sink.buffers}
        want_pts = {i * 100 * MS for i in range(6)}
        assert base_pts <= want_pts, "basepad output must use base-pad PTS"

    def test_refresh_reuses_stale_pad(self):
        """REFRESH emits on every arrival, reusing the other pad's last."""
        a = stamped(range(3), 200 * MS)
        b = stamped(range(20, 29), 33 * MS)
        sink = run_mux(a, b, "refresh")
        # every pushed buffer pairs both pads even when one is stale
        assert sink.num_buffers >= 9
        for buf in sink.buffers:
            assert buf.num_tensors == 2


class TestMergePolicies:
    def test_merge_concat_first_with_sync(self):
        p = Pipeline()
        a = stamped([1, 2, 3], 100 * MS)
        b = stamped([9, 8, 7], 100 * MS)
        # (2,) tensors -> dims "2"; merge along innermost => (4,)
        s1 = p.add_new("appsrc", caps=caps_of("2", "float32"), data=a)
        s2 = p.add_new("appsrc", caps=caps_of("2", "float32"), data=b)
        mrg = p.add_new("tensor_merge", mode="linear", option="first",
                        sync_mode="slowest")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(s1, mrg)
        Pipeline.link(s2, mrg)
        Pipeline.link(mrg, sink)
        p.run(timeout=60)
        assert sink.num_buffers == 3
        first = sink.buffers[0].memories[0].host()
        assert first.shape == (4,)
        np.testing.assert_array_equal(first, [1, 1, 9, 9])

    def test_merge_concat_second_axis(self):
        """option=second concatenates along the 2nd-innermost dim: two
        (3, 2) tensors (dims 2:3) become (6, 2)."""
        p = Pipeline()

        def bufs(base):
            return [Buffer.of(
                np.full((3, 2), base + i, np.float32),
                pts=i * 100 * MS, duration=100 * MS) for i in range(2)]

        s1 = p.add_new("appsrc", caps=caps_of("2:3", "float32"),
                       data=bufs(0))
        s2 = p.add_new("appsrc", caps=caps_of("2:3", "float32"),
                       data=bufs(10))
        mrg = p.add_new("tensor_merge", mode="linear", option="second",
                        sync_mode="slowest")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(s1, mrg)
        Pipeline.link(s2, mrg)
        Pipeline.link(mrg, sink)
        p.run(timeout=60)
        assert sink.num_buffers == 2
        out = sink.buffers[0].memories[0].host()
        assert out.shape == (6, 2)
        np.testing.assert_array_equal(
            out, np.concatenate([np.full((3, 2), 0), np.full((3, 2), 10)]))

    def test_merge_rejects_rank_mismatch(self):
        from nnstreamer_tpu.graph.pipeline import PipelineError

        p = Pipeline()
        s1 = p.add_new("appsrc", caps=caps_of("2", "float32"),
                       data=stamped([1], 33 * MS))
        s2 = p.add_new("appsrc", caps=caps_of("2:3", "float32"),
                       data=[Buffer.of(np.zeros((3, 2), np.float32),
                                       pts=0, duration=33 * MS)])
        mrg = p.add_new("tensor_merge", mode="linear", option="first",
                        sync_mode="nosync")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(s1, mrg)
        Pipeline.link(s2, mrg)
        Pipeline.link(mrg, sink)
        with pytest.raises((PipelineError, ValueError)):
            p.run(timeout=30)


class TestMuxThreeStreams:
    def test_three_pads_slowest(self):
        """Reference SSAT exercises 3-4 stream muxes; pairing must hold
        with a third, slowest stream driving the cadence."""
        p = Pipeline()
        streams = [stamped(range(9), 33 * MS),
                   stamped(range(10, 16), 50 * MS),
                   stamped(range(20, 23), 100 * MS)]
        mux = p.add_new("tensor_mux", sync_mode="slowest")
        for st in streams:
            src = p.add_new("appsrc", caps=caps_of("2", "float32"), data=st)
            Pipeline.link(src, mux)
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(mux, sink)
        p.run(timeout=60)
        assert sink.num_buffers >= 2
        for b in sink.buffers:
            assert b.num_tensors == 3
        pts = [b.pts for b in sink.buffers]
        assert pts == sorted(pts)
