"""MQTT 3.1.1 wire-protocol tests (query/mqtt.py + pubsub elements).

Mirrors the reference's MQTT element tests (tests/gstreamer_mqtt/
unittest_mqtt_w_helper.cc uses a mocked paho; here the protocol itself is
asserted against scripted sockets — real 3.1.1 frames, reference-exact
GstMQTTMessageHdr layout per mqttcommon.h:29-63, and ntputil.c SNTP
conversion semantics)."""

import socket
import struct
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.query import mqtt


class TestPacketCodec:
    def test_remaining_length_varint(self):
        for n, expect in [(0, b"\x00"), (127, b"\x7f"),
                          (128, b"\x80\x01"), (16383, b"\xff\x7f"),
                          (268435455, b"\xff\xff\xff\x7f")]:
            assert mqtt.encode_remaining_length(n) == expect
        with pytest.raises(ValueError):
            mqtt.encode_remaining_length(268435456)

    def test_connect_roundtrip(self):
        pkt = mqtt.encode_connect("cl1", keep_alive=30)
        # fixed header: type 1, flags 0
        assert pkt[0] == 0x10
        # body parses back
        body = pkt[2:]
        info = mqtt.parse_connect(body)
        assert info == {"level": 4, "clean_session": True,
                        "keep_alive": 30, "client_id": "cl1"}

    def test_publish_roundtrip(self):
        pkt = mqtt.encode_publish("a/b", b"payload")
        assert pkt[0] == 0x30
        topic, payload, qos, pid = mqtt.parse_publish(pkt[0] & 0xF, pkt[2:])
        assert (topic, payload, qos, pid) == ("a/b", b"payload", 0, 0)

    def test_publish_qos1_has_packet_id_and_broker_pubacks(self):
        pkt = mqtt.encode_publish("t", b"x", qos=1, packet_id=42)
        topic, payload, qos, pid = mqtt.parse_publish((pkt[0]) & 0xF, pkt[2:])
        assert (qos, pid) == (1, 42)
        broker = mqtt.MqttBroker(port=0).start()
        try:
            c = mqtt.MqttClient(broker.host, broker.port, "q1")
            c.sock.sendall(pkt)
            ptype, _, body = mqtt.read_packet(c.sock)
            assert ptype == mqtt.PUBACK
            assert struct.unpack(">H", body)[0] == 42
            c.close()
        finally:
            broker.stop()

    def test_subscribe_flags_and_roundtrip(self):
        pkt = mqtt.encode_subscribe(7, [("t/+/x", 0), ("u/#", 0)])
        assert pkt[0] == 0x82  # reserved flags 0010 (spec 3.8.1)
        pid, topics = mqtt.parse_subscribe(pkt[2:])
        assert pid == 7 and topics == [("t/+/x", 0), ("u/#", 0)]

    def test_topic_wildcards(self):
        assert mqtt.topic_matches("a/+/c", "a/b/c")
        assert not mqtt.topic_matches("a/+/c", "a/b/d")
        assert mqtt.topic_matches("a/#", "a/b/c/d")
        assert mqtt.topic_matches("#", "anything/at/all")
        assert not mqtt.topic_matches("a/b", "a/b/c")
        assert not mqtt.topic_matches("a/b/c", "a/b")


class TestMessageHdr:
    def test_layout_offsets_match_reference(self):
        """mqttcommon.h:29-63: 1024 total; num_mems@0, size_mems@8 (after
        4-byte alignment pad), epochs@136/144, duration/dts/pts@152-176,
        caps@176 (512 bytes)."""
        hdr = mqtt.MessageHdr(
            num_mems=2, size_mems=(10, 20), base_time_epoch=111,
            sent_time_epoch=222, duration=5, dts=6, pts=7, caps_str="caps!")
        raw = hdr.pack()
        assert len(raw) == 1024
        assert struct.unpack_from("<I", raw, 0)[0] == 2
        assert struct.unpack_from("<Q", raw, 8)[0] == 10
        assert struct.unpack_from("<Q", raw, 16)[0] == 20
        assert struct.unpack_from("<q", raw, 136)[0] == 111
        assert struct.unpack_from("<q", raw, 144)[0] == 222
        assert struct.unpack_from("<Q", raw, 152)[0] == 5
        assert struct.unpack_from("<Q", raw, 160)[0] == 6
        assert struct.unpack_from("<Q", raw, 168)[0] == 7
        assert raw[176:181] == b"caps!"

    def test_none_timestamps_use_clock_time_none(self):
        raw = mqtt.MessageHdr(num_mems=0).pack()
        assert struct.unpack_from("<Q", raw, 168)[0] == 0xFFFFFFFFFFFFFFFF
        back = mqtt.MessageHdr.unpack(raw)
        assert back.pts is None and back.dts is None and back.duration is None

    def test_roundtrip(self):
        hdr = mqtt.MessageHdr(num_mems=3, size_mems=(1, 2, 3),
                              base_time_epoch=-5, sent_time_epoch=9,
                              pts=123, caps_str="other/tensors")
        back = mqtt.MessageHdr.unpack(hdr.pack())
        assert back == hdr

    def test_unpack_rejects_garbage(self):
        with pytest.raises(ValueError):
            mqtt.MessageHdr.unpack(b"short")
        bad = bytearray(mqtt.MessageHdr(num_mems=0).pack())
        struct.pack_into("<I", bad, 0, 17)  # > GST_MQTT_MAX_NUM_MEMS
        with pytest.raises(ValueError):
            mqtt.MessageHdr.unpack(bytes(bad))


class TestScriptedSocketProtocol:
    """Raw-socket assertions: the broker answers hand-built MQTT 3.1.1
    frames byte-for-byte (no client library involved)."""

    def test_connect_subscribe_publish_wire_format(self):
        broker = mqtt.MqttBroker(port=0).start()
        try:
            sub = socket.create_connection((broker.host, broker.port), 5)
            # hand-built CONNECT: MQTT, level 4, clean session, id "s"
            body = (b"\x00\x04MQTT\x04\x02\x00\x3c" + b"\x00\x01s")
            sub.sendall(bytes([0x10, len(body)]) + body)
            connack = sub.recv(4)
            assert connack == b"\x20\x02\x00\x00"
            # SUBSCRIBE pid=1 "t" qos0 → SUBACK pid=1 rc=0
            sbody = b"\x00\x01" + b"\x00\x01t" + b"\x00"
            sub.sendall(bytes([0x82, len(sbody)]) + sbody)
            assert sub.recv(5) == b"\x90\x03\x00\x01\x00"

            pub = socket.create_connection((broker.host, broker.port), 5)
            body = (b"\x00\x04MQTT\x04\x02\x00\x3c" + b"\x00\x01p")
            pub.sendall(bytes([0x10, len(body)]) + body)
            assert pub.recv(4) == b"\x20\x02\x00\x00"
            pbody = b"\x00\x01t" + b"hello"
            pub.sendall(bytes([0x30, len(pbody)]) + pbody)

            sub.settimeout(5)
            frame = sub.recv(64)
            assert frame == bytes([0x30, len(pbody)]) + pbody
        finally:
            broker.stop()

    def test_bad_protocol_level_refused(self):
        broker = mqtt.MqttBroker(port=0).start()
        try:
            c = socket.create_connection((broker.host, broker.port), 5)
            body = b"\x00\x04MQTT\x03\x02\x00\x3c" + b"\x00\x01x"  # level 3
            c.sendall(bytes([0x10, len(body)]) + body)
            assert c.recv(4) == b"\x20\x02\x00\x01"  # unacceptable version
        finally:
            broker.stop()


class TestClientBroker:
    def test_pub_sub_ping_unsubscribe(self):
        broker = mqtt.MqttBroker(port=0).start()
        try:
            sub = mqtt.MqttClient(broker.host, broker.port, "sub")
            pub = mqtt.MqttClient(broker.host, broker.port, "pub")
            sub.subscribe("sensors/+/temp")
            pub.publish("sensors/k1/temp", b"21.5")
            got = sub.recv_publish(timeout=5)
            assert got == ("sensors/k1/temp", b"21.5")
            assert pub.ping()
            # unsubscribe stops delivery
            sub.sock.sendall(mqtt.encode_unsubscribe(9, ["sensors/+/temp"]))
            ptype, _, body = mqtt.read_packet(sub.sock)
            assert ptype == mqtt.UNSUBACK
            pub.publish("sensors/k1/temp", b"22")
            assert sub.recv_publish(timeout=0.4) is None
            sub.close()
            pub.close()
        finally:
            broker.stop()


class TestSntp:
    def test_ntp_epoch_from_scripted_server(self):
        """Scripted UDP NTP server returns a fixed transmit timestamp; the
        conversion must match ntputil.c:211-229 exactly."""
        srv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        srv.bind(("127.0.0.1", 0))
        host, port = srv.getsockname()
        sec = mqtt.NTP_DELTA + 1_700_000_000
        frac = 0x80000000  # 0.5s

        def serve():
            data, addr = srv.recvfrom(64)
            assert data[0] == 0x1B
            resp = bytearray(48)
            struct.pack_into(">II", resp, 40, sec, frac)
            srv.sendto(bytes(resp), addr)

        t = threading.Thread(target=serve, daemon=True)
        t.start()
        got = mqtt.ntp_epoch_us([(host, port)])
        expect = 1_700_000_000 * 1_000_000 + int(
            frac / 4294967295.0 * 1_000_000)
        assert got == expect
        srv.close()

    def test_ntp_invalid_timestamp_rejected(self):
        srv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        srv.bind(("127.0.0.1", 0))
        host, port = srv.getsockname()

        def serve():
            data, addr = srv.recvfrom(64)
            srv.sendto(bytes(48), addr)  # all-zero → sec <= delta

        threading.Thread(target=serve, daemon=True).start()
        with pytest.raises(OSError):
            mqtt.ntp_epoch_us([(host, port)])
        srv.close()

    def test_get_epoch_falls_back_to_system_clock(self):
        # unroutable host port → fallback near time.time
        before = time.time_ns() // 1000
        got = mqtt.get_epoch_us([("127.0.0.1", 1)])
        after = time.time_ns() // 1000
        assert before <= got <= after + 10_000_000


class TestElementsOverRealMqtt:
    def test_tensor_stream_with_header_parity(self):
        """mqttsink publishes; a RAW MqttClient (not our element) receives
        and parses the reference-layout header + payload."""
        from fractions import Fraction

        from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
        from nnstreamer_tpu.graph import Pipeline

        broker = mqtt.MqttBroker(port=0).start()
        try:
            watcher = mqtt.MqttClient(broker.host, broker.port, "watcher")
            watcher.subscribe("nns/#")

            tp = Pipeline("publisher")
            caps = Caps.tensors(TensorsConfig(
                TensorsInfo.from_strings("2:1", "float32"), 30))
            src = tp.add_new("appsrc", caps=caps,
                             data=[np.full((1, 2), 7.5, np.float32)])
            msink = tp.add_new("mqttsink", port=broker.port,
                               pub_topic="nns/t0")
            Pipeline.link(src, msink)
            tp.run(timeout=30)

            got = watcher.recv_publish(timeout=5)
            assert got is not None
            topic, payload = got
            assert topic == "nns/t0"
            hdr = mqtt.MessageHdr.unpack(payload)
            assert hdr.num_mems == 1
            assert hdr.size_mems == (8,)
            assert "other/tensors" in hdr.caps_str
            assert "dimensions=(string)2:1" in hdr.caps_str
            vals = np.frombuffer(payload[1024:1032], np.float32)
            np.testing.assert_array_equal(vals, [7.5, 7.5])
            assert hdr.sent_time_epoch > 0
            watcher.close()
        finally:
            broker.stop()


class TestKeepAlive:
    def test_idle_client_sends_pingreq(self):
        """§3.1.2.10: a client silent for 1.5x keep-alive gets dropped by
        real brokers; our client must PINGREQ when idle past half the
        interval (receiving doesn't count as activity)."""
        broker = mqtt.MqttBroker(port=0).start()
        try:
            c = mqtt.MqttClient(broker.host, broker.port, "ka", keep_alive=1)
            c.subscribe("t")
            t0 = time.monotonic()
            # poll well past keep_alive/2 with no traffic: the tick must
            # fire PINGREQ (and swallow the PINGRESP) without erroring
            while time.monotonic() - t0 < 1.2:
                assert c.recv_publish(timeout=0.1) is None
            assert c._last_send > t0, "no PINGREQ was sent while idle"
            c.close()
        finally:
            broker.stop()


class TestHeaderLimits:
    def test_pack_rejects_too_many_memories(self):
        with pytest.raises(ValueError, match="GST_MQTT_MAX_NUM_MEMS"):
            mqtt.MessageHdr(num_mems=17, size_mems=tuple(range(17))).pack()


class TestSparseLink:
    def test_sparse_compressed_stream(self):
        """mqttsink sparse=true ships sparse-encoded memories under
        format=sparse caps (reference tensor_sparse link compression);
        subscriber transparently decodes to dense."""
        from fractions import Fraction

        from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
        from nnstreamer_tpu.graph import Pipeline

        broker = mqtt.MqttBroker(port=0).start()
        try:
            rp = Pipeline("rx")
            msrc = rp.add_new("mqttsrc", port=broker.port, sub_topic="s")
            rsink = rp.add_new("tensor_sink", store=True)
            Pipeline.link(msrc, rsink)
            rp.start()
            time.sleep(0.3)

            dense = np.zeros((64, 64), np.float32)
            dense[3, 7] = 42.0
            watcher = mqtt.MqttClient(broker.host, broker.port, "w")
            watcher.subscribe("s")
            tp = Pipeline("tx")
            caps = Caps.tensors(TensorsConfig(
                TensorsInfo.from_strings("64:64", "float32"),
                Fraction(30, 1)))
            src = tp.add_new("appsrc", caps=caps, data=[dense])
            msink = tp.add_new("mqttsink", port=broker.port, pub_topic="s",
                               sparse=True)
            Pipeline.link(src, msink)
            tp.run(timeout=30)

            got = watcher.recv_publish(timeout=5)
            assert got is not None
            hdr = mqtt.MessageHdr.unpack(got[1])
            assert "sparse" in hdr.caps_str
            assert hdr.size_mems[0] < dense.nbytes // 4  # compressed
            deadline = time.monotonic() + 10
            while rsink.num_buffers < 1 and time.monotonic() < deadline:
                time.sleep(0.05)
            rp.stop()
            np.testing.assert_array_equal(
                rsink.buffers[0].memories[0].host(), dense)
            watcher.close()
        finally:
            broker.stop()

    def test_sparse_preserves_config_and_survives_corruption(self):
        """Sparse wire carries the dense dims/types/rate; a corrupt sparse
        message is dropped, not fatal to the subscription."""
        from fractions import Fraction

        from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
        from nnstreamer_tpu.graph import Pipeline

        broker = mqtt.MqttBroker(port=0).start()
        try:
            rp = Pipeline("rx")
            msrc = rp.add_new("mqttsrc", port=broker.port, sub_topic="s2")
            rsink = rp.add_new("tensor_sink", store=True)
            Pipeline.link(msrc, rsink)
            rp.start()
            time.sleep(0.3)

            # 1: corrupt sparse message straight to the topic
            evil = mqtt.MqttClient(broker.host, broker.port, "evil")
            hdr = mqtt.MessageHdr(
                num_mems=1, size_mems=(16,), sent_time_epoch=1,
                caps_str='other/tensors,format=(string)sparse,'
                         'dimensions=(string)4:4,types=(string)float32')
            evil.publish("s2", hdr.pack() + b"\xff" * 16)

            # 2: then a valid sparse frame from the element
            dense = np.zeros((4, 4), np.float32)
            dense[1, 2] = 5.0
            tp = Pipeline("tx")
            caps = Caps.tensors(TensorsConfig(
                TensorsInfo.from_strings("4:4", "float32"),
                Fraction(25, 1)))
            src = tp.add_new("appsrc", caps=caps, data=[dense])
            msink = tp.add_new("mqttsink", port=broker.port,
                               pub_topic="s2", sparse=True)
            Pipeline.link(src, msink)
            tp.run(timeout=30)

            deadline = time.monotonic() + 10
            while rsink.num_buffers < 1 and time.monotonic() < deadline:
                time.sleep(0.05)
            rp.stop()
            assert rsink.num_buffers == 1  # corrupt one dropped, good kept
            b = rsink.buffers[0]
            np.testing.assert_array_equal(b.memories[0].host(), dense)
            assert b.config is not None
            assert b.config.rate == Fraction(25, 1)
            evil.close()
        finally:
            broker.stop()


class TestBrokerLifecycle:
    """Regression (nnslint concurrency/thread-join): stop() must join
    the accept thread — returning while it is still inside its bounded
    accept() keeps the LISTEN socket alive past close(), so an
    immediate rebind of the same port races EADDRINUSE."""

    def test_stop_joins_accept_thread_and_frees_port(self):
        broker = mqtt.MqttBroker(port=0).start()
        port = broker.port
        worker = broker._thread
        assert worker is not None and worker.is_alive()
        broker.stop()
        assert broker._thread is None
        assert not worker.is_alive()
        # deterministic rebind of the very same port
        broker2 = mqtt.MqttBroker(port=port).start()
        try:
            c = mqtt.MqttClient(broker2.host, broker2.port, "rebind")
            c.close()
        finally:
            broker2.stop()

    def test_stop_is_reentrant(self):
        broker = mqtt.MqttBroker(port=0).start()
        broker.stop()
        broker.stop()  # second stop: no thread left, must not raise
