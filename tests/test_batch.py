"""tensor_batch / tensor_unbatch — adaptive micro-batching serving path.

No reference equivalent (the converter's frames-per-tensor is static and
leaves the stream batched); this is the TPU serving capability that
amortizes per-frame H2D transfer overhead. Covered here:
group-and-restore exactness, partial-group EOS flush, budget-deadline
flush, PTS/offset restoration, device-resident unbatch slices, and the
full converter→batch→filter→unbatch→decoder pipeline.
"""

import time
from fractions import Fraction

import numpy as np
import pytest

from nnstreamer_tpu.core import Caps
from nnstreamer_tpu.core.types import TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline


def _tensor_caps(dims: str, types: str, rate=Fraction(30, 1)) -> Caps:
    return Caps.tensors(TensorsConfig(
        TensorsInfo.from_strings(dims, types), rate))


def _frames(n, shape=(1, 4, 4, 3), seed=0):
    rng = np.random.default_rng(seed)
    return [rng.normal(size=shape).astype(np.float32) for _ in range(n)]


def _scaler(max_batch, hw=4):
    return (f"zoo://scaler?scale=2&dims=3:{hw}:{hw}:{max_batch}"
            "&types=float32")


def run_batched(frames, max_batch, budget_ms=1000.0, model=None):
    model = model or _scaler(max_batch)
    p = Pipeline()
    dims = ":".join(str(d) for d in reversed(frames[0].shape))
    src = p.add_new("appsrc", caps=_tensor_caps(dims, "float32"),
                    data=frames)
    bat = p.add_new("tensor_batch", max_batch=max_batch, budget_ms=budget_ms)
    filt = p.add_new("tensor_filter", framework="xla-tpu", model=model)
    unb = p.add_new("tensor_unbatch")
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, bat, filt, unb, sink)
    p.run(timeout=60)
    return sink


class TestBatchUnbatch:
    def test_full_groups_exact_and_per_frame(self):
        frames = _frames(12)
        sink = run_batched(frames, max_batch=4)
        assert sink.num_buffers == 12
        for i, buf in enumerate(sink.buffers):
            np.testing.assert_allclose(
                buf.memories[0].host(), frames[i] * 2, rtol=1e-6)

    def test_partial_group_flushed_at_eos(self):
        frames = _frames(10)
        sink = run_batched(frames, max_batch=4)
        # 4+4+2: the trailing partial group must be flushed, pad dropped
        assert sink.num_buffers == 10
        np.testing.assert_allclose(
            sink.buffers[-1].memories[0].host(), frames[-1] * 2, rtol=1e-6)

    def test_single_frame_stream(self):
        frames = _frames(1)
        sink = run_batched(frames, max_batch=8)
        assert sink.num_buffers == 1
        np.testing.assert_allclose(
            sink.buffers[0].memories[0].host(), frames[0] * 2, rtol=1e-6)

    def test_budget_deadline_flushes_partial_group(self):
        frames = _frames(6)

        def trickle():
            yield from frames[:2]
            time.sleep(0.6)  # well past the 150 ms budget
            yield from frames[2:]

        p = Pipeline()
        src = p.add_new("appsrc", caps=_tensor_caps("3:4:4:1", "float32"),
                        data=trickle())
        bat = p.add_new("tensor_batch", max_batch=4, budget_ms=150.0)
        filt = p.add_new("tensor_filter", framework="xla-tpu",
                         model=_scaler(4))
        unb = p.add_new("tensor_unbatch")
        sink = p.add_new("tensor_sink", store=True)
        arrivals = []
        sink.new_data = lambda buf: arrivals.append(time.monotonic())
        Pipeline.link(src, bat, filt, unb, sink)
        p.run(timeout=60)
        assert sink.num_buffers == 6
        # first two frames must arrive well before the post-sleep batch:
        # the budget deadline, not EOS, flushed them
        assert arrivals[1] - arrivals[0] < 0.3
        assert arrivals[2] - arrivals[1] > 0.2
        for i, buf in enumerate(sink.buffers):
            np.testing.assert_allclose(
                buf.memories[0].host(), frames[i] * 2, rtol=1e-6)

    def test_pts_and_offset_restored(self):
        frames = _frames(6)
        p = Pipeline()
        src = p.add_new("appsrc", caps=_tensor_caps("3:4:4:1", "float32"),
                        data=frames, framerate=Fraction(30, 1))
        bat = p.add_new("tensor_batch", max_batch=3, budget_ms=1000.0)
        unb = p.add_new("tensor_unbatch")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, bat, unb, sink)
        p.run(timeout=60)
        assert sink.num_buffers == 6
        pts = [b.pts for b in sink.buffers]
        assert pts == sorted(pts) and len(set(pts)) == 6
        assert pts[1] - pts[0] == pytest.approx(1e9 / 30, rel=1e-3)

    def test_unbatch_slices_stay_device_resident(self):
        frames = _frames(4)
        sink = run_batched(frames, max_batch=4)
        assert all(b.memories[0].is_device for b in sink.buffers), \
            "unbatch must slice on device, not round-trip through host"

    def test_batched_buffer_metadata(self):
        frames = _frames(5)
        p = Pipeline()
        src = p.add_new("appsrc", caps=_tensor_caps("3:4:4:1", "float32"),
                        data=frames)
        bat = p.add_new("tensor_batch", max_batch=4, budget_ms=1000.0)
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, bat, sink)
        p.run(timeout=60)
        assert sink.num_buffers == 2
        full, partial = sink.buffers
        assert full.meta["batch_n"] == 4 and full.meta["batch_frames"] == 4
        assert partial.meta["batch_n"] == 1 and partial.meta["batch_frames"] == 4
        # padded group still carries the full static shape
        assert partial.memories[0].host().shape == (4, 4, 4, 3)
        np.testing.assert_allclose(partial.memories[0].host()[:1], frames[4])

    def test_unbatch_passthrough_without_metadata(self):
        frames = _frames(3)
        p = Pipeline()
        src = p.add_new("appsrc", caps=_tensor_caps("3:4:4:1", "float32"),
                        data=frames)
        unb = p.add_new("tensor_unbatch")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, unb, sink)
        p.run(timeout=60)
        assert sink.num_buffers == 3

    def test_invalid_max_batch_rejected(self):
        with pytest.raises(ValueError):
            Pipeline().add_new("tensor_batch", max_batch=0)

    def test_caps_renegotiation_flushes_pending_group(self):
        """A mid-stream caps change must flush the old-shape partial group
        under the OLD config before the new caps reach downstream."""
        from nnstreamer_tpu.core.buffer import Buffer
        from nnstreamer_tpu.graph.element import make_element
        from nnstreamer_tpu.graph.events import Event

        bat = make_element("tensor_batch", max_batch=4, budget_ms=10000.0)
        sink = make_element("tensor_sink", store=True)
        Pipeline.link(bat, sink)
        sink.start()
        bat.start()
        try:
            caps_a = _tensor_caps("3:4:4:1", "float32")
            bat._event_entry(bat.sink_pad, Event.caps(caps_a))
            old = [np.full((1, 4, 4, 3), i, np.float32) for i in range(2)]
            for f in old:
                bat._chain_entry(bat.sink_pad, Buffer.of(f))
            caps_b = _tensor_caps("3:8:8:1", "float32")
            bat._event_entry(bat.sink_pad, Event.caps(caps_b))
            bat._chain_entry(bat.sink_pad,
                             Buffer.of(np.full((1, 8, 8, 3), 9, np.float32)))
            bat._event_entry(bat.sink_pad, Event.eos())
            deadline = time.monotonic() + 10
            while sink.num_buffers < 2 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert sink.num_buffers == 2
            first, second = sink.buffers
            # old-shape group flushed with old dims, before the new stream
            assert first.memories[0].host().shape == (4, 4, 4, 3)
            assert first.meta["batch_n"] == 2
            assert first.config.info[0].shape == (4, 4, 4, 3)
            assert second.memories[0].host().shape == (4, 8, 8, 3)
            assert second.config.info[0].shape == (4, 8, 8, 3)
        finally:
            bat.stop()

    def test_unbatch_caps_renegotiation_refreshes_config(self):
        from nnstreamer_tpu.core.buffer import Buffer
        from nnstreamer_tpu.graph.element import make_element
        from nnstreamer_tpu.graph.events import Event

        unb = make_element("tensor_unbatch")
        sink = make_element("tensor_sink", store=True)
        Pipeline.link(unb, sink)

        def batched(shape, n):
            arr = np.zeros(shape, np.float32)
            return Buffer.of(arr, meta={"batch_frames": 2, "batch_n": n,
                                        "batch_pts": [0] * n})

        unb._event_entry(unb.sink_pad, Event.caps(_tensor_caps("3:4:4:2",
                                                               "float32")))
        unb._chain_entry(unb.sink_pad, batched((2, 4, 4, 3), 2))
        assert sink.buffers[-1].config.info[0].shape == (1, 4, 4, 3)
        unb._event_entry(unb.sink_pad, Event.caps(_tensor_caps("3:8:8:2",
                                                               "float32")))
        unb._chain_entry(unb.sink_pad, batched((2, 8, 8, 3), 1))
        assert sink.num_buffers == 3
        assert sink.buffers[-1].config.info[0].shape == (1, 8, 8, 3), \
            "per-frame config must refresh after renegotiation"

    def test_unbatch_passthrough_forwards_caps(self):
        frames = _frames(3)
        p = Pipeline()
        src = p.add_new("appsrc", caps=_tensor_caps("3:4:4:1", "float32"),
                        data=frames)
        unb = p.add_new("tensor_unbatch")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, unb, sink)
        p.run(timeout=60)
        assert sink.num_buffers == 3
        assert sink.sink_pad.caps is not None, \
            "passthrough must still forward caps downstream"


class TestBatchedServingPipeline:
    def test_video_to_labels_end_to_end(self, tmp_path):
        """converter → batch → model → unbatch → decoder: per-frame labels
        equal the unbatched pipeline's output."""
        labels = tmp_path / "labels.txt"
        labels.write_text("\n".join(f"l{i}" for i in range(16)))
        rng = np.random.default_rng(7)
        frames = [rng.integers(0, 255, (32, 32, 3)).astype(np.uint8)
                  for _ in range(10)]
        video_caps = Caps("video/x-raw", {
            "format": "RGB", "width": 32, "height": 32,
            "framerate": Fraction(0, 1)})
        results = {}
        for key, batched in (("ref", False), ("batched", True)):
            p = Pipeline()
            src = p.add_new("appsrc", caps=video_caps, data=frames)
            conv = p.add_new("tensor_converter")
            chain = [src, conv]
            if batched:
                chain.append(p.add_new("tensor_batch", max_batch=4,
                                       budget_ms=1000.0))
            chain.append(p.add_new(
                "tensor_filter", framework="xla-tpu",
                model="zoo://mobilenet_v2?width=0.25&size=32&num_classes=16"
                      f"&dtype=float32&batch={4 if batched else 1}"))
            if batched:
                chain.append(p.add_new("tensor_unbatch"))
            chain.append(p.add_new("tensor_decoder", mode="image_labeling",
                                   option1=str(labels)))
            sink = p.add_new("tensor_sink", store=True)
            chain.append(sink)
            Pipeline.link(*chain)
            p.run(timeout=120)
            results[key] = [bytes(b.memories[0].host().tobytes())
                            for b in sink.buffers]
        assert len(results["batched"]) == 10
        assert results["batched"] == results["ref"]

    def test_ssd_detection_batched_equals_per_frame(self, tmp_path):
        """bounding_box decode after tensor_unbatch (sliced batched model
        output) must byte-equal the per-frame pipeline's overlays."""
        from nnstreamer_tpu.models.ssd_mobilenet import write_box_priors

        priors = tmp_path / "p.txt"
        write_box_priors(str(priors), size=96)
        labels = tmp_path / "l.txt"
        labels.write_text("\n".join(f"c{i}" for i in range(6)))
        spec = ("zoo://ssd_mobilenet_v2?size=96&width=0.25&num_classes=6"
                "&dtype=float32")
        opts = dict(option1="mobilenet-ssd", option2=str(labels),
                    option3=str(priors), option4="96:96", option5="96:96")
        results = {}
        for key, batched in (("ref", 0), ("batched", 4)):
            p = Pipeline()
            src = p.add_new("videotestsrc", width=96, height=96,
                            num_buffers=6, pattern="random")
            conv = p.add_new("tensor_converter")
            chain = [src, conv]
            model = spec
            if batched:
                chain.append(p.add_new("tensor_batch", max_batch=batched,
                                       budget_ms=1000.0))
                model = spec + f"&batch={batched}"
            chain.append(p.add_new("tensor_filter", framework="xla-tpu",
                                   model=model))
            if batched:
                chain.append(p.add_new("tensor_unbatch"))
            chain.append(p.add_new("tensor_decoder", mode="bounding_box",
                                   **opts))
            sink = p.add_new("tensor_sink", store=True)
            chain.append(sink)
            Pipeline.link(*chain)
            p.run(timeout=180)
            results[key] = [b.memories[0].host().tobytes()
                            for b in sink.buffers]
        assert len(results["batched"]) == 6
        assert results["batched"] == results["ref"]


class TestAutoBudget:
    def test_auto_budget_fills_groups_at_steady_rate(self):
        """budget_ms=0: with a ~4ms-interval source and max_batch=4, the
        adaptive window must let groups FILL (fill ratio near 1), where a
        fixed 5ms budget would flush partial pairs."""
        import time as _t

        import numpy as np

        from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
        from nnstreamer_tpu.graph import Pipeline

        def timed_gen():
            for i in range(24):
                _t.sleep(0.004)
                yield np.full((1, 4), float(i), np.float32)

        p = Pipeline()
        caps = Caps.tensors(TensorsConfig(
            TensorsInfo.from_strings("4:1", "float32")))
        src = p.add_new("appsrc", caps=caps, data=timed_gen())
        bat = p.add_new("tensor_batch", max_batch=4, budget_ms=0)
        unb = p.add_new("tensor_unbatch")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, bat, unb, sink)
        p.run(timeout=60)
        assert sink.num_buffers == 24
        # pad-waste observability: fill ratio = frames / (groups * max)
        assert bat.frames_grouped == 24
        fill = bat.frames_grouped / (bat.groups_emitted * 4)
        assert fill >= 0.6, (bat.groups_emitted, fill)

    def test_auto_budget_lone_frame_not_stuck(self):
        """An idle stream's lone frame flushes within the clamped window,
        not never."""
        import numpy as np

        from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
        from nnstreamer_tpu.graph import Pipeline

        p = Pipeline()
        caps = Caps.tensors(TensorsConfig(
            TensorsInfo.from_strings("4:1", "float32")))
        src = p.add_new("appsrc", caps=caps,
                        data=[np.ones((1, 4), np.float32)])
        bat = p.add_new("tensor_batch", max_batch=8, budget_ms=0)
        unb = p.add_new("tensor_unbatch")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, bat, unb, sink)
        p.run(timeout=60)
        assert sink.num_buffers == 1


class TestTenantAwareBudget:
    """sched_enroll-aware flush budget: a backed-up DeviceEngine shrinks
    the batching window (holding frames to fill a group while the device
    queue is deep only stacks latency). Fake clock + fake engine — no
    real sleeps, no device."""

    class _FakeEngine:
        def __init__(self, depth=0):
            self.depth = depth

        def pending(self):
            return self.depth

    class _FakeClock:
        def __init__(self):
            self.t = 100.0

        def __call__(self):
            return self.t

        def advance(self, dt):
            self.t += dt

    def _element(self, **props):
        from nnstreamer_tpu.elements.batch import TensorBatch
        return TensorBatch(**props)

    def test_fixed_budget_unchanged_without_engine(self):
        el = self._element(max_batch=8, budget_ms=100.0)
        assert el._budget_s() == 0.1

    def test_engine_depth_shrinks_budget(self):
        el = self._element(max_batch=8, budget_ms=100.0)
        eng = self._FakeEngine(depth=8)
        el.sched_enroll(eng, tenant=None)
        # depth == max_batch -> budget halves
        assert abs(el._budget_s() - 0.05) < 1e-9
        eng.depth = 24  # 3x max_batch -> quarter
        assert abs(el._budget_s() - 0.025) < 1e-9
        eng.depth = 0  # idle engine -> full window again
        assert el._budget_s() == 0.1

    def test_detach_restores_full_budget(self):
        el = self._element(max_batch=8, budget_ms=100.0)
        el.sched_enroll(self._FakeEngine(depth=16), tenant=None)
        assert el._budget_s() < 0.1
        el.sched_detach()
        assert el._budget_s() == 0.1
        assert el._sched_engine is None

    def test_engine_error_falls_back_to_full_budget(self):
        class _Broken:
            def pending(self):
                raise RuntimeError("engine mid-teardown")

        el = self._element(max_batch=8, budget_ms=100.0)
        el.sched_enroll(_Broken(), tenant=None)
        assert el._budget_s() == 0.1

    def test_auto_budget_with_fake_clock_and_load(self):
        """Drive the arrival EMA through the injectable clock: exactly
        4 ms gaps -> deterministic auto window, then engine depth
        shrinks it. No wall-clock sleeps anywhere."""
        import numpy as np

        from nnstreamer_tpu.core.buffer import Buffer

        el = self._element(max_batch=8, budget_ms=0)
        clock = self._FakeClock()
        el._clock = clock
        for i in range(6):
            el._enqueue(Buffer.from_arrays([np.ones((1, 4), np.float32)]))
            clock.advance(0.004)
        # EMA of a constant gap converges to the gap exactly
        assert abs(el._ema_interval - 0.004) < 1e-12
        base = el._budget_s()
        assert abs(base - min(max(1.3 * 8 * 0.004, 0.002), 0.5)) < 1e-9
        el.sched_enroll(self._FakeEngine(depth=16), tenant=None)
        assert abs(el._budget_s() - base / 3.0) < 1e-9

    def test_deadline_math_uses_injected_clock(self):
        """The worker's deadline arithmetic must run off self._clock so
        tests (and simulations) can drive time: replicate the _drain
        deadline expressions against the fake clock."""
        el = self._element(max_batch=8, budget_ms=50.0)
        clock = self._FakeClock()
        el._clock = clock
        deadline = el._clock() + el._budget_s()
        assert deadline == 100.05
        clock.advance(0.049)
        assert deadline - el._clock() > 0
        clock.advance(0.002)
        assert deadline - el._clock() <= 0
