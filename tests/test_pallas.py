"""Pallas kernel correctness (interpret mode on the CPU mesh; the same
kernels compile for TPU via pallas_call)."""

import numpy as np
import pytest

from nnstreamer_tpu.ops.pallas import preprocess as pp


class TestNormalize:
    def test_matches_reference(self):
        import jax.numpy as jnp

        x = np.random.default_rng(0).integers(0, 256, (2, 33, 47, 3)).astype(np.uint8)
        out = pp.normalize_u8(jnp.asarray(x), interpret=True, out_dtype=jnp.float32)
        ref = pp.normalize_u8_reference(jnp.asarray(x), 1 / 127.5, -1.0, jnp.float32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)
        assert out.shape == x.shape

    def test_nonaligned_sizes(self):
        import jax.numpy as jnp

        for shape in [(1,), (7, 13), (129,), (31, 127)]:
            x = np.ones(shape, np.uint8) * 200
            out = pp.normalize_u8(jnp.asarray(x), interpret=True,
                                  out_dtype=jnp.float32)
            np.testing.assert_allclose(np.asarray(out),
                                       (200 / 127.5 - 1.0) * np.ones(shape),
                                       rtol=1e-6)


class TestQuantize:
    def test_roundtrip(self):
        import jax.numpy as jnp

        x = np.random.default_rng(1).uniform(-1, 1, (16, 130)).astype(np.float32)
        q = pp.quantize_affine(jnp.asarray(x), scale=1 / 127.5, zero_point=128,
                               interpret=True)
        ref = pp.quantize_affine_reference(jnp.asarray(x), 1 / 127.5, 128)
        np.testing.assert_array_equal(np.asarray(q), np.asarray(ref))
        assert np.asarray(q).dtype == np.uint8
