"""Pallas kernel correctness (interpret mode on the CPU mesh; the same
kernels compile for TPU via pallas_call)."""

import numpy as np
import pytest

from nnstreamer_tpu.ops.pallas import preprocess as pp


class TestNormalize:
    def test_matches_reference(self):
        import jax.numpy as jnp

        x = np.random.default_rng(0).integers(0, 256, (2, 33, 47, 3)).astype(np.uint8)
        out = pp.normalize_u8(jnp.asarray(x), interpret=True, out_dtype=jnp.float32)
        ref = pp.normalize_u8_reference(jnp.asarray(x), 1 / 127.5, -1.0, jnp.float32)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)
        assert out.shape == x.shape

    def test_nonaligned_sizes(self):
        import jax.numpy as jnp

        for shape in [(1,), (7, 13), (129,), (31, 127)]:
            x = np.ones(shape, np.uint8) * 200
            out = pp.normalize_u8(jnp.asarray(x), interpret=True,
                                  out_dtype=jnp.float32)
            np.testing.assert_allclose(np.asarray(out),
                                       (200 / 127.5 - 1.0) * np.ones(shape),
                                       rtol=1e-6)


class TestQuantize:
    def test_roundtrip(self):
        import jax.numpy as jnp

        x = np.random.default_rng(1).uniform(-1, 1, (16, 130)).astype(np.float32)
        q = pp.quantize_affine(jnp.asarray(x), scale=1 / 127.5, zero_point=128,
                               interpret=True)
        ref = pp.quantize_affine_reference(jnp.asarray(x), 1 / 127.5, 128)
        np.testing.assert_array_equal(np.asarray(q), np.asarray(ref))
        assert np.asarray(q).dtype == np.uint8


class TestFlashAttention:
    """Blockwise causal attention kernel (ops/pallas/flash_attention.py)
    vs the dense reference, interpret mode on the CPU mesh."""

    @pytest.mark.parametrize("shape,causal", [
        ((1, 2, 64, 32), True),
        ((2, 1, 100, 16), True),     # non-block-multiple length
        ((1, 2, 64, 32), False),
        ((1, 1, 7, 8), True),        # shorter than one block
        ((1, 2, 100, 16), False),    # padded + full attention
    ])
    def test_matches_dense_reference(self, shape, causal):
        self._check(shape, causal, 32, 32)

    @pytest.mark.parametrize("bq,bk", [(16, 32), (32, 16), (16, 4), (4, 16)])
    def test_unequal_blocks(self, bq, bk):
        """block_q != block_k with L=40 padded to 128: sub-128 requests
        resolve to divisors of the padded length, so the multi-block
        tiling (and the whole-k-block causal skip) really executes —
        trailing keys must not drop / output rows must not go
        unwritten."""
        from nnstreamer_tpu.ops.pallas.flash_attention import _pick_block

        # guard the guard: both picks must stay sub-128 multi-block
        assert _pick_block(128, bq) > 1 and _pick_block(128, bq) <= bq
        assert _pick_block(128, bk) > 1 and _pick_block(128, bk) <= bk
        self._check((1, 1, 40, 16), True, bq, bk)
        self._check((1, 1, 40, 16), False, bq, bk)

    def _check(self, shape, causal, bq, bk):
        from nnstreamer_tpu.ops.pallas.flash_attention import flash_attention
        from nnstreamer_tpu.parallel.ring import reference_attention

        rng = np.random.default_rng(5)
        q, k, v = [rng.standard_normal(shape).astype(np.float32)
                   for _ in range(3)]
        out = np.asarray(flash_attention(q, k, v, causal=causal,
                                         block_q=bq, block_k=bk))
        ref = np.asarray(reference_attention(q, k, v, causal=causal))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)

    def test_lm_prefill_flash_equals_dense(self, monkeypatch):
        """NNS_LM_FLASH=1 swaps the prefill attention for the pallas
        kernel; logits and the emitted KV cache must match the dense
        path."""
        import jax

        from nnstreamer_tpu.models.causal_lm import init_causal_lm, lm_prefill

        params = init_causal_lm(jax.random.PRNGKey(0), vocab=64, d_model=32,
                                n_heads=4, n_layers=2, max_len=64)
        toks = np.asarray(
            np.random.default_rng(2).integers(0, 64, (2, 48)), np.int32)
        dense = lm_prefill(params, toks, n_heads=4, max_len=64)
        monkeypatch.setenv("NNS_LM_FLASH", "1")
        flash = lm_prefill(params, toks, n_heads=4, max_len=64)
        for a, b in zip(dense, flash):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)


def test_flash_head_dim_padding_numerics():
    """The real-TPU head-dim pad to 128 lanes must not change results
    (exercised in interpret mode via the test hook; sm_scale uses the
    TRUE head dim, not the padded one)."""
    from nnstreamer_tpu.ops.pallas.flash_attention import flash_attention
    from nnstreamer_tpu.parallel.ring import reference_attention

    rng = np.random.default_rng(11)
    q, k, v = [rng.standard_normal((1, 2, 48, 64)).astype(np.float32)
               for _ in range(3)]
    out = np.asarray(flash_attention(q, k, v, causal=True, block_q=16,
                                     block_k=16, _force_pad_d=True))
    assert out.shape == q.shape  # padded d columns sliced off
    ref = np.asarray(reference_attention(q, k, v, causal=True))
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_flash_bf16_inputs_tolerance():
    """bf16 q/k/v: the flash precision model (bf16 softmax weights, f32
    accumulate) tracks the f32 oracle to ~1e-2 relative."""
    import jax.numpy as jnp

    from nnstreamer_tpu.ops.pallas.flash_attention import flash_attention
    from nnstreamer_tpu.parallel.ring import reference_attention

    rng = np.random.default_rng(13)
    qf, kf, vf = [rng.standard_normal((1, 2, 64, 32)).astype(np.float32)
                  for _ in range(3)]
    out = np.asarray(flash_attention(
        jnp.asarray(qf, jnp.bfloat16), jnp.asarray(kf, jnp.bfloat16),
        jnp.asarray(vf, jnp.bfloat16), causal=True,
        block_q=16, block_k=16)).astype(np.float32)
    ref = np.asarray(reference_attention(qf, kf, vf, causal=True))
    np.testing.assert_allclose(out, ref, rtol=5e-2, atol=3e-2)
