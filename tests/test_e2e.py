"""End-to-end slice (SURVEY §7 step 6): the reference's golden pipeline shape
``videotestsrc ! tensor_converter ! tensor_transform ! tensor_filter !
tensor_decoder ! sink`` running a real flax model through the xla backend."""

import numpy as np
import pytest

from nnstreamer_tpu.graph import Pipeline
from nnstreamer_tpu.models.zoo import get_model


@pytest.fixture(scope="module")
def tiny_mobilenet():
    return get_model("zoo://mobilenet_v2?width=0.1&size=32&num_classes=5")


def test_classification_pipeline(tmp_path, tiny_mobilenet):
    labels = tmp_path / "labels.txt"
    labels.write_text("\n".join(f"class{i}" for i in range(5)))
    p = Pipeline()
    src = p.add_new("videotestsrc", width=32, height=32, num_buffers=3,
                    pattern="random", seed=7)
    conv = p.add_new("tensor_converter")
    filt = p.add_new("tensor_filter", framework="xla-tpu", model=tiny_mobilenet)
    dec = p.add_new("tensor_decoder", mode="image_labeling", option1=str(labels))
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, conv, filt, dec, sink)
    p.run(timeout=120)
    assert sink.num_buffers == 3
    for b in sink.buffers:
        assert b.meta["label"].startswith("class")
        assert 0 <= b.meta["label_index"] < 5
    assert filt.latency >= 0 or filt.stats.total_invoke_num == 3


def test_detection_pipeline(tmp_path):
    """SSD-style: model emits postprocessed boxes; bounding_box decodes."""
    import jax.numpy as jnp

    labels = tmp_path / "labels.txt"
    labels.write_text("thing\nother\n")

    def fake_ssd(x):
        b = x.shape[0]
        boxes = jnp.tile(jnp.array([[0.25, 0.25, 0.75, 0.75]], jnp.float32), (b, 1))
        boxes = boxes.reshape(b, 1, 4)
        classes = jnp.zeros((b, 1), jnp.float32)
        scores = jnp.full((b, 1), 0.95, jnp.float32)
        count = jnp.ones((b,), jnp.float32)
        return boxes, classes, scores, count

    p = Pipeline()
    src = p.add_new("videotestsrc", width=32, height=32, num_buffers=2)
    conv = p.add_new("tensor_converter")
    filt = p.add_new("tensor_filter", model=fake_ssd)
    dec = p.add_new("tensor_decoder", mode="bounding_box",
                    option1="mobilenet-ssd-postprocess", option2=str(labels),
                    option4="64:64", option5="32:32")
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, conv, filt, dec, sink)
    p.run(timeout=120)
    assert sink.num_buffers == 2
    dets = sink.buffers[0].meta["detections"]
    assert len(dets) == 1 and dets[0]["label"] == "thing"
    canvas = sink.buffers[0].memories[0].host()
    assert canvas.shape == (64, 64, 4)
    assert canvas[16, 16, 1] == 255  # green box corner at (0.25*64, 0.25*64)


def test_transform_filter_fused_chain_device_resident(tiny_mobilenet):
    """converter → transform(normalize) → filter stays on device end-to-end."""
    p = Pipeline()
    src = p.add_new("videotestsrc", width=32, height=32, num_buffers=2)
    conv = p.add_new("tensor_converter")
    tr = p.add_new("tensor_transform", mode="arithmetic",
                   option="typecast:float32,add:-127.5,div:127.5")
    filt = p.add_new("tensor_filter", model=lambda x: x.mean(axis=(1, 2, 3),
                                                            keepdims=False))
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, conv, tr, filt, sink)
    p.run(timeout=120)
    assert sink.buffers[0].memories[0].is_device
