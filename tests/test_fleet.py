"""obs.fleet tests — metric federation (merge, labels, conflicts,
expiry), remote span collection, fleet health/readiness rollup, the
query-wire OBS_PUSH piggyback, concurrent scrapes under a push storm,
and the zero-overhead-when-disabled contract."""

import json
import re
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from nnstreamer_tpu.graph import Pipeline
from nnstreamer_tpu.obs import events as obs_events
from nnstreamer_tpu.obs import fleet as obs_fleet
from nnstreamer_tpu.obs import health as obs_health
from nnstreamer_tpu.obs import metrics as obs_metrics
from nnstreamer_tpu.obs import tracing as obs_tracing
from nnstreamer_tpu.obs.exporter import start_exporter
from nnstreamer_tpu.obs.fleet import FleetAggregator, FleetPusher, build_push
from nnstreamer_tpu.obs.metrics import MetricsRegistry
from nnstreamer_tpu.obs.tracing import SpanStore
from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def caps_of(dims, types, rate=30):
    return Caps.tensors(
        TensorsConfig(TensorsInfo.from_strings(dims, types), rate))


@pytest.fixture
def events():
    ring = obs_events.ring()
    was = ring.is_enabled
    ring.reset()
    obs_events.enable()
    yield obs_events
    obs_events.disable()
    ring.reset()
    ring._enabled = was


@pytest.fixture
def fleet_off_after():
    """Whatever a test enables on the module globals, put it back."""
    tracing_was = obs_tracing.enabled()
    metrics_was = obs_metrics.enabled()
    yield obs_fleet
    obs_fleet.disable_push()
    obs_fleet.disable_aggregator()
    store = obs_tracing.store()
    store.set_export(False)
    store.reset()
    store._enabled = tracing_was
    (obs_metrics.enable if metrics_was else obs_metrics.disable)()


@pytest.fixture
def global_health():
    reg = obs_health.registry()
    was = reg.is_enabled
    reg.reset()
    yield obs_health
    reg.reset()
    reg._enabled = was


def worker_push(instance, seq=1, interval_s=2.0, counters=(), ready=True,
                status="ok", spans=(), role="worker"):
    """A synthetic worker's push document built through the real
    build_push path (private registries — no global state)."""
    reg = MetricsRegistry(enabled=True)
    for name, labels, value in counters:
        fam = reg.counter(name, "test", tuple(labels))
        (fam.labels(*labels.values()) if labels else fam).inc(value)
    doc = build_push(instance, role, seq, interval_s=interval_s,
                     registry=reg,
                     health_registry=obs_health.HealthRegistry(),
                     span_store=SpanStore())
    doc["ready"] = {"ready": ready, "conditions": {"up": ready}}
    doc["health"]["status"] = status
    doc["spans"] = list(spans)
    return doc


# --------------------------------------------------------------------------- #
# Prometheus text parser (test oracle)
# --------------------------------------------------------------------------- #

_SAMPLE_RE = re.compile(
    r'^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)'
    r'(?:\{(?P<labels>(?:[a-z_][a-z0-9_]*="(?:\\.|[^"\\])*",?)*)\})? '
    r'(?P<value>[0-9.eE+-]+|\+Inf|-Inf|NaN)$')


def parse_prom(text):
    """Strict 0.0.4 parse: returns {family: {"type", "help",
    "samples": [(name, labels_str, float)]}}; raises AssertionError on
    any malformed line, duplicated HELP/TYPE, or samples preceding
    their TYPE line."""
    fams = {}
    current = None
    for ln in text.splitlines():
        if not ln:
            continue
        if ln.startswith("# HELP "):
            name = ln.split(" ", 3)[2]
            assert name not in fams, f"duplicate HELP for {name}"
            fams[name] = {"type": None, "help": ln.split(" ", 3)[3],
                          "samples": []}
            current = name
        elif ln.startswith("# TYPE "):
            _, _, name, mtype = ln.split(" ", 3)
            fam = fams.setdefault(
                name, {"type": None, "help": "", "samples": []})
            assert fam["type"] is None, f"duplicate TYPE for {name}"
            fam["type"] = mtype
            current = name
        else:
            m = _SAMPLE_RE.match(ln)
            assert m, f"malformed sample line: {ln!r}"
            base = m.group("name")
            for suffix in ("_bucket", "_sum", "_count"):
                if base.endswith(suffix) and base[:-len(suffix)] in fams:
                    base = base[:-len(suffix)]
                    break
            assert current == base, f"sample {ln!r} outside its family"
            fams[base]["samples"].append(
                (m.group("name"), m.group("labels") or "",
                 float(m.group("value").replace("+Inf", "inf"))))
    return fams


def check_histograms_consistent(fams):
    """No torn histograms: per series, buckets cumulative
    non-decreasing and +Inf == _count."""
    for name, fam in fams.items():
        if fam["type"] != "histogram":
            continue
        series = {}
        for sname, labels, value in fam["samples"]:
            # (?<![a-z_]) keeps e.g. role="..." from matching as le="..."
            key = re.sub(r'(?<![a-z_])le="[^"]*",?', "",
                         labels).rstrip(",")
            entry = series.setdefault(key, {"buckets": [], "count": None})
            if sname.endswith("_bucket"):
                le = re.search(r'(?<![a-z_])le="([^"]*)"',
                               labels).group(1)
                entry["buckets"].append(
                    (float(le.replace("+Inf", "inf")), value))
            elif sname.endswith("_count"):
                entry["count"] = value
        for key, entry in series.items():
            entry["buckets"].sort()
            values = [v for _, v in entry["buckets"]]
            assert values == sorted(values), \
                f"{name}{{{key}}}: non-monotonic buckets {values}"
            assert entry["buckets"][-1][0] == float("inf")
            assert entry["buckets"][-1][1] == entry["count"], \
                f"{name}{{{key}}}: +Inf {entry['buckets'][-1][1]} " \
                f"!= count {entry['count']}"


# --------------------------------------------------------------------------- #
# Federation: merge + exposition
# --------------------------------------------------------------------------- #

class TestFederation:
    def test_merged_exposition_instance_labels(self):
        agg = FleetAggregator(span_store=SpanStore(), instance="agg:1")
        agg.ingest(worker_push(
            "w1:1", counters=[("nnstpu_query_messages_total",
                               {"direction": "sent"}, 3)]))
        agg.ingest(worker_push(
            "w2:1", counters=[("nnstpu_query_messages_total",
                               {"direction": "sent"}, 7)]))
        local = MetricsRegistry(enabled=True)
        local.counter("nnstpu_query_messages_total", "test",
                      ("direction",)).labels("recv").inc(10)
        text = agg.exposition(local)
        assert ('nnstpu_query_messages_total{direction="sent",'
                'instance="w1:1",role="worker"} 3') in text
        assert ('nnstpu_query_messages_total{direction="sent",'
                'instance="w2:1",role="worker"} 7') in text
        assert ('nnstpu_query_messages_total{direction="recv",'
                'instance="agg:1",role="aggregator"} 10') in text

    def test_help_type_once_per_family(self):
        """Satellite: HELP/TYPE exactly once per family even when the
        same family arrives from several instances — parse_prom raises
        on duplicates."""
        agg = FleetAggregator(span_store=SpanStore())
        for i in range(4):
            agg.ingest(worker_push(
                f"w{i}:1", counters=[("nnstpu_query_messages_total",
                                      {"direction": "sent"}, i)]))
        fams = parse_prom(agg.exposition(MetricsRegistry(enabled=True)))
        fam = fams["nnstpu_query_messages_total"]
        assert fam["type"] == "counter"
        assert len(fam["samples"]) == 4

    def test_histogram_merge_renders_buckets(self):
        reg = MetricsRegistry(enabled=True)
        h = reg.histogram("nnstpu_serving_ttft_seconds", "ttft",
                          buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        doc = build_push("w1:1", "worker", 1, registry=reg,
                         health_registry=obs_health.HealthRegistry(),
                         span_store=SpanStore())
        # JSON round-trip: bucket keys become strings, like a real push
        doc = json.loads(json.dumps(doc))
        agg = FleetAggregator(span_store=SpanStore())
        agg.ingest(doc)
        fams = parse_prom(agg.exposition(MetricsRegistry(enabled=True)))
        check_histograms_consistent(fams)
        fam = fams["nnstpu_serving_ttft_seconds"]
        values = {(n, l): v for n, l, v in fam["samples"]}
        assert values[("nnstpu_serving_ttft_seconds_bucket",
                       'instance="w1:1",role="worker",le="0.1"')] == 1
        assert values[("nnstpu_serving_ttft_seconds_bucket",
                       'instance="w1:1",role="worker",le="+Inf"')] == 3
        assert values[("nnstpu_serving_ttft_seconds_count",
                       'instance="w1:1",role="worker"')] == 3

    def test_label_values_escaped_in_merge(self):
        """Satellite: backslash/quote/newline in a pushed label value
        stay escaped through the aggregator."""
        agg = FleetAggregator(span_store=SpanStore())
        agg.ingest(worker_push(
            "w1:1", counters=[("nnstpu_query_messages_total",
                               {"cmd": 'we"ird\\x\n'}, 1)]))
        text = agg.exposition(MetricsRegistry(enabled=True))
        assert 'cmd="we\\"ird\\\\x\\n"' in text
        parse_prom(text)  # and the result still parses

    def test_type_conflict_skipped_and_journaled(self, events):
        agg = FleetAggregator(span_store=SpanStore())
        agg.ingest(worker_push(
            "w1:1", counters=[("nnstpu_query_messages_total", {}, 1)]))
        bad = worker_push("w2:1")
        bad["metrics"]["nnstpu_query_messages_total"] = {
            "type": "gauge", "help": "drifted",
            "series": [{"labels": {}, "value": 9}]}
        agg.ingest(bad)
        fams = parse_prom(agg.exposition(MetricsRegistry(enabled=True)))
        fam = fams["nnstpu_query_messages_total"]
        assert fam["type"] == "counter"
        # the conflicting instance's series is skipped, not mangled in
        assert all('instance="w2:1"' not in l for _, l, _ in fam["samples"])
        evs = [e for e in obs_events.ring().snapshot()
               if e["type"] == "fleet.merge_conflict"]
        assert len(evs) == 1
        assert evs[0]["attrs"]["instance"] == "w2:1"
        # deduped: the next scrape does not journal it again
        agg.exposition(MetricsRegistry(enabled=True))
        assert len([e for e in obs_events.ring().snapshot()
                    if e["type"] == "fleet.merge_conflict"]) == 1

    def test_cumulative_replacement_not_double_count(self):
        agg = FleetAggregator(span_store=SpanStore())
        for seq, total in ((1, 5), (2, 9)):
            agg.ingest(worker_push(
                "w1:1", seq=seq,
                counters=[("nnstpu_query_messages_total", {}, total)]))
        fams = parse_prom(agg.exposition(MetricsRegistry(enabled=True)))
        # latest cumulative snapshot wins — 9, not 14
        assert fams["nnstpu_query_messages_total"]["samples"][0][2] == 9

    def test_bad_push_rejected(self):
        agg = FleetAggregator(span_store=SpanStore())
        with pytest.raises(ValueError, match="instance"):
            agg.ingest({"v": 1})
        with pytest.raises(ValueError, match="version"):
            agg.ingest({"v": 99, "instance": "w"})
        assert agg.bad_pushes == 2

    def test_non_scalar_fields_raise_valueerror_no_ghost(self):
        """A push with non-scalar junk in a coerced field raises
        ValueError (never TypeError) and leaves NO half-mutated
        instance behind — a ghost would flip /readyz fleet-wide."""
        agg = FleetAggregator(span_store=SpanStore())
        for field, junk in (("seq", [1]), ("ts", {"t": 1}),
                            ("interval_s", ["0.1"])):
            doc = worker_push("w1:1")
            doc[field] = junk
            with pytest.raises(ValueError, match="malformed push field"):
                agg.ingest(doc)
        assert agg.bad_pushes == 3
        assert agg.snapshot()["instances"] == []
        assert agg.ready_rollup(True, {}) == (True, {})
        # a later bad push must not corrupt an existing record either
        agg.ingest(worker_push("w1:1", seq=3))
        doc = worker_push("w1:1", seq=9)
        doc["seq"] = [9]
        with pytest.raises(ValueError):
            agg.ingest(doc)
        assert agg.snapshot()["instances"][0]["seq"] == 3

    def test_ingest_wire_never_raises(self, fleet_off_after):
        """The wire handler's contract: any junk — undecodable JSON or
        a document whose fields are the wrong shape — is counted and
        journaled, never raised into the server connection loop."""
        obs_fleet.enable_aggregator(ttl_s=30.0)
        obs_fleet.ingest_wire({"instance": "w"}, b"not json")
        bad = worker_push("w1:1")
        bad["seq"] = [1]
        obs_fleet.ingest_wire({"instance": "w1:1"},
                              json.dumps(bad).encode())
        obs_fleet.ingest_wire({}, json.dumps(["not", "a", "dict"]).encode())
        agg = obs_fleet.aggregator()
        assert agg.snapshot()["instances"] == []
        assert agg.bad_pushes >= 2


# --------------------------------------------------------------------------- #
# Expiry + health/readiness rollup
# --------------------------------------------------------------------------- #

class TestFleetHealth:
    def test_stale_instance_flips_rollups_then_expires(self, events):
        agg = FleetAggregator(ttl_s=0.15, expire_after_s=0.6,
                              span_store=SpanStore())
        agg.ingest(worker_push("w1:1", ready=True))
        ready, conds = agg.ready_rollup(True, {})
        assert ready and conds["fleet:w1:1"]
        snap = agg.health_rollup({"status": "ok", "ok": True,
                                  "components": []})
        assert snap["status"] == "ok"
        time.sleep(0.2)  # past ttl, before expiry
        ready, conds = agg.ready_rollup(True, {})
        assert not ready and conds["fleet:w1:1"] is False
        snap = agg.health_rollup({"status": "ok", "ok": True,
                                  "components": []})
        assert snap["status"] == "stalled" and not snap["ok"]
        time.sleep(0.5)  # past expire_after
        assert agg.snapshot()["instances"] == []
        assert agg.ready_rollup(True, {}) == (True, {})
        evs = [e for e in obs_events.ring().snapshot()
               if e["type"] == "fleet.expire"]
        assert len(evs) == 1 and evs[0]["attrs"]["instance"] == "w1:1"

    def test_worst_of_fleet_status(self):
        agg = FleetAggregator(ttl_s=30.0, span_store=SpanStore())
        agg.ingest(worker_push("w1:1", status="ok"))
        agg.ingest(worker_push("w2:1", status="degraded"))
        snap = agg.health_rollup({"status": "ok", "ok": True,
                                  "components": []})
        assert snap["status"] == "degraded" and snap["ok"]
        agg.ingest(worker_push("w3:1", status="failing"))
        snap = agg.health_rollup({"status": "ok", "ok": True,
                                  "components": []})
        assert snap["status"] == "failing" and not snap["ok"]

    def test_not_ready_worker_blocks_fleet_readiness(self):
        agg = FleetAggregator(ttl_s=30.0, span_store=SpanStore())
        agg.ingest(worker_push("w1:1", ready=True))
        agg.ingest(worker_push("w2:1", ready=False))
        ready, conds = agg.ready_rollup(True, {"local": True})
        assert not ready
        assert conds == {"local": True, "fleet:w1:1": True,
                         "fleet:w2:1": False}

    def test_watchdog_missing_heartbeat_rule(self, events, global_health,
                                             fleet_off_after):
        """The kind="fleet" watchdog rule: a silent instance goes
        STALLED on check_now and recovers when pushes resume."""
        obs_health.enable()
        agg = obs_fleet.enable_aggregator(ttl_s=0.1)
        agg.ingest(worker_push("w1:1"))
        obs_health.check_now()
        comp = {c["name"]: c for c in
                obs_health.snapshot()["components"]}["fleet:w1:1"]
        assert comp["status"] == "ok"
        time.sleep(0.15)
        obs_health.check_now()
        comp = {c["name"]: c for c in
                obs_health.snapshot()["components"]}["fleet:w1:1"]
        assert comp["status"] == "stalled"
        assert "no push" in comp["detail"]
        assert any(e["type"] == "fleet.stall"
                   for e in obs_events.ring().snapshot())
        agg.ingest(worker_push("w1:1", seq=2))
        obs_health.check_now()
        comp = {c["name"]: c for c in
                obs_health.snapshot()["components"]}["fleet:w1:1"]
        assert comp["status"] == "ok"
        assert any(e["type"] == "fleet.recover"
                   for e in obs_events.ring().snapshot())

    def test_rollup_components_not_duplicated(self, global_health,
                                              fleet_off_after):
        """/healthz lists each instance once: the rollup's authoritative
        fleet:<iid> entry replaces the kind="fleet" watchdog component
        _register_health put in the local registry — even when the two
        would disagree (watchdog stalled vs rollup fresh)."""
        obs_health.enable()
        agg = obs_fleet.enable_aggregator(ttl_s=30.0)
        agg.ingest(worker_push("w1:1"))
        obs_health.check_now()
        local = obs_health.snapshot()
        # local registry does carry the watchdog component...
        assert [c["name"] for c in local["components"]] == ["fleet:w1:1"]
        # ...but force its status to disagree with the fresh rollup
        local["components"][0]["status"] = "stalled"
        snap = agg.health_rollup(local)
        fleet_comps = [c for c in snap["components"]
                       if c["name"] == "fleet:w1:1"]
        assert len(fleet_comps) == 1
        assert fleet_comps[0]["status"] == "ok"
        assert snap["status"] == "ok" and snap["ok"]

    def test_push_events_carry_instance(self, events):
        agg = FleetAggregator(span_store=SpanStore())
        agg.ingest(worker_push("w1:1"), via="wire")
        evs = [e for e in obs_events.ring().snapshot()
               if e["type"] == "fleet.push"]
        assert evs and evs[0]["attrs"]["instance"] == "w1:1"
        assert evs[0]["attrs"]["via"] == "wire"


# --------------------------------------------------------------------------- #
# Remote span collection
# --------------------------------------------------------------------------- #

class TestRemoteSpans:
    def _worker_spans(self):
        """A worker-side store: tracing on, export on, one marked trace
        with two spans."""
        store = SpanStore()
        store.enable()
        store.set_export(True)
        with store.start_span("query.request") as root:
            store.mark_export(root.context.trace_id)
            with store.start_span("serving.request",
                                  parent=root.context):
                pass
        return store, root.context.trace_id

    def test_drain_and_ingest_builds_cross_host_tree(self):
        wstore, tid = self._worker_spans()
        wire = wstore.drain_export()
        assert len(wire) == 2
        assert wstore.drain_export() == []  # drained
        astore = SpanStore()
        assert astore.ingest_remote(wire, "w1:1") == 2
        tree = astore.tree(tid)
        assert tree is not None and tree["spans"] == 2
        root = tree["tree"][0]
        assert root["name"] == "query.request"
        assert root["attrs"]["instance"] == "w1:1"
        assert [k["name"] for k in root["children"]] \
            == ["serving.request"]

    def test_failed_push_requeues_drained_spans(self):
        """push_now drains the export queue into the doc; a down
        aggregator must not lose that batch — it goes back to the FRONT
        so the next successful push carries it, oldest first."""
        wstore, tid = self._worker_spans()
        psh = FleetPusher(url="http://127.0.0.1:9", interval_s=3600,
                          instance="w1:1", span_store=wstore)
        try:
            assert psh.push_now() is False  # port 9: nothing listens
            requeued = wstore.drain_export()
            assert [s["tid"] for s in requeued] == [tid, tid]
            assert len(requeued) == 2
        finally:
            psh.close()

    def test_requeue_preserves_order_ahead_of_new_spans(self):
        store = SpanStore()
        store.enable()
        store.set_export(True)
        with store.start_span("query.request") as root:
            store.mark_export(root.context.trace_id)
        batch = store.drain_export()
        with store.start_span("serving.request",
                              parent=root.context):
            pass
        store.requeue_export(batch)
        names = [s["name"] for s in store.drain_export()]
        assert names == ["query.request", "serving.request"]

    def test_unmarked_traces_not_exported(self):
        store = SpanStore()
        store.enable()
        store.set_export(True)
        with store.start_span("query.request"):
            pass  # never marked
        assert store.drain_export() == []

    def test_export_off_is_free_and_clears(self):
        store = SpanStore()
        store.enable()
        with store.start_span("query.request") as s:
            store.mark_export(s.context.trace_id)  # no-op while off
        assert store.drain_export() == []
        assert store._export_on is False

    def test_remote_spans_rebased_into_local_clock_domain(self):
        """A trace holding both halves — the aggregator's own local
        (monotonic) spans plus ingested remote (wall-derived) spans —
        must render with one time base: offsets stay request-scale, not
        epoch-scale (~1.7e18 ns) garbage."""
        astore = SpanStore()
        astore.enable()
        with astore.start_span("query.server_handle") as local_span:
            tid = local_span.context.trace_id
        wire = [{"tid": tid, "sid": "remote01", "par": None,
                 "name": "query.request", "wall": time.time() - 0.01,
                 "dur_ns": int(20e6), "attrs": {}}]
        assert astore.ingest_remote(wire, "w1:1") == 1
        tree = astore.tree(tid)
        offsets = [n["start_us"] for n in tree["tree"]]
        # both roots within a minute of each other, not epoch-scale
        assert all(abs(o) < 60e6 for o in offsets), offsets
        tr = astore._traces[tid]
        assert abs(tr.end_ns - tr.start_ns) < int(60e9)

    def test_malformed_remote_spans_skipped(self):
        store = SpanStore()
        ok = {"tid": "t1", "sid": "s1", "par": None,
              "name": "query.request", "wall": 1e9, "dur_ns": 5,
              "attrs": {}}
        assert store.ingest_remote(
            [ok, {"bogus": 1}, "not a dict"], "w") == 1


# --------------------------------------------------------------------------- #
# End-to-end: two instances, one aggregator (ISSUE acceptance)
# --------------------------------------------------------------------------- #

class TestEndToEnd:
    def test_fleet_acceptance(self, events, fleet_off_after):
        """Faked-wire two-instance deployment: worker pushes over HTTP
        to the aggregator's exporter; /metrics shows both instances'
        counters, /debug/traces/<id> has spans from both sides of one
        request, and killing the worker flips /readyz within one
        watchdog interval."""
        agg = obs_fleet.enable_aggregator(ttl_s=0.3, expire_after_s=30.0)
        local_reg = MetricsRegistry(enabled=True)
        local_reg.counter("nnstpu_query_messages_total", "m",
                          ("direction",)).labels("recv").inc(2)
        # the aggregator's own half of the trace (adopted remote parent)
        astore = obs_tracing.store()
        astore.enable()
        with start_exporter(port=0, registry=local_reg) as exp:
            base = f"http://127.0.0.1:{exp.port}"

            # -- worker side (private registries = separate process) --
            wreg = MetricsRegistry(enabled=True)
            wreg.counter("nnstpu_query_messages_total", "m",
                         ("direction",)).labels("sent").inc(5)
            wstore = SpanStore()
            wstore.enable()
            wstore.set_export(True)
            whealth = obs_health.HealthRegistry()
            with wstore.start_span("query.request") as wroot:
                tid = wroot.context.trace_id
                wstore.mark_export(tid)
            # server half adopts the propagated context
            with astore.start_span(
                    "query.server_handle",
                    parent=obs_tracing.SpanContext(tid, "remote01")):
                pass

            def push(seq, ready=True):
                doc = build_push("worker:1", "worker", seq,
                                 interval_s=0.1, registry=wreg,
                                 health_registry=whealth,
                                 span_store=wstore)
                doc["ready"] = {"ready": ready, "conditions": {}}
                req = urllib.request.Request(
                    base + "/fleet/push",
                    data=json.dumps(doc).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=5) as r:
                    assert r.status == 200

            push(1)

            # -- /metrics: both instances, instance labels -----------
            with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
                fams = parse_prom(r.read().decode())
            samples = fams["nnstpu_query_messages_total"]["samples"]
            by_labels = {l: v for _, l, v in samples}
            assert any('instance="worker:1"' in l and v == 5
                       for l, v in by_labels.items())
            assert any('role="aggregator"' in l and v == 2
                       for l, v in by_labels.items())

            # -- /debug/traces/<id>: spans from both sides -----------
            with urllib.request.urlopen(
                    base + f"/debug/traces/{tid}", timeout=5) as r:
                tree = json.loads(r.read())

            def flatten(nodes):
                for n in nodes:
                    yield n
                    yield from flatten(n["children"])

            names = {s["name"]: s for s in flatten(tree["tree"])}
            assert "query.request" in names          # worker side
            assert "query.server_handle" in names    # aggregator side
            assert names["query.request"]["attrs"]["instance"] \
                == "worker:1"

            # -- /debug/fleet ----------------------------------------
            with urllib.request.urlopen(
                    base + "/debug/fleet", timeout=5) as r:
                snap = json.loads(r.read())
            assert [i["instance"] for i in snap["instances"]] \
                == ["worker:1"]
            assert snap["instances"][0]["spans_ingested"] == 1

            # -- killing the worker flips /readyz within one ttl -----
            with urllib.request.urlopen(base + "/readyz", timeout=5) as r:
                assert json.loads(r.read())["ready"] is True
            time.sleep(0.4)  # one watchdog interval past ttl_s=0.3
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(base + "/readyz", timeout=5)
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["conditions"]["fleet:worker:1"] is False

    def test_wire_piggyback_real_pipelines(self, fleet_off_after):
        """OBS_PUSH frames ride a real client→server query connection:
        the server-side aggregator learns the client instance without
        any HTTP channel."""
        agg = obs_fleet.enable_aggregator(ttl_s=30.0)
        port = free_port()
        sp = Pipeline("server")
        ssrc = sp.add_new("tensor_query_serversrc", host="127.0.0.1",
                          port=port, id=0, dims="4:1", types="float32")
        filt = sp.add_new("tensor_filter", model=lambda x: x * 10)
        ssink = sp.add_new("tensor_query_serversink", id=0)
        Pipeline.link(ssrc, filt, ssink)
        sp.start()
        try:
            time.sleep(0.2)
            # wire-only pusher: interval 0 → every DATA send carries one
            psh = obs_fleet.enable_push(url=None, interval_s=0.0,
                                        instance="client:wire")
            assert psh._thread is None  # wire-only: no HTTP thread
            cp = Pipeline("client")
            src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"),
                             data=[np.full((1, 4), i, np.float32)
                                   for i in range(3)])
            qc = cp.add_new("tensor_query_client", host="127.0.0.1",
                            port=port)
            sink = cp.add_new("tensor_sink", store=True)
            Pipeline.link(src, qc, sink)
            cp.run(timeout=60)
            assert sink.num_buffers == 3  # data flow unharmed
            insts = [i["instance"] for i in agg.snapshot()["instances"]]
            assert insts == ["client:wire"]
            rec = agg.snapshot()["instances"][0]
            assert rec["via"] == "wire" and rec["pushes"] >= 1
        finally:
            sp.stop()

    def test_http_pusher_thread_end_to_end(self, fleet_off_after):
        """The standalone HTTP pusher (non-query processes) reaches the
        aggregator's exporter and close() stops the thread."""
        obs_fleet.enable_aggregator(ttl_s=30.0)
        with start_exporter(port=0,
                            registry=MetricsRegistry(enabled=True)) as exp:
            psh = obs_fleet.enable_push(
                url=f"http://127.0.0.1:{exp.port}", interval_s=0.05,
                instance="pusher:http", role="serving")
            try:
                deadline = time.monotonic() + 5
                agg = obs_fleet.aggregator()
                while time.monotonic() < deadline:
                    if agg.snapshot()["instances"]:
                        break
                    time.sleep(0.02)
                recs = agg.snapshot()["instances"]
                assert [r["instance"] for r in recs] == ["pusher:http"]
                assert recs[0]["role"] == "serving"
                assert any(t.name.startswith("obs-fleet-push")
                           for t in threading.enumerate())
            finally:
                obs_fleet.disable_push()
            assert not any(t.name.startswith("obs-fleet-push")
                           for t in threading.enumerate())


# --------------------------------------------------------------------------- #
# Concurrent scrapes under a push storm (satellite)
# --------------------------------------------------------------------------- #

class TestConcurrency:
    def test_scrapes_parseable_under_push_storm(self, fleet_off_after):
        agg = obs_fleet.enable_aggregator(ttl_s=30.0)
        stop = threading.Event()
        errors = []

        def storm(wid):
            seq = 0
            while not stop.is_set():
                seq += 1
                reg = MetricsRegistry(enabled=True)
                h = reg.histogram("nnstpu_serving_ttft_seconds", "t",
                                  buckets=(0.1, 1.0))
                for i in range(seq % 7 + 1):
                    h.observe(0.05 * i)
                reg.counter("nnstpu_query_messages_total", "m",
                            ("direction",)).labels("sent").inc(seq)
                doc = build_push(f"w{wid}:1", "worker", seq,
                                 registry=reg,
                                 health_registry=obs_health.HealthRegistry(),
                                 span_store=SpanStore())
                try:
                    agg.ingest(json.loads(json.dumps(doc)))
                except Exception as e:  # noqa: BLE001
                    errors.append(e)
                    return

        threads = [threading.Thread(target=storm, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        local = MetricsRegistry(enabled=True)
        try:
            deadline = time.monotonic() + 2.0
            scrapes = 0
            while time.monotonic() < deadline:
                fams = parse_prom(agg.exposition(local))
                check_histograms_consistent(fams)
                scrapes += 1
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        assert not errors
        assert scrapes > 10

    def test_expiry_under_concurrent_ingest(self):
        """Lazy expiry racing ingest never corrupts the instance map."""
        agg = FleetAggregator(ttl_s=0.01, expire_after_s=0.02,
                              span_store=SpanStore())
        stop = threading.Event()

        def churn(wid):
            seq = 0
            while not stop.is_set():
                seq += 1
                agg.ingest(worker_push(f"w{wid}:1", seq=seq,
                                       interval_s=0.01))

        threads = [threading.Thread(target=churn, args=(i,))
                   for i in range(3)]
        for t in threads:
            t.start()
        try:
            for _ in range(50):
                agg.snapshot()
                agg.exposition(MetricsRegistry(enabled=True))
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)
        time.sleep(0.1)
        assert agg.snapshot()["instances"] == []  # all expired clean


# --------------------------------------------------------------------------- #
# Zero-overhead contract (ISSUE acceptance)
# --------------------------------------------------------------------------- #

class TestZeroOverhead:
    def test_disabled_fast_paths(self):
        assert obs_fleet.pusher() is None
        assert not obs_fleet.push_enabled()
        # THE hot-path check the query client makes per send
        assert obs_fleet.wire_frame_due() is None
        assert obs_fleet.aggregator() is None
        # no fleet threads exist
        assert not any(t.name.startswith("obs-fleet-push")
                       for t in threading.enumerate())
        # span export costs one attribute read and is off
        assert obs_tracing.store()._export_on is False

    def test_no_extra_wire_bytes_when_disabled(self, fleet_off_after):
        """With fleet off, a query roundtrip sends zero OBS_PUSH frames
        (counted at the server's protocol layer via the shared message
        counter)."""
        def obs_push_msgs():
            snap = obs_metrics.registry().snapshot()
            series = snap.get("nnstpu_query_messages_total",
                              {"series": []})["series"]
            return sum(s["value"] for s in series
                       if s["labels"].get("cmd") == "OBS_PUSH")

        was = obs_metrics.enabled()
        obs_metrics.enable()
        before = obs_push_msgs()
        try:
            port = free_port()
            sp = Pipeline("server")
            ssrc = sp.add_new("tensor_query_serversrc", host="127.0.0.1",
                              port=port, id=0, dims="4:1",
                              types="float32")
            filt = sp.add_new("tensor_filter", model=lambda x: x + 1)
            ssink = sp.add_new("tensor_query_serversink", id=0)
            Pipeline.link(ssrc, filt, ssink)
            sp.start()
            try:
                time.sleep(0.2)
                cp = Pipeline("client")
                src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"),
                                 data=[np.zeros((1, 4), np.float32)])
                qc = cp.add_new("tensor_query_client", host="127.0.0.1",
                                port=port)
                sink = cp.add_new("tensor_sink", store=True)
                Pipeline.link(src, qc, sink)
                cp.run(timeout=60)
                assert sink.num_buffers == 1
            finally:
                sp.stop()
            # the cumulative registry outlives other tests that DO push:
            # the contract is zero NEW frames during this disabled run
            assert obs_push_msgs() == before
        finally:
            (obs_metrics.enable if was else obs_metrics.disable)()

    def test_ingest_wire_noop_without_aggregator(self):
        # never raises, never allocates an aggregator
        obs_fleet.ingest_wire({"instance": "w"}, b"not json")
        assert obs_fleet.aggregator() is None

    def test_span_record_overhead_disabled(self):
        """_record with export off takes the single-flag branch: the
        pending queue stays untouched even for marked-looking ids."""
        store = SpanStore()
        store.enable()
        with store.start_span("query.request"):
            pass
        assert len(store._export_pending) == 0


class TestIngestNeverRaises:
    """Regression (nnslint contracts/never-raise): ingest_remote's
    docstring promises malformed entries are skipped, never raised —
    including exception types outside the originally enumerated
    (KeyError, TypeError, ValueError) narrow list."""

    def test_entry_raising_arbitrary_exception_is_skipped(self):
        class IndexableNoGet:
            # __getitem__ works, .get() does not -> AttributeError,
            # which the old narrow except list leaked to the caller
            def __getitem__(self, key):
                return {"tid": "t9", "sid": "s9",
                        "wall": 1e9, "dur_ns": 5}[key]

        store = SpanStore()
        ok = {"tid": "t9", "sid": "s1", "par": None,
              "name": "query.request", "wall": 1e9, "dur_ns": 5,
              "attrs": {}}
        assert store.ingest_remote([IndexableNoGet(), ok], "w") == 1


class TestPusherKvDigest:
    """FleetPusher kv-digest wiring: a per-pusher digest source wins;
    without one, build_push defers to the module KV_DIGEST_HOOK that
    serving/disagg.py installs when a worker starts."""

    def test_kv_digest_param_flows_into_doc(self):
        psh = FleetPusher(instance="w:1",
                          kv_digest=lambda: ["h1", "h2", "h3"])
        try:
            doc = psh._next_doc()
            assert doc["kv_prefix"] == ["h1", "h2", "h3"]
        finally:
            psh.close()

    def test_default_defers_to_module_hook(self):
        prior = obs_fleet.KV_DIGEST_HOOK
        obs_fleet.KV_DIGEST_HOOK = lambda: ["m1"]
        psh = FleetPusher(instance="w:2")
        try:
            assert psh._next_doc()["kv_prefix"] == ["m1"]
        finally:
            psh.close()
            obs_fleet.KV_DIGEST_HOOK = prior

    def test_no_source_pushes_none(self):
        assert obs_fleet.KV_DIGEST_HOOK is None
        psh = FleetPusher(instance="w:3")
        try:
            assert psh._next_doc()["kv_prefix"] is None
        finally:
            psh.close()
