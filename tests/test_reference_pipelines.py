"""Reference pipeline STRINGS run unmodified (the north-star claim).

These are the reference's own gst-launch pipeline descriptions from its
SSAT suites — same element names, same properties, same model files —
parsed by graph/parse.py and executed end to end. Golden source:
tests/nnstreamer_filter_tensorflow2_lite/runTest.sh:74 (classification
must yield "orange") and its negative property cases (:79-84).
"""

import os

import numpy as np
import pytest

from nnstreamer_tpu.graph.parse import parse_pipeline

MODELS = "/root/reference/tests/test_models/models"
DATA = "/root/reference/tests/test_models/data"
LABELS = "/root/reference/tests/test_models/labels/labels.txt"

needs_ref = pytest.mark.skipif(
    not os.path.isdir(MODELS), reason="reference test models not mounted")

# the reference golden string, verbatim apart from the mounted paths and
# the v2-quant model actually shipped in the mount (runTest.sh names
# mobilenet_v1_1.0_224_quant.tflite, downloaded at test time there)
GOLDEN = (
    "filesrc location={img} ! pngdec ! videoscale ! imagefreeze ! "
    "videoconvert ! video/x-raw,format=RGB,framerate=0/1 ! "
    "tensor_converter ! "
    "tensor_filter framework=tensorflow2-lite model={model} ! "
    "filesink location={out}"
)


@needs_ref
def test_reference_golden_classification_string(tmp_path):
    out = tmp_path / "tensorfilter.out.log"
    p = parse_pipeline(GOLDEN.format(
        img=os.path.join(DATA, "orange.png"),
        model=os.path.join(MODELS, "mobilenet_v2_1.0_224_quant.tflite"),
        out=out))
    p.run(timeout=300)
    # checkLabel.py semantics: raw output bytes -> argmax -> label text
    scores = np.frombuffer(out.read_bytes(), np.uint8)
    assert scores.size == 1001
    labels = open(LABELS).read().splitlines()
    assert labels[int(scores.argmax())] == "orange"


@needs_ref
def test_reference_negative_invalid_input_property(tmp_path):
    """runTest.sh 2F_n: invalid input= dims must FAIL the pipeline."""
    bad = GOLDEN.format(
        img=os.path.join(DATA, "orange.png"),
        model=os.path.join(MODELS, "mobilenet_v2_1.0_224_quant.tflite"),
        out=tmp_path / "o.log").replace(
        "! filesink",
        "input=7:1 inputtype=float32 ! filesink")
    p = parse_pipeline(bad)
    with pytest.raises(Exception):
        p.run(timeout=120)


@needs_ref
def test_reference_add_pipeline_string(tmp_path):
    """runTest.sh-style add.tflite passthrough-plus-two over octet input."""
    raw = tmp_path / "x.raw"
    np.array([2.5], np.float32).tofile(raw)
    out = tmp_path / "add.out"
    p = parse_pipeline(
        f"filesrc location={raw} ! "
        "tensor_converter input-dim=1 input-type=float32 ! "
        f"tensor_filter framework=tensorflow2-lite "
        f"model={os.path.join(MODELS, 'add.tflite')} ! "
        f"filesink location={out}")
    p.run(timeout=120)
    assert np.frombuffer(out.read_bytes(), np.float32)[0] == 4.5


def test_imagefreeze_repeats_frames(tmp_path):
    from PIL import Image

    img = tmp_path / "t.png"
    Image.fromarray(np.full((8, 8, 3), 7, np.uint8)).save(img)
    p = parse_pipeline(
        f"filesrc location={img} ! pngdec ! imagefreeze num_buffers=5 ! "
        "tensor_converter ! tensor_sink store=true")
    p.run(timeout=60)
    sink = [e for e in p.elements.values()
            if e.ELEMENT_NAME == "tensor_sink"][0]
    assert sink.num_buffers == 5
    assert sink.buffers[4].offset == 4


def _make_sequence(tmp_path, n=4, size=(16, 12)):
    from PIL import Image

    rng = np.random.default_rng(5)
    for i in range(n):
        arr = rng.integers(0, 255, (size[1], size[0], 3)).astype(np.uint8)
        Image.fromarray(arr).save(tmp_path / f"testsequence_{i}.png")


def test_reference_typecast_tee_string(tmp_path):
    """transform_typecast/runTest.sh case 1, verbatim: multifilesrc !
    pngdec ! videoconvert ! caps ! tensor_converter ! tee ! two branches
    (typecast=uint32 and direct); golden: typecast log == direct bytes
    cast to uint32."""
    _make_sequence(tmp_path)
    tc_log = tmp_path / "testcase01.typecast.log"
    di_log = tmp_path / "testcase01.direct.log"
    p = parse_pipeline(
        f'multifilesrc location="{tmp_path}/testsequence_%1d.png" index=0 '
        'caps="image/png,framerate=(fraction)30/1" ! pngdec ! '
        'videoconvert ! video/x-raw, format=RGB ! tensor_converter ! '
        'tee name=t ! queue ! tensor_transform mode=typecast '
        f'option=uint32 ! filesink location="{tc_log}" sync=true '
        f't. ! queue ! filesink location="{di_log}" sync=true')
    p.run(timeout=120)
    direct = np.frombuffer(di_log.read_bytes(), np.uint8)
    typecast = np.frombuffer(tc_log.read_bytes(), np.uint32)
    np.testing.assert_array_equal(typecast, direct.astype(np.uint32))


def test_reference_converter_gray8_string(tmp_path):
    """nnstreamer_converter/runTest.sh 1G, verbatim: GRAY8 videotestsrc
    through tensor_converter to a filesink dump."""
    log = tmp_path / "test.gray8.log"
    p = parse_pipeline(
        "videotestsrc num-buffers=1 ! "
        "video/x-raw,format=GRAY8,width=280,height=40,framerate=0/1 ! "
        "queue ! tensor_converter silent=TRUE ! "
        f'filesink location="{log}" sync=true')
    p.run(timeout=120)
    assert log.stat().st_size == 280 * 40  # one GRAY8 frame, dims 280x40


def test_reference_typecast_invalid_type_fails(tmp_path):
    """transform_typecast 2F_n: option=uint128 must fail."""
    _make_sequence(tmp_path)
    with pytest.raises(Exception):
        p = parse_pipeline(
            f'multifilesrc location="{tmp_path}/testsequence_%1d.png" '
            'index=0 caps="image/png,framerate=(fraction)30/1" ! pngdec ! '
            'videoconvert ! video/x-raw, format=RGB ! tensor_converter ! '
            'tensor_transform mode=typecast option=uint128 ! '
            f'filesink location="{tmp_path}/x.log" sync=true')
        p.run(timeout=60)


def test_caps_configures_intermediate_videoscale(tmp_path):
    """The classic reference scaling shape: videoscale ! caps with
    width/height configures the scaler (gst upstream negotiation)."""
    log = tmp_path / "scaled.log"
    p = parse_pipeline(
        "videotestsrc num-buffers=1 width=64 height=64 ! videoscale ! "
        "video/x-raw,width=16,height=16 ! tensor_converter ! "
        f'filesink location="{log}"')
    p.run(timeout=60)
    assert log.stat().st_size == 16 * 16 * 3


def test_caps_after_backreference_respects_explicit_props(tmp_path):
    """A caps filter following a name. back-reference must not override
    props set explicitly on the referenced element."""
    from nnstreamer_tpu.graph import PipelineError

    p = parse_pipeline(
        "videotestsrc name=s width=8 height=8 num-buffers=1 ! "
        "tee name=t ! queue ! fakesink "
        "t. ! video/x-raw,width=999 ! fakesink")
    with pytest.raises(Exception, match="incompatible"):
        p.run(timeout=30)


def test_corrupt_png_fails_at_bad_frame(tmp_path):
    """A complete-but-corrupt PNG (IEND present, body garbage) must fail
    the stream at that frame, not silently swallow it."""
    from PIL import Image

    good = tmp_path / "seq_0.png"
    Image.fromarray(np.zeros((4, 4, 3), np.uint8)).save(good)
    bad = good.read_bytes()
    # corrupt the IDAT payload, keep signature + IEND
    idx = bad.index(b"IDAT") + 8
    corrupt = bad[:idx] + bytes([b ^ 0xFF for b in bad[idx:idx + 8]]) \
        + bad[idx + 8:]
    (tmp_path / "seq_1.png").write_bytes(corrupt)
    p = parse_pipeline(
        f'multifilesrc location="{tmp_path}/seq_%1d.png" index=0 ! '
        "pngdec ! tensor_converter ! fakesink")
    with pytest.raises(Exception):
        p.run(timeout=30)


def test_reference_demux_string_single_stream(tmp_path):
    """nnstreamer_demux/runTest.sh case 1, verbatim shape: mux+demux by
    name with explicit pad references (mux.sink_0 / demux.src_0)."""
    from PIL import Image

    rng = np.random.default_rng(9)
    arr = rng.integers(0, 255, (16, 16, 3)).astype(np.uint8)
    img = tmp_path / "testcase_RGB.png"
    Image.fromarray(arr).save(img)
    log = tmp_path / "demux00.log"
    p = parse_pipeline(
        "tensor_mux name=mux ! tensor_demux name=demux "
        f"filesrc location={img} ! pngdec ! videoscale ! imagefreeze ! "
        "videoconvert ! video/x-raw,format=RGB,width=16,height=16,"
        "framerate=0/1 ! tensor_converter ! mux.sink_0 "
        f"demux.src_0 !queue! filesink location={log}")
    p.run(timeout=120)
    np.testing.assert_array_equal(
        np.frombuffer(log.read_bytes(), np.uint8).reshape(16, 16, 3), arr)


def test_reference_demux_string_two_streams(tmp_path):
    """nnstreamer_demux/runTest.sh case 2 shape: two muxed streams split
    back out to two sinks via explicit pads."""
    from PIL import Image

    rng = np.random.default_rng(10)
    arrs = [rng.integers(0, 255, (8, 8, 3)).astype(np.uint8)
            for _ in range(2)]
    imgs = []
    for i, a in enumerate(arrs):
        path = tmp_path / f"img{i}.png"
        Image.fromarray(a).save(path)
        imgs.append(path)
    logs = [tmp_path / "demux02_0.log", tmp_path / "demux02_1.log"]
    p = parse_pipeline(
        "tensor_mux name=mux ! tensor_demux name=demux "
        f"filesrc location={imgs[0]} ! pngdec ! videoscale ! imagefreeze ! "
        "videoconvert ! video/x-raw,format=RGB,width=8,height=8,"
        "framerate=0/1 ! tensor_converter ! mux.sink_0 "
        f"filesrc location={imgs[1]} ! pngdec ! videoscale ! imagefreeze ! "
        "videoconvert ! video/x-raw,format=RGB,width=8,height=8,"
        "framerate=0/1 ! tensor_converter ! mux.sink_1 "
        f"demux.src_0 ! queue ! filesink location={logs[0]} "
        f"demux.src_1 ! queue ! filesink location={logs[1]}")
    p.run(timeout=120)
    for log, a in zip(logs, arrs):
        np.testing.assert_array_equal(
            np.frombuffer(log.read_bytes(), np.uint8).reshape(8, 8, 3), a)


def test_reference_clamp_octet_string(tmp_path):
    """transform_clamp/runTest.sh case 1, verbatim: octet filesrc with
    blocksize=-1 reinterpreted by tensor_converter, clamped, dumped."""
    data = np.random.default_rng(11).integers(
        -128, 127, 50 * 100).astype(np.int8)
    src = tmp_path / "test_00.dat"
    data.tofile(src)
    out = tmp_path / "result_00.dat"
    p = parse_pipeline(
        f'filesrc location="{src}" blocksize=-1 ! '
        "application/octet-stream ! "
        "tensor_converter input-dim=50:100:1:1 input-type=int8 ! "
        "tensor_transform mode=clamp option=-50:50 ! "
        f'filesink location="{out}" sync=true')
    p.run(timeout=120)
    got = np.frombuffer(out.read_bytes(), np.int8)
    np.testing.assert_array_equal(got, np.clip(data, -50, 50))


class TestPadRefEdgeCases:
    def test_bare_named_target_links(self, tmp_path):
        """'... ! name.' links into the named element's free sink pad."""
        log = tmp_path / "m.log"
        p = parse_pipeline(
            f"tensor_mux name=m ! filesink location={log} "
            "videotestsrc num-buffers=1 width=4 height=4 ! "
            "tensor_converter ! m.")
        p.run(timeout=60)
        assert log.stat().st_size == 4 * 4 * 3

    def test_chain_after_sink_pad_ref_rejected(self):
        with pytest.raises(ValueError, match="after linking"):
            parse_pipeline(
                "tensor_mux name=m ! fakesink "
                "videotestsrc num-buffers=1 ! tensor_converter ! "
                "m.sink_0 ! queue")

    def test_out_of_order_pad_ref_rejected(self):
        with pytest.raises(ValueError, match="index order"):
            parse_pipeline(
                "tensor_mux name=m ! fakesink "
                "videotestsrc num-buffers=1 ! tensor_converter ! m.sink_1")

    def test_forward_pad_ref_before_declaration(self, tmp_path):
        """gst-launch resolves 'mux.sink_0' appearing before
        'tensor_mux name=mux' is declared."""
        log = tmp_path / "f.log"
        p = parse_pipeline(
            "videotestsrc num-buffers=1 width=4 height=4 ! "
            "tensor_converter ! mux.sink_0 "
            f"tensor_mux name=mux ! filesink location={log}")
        p.run(timeout=60)
        assert log.stat().st_size == 4 * 4 * 3

    def test_pad_refs_straddling_declaration_keep_index_order(self, tmp_path):
        """sink_0 referenced before the declaration, sink_1 after — request
        pads must still be created in index order (global encounter order)."""
        log = tmp_path / "s.log"
        p = parse_pipeline(
            "videotestsrc num-buffers=1 width=4 height=4 ! "
            "tensor_converter ! mux.sink_0 "
            f"tensor_mux name=mux ! filesink location={log} "
            "videotestsrc num-buffers=1 width=2 height=2 ! "
            "tensor_converter ! mux.sink_1")
        p.run(timeout=60)
        assert log.stat().st_size == 4 * 4 * 3 + 2 * 2 * 3

    def test_dangling_forward_ref_rejected(self):
        with pytest.raises(ValueError, match="unknown element reference"):
            parse_pipeline(
                "videotestsrc num-buffers=1 ! tensor_converter ! ghost.sink_0")

    def test_uint8_clamp_with_negative_bound(self, tmp_path):
        """clamp -50:50 on a uint8 stream: bounds clamp into range
        instead of wrapping (206 > 50 would flatten the tensor)."""
        data = np.arange(0, 200, dtype=np.uint8)
        src = tmp_path / "u8.dat"
        data.tofile(src)
        out = tmp_path / "u8.out"
        p = parse_pipeline(
            f'filesrc location="{src}" blocksize=-1 ! '
            "application/octet-stream ! "
            "tensor_converter input-dim=200:1 input-type=uint8 ! "
            "tensor_transform mode=clamp option=-50:50 ! "
            f'filesink location="{out}"')
        p.run(timeout=60)
        np.testing.assert_array_equal(
            np.frombuffer(out.read_bytes(), np.uint8),
            np.clip(data, 0, 50))


def test_reference_audio_s16le_string(tmp_path):
    """nnstreamer_converter/runTest.sh 5-1, verbatim: audiotestsrc !
    audioconvert ! caps ! tee (converter + direct dump branches)."""
    conv_log = tmp_path / "test.audio8k.s16le.log"
    direct_log = tmp_path / "test.audio8k.s16le.origin.log"
    p = parse_pipeline(
        "audiotestsrc num-buffers=1 samplesperbuffer=8000 ! audioconvert "
        "! audio/x-raw,format=S16LE,rate=8000 ! tee name=t ! queue ! "
        "audioconvert ! tensor_converter frames-per-tensor=8000 ! "
        f'filesink location="{conv_log}" sync=true '
        f't. ! queue ! filesink location="{direct_log}" sync=true')
    p.run(timeout=60)
    # converter output must be byte-identical to the raw dump
    assert conv_log.read_bytes() == direct_log.read_bytes()
    assert conv_log.stat().st_size == 8000 * 2  # S16LE mono


def test_audioconvert_s16_to_f32(tmp_path):
    log = tmp_path / "f32.log"
    p = parse_pipeline(
        "audiotestsrc num-buffers=1 samplesperbuffer=100 ! "
        "audioconvert ! audio/x-raw,format=F32LE,rate=16000 ! "
        "tensor_converter frames-per-tensor=100 ! "
        f'filesink location="{log}"')
    p.run(timeout=60)
    f = np.frombuffer(log.read_bytes(), np.float32)
    assert f.size == 100 and np.abs(f).max() <= 1.0


def test_audio_s16_f32_roundtrip_exact(tmp_path):
    """S16 -> F32 -> S16 must be bit-exact (rounding, (max+1) scaling)."""
    import jax

    from nnstreamer_tpu.core.buffer import Buffer, TensorMemory
    from nnstreamer_tpu.core.types import Caps
    from nnstreamer_tpu.elements.media import AudioConvert

    data = np.array([1, 2, 100, -1, 32767, -32768], np.int16)

    def convert(samples, in_fmt, out_fmt):
        el = AudioConvert(format=out_fmt)
        el._in_fmt = in_fmt
        got = {}
        el.push = lambda b: got.setdefault("m", b.memories[0].host())
        el.chain(None, Buffer([TensorMemory(samples)]))
        return got["m"]

    f = convert(data, "S16LE", "F32LE")
    back = convert(f, "F32LE", "S16LE")
    np.testing.assert_array_equal(back, data)


def test_tensor_caps_filter_does_not_clobber_video_format(tmp_path):
    """An other/tensors caps filter's `format` field must not walk past
    tensor_converter onto a video element (media-type boundary)."""
    p = parse_pipeline(
        "videotestsrc num-buffers=2 width=4 height=4 ! videoconvert ! "
        "video/x-raw,format=RGB,width=4,height=4 ! tensor_converter ! "
        "other/tensors,num_tensors=1,dimensions=3:4:4:1,types=uint8,"
        "format=static ! fakesink")
    p.run(timeout=60)


@needs_ref
def test_reference_decoder_image_labeling_tee_string(tmp_path):
    """nnstreamer_decoder_image_labeling/runTest.sh shape, verbatim:
    tflite filter output teed into typecast branches, each decoded to a
    text label — both branches must say orange."""
    u8 = tmp_path / "tensordecoder.orange.uint8.log"
    u16 = tmp_path / "tensordecoder.orange.uint16.log"
    p = parse_pipeline(
        f'filesrc location="{os.path.join(DATA, "orange.png")}" ! pngdec '
        "! videoscale ! imagefreeze ! videoconvert ! "
        "video/x-raw, format=RGB, framerate=0/1 ! tensor_converter ! "
        'tensor_filter framework="tensorflow2-lite" '
        f'model="{os.path.join(MODELS, "mobilenet_v2_1.0_224_quant.tflite")}" ! '
        "tee name=t ! queue ! tensor_transform mode=typecast option=uint8 "
        f'! tensor_decoder mode=image_labeling option1="{LABELS}" ! '
        f'filesink location="{u8}" '
        "t. ! queue ! tensor_transform mode=typecast option=uint16 ! "
        f'tensor_decoder mode=image_labeling option1="{LABELS}" ! '
        f'filesink location="{u16}"')
    p.run(timeout=300)
    for log in (u8, u16):
        assert log.read_bytes().decode().strip("\x00\n") == "orange"


def test_reference_merge_string_two_streams(tmp_path):
    """nnstreamer_merge/runTest.sh case 2 shape: two streams merged
    mode=linear option=2 (reference dim axis 2 = height for RGB video:
    frames stack vertically) through explicit merge.sink_N pads."""
    from PIL import Image

    rng = np.random.default_rng(12)
    arrs = [rng.integers(0, 255, (8, 8, 3)).astype(np.uint8)
            for _ in range(2)]
    imgs = []
    for i, a in enumerate(arrs):
        path = tmp_path / f"m{i}.png"
        Image.fromarray(a).save(path)
        imgs.append(path)
    log = tmp_path / "merge02.log"
    p = parse_pipeline(
        "tensor_merge name=merge mode=linear option=2 sync-mode=nosync ! "
        f"filesink location={log} "
        f"filesrc location={imgs[0]} ! pngdec ! videoscale ! imagefreeze "
        "! videoconvert ! video/x-raw,format=RGB,width=8,height=8,"
        "framerate=0/1 ! tensor_converter ! merge.sink_0 "
        f"filesrc location={imgs[1]} ! pngdec ! videoscale ! imagefreeze "
        "! videoconvert ! video/x-raw,format=RGB,width=8,height=8,"
        "framerate=0/1 ! tensor_converter ! merge.sink_1")
    p.run(timeout=120)
    got = np.frombuffer(log.read_bytes(), np.uint8).reshape(16, 8, 3)
    np.testing.assert_array_equal(got, np.concatenate(arrs, axis=0))


@needs_ref
def test_reference_own_passthrough_py_script(tmp_path):
    """The reference's OWN passthrough.py (nnstreamer_python contract,
    `import nnstreamer_python as nns`) serves unmodified — SSAT case 1:
    tee with filter and direct branches must dump identical bytes."""
    pt = tmp_path / "testcase1.passthrough.log"
    di = tmp_path / "testcase1.direct.log"
    p = parse_pipeline(
        "videotestsrc num-buffers=1 ! video/x-raw,format=RGB,width=280,"
        "height=40,framerate=0/1 ! videoconvert ! video/x-raw, format=RGB "
        "! tensor_converter ! tee name=t ! queue ! tensor_filter "
        f'framework="python3" '
        f'model="{os.path.join(MODELS, "passthrough.py")}" '
        'input="3:280:40:1" inputtype="uint8" output="3:280:40:1" '
        f'outputtype="uint8" ! filesink location="{pt}" sync=true '
        f't. ! queue ! filesink location="{di}" sync=true')
    p.run(timeout=120)
    assert pt.read_bytes() == di.read_bytes()
    assert pt.stat().st_size == 3 * 280 * 40


@needs_ref
def test_reference_own_scaler_py_script(tmp_path):
    """The reference's OWN scaler.py (setInputDim + flat-array invoke +
    custom= constructor args) serves unmodified; golden: its own
    nearest-neighbor subsample semantics."""
    sc = tmp_path / "testcase2.scaled.log"
    di = tmp_path / "testcase2.direct.log"
    p = parse_pipeline(
        "videotestsrc num-buffers=1 ! video/x-raw,format=RGB,width=64,"
        "height=48,framerate=0/1 ! videoconvert ! video/x-raw, format=RGB "
        "! tensor_converter ! tee name=t ! queue ! tensor_filter "
        f'framework="python3" model="{os.path.join(MODELS, "scaler.py")}" '
        f'custom="32x24" ! filesink location="{sc}" sync=true '
        f't. ! queue ! filesink location="{di}" sync=true')
    p.run(timeout=120)
    src = np.frombuffer(di.read_bytes(), np.uint8).reshape(48, 64, 3)
    got = np.frombuffer(sc.read_bytes(), np.uint8).reshape(24, 32, 3)
    iy = (np.arange(24) * 48) // 24
    ix = (np.arange(32) * 64) // 32
    np.testing.assert_array_equal(got, src[iy][:, ix])


def test_custom_args_split_on_spaces_and_noarg_fallback(tmp_path):
    """custom= splits into separate constructor args (reference
    g_strsplit semantics); native no-arg constructors ignore custom=."""
    multi = tmp_path / "multi.py"
    multi.write_text(
        "import numpy as np\n"
        "import nnstreamer_python as nns\n"
        "class CustomFilter:\n"
        "    def __init__(self, *args):\n"
        "        assert args == ('a', 'b'), args\n"
        "        self.d = [nns.TensorShape([4, 1], np.float32)]\n"
        "    def getInputDim(self): return self.d\n"
        "    def getOutputDim(self): return self.d\n"
        "    def invoke(self, xs): return [xs[0]]\n")
    noarg = tmp_path / "noarg.py"
    noarg.write_text(
        "class CustomFilter:\n"
        "    def __init__(self):\n"
        "        pass\n"
        "    def getInputDimension(self): return '4:1', 'float32'\n"
        "    def getOutputDimension(self): return '4:1', 'float32'\n"
        "    def invoke(self, x): return x\n")
    from nnstreamer_tpu.filters.base import FilterProps
    from nnstreamer_tpu.filters.custom import Python3Filter

    f1 = Python3Filter()
    f1.open(FilterProps(model=str(multi), custom="a b"))
    f2 = Python3Filter()
    f2.open(FilterProps(model=str(noarg), custom="ignored"))


@needs_ref
def test_reference_own_custom_converter_script(tmp_path):
    """nnstreamer_converter_python3/runTest.sh 2-1 shape, verbatim: the
    reference's OWN custom_converter.py turns a flexbuf stream back into
    tensors; converter output must equal the raw dump."""
    conv = tmp_path / "test.audio8k.s16le.log"
    direct = tmp_path / "test.audio8k.s16le.origin.log"
    script = os.path.join(MODELS, "custom_converter.py")
    p = parse_pipeline(
        "audiotestsrc num-buffers=1 samplesperbuffer=8000 ! audioconvert "
        "! audio/x-raw,format=S16LE,rate=8000 ! tee name=t ! queue ! "
        "audioconvert ! tensor_converter frames-per-tensor=8000 ! "
        "tensor_decoder mode=flexbuf ! other/flexbuf ! "
        f"tensor_converter mode=custom-script:{script} ! "
        f'filesink location="{conv}" sync=true '
        f't. ! queue ! filesink location="{direct}" sync=true')
    p.run(timeout=120)
    assert conv.read_bytes() == direct.read_bytes()
    assert conv.stat().st_size == 8000 * 2


@needs_ref
def test_reference_own_custom_decoder_script(tmp_path):
    """The reference's OWN custom_decoder.py emits its flexbuf layout;
    feeding it back through our flexbuf converter round-trips exactly."""
    out = tmp_path / "dec.log"
    script = os.path.join(MODELS, "custom_decoder.py")
    p = parse_pipeline(
        "videotestsrc num-buffers=1 width=8 height=8 ! tensor_converter "
        f"! tensor_decoder mode=custom-script:{script} ! other/flexbuf ! "
        f"tensor_converter ! filesink location={out}")
    p.run(timeout=120)
    assert out.stat().st_size == 8 * 8 * 3  # decoded back to raw tensor


def test_multifilesink_writes_per_buffer(tmp_path):
    p = parse_pipeline(
        "videotestsrc num-buffers=3 width=4 height=4 ! tensor_converter "
        f'! multifilesink location="{tmp_path}/out_%1d.log"')
    p.run(timeout=60)
    for i in range(3):
        assert (tmp_path / f"out_{i}.log").stat().st_size == 4 * 4 * 3


def test_reference_split_single_seg_string(tmp_path):
    """nnstreamer_split/runTest.sh case 1, verbatim (incl. the spaced
    `format = RGB` caps): one tensorseg = identity split."""
    from PIL import Image

    rng = np.random.default_rng(13)
    arr = rng.integers(0, 255, (16, 16, 3)).astype(np.uint8)
    img = tmp_path / "testcase_RGB.png"
    Image.fromarray(arr).save(img)
    log = tmp_path / "split00.log"
    p = parse_pipeline(
        f"filesrc location={img} ! pngdec ! videoscale ! imagefreeze ! "
        "videoconvert ! video/x-raw, format = RGB, width=16, height=16, "
        "framerate=0/1 ! tensor_converter ! tensor_split name=split "
        "tensorseg=3:16:16 "
        f"split. ! queue ! filesink location={log}")
    p.run(timeout=120)
    np.testing.assert_array_equal(
        np.frombuffer(log.read_bytes(), np.uint8).reshape(16, 16, 3), arr)


def test_reference_split_two_segs_string(tmp_path):
    """nnstreamer_split/runTest.sh case 2 shape. Reference semantics are
    FLAT contiguous regions of the raster (gsttensorsplit.c:414-445
    memcpy at summed element offsets), NOT strided channel planes — the
    golden below is byte-for-byte what the reference's memcpy yields."""
    from PIL import Image

    rng = np.random.default_rng(14)
    arr = rng.integers(0, 255, (16, 16, 3)).astype(np.uint8)
    img = tmp_path / "t2.png"
    Image.fromarray(arr).save(img)
    l0, l1 = tmp_path / "split01_0.log", tmp_path / "split01_1.log"
    p = parse_pipeline(
        f"filesrc location={img} ! pngdec ! videoscale ! imagefreeze ! "
        "videoconvert ! video/x-raw, format = RGB, width=16, height=16, "
        "framerate=0/1 ! tensor_converter ! tensor_split name=split "
        "tensorseg=1:16:16,2:16:16 "
        f"split. ! queue ! filesink location={l0} "
        f"split. ! queue ! filesink location={l1}")
    p.run(timeout=120)
    flat = arr.reshape(-1)
    np.testing.assert_array_equal(
        np.frombuffer(l0.read_bytes(), np.uint8), flat[:256])
    np.testing.assert_array_equal(
        np.frombuffer(l1.read_bytes(), np.uint8), flat[256:])


def test_spaced_equals_prop_does_not_split_branch():
    """'name = queue' is one prop with value 'queue', not a new branch."""
    from nnstreamer_tpu.graph.parse import parse_pipeline as pp

    p = pp("videotestsrc num-buffers=1 width=4 height=4 ! "
           "tee name = t ! queue ! fakesink t. ! queue ! fakesink")
    assert "t" in p.elements
    p.run(timeout=30)


def test_reference_repo_loop_string(tmp_path):
    """nnstreamer_repo/runTest.sh case 1, verbatim: a reposink/reposrc
    handoff with the reference's caps-string prop on reposrc; each input
    frame comes back out through the repo slot."""
    from PIL import Image

    from nnstreamer_tpu.elements.repo import reset_repo

    reset_repo()
    rng = np.random.default_rng(15)
    arrs = [rng.integers(0, 255, (16, 16, 3)).astype(np.uint8)
            for _ in range(3)]
    for i, a in enumerate(arrs):
        Image.fromarray(a).save(tmp_path / f"testsequence_{i}.png")
    p = parse_pipeline(
        f'multifilesrc location={tmp_path}/testsequence_%1d.png index=0 '
        'caps="image/png,framerate=(fraction)3/1" ! pngdec ! '
        'tensor_converter ! queue ! tensor_reposink silent=false '
        'slot-index=0 '
        'tensor_reposrc silent=false slot-index=0 '
        'caps="other/tensor,dimension=(string)3:16:16:1,'
        'type=(string)uint8,framerate=(fraction)3/1" ! '
        f'multifilesink location={tmp_path}/testsequence01_%1d.log')
    p.run(timeout=120)
    # the repo src emits one zero initial frame, then the handed-off ones
    first = np.frombuffer(
        (tmp_path / "testsequence01_0.log").read_bytes(), np.uint8)
    assert first.size == 16 * 16 * 3 and not first.any()
    for i, a in enumerate(arrs[:2]):
        got = np.frombuffer(
            (tmp_path / f"testsequence01_{i + 1}.log").read_bytes(),
            np.uint8)
        np.testing.assert_array_equal(got, a.reshape(-1))


def test_repo_slot_reusable_across_runs(tmp_path):
    """A slot EOS'd by one run must serve a fresh run without
    reset_repo() (slots are process-global, runs are not)."""
    def run_once(seed):
        x = np.full((1, 4), float(seed), np.float32)
        p = parse_pipeline(
            "appsrc name=a ! tensor_reposink slot-index=55 "
            "tensor_reposrc slot-index=55 dims=4:1 types=float32 "
            "no-initial=true ! tensor_sink name=s store=true")
        p["a"].caps = __import__(
            "nnstreamer_tpu.core", fromlist=["Caps"]).Caps.tensors(
            __import__("nnstreamer_tpu.core", fromlist=["x"]).TensorsConfig(
                __import__("nnstreamer_tpu.core",
                           fromlist=["x"]).TensorsInfo.from_strings(
                    "4:1", "float32")))
        p["a"].data = [x]
        p.run(timeout=60)
        return p["s"].buffers[0].memories[0].host()

    np.testing.assert_array_equal(run_once(1), np.full((1, 4), 1.0))
    np.testing.assert_array_equal(run_once(2), np.full((1, 4), 2.0))


def test_base64ish_value_does_not_swallow_branch():
    """A complete prop value ending in '=' must not merge the following
    branch token."""
    p = parse_pipeline(
        "videotestsrc num-buffers=1 width=4 height=4 ! tensor_converter "
        "! tee name=t ! queue ! tensor_sink name=x store=true "
        "t. ! queue ! tensor_sink name=y store=true")
    # same topology but with a trailing-'=' value in an earlier prop
    p2 = parse_pipeline(
        'videotestsrc num-buffers=1 width=4 height=4 name=AB== ! '
        "tensor_converter ! tee name=t ! queue ! fakesink "
        "t. ! queue ! fakesink")
    assert "t" in p2.elements
    p.run(timeout=30)
    assert p["x"].num_buffers == 1 and p["y"].num_buffers == 1
