"""Reference pipeline STRINGS run unmodified (the north-star claim).

These are the reference's own gst-launch pipeline descriptions from its
SSAT suites — same element names, same properties, same model files —
parsed by graph/parse.py and executed end to end. Golden source:
tests/nnstreamer_filter_tensorflow2_lite/runTest.sh:74 (classification
must yield "orange") and its negative property cases (:79-84).
"""

import os

import numpy as np
import pytest

from nnstreamer_tpu.graph.parse import parse_pipeline

MODELS = "/root/reference/tests/test_models/models"
DATA = "/root/reference/tests/test_models/data"
LABELS = "/root/reference/tests/test_models/labels/labels.txt"

needs_ref = pytest.mark.skipif(
    not os.path.isdir(MODELS), reason="reference test models not mounted")

# the reference golden string, verbatim apart from the mounted paths and
# the v2-quant model actually shipped in the mount (runTest.sh names
# mobilenet_v1_1.0_224_quant.tflite, downloaded at test time there)
GOLDEN = (
    "filesrc location={img} ! pngdec ! videoscale ! imagefreeze ! "
    "videoconvert ! video/x-raw,format=RGB,framerate=0/1 ! "
    "tensor_converter ! "
    "tensor_filter framework=tensorflow2-lite model={model} ! "
    "filesink location={out}"
)


@needs_ref
def test_reference_golden_classification_string(tmp_path):
    out = tmp_path / "tensorfilter.out.log"
    p = parse_pipeline(GOLDEN.format(
        img=os.path.join(DATA, "orange.png"),
        model=os.path.join(MODELS, "mobilenet_v2_1.0_224_quant.tflite"),
        out=out))
    p.run(timeout=300)
    # checkLabel.py semantics: raw output bytes -> argmax -> label text
    scores = np.frombuffer(out.read_bytes(), np.uint8)
    assert scores.size == 1001
    labels = open(LABELS).read().splitlines()
    assert labels[int(scores.argmax())] == "orange"


@needs_ref
def test_reference_negative_invalid_input_property(tmp_path):
    """runTest.sh 2F_n: invalid input= dims must FAIL the pipeline."""
    bad = GOLDEN.format(
        img=os.path.join(DATA, "orange.png"),
        model=os.path.join(MODELS, "mobilenet_v2_1.0_224_quant.tflite"),
        out=tmp_path / "o.log").replace(
        "! filesink",
        "input=7:1 inputtype=float32 ! filesink")
    p = parse_pipeline(bad)
    with pytest.raises(Exception):
        p.run(timeout=120)


@needs_ref
def test_reference_add_pipeline_string(tmp_path):
    """runTest.sh-style add.tflite passthrough-plus-two over octet input."""
    raw = tmp_path / "x.raw"
    np.array([2.5], np.float32).tofile(raw)
    out = tmp_path / "add.out"
    p = parse_pipeline(
        f"filesrc location={raw} ! "
        "tensor_converter input-dim=1 input-type=float32 ! "
        f"tensor_filter framework=tensorflow2-lite "
        f"model={os.path.join(MODELS, 'add.tflite')} ! "
        f"filesink location={out}")
    p.run(timeout=120)
    assert np.frombuffer(out.read_bytes(), np.float32)[0] == 4.5


def test_imagefreeze_repeats_frames(tmp_path):
    from PIL import Image

    img = tmp_path / "t.png"
    Image.fromarray(np.full((8, 8, 3), 7, np.uint8)).save(img)
    p = parse_pipeline(
        f"filesrc location={img} ! pngdec ! imagefreeze num_buffers=5 ! "
        "tensor_converter ! tensor_sink store=true")
    p.run(timeout=60)
    sink = [e for e in p.elements.values()
            if e.ELEMENT_NAME == "tensor_sink"][0]
    assert sink.num_buffers == 5
    assert sink.buffers[4].offset == 4
