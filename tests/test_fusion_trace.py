"""Graph fusion pass + pipeline tracer tests."""

import numpy as np
import pytest

from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline


def caps_of(dims, types, rate=30):
    return Caps.tensors(TensorsConfig(TensorsInfo.from_strings(dims, types), rate))


def build(auto_fuse, data):
    p = Pipeline()
    p.auto_fuse = auto_fuse
    src = p.add_new("appsrc", caps=caps_of("4:1", "uint8"), data=data)
    t1 = p.add_new("tensor_transform", mode="arithmetic",
                   option="typecast:float32,add:-127.5,div:127.5")
    t2 = p.add_new("tensor_transform", mode="clamp", option="-0.5:0.5")
    f = p.add_new("tensor_filter", model=lambda x: x * 2)
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, t1, t2, f, sink)
    p.run(timeout=60)
    return p, sink


class TestFusion:
    def test_fused_matches_unfused(self):
        data = [np.array([[0, 100, 127, 255]], np.uint8)]
        p_fused, s_fused = build(True, data)
        p_plain, s_plain = build(False, data)
        assert p_fused._fused_count == 2
        assert p_plain._fused_count == 0
        np.testing.assert_allclose(s_fused.buffers[0].memories[0].host(),
                                   s_plain.buffers[0].memories[0].host(),
                                   rtol=1e-6)

    def test_fused_transforms_forward_untouched(self):
        data = [np.array([[1, 2, 3, 4]], np.uint8)]
        p, sink = build(True, data)
        t1 = p["tensor_transform0"] if "tensor_transform0" in p.elements else None
        # find the transform elements generically
        from nnstreamer_tpu.elements.transform import TensorTransform

        transforms = [e for e in p.elements.values()
                      if isinstance(e, TensorTransform)]
        assert all(t._fused for t in transforms)

    def test_fusion_stops_at_branching(self):
        p = Pipeline()
        src = p.add_new("appsrc", caps=caps_of("4:1", "uint8"),
                        data=[np.ones((1, 4), np.uint8)])
        t = p.add_new("tensor_transform", mode="typecast", option="float32")
        tee = p.add_new("tee")
        q1 = p.add_new("queue")
        f = p.add_new("tensor_filter", model=lambda x: x + 1)
        s1 = p.add_new("tensor_sink", store=True)
        q2 = p.add_new("queue")
        s2 = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, t, tee)
        Pipeline.link(tee, q1, f, s1)
        Pipeline.link(tee, q2, s2)
        p.run(timeout=60)
        # transform feeds a tee → must NOT be fused away
        from nnstreamer_tpu.elements.transform import TensorTransform

        tr = next(e for e in p.elements.values() if isinstance(e, TensorTransform))
        assert not tr._fused
        np.testing.assert_array_equal(s1.buffers[0].memories[0].host(),
                                      np.full((1, 4), 2.0, np.float32))


class TestTracer:
    def test_proctime_collection(self):
        from nnstreamer_tpu.utils.trace import PipelineTracer

        p = Pipeline()
        src = p.add_new("videotestsrc", width=16, height=16, num_buffers=5)
        conv = p.add_new("tensor_converter")
        sink = p.add_new("tensor_sink")
        Pipeline.link(src, conv, sink)
        tracer = PipelineTracer.attach(p)
        p.run(timeout=30)
        d = tracer.as_dict()
        assert d[conv.name]["n"] == 5
        assert d[conv.name]["proctime_us"] > 0
        assert d[sink.name]["interlatency_us"] > 0
        assert conv.name in tracer.report()
