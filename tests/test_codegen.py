"""nns-new-filter scaffolding: generated skeletons must compile/load and
serve frames (reference dev-tool parity:
tools/development/nnstreamerCodeGenCustomFilter.py)."""

import os
import shutil
import subprocess

import numpy as np
import pytest

from nnstreamer_tpu.codegen import generate, main
from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline


def caps_of(dims, types, rate=30):
    return Caps.tensors(
        TensorsConfig(TensorsInfo.from_strings(dims, types), rate))


def test_generated_python_filter_serves(tmp_path):
    (path,) = generate("myscaler", "py", str(tmp_path))
    assert os.path.basename(path) == "myscaler.py"
    x = np.arange(4, dtype=np.float32).reshape(1, 4)
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps_of("4:1", "float32"), data=[x])
    filt = p.add_new("tensor_filter", framework="python3", model=path)
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, filt, sink)
    p.run(timeout=60)
    np.testing.assert_allclose(sink.buffers[0].memories[0].host(), x)


@pytest.mark.skipif(shutil.which("gcc") is None and
                    shutil.which("cc") is None, reason="no C compiler")
def test_generated_c_filter_compiles_and_serves(tmp_path):
    src_c, makefile = generate("cscale", "c", str(tmp_path))
    subprocess.run(["make", "-C", str(tmp_path)], check=True,
                   capture_output=True)
    so = tmp_path / "libcscale.so"
    assert so.exists()
    x = np.arange(4, dtype=np.float32).reshape(1, 4)
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps_of("4:1", "float32"), data=[x])
    filt = p.add_new("tensor_filter", framework="custom", model=str(so))
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, filt, sink)
    p.run(timeout=60)
    np.testing.assert_allclose(sink.buffers[0].memories[0].host(), x * 2.0)


def test_refuses_overwrite_and_bad_names(tmp_path):
    generate("dup", "py", str(tmp_path))
    with pytest.raises(FileExistsError):
        generate("dup", "py", str(tmp_path))
    with pytest.raises(ValueError, match="identifier"):
        generate("bad-name", "py", str(tmp_path))


def test_cli_entry(tmp_path, capsys):
    assert main(["gencli", "--dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert str(tmp_path / "gencli.py") in out
    assert main(["gencli", "--dir", str(tmp_path)]) == 1  # exists


def test_second_c_filter_shares_makefile(tmp_path):
    generate("f_one", "c", str(tmp_path))
    generate("f_two", "c", str(tmp_path))  # Makefile reused, no collision
    subprocess.run(["make", "-C", str(tmp_path)], check=True,
                   capture_output=True)
    assert (tmp_path / "libf_one.so").exists()
    assert (tmp_path / "libf_two.so").exists()
