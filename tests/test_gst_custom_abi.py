"""Reference custom-filter .so binary ABI (NNStreamer_custom vtable).

The fixture below is OUR OWN C source compiled against the REFERENCE's
public devel headers (tensor_filter_custom.h — the file its packagers ship
to NN developers), so the resulting .so is exactly what an existing
NNStreamer custom-filter plugin is: if it loads and serves here, real
reference plugins do too.
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline

REF_INC = "/root/reference/gst/nnstreamer/include"

needs_ref = pytest.mark.skipif(
    not os.path.isdir(REF_INC) or shutil.which("gcc") is None,
    reason="reference headers or gcc not available")

# our own plugin source, written fresh against the public ABI: a filter
# that doubles float32 input, declares 4:1 I/O via getInputDim/getOutputDim
_PLUGIN_SRC = r"""
#include <stdlib.h>
#include <string.h>
#include "tensor_filter_custom.h"

static void *pv_init (const GstTensorFilterProperties *prop)
{
  (void) prop;
  return malloc (4);  /* non-NULL private data */
}

static void pv_exit (void *pd, const GstTensorFilterProperties *prop)
{
  (void) prop;
  free (pd);
}

static void set_41_f32 (GstTensorsInfo *info)
{
  unsigned int i;
  memset (info, 0, sizeof (*info));
  info->num_tensors = 1;
  info->info[0].type = _NNS_FLOAT32;
  info->info[0].dimension[0] = 4;
  for (i = 1; i < 4; i++)
    info->info[0].dimension[i] = 1;
}

static int get_in (void *pd, const GstTensorFilterProperties *prop,
    GstTensorsInfo *info)
{
  (void) pd; (void) prop;
  set_41_f32 (info);
  return 0;
}

static int get_out (void *pd, const GstTensorFilterProperties *prop,
    GstTensorsInfo *info)
{
  (void) pd; (void) prop;
  set_41_f32 (info);
  return 0;
}

static int pv_invoke (void *pd, const GstTensorFilterProperties *prop,
    const GstTensorMemory *input, GstTensorMemory *output)
{
  size_t i, n = input[0].size / sizeof (float);
  const float *in = (const float *) input[0].data;
  float *out = (float *) output[0].data;
  (void) pd; (void) prop;
  for (i = 0; i < n; i++)
    out[i] = in[i] * 2.0f;
  return 0;
}

static NNStreamer_custom_class cls = {
  .initfunc = pv_init,
  .exitfunc = pv_exit,
  .getInputDim = get_in,
  .getOutputDim = get_out,
  .setInputDim = NULL,
  .invoke = pv_invoke,
  .allocate_invoke = NULL,
  .destroy_notify = NULL,
};

NNStreamer_custom_class *NNStreamer_custom = &cls;
"""


def _build(tmp_path):
    src = tmp_path / "ref_abi_filter.c"
    src.write_text(_PLUGIN_SRC)
    so = tmp_path / "libref_abi_filter.so"
    subprocess.run(
        ["gcc", "-O2", "-fPIC", "-shared", "-I", REF_INC,
         "-o", str(so), str(src)],
        check=True, capture_output=True)
    return so


def caps_of(dims, types):
    return Caps.tensors(
        TensorsConfig(TensorsInfo.from_strings(dims, types), 30))


@needs_ref
def test_reference_abi_so_compiles_and_serves(tmp_path):
    so = _build(tmp_path)
    x = np.arange(4, dtype=np.float32).reshape(1, 4)
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps_of("4:1", "float32"), data=[x])
    filt = p.add_new("tensor_filter", framework="custom", model=str(so))
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, filt, sink)
    p.run(timeout=60)
    np.testing.assert_allclose(
        sink.buffers[0].memories[0].host().reshape(-1),
        (x * 2.0).reshape(-1))


@needs_ref
def test_reference_abi_model_info(tmp_path):
    from nnstreamer_tpu.filters.base import FilterProps
    from nnstreamer_tpu.filters.c_custom import CCustomFilter

    so = _build(tmp_path)
    f = CCustomFilter()
    f.open(FilterProps(model=str(so)))
    ii, oi = f.get_model_info()
    assert ii[0].dim_string == "4:1" or ii[0].dims == (4,)
    assert str(ii[0].dtype) == "float32"
    f.close()


@needs_ref
def test_flat_abi_still_loads(tmp_path):
    """Detection must not break the flat nns_custom.h ABI."""
    from nnstreamer_tpu.codegen import generate

    generate("flatone", "c", str(tmp_path))
    subprocess.run(["make", "-C", str(tmp_path)], check=True,
                   capture_output=True)
    x = np.arange(4, dtype=np.float32).reshape(1, 4)
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps_of("4:1", "float32"), data=[x])
    filt = p.add_new("tensor_filter", framework="custom",
                     model=str(tmp_path / "libflatone.so"))
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, filt, sink)
    p.run(timeout=60)
    np.testing.assert_allclose(sink.buffers[0].memories[0].host(), x * 2.0)


# two-tensor plugin that also reads prop->custom_properties and soft-drops
# when it says "drop" — multi-tensor structs + the properties block offsets
# would all break under any ctypes layout mismatch
_PLUGIN2_SRC = r"""
#include <stdlib.h>
#include <string.h>
#include "tensor_filter_custom.h"

static void *pv_init (const GstTensorFilterProperties *prop)
{
  int *drop = malloc (sizeof (int));
  *drop = (prop->custom_properties != NULL &&
           strcmp (prop->custom_properties, "drop") == 0);
  return drop;
}

static void pv_exit (void *pd, const GstTensorFilterProperties *prop)
{
  (void) prop;
  free (pd);
}

static void set_two (GstTensorsInfo *info)
{
  unsigned int i;
  memset (info, 0, sizeof (*info));
  info->num_tensors = 2;
  info->info[0].type = _NNS_FLOAT32;
  info->info[0].dimension[0] = 3;
  info->info[1].type = _NNS_INT32;
  info->info[1].dimension[0] = 2;
  for (i = 1; i < NNS_TENSOR_RANK_LIMIT; i++) {
    info->info[0].dimension[i] = 1;
    info->info[1].dimension[i] = 1;
  }
}

static int get_in (void *pd, const GstTensorFilterProperties *prop,
    GstTensorsInfo *info)
{
  (void) pd; (void) prop;
  set_two (info);
  return 0;
}

static int get_out (void *pd, const GstTensorFilterProperties *prop,
    GstTensorsInfo *info)
{
  (void) pd; (void) prop;
  set_two (info);
  return 0;
}

static int pv_invoke (void *pd, const GstTensorFilterProperties *prop,
    const GstTensorMemory *input, GstTensorMemory *output)
{
  size_t i;
  const float *f_in = (const float *) input[0].data;
  float *f_out = (float *) output[0].data;
  const int32_t *i_in = (const int32_t *) input[1].data;
  int32_t *i_out = (int32_t *) output[1].data;
  (void) prop;
  if (*(int *) pd)
    return 1;  /* soft drop */
  for (i = 0; i < input[0].size / sizeof (float); i++)
    f_out[i] = f_in[i] + 0.5f;
  for (i = 0; i < input[1].size / sizeof (int32_t); i++)
    i_out[i] = i_in[i] - 1;
  return 0;
}

static NNStreamer_custom_class cls = {
  .initfunc = pv_init,
  .exitfunc = pv_exit,
  .getInputDim = get_in,
  .getOutputDim = get_out,
  .setInputDim = NULL,
  .invoke = pv_invoke,
  .allocate_invoke = NULL,
  .destroy_notify = NULL,
};

NNStreamer_custom_class *NNStreamer_custom = &cls;
"""


def _build2(tmp_path):
    src = tmp_path / "ref_abi_two.c"
    src.write_text(_PLUGIN2_SRC)
    so = tmp_path / "libref_abi_two.so"
    subprocess.run(
        ["gcc", "-O2", "-fPIC", "-shared", "-I", REF_INC,
         "-o", str(so), str(src)],
        check=True, capture_output=True)
    return so


@needs_ref
def test_reference_abi_multi_tensor_and_custom_props(tmp_path):
    """Two-tensor I/O + custom_properties readback: any struct layout
    drift between the compiled .so and the ctypes mapping breaks this."""
    so = _build2(tmp_path)
    f32 = np.array([1.0, 2.0, 3.0], np.float32).reshape(1, 3)
    i32 = np.array([10, 20], np.int32).reshape(1, 2)
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps_of("3:1,2:1", "float32,int32"),
                    data=[(f32, i32)])
    filt = p.add_new("tensor_filter", framework="custom", model=str(so))
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, filt, sink)
    p.run(timeout=60)
    out = sink.buffers[0]
    np.testing.assert_allclose(out.memories[0].host().reshape(-1),
                               [1.5, 2.5, 3.5])
    np.testing.assert_array_equal(out.memories[1].host().reshape(-1),
                                  [9, 19])


@needs_ref
def test_reference_abi_custom_props_soft_drop(tmp_path):
    """custom=drop reaches the plugin through prop->custom_properties
    (offset-sensitive) and its ret>0 soft-drops every frame."""
    so = _build2(tmp_path)
    f32 = np.zeros((1, 3), np.float32)
    i32 = np.zeros((1, 2), np.int32)
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps_of("3:1,2:1", "float32,int32"),
                    data=[(f32, i32)] * 3)
    filt = p.add_new("tensor_filter", framework="custom", model=str(so),
                     custom="drop")
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, filt, sink)
    p.run(timeout=60)
    assert sink.num_buffers == 0  # every frame soft-dropped


@needs_ref
def test_no_invoke_callback_rejected_at_open(tmp_path):
    """A .so defining neither invoke nor allocate_invoke must fail at open
    (the reference's custom_open XOR check), not NULL-call at frame 1."""
    src_text = _PLUGIN_SRC.replace(
        ".invoke = pv_invoke,", ".invoke = NULL,")
    src = tmp_path / "no_invoke.c"
    src.write_text(src_text)
    so = tmp_path / "libno_invoke.so"
    subprocess.run(
        ["gcc", "-O2", "-fPIC", "-shared", "-I", REF_INC,
         "-o", str(so), str(src)],
        check=True, capture_output=True)
    p = Pipeline()
    src_el = p.add_new("appsrc", caps=caps_of("4:1", "float32"),
                       data=[np.zeros((1, 4), np.float32)])
    filt = p.add_new("tensor_filter", framework="custom", model=str(so))
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src_el, filt, sink)
    with pytest.raises(Exception, match="invoke"):
        p.run(timeout=60)
