"""tensor_filter + backend tests (mirrors reference unittest_filter_* and
tensor_filter SSAT groups: auto-detect, props, stats, combinations, reload,
shared key, custom-easy, python3)."""

import numpy as np
import pytest

from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.filters import register_custom_easy, unregister_custom_easy
from nnstreamer_tpu.graph import Pipeline, PipelineError
from nnstreamer_tpu.models.zoo import get_model


def tensor_caps(dims, types, rate=30):
    return Caps.tensors(TensorsConfig(TensorsInfo.from_strings(dims, types), rate))


def run_filter_pipeline(data, caps, sink_store=True, **filter_props):
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps, data=data)
    f = p.add_new("tensor_filter", **filter_props)
    sink = p.add_new("tensor_sink", store=sink_store)
    Pipeline.link(src, f, sink)
    p.run(timeout=60)
    return f, sink


class TestXLABackend:
    def test_zoo_scaler(self):
        f, sink = run_filter_pipeline(
            [np.full((1, 8), 3.0, np.float32)],
            tensor_caps("8:1", "float32"),
            framework="xla-tpu", model="zoo://scaler?dims=8:1&types=float32&scale=5")
        np.testing.assert_array_equal(sink.buffers[0].memories[0].host(),
                                      np.full((1, 8), 15.0, np.float32))

    def test_callable_model_auto_detect(self):
        import jax.numpy as jnp

        f, sink = run_filter_pipeline(
            [np.ones((1, 4), np.float32)],
            tensor_caps("4:1", "float32"),
            model=lambda x: jnp.sum(x, axis=1, keepdims=True))
        assert f.resolved_framework == "xla-tpu"
        np.testing.assert_array_equal(sink.buffers[0].memories[0].host(), [[4.0]])

    def test_out_caps_from_model_info(self):
        f, sink = run_filter_pipeline(
            [np.ones((1, 4), np.float32)],
            tensor_caps("4:1", "float32"),
            model=lambda x: x.reshape(1, 2, 2))
        cfg = sink.buffers[0].config
        assert cfg.info[0].shape == (1, 2, 2)

    def test_incompatible_stream_fails(self):
        with pytest.raises(PipelineError, match="incompatible"):
            run_filter_pipeline(
                [np.ones((1, 7), np.float32)],
                tensor_caps("7:1", "float32"),
                framework="xla-tpu",
                model="zoo://scaler?dims=8:1&types=float32")

    def test_stats_recorded(self):
        f, sink = run_filter_pipeline(
            [np.ones((1, 4), np.float32)] * 5,
            tensor_caps("4:1", "float32"),
            model=lambda x: x * 2, custom="sync=true")
        assert f.latency >= 0
        assert f.stats.total_invoke_num == 5

    def test_multi_output_model(self):
        f, sink = run_filter_pipeline(
            [np.ones((1, 4), np.float32)],
            tensor_caps("4:1", "float32"),
            model=lambda x: (x * 2, x + 1))
        assert sink.buffers[0].num_tensors == 2

    def test_bf16_precision_option(self):
        f, sink = run_filter_pipeline(
            [np.full((1, 4), 2.0, np.float32)],
            tensor_caps("4:1", "float32"),
            model=lambda x: x * x, custom="precision=bf16")
        out = sink.buffers[0].memories[0].host()
        assert str(out.dtype) == "bfloat16"
        np.testing.assert_allclose(np.asarray(out, np.float32), 4.0)


class TestCombinations:
    def test_input_combination(self):
        p = Pipeline()
        src = p.add_new("appsrc", caps=tensor_caps("4:1,2:1", "float32,float32"),
                        data=[(np.ones((1, 4), np.float32),
                               np.full((1, 2), 9.0, np.float32))])
        f = p.add_new("tensor_filter", model=lambda x: x * 10,
                      input_combination="1")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, f, sink)
        p.run(timeout=30)
        np.testing.assert_array_equal(sink.buffers[0].memories[0].host(),
                                      np.full((1, 2), 90.0, np.float32))

    def test_output_combination_forwards_input(self):
        p = Pipeline()
        src = p.add_new("appsrc", caps=tensor_caps("4:1", "float32"),
                        data=[np.full((1, 4), 2.0, np.float32)])
        f = p.add_new("tensor_filter", model=lambda x: x * 3,
                      output_combination="i0,o0")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, f, sink)
        p.run(timeout=30)
        b = sink.buffers[0]
        assert b.num_tensors == 2
        np.testing.assert_array_equal(b.memories[0].host(),
                                      np.full((1, 4), 2.0, np.float32))
        np.testing.assert_array_equal(b.memories[1].host(),
                                      np.full((1, 4), 6.0, np.float32))
        # caps reflect the combination
        assert b.config.info.num_tensors == 2


class TestCustomEasy:
    def test_roundtrip(self):
        register_custom_easy("doubler", lambda x: x * 2,
                             ("4:1", "float32"), ("4:1", "float32"))
        try:
            f, sink = run_filter_pipeline(
                [np.ones((1, 4), np.float32)],
                tensor_caps("4:1", "float32"),
                framework="custom-easy", model="doubler")
            np.testing.assert_array_equal(sink.buffers[0].memories[0].host(),
                                          np.full((1, 4), 2.0, np.float32))
        finally:
            unregister_custom_easy("doubler")

    def test_unregistered_fails(self):
        with pytest.raises(ValueError, match="not registered"):
            run_filter_pipeline([np.ones((1, 4), np.float32)],
                                tensor_caps("4:1", "float32"),
                                framework="custom-easy", model="nope")


class TestPython3Backend:
    def test_script_filter(self, tmp_path):
        script = tmp_path / "pyfilter.py"
        script.write_text(
            "import numpy as np\n"
            "class CustomFilter:\n"
            "    def getInputDimension(self):\n"
            "        return ('4:1', 'float32')\n"
            "    def getOutputDimension(self):\n"
            "        return ('4:1', 'float32')\n"
            "    def invoke(self, x):\n"
            "        return x + 100\n")
        f, sink = run_filter_pipeline(
            [np.zeros((1, 4), np.float32)],
            tensor_caps("4:1", "float32"),
            framework="python3", model=str(script))
        np.testing.assert_array_equal(sink.buffers[0].memories[0].host(),
                                      np.full((1, 4), 100.0, np.float32))

    def test_auto_detect_py_extension(self, tmp_path):
        from nnstreamer_tpu.filters import detect_framework

        script = tmp_path / "f.py"
        script.write_text("x = 1\n")
        assert detect_framework(str(script)) == "python3"


class TestReload:
    def test_hot_reload(self):
        import jax.numpy as jnp

        p = Pipeline()
        src = p.add_new("appsrc", caps=tensor_caps("4:1", "float32"),
                        data=None)
        f = p.add_new("tensor_filter", model=lambda x: x * 2, is_updatable=True)
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, f, sink)
        p.start()
        src.push_buffer(np.ones((1, 4), np.float32))
        import time

        time.sleep(0.5)
        f.update_model(lambda x: x * 5)
        src.push_buffer(np.ones((1, 4), np.float32))
        src.end_of_stream()
        p.wait_eos(30)
        p.stop()
        assert sink.num_buffers == 2
        np.testing.assert_array_equal(sink.buffers[0].memories[0].host()[0, 0], 2.0)
        np.testing.assert_array_equal(sink.buffers[1].memories[0].host()[0, 0], 5.0)

    def test_reload_rejects_shape_change(self):
        f, sink = run_filter_pipeline(
            [np.ones((1, 4), np.float32)],
            tensor_caps("4:1", "float32"),
            model=lambda x: x * 2, is_updatable=True)
        with pytest.raises(ValueError, match="reload rejected"):
            f._open_fw()  # reopen after stop for direct fw access
            f.fw.set_input_info(TensorsInfo.from_strings("4:1", "float32"))
            f.fw.reload_model(lambda x: x.reshape(2, 2, 1))

    def test_not_updatable_fails(self):
        f, sink = run_filter_pipeline(
            [np.ones((1, 4), np.float32)],
            tensor_caps("4:1", "float32"), model=lambda x: x)
        with pytest.raises(RuntimeError, match="not is-updatable"):
            f.update_model(lambda x: x * 2)


class TestSharedModel:
    def test_shared_backend_instance(self):
        p = Pipeline()
        caps = tensor_caps("4:1", "float32")
        src1 = p.add_new("appsrc", caps=caps, data=[np.ones((1, 4), np.float32)])
        src2 = p.add_new("appsrc", caps=caps, data=[np.ones((1, 4), np.float32)])
        f1 = p.add_new("tensor_filter", model="zoo://scaler?dims=4:1&types=float32",
                       framework="xla-tpu", shared_tensor_filter_key="k1")
        f2 = p.add_new("tensor_filter", model="zoo://scaler?dims=4:1&types=float32",
                       framework="xla-tpu", shared_tensor_filter_key="k1")
        s1 = p.add_new("tensor_sink")
        s2 = p.add_new("tensor_sink")
        Pipeline.link(src1, f1, s1)
        Pipeline.link(src2, f2, s2)
        p.run(timeout=60)
        assert f1.fw is None and f2.fw is None  # both closed/released
        assert s1.num_buffers == 1 and s2.num_buffers == 1


class TestMobileNetV2:
    def test_tiny_mobilenet_forward(self):
        bundle = get_model("zoo://mobilenet_v2?width=0.1&size=32&num_classes=10")
        f, sink = run_filter_pipeline(
            [np.random.default_rng(0).integers(0, 255, (1, 32, 32, 3)).astype(np.uint8)],
            tensor_caps("3:32:32:1", "uint8", 30),
            framework="xla-tpu", model=bundle)
        out = sink.buffers[0].memories[0].host()
        assert out.shape == (1, 10)
        assert out.dtype == np.float32
        assert np.all(np.isfinite(out))


class TestBucketedInvoke:
    """custom="bucket=N": dynamic-count flexible streams (tensor_crop
    regions) through static-shape XLA programs via batch padding."""

    def test_crop_to_bucketed_filter(self):
        def region_mean(x):  # (B, H, W, C) -> (B, C)
            return x.mean(axis=(1, 2))

        img = np.arange(12 * 12 * 2, dtype=np.float32).reshape(1, 12, 12, 2)
        frames = [np.array([[0, 0, 4, 4], [2, 2, 4, 4], [1, 1, 8, 8]], np.int32),
                  np.array([[0, 0, 4, 4]], np.int32)]  # n varies per frame
        p = Pipeline()
        raw = p.add_new("appsrc",
                        caps=tensor_caps("2:12:12:1", "float32"),
                        data=[img, img.copy()], framerate=30)
        info = p.add_new("appsrc", caps=Caps.tensors(TensorsConfig(
            TensorsInfo((), __import__("nnstreamer_tpu").core.TensorFormat.FLEXIBLE), 30)),
            data=frames)
        crop = p.add_new("tensor_crop")
        filt = p.add_new("tensor_filter", framework="xla-tpu",
                         model=region_mean, custom="bucket=4,resize=4:4")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(raw, crop)
        Pipeline.link(info, crop)
        Pipeline.link(crop, filt, sink)
        p.run(timeout=120)
        assert sink.num_buffers == 2
        out0 = sink.buffers[0].memories[0].host()
        out1 = sink.buffers[1].memories[0].host()
        assert out0.shape == (3, 2) and out1.shape == (1, 2)
        # region 0 of frame 0: img[0, 0:4, 0:4] — resize 4x4 is identity
        np.testing.assert_allclose(out0[0], img[0, 0:4, 0:4].mean(axis=(0, 1)),
                                   rtol=1e-5)
        np.testing.assert_allclose(out1[0], out0[0], rtol=1e-5)

    def test_mixed_shapes_without_resize_fails(self):
        def ident(x):
            return x

        p = Pipeline()
        img = np.zeros((1, 10, 10, 1), np.float32)
        boxes = np.array([[0, 0, 2, 2], [0, 0, 4, 4]], np.int32)
        raw = p.add_new("appsrc", caps=tensor_caps("1:10:10:1", "float32"),
                        data=[img], framerate=30)
        info = p.add_new("appsrc", caps=tensor_caps("4:2", "int32"),
                         data=[boxes], framerate=30)
        crop = p.add_new("tensor_crop")
        filt = p.add_new("tensor_filter", framework="xla-tpu", model=ident,
                         custom="bucket=4")
        sink = p.add_new("tensor_sink")
        Pipeline.link(raw, crop)
        Pipeline.link(info, crop)
        Pipeline.link(crop, filt, sink)
        with pytest.raises(PipelineError, match="same-shape"):
            p.run(timeout=60)


# --------------------------------------------------------------------------- #
# Serialized model deployment (models/deploy.py)
# --------------------------------------------------------------------------- #

class TestSerializedDeployment:
    def test_export_load_roundtrip_exact(self, tmp_path):
        """Deterministic fn: exported artifact reproduces exact outputs."""
        import numpy as np
        from nnstreamer_tpu.models import export_model, load_exported

        path = str(tmp_path / "double.jaxexport")
        export_model(path, lambda x: x * 2.0 + 1.0,
                     example_args=[np.zeros((2, 3), np.float32)])
        bundle = load_exported(path)
        out = bundle.fn()(np.ones((2, 3), np.float32))[0]
        np.testing.assert_allclose(np.asarray(out), np.full((2, 3), 3.0))
        assert bundle.in_info[0].shape == (2, 3)
        assert bundle.out_info[0].shape == (2, 3)
        assert "cpu" in bundle.metadata["platforms"]

    def test_cross_process_export_then_pipeline_deploy(self, tmp_path):
        """VERDICT r2 #2 acceptance: export in ONE process, load+invoke
        e2e in ANOTHER via a pipeline string — no Python model source in
        the consumer."""
        import os
        import subprocess
        import sys

        import numpy as np

        path = str(tmp_path / "model.jaxexport")
        code = f"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from nnstreamer_tpu.models import export_model, get_model
bundle = get_model("zoo://mobilenet_v2?width=0.25&size=32&num_classes=7&dtype=float32")
export_model({path!r}, bundle)
"""
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=300,
                           cwd=os.path.dirname(os.path.dirname(
                               os.path.abspath(__file__))))
        assert r.returncode == 0, r.stderr[-2000:]

        from nnstreamer_tpu.graph import Pipeline

        p = Pipeline()
        src = p.add_new("videotestsrc", width=32, height=32, num_buffers=2,
                        pattern="random")
        conv = p.add_new("tensor_converter")
        filt = p.add_new("tensor_filter", model=path)  # framework=auto
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, conv, filt, sink)
        p.run(timeout=180)
        assert filt.resolved_framework == "xla-tpu"
        assert sink.num_buffers == 2
        assert sink.buffers[0].memories[0].host().shape == (1, 7)

    def test_checkpoint_plus_arch_deploy(self, tmp_path):
        """Trained-weights deployment: params checkpoint + arch= glue."""
        import numpy as np
        from nnstreamer_tpu.models import get_model, load_checkpointed
        from nnstreamer_tpu.utils.checkpoints import save_variables

        arch = "zoo://mobilenet_v2?width=0.25&size=32&num_classes=5&dtype=float32"
        bundle = get_model(arch)
        ckpt = str(tmp_path / "params.msgpack")
        save_variables(ckpt, bundle.params)
        restored = load_checkpointed(
            ckpt, "zoo://mobilenet_v2", width="0.25", size="32",
            num_classes="5", dtype="float32")
        x = np.random.default_rng(0).normal(size=(1, 32, 32, 3)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(bundle.fn()(x)), np.asarray(restored.fn()(x)),
            rtol=1e-6)

    def test_checkpoint_via_filter_custom_arch(self, tmp_path):
        """Pipeline-string form: model=<ckpt> custom="arch=...;arch_*"."""
        from nnstreamer_tpu.core.buffer import TensorMemory
        from nnstreamer_tpu.filters.base import FilterProps, detect_framework
        from nnstreamer_tpu.filters.xla import XLAFilter
        from nnstreamer_tpu.models import get_model
        from nnstreamer_tpu.utils.checkpoints import save_variables

        import numpy as np

        assert detect_framework("foo.jaxexport") == "xla-tpu"
        assert detect_framework("foo.msgpack") == "xla-tpu"

        bundle = get_model("zoo://lstm_cell?features=4&input_size=3")
        ckpt = str(tmp_path / "cell.msgpack")
        save_variables(ckpt, bundle.params)
        f = XLAFilter()
        f.open(FilterProps(
            model=ckpt,
            custom="sync=true,arch=zoo://lstm_cell,arch_features=4,"
                   "arch_input_size=3"))
        x = np.zeros((1, 3), np.float32)
        h = np.zeros((1, 4), np.float32)
        c = np.zeros((1, 4), np.float32)
        outs = f.invoke([TensorMemory(x), TensorMemory(h), TensorMemory(c)])
        ref = bundle.fn()(x, h, c)
        ref = ref if isinstance(ref, (tuple, list)) else (ref,)
        for o, r in zip(outs, ref):
            np.testing.assert_allclose(o.host(), np.asarray(r), rtol=1e-6)

    def test_missing_arch_rejected(self, tmp_path):
        import pytest

        from nnstreamer_tpu.filters.xla import resolve_model

        ckpt = tmp_path / "w.msgpack"
        ckpt.write_bytes(b"x")
        with pytest.raises(ValueError, match="arch"):
            resolve_model(str(ckpt))
