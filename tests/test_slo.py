"""obs.slo tests — the zero-overhead-when-off hook contract, per-tenant
cost-attribution conservation against DeviceEngine totals, goodput and
shed accounting, fake-clock multi-window burn-rate evaluation, the
health-registry breach/recovery loop (slo.burn_alert / slo.recover),
the sched starvation-storm watchdog rule, the /debug/slo and
/debug/profile/samples exporter routes, the fleet slo rollup, the
Perfetto per-tenant goodput lane, and the --slo spec parser."""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from nnstreamer_tpu.core.buffer import TensorMemory
from nnstreamer_tpu.obs import events as obs_events
from nnstreamer_tpu.obs import fleet as obs_fleet
from nnstreamer_tpu.obs import health as obs_health
from nnstreamer_tpu.obs import metrics as obs_metrics
from nnstreamer_tpu.obs import profile as obs_profile
from nnstreamer_tpu.obs import slo
from nnstreamer_tpu.obs.exporter import start_exporter
from nnstreamer_tpu.obs.fleet import FleetAggregator
from nnstreamer_tpu.obs.health import Status
from nnstreamer_tpu.sched import SHED, DeviceEngine


class FakeClock:
    """Injectable monotonic-seconds source (no sleeping in burn tests)."""

    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FakeDeadline:
    def __init__(self, expired: bool) -> None:
        self._expired = expired

    def expired(self) -> bool:
        return self._expired


class TagFilter:
    """Minimal filter double (distinct instances never coalesce)."""

    def __init__(self, name="f"):
        self.name = name

    def invoke(self, inputs):
        return [inputs[0].host() * 2]


def _mem(rows=2):
    return TensorMemory(np.ones((rows, 2), np.float32))


_THRESHOLDS = ("stall_after_s", "queue_dwell_s", "reconnect_storm",
               "reconnect_window_s", "admission_deadline_s", "interval_s",
               "starvation_storm", "starvation_window_s")


@pytest.fixture
def slo_off():
    """SLO capture off and fresh around every test in this file."""
    slo.disable()
    yield slo
    slo.disable()


@pytest.fixture
def global_metrics():
    was = obs_metrics.enabled()
    yield obs_metrics.registry()
    (obs_metrics.enable if was else obs_metrics.disable)()


@pytest.fixture
def health():
    reg = obs_health.registry()
    was = reg.is_enabled
    saved = {k: getattr(reg, k) for k in _THRESHOLDS}
    reg.reset()
    yield obs_health
    reg.reset()
    for k, v in saved.items():
        setattr(reg, k, v)
    reg._enabled = was


@pytest.fixture
def events():
    ring = obs_events.ring()
    was = ring.is_enabled
    ring.reset()
    obs_events.enable()
    yield obs_events
    obs_events.disable()
    ring.reset()
    ring._enabled = was


def _etypes(events_mod):
    return [e["type"] for e in events_mod.ring().snapshot()]


# --------------------------------------------------------------------------- #
# Zero-overhead-when-off hook contract
# --------------------------------------------------------------------------- #

class TestSloHooks:
    def test_hooks_are_none_when_off(self, slo_off):
        assert slo.SCHED_SLO_HOOK is None
        assert slo.ENGINE_SLO_HOOK is None
        assert slo.ROUTER_SLO_HOOK is None
        assert not slo.enabled()
        assert slo.snapshot() == {"enabled": False, "tenants": {}}
        assert slo.push_data() is None
        assert slo.trace_points() == []
        assert slo.report() == "slo: off"

    def test_enable_installs_and_disable_clears(self, slo_off):
        reg = slo.enable()
        try:
            assert slo.SCHED_SLO_HOOK is reg
            assert slo.ENGINE_SLO_HOOK is reg
            assert slo.ROUTER_SLO_HOOK is reg
            assert slo.enabled() and slo.slo_registry() is reg
        finally:
            slo.disable()
        assert slo.SCHED_SLO_HOOK is None
        assert slo.ENGINE_SLO_HOOK is None
        assert slo.ROUTER_SLO_HOOK is None
        assert not slo.enabled()

    def test_disabled_run_records_nothing(self, slo_off, global_metrics):
        """A full engine run with capture off leaves no accounts behind
        (the hook sites were never called, not merely filtered)."""
        obs_metrics.disable()
        clock = FakeClock()
        eng = DeviceEngine("slo-off", autostart=False, clock=clock,
                           max_coalesce=1)
        t = eng.register("a")
        f = TagFilter("a")
        for _ in range(4):
            t.submit(f, [_mem()])
        while eng.step():
            pass
        assert slo.snapshot() == {"enabled": False, "tenants": {}}
        # a later enable starts from an empty ledger
        reg = slo.enable()
        assert reg.snapshot()["tenants"] == {}

    def test_set_objective_requires_enable(self, slo_off):
        with pytest.raises(RuntimeError):
            slo.set_objective("rt", p99_ms=50.0)


# --------------------------------------------------------------------------- #
# Cost attribution: conservation against engine totals
# --------------------------------------------------------------------------- #

class TestConservation:
    def test_per_tenant_sums_match_engine_totals(self, slo_off,
                                                 global_metrics):
        """The acceptance invariant: Σ device_seconds == busy_seconds
        and Σ wait_seconds == wait_seconds, within float tolerance."""
        obs_metrics.disable()
        slo.enable()
        clock = FakeClock()
        eng = DeviceEngine("slo-c", autostart=False, clock=clock,
                           max_coalesce=4)
        a = eng.register("a")
        b = eng.register("b")
        f = TagFilter("shared")  # one filter: a+b coalesce into batches
        for i in range(6):
            a.submit(f, [_mem()])
            clock.advance(0.01 * (i + 1))  # staggered, nonzero waits
            b.submit(f, [_mem()])
            clock.advance(0.02)
        while eng.step():
            pass
        assert eng.busy_seconds > 0.0
        assert eng.wait_seconds > 0.0
        snap = slo.snapshot()
        rows = snap["tenants"]
        assert set(rows) == {"a", "b"}
        dev_sum = sum(r["device_seconds"] for r in rows.values())
        wait_sum = sum(r["wait_seconds"] for r in rows.values())
        assert dev_sum == pytest.approx(eng.busy_seconds, rel=1e-9)
        assert wait_sum == pytest.approx(eng.wait_seconds, rel=1e-9)
        done = sum(sum(r["outcomes"].values()) for r in rows.values())
        assert done == 12

    def test_shed_feeds_outcomes_but_not_wait_account(self, slo_off,
                                                      global_metrics):
        """Shed work never reached the device: it lands as a shed
        outcome (with its queue wait as latency) but charges neither
        device_seconds nor wait_seconds — conservation stays exact."""
        obs_metrics.disable()
        slo.enable()
        clock = FakeClock()
        eng = DeviceEngine("slo-s", autostart=False, clock=clock,
                           max_coalesce=1)
        t = eng.register("a")
        fut = t.submit(TagFilter(), [_mem()],
                       deadline=FakeDeadline(True))  # shed at submit
        assert fut.result() is SHED
        row = slo.snapshot()["tenants"]["a"]
        assert row["outcomes"]["shed"] == 1
        assert row["shed_total"] == 1
        assert row["device_seconds"] == 0.0
        assert row["wait_seconds"] == 0.0
        assert eng.wait_seconds == 0.0


# --------------------------------------------------------------------------- #
# Registry accounting (driven directly, no engine)
# --------------------------------------------------------------------------- #

class TestRegistryAccounting:
    def test_busy_splits_proportional_to_rows(self, slo_off):
        reg = slo.SloRegistry(clock=FakeClock())
        reg.record_sched_batch(
            "dev0", 0.4,
            [("a", 0.1, 4, None), ("b", 0.2, 12, None)])
        rows = reg.snapshot()["tenants"]
        assert rows["a"]["device_seconds"] == pytest.approx(0.1)
        assert rows["b"]["device_seconds"] == pytest.approx(0.3)
        assert rows["a"]["wait_seconds"] == pytest.approx(0.1)
        assert rows["b"]["wait_seconds"] == pytest.approx(0.2)
        assert rows["a"]["outcomes"]["met"] == 1
        assert rows["b"]["outcomes"]["met"] == 1

    def test_expired_deadline_counts_as_missed(self, slo_off):
        reg = slo.SloRegistry(clock=FakeClock())
        reg.record_sched_batch(
            "dev0", 0.1,
            [("a", 0.0, 1, FakeDeadline(True)),
             ("b", 0.0, 1, FakeDeadline(False))])
        rows = reg.snapshot()["tenants"]
        assert rows["a"]["outcomes"]["missed"] == 1
        assert rows["b"]["outcomes"]["met"] == 1

    def test_engine_phase_charges_device_time(self, slo_off):
        reg = slo.SloRegistry(clock=FakeClock())
        reg.record_engine_phase("lm", "prefill", 0.25)
        reg.record_engine_phase("lm", "decode", 0.75)
        assert reg.snapshot()["tenants"]["lm"]["device_seconds"] \
            == pytest.approx(1.0)

    def test_tenant_overflow_folds(self, slo_off):
        reg = slo.SloRegistry(max_tenants=2, clock=FakeClock())
        for name in ("a", "b", "c", "d"):
            reg.record_outcome(name, "met", 0.01)
        rows = reg.snapshot()["tenants"]
        assert set(rows) == {"a", "b", slo.OVERFLOW_TENANT}
        assert rows[slo.OVERFLOW_TENANT]["outcomes"]["met"] == 2

    def test_unknown_router_session_folds_to_other(self, slo_off):
        reg = slo.SloRegistry(clock=FakeClock())
        reg.set_objective("rt", p99_ms=50.0)
        reg.record_dispatch("rt", 100, 200)
        reg.record_dispatch("random-session-9f3a", 7, 11)
        reg.record_dispatch(None, 1, 2)
        rows = reg.snapshot()["tenants"]
        assert rows["rt"]["bytes_tx"] == 100
        assert rows["rt"]["bytes_rx"] == 200
        assert rows[slo.OTHER_TENANT]["bytes_tx"] == 8
        assert rows[slo.OTHER_TENANT]["bytes_rx"] == 13


# --------------------------------------------------------------------------- #
# Burn-rate evaluation (fake clock, deterministic)
# --------------------------------------------------------------------------- #

class TestBurnRate:
    def _reg(self):
        fc = FakeClock()
        reg = slo.SloRegistry(fast_window_s=10.0, slow_window_s=100.0,
                              clock=fc)
        return reg, fc

    def test_empty_windows_burn_zero(self, slo_off):
        reg, _fc = self._reg()
        reg.set_objective("rt", p99_ms=50.0, goodput_ratio=0.99)
        ev = reg.evaluate("rt")
        assert not ev["breached"]
        assert ev["worst_burn"] == 0.0
        for w in ("fast", "slow"):
            assert ev["windows"][w]["burn"] == {"goodput": 0.0, "p99": 0.0}

    def test_goodput_burn_is_budget_normalized(self, slo_off):
        reg, fc = self._reg()
        reg.set_objective("rt", goodput_ratio=0.9)  # 10% bad budget
        for _ in range(8):
            reg.record_outcome("rt", "met", 0.01)
        reg.record_outcome("rt", "missed", 0.2)
        reg.record_shed("rt", "sched")
        # 2 bad of 10 = 20% observed over the 10% budget -> burn 2.0
        ev = reg.evaluate("rt", now=fc.t)
        assert ev["windows"]["fast"]["burn"]["goodput"] \
            == pytest.approx(2.0)
        assert ev["breached"] and ev["breached_objectives"] == ["goodput"]
        assert ev["worst_objective"] == "goodput"

    def test_p99_burn_counts_slow_and_shed(self, slo_off):
        reg, fc = self._reg()
        reg.set_objective("rt", p99_ms=50.0)
        for _ in range(9):
            reg.record_outcome("rt", "met", 0.001)
        reg.record_outcome("rt", "met", 0.2)  # met, but over the target
        # 1 slow of 10 = 10% over the 1% p99 budget -> burn 10.0
        ev = reg.evaluate("rt", now=fc.t)
        assert ev["windows"]["fast"]["burn"]["p99"] == pytest.approx(10.0)
        assert ev["breached"]

    def test_breach_requires_both_windows(self, slo_off):
        """Multi-window semantics: once the fast window drains, the old
        misses still burning the slow window no longer alert."""
        reg, fc = self._reg()
        reg.set_objective("rt", goodput_ratio=0.9)
        for _ in range(10):
            reg.record_outcome("rt", "missed", 0.2)
        assert reg.evaluate("rt")["breached"]
        fc.advance(50.0)  # past fast (10s), inside slow (100s)
        ev = reg.evaluate("rt")
        assert ev["windows"]["fast"]["burn"]["goodput"] == 0.0
        assert ev["windows"]["slow"]["burn"]["goodput"] \
            == pytest.approx(10.0)
        assert not ev["breached"]
        fc.advance(100.0)  # everything aged out
        ev = reg.evaluate("rt")
        assert ev["windows"]["slow"]["burn"]["goodput"] == 0.0

    def test_objective_validation(self, slo_off):
        reg, _fc = self._reg()
        with pytest.raises(ValueError):
            reg.set_objective("rt")
        with pytest.raises(ValueError):
            reg.set_objective("rt", p99_ms=0.0)
        with pytest.raises(ValueError):
            reg.set_objective("rt", goodput_ratio=1.0)


# --------------------------------------------------------------------------- #
# Health integration: breach -> DEGRADED -> recovery
# --------------------------------------------------------------------------- #

class TestHealthIntegration:
    def test_miss_storm_degrades_only_offending_tenant(
            self, slo_off, health, events):
        health.enable(interval_s=60.0)
        fc = FakeClock()
        slo.enable(fast_window_s=10.0, slow_window_s=100.0, clock=fc)
        slo.set_objective("rt", goodput_ratio=0.9)
        slo.set_objective("bulk", goodput_ratio=0.5)
        reg = slo.slo_registry()
        for _ in range(10):
            reg.record_outcome("rt", "missed", 0.2)
            reg.record_outcome("bulk", "met", 0.2)
        health.check_now()
        by_name = {c["name"]: c for c in
                   health.snapshot()["components"]}
        assert by_name["slo:rt"]["status"] == "degraded"
        assert "SLO burn" in by_name["slo:rt"]["detail"]
        assert by_name["slo:bulk"]["status"] == "ok"
        alerts = [e for e in events.ring().snapshot()
                  if e["type"] == "slo.burn_alert"]
        assert len(alerts) == 1 and alerts[0]["attrs"]["tenant"] == "rt"
        # /debug/slo-visible snapshot reflects the breach
        assert slo.snapshot()["tenants"]["rt"]["burn"]["breached"]

        # drain both windows: the same watchdog pass recovers it
        fc.advance(200.0)
        health.check_now()
        by_name = {c["name"]: c for c in
                   health.snapshot()["components"]}
        assert by_name["slo:rt"]["status"] == "ok"
        assert "slo.recover" in _etypes(events)
        assert not slo.snapshot()["tenants"]["rt"]["burn"]["breached"]
        # alert does not re-fire while already recovered
        health.check_now()
        assert _etypes(events).count("slo.recover") == 1

    def test_disable_retires_components(self, slo_off, health):
        health.enable(interval_s=60.0)
        slo.enable()
        slo.set_objective("rt", p99_ms=50.0)
        health.check_now()
        names = [c["name"] for c in health.snapshot()["components"]]
        assert "slo:rt" in names
        slo.disable()
        health.check_now()  # probe returns None: component retired
        names = [c["name"] for c in health.snapshot()["components"]]
        assert "slo:rt" not in names


# --------------------------------------------------------------------------- #
# Sched starvation-storm watchdog rule
# --------------------------------------------------------------------------- #

class TestStarvationWatchdog:
    def test_relief_storm_degrades_and_recovers(self, health, events):
        health.enable(interval_s=60.0)
        health.registry().configure(starvation_storm=3,
                                    starvation_window_s=0.0)
        eng = DeviceEngine("wd", autostart=False, clock=FakeClock(),
                           max_coalesce=1)
        health.check_now()  # opens the counting window
        by_name = {c["name"]: c for c in
                   health.snapshot()["components"]}
        assert by_name["sched:wd"]["status"] == "ok"
        eng.stats["starvation_reliefs"] += 3
        health.check_now()  # window elapsed (0s): delta 3 >= storm 3
        by_name = {c["name"]: c for c in
                   health.snapshot()["components"]}
        assert by_name["sched:wd"]["status"] == "degraded"
        assert "starvation" in by_name["sched:wd"]["detail"]
        assert "sched.starvation_storm" in _etypes(events)
        health.check_now()  # quiet window: recovery
        by_name = {c["name"]: c for c in
                   health.snapshot()["components"]}
        assert by_name["sched:wd"]["status"] == "ok"
        assert "sched.recover" in _etypes(events)

    def test_below_threshold_stays_ok(self, health, events):
        health.enable(interval_s=60.0)
        health.registry().configure(starvation_storm=5,
                                    starvation_window_s=0.0)
        eng = DeviceEngine("wd2", autostart=False, clock=FakeClock(),
                           max_coalesce=1)
        health.check_now()
        eng.stats["starvation_reliefs"] += 2
        health.check_now()
        by_name = {c["name"]: c for c in
                   health.snapshot()["components"]}
        assert by_name["sched:wd2"]["status"] == "ok"
        assert "sched.starvation_storm" not in _etypes(events)


# --------------------------------------------------------------------------- #
# Exporter routes
# --------------------------------------------------------------------------- #

class TestExporterRoutes:
    def _get(self, port, path):
        return json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5).read().decode())

    def test_debug_slo_off_is_still_200(self, slo_off, global_metrics):
        with start_exporter(port=0) as exp:
            doc = self._get(exp.port, "/debug/slo")
        assert doc["enabled"] is False and doc["tenants"] == {}
        assert "fleet" not in doc

    def test_debug_slo_serves_snapshot_and_fleet_rollup(
            self, slo_off, global_metrics):
        slo.enable(fast_window_s=10.0, slow_window_s=100.0)
        slo.set_objective("rt", goodput_ratio=0.9)
        reg = slo.slo_registry()
        for _ in range(4):
            reg.record_outcome("rt", "missed", 0.2)
        obs_fleet.enable_aggregator(ttl_s=30.0)
        try:
            with start_exporter(port=0) as exp:
                doc = self._get(exp.port, "/debug/slo")
        finally:
            obs_fleet.disable_aggregator()
        assert doc["enabled"] is True
        assert doc["tenants"]["rt"]["burn"]["breached"] is True
        assert "rt" in doc["fleet"]["breached"]
        assert any(s.get("enabled")
                   for s in doc["fleet"]["instances"].values())

    def test_debug_profile_samples_route(self, slo_off, global_metrics):
        with start_exporter(port=0) as exp:
            doc = self._get(exp.port, "/debug/profile/samples")
        assert doc["version"] == 1
        assert doc["profile_enabled"] is obs_profile.enabled()
        assert isinstance(doc["samples"], list)

    def test_404_hint_includes_new_routes(self, slo_off, global_metrics):
        with start_exporter(port=0) as exp:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{exp.port}/nope", timeout=5)
            assert ei.value.code == 404
            hint = ei.value.read().decode()
        assert "/debug/slo" in hint
        assert "/debug/profile/samples" in hint


# --------------------------------------------------------------------------- #
# Fleet rollup + push document
# --------------------------------------------------------------------------- #

class TestFleetRollup:
    def test_rollup_merges_local_and_remote_breaches(self, slo_off):
        agg = FleetAggregator(instance="agg:1")
        agg.ingest({
            "v": 1, "instance": "w1:1", "seq": 1,
            "slo": {"enabled": True,
                    "tenants": {"rt": {"burn": {"breached": True}}}},
        })
        local = {"enabled": True,
                 "tenants": {"bulk": {"burn": {"breached": True}},
                             "ok-t": {"burn": {"breached": False}}}}
        roll = agg.slo_rollup(local)
        assert set(roll["instances"]) == {"agg:1", "w1:1"}
        assert roll["breached"] == ["bulk", "rt"]

    def test_rollup_skips_disabled_snapshots(self, slo_off):
        agg = FleetAggregator(instance="agg:1")
        agg.ingest({"v": 1, "instance": "w1:1", "seq": 1,
                    "slo": {"enabled": False, "tenants": {}}})
        roll = agg.slo_rollup(None)
        assert roll == {"instances": {}, "breached": []}

    def test_push_document_carries_slo(self, slo_off):
        from nnstreamer_tpu.obs.fleet import build_push
        from nnstreamer_tpu.obs.metrics import MetricsRegistry
        from nnstreamer_tpu.obs.tracing import SpanStore

        def push():
            return build_push(
                "w1:1", "worker", 1, interval_s=2.0,
                registry=MetricsRegistry(enabled=True),
                health_registry=obs_health.HealthRegistry(),
                span_store=SpanStore())

        assert push()["slo"] is None  # disabled: no payload bytes
        slo.enable()
        slo.slo_registry().record_outcome("rt", "met", 0.01)
        doc = push()
        assert doc["slo"]["enabled"] is True
        assert "rt" in doc["slo"]["tenants"]


# --------------------------------------------------------------------------- #
# Perfetto per-tenant goodput lane (pid 5)
# --------------------------------------------------------------------------- #

class TestPerfettoLane:
    def test_goodput_counter_track(self, slo_off):
        slo.enable()
        reg = slo.slo_registry()
        reg.record_outcome("rt", "met", 0.01)
        reg.record_outcome("rt", "missed", 0.2)
        reg.record_shed("rt", "sched")
        doc = obs_profile.perfetto_trace()
        assert doc["otherData"]["slo_enabled"] is True
        pts = [e for e in doc["traceEvents"]
               if e.get("ph") == "C" and e.get("name") == "rt.goodput"]
        assert len(pts) == 3
        assert all(p["pid"] == 5 for p in pts)
        assert pts[-1]["args"] == {"met": 1, "missed": 1, "shed": 1}

    def test_no_lane_while_off(self, slo_off):
        doc = obs_profile.perfetto_trace()
        assert doc["otherData"]["slo_enabled"] is False
        assert not any(e.get("name", "").endswith(".goodput")
                       for e in doc["traceEvents"])


# --------------------------------------------------------------------------- #
# --slo spec parser
# --------------------------------------------------------------------------- #

class TestParseSloSpec:
    def test_full_spec(self):
        spec = slo.parse_slo_spec("rt:p99=50:goodput=0.99,batch:goodput=0.9")
        assert spec == {
            "rt": {"p99_ms": 50.0, "goodput_ratio": 0.99},
            "batch": {"goodput_ratio": 0.9},
        }

    @pytest.mark.parametrize("bad", [
        "rt:p99=50,",                # empty trailing entry
        ":p99=50",                   # missing tenant
        "rt:p99=50,rt:goodput=0.9",  # duplicate tenant
        "rt",                        # no objectives
        "rt:p42=50",                 # unknown key
        "rt:p99=abc",                # non-numeric value
        "rt:p99=0",                  # out of range
        "rt:goodput=1.5",            # out of range
        "rt:p99",                    # missing '='
    ])
    def test_malformed_specs_raise(self, bad):
        with pytest.raises(ValueError):
            slo.parse_slo_spec(bad)
