"""fleet/ — SLO-driven autoscaling with live drain and zero-loss
stream migration.

Contracts pinned here:

- Policy discipline: hysteresis (N consecutive pressure ticks),
  cooldown, min/max clamps, and a deadband where both streaks reset —
  a signal oscillating around one threshold can NEVER flap the fleet.
  The priced policy additionally refuses scale-ups whose backlog would
  drain before the spawn pays off and scale-ins whose migration census
  is too expensive.
- Router session tables: explicit pins are honored by placement before
  the affinity ring, dispatch success notes observed ownership, and a
  drain EAGERLY re-pins every owned session to a surviving backend at
  drain start (not lazily per next-request).
- Engine freeze/export/resume: a frozen session's submit is refused
  (router failover moves it under the ORIGINAL deadline), export
  produces the same page document the disagg hand-off ships, resume
  lifts the freeze (absorb path).
- Live migration over the wire: export → KV_PAGE_XFER ship → re-pin
  moves real pages; a partitioned transfer absorbs (target re-prefills)
  with the pin still moved — the stream never dies either way.
- Aggregator hygiene: tombstone compaction is deterministic
  oldest-first, and a controller-confirmed drain clears both the live
  record and the tombstone.
- Controller: reconcile_once is deterministic under an injectable
  clock; scale-up launches + gates on readiness + journals; scale-in
  migrates the victim census then drains; the breaker stops a
  crash-looping launch path; the journal rides push docs and
  /debug/fleet/actions.
- Zero-overhead-when-off: AUTOSCALE_HOOK defaults to None and the only
  hot-path cost is one attribute load + None test.
- Acceptance (the ISSUE bar): halving a 4-backend fleet under a
  multi-turn session load — one scale-in clean, one under a seeded
  chaos partition of the transfer wire — keeps every stream alive,
  keeps the goodput SLO burn under threshold on BOTH windows, and
  yields token-for-token the outputs of an unhalved control run.
"""

import json
import sys
import urllib.request

import numpy as np
import pytest

import jax

from nnstreamer_tpu import fleet
from nnstreamer_tpu.fleet.autoscale import (AutoscalePolicy, PricedPolicy,
                                            parse_autoscale_spec)
from nnstreamer_tpu.fleet.controller import BackendLauncher, FleetController
from nnstreamer_tpu.fleet.migrate import LM_CAPS, SessionMigrator
from nnstreamer_tpu.models import causal_lm
from nnstreamer_tpu.obs import events as obs_events
from nnstreamer_tpu.obs import fleet as obs_fleet
from nnstreamer_tpu.obs import slo as obs_slo
from nnstreamer_tpu.obs.exporter import start_exporter
from nnstreamer_tpu.obs.metrics import MetricsRegistry
from nnstreamer_tpu.query.router import (SESSION_PIN_LIMIT, BackendSet,
                                         QueryRouter)
from nnstreamer_tpu.resilience import chaos
from nnstreamer_tpu.resilience import policy as rp
from nnstreamer_tpu.serving import LMEngine, disagg

V, D, H, L, MAXLEN = 97, 32, 4, 2, 64
PS = 8


@pytest.fixture(scope="module")
def params():
    return causal_lm.init_causal_lm(
        jax.random.PRNGKey(7), V, D, H, L, MAXLEN)


@pytest.fixture
def events():
    ring = obs_events.ring()
    was = ring.is_enabled
    ring.reset()
    obs_events.enable()
    yield obs_events
    obs_events.disable()
    ring.reset()
    ring._enabled = was


@pytest.fixture
def agg():
    a = obs_fleet.enable_aggregator(ttl_s=30.0)
    yield a
    obs_fleet.disable_aggregator()


@pytest.fixture
def fleet_off_after():
    yield
    fleet.disable()


@pytest.fixture
def slo_off_after():
    yield
    obs_slo.disable()


def events_of(etype):
    return [e for e in obs_events.ring().snapshot() if e["type"] == etype]


class FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def mkeng(params, pages=32, slots=2):
    return LMEngine(params, H, MAXLEN, n_slots=slots, chunk=4,
                    kv_page_size=PS, kv_pages=pages)


def mkfleet(params, n, name="fleet-test"):
    """n unified DisaggWorkers behind one QueryRouter."""
    engines = [mkeng(params) for _ in range(n)]
    workers = [disagg.DisaggWorker(e) for e in engines]
    router = QueryRouter(
        BackendSet([(w.host, w.port) for w in workers], name), name)
    router.set_caps_provider(lambda: LM_CAPS)
    return workers, router


def lm_dispatch(router, prompt, session, max_new=6):
    rmeta, _ = router.dispatch(
        {"lm": {"prompt": [int(x) for x in prompt], "max_new": max_new,
                "session": session}},
        b"", session=session)
    return [int(t) for t in rmeta.get("tokens", [])]


def stop_all(router, workers):
    router.close()
    for w in workers:
        w.stop()


# --------------------------------------------------------------------------- #
# Policy discipline
# --------------------------------------------------------------------------- #

class TestPolicy:
    def mkpol(self, clk, **kw):
        kw.setdefault("hysteresis", 2)
        kw.setdefault("cooldown_s", 10.0)
        return AutoscalePolicy(1, 4, clock=clk, **kw)

    def test_hysteresis_gates_action(self):
        clk = FakeClock()
        pol = self.mkpol(clk)
        up = {"replicas": 2, "queue_depth": 100.0, "occupancy": 0.0}
        assert pol.decide(up).action == "hold"          # streak 1/2
        assert pol.decide(up).action == "scale_up"      # streak 2/2

    def test_cooldown_blocks_consecutive_actions(self):
        clk = FakeClock()
        pol = self.mkpol(clk)
        up = {"replicas": 2, "queue_depth": 100.0, "occupancy": 0.0}
        pol.decide(up)
        assert pol.decide(up).action == "scale_up"
        # still pressured, but inside the cooldown window
        assert pol.decide(up).action == "hold"
        assert pol.decide(up).action == "hold"
        clk.advance(11.0)
        # streak kept building through the cooldown holds, so the first
        # post-cooldown tick acts
        assert pol.decide(up).action == "scale_up"

    def test_deadband_resets_both_streaks(self):
        clk = FakeClock()
        pol = self.mkpol(clk)
        up = {"replicas": 2, "queue_depth": 100.0, "occupancy": 0.0}
        mid = {"replicas": 2, "queue_depth": 4.0, "occupancy": 0.5}
        pol.decide(up)                                   # up streak 1
        d = pol.decide(mid)                              # deadband
        assert d.action == "hold" and "between" in d.reason
        # the earlier streak must NOT carry over
        assert pol.decide(up).action == "hold"

    def test_oscillation_never_flaps(self):
        """A signal alternating across the scale-in threshold can never
        accumulate the hysteresis streak — zero actions, ever."""
        clk = FakeClock()
        pol = self.mkpol(clk)
        low = {"replicas": 3, "queue_depth": 0.0, "occupancy": 0.0}
        mid = {"replicas": 3, "queue_depth": 4.0, "occupancy": 0.5}
        actions = []
        for i in range(40):
            actions.append(pol.decide(low if i % 2 == 0 else mid).action)
            clk.advance(60.0)                            # cooldown never binds
        assert set(actions) == {"hold"}

    def test_min_max_clamp(self):
        clk = FakeClock()
        pol = self.mkpol(clk, hysteresis=1)
        up = {"replicas": 4, "queue_depth": 100.0, "occupancy": 0.0}
        d = pol.decide(up)
        assert d.action == "hold" and "max_replicas" in d.reason
        clk.advance(11.0)
        down = {"replicas": 1, "queue_depth": 0.0, "occupancy": 0.0}
        d = pol.decide(down)
        assert d.action == "hold" and "min_replicas" in d.reason

    def test_breach_is_up_pressure(self):
        clk = FakeClock()
        pol = self.mkpol(clk, hysteresis=1)
        d = pol.decide({"replicas": 2, "queue_depth": 0.0,
                        "occupancy": 0.0, "breached": ["tenant-a"]})
        assert d.action == "scale_up" and "tenant-a" in d.reason

    def test_parse_spec(self):
        assert parse_autoscale_spec("2:8") == (2, 8, "default")
        assert parse_autoscale_spec("1:4:priced") == (1, 4, "priced")
        for bad in ("3", "0:4", "4:2", "2:8:nope", "a:b", "2:8:x:y"):
            with pytest.raises(ValueError):
                parse_autoscale_spec(bad)


class TestPricedPolicy:
    def test_scale_up_priced_out_when_backlog_drains_first(self):
        clk = FakeClock()
        pol = PricedPolicy(1, 4, hysteresis=1, cooldown_s=0.0,
                           spawn_cost_s=5.0, service_rate=4.0, clock=clk)
        # queue 10 over 2 replicas * 4/s = 1.25s to drain < 5s spawn
        d = pol.decide({"replicas": 2, "queue_depth": 10.0,
                        "occupancy": 0.0})
        assert d.action == "hold" and "priced out" in d.reason
        # a backlog worth the spawn goes through
        d = pol.decide({"replicas": 2, "queue_depth": 100.0,
                        "occupancy": 0.0})
        assert d.action == "scale_up"

    def test_breach_overrides_the_price(self):
        clk = FakeClock()
        pol = PricedPolicy(1, 4, hysteresis=1, cooldown_s=0.0, clock=clk)
        d = pol.decide({"replicas": 2, "queue_depth": 0.0,
                        "occupancy": 0.0, "breached": ["t"]})
        assert d.action == "scale_up"

    def test_scale_in_priced_out_by_migration_census(self):
        clk = FakeClock()
        pol = PricedPolicy(1, 4, hysteresis=1, cooldown_s=0.0,
                           max_migration_sessions=8, clock=clk)
        down = {"replicas": 3, "queue_depth": 0.0, "occupancy": 0.0,
                "victim_sessions": 9}
        d = pol.decide(down)
        assert d.action == "hold" and "9 sessions" in d.reason
        d = pol.decide(dict(down, victim_sessions=3))
        assert d.action == "scale_in"


# --------------------------------------------------------------------------- #
# Router session tables + eager drain re-pin
# --------------------------------------------------------------------------- #

class TestSessionTables:
    def mkset(self, n=3):
        eps = [("127.0.0.1", 40001 + i) for i in range(n)]
        return BackendSet(eps, "pins-test"), [f"{h}:{p}" for h, p in eps]

    def test_pin_wins_placement(self):
        bs, eps = self.mkset()
        for _ in range(4):
            bs.pin_session("s1", eps[2])
            be = bs.pick(session="s1")
            assert be is not None and be.endpoint == eps[2]

    def test_pin_respects_exclude(self):
        bs, eps = self.mkset()
        bs.pin_session("s1", eps[2])
        be = bs.pick(session="s1", exclude=frozenset({eps[2]}))
        assert be is not None and be.endpoint != eps[2]

    def test_note_session_updates_ownership_census(self):
        bs, eps = self.mkset()
        bs.note_session("s1", eps[0])
        bs.note_session("s2", eps[0])
        bs.note_session("s2", eps[1])               # moved
        assert bs.sessions_owned(eps[0]) == ["s1"]
        assert bs.sessions_owned(eps[1]) == ["s2"]

    def test_drain_eagerly_repins_all_owned_sessions(self, events):
        bs, eps = self.mkset()
        for i in range(6):
            bs.note_session(f"s{i}", eps[0])
        bs.drain(eps[0])
        # every session re-homed NOW, not lazily at its next request
        assert bs.sessions_owned(eps[0]) == []
        rehomed = {s for ep in eps[1:] for s in bs.sessions_owned(ep)}
        assert rehomed == {f"s{i}" for i in range(6)}
        for i in range(6):
            be = bs.pick(session=f"s{i}")
            assert be is not None and be.endpoint != eps[0]
        evs = events_of("router.repin")
        assert len(evs) == 1 and evs[0]["attrs"]["sessions"] == 6

    def test_remove_drops_pins_naming_the_endpoint(self):
        bs, eps = self.mkset()
        bs.pin_session("s1", eps[1])
        bs.remove(eps[1], drain=False)
        assert bs.sessions_owned(eps[1]) == []
        # placement falls back to the ring, never a dead endpoint
        be = bs.pick(session="s1")
        assert be is not None and be.endpoint != eps[1]

    def test_session_tables_are_bounded(self):
        bs, eps = self.mkset()
        for i in range(SESSION_PIN_LIMIT + 50):
            bs.note_session(f"s{i}", eps[0])
        assert len(bs._owners) <= SESSION_PIN_LIMIT
        # LRU: the newest survive
        assert f"s{SESSION_PIN_LIMIT + 49}" in bs._owners
        assert "s0" not in bs._owners


# --------------------------------------------------------------------------- #
# Engine freeze / export / resume
# --------------------------------------------------------------------------- #

class TestEngineFreeze:
    def test_frozen_submit_refused_and_resume_lifts(self, params):
        eng = mkeng(params)
        p = np.arange(12, dtype=np.int32) % V
        rid = eng.submit(p, 4, session="sess-a")
        eng.run()
        assert len(eng.results[rid]) == 4
        assert eng.freeze_session("sess-a") is True     # path recorded
        with pytest.raises(ValueError, match="frozen for migration"):
            eng.submit(p, 4, session="sess-a")
        # other sessions unaffected
        eng.submit(p, 2, session="sess-b")
        eng.run()
        eng.resume_session("sess-a")
        rid = eng.submit(p, 4, session="sess-a")
        eng.run()
        assert len(eng.results[rid]) == 4

    def test_export_session_produces_page_doc(self, params):
        eng = mkeng(params)
        p = np.arange(2 * PS + 3, dtype=np.int32) % V
        eng.submit(p, 4, session="sess-x")
        eng.run()
        doc = eng.export_session("sess-x")
        assert doc is not None and len(doc["entries"]) >= 2
        # export froze the session as a side effect
        with pytest.raises(ValueError, match="frozen"):
            eng.submit(p, 2, session="sess-x")

    def test_export_unknown_session_is_none(self, params):
        eng = mkeng(params)
        assert eng.export_session("never-seen") is None


# --------------------------------------------------------------------------- #
# Live migration over the wire
# --------------------------------------------------------------------------- #

class TestMigrationWire:
    def test_migrate_moves_pages_and_repins(self, params, events):
        workers, router = mkfleet(params, 2)
        try:
            prompt = np.arange(2 * PS + 5, dtype=np.int32) % V
            out1 = lm_dispatch(router, prompt, "mig-s")
            assert len(out1) == 6
            src_ep = router.backends.sessions_owned(
                workers[0].endpoint) and workers[0].endpoint \
                or workers[1].endpoint
            source = router.backends.get(src_ep)
            target = router.backends.pick(session="mig-s",
                                          exclude=frozenset({src_ep}))
            mig = SessionMigrator(router)
            res = mig.migrate("mig-s", source, target)
            assert res["ok"] and not res["absorbed"]
            assert res["pages"] >= 2
            assert mig.stats["migrated"] == 1
            assert mig.stats["pages_moved"] == res["pages"]
            # pinned to the target: the next turn dials it directly
            be = router.backends.pick(session="mig-s")
            assert be is not None and be.endpoint == target.endpoint
            # and the stream keeps decoding — same prompt, same greedy
            # tokens on the migrated backend
            out2 = lm_dispatch(router, prompt, "mig-s")
            assert out2 == out1
            assert len(events_of("fleet.migrate_start")) == 1
            assert len(events_of("fleet.migrate_done")) == 1
        finally:
            stop_all(router, workers)

    def test_partitioned_transfer_absorbs(self, params, events):
        """Chaos partition on the KV_PAGE_XFER wire: the export ships
        nothing, the migration reports absorbed, the pin STILL moves,
        and the stream survives via target re-prefill."""
        workers, router = mkfleet(params, 2)
        try:
            prompt = np.arange(2 * PS + 5, dtype=np.int32) % V
            out1 = lm_dispatch(router, prompt, "abs-s")
            owned0 = router.backends.sessions_owned(workers[0].endpoint)
            source = router.backends.get(
                workers[0].endpoint if "abs-s" in owned0
                else workers[1].endpoint)
            target = router.backends.pick(
                session="abs-s", exclude=frozenset({source.endpoint}))
            plan = chaos.FaultPlan(
                [chaos.Fault(kind="partition", target="send",
                             cmd="KV_PAGE_XFER", nth=1)], seed=7)
            chaos.install(plan)
            try:
                mig = SessionMigrator(router)
                res = mig.migrate("abs-s", source, target)
            finally:
                chaos.uninstall()
            assert res["absorbed"] and not res["ok"]
            assert res["pages"] == 0
            assert mig.stats["absorbed"] == 1
            be = router.backends.pick(session="abs-s")
            assert be is not None and be.endpoint == target.endpoint
            # zero loss: the target re-prefills and the greedy stream
            # is token-identical to the warm path
            out2 = lm_dispatch(router, prompt, "abs-s")
            assert out2 == out1
            assert len(events_of("fleet.migrate_abandon")) == 1
        finally:
            stop_all(router, workers)

    def test_dead_source_absorbs(self, params):
        workers, router = mkfleet(params, 2)
        try:
            prompt = np.arange(12, dtype=np.int32) % V
            lm_dispatch(router, prompt, "dead-s")
            owned0 = router.backends.sessions_owned(workers[0].endpoint)
            src_w, tgt_w = (workers if "dead-s" in owned0
                            else workers[::-1])
            source = router.backends.get(src_w.endpoint)
            target = router.backends.get(tgt_w.endpoint)
            # kill the owner: listener down AND the pooled connection
            # dropped, so the export round trip must dial a dead port
            src_w.stop()
            source.close()
            mig = SessionMigrator(router, timeout_s=2.0)
            res = mig.migrate("dead-s", source, target)
            assert res["absorbed"]
            be = router.backends.pick(session="dead-s")
            assert be is not None and be.endpoint == target.endpoint
        finally:
            stop_all(router, workers)


# --------------------------------------------------------------------------- #
# Aggregator hygiene: tombstone compaction + confirmed drain
# --------------------------------------------------------------------------- #

class TestAggregatorHygiene:
    def test_tombstone_compaction_is_oldest_first(self, agg, monkeypatch):
        monkeypatch.setattr(obs_fleet, "TOMBSTONE_LIMIT", 2)
        with agg._lock:
            for iid, t in (("w-a", 3.0), ("w-b", 1.0),
                           ("w-c", 2.0), ("w-d", 4.0)):
                agg._tombstones[iid] = {"role": "worker",
                                        "expired_mono": t}
            agg._compact_tombstones()
            left = set(agg._tombstones)
        assert left == {"w-a", "w-d"}                  # newest deaths stay

    def test_compaction_tiebreak_is_deterministic(self, agg, monkeypatch):
        monkeypatch.setattr(obs_fleet, "TOMBSTONE_LIMIT", 1)
        with agg._lock:
            # equal expiry: lexicographically smallest id evicted first
            for iid in ("w-z", "w-a", "w-m"):
                agg._tombstones[iid] = {"role": "worker",
                                        "expired_mono": 5.0}
            agg._compact_tombstones()
            left = set(agg._tombstones)
        assert left == {"w-z"}

    def test_confirm_drain_clears_record_and_tombstone(self, agg, events):
        agg.ingest(obs_fleet.build_push("w-gone", "worker", 1))
        assert "w-gone" in agg.routing_view()
        assert agg.confirm_drain("w-gone") is True
        view = agg.routing_view()
        assert "w-gone" not in view
        with agg._lock:
            assert "w-gone" not in agg._tombstones
        assert agg.confirm_drain("w-gone") is False    # idempotent
        assert len(events_of("fleet.drain_confirmed")) == 1

    def test_confirm_drain_clears_a_tombstone(self, agg):
        with agg._lock:
            agg._tombstones["w-stone"] = {"role": "worker",
                                          "expired_mono": 1.0}
        assert agg.confirm_drain("w-stone") is True
        with agg._lock:
            assert "w-stone" not in agg._tombstones


# --------------------------------------------------------------------------- #
# Controller
# --------------------------------------------------------------------------- #

class _FakeLauncher:
    """In-process 'subprocess': launches a real DisaggWorker."""

    def __init__(self, params, fail=False):
        self.params = params
        self.fail = fail
        self.live = {}
        self.terminated = []

    def launch(self):
        from nnstreamer_tpu.fleet.controller import LaunchHandle

        if self.fail:
            raise RuntimeError("boom: worker crash-loop")
        w = disagg.DisaggWorker(mkeng(self.params))
        self.live[w.endpoint] = w
        return LaunchHandle(w.endpoint, 0, None)

    def terminate(self, handle):
        self.terminated.append(handle.endpoint)
        w = self.live.pop(handle.endpoint, None)
        if w is not None:
            w.stop()

    def stop_all(self):
        for w in list(self.live.values()):
            w.stop()
        self.live.clear()


class TestController:
    def test_scale_up_launches_and_routes(self, params, events,
                                          fleet_off_after):
        workers, router = mkfleet(params, 1)
        launcher = _FakeLauncher(params)
        clk = FakeClock()
        pol = AutoscalePolicy(1, 3, hysteresis=1, cooldown_s=0.0,
                              clock=clk)
        ctl = FleetController(router, pol, launcher=launcher, clock=clk)
        try:
            ctl.observe_occupancy("eng0", 0.95)        # up-pressure
            d = ctl.reconcile_once()
            assert d.action == "scale_up"
            assert ctl.stats["scale_up"] == 1
            eps = {be.endpoint for be in router.backends.backends()}
            assert len(eps) == 2
            # the new backend actually serves
            out = lm_dispatch(router, np.arange(10, dtype=np.int32) % V,
                              None, max_new=2)
            assert len(out) == 2
            assert any(a["action"] == "scale_up" for a in ctl.actions())
            assert len(events_of("fleet.scale_up")) == 1
        finally:
            stop_all(router, workers)
            launcher.stop_all()

    def test_scale_up_failure_journals_and_feeds_breaker(self, params,
                                                         fleet_off_after):
        workers, router = mkfleet(params, 1)
        clk = FakeClock()
        pol = AutoscalePolicy(1, 3, hysteresis=1, cooldown_s=0.0,
                              clock=clk)
        ctl = FleetController(router, pol,
                              launcher=_FakeLauncher(params, fail=True),
                              clock=clk)
        try:
            ctl.observe_occupancy("eng0", 0.95)
            for _ in range(ctl._breaker.failure_threshold):
                ctl.reconcile_once()
            acts = [a["action"] for a in ctl.actions()]
            assert acts.count("scale_up_failed") == \
                ctl._breaker.failure_threshold
            # breaker now open: the next tick skips without launching
            assert ctl._breaker.state == rp.OPEN
            ctl.reconcile_once()
            assert ctl.actions()[-1]["action"] == "scale_up_skipped"
            assert "breaker open" in ctl.actions()[-1]["reason"]
        finally:
            stop_all(router, workers)

    def test_scale_in_migrates_census_then_drains(self, params, agg,
                                                  events, fleet_off_after):
        workers, router = mkfleet(params, 3)
        for w in workers:
            w.push_fleet(agg)
        clk = FakeClock()
        pol = AutoscalePolicy(1, 3, hysteresis=1, cooldown_s=0.0,
                              clock=clk)
        ctl = FleetController(router, pol, aggregator=agg, clock=clk)
        try:
            prompt = np.arange(2 * PS + 3, dtype=np.int32) % V
            outs = {s: lm_dispatch(router, prompt, s)
                    for s in ("c-s0", "c-s1", "c-s2", "c-s3")}
            d = ctl.reconcile_once()                   # idle fleet: down
            assert d.action == "scale_in"
            active = [be for be in router.backends.backends()
                      if be.state == "active"]
            assert len(active) == 2
            # the drained instance was confirmed out of the aggregator
            assert len(agg.routing_view()) == 2
            # zero loss: every stream still answers, token-identical
            for s, first in outs.items():
                assert lm_dispatch(router, prompt, s) == first
            assert len(events_of("fleet.scale_in")) == 1
            entry = [a for a in ctl.actions()
                     if a["action"] == "scale_in"][0]
            assert entry["migrated"] + entry["absorbed"] >= 0
        finally:
            stop_all(router, workers)

    def test_victim_choice_is_deterministic(self, params, fleet_off_after):
        workers, router = mkfleet(params, 3)
        try:
            clk = FakeClock()
            pol = AutoscalePolicy(1, 3, hysteresis=1, cooldown_s=0.0,
                                  clock=clk)
            ctl = FleetController(router, pol, clock=clk)
            eps = sorted(w.endpoint for w in workers)
            # load two backends; the empty lexicographically-first one
            # must be the victim, every time
            router.backends.note_session("v-a", eps[1])
            router.backends.note_session("v-b", eps[2])
            active = [be for be in router.backends.backends()
                      if be.state == "active"]
            picks = {ctl._pick_victim(active).endpoint for _ in range(5)}
            assert picks == {eps[0]}
        finally:
            stop_all(router, workers)

    def test_snapshot_shape(self, params, fleet_off_after):
        workers, router = mkfleet(params, 1)
        try:
            clk = FakeClock()
            ctl = FleetController(
                router, AutoscalePolicy(1, 2, clock=clk), clock=clk)
            ctl.reconcile_once()
            snap = ctl.snapshot()
            assert snap["policy"] == "default"
            assert snap["min_replicas"] == 1
            assert snap["stats"]["ticks"] == 1
            assert isinstance(snap["actions"], list)
        finally:
            stop_all(router, workers)


# --------------------------------------------------------------------------- #
# Hook wiring: zero-overhead-when-off + journal federation
# --------------------------------------------------------------------------- #

class TestHookWiring:
    def test_hook_defaults_off(self):
        assert fleet.AUTOSCALE_HOOK is None
        assert obs_fleet.FLEET_ACTIONS_HOOK is None
        assert fleet.enabled() is False
        assert fleet.snapshot() is None

    def test_enable_installs_both_hooks(self, params, fleet_off_after):
        workers, router = mkfleet(params, 1)
        try:
            ctl = fleet.enable(router, 1, 2, clock=FakeClock())
            assert fleet.AUTOSCALE_HOOK is ctl
            assert obs_fleet.FLEET_ACTIONS_HOOK == ctl.actions
            # idempotent: a second enable returns the installed one
            assert fleet.enable(router, 1, 8) is ctl
            fleet.disable()
            assert fleet.AUTOSCALE_HOOK is None
            assert obs_fleet.FLEET_ACTIONS_HOOK is None
        finally:
            stop_all(router, workers)

    def test_journal_rides_push_docs(self, params, agg, fleet_off_after):
        workers, router = mkfleet(params, 1)
        try:
            ctl = fleet.enable(router, 1, 2, clock=FakeClock())
            ctl._journal_add("scale_up", "test entry", endpoint="x:1")
            doc = obs_fleet.build_push("w-journal", "worker", 1)
            assert doc["fleet_actions"][-1]["action"] == "scale_up"
            agg.ingest(doc)
            rolled = agg.actions_rollup()
            assert rolled["w-journal"][-1]["reason"] == "test entry"
        finally:
            stop_all(router, workers)

    def test_sched_occupancy_tap(self, params, fleet_off_after):
        """The sched/engine.py hook site: one attribute load, None test,
        then observe_occupancy lands in the controller's signal set."""
        workers, router = mkfleet(params, 1)
        try:
            ctl = fleet.enable(router, 1, 2, clock=FakeClock())
            hook = fleet.AUTOSCALE_HOOK
            assert hook is not None
            hook.observe_occupancy("dev0", 0.42)
            assert ctl.observe()["occupancy"] == pytest.approx(0.42)
        finally:
            stop_all(router, workers)


# --------------------------------------------------------------------------- #
# Launcher readiness gating
# --------------------------------------------------------------------------- #

_READY_WORKER = """
import http.server, sys, time
time.sleep(0.2)
port = int(sys.argv[1])
class H(http.server.BaseHTTPRequestHandler):
    def do_GET(self):
        self.send_response(200 if self.path == "/readyz" else 404)
        self.end_headers()
    def log_message(self, *a):
        pass
http.server.HTTPServer(("127.0.0.1", port), H).serve_forever()
"""


class TestLauncher:
    def test_launch_waits_for_readyz(self):
        launcher = BackendLauncher(
            [sys.executable, "-c", _READY_WORKER, "{ready_port}"],
            ready_timeout_s=10.0, poll_interval_s=0.05)
        handle = launcher.launch()
        try:
            assert handle.proc.poll() is None          # up and serving
        finally:
            launcher.terminate(handle)
        assert handle.proc.poll() is not None

    def test_early_exit_raises(self):
        launcher = BackendLauncher(
            [sys.executable, "-c", "import sys; sys.exit(3)"],
            ready_timeout_s=5.0, poll_interval_s=0.05)
        with pytest.raises(RuntimeError, match="rc=3"):
            launcher.launch()

    def test_never_ready_times_out(self):
        launcher = BackendLauncher(
            [sys.executable, "-c", "import time; time.sleep(60)"],
            ready_timeout_s=0.5, poll_interval_s=0.05)
        with pytest.raises(TimeoutError):
            launcher.launch()


# --------------------------------------------------------------------------- #
# /debug/fleet/actions
# --------------------------------------------------------------------------- #

class TestDebugRoute:
    def test_route_off_and_on(self, params, agg, fleet_off_after):
        workers, router = mkfleet(params, 1)
        try:
            with start_exporter(port=0,
                                registry=MetricsRegistry(enabled=True)
                                ) as exp:
                url = (f"http://127.0.0.1:{exp.port}"
                       f"/debug/fleet/actions")
                with urllib.request.urlopen(url, timeout=5) as r:
                    body = json.loads(r.read())
                assert body["enabled"] is False and body["local"] is None
                ctl = fleet.enable(router, 1, 2, clock=FakeClock())
                ctl.reconcile_once()
                with urllib.request.urlopen(url, timeout=5) as r:
                    body = json.loads(r.read())
                assert body["enabled"] is True
                assert body["local"]["stats"]["ticks"] == 1
                assert isinstance(body["fleet"], dict)
        finally:
            stop_all(router, workers)


# --------------------------------------------------------------------------- #
# Acceptance: halve the fleet under load, zero loss, SLO holds
# --------------------------------------------------------------------------- #

class TestAcceptance:
    N_SESSIONS = 6
    N_TURNS = 4
    GEN = 5

    def _prompts(self):
        rng = np.random.default_rng(11)
        return [rng.integers(0, V, 2 * PS + 4 + i).astype(np.int32)
                for i in range(self.N_SESSIONS)]

    def _run_turn(self, router, prompts, outputs, reg=None):
        for i, p in enumerate(prompts):
            sid = f"acc-s{i}"
            t0 = __import__("time").monotonic()
            toks = lm_dispatch(router, p, sid, max_new=self.GEN)
            if reg is not None:
                reg.record_outcome(
                    "streams", "met" if len(toks) == self.GEN
                    else "missed", __import__("time").monotonic() - t0)
            outputs.setdefault(sid, []).append(toks)

    def test_halving_under_chaos_keeps_streams_and_slo(
            self, params, agg, events, fleet_off_after, slo_off_after):
        prompts = self._prompts()

        # -- control: same load, fleet never touched ------------------
        workers, router = mkfleet(params, 4, name="acc-ctl")
        control = {}
        try:
            for _ in range(self.N_TURNS):
                self._run_turn(router, prompts, control)
        finally:
            stop_all(router, workers)

        # -- the run under test: 4 -> 2 mid-load ----------------------
        reg = obs_slo.enable()
        reg.set_objective("streams", goodput_ratio=0.9)
        workers, router = mkfleet(params, 4, name="acc-run")
        for w in workers:
            w.push_fleet(agg)
        clk = FakeClock()
        pol = AutoscalePolicy(2, 4, hysteresis=2, cooldown_s=10.0,
                              clock=clk)
        controller = FleetController(router, pol, aggregator=agg,
                                     clock=clk)
        outputs = {}
        try:
            self._run_turn(router, prompts, outputs, reg)
            # tick 1: idle fleet is down-pressure, hysteresis 1/2
            assert controller.reconcile_once().action == "hold"
            # tick 2: first scale-in, clean wire — pages migrate
            assert controller.reconcile_once().action == "scale_in"
            self._run_turn(router, prompts, outputs, reg)
            clk.advance(11.0)                          # clear cooldown
            # second scale-in under a seeded chaos partition of the
            # transfer wire: every shipment dies, every migration
            # must absorb — and no stream may die with it
            plan = chaos.FaultPlan(
                [chaos.Fault(kind="partition", target="send",
                             cmd="KV_PAGE_XFER", nth=1)], seed=7)
            controller.reconcile_once()                # hysteresis 1/2
            chaos.install(plan)
            try:
                assert controller.reconcile_once().action == "scale_in"
            finally:
                chaos.uninstall()
            for _ in range(self.N_TURNS - 2):
                self._run_turn(router, prompts, outputs, reg)

            # fleet really halved, and the policy floor holds
            active = [be for be in router.backends.backends()
                      if be.state == "active"]
            assert len(active) == 2
            clk.advance(11.0)
            for _ in range(4):
                d = controller.reconcile_once()
                assert d.action == "hold"              # at min_replicas
                clk.advance(11.0)

            # zero stream loss: every turn of every session completed
            for sid, turns in outputs.items():
                assert len(turns) == self.N_TURNS
                assert all(len(t) == self.GEN for t in turns)
            # token-identical to the unhalved control run — migration
            # (clean AND absorbed) never corrupted a stream
            assert outputs == control

            # SLO: burn under threshold on BOTH windows
            ev = reg.evaluate("streams")
            assert ev["breached"] is False
            assert ev["windows"]["fast"]["burn"]["goodput"] \
                < reg.burn_threshold
            assert ev["windows"]["slow"]["burn"]["goodput"] \
                < reg.burn_threshold
            assert ev["windows"]["fast"]["n"] == \
                self.N_SESSIONS * self.N_TURNS

            # both migration modes actually exercised
            assert controller.migrator.stats["migrated"] \
                + controller.migrator.stats["absorbed"] \
                == controller.stats["migrations"]
            assert len(events_of("fleet.scale_in")) == 2
            # drained instances confirmed out of the aggregator
            assert len(agg.routing_view()) == 2
        finally:
            stop_all(router, workers)

    def test_halving_schedule_is_deterministic(self, params, agg,
                                               fleet_off_after):
        """Same signals + same injected clock => the same action tape,
        run to run — the controller adds no hidden nondeterminism."""
        def tape():
            workers, router = mkfleet(params, 4, name="acc-det")
            clk = FakeClock()
            pol = AutoscalePolicy(2, 4, hysteresis=2, cooldown_s=10.0,
                                  clock=clk)
            ctl = FleetController(router, pol, clock=clk)
            acts = []
            try:
                for _ in range(8):
                    acts.append(ctl.reconcile_once().action)
                    clk.advance(6.0)
            finally:
                stop_all(router, workers)
            return acts

        t1, t2 = tape(), tape()
        assert t1 == t2
        assert t1.count("scale_in") == 2               # 4 -> 3 -> 2, floor
