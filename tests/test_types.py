"""Tensor type system tests (mirrors reference unittest_common coverage:
dim/type string parse & print, size calc, config compare, caps intersect)."""

import numpy as np
import pytest
from fractions import Fraction

from nnstreamer_tpu.core import (
    ANY,
    Caps,
    TensorDType,
    TensorFormat,
    TensorInfo,
    TensorsConfig,
    TensorsInfo,
    dimension_string,
    dims_to_shape,
    parse_dimension,
    shape_to_dims,
)


class TestDimensions:
    def test_parse_basic(self):
        assert parse_dimension("3:224:224:1") == (3, 224, 224, 1)

    def test_parse_single(self):
        assert parse_dimension("1001") == (1001,)

    def test_roundtrip(self):
        s = "3:224:224:1"
        assert dimension_string(parse_dimension(s)) == s

    def test_row_major_conversion(self):
        # reference dims are innermost-first: "3:224:224:1" ↔ numpy (1,224,224,3)
        assert dims_to_shape((3, 224, 224, 1)) == (1, 224, 224, 3)
        assert shape_to_dims((1, 224, 224, 3)) == (3, 224, 224, 1)

    @pytest.mark.parametrize("bad", ["", "0:3", "-1", "a:b", "3::4", "1:2:3:4:5:6:7:8:9"])
    def test_parse_invalid(self, bad):
        with pytest.raises(ValueError):
            parse_dimension(bad)


class TestDType:
    def test_parse_all_names(self):
        for name in ["int8", "uint8", "int16", "uint16", "int32", "uint32",
                     "int64", "uint64", "float32", "float64", "float16", "bfloat16"]:
            assert str(TensorDType.parse(name)) == name

    def test_aliases(self):
        assert TensorDType.parse("float") is TensorDType.FLOAT32
        assert TensorDType.parse("double") is TensorDType.FLOAT64

    def test_from_numpy(self):
        assert TensorDType.parse(np.dtype("uint8")) is TensorDType.UINT8
        assert TensorDType.parse(np.float32) is TensorDType.FLOAT32

    def test_itemsize(self):
        assert TensorDType.UINT8.itemsize == 1
        assert TensorDType.BFLOAT16.itemsize == 2
        assert TensorDType.FLOAT64.itemsize == 8

    def test_invalid(self):
        with pytest.raises(ValueError):
            TensorDType.parse("complex64")


class TestTensorInfo:
    def test_size_bytes(self):
        ti = TensorInfo.from_strings("3:224:224:1", "uint8")
        assert ti.size_bytes == 3 * 224 * 224
        assert ti.num_elements == 3 * 224 * 224

    def test_shape_view(self):
        ti = TensorInfo.from_strings("3:224:224:1", "float32")
        assert ti.shape == (1, 224, 224, 3)

    def test_compat_trailing_ones(self):
        a = TensorInfo.from_strings("3:224:224:1", "uint8")
        b = TensorInfo.from_strings("3:224:224", "uint8")
        assert a.is_compatible(b)

    def test_incompat_dtype(self):
        a = TensorInfo.from_strings("3:4", "uint8")
        b = TensorInfo.from_strings("3:4", "int8")
        assert not a.is_compatible(b)

    def test_from_array(self):
        ti = TensorInfo.from_array(np.zeros((2, 3, 4), np.int16))
        assert ti.shape == (2, 3, 4)
        assert ti.dtype is TensorDType.INT16


class TestTensorsInfo:
    def test_multi_parse(self):
        info = TensorsInfo.from_strings("3:224:224:1,1001:1", "uint8,float32")
        assert info.num_tensors == 2
        assert info[0].dtype is TensorDType.UINT8
        assert info[1].dims == (1001, 1)
        assert info.dim_string == "3:224:224:1,1001:1"
        assert info.type_string == "uint8,float32"

    def test_single_type_broadcast(self):
        info = TensorsInfo.from_strings("2:2,3:3", "float32")
        assert all(i.dtype is TensorDType.FLOAT32 for i in info)

    def test_count_limit(self):
        with pytest.raises(ValueError):
            TensorsInfo.from_strings(",".join(["2"] * 17), "uint8")

    def test_mismatch(self):
        with pytest.raises(ValueError):
            TensorsInfo.from_strings("2:2,3:3", "uint8,int8,int8")

    def test_total_size(self):
        info = TensorsInfo.from_strings("10,20", "float32,uint8")
        assert info.total_size_bytes == 40 + 20


class TestConfigAndCaps:
    def test_rate(self):
        cfg = TensorsConfig(TensorsInfo.from_strings("4", "uint8"), Fraction(30, 1))
        assert cfg.rate_n == 30
        assert cfg.frame_duration_ns == 33_333_333

    def test_rate_unknown(self):
        cfg = TensorsConfig(TensorsInfo.from_strings("4", "uint8"))
        assert cfg.frame_duration_ns is None

    def test_caps_roundtrip(self):
        cfg = TensorsConfig(
            TensorsInfo.from_strings("3:224:224:1,1001", "uint8,float32"),
            Fraction(25, 1))
        caps = Caps.tensors(cfg)
        cfg2 = caps.to_config()
        assert cfg2.info.is_compatible(cfg.info)
        assert cfg2.rate == cfg.rate

    def test_caps_intersect_fixes_any(self):
        a = Caps("other/tensors", {"format": TensorFormat.STATIC, "dims": ANY})
        b = Caps("other/tensors", {"dims": "3:4", "types": "uint8"})
        c = a.intersect(b)
        assert c is not None
        assert c.get("dims") == "3:4"

    def test_caps_disjoint(self):
        a = Caps("other/tensors", {"dims": "3:4"})
        b = Caps("other/tensors", {"dims": "5:6"})
        assert a.intersect(b) is None
        assert Caps("video/x-raw").intersect(Caps("other/tensors")) is None


class TestFormats:
    def test_parse(self):
        assert TensorFormat.parse("flexible") is TensorFormat.FLEXIBLE

    def test_flexible_info_no_count_requirement(self):
        info = TensorsInfo((), TensorFormat.FLEXIBLE)
        assert info.num_tensors == 0
