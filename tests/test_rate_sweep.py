"""tensor_rate conformance sweep: upsampling (duplicate), downsampling
(drop), counters, and edge cases.

Reference model: gst/nnstreamer/elements/gsttensorrate.c props
framerate/drop/duplicate and the in/out/duplicate/drop counters
(gsttensorrate.c:957-993) exercised by tests/nnstreamer_rate/runTest.sh.
"""

from fractions import Fraction

import numpy as np
import pytest

from nnstreamer_tpu.core import Caps
from nnstreamer_tpu.core.buffer import Buffer
from nnstreamer_tpu.core.types import TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline

MS = 1_000_000


def caps_of(rate):
    return Caps.tensors(TensorsConfig(
        TensorsInfo.from_strings("2", "float32"), rate))


def run_rate(in_rate_hz, out_rate, n, **props):
    p = Pipeline()
    period = int(1e9 / in_rate_hz)
    data = [Buffer.of(np.full((2,), i, np.float32), pts=i * period,
                      duration=period) for i in range(n)]
    src = p.add_new("appsrc", caps=caps_of(Fraction(in_rate_hz, 1)),
                    data=data)
    rate = p.add_new("tensor_rate", framerate=out_rate, throttle=False,
                     **props)
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, rate, sink)
    p.run(timeout=60)
    return rate, sink


class TestRateConform:
    def test_downsample_3x(self):
        rate, sink = run_rate(30, "10/1", 30)
        # 1 second of 30 Hz → ~10 output frames
        assert 9 <= sink.num_buffers <= 11
        assert rate.n_in == 30
        assert rate.n_drop >= 18
        pts = [b.pts for b in sink.buffers]
        assert pts == sorted(pts)

    def test_upsample_duplicates(self):
        rate, sink = run_rate(10, "30/1", 10)
        # 1 second of 10 Hz → ~30 outputs, two thirds duplicated
        assert 27 <= sink.num_buffers <= 33
        assert rate.n_dup >= 18
        # duplicated frames repeat the previous payload
        vals = [int(b.memories[0].host()[0]) for b in sink.buffers]
        assert vals == sorted(vals)  # non-decreasing source indices
        assert len(set(vals)) == 10

    def test_same_rate_passthrough(self):
        rate, sink = run_rate(30, "30/1", 15)
        assert sink.num_buffers == 15
        assert rate.n_drop == 0 and rate.n_dup == 0

    def test_drop_disabled_passes_everything(self):
        rate, sink = run_rate(30, "10/1", 12, drop=False)
        assert sink.num_buffers == 12  # conform disabled: passthrough

    def test_counters_match_io(self):
        rate, sink = run_rate(20, "5/1", 20)
        assert rate.n_in == 20
        assert rate.n_out == sink.num_buffers
        assert rate.n_in - rate.n_drop <= rate.n_out + 1

    @pytest.mark.parametrize("bad", ["0/1", "-5/1", "x/y", "1/0"])
    def test_invalid_framerate_rejected(self, bad):
        from nnstreamer_tpu.graph.pipeline import PipelineError

        p = Pipeline()
        src = p.add_new("appsrc", caps=caps_of(Fraction(30, 1)),
                        data=[Buffer.of(np.zeros(2, np.float32), pts=0,
                                        duration=33 * MS)])
        rate = p.add_new("tensor_rate", framerate=bad, throttle=False)
        sink = p.add_new("tensor_sink")
        Pipeline.link(src, rate, sink)
        with pytest.raises((PipelineError, ValueError, ZeroDivisionError)):
            p.run(timeout=30)
