"""Golden fixture generator for bit-exact decoder tests.

Mirrors the reference's golden-compare SSAT discipline
(tests/nnstreamer_decoder_boundingbox/runTest.sh: decode a frozen input,
byte-compare the rendered output). Inputs are seeded-deterministic; outputs
are the decoders' exact RGBA/text bytes at generation time, committed as
``goldens.npz``. The test re-decodes and byte-compares — any silent
draw/NMS/palette/scaling regression breaks it.

Regenerate (ONLY after an intentional, reviewed behavior change):
    python tests/goldens/generate.py
"""

from __future__ import annotations

import os
import sys

import numpy as np

HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(os.path.dirname(HERE)))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def build_cases():
    """[(name, mode, options, input_arrays, config)] — all host-path."""
    from nnstreamer_tpu.core.types import TensorsConfig, TensorsInfo
    from nnstreamer_tpu.models.ssd_mobilenet import write_box_priors

    rng = np.random.default_rng(20260729)
    cases = []

    # -- bounding_box: mobilenet-ssd (priors + raw head) -------------------- #
    priors_path = os.path.join(HERE, "box_priors_96.txt")
    n_anchors = write_box_priors(priors_path, size=96)
    labels_path = os.path.join(HERE, "labels6.txt")
    with open(labels_path, "w") as f:
        f.write("\n".join(f"class{i}" for i in range(6)))
    locs = rng.normal(size=(1, n_anchors, 4)).astype(np.float32)
    scores = (rng.normal(size=(1, n_anchors, 6)) * 4).astype(np.float32)
    cases.append((
        "bbox_mobilenet_ssd", "bounding_box",
        {1: "mobilenet-ssd", 2: labels_path, 3: priors_path,
         4: "96:96", 5: "96:96"},
        [locs, scores],
        TensorsConfig(TensorsInfo.from_strings(
            f"4:{n_anchors}:1,6:{n_anchors}:1", "float32,float32"))))

    # -- bounding_box: mobilenet-ssd-postprocess ---------------------------- #
    boxes = rng.uniform(0, 0.6, size=(1, 8, 4)).astype(np.float32)
    boxes[..., 2:] += 0.3
    classes = rng.integers(0, 6, (1, 8)).astype(np.float32)
    det_scores = rng.uniform(0.3, 0.95, (1, 8)).astype(np.float32)
    count = np.asarray([6], np.float32)
    cases.append((
        "bbox_postprocess", "bounding_box",
        {1: "mobilenet-ssd-postprocess", 2: labels_path, 4: "128:128",
         5: "128:128"},
        [boxes, classes, det_scores, count],
        TensorsConfig(TensorsInfo.from_strings(
            "4:8:1,8:1,8:1,1", "float32,float32,float32,float32"))))

    # -- bounding_box: ov-person-detection ---------------------------------- #
    rows = np.zeros((1, 4, 7), np.float32)
    for i in range(4):
        x0, y0 = rng.uniform(0, 0.5, 2)
        rows[0, i] = [0, i % 3, 0.4 + 0.15 * i, x0, y0, x0 + 0.3, y0 + 0.4]
    rows[0, 3, 0] = -1  # terminator row (image_id < 0)
    cases.append((
        "bbox_ov_person", "bounding_box",
        {1: "ov-person-detection", 2: labels_path, 4: "96:96", 5: "96:96"},
        [rows],
        TensorsConfig(TensorsInfo.from_strings("7:4:1", "float32"))))

    # -- image_segment: all three schemes ----------------------------------- #
    seg_logits = rng.normal(size=(1, 24, 32, 5)).astype(np.float32)
    cases.append((
        "segment_tflite_deeplab", "image_segment", {1: "tflite-deeplab"},
        [seg_logits],
        TensorsConfig(TensorsInfo.from_strings("5:32:24:1", "float32"))))
    seg_ids = rng.integers(0, 5, (1, 24, 32)).astype(np.uint8)
    cases.append((
        "segment_snpe_deeplab", "image_segment", {1: "snpe-deeplab"},
        [seg_ids],
        TensorsConfig(TensorsInfo.from_strings("32:24:1", "uint8"))))
    depth = rng.uniform(0.5, 4.0, (1, 24, 32)).astype(np.float32)
    cases.append((
        "segment_snpe_depth", "image_segment", {1: "snpe-depth"},
        [depth],
        TensorsConfig(TensorsInfo.from_strings("32:24:1", "float32"))))

    # -- pose_estimation: plain + heatmap-offset ---------------------------- #
    hm = rng.normal(size=(1, 9, 9, 17)).astype(np.float32)
    cases.append((
        "pose_plain", "pose_estimation", {1: "96:96", 2: "33:33"},
        [hm],
        TensorsConfig(TensorsInfo.from_strings("17:9:9:1", "float32"))))
    off = rng.normal(size=(1, 9, 9, 34)).astype(np.float32) * 2
    cases.append((
        "pose_heatmap_offset", "pose_estimation",
        {1: "96:96", 2: "33:33", 4: "heatmap-offset"},
        [hm, off],
        TensorsConfig(TensorsInfo.from_strings(
            "17:9:9:1,34:9:9:1", "float32,float32"))))

    # -- image_labeling ------------------------------------------------------ #
    lab_scores = rng.normal(size=(1, 6)).astype(np.float32)
    cases.append((
        "labeling", "image_labeling", {1: labels_path},
        [lab_scores],
        TensorsConfig(TensorsInfo.from_strings("6:1", "float32"))))

    # -- font ---------------------------------------------------------------- #
    text = np.frombuffer(b"hello nns 42", np.uint8).copy()
    cases.append((
        "font", "font", {1: "128:32"},
        [text],
        TensorsConfig(TensorsInfo.from_strings("12", "uint8"))))

    # -- direct_video -------------------------------------------------------- #
    vid = rng.integers(0, 255, (1, 8, 12, 3)).astype(np.uint8)
    cases.append((
        "direct_video", "direct_video", {},
        [vid],
        TensorsConfig(TensorsInfo.from_strings("3:12:8:1", "uint8"))))

    # -- variants: non-1:1 output scaling draw paths ------------------------- #
    # (appended AFTER all original draws so the rng sequence — and thus
    # every committed original array — stays bit-identical)
    locs2 = rng.normal(size=(1, n_anchors, 4)).astype(np.float32)
    scores2 = (rng.normal(size=(1, n_anchors, 6)) * 4).astype(np.float32)
    cases.append((
        "bbox_mobilenet_ssd_upscale", "bounding_box",
        {1: "mobilenet-ssd", 2: labels_path, 3: priors_path,
         4: "96:96", 5: "192:160"},  # model dims ≠ draw dims
        [locs2, scores2],
        TensorsConfig(TensorsInfo.from_strings(
            f"4:{n_anchors}:1,6:{n_anchors}:1", "float32,float32"))))
    hm2 = rng.normal(size=(1, 9, 9, 17)).astype(np.float32)
    cases.append((
        "pose_upscale", "pose_estimation", {1: "192:128", 2: "33:33"},
        [hm2],
        TensorsConfig(TensorsInfo.from_strings("17:9:9:1", "float32"))))
    return cases


def decode_case(mode, options, arrays, config):
    from nnstreamer_tpu.core.buffer import Buffer
    from nnstreamer_tpu.decoders.base import find_decoder

    d = find_decoder(mode)()
    d.init(options)
    return d.decode(Buffer.of(*arrays), config)


def main() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    out = {}
    for name, mode, options, arrays, config in build_cases():
        decoded = decode_case(mode, options, arrays, config)
        for i, a in enumerate(arrays):
            out[f"{name}__in{i}"] = a
        out[f"{name}__out"] = decoded.memories[0].host()
    path = os.path.join(HERE, "goldens.npz")
    np.savez_compressed(path, **out)
    print(f"wrote {path}: {len(out)} arrays, "
          f"{os.path.getsize(path) / 1024:.0f} KiB")


if __name__ == "__main__":
    main()
