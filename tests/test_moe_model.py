"""MoE streaming transformer model family (models/moe_transformer.py).

Covers: zoo resolution + pipeline serving through tensor_filter,
expert-parallel sharded inference == single-device oracle, router metrics
via the moe_metrics collection, and composition with sequence windows
(aggregator → filter), mirroring how the stream_transformer family is
exercised."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nnstreamer_tpu.core.types import TensorsConfig, TensorsInfo
from nnstreamer_tpu.core import Caps
from nnstreamer_tpu.graph import Pipeline

SPEC = ("zoo://moe_transformer?layers=2&dim=32&heads=4&experts=4&seq=16"
        "&dtype=float32")


def test_zoo_resolution_and_shapes():
    from nnstreamer_tpu.models.zoo import get_model

    b = get_model(SPEC)
    assert b.in_info[0].shape == (1, 16, 32)
    assert b.out_info[0].shape == (1, 16, 32)
    x = np.random.default_rng(0).normal(size=(1, 16, 32)).astype(np.float32)
    out = jax.jit(b.fn())(x)
    assert out.shape == (1, 16, 32)
    assert np.isfinite(np.asarray(out)).all()


def test_pipeline_serving():
    p = Pipeline()
    frames = [np.random.default_rng(i).normal(size=(1, 16, 32))
              .astype(np.float32) for i in range(4)]
    src = p.add_new("appsrc", caps=Caps.tensors(TensorsConfig(
        TensorsInfo.from_strings("32:16:1", "float32"))), data=frames)
    filt = p.add_new("tensor_filter", framework="xla-tpu", model=SPEC)
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, filt, sink)
    p.run(timeout=120)
    assert sink.num_buffers == 4
    assert sink.buffers[0].memories[0].shape == (1, 16, 32)


def test_expert_parallel_equals_single_device():
    from nnstreamer_tpu.models.moe_transformer import make_ep_infer
    from nnstreamer_tpu.models.zoo import get_model
    from nnstreamer_tpu.parallel import make_mesh

    b = get_model(SPEC + "&batch=2")
    x = np.random.default_rng(1).normal(size=(2, 16, 32)).astype(np.float32)
    want = np.asarray(jax.jit(b.fn())(x))
    mesh = make_mesh({"data": 2, "expert": 4})
    jitted, placed = make_ep_infer(b, mesh)
    got = np.asarray(jitted(placed, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ep_param_shardings_rule():
    from nnstreamer_tpu.models.moe_transformer import ep_param_shardings
    from nnstreamer_tpu.models.zoo import get_model
    from nnstreamer_tpu.parallel import make_mesh
    from jax.sharding import PartitionSpec as P

    b = get_model(SPEC)
    mesh = make_mesh({"data": 2, "expert": 4})
    sh = ep_param_shardings(b.params, mesh, 4)
    flat = jax.tree_util.tree_flatten_with_path(sh)[0]
    expert_leaves = [("/".join(str(getattr(k, "key", k)) for k in path), s)
                     for path, s in flat if s.spec == P("expert")]
    assert expert_leaves, "no expert-sharded leaves found"
    for name, _ in expert_leaves:
        assert "moe_block" in name, name


def test_router_metrics_collection():
    from nnstreamer_tpu.models.moe_transformer import MoEStreamTransformer

    model = MoEStreamTransformer(layers=2, dim=32, heads=4, n_experts=4,
                                 dtype=jnp.float32)
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(1, 16, 32)).astype(np.float32))
    variables = model.init(jax.random.PRNGKey(0), x)
    out, aux = model.apply(variables, x, mutable=["moe_metrics"])
    metrics = aux["moe_metrics"]["moe_block_1"]
    lb = float(metrics["load_balance_loss"][0])
    counts = np.asarray(metrics["expert_counts"][0])
    assert lb >= 1.0 - 1e-3
    assert counts.sum() == 16  # every token routed


def test_synthesized_init_has_nonzero_experts():
    """The accelerator-backend init path (eval_shape + synthesize) must not
    zero the router/expert stacks — that would silently make every MoE
    layer a no-op on real TPU serving."""
    from nnstreamer_tpu.models.moe_transformer import MoEStreamTransformer
    from nnstreamer_tpu.models.zoo import synthesize_variables

    model = MoEStreamTransformer(layers=2, dim=32, heads=4, n_experts=4,
                                 dtype=jnp.float32)
    shapes = jax.eval_shape(
        lambda k: model.init(k, jnp.zeros((1, 16, 32), jnp.float32)),
        jax.random.PRNGKey(0))
    synth = synthesize_variables(shapes, 0)
    moe = synth["params"]["moe_block_1"]
    for name in ("router", "w1", "w2"):
        arr = np.asarray(moe[name])
        assert np.abs(arr).max() > 0, f"{name} synthesized to zeros"
    out = model.apply({"params": synth["params"]},
                      jnp.asarray(np.random.default_rng(0).normal(
                          size=(1, 16, 32)).astype(np.float32)))
    assert np.isfinite(np.asarray(out)).all()


def test_ep_infer_rejects_indivisible_batch():
    from nnstreamer_tpu.models.moe_transformer import make_ep_infer
    from nnstreamer_tpu.models.zoo import get_model
    from nnstreamer_tpu.parallel import make_mesh

    b = get_model(SPEC)  # batch=1 bundle
    mesh = make_mesh({"data": 2, "expert": 4})
    infer, placed = make_ep_infer(b, mesh)
    with pytest.raises(ValueError, match="divisible"):
        infer(placed, jnp.zeros((1, 16, 32), jnp.float32))
    # dp_axis=None serves any batch, replicated
    infer1, placed1 = make_ep_infer(b, mesh, dp_axis=None)
    out = infer1(placed1, jnp.zeros((1, 16, 32), jnp.float32))
    assert out.shape == (1, 16, 32)


@pytest.mark.parametrize("sp_mode", ["ring", "a2a"])
def test_sp_ep_composed_equals_single_device(sp_mode):
    """Sequence-parallel attention × expert-parallel MoE on one 2D mesh
    equals the single-device oracle (long-context + experts composed)."""
    from nnstreamer_tpu.models.moe_transformer import make_sp_ep_infer
    from nnstreamer_tpu.models.zoo import get_model
    from nnstreamer_tpu.parallel import make_mesh

    b = get_model(SPEC)  # seq=16, experts=4, float32
    x = np.random.default_rng(2).normal(size=(1, 16, 32)).astype(np.float32)
    want = np.asarray(jax.jit(b.fn())(x))
    mesh = make_mesh({"sp": 2, "expert": 4})
    infer, placed = make_sp_ep_infer(b, mesh, sp_mode=sp_mode)
    got = np.asarray(infer(placed, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_sp_ep_rejects_indivisible_sequence():
    from nnstreamer_tpu.models.moe_transformer import make_sp_ep_infer
    from nnstreamer_tpu.models.zoo import get_model
    from nnstreamer_tpu.parallel import make_mesh

    b = get_model(SPEC.replace("seq=16", "seq=15"))
    mesh = make_mesh({"sp": 2, "expert": 4})
    infer, placed = make_sp_ep_infer(b, mesh)
    with pytest.raises(ValueError, match="divisible"):
        infer(placed, jnp.zeros((1, 15, 32), np.float32))


def test_sp_ep_honors_nondefault_capacity_factor():
    """The rebuilt sp×ep model must reuse the bundle's capacity_factor —
    a default-capacity rebuild would drop different tokens than the
    oracle."""
    from nnstreamer_tpu.models.moe_transformer import make_sp_ep_infer
    from nnstreamer_tpu.models.zoo import get_model
    from nnstreamer_tpu.parallel import make_mesh

    spec = SPEC + "&capacity_factor=0.5"
    b = get_model(spec)
    x = np.random.default_rng(5).normal(size=(1, 16, 32)).astype(np.float32)
    want = np.asarray(jax.jit(b.fn())(x))
    mesh = make_mesh({"sp": 2, "expert": 4})
    infer, placed = make_sp_ep_infer(b, mesh)
    got = np.asarray(infer(placed, jnp.asarray(x)))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ep_bundle_serves_through_filter():
    """tensor_filter serves the expert-sharded MoE pjit program (pod-slice
    offload path), equal to the unsharded oracle."""
    from nnstreamer_tpu.core.buffer import TensorMemory
    from nnstreamer_tpu.filters.base import FilterProps
    from nnstreamer_tpu.filters.xla import XLAFilter
    from nnstreamer_tpu.models.moe_transformer import ep_bundle
    from nnstreamer_tpu.models.zoo import get_model
    from nnstreamer_tpu.parallel import make_mesh

    b = get_model(SPEC + "&batch=2")
    mesh = make_mesh({"data": 2, "expert": 4})
    served = ep_bundle(b, mesh)
    filt = XLAFilter()
    filt.open(FilterProps(model=served))
    x = np.random.default_rng(3).normal(size=(2, 16, 32)).astype(np.float32)
    got = filt.invoke([TensorMemory(x)])[0].host()
    ref = np.asarray(jax.jit(b.fn())(x))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)
