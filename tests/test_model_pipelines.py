"""Full BASELINE-config pipelines with the native model zoo:
SSD→bounding_box, DeepLab→image_segment, PoseNet→pose, LSTM repo loop
(mirrors BASELINE.md's five configs on tiny shapes)."""

import numpy as np
import pytest

from nnstreamer_tpu.graph import Pipeline
from nnstreamer_tpu.models.zoo import get_model, model_names


def test_zoo_catalog_complete():
    names = model_names()
    for required in ["mobilenet_v1", "mobilenet_v2", "ssd_mobilenet_v2", "deeplab_v3",
                     "posenet", "lstm_cell", "lenet", "mnist", "causal_lm",
                     "moe_transformer", "stream_transformer",
                     "passthrough", "scaler"]:
        assert required in names


def test_ssd_detection_pipeline_with_priors(tmp_path):
    from nnstreamer_tpu.models.ssd_mobilenet import write_box_priors

    priors = tmp_path / "box_priors.txt"
    n = write_box_priors(str(priors), size=96)
    labels = tmp_path / "labels.txt"
    labels.write_text("\n".join(f"c{i}" for i in range(6)))
    bundle = get_model("zoo://ssd_mobilenet_v2?size=96&width=0.25"
                       "&num_classes=6&dtype=float32")
    assert bundle.metadata["anchors"] == n
    p = Pipeline()
    src = p.add_new("videotestsrc", width=96, height=96, num_buffers=2,
                    pattern="random")
    conv = p.add_new("tensor_converter")
    filt = p.add_new("tensor_filter", framework="xla-tpu", model=bundle)
    dec = p.add_new("tensor_decoder", mode="bounding_box",
                    option1="mobilenet-ssd", option2=str(labels),
                    option3=str(priors), option4="96:96", option5="96:96")
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, conv, filt, dec, sink)
    p.run(timeout=180)
    assert sink.num_buffers == 2
    b = sink.buffers[0]
    assert b.memories[0].host().shape == (96, 96, 4)
    assert isinstance(b.meta["detections"], list)  # untrained → any count


def test_deeplab_segmentation_pipeline():
    bundle = get_model("zoo://deeplab_v3?size=33&width=0.25&num_classes=5"
                       "&dtype=float32")
    p = Pipeline()
    src = p.add_new("videotestsrc", width=33, height=33, num_buffers=2)
    conv = p.add_new("tensor_converter")
    filt = p.add_new("tensor_filter", model=bundle)
    dec = p.add_new("tensor_decoder", mode="image_segment",
                    option1="tflite-deeplab")
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, conv, filt, dec, sink)
    p.run(timeout=180)
    mask = sink.buffers[0].memories[0].host()
    assert mask.shape == (33, 33, 4)


def test_posenet_pipeline():
    bundle = get_model("zoo://posenet?size=33&width=0.25&dtype=float32")
    p = Pipeline()
    src = p.add_new("videotestsrc", width=33, height=33, num_buffers=1)
    conv = p.add_new("tensor_converter")
    filt = p.add_new("tensor_filter", model=bundle)
    dec = p.add_new("tensor_decoder", mode="pose_estimation",
                    option1="66:66", option2="33:33", option4="heatmap-offset")
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, conv, filt, dec, sink)
    p.run(timeout=180)
    b = sink.buffers[0]
    assert len(b.meta["keypoints"]) == 17
    assert b.memories[0].host().shape == (66, 66, 4)


def test_lstm_repo_loop_with_zoo_cell():
    """Composite config: mux + repo loop driving the flax LSTM cell."""
    from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
    from nnstreamer_tpu.elements.repo import reset_repo

    reset_repo()
    bundle = get_model("zoo://lstm_cell?features=8&input_size=4")
    p = Pipeline()
    xs = [np.random.default_rng(i).normal(size=(1, 4)).astype(np.float32)
          for i in range(3)]
    src = p.add_new("appsrc",
                    caps=Caps.tensors(TensorsConfig(
                        TensorsInfo.from_strings("4:1", "float32"), 30)),
                    data=xs)
    state = p.add_new("tensor_reposrc", slot_index=9, dims="8:1,8:1",
                      types="float32,float32")
    mux = p.add_new("tensor_mux", sync_mode="nosync")
    filt = p.add_new("tensor_filter", model=bundle)
    demux = p.add_new("tensor_demux", tensorpick="0,1:2")
    qo = p.add_new("queue")
    qs = p.add_new("queue")
    out_sink = p.add_new("tensor_sink", store=True)
    repo_sink = p.add_new("tensor_reposink", slot_index=9)
    Pipeline.link(src, mux)
    Pipeline.link(state, mux)
    Pipeline.link(mux, filt, demux)
    Pipeline.link(demux, qo, out_sink)   # y
    Pipeline.link(demux, qs, repo_sink)  # (h', c') back into the loop
    p.start()
    import time

    deadline = time.monotonic() + 60
    while out_sink.num_buffers < 3 and time.monotonic() < deadline:
        time.sleep(0.05)
    p.stop()
    assert out_sink.num_buffers >= 3
    # recurrent state actually evolved: same input at t0/t1 would give
    # different outputs; verify outputs finite and not identical
    y0 = out_sink.buffers[0].memories[0].host()
    y1 = out_sink.buffers[1].memories[0].host()
    assert np.all(np.isfinite(y0)) and np.all(np.isfinite(y1))
    assert not np.array_equal(y0, y1)


class TestStreamTransformer:
    def test_single_device_forward(self):
        bundle = get_model("zoo://stream_transformer?layers=1&dim=32&heads=4"
                           "&seq=16&dtype=float32")
        import jax

        out = jax.jit(bundle.fn())(np.zeros((1, 16, 32), np.float32))
        assert out.shape == (1, 16, 32)

    def test_sequence_parallel_matches_single_device(self):
        import jax
        import jax.numpy as jnp
        from nnstreamer_tpu.models.stream_transformer import make_sp_apply
        from nnstreamer_tpu.parallel import make_mesh

        bundle = get_model("zoo://stream_transformer?layers=1&dim=32&heads=8"
                           "&seq=64&dtype=float32")
        x = np.random.default_rng(0).normal(size=(1, 64, 32)).astype(np.float32)
        ref = np.asarray(bundle.fn()(jnp.asarray(x)))
        mesh = make_mesh({"sp": 8})
        for mode in ("ring", "a2a"):
            apply_sp, params = make_sp_apply(bundle, mesh, mode=mode)
            out = np.asarray(apply_sp(params, jnp.asarray(x)))
            np.testing.assert_allclose(out, ref, rtol=5e-3, atol=5e-4)

    def test_in_pipeline_with_aggregator(self):
        """Streaming use: per-frame embeddings → aggregator window →
        transformer filter (the long-context streaming pattern)."""
        from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
        from nnstreamer_tpu.graph import Pipeline

        bundle = get_model("zoo://stream_transformer?layers=1&dim=16&heads=2"
                           "&seq=4&dtype=float32")
        p = Pipeline()
        src = p.add_new("appsrc",
                        caps=Caps.tensors(TensorsConfig(
                            TensorsInfo.from_strings("16:1:1", "float32"), 30)),
                        data=[np.full((1, 1, 16), i, np.float32)
                              for i in range(8)])
        agg = p.add_new("tensor_aggregator", frames_out=4, frames_dim=1)
        filt = p.add_new("tensor_filter", model=bundle)
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, agg, filt, sink)
        p.run(timeout=120)
        assert sink.num_buffers == 2
        assert sink.buffers[0].memories[0].host().shape == (1, 4, 16)


def test_bounding_box_device_reduce_matches_host(tmp_path):
    """submit/complete (device top-K reduce) must yield the same detections
    as the plain host decode path."""
    import jax
    import numpy as np
    from nnstreamer_tpu.core.buffer import Buffer
    from nnstreamer_tpu.core.types import TensorsConfig, TensorsInfo
    from nnstreamer_tpu.decoders.base import find_decoder
    from nnstreamer_tpu.models.ssd_mobilenet import write_box_priors

    priors = tmp_path / "p.txt"
    n = write_box_priors(str(priors), size=96)
    labels = tmp_path / "l.txt"
    labels.write_text("\n".join(f"c{i}" for i in range(6)))
    rng = np.random.default_rng(3)
    locs = rng.normal(size=(1, n, 4)).astype(np.float32)
    raw = rng.normal(size=(1, n, 6)).astype(np.float32) * 4  # some pass 0.5

    def make():
        d = find_decoder("bounding_box")()
        d.init({1: "mobilenet-ssd", 2: str(labels), 3: str(priors),
                4: "96:96", 5: "96:96"})
        return d

    cfg = TensorsConfig(TensorsInfo.from_strings(
        f"4:{n}:1,6:{n}:1", "float32,float32"))
    host_out = make().decode(Buffer.of(locs, raw), cfg)
    dev = make()
    buf_dev = Buffer.of(jax.device_put(locs), jax.device_put(raw))
    token = dev.submit(buf_dev, cfg)
    assert isinstance(token, tuple), "device reduce path not taken"
    dev_out = dev.complete(token, cfg)
    h = host_out.meta["detections"]
    d = dev_out.meta["detections"]
    # both paths apply the same PRE_NMS_TOPK cap + NMS: identical results
    assert len(d) > 0 and len(h) == len(d)
    for a, b in zip(h, d):
        assert a["class"] == b["class"]
        np.testing.assert_allclose(a["box"], b["box"], rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(a["score"], b["score"], rtol=1e-4)
    np.testing.assert_array_equal(host_out.memories[0].host().shape,
                                  dev_out.memories[0].host().shape)


def test_image_segment_device_reduce_matches_host():
    import jax
    import numpy as np
    from nnstreamer_tpu.core.buffer import Buffer
    from nnstreamer_tpu.core.types import TensorsConfig, TensorsInfo
    from nnstreamer_tpu.decoders.base import find_decoder

    rng = np.random.default_rng(0)
    seg = rng.normal(size=(1, 17, 19, 5)).astype(np.float32)
    cfg = TensorsConfig(TensorsInfo.from_strings("5:19:17:1", "float32"))

    def make():
        d = find_decoder("image_segment")()
        d.init({1: "tflite-deeplab"})
        return d

    host_out = make().decode(Buffer.of(seg), cfg)
    dev = make()
    token = dev.submit(Buffer.of(jax.device_put(seg)), cfg)
    assert isinstance(token, tuple)
    dev_out = dev.complete(token, cfg)
    np.testing.assert_array_equal(host_out.memories[0].host(),
                                  dev_out.memories[0].host())


def test_pose_device_reduce_matches_host():
    import jax
    import numpy as np
    from nnstreamer_tpu.core.buffer import Buffer
    from nnstreamer_tpu.core.types import TensorsConfig, TensorsInfo
    from nnstreamer_tpu.decoders.base import find_decoder

    rng = np.random.default_rng(1)
    hm = rng.normal(size=(1, 9, 11, 17)).astype(np.float32)
    off = rng.normal(size=(1, 9, 11, 34)).astype(np.float32)
    cfg = TensorsConfig(TensorsInfo.from_strings(
        "17:11:9:1,34:11:9:1", "float32,float32"))

    def make():
        d = find_decoder("pose_estimation")()
        d.init({1: "66:66", 2: "33:33", 4: "heatmap-offset"})
        return d

    host_out = make().decode(Buffer.of(hm, off), cfg)
    dev = make()
    token = dev.submit(Buffer.of(jax.device_put(hm), jax.device_put(off)), cfg)
    assert isinstance(token, tuple)
    dev_out = dev.complete(token, cfg)
    np.testing.assert_allclose(host_out.meta["keypoints"],
                               dev_out.meta["keypoints"], rtol=1e-5, atol=1e-6)
    np.testing.assert_array_equal(host_out.memories[0].host(),
                                  dev_out.memories[0].host())

def test_bounding_box_device_reduce_overflow_candidates(tmp_path):
    """When more anchors pass the threshold than PRE_NMS_TOPK (untrained
    models emit ~0.5 sigmoid scores everywhere), both paths must cap at the
    same top-K candidate set and still agree — the round-2 host fallback
    that shipped full logits D2H every frame is gone by design."""
    import jax
    import numpy as np
    from nnstreamer_tpu.core.buffer import Buffer
    from nnstreamer_tpu.core.types import TensorsConfig, TensorsInfo
    from nnstreamer_tpu.decoders.base import find_decoder
    from nnstreamer_tpu.models.ssd_mobilenet import write_box_priors

    priors = tmp_path / "p.txt"
    n = write_box_priors(str(priors), size=192)
    assert n > 256, "need more anchors than the cap for this test"
    rng = np.random.default_rng(7)
    locs = rng.normal(size=(1, n, 4)).astype(np.float32)
    raw = np.abs(rng.normal(size=(1, n, 6))).astype(np.float32)  # all >= 0.5

    def make():
        d = find_decoder("bounding_box")()
        d.init({1: "mobilenet-ssd", 3: str(priors), 4: "192:192",
                5: "192:192"})
        return d

    cfg = TensorsConfig(TensorsInfo.from_strings(
        f"4:{n}:1,6:{n}:1", "float32,float32"))
    host_out = make().decode(Buffer.of(locs, raw), cfg)
    dev = make()
    token = dev.submit(
        Buffer.of(jax.device_put(locs), jax.device_put(raw)), cfg)
    assert isinstance(token, tuple), "device reduce path not taken"
    # the shipped reduction is K rows of 6 floats — nowhere near the
    # n*(4+classes) logits the old fallback pulled back
    assert token[1].host().nbytes <= dev.PRE_NMS_TOPK * 6 * 4
    dev_out = dev.complete(token, cfg)
    h, d = host_out.meta["detections"], dev_out.meta["detections"]
    assert len(h) == len(d) > 0
    for a, b in zip(h, d):
        assert a["class"] == b["class"]
        np.testing.assert_allclose(a["box"], b["box"], rtol=1e-4, atol=1e-5)


def test_batched_serving_frames_per_tensor(tmp_path):
    """Micro-batched serving (VERDICT r2 #4): converter frames-per-tensor
    regroups N frames into one (N,...) tensor, the model runs batch=N on
    one invoke, and image_labeling emits one label per frame."""
    labels = tmp_path / "l.txt"
    labels.write_text("\n".join(f"c{i}" for i in range(7)))
    batch = 4
    p = Pipeline()
    src = p.add_new("videotestsrc", width=32, height=32,
                    num_buffers=3 * batch, pattern="random")
    conv = p.add_new("tensor_converter", frames_per_tensor=batch)
    filt = p.add_new("tensor_filter", framework="xla-tpu",
                     model="zoo://mobilenet_v2?width=0.25&size=32"
                           f"&num_classes=7&dtype=float32&batch={batch}")
    dec = p.add_new("tensor_decoder", mode="image_labeling",
                    option1=str(labels), async_depth=2)
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, conv, filt, dec, sink)
    p.run(timeout=180)
    assert sink.num_buffers == 3
    for b in sink.buffers:
        assert len(b.meta["labels"]) == batch
        assert len(b.meta["label_scores"]) == batch


def test_synthesized_init_matches_flax_shapes():
    """Accelerator-path init (eval_shape + host synthesis) must produce the
    exact param pytree structure/shapes/dtypes flax init would."""
    import jax

    from nnstreamer_tpu.models.mobilenet_v2 import MobileNetV2
    from nnstreamer_tpu.models.zoo import synthesize_variables

    model = MobileNetV2(num_classes=5, width=0.25, dtype=np.float32)
    key = jax.random.PRNGKey(0)
    dummy = np.zeros((1, 32, 32, 3), np.float32)
    real = model.init(key, dummy)
    shapes = jax.eval_shape(lambda k: model.init(k, dummy), key)
    synth = synthesize_variables(shapes, 0)
    real_flat = jax.tree_util.tree_flatten_with_path(real)[0]
    synth_flat = jax.tree_util.tree_flatten_with_path(synth)[0]
    assert len(real_flat) == len(synth_flat)
    for (rp, rv), (sp, sv) in zip(real_flat, synth_flat):
        assert rp == sp
        assert np.shape(rv) == np.shape(sv)
        assert np.asarray(rv).dtype == np.asarray(sv).dtype
    # kernels have sane scale (not all-zero), norms are identity-ish
    out = jax.jit(model.apply)(synth, dummy)
    assert np.all(np.isfinite(np.asarray(out)))


def test_get_model_memoizes_pure_specs(tmp_path):
    from nnstreamer_tpu.models.zoo import get_model

    a = get_model("zoo://scaler?dims=4:1&types=float32&scale=2")
    b = get_model("zoo://scaler?dims=4:1&types=float32&scale=2")
    assert a is b
    c = get_model("zoo://scaler?dims=4:1&types=float32&scale=3")
    assert c is not a


def test_filter_only_options_do_not_fork_bundles():
    """custom= options the filter consumes (sync/precision/donate/...) must
    not leak into model resolution — a latency (sync=true) and a
    throughput pipeline over the same spec share one bundle and one jit."""
    from nnstreamer_tpu.filters.base import FilterProps
    from nnstreamer_tpu.filters.xla import XLAFilter, resolve_model

    a = resolve_model("zoo://scaler?dims=4:1&types=float32&scale=2",
                      {"sync": "true"})
    b = resolve_model("zoo://scaler?dims=4:1&types=float32&scale=2", {})
    assert a is b
    fa, fb = XLAFilter(), XLAFilter()
    fa.open(FilterProps(model="zoo://scaler?dims=4:1&types=float32&scale=2",
                        custom="sync=true"))
    fb.open(FilterProps(model="zoo://scaler?dims=4:1&types=float32&scale=2"))
    assert fa._jitted is fb._jitted, "jit not shared across filters"


def test_get_model_non_string_override_still_resolves():
    """Non-str overrides (programmatic callers) bypass the memo without
    crashing on key construction."""
    from nnstreamer_tpu.models.zoo import get_model

    a = get_model("zoo://scaler?dims=4:1&types=float32", scale=2.5)
    b = get_model("zoo://scaler?dims=4:1&types=float32", scale=2.5)
    assert a is not b  # float override -> uncacheable -> fresh bundle


def test_lenet_mnist_pipeline(tmp_path):
    """GRAY8 stream → zoo://lenet → image_labeling (the reference's
    mnist.pb classification pipeline shape, tests/test_models parity)."""
    from fractions import Fraction

    from nnstreamer_tpu.core import Caps

    labels = tmp_path / "digits.txt"
    labels.write_text("\n".join(str(i) for i in range(10)))
    p = Pipeline()
    frames = [np.random.default_rng(i).integers(0, 255, (28, 28, 1))
              .astype(np.uint8) for i in range(3)]
    src = p.add_new("appsrc", caps=Caps("video/x-raw", {
        "format": "GRAY8", "width": 28, "height": 28,
        "framerate": Fraction(0, 1)}), data=frames)
    conv = p.add_new("tensor_converter")
    filt = p.add_new("tensor_filter", framework="xla-tpu",
                     model="zoo://lenet")
    dec = p.add_new("tensor_decoder", mode="image_labeling",
                    option1=str(labels))
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, conv, filt, dec, sink)
    p.run(timeout=120)
    assert sink.num_buffers == 3
    assert sink.buffers[0].meta["label"] in [str(i) for i in range(10)]


def test_lenet_exports_and_redeploys(tmp_path):
    from nnstreamer_tpu.models import export_model, get_model, load_exported

    bundle = get_model("zoo://mnist")
    assert bundle is get_model("zoo://lenet")  # alias shares the memo entry
    path = str(tmp_path / "mnist.jaxexport")
    export_model(path, bundle)
    back = load_exported(path)
    x = np.random.default_rng(0).integers(0, 255, (1, 28, 28, 1)).astype(np.uint8)
    np.testing.assert_allclose(
        np.asarray(bundle.fn()(x)), np.asarray(back.fn()(x)[0]),
        rtol=1e-5, atol=1e-6)


def test_user_factory_beats_builtin_alias():
    """register_model under an aliased name must win over the alias (user
    extension point; silent shadowing would swap in the wrong model)."""
    from nnstreamer_tpu.models.zoo import (
        _aliases, _factories, get_model, register_alias, register_model)
    from nnstreamer_tpu.models.zoo import ModelBundle

    import pytest

    marker = ModelBundle("user_mnist", lambda x: x)
    register_model("mnist", lambda **_: marker)
    try:
        assert get_model("zoo://mnist") is marker
        with pytest.raises(ValueError, match="unknown canonical"):
            register_alias("foo", "no_such_model")
    finally:
        # restore the builtin alias
        _factories.pop("mnist", None)
        register_alias("mnist", "lenet")


class TestMobileNetV1:
    """The reference's flagship test model (mobilenet_v1 quant tflite):
    native v1 + quant=w8 mirrors the quantized serving shape."""

    def test_forward_shapes_and_param_count(self):
        import jax

        b = get_model("zoo://mobilenet_v1?width=0.25&size=32&num_classes=16"
                      "&dtype=float32")
        x = np.random.default_rng(0).integers(
            0, 255, (1, 32, 32, 3)).astype(np.uint8)
        out = jax.jit(b.fn())(x)
        assert out.shape == (1, 16)
        assert np.isfinite(np.asarray(out)).all()
        # v1@0.25 must be a different (smaller) network than v2@0.25
        v2 = get_model("zoo://mobilenet_v2?width=0.25&size=32"
                       "&num_classes=16&dtype=float32")
        n1 = sum(np.asarray(p).size
                 for p in jax.tree_util.tree_leaves(b.params))
        n2 = sum(np.asarray(p).size
                 for p in jax.tree_util.tree_leaves(v2.params))
        assert n1 != n2

    def test_quantized_label_pipeline(self, tmp_path):
        labels = tmp_path / "l.txt"
        labels.write_text("\n".join(f"c{i}" for i in range(16)))
        p = Pipeline()
        src = p.add_new("videotestsrc", width=32, height=32, num_buffers=3,
                        pattern="random")
        conv = p.add_new("tensor_converter")
        filt = p.add_new("tensor_filter", framework="xla-tpu",
                         model="zoo://mobilenet_v1?width=0.25&size=32"
                               "&num_classes=16&dtype=float32",
                         custom="quant=w8")
        dec = p.add_new("tensor_decoder", mode="image_labeling",
                        option1=str(labels))
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, conv, filt, dec, sink)
        p.run(timeout=180)
        assert sink.num_buffers == 3
        assert sink.buffers[0].meta["label"].startswith("c")
