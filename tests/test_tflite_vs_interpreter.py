"""Cross-validate the from-scratch TFLite importer against tf.lite.Interpreter.

The importer (models/tflite_import.py) is a hand-rolled flatbuffer reader +
JAX lowering; every golden so far was self-authored. Here the REAL TFLite
runtime is the independent oracle — the semantics the reference's
tensor_filter_tensorflow_lite.cc:154 (Interpreter::Invoke) delivers:

- whole-model: the reference's add.tflite / mobilenet quant / deeplab
- per-op: the same in-memory single-op flatbuffers used by
  test_tflite_ops.py, now ALSO executed by the real interpreter — which
  double-checks both the fixture builder's schema encoding and our lowering

Measured drift (recorded in docs/performance.md): quantized mobilenet runs
dequantized-float here vs true-int in the interpreter → ≤3 uint8 steps on
output scores (mean 0.37), identical top-1; float models agree to ~1e-5.
"""

import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")
jax = pytest.importorskip("jax")

from nnstreamer_tpu.models.tflite_import import load_tflite  # noqa: E402

import sys

sys.path.insert(0, os.path.dirname(__file__))
from test_tflite_ops import (  # noqa: E402 — shared fixture builder
    F32,
    UINT8,
    build_tflite,
    conv_options,
    dwconv_options,
    fc_options,
    pool_options,
    reducer_options,
    resize_bilinear_options,
    transpose_conv_options,
)

MODELS = "/root/reference/tests/test_models/models"
DATA = "/root/reference/tests/test_models/data"

needs_ref = pytest.mark.skipif(
    not os.path.isdir(MODELS), reason="reference test models not mounted")


def _interp_run(model_bytes_or_path, *inputs):
    if isinstance(model_bytes_or_path, (bytes, bytearray)):
        it = tf.lite.Interpreter(model_content=bytes(model_bytes_or_path))
    else:
        it = tf.lite.Interpreter(model_path=model_bytes_or_path)
    it.allocate_tensors()
    for d, x in zip(it.get_input_details(), inputs):
        it.set_tensor(d["index"], np.ascontiguousarray(x))
    it.invoke()
    return [it.get_tensor(d["index"]) for d in it.get_output_details()]


def _ours_run(model_bytes_or_path, tmp_path, *inputs):
    if isinstance(model_bytes_or_path, (bytes, bytearray)):
        path = tmp_path / "m.tflite"
        path.write_bytes(model_bytes_or_path)
        model_bytes_or_path = str(path)
    bundle = load_tflite(model_bytes_or_path)
    return [np.asarray(o) for o in jax.jit(bundle.fn())(*inputs)]


# --------------------------------------------------------------------------- #
# Whole reference models
# --------------------------------------------------------------------------- #


@needs_ref
def test_add_tflite_exact():
    x = np.linspace(-3, 3, 1, dtype=np.float32).reshape(1)
    (ours,) = _ours_run(os.path.join(MODELS, "add.tflite"), None, x)
    (ref,) = _interp_run(os.path.join(MODELS, "add.tflite"), x)
    np.testing.assert_allclose(ours, ref, rtol=0, atol=1e-6)


@needs_ref
def test_mobilenet_quant_vs_interpreter():
    """Dequantized-float strategy vs true-int interpreter: ≤3 uint8 steps
    on the score vector, identical top-1."""
    from PIL import Image

    img = np.array(Image.open(os.path.join(DATA, "orange.png"))
                   .convert("RGB").resize((224, 224)), np.uint8)[None]
    path = os.path.join(MODELS, "mobilenet_v2_1.0_224_quant.tflite")
    (ours,) = _ours_run(path, None, img)
    (ref,) = _interp_run(path, img)
    assert ours.dtype == ref.dtype == np.uint8
    diff = np.abs(ours.astype(np.int32) - ref.astype(np.int32))
    assert int(diff.max()) <= 4, f"max uint8 drift {int(diff.max())}"
    assert float(diff.mean()) < 1.0
    assert int(ours.argmax()) == int(ref.argmax())


@needs_ref
def test_deeplab_vs_interpreter():
    from PIL import Image

    x = np.array(Image.open(os.path.join(DATA, "orange.png"))
                 .convert("RGB").resize((257, 257)),
                 np.float32)[None] / 127.5 - 1.0
    path = os.path.join(MODELS, "deeplabv3_257_mv_gpu.tflite")
    (ours,) = _ours_run(path, None, x)
    (ref,) = _interp_run(path, x)
    assert ours.shape == ref.shape
    np.testing.assert_allclose(ours, ref, rtol=0, atol=5e-4)
    # segmentation decision identical everywhere
    assert (ours.argmax(-1) == ref.argmax(-1)).all()


# --------------------------------------------------------------------------- #
# Per-op fixtures vs the real runtime
# --------------------------------------------------------------------------- #

CONV2D, DWCONV, AVGPOOL, MAXPOOL = 3, 4, 1, 17
RESIZE_BILINEAR, FULLY_CONNECTED, MEAN, SOFTMAX = 23, 9, 40, 25
TRANSPOSE_CONV = 67

def _softmax_opts():
    def build(b):
        b.StartObject(1)            # SoftmaxOptions: beta
        b.PrependFloat32Slot(0, 1.0, 0.0)
        return b.EndObject()

    return (9, build)               # BuiltinOptions.SoftmaxOptions


def _fixture_conv_same_relu(rng):
    x = rng.standard_normal((1, 5, 5, 2), dtype=np.float32)
    w = rng.standard_normal((3, 2, 2, 2), dtype=np.float32)
    bias = rng.standard_normal(3, dtype=np.float32)
    blob = build_tflite(
        tensors=[
            {"shape": (1, 5, 5, 2), "type": F32, "data": None},
            {"shape": (3, 2, 2, 2), "type": F32, "data": w},
            {"shape": (3,), "type": F32, "data": bias},
            {"shape": (1, 3, 3, 3), "type": F32, "data": None},
        ],
        operators=[{"code": CONV2D, "inputs": [0, 1, 2], "outputs": [3],
                    "options": conv_options(stride=2, padding=0,
                                                     activation=1)}],
        inputs=[0], outputs=[3])
    return blob, (x,)


def _fixture_dwconv(rng):
    x = rng.standard_normal((1, 4, 4, 3), dtype=np.float32)
    w = rng.standard_normal((1, 3, 3, 3), dtype=np.float32)
    bias = np.zeros(3, np.float32)
    blob = build_tflite(
        tensors=[
            {"shape": (1, 4, 4, 3), "type": F32, "data": None},
            {"shape": (1, 3, 3, 3), "type": F32, "data": w},
            {"shape": (3,), "type": F32, "data": bias},
            {"shape": (1, 4, 4, 3), "type": F32, "data": None},
        ],
        operators=[{"code": DWCONV, "inputs": [0, 1, 2], "outputs": [3],
                    "options": dwconv_options(stride=1, padding=0)}],
        inputs=[0], outputs=[3])
    return blob, (x,)


def _fixture_avgpool_same(rng):
    x = rng.standard_normal((1, 5, 5, 2), dtype=np.float32)
    blob = build_tflite(
        tensors=[
            {"shape": (1, 5, 5, 2), "type": F32, "data": None},
            {"shape": (1, 3, 3, 2), "type": F32, "data": None},
        ],
        operators=[{"code": AVGPOOL, "inputs": [0], "outputs": [1],
                    "options": pool_options(filt=2, stride=2,
                                                     padding=0)}],
        inputs=[0], outputs=[1])
    return blob, (x,)


def _fixture_maxpool(rng):
    x = rng.standard_normal((1, 4, 4, 2), dtype=np.float32)
    blob = build_tflite(
        tensors=[
            {"shape": (1, 4, 4, 2), "type": F32, "data": None},
            {"shape": (1, 2, 2, 2), "type": F32, "data": None},
        ],
        operators=[{"code": MAXPOOL, "inputs": [0], "outputs": [1],
                    "options": pool_options(filt=2, stride=2,
                                                     padding=1)}],
        inputs=[0], outputs=[1])
    return blob, (x,)


def _fixture_resize_half_pixel(rng):
    x = rng.standard_normal((1, 3, 3, 1), dtype=np.float32)

    def size_const():
        return np.array([6, 6], np.int32)

    blob = build_tflite(
        tensors=[
            {"shape": (1, 3, 3, 1), "type": F32, "data": None},
            {"shape": (2,), "type": 2, "data": size_const()},
            {"shape": (1, 6, 6, 1), "type": F32, "data": None},
        ],
        operators=[{"code": RESIZE_BILINEAR, "inputs": [0, 1], "outputs": [2],
                    "options": resize_bilinear_options(
                        align_corners=False, half_pixel=True)}],
        inputs=[0], outputs=[2])
    return blob, (x,)


def _fixture_fc(rng):
    x = rng.standard_normal((2, 6), dtype=np.float32)
    w = rng.standard_normal((4, 6), dtype=np.float32)
    bias = rng.standard_normal(4, dtype=np.float32)
    blob = build_tflite(
        tensors=[
            {"shape": (2, 6), "type": F32, "data": None},
            {"shape": (4, 6), "type": F32, "data": w},
            {"shape": (4,), "type": F32, "data": bias},
            {"shape": (2, 4), "type": F32, "data": None},
        ],
        operators=[{"code": FULLY_CONNECTED, "inputs": [0, 1, 2],
                    "outputs": [3],
                    "options": fc_options(activation=0)}],
        inputs=[0], outputs=[3])
    return blob, (x,)


def _fixture_mean(rng):
    x = rng.standard_normal((1, 4, 5, 3), dtype=np.float32)
    blob = build_tflite(
        tensors=[
            {"shape": (1, 4, 5, 3), "type": F32, "data": None},
            {"shape": (2,), "type": 2, "data": np.array([1, 2], np.int32)},
            {"shape": (1, 1, 1, 3), "type": F32, "data": None},
        ],
        operators=[{"code": MEAN, "inputs": [0, 1], "outputs": [2],
                    "options": reducer_options(keep_dims=True)}],
        inputs=[0], outputs=[2])
    return blob, (x,)


def _fixture_softmax(rng):
    x = rng.standard_normal((2, 7), dtype=np.float32)
    blob = build_tflite(
        tensors=[
            {"shape": (2, 7), "type": F32, "data": None},
            {"shape": (2, 7), "type": F32, "data": None},
        ],
        operators=[{"code": SOFTMAX, "inputs": [0], "outputs": [1],
                    "options": _softmax_opts()}],
        inputs=[0], outputs=[1])
    return blob, (x,)


def _fixture_quant_conv(rng):
    """Per-tensor quantized conv: uint8 in/out, float internally here vs
    true-int in the interpreter — tolerance is a few quant steps."""
    x = rng.integers(0, 255, (1, 4, 4, 1), dtype=np.uint8)
    w = rng.integers(0, 255, (2, 3, 3, 1), dtype=np.uint8)
    bias = rng.integers(-100, 100, (2,), dtype=np.int32)
    blob = build_tflite(
        tensors=[
            {"shape": (1, 4, 4, 1), "type": UINT8, "data": None,
             "quant": (0.02, 128)},
            {"shape": (2, 3, 3, 1), "type": UINT8, "data": w,
             "quant": (0.005, 121)},
            {"shape": (2,), "type": 2, "data": bias, "quant": (0.0001, 0)},
            {"shape": (1, 2, 2, 2), "type": UINT8, "data": None,
             "quant": (0.05, 110)},
        ],
        operators=[{"code": CONV2D, "inputs": [0, 1, 2], "outputs": [3],
                    "options": conv_options(stride=1, padding=0)}],
        inputs=[0], outputs=[3])
    return blob, (x,)


def _fixture_transpose_conv(rng):
    w = rng.standard_normal((1, 3, 3, 1), dtype=np.float32)
    x = rng.standard_normal((1, 2, 2, 1), dtype=np.float32)
    blob = build_tflite(
        tensors=[
            {"shape": (4,), "type": 2,
             "data": np.array([1, 5, 5, 1], np.int32)},  # VALID: (2-1)*2+3
            {"shape": (1, 3, 3, 1), "type": F32, "data": w},
            {"shape": (1, 2, 2, 1), "type": F32, "data": None},
            {"shape": (1, 5, 5, 1), "type": F32, "data": None},
        ],
        operators=[{"code": TRANSPOSE_CONV, "inputs": [0, 1, 2],
                    "outputs": [3],
                    "options": transpose_conv_options(stride=2,
                                                      padding=1)}],
        inputs=[2], outputs=[3])
    return blob, (x,)


FIXTURES = {
    "conv_same_relu": (_fixture_conv_same_relu, 1e-5),
    "dwconv": (_fixture_dwconv, 1e-5),
    "avgpool_same": (_fixture_avgpool_same, 1e-5),
    "maxpool": (_fixture_maxpool, 1e-5),
    "resize_half_pixel": (_fixture_resize_half_pixel, 1e-5),
    "fully_connected": (_fixture_fc, 1e-5),
    "mean_keepdims": (_fixture_mean, 1e-5),
    "softmax": (_fixture_softmax, 1e-5),
    "transpose_conv": (_fixture_transpose_conv, 1e-5),
}


@pytest.mark.parametrize("name", sorted(FIXTURES))
def test_op_matches_interpreter(name, tmp_path):
    build, atol = FIXTURES[name]
    blob, inputs = build(np.random.default_rng(17))
    ref = _interp_run(blob, *inputs)
    ours = _ours_run(blob, tmp_path, *inputs)
    assert len(ours) == len(ref)
    for o, r in zip(ours, ref):
        assert o.shape == r.shape and o.dtype == r.dtype
        np.testing.assert_allclose(o, r, rtol=1e-5, atol=atol)


def _build_detection_postprocess(rng, n_anchors=32, num_classes=3,
                                 max_detections=8, with_background=True):
    """A TFLite_Detection_PostProcess graph (the SSD family the reference's
    mobilenet-ssd-postprocess decoder mode exists for)."""
    from flatbuffers import flexbuffers

    fbb = flexbuffers.Builder()
    with fbb.Map():
        fbb.Int("max_detections", max_detections)
        fbb.Int("max_classes_per_detection", 1)
        fbb.Int("detections_per_class", 100)
        fbb.Bool("use_regular_nms", False)
        fbb.Float("nms_score_threshold", 0.3)
        fbb.Float("nms_iou_threshold", 0.5)
        fbb.Int("num_classes", num_classes)
        fbb.Float("y_scale", 10.0)
        fbb.Float("x_scale", 10.0)
        fbb.Float("h_scale", 5.0)
        fbb.Float("w_scale", 5.0)
    opts = fbb.Finish()

    # anchors: a grid of centers with fixed size (ycenter, xcenter, h, w)
    g = int(np.ceil(np.sqrt(n_anchors)))
    yy, xx = np.meshgrid(np.linspace(0.1, 0.9, g), np.linspace(0.1, 0.9, g))
    anchors = np.stack([yy.ravel()[:n_anchors], xx.ravel()[:n_anchors],
                        np.full(n_anchors, 0.2), np.full(n_anchors, 0.2)],
                       axis=1).astype(np.float32)
    locs = (rng.standard_normal((1, n_anchors, 4)) * 0.5).astype(np.float32)
    ncols = num_classes + (1 if with_background else 0)
    scores = rng.uniform(0, 1, (1, n_anchors, ncols)).astype(np.float32)

    blob = build_tflite(
        tensors=[
            {"shape": (1, n_anchors, 4), "type": F32, "data": None},
            {"shape": (1, n_anchors, ncols), "type": F32, "data": None},
            {"shape": (n_anchors, 4), "type": F32, "data": anchors},
            {"shape": (1, max_detections, 4), "type": F32, "data": None},
            {"shape": (1, max_detections), "type": F32, "data": None},
            {"shape": (1, max_detections), "type": F32, "data": None},
            {"shape": (1,), "type": F32, "data": None},
        ],
        operators=[{"code": 32, "custom_code": "TFLite_Detection_PostProcess",
                    "custom_options": opts,
                    "inputs": [0, 1, 2], "outputs": [3, 4, 5, 6]}],
        inputs=[0, 1], outputs=[3, 4, 5, 6])
    return blob, (locs, scores)


def test_detection_postprocess_vs_interpreter(tmp_path):
    """CUSTOM:TFLite_Detection_PostProcess lowering matches the real
    runtime's registered kernel on boxes/classes/scores/count."""
    blob, inputs = _build_detection_postprocess(np.random.default_rng(5))
    ref = _interp_run(blob, *inputs)
    ours = _ours_run(blob, tmp_path, *inputs)
    r_boxes, r_cls, r_scr, r_num = ref
    o_boxes, o_cls, o_scr, o_num = ours
    assert int(o_num[0]) == int(r_num[0]) > 0
    n = int(r_num[0])
    np.testing.assert_allclose(o_scr[0, :n], r_scr[0, :n], atol=1e-5)
    np.testing.assert_array_equal(o_cls[0, :n], r_cls[0, :n])
    np.testing.assert_allclose(o_boxes[0, :n], r_boxes[0, :n], atol=1e-5)


def test_detection_postprocess_no_background_column(tmp_path):
    """num_classes == score columns (no implicit background): label offset 0."""
    blob, inputs = _build_detection_postprocess(
        np.random.default_rng(9), with_background=False)
    ref = _interp_run(blob, *inputs)
    ours = _ours_run(blob, tmp_path, *inputs)
    n = int(ref[3][0])
    assert int(ours[3][0]) == n > 0
    np.testing.assert_array_equal(ours[1][0, :n], ref[1][0, :n])
    np.testing.assert_allclose(ours[0][0, :n], ref[0][0, :n], atol=1e-5)


def _build_detection_postprocess_regular(rng, n_anchors=32, num_classes=3,
                                         max_detections=8,
                                         detections_per_class=100):
    """Same graph with use_regular_nms=true (per-class NMS kernel path)."""
    from flatbuffers import flexbuffers

    fbb = flexbuffers.Builder()
    with fbb.Map():
        fbb.Int("max_detections", max_detections)
        fbb.Int("max_classes_per_detection", 1)
        fbb.Int("detections_per_class", detections_per_class)
        fbb.Bool("use_regular_nms", True)
        fbb.Float("nms_score_threshold", 0.3)
        fbb.Float("nms_iou_threshold", 0.5)
        fbb.Int("num_classes", num_classes)
        fbb.Float("y_scale", 10.0)
        fbb.Float("x_scale", 10.0)
        fbb.Float("h_scale", 5.0)
        fbb.Float("w_scale", 5.0)
    opts = fbb.Finish()
    g = int(np.ceil(np.sqrt(n_anchors)))
    yy, xx = np.meshgrid(np.linspace(0.1, 0.9, g), np.linspace(0.1, 0.9, g))
    anchors = np.stack([yy.ravel()[:n_anchors], xx.ravel()[:n_anchors],
                        np.full(n_anchors, 0.2), np.full(n_anchors, 0.2)],
                       axis=1).astype(np.float32)
    locs = (rng.standard_normal((1, n_anchors, 4)) * 0.5).astype(np.float32)
    scores = rng.uniform(0, 1, (1, n_anchors, num_classes + 1)) \
        .astype(np.float32)
    blob = build_tflite(
        tensors=[
            {"shape": (1, n_anchors, 4), "type": F32, "data": None},
            {"shape": (1, n_anchors, num_classes + 1), "type": F32,
             "data": None},
            {"shape": (n_anchors, 4), "type": F32, "data": anchors},
            {"shape": (1, max_detections, 4), "type": F32, "data": None},
            {"shape": (1, max_detections), "type": F32, "data": None},
            {"shape": (1, max_detections), "type": F32, "data": None},
            {"shape": (1,), "type": F32, "data": None},
        ],
        operators=[{"code": 32, "custom_code": "TFLite_Detection_PostProcess",
                    "custom_options": opts,
                    "inputs": [0, 1, 2], "outputs": [3, 4, 5, 6]}],
        inputs=[0, 1], outputs=[3, 4, 5, 6])
    return blob, (locs, scores)


@pytest.mark.parametrize("dpc", [100, 2])
def test_detection_postprocess_regular_nms_vs_interpreter(tmp_path, dpc):
    """use_regular_nms=true (per-class NMS, incl. a binding
    detections_per_class cap) matches the interpreter's kernel."""
    blob, inputs = _build_detection_postprocess_regular(
        np.random.default_rng(21), detections_per_class=dpc)
    ref = _interp_run(blob, *inputs)
    ours = _ours_run(blob, tmp_path, *inputs)
    r_boxes, r_cls, r_scr, r_num = ref
    o_boxes, o_cls, o_scr, o_num = ours
    assert int(o_num[0]) == int(r_num[0]) > 0
    nn = int(r_num[0])
    np.testing.assert_allclose(o_scr[0, :nn], r_scr[0, :nn], atol=1e-5)
    np.testing.assert_array_equal(o_cls[0, :nn], r_cls[0, :nn])
    np.testing.assert_allclose(o_boxes[0, :nn], r_boxes[0, :nn], atol=1e-5)


def test_detection_postprocess_feeds_ssd_decoder(tmp_path):
    """E2e: the imported postprocess model serves through a pipeline and its
    4 outputs feed tensor_decoder mode=bounding_boxes
    option1=mobilenet-ssd-postprocess (the reference decoder pairing,
    tensordec-boundingbox.c:121-133)."""
    from nnstreamer_tpu.graph import Pipeline

    blob, (locs, scores) = _build_detection_postprocess(
        np.random.default_rng(5))
    model = tmp_path / "ssd_pp.tflite"
    model.write_bytes(blob)
    (ref_boxes, ref_cls, ref_scr, ref_num) = _interp_run(blob, locs, scores)

    from nnstreamer_tpu.core.types import Caps, TensorsConfig, TensorsInfo

    labels = tmp_path / "labels.txt"
    labels.write_text("a\nb\nc\n")
    info = TensorsInfo.from_strings("4:32:1,4:32:1", "float32")
    p = Pipeline()
    src = p.add_new("appsrc", caps=Caps.tensors(TensorsConfig(info, 0)),
                    data=[(locs, scores)])
    filt = p.add_new("tensor_filter", framework="tensorflow2-lite",
                     model=str(model))
    dec = p.add_new("tensor_decoder", mode="bounding_box",
                    option1="mobilenet-ssd-postprocess",
                    option2=str(labels), option4="160:120", option5="320:320")
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, filt, dec, sink)
    p.run(timeout=120)
    b = sink.buffers[0]
    assert b.memories[0].host().shape == (120, 160, 4)
    dets = b.meta["detections"]
    assert len(dets) == int(ref_num[0])
    got_scores = sorted(round(d["score"], 5) for d in dets)
    want_scores = sorted(round(float(s), 5) for s in ref_scr[0, :int(ref_num[0])])
    assert got_scores == want_scores


def test_quant_conv_within_quant_steps(tmp_path):
    blob, inputs = _fixture_quant_conv(np.random.default_rng(17))
    (ref,) = _interp_run(blob, *inputs)
    (ours,) = _ours_run(blob, tmp_path, *inputs)
    assert ours.dtype == ref.dtype == np.uint8
    diff = np.abs(ours.astype(np.int32) - ref.astype(np.int32))
    assert int(diff.max()) <= 2, f"quant drift {int(diff.max())} steps"


def test_full_integer_int8_model_from_real_converter(tmp_path):
    """A full-integer (int8 I/O) model produced by the REAL
    tf.lite.TFLiteConverter — the modern quantization path (the uint8
    reference models are the legacy one) — imports and matches the
    interpreter exactly."""
    tf.random.set_seed(0)
    m = tf.keras.Sequential([
        tf.keras.layers.Input((16, 16, 3)),
        tf.keras.layers.Conv2D(8, 3, strides=2, padding="same",
                               activation="relu"),
        tf.keras.layers.Conv2D(16, 3, strides=2, padding="same",
                               activation="relu"),
        tf.keras.layers.GlobalAveragePooling2D(),
        tf.keras.layers.Dense(10),
        tf.keras.layers.Softmax(),
    ])
    conv = tf.lite.TFLiteConverter.from_keras_model(m)
    conv.optimizations = [tf.lite.Optimize.DEFAULT]
    rng = np.random.default_rng(0)

    def rep():
        for _ in range(16):
            yield [rng.uniform(0, 1, (1, 16, 16, 3)).astype(np.float32)]

    conv.representative_dataset = rep
    conv.target_spec.supported_ops = [tf.lite.OpsSet.TFLITE_BUILTINS_INT8]
    conv.inference_input_type = tf.int8
    conv.inference_output_type = tf.int8
    blob = conv.convert()

    x = rng.integers(-128, 127, (1, 16, 16, 3), dtype=np.int8)
    (ref,) = _interp_run(blob, x)
    (ours,) = _ours_run(blob, tmp_path, x)
    assert ours.dtype == ref.dtype == np.int8
    diff = np.abs(ours.astype(np.int32) - ref.astype(np.int32))
    assert int(diff.max()) <= 1, f"int8 drift {int(diff.max())} steps"


# --------------------------------------------------------------------------- #
# Multi-subgraph control flow (IF / WHILE → lax.cond / lax.while_loop)
# --------------------------------------------------------------------------- #


def _convert_fn(fn, signature):
    conv = tf.lite.TFLiteConverter.from_concrete_functions(
        [tf.function(fn, input_signature=signature).get_concrete_function()])
    return conv.convert()


def test_if_model_both_branches(tmp_path):
    """tf.cond converts to a 3-subgraph IF model; both branches match the
    interpreter (lax.cond traces both — same semantics)."""

    def f(x):
        return tf.cond(tf.reduce_sum(x) > 0, lambda: x * 2.0, lambda: x - 1.0)

    blob = _convert_fn(f, [tf.TensorSpec([4], tf.float32)])
    for x in (np.array([1., -2., 3., 0.5], np.float32),
              np.array([-1., -2., -3., -0.5], np.float32)):
        (ref,) = _interp_run(blob, x)
        (ours,) = _ours_run(blob, tmp_path, x)
        np.testing.assert_allclose(ours, ref, rtol=1e-6, atol=1e-6)


def test_while_model(tmp_path):
    """tf.while_loop converts to a 3-subgraph WHILE model; the carried
    tuple maps onto lax.while_loop."""

    def g(x):
        i = tf.constant(0)

        def cond(i, x):
            return i < 3

        def body(i, x):
            return i + 1, x * 2.0

        _, out = tf.while_loop(cond, body, [i, x])
        return out

    blob = _convert_fn(g, [tf.TensorSpec([3], tf.float32)])
    x = np.array([1., -2., 3.], np.float32)
    (ref,) = _interp_run(blob, x)
    (ours,) = _ours_run(blob, tmp_path, x)
    np.testing.assert_allclose(ours, ref, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(ours, x * 8.0, rtol=1e-6)  # 3 doublings


def test_while_data_dependent_trip_count(tmp_path):
    """Trip count depending on runtime DATA (not just a constant): the
    while condition reads the carried tensor value."""

    def g(x):
        def cond(x):
            return tf.reduce_max(x) < 100.0

        def body(x):
            return (x * 3.0,)

        (out,) = tf.while_loop(cond, body, [x])
        return out

    blob = _convert_fn(g, [tf.TensorSpec([2], tf.float32)])
    for x in (np.array([1., 2.], np.float32),
              np.array([50., 1.], np.float32),
              np.array([200., 1.], np.float32)):  # zero iterations
        (ref,) = _interp_run(blob, x)
        (ours,) = _ours_run(blob, tmp_path, x)
        np.testing.assert_allclose(ours, ref, rtol=1e-6, atol=1e-6)


def test_gather_batch_dims(tmp_path):
    def f(params, idx):
        return tf.gather(params, idx, axis=2, batch_dims=1)

    blob = _convert_fn(f, [tf.TensorSpec([2, 3, 5], tf.float32),
                           tf.TensorSpec([2, 4], tf.int32)])
    rng = np.random.default_rng(3)
    params = rng.standard_normal((2, 3, 5)).astype(np.float32)
    idx = rng.integers(0, 5, (2, 4)).astype(np.int32)
    (ref,) = _interp_run(blob, params, idx)
    (ours,) = _ours_run(blob, tmp_path, params, idx)
    assert ours.shape == ref.shape
    np.testing.assert_allclose(ours, ref)


def test_strided_slice_newaxis_and_ellipsis(tmp_path):
    def f(x):
        a = x[:, tf.newaxis, 1:, 0]      # new_axis + shrink
        b = x[..., ::2]                  # ellipsis + stride
        return a, b

    blob = _convert_fn(f, [tf.TensorSpec([2, 3, 4], tf.float32)])
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    ref = _interp_run(blob, x)
    ours = _ours_run(blob, tmp_path, x)
    assert len(ours) == len(ref)
    for o, r in zip(ours, ref):
        assert o.shape == r.shape, (o.shape, r.shape)
        np.testing.assert_allclose(o, r)
