"""SSAT-style end-to-end launch-string sweep.

Reference model: the 41 runTest.sh SSAT groups drive full pipelines via
gst-launch strings (tests/nnstreamer_*/runTest.sh, `gstTest "<pipeline>"
caseid [expect-fail]`). This suite does the same through the REAL CLI
entry (`nnstreamer_tpu.cli.main`) so every case exercises the textual
parser + element construction + full run, not the Python API.
"""

import sys

import numpy as np
import pytest

from nnstreamer_tpu.cli import main as cli_main


def launch(pipeline: str, timeout: float = 120.0) -> int:
    return cli_main([pipeline, "--timeout", str(timeout)])


@pytest.fixture(scope="module")
def labels16(tmp_path_factory):
    p = tmp_path_factory.mktemp("launch") / "labels.txt"
    p.write_text("\n".join(f"l{i}" for i in range(16)))
    return str(p)


MODEL = ("zoo://mobilenet_v2?width=0.25&size=32&num_classes=16"
         "&dtype=float32")

PASS_CASES = [
    # structural
    "videotestsrc num-buffers=4 width=16 height=16 ! tensor_converter ! "
    "tensor_sink",
    "videotestsrc num-buffers=4 width=16 height=16 ! tensor_converter ! "
    "queue ! tensor_sink",
    # transform grammar (reference tensor_transform modes)
    "videotestsrc num-buffers=4 width=16 height=16 ! tensor_converter ! "
    "tensor_transform mode=arithmetic "
    "option=typecast:float32,add:-127.5,div:127.5 ! tensor_sink",
    "videotestsrc num-buffers=4 width=16 height=16 ! tensor_converter ! "
    "tensor_transform mode=transpose option=1:0:2:3 ! tensor_sink",
    "videotestsrc num-buffers=4 width=16 height=16 ! tensor_converter ! "
    "tensor_transform mode=clamp option=10:200 ! tensor_sink",
    # filter + decoder
    f"videotestsrc num-buffers=3 width=32 height=32 ! tensor_converter ! "
    f'tensor_filter framework=xla-tpu model="{MODEL}" ! tensor_sink',
    # quantized serving through the launch string
    f"videotestsrc num-buffers=3 width=32 height=32 ! tensor_converter ! "
    f'tensor_filter framework=xla-tpu model="{MODEL}" custom=quant=w8 ! '
    f"tensor_sink",
    # adaptive micro-batching elements
    f"videotestsrc num-buffers=8 width=32 height=32 ! tensor_converter ! "
    f"tensor_batch max-batch=4 budget-ms=100 ! "
    f'tensor_filter framework=xla-tpu model="{MODEL}&batch=4" ! '
    f"tensor_unbatch ! tensor_sink",
    # aggregator window
    "videotestsrc num-buffers=8 width=8 height=8 ! tensor_converter ! "
    "tensor_aggregator frames_in=1 frames_out=4 frames_flush=4 "
    "frames_dim=3 ! tensor_sink",
    # tee fan-out with two sinks
    "videotestsrc num-buffers=4 width=8 height=8 ! tensor_converter ! "
    "tee name=t t. ! queue ! tensor_sink t. ! queue ! tensor_sink",
]

FAIL_CASES = [
    # unknown element / property / malformed grammar (SSAT expect-fail)
    "videotestsrc num-buffers=2 ! tensor_bogus ! tensor_sink",
    "videotestsrc num-buffers=2 bogus-prop=1 ! tensor_sink",
    "videotestsrc num-buffers=2 ! tensor_converter ! "
    "tensor_transform mode=nope option=1 ! tensor_sink",
    "videotestsrc num-buffers=2 ! tensor_converter ! "
    "tensor_filter framework=no-such-fw model=x ! tensor_sink",
    "videotestsrc num-buffers=2 ! ! tensor_sink",
]


@pytest.mark.parametrize("pipeline", PASS_CASES,
                         ids=[f"ok{i}" for i in range(len(PASS_CASES))])
def test_launch_ok(pipeline):
    assert launch(pipeline) == 0


def test_launch_with_labels_decode(labels16):
    pipeline = (
        f"videotestsrc num-buffers=3 width=32 height=32 ! tensor_converter "
        f'! tensor_filter framework=xla-tpu model="{MODEL}" ! '
        f"tensor_decoder mode=image_labeling option1={labels16} ! "
        f"tensor_sink")
    assert launch(pipeline) == 0


@pytest.mark.parametrize("pipeline", FAIL_CASES,
                         ids=[f"bad{i}" for i in range(len(FAIL_CASES))])
def test_launch_expect_fail(pipeline):
    assert launch(pipeline, timeout=30.0) != 0


def test_list_elements_includes_new():
    import io
    from contextlib import redirect_stdout

    out = io.StringIO()
    with redirect_stdout(out):
        assert cli_main(["--list-elements"]) == 0
    listing = out.getvalue()
    for el in ("tensor_batch", "tensor_unbatch", "tensor_trainer",
               "tensor_query_client", "tensor_filter"):
        assert el in listing


def test_inspect_new_elements():
    import io
    from contextlib import redirect_stdout

    for el in ("tensor_batch", "tensor_unbatch"):
        out = io.StringIO()
        with redirect_stdout(out):
            assert cli_main(["--inspect", el]) == 0
        assert "max_batch" in out.getvalue() or "sink" in out.getvalue()


def test_quoted_bang_preserved_in_prop():
    from nnstreamer_tpu.graph.parse import _split_branches

    branches = _split_branches('a ! b opt="x!y" ! c')
    assert branches[0][1] == ("b", {"opt": "x!y"})


def test_timeout_returns_distinct_code():
    # an endless source never reaches EOS: rc 2, not success
    rc = launch("videotestsrc width=8 height=8 ! tensor_converter ! "
                "tensor_sink", timeout=1.0)
    assert rc == 2


def test_failed_start_leaks_no_threads():
    import threading

    before = {t.name for t in threading.enumerate()}
    rc = launch("videotestsrc num-buffers=4 width=8 height=8 ! "
                "tensor_converter ! queue ! "
                "tensor_transform mode=nope option=1 ! tensor_sink",
                timeout=10.0)
    assert rc == 1
    import time as _t

    _t.sleep(0.3)
    leaked = {t.name for t in threading.enumerate()} - before
    assert not {n for n in leaked if n.startswith(("q:", "src:", "batch:"))}, \
        f"leaked pipeline threads: {leaked}"


def test_hash_in_prop_value_not_a_comment():
    from nnstreamer_tpu.graph.parse import _split_branches

    branches = _split_branches("a ! b opt=x#y ! c")
    assert branches[0][1] == ("b", {"opt": "x#y"})
    assert branches[0][2] == ("c", {})


def test_kv_flags_set_env_transport(monkeypatch):
    # --kv-page-size/--kv-pages export the NNS_LM_KV_* env BEFORE the
    # pipeline starts, so any LMEngine built during the run picks the
    # paged cache up (serving/lm_engine.py reads them at __init__)
    import os

    monkeypatch.delenv("NNS_LM_KV_PAGE_SIZE", raising=False)
    monkeypatch.delenv("NNS_LM_KV_PAGES", raising=False)
    rc = cli_main(["--kv-page-size", "8", "--kv-pages", "64",
                   "--timeout", "30",
                   "videotestsrc num-buffers=2 width=8 height=8 ! "
                   "tensor_converter ! tensor_sink"])
    try:
        assert rc == 0
        assert os.environ["NNS_LM_KV_PAGE_SIZE"] == "8"
        assert os.environ["NNS_LM_KV_PAGES"] == "64"
    finally:
        os.environ.pop("NNS_LM_KV_PAGE_SIZE", None)
        os.environ.pop("NNS_LM_KV_PAGES", None)


@pytest.mark.parametrize("argv", [
    ["--kv-pages", "8"],                      # pages without a page size
    ["--kv-page-size", "0"],                  # page size must be >= 1
    ["--kv-page-size", "8", "--kv-pages", "0"],
], ids=["pages-alone", "zero-ps", "zero-pages"])
def test_kv_flag_validation_rejected(argv, monkeypatch):
    import os

    monkeypatch.delenv("NNS_LM_KV_PAGE_SIZE", raising=False)
    with pytest.raises(SystemExit) as ei:
        cli_main(argv + ["videotestsrc num-buffers=1 ! tensor_converter "
                         "! tensor_sink"])
    assert ei.value.code == 2
    # a rejected flag combo must not leak half-set env into the process
    assert "NNS_LM_KV_PAGE_SIZE" not in os.environ
    assert "NNS_LM_KV_PAGES" not in os.environ


def test_role_and_disagg_flags_set_env_transport(monkeypatch):
    # --role/--disagg export NNS_LM_ROLE/NNS_LM_DISAGG before the run,
    # so every LMEngine built inside picks its disagg role up
    import os

    monkeypatch.delenv("NNS_LM_ROLE", raising=False)
    monkeypatch.delenv("NNS_LM_DISAGG", raising=False)
    monkeypatch.delenv("NNS_LM_KV_PAGE_SIZE", raising=False)
    rc = cli_main(["--kv-page-size", "8", "--role", "decode",
                   "--disagg", "127.0.0.1:7001;127.0.0.1:7002",
                   "--timeout", "30",
                   "videotestsrc num-buffers=2 width=8 height=8 ! "
                   "tensor_converter ! tensor_sink"])
    try:
        assert rc == 0
        assert os.environ["NNS_LM_ROLE"] == "decode"
        assert os.environ["NNS_LM_DISAGG"] \
            == "127.0.0.1:7001;127.0.0.1:7002"
    finally:
        os.environ.pop("NNS_LM_ROLE", None)
        os.environ.pop("NNS_LM_DISAGG", None)
        os.environ.pop("NNS_LM_KV_PAGE_SIZE", None)


@pytest.mark.parametrize("argv", [
    ["--role", "prefill"],                    # role needs the paged cache
    ["--role", "supervisor", "--kv-page-size", "8"],   # unknown role
    ["--disagg", "127.0.0.1:7001"],           # no ';' split
    ["--disagg", ";127.0.0.1:7002"],          # empty prefill side
    ["--disagg", "127.0.0.1:7001;oops"],      # unparsable decode side
], ids=["role-no-paging", "bad-role", "no-split", "empty-side",
        "bad-endpoint"])
def test_role_disagg_validation_rejected(argv, monkeypatch):
    import os

    monkeypatch.delenv("NNS_LM_ROLE", raising=False)
    monkeypatch.delenv("NNS_LM_DISAGG", raising=False)
    with pytest.raises(SystemExit) as ei:
        cli_main(argv + ["videotestsrc num-buffers=1 ! tensor_converter "
                         "! tensor_sink"])
    assert ei.value.code == 2
    assert "NNS_LM_ROLE" not in os.environ
    assert "NNS_LM_DISAGG" not in os.environ


@pytest.mark.parametrize("argv", [
    ["--hedge-ms", "5"],                                # hedging is routed-only
    ["--backends", "nonsense"],                         # not host:port
    ["--backends", "127.0.0.1:1,127.0.0.1:1"],          # duplicate endpoint
    ["--backends", "127.0.0.1:1,x:70000"],              # port out of range
    ["--backends", "127.0.0.1:1", "--hedge-ms", "5"],   # hedge needs >= 2
    ["--backends", "127.0.0.1:1,127.0.0.1:2", "--hedge-ms", "0"],
], ids=["hedge-alone", "bad-endpoint", "dup-endpoint", "bad-port",
        "hedge-single-backend", "zero-hedge"])
def test_backends_flag_validation_rejected(argv):
    with pytest.raises(SystemExit) as ei:
        cli_main(argv + ["videotestsrc num-buffers=1 ! tensor_converter "
                         "! tensor_query_client ! tensor_sink"])
    assert ei.value.code == 2


def test_backends_flag_needs_a_query_client():
    with pytest.raises(SystemExit) as ei:
        cli_main(["--backends", "127.0.0.1:1",
                  "videotestsrc num-buffers=1 ! tensor_converter ! "
                  "tensor_sink"])
    assert ei.value.code == 2


def test_backends_flag_wires_router_with_fallback_last_resort():
    # both endpoints dead: the routed client exhausts its backends and
    # takes the local fallback — the run COMPLETES (rc 0), the fleet
    # flags reached the element through the real CLI path
    import socket

    def _free():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    eps = f"127.0.0.1:{_free()},127.0.0.1:{_free()}"
    rc = cli_main(["--backends", eps, "--hedge-ms", "5",
                   "--fallback", "passthrough", "--timeout", "60",
                   "videotestsrc num-buffers=2 width=8 height=8 ! "
                   "tensor_converter ! "
                   "tensor_query_client max-request-retry=1 timeout-s=0.3 "
                   "retry-base-s=0.001 retry-max-s=0.002 "
                   "breaker-threshold=1 ! tensor_sink"])
    assert rc == 0


SCHED_FILTER_PIPELINE = (
    f"videotestsrc num-buffers=4 width=32 height=32 ! tensor_converter ! "
    f'tensor_filter framework=xla-tpu model="{MODEL}" ! tensor_sink')


def _no_scheduler_leaked():
    from nnstreamer_tpu import sched

    return sched.installed() is None


def test_sched_bare_flag_keeps_pipeline_positional(capsys):
    # bare --sched (nargs="?") directly before the positional: the
    # normalizer must not let argparse eat the pipeline as WIDTH
    rc = cli_main(["--sched", SCHED_FILTER_PIPELINE, "--timeout", "120"])
    assert rc == 0
    assert "multiplexing" in capsys.readouterr().err
    assert _no_scheduler_leaked()


def test_sched_chained_bare_flags_before_positional():
    # regression: two bare optional-value flags back to back — deferring
    # --profile must not slide the pipeline into --sched's value slot
    from nnstreamer_tpu.obs import profile, tracing
    try:
        rc = cli_main(["--sched", "--profile", SCHED_FILTER_PIPELINE,
                       "--timeout", "120"])
    finally:
        # cli_main enables these process-wide (a real launch exits);
        # in-process they must not instrument later tests' pipelines
        profile.disable()
        tracing.disable()
    assert rc == 0
    assert _no_scheduler_leaked()


def test_sched_composes_with_trace_and_explicit_width():
    from nnstreamer_tpu.obs import tracing
    try:
        rc = cli_main(["--sched", "4", "--trace", SCHED_FILTER_PIPELINE,
                       "--timeout", "120"])
    finally:
        tracing.disable()
    assert rc == 0
    assert _no_scheduler_leaked()


def test_sched_composes_with_deadline_and_fallback():
    # --deadline-ms needs a tensor_query_client; dead default backend +
    # passthrough fallback completes, with every invoke sched-routed
    rc = cli_main(["--sched", "--deadline-ms", "200",
                   "--fallback", "passthrough", "--timeout", "60",
                   "videotestsrc num-buffers=2 width=8 height=8 ! "
                   "tensor_converter ! "
                   "tensor_query_client max-request-retry=1 timeout-s=0.3 "
                   "retry-base-s=0.001 retry-max-s=0.002 "
                   "breaker-threshold=1 ! tensor_sink"])
    assert rc == 0
    assert _no_scheduler_leaked()


def test_sched_tenant_presets_accepted():
    rc = cli_main(["--sched", "8", "--sched-tenants", "pipe:4:1,lm:1",
                   SCHED_FILTER_PIPELINE, "--timeout", "120"])
    assert rc == 0
    assert _no_scheduler_leaked()


@pytest.mark.parametrize("argv", [
    ["--sched", "0"],                          # width must be >= 1
    ["--sched-tenants", "cam:4"],              # presets need --sched
    ["--sched", "--sched-tenants", "cam"],     # missing weight
    ["--sched", "--sched-tenants", "cam:0"],   # weight must be > 0
    ["--sched", "--sched-tenants", "cam:x"],   # weight must be numeric
], ids=["zero-width", "tenants-alone", "no-weight", "zero-weight",
        "bad-weight"])
def test_sched_flag_validation_rejected(argv):
    with pytest.raises(SystemExit) as ei:
        cli_main(argv + ["videotestsrc num-buffers=1 ! tensor_converter "
                         "! tensor_sink"])
    assert ei.value.code == 2
    assert _no_scheduler_leaked()


def test_list_models_includes_zoo_families():
    import io
    from contextlib import redirect_stdout

    out = io.StringIO()
    with redirect_stdout(out):
        assert cli_main(["--list-models"]) == 0
    listing = out.getvalue()
    for m in ("mobilenet_v1", "mobilenet_v2", "ssd_mobilenet_v2",
              "deeplab_v3", "posenet", "causal_lm", "moe_transformer"):
        assert m in listing
