"""Pipeline graph runtime tests: linking, dataflow, queue/tee/join, EOS,
errors, sync policies (mirrors reference unittest_sink + join + common)."""

import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import (
    CollectPads,
    Element,
    FlowReturn,
    Pipeline,
    PipelineError,
    SyncPolicy,
)
from nnstreamer_tpu.elements.sources import AppSrc, VideoTestSrc
from nnstreamer_tpu.elements.sinks import AppSink, FakeSink, TensorSink


def tensor_caps(dims, types, rate=30):
    return Caps.tensors(TensorsConfig(TensorsInfo.from_strings(dims, types), rate))


def make_arrays(n, shape=(4,), dtype=np.float32):
    return [np.full(shape, i, dtype) for i in range(n)]


class TestBasicFlow:
    def test_appsrc_to_sink(self):
        p = Pipeline()
        src = AppSrc(caps=tensor_caps("4", "float32"), data=make_arrays(5))
        sink = TensorSink(store=True)
        p.add_linked(src, sink)
        p.run(timeout=10)
        assert sink.num_buffers == 5
        np.testing.assert_array_equal(sink.buffers[2].memories[0].host(),
                                      np.full((4,), 2, np.float32))

    def test_pts_synthesis(self):
        p = Pipeline()
        src = AppSrc(caps=tensor_caps("4", "float32"), data=make_arrays(3),
                     framerate=30)
        sink = TensorSink(store=True)
        p.add_linked(src, sink)
        p.run(timeout=10)
        pts = [b.pts for b in sink.buffers]
        assert pts[0] == 0 and pts[1] == pytest.approx(1e9 / 30, rel=1e-3)

    def test_num_buffers_prop(self):
        p = Pipeline()
        src = VideoTestSrc(width=8, height=8, num_buffers=4)
        sink = FakeSink()
        p.add_linked(src, sink)
        p.run(timeout=10)
        assert sink.num_buffers == 4

    def test_caps_event_reaches_sink(self):
        p = Pipeline()
        src = AppSrc(caps=tensor_caps("2:2", "uint8"), data=[np.zeros((2, 2), np.uint8)])
        sink = TensorSink()
        p.add_linked(src, sink)
        p.run(timeout=10)
        assert sink.sink_pad.caps is not None
        assert sink.sink_pad.caps.media_type == "other/tensors"

    def test_new_data_callback(self):
        seen = []
        p = Pipeline()
        src = AppSrc(caps=tensor_caps("4", "float32"), data=make_arrays(3))
        sink = TensorSink(new_data=lambda b: seen.append(b.offset))
        p.add_linked(src, sink)
        p.run(timeout=10)
        assert seen == [0, 1, 2]

    def test_unlinked_pad_fails(self):
        p = Pipeline()
        p.add(AppSrc(caps=tensor_caps("4", "float32"), data=[]))
        with pytest.raises(ValueError, match="unlinked"):
            p.start()

    def test_unknown_property_fails(self):
        with pytest.raises(ValueError, match="unknown property"):
            FakeSink(bogus_prop=1)


class TestQueueTeeJoin:
    def test_queue_decouples(self):
        from nnstreamer_tpu.graph import Queue

        p = Pipeline()
        src = AppSrc(caps=tensor_caps("4", "float32"), data=make_arrays(20))
        q = Queue(max_size_buffers=4)
        sink = TensorSink(store=True)
        p.add_linked(src, q, sink)
        p.run(timeout=10)
        assert sink.num_buffers == 20
        assert [b.offset for b in sink.buffers] == list(range(20))

    def test_tee_fanout(self):
        from nnstreamer_tpu.graph import Queue, Tee

        p = Pipeline()
        src = AppSrc(caps=tensor_caps("4", "float32"), data=make_arrays(6))
        tee = Tee()
        q1, q2 = Queue(), Queue()
        s1, s2 = TensorSink(store=True), TensorSink(store=True)
        p.add(src, tee, q1, q2, s1, s2)
        Pipeline.link(src, tee)
        Pipeline.link(tee, q1, s1)
        Pipeline.link(tee, q2, s2)
        p.run(timeout=10)
        assert s1.num_buffers == 6 and s2.num_buffers == 6

    def test_join_first_come(self):
        from nnstreamer_tpu.graph import Join

        p = Pipeline()
        a = AppSrc(caps=tensor_caps("4", "float32"), data=make_arrays(3))
        b = AppSrc(caps=tensor_caps("4", "float32"), data=make_arrays(2))
        j = Join()
        sink = TensorSink(store=True)
        p.add(a, b, j, sink)
        Pipeline.link(a, j)
        Pipeline.link(b, j)
        Pipeline.link(j, sink)
        p.run(timeout=10)
        assert sink.num_buffers == 5


class TestErrors:
    def test_chain_error_posts_bus_error(self):
        class Boom(Element):
            ELEMENT_NAME = "boom"

            def __init__(self, **kw):
                super().__init__(**kw)
                self.add_sink_pad()
                self.add_src_pad()

            def chain(self, pad, buf):
                raise RuntimeError("kaboom")

        p = Pipeline()
        src = AppSrc(caps=tensor_caps("4", "float32"), data=make_arrays(3))
        boom = Boom()
        sink = FakeSink()
        p.add_linked(src, boom, sink)
        with pytest.raises(PipelineError, match="kaboom"):
            p.run(timeout=10)


class TestAppSink:
    def test_pull(self):
        p = Pipeline()
        src = AppSrc(caps=tensor_caps("4", "float32"), data=make_arrays(3))
        sink = AppSink()
        p.add_linked(src, sink)
        p.start()
        got = []
        while True:
            b = sink.pull(timeout=5)
            if b is None:
                break
            got.append(b)
        p.stop()
        assert len(got) == 3


class TestCollectPads:
    def B(self, pts, v=0):
        return Buffer.of(np.full((2,), v, np.float32), pts=pts, duration=10)

    def test_nosync(self):
        c = CollectPads(["a", "b"], SyncPolicy.NOSYNC)
        assert c.push("a", self.B(0)) == []
        sets = c.push("b", self.B(100))
        assert len(sets) == 1
        s, pts = sets[0]
        assert set(s) == {"a", "b"}

    def test_slowest_drops_stale(self):
        c = CollectPads(["a", "b"], SyncPolicy.SLOWEST)
        c.push("a", self.B(0, v=1))
        c.push("a", self.B(100, v=2))
        sets = c.push("b", self.B(100, v=3))
        assert len(sets) == 1
        s, pts = sets[0]
        assert pts == 100
        # pad a's stale pts=0 buffer was dropped in favor of pts=100
        np.testing.assert_array_equal(s["a"].memories[0].host(),
                                      np.full((2,), 2, np.float32))

    def test_basepad(self):
        c = CollectPads(["a", "b"], SyncPolicy.BASEPAD, base_key="a",
                        base_duration_ns=50)
        c.push("b", self.B(0))
        c.push("b", self.B(40))
        sets = c.push("a", self.B(35))
        assert len(sets) == 1
        _, pts = sets[0]
        assert pts == 35

    def test_refresh_reuses_last(self):
        c = CollectPads(["a", "b"], SyncPolicy.REFRESH)
        c.push("a", self.B(0, v=1))
        s1 = c.push("b", self.B(5, v=2))
        assert len(s1) == 1
        s2 = c.push("b", self.B(10, v=3))  # 'a' not updated: reuse last
        assert len(s2) == 1
        np.testing.assert_array_equal(s2[0][0]["a"].memories[0].host(),
                                      np.full((2,), 1, np.float32))

    def test_exhausted_on_eos(self):
        c = CollectPads(["a", "b"], SyncPolicy.SLOWEST)
        c.push("a", self.B(0))
        c.set_eos("b")
        assert c.exhausted


class TestLeakyQueue:
    def test_leaky_upstream_never_drops_eos(self):
        import time
        from nnstreamer_tpu.graph import Queue

        class SlowSink(TensorSink):
            ELEMENT_NAME = "slowsink"

            def chain(self, pad, buf):
                time.sleep(0.01)
                return super().chain(pad, buf)

        p = Pipeline()
        src = AppSrc(caps=tensor_caps("4", "float32"), data=make_arrays(30))
        q = Queue(max_size_buffers=2, leaky="upstream")
        sink = SlowSink(store=True)
        p.add_linked(src, q, sink)
        p.run(timeout=10)  # must reach EOS even though buffers are dropped
        assert 0 < sink.num_buffers <= 30


class TestAudioSrc:
    def test_unsigned_offset_sine(self):
        from nnstreamer_tpu.elements.sources import AudioTestSrc

        p = Pipeline()
        src = AudioTestSrc(format="U8", num_buffers=2, samplesperbuffer=256)
        sink = TensorSink(store=True)
        p.add_linked(src, sink)
        p.run(timeout=10)
        samples = sink.buffers[0].memories[0].host()
        # offset sine: mean near midpoint, no wraparound clustering at extremes
        assert 100 < samples.astype(np.float64).mean() < 155
