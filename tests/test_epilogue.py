"""Epilogue fusion tests: ops/epilogue.py + ops/pallas/epilogue.py.

The contract under test is bit-identity: a fused pipeline (post-filter
chain compiled into the filter's jit) must produce exactly what the
unfused element-by-element pipeline produces, for every fused stage
kind — transforms, passthrough converters, and reduce-capable decoders.
Kernel tests run the Pallas programs in interpret mode against their
jnp references; pipeline tests diff fused vs unfused end-to-end.
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.core.buffer import Buffer, TensorMemory
from nnstreamer_tpu.graph import Pipeline
from nnstreamer_tpu.ops.pallas import epilogue as ep


def caps_of(dims, types, rate=30):
    return Caps.tensors(TensorsConfig(TensorsInfo.from_strings(dims, types),
                                      rate))


# --------------------------------------------------------------------------- #
# Pallas kernels vs references (interpret mode)
# --------------------------------------------------------------------------- #

class TestKernels:
    def _boxes(self, k, seed=0):
        rng = np.random.default_rng(seed)
        x0 = rng.uniform(0, 0.8, k).astype(np.float32)
        y0 = rng.uniform(0, 0.8, k).astype(np.float32)
        x1 = x0 + rng.uniform(0.05, 0.3, k).astype(np.float32)
        y1 = y0 + rng.uniform(0.05, 0.3, k).astype(np.float32)
        scores = np.sort(rng.uniform(0, 1, k).astype(np.float32))[::-1].copy()
        return tuple(jnp.asarray(v) for v in (x0, y0, x1, y1, scores))

    @pytest.mark.parametrize("k", [32, 37])  # aligned + non-lane-aligned
    def test_nms_sweep_bit_exact(self, k):
        x0, y0, x1, y1, s = self._boxes(k, seed=k)
        ref = ep.nms_sweep_reference(x0, y0, x1, y1, s, 0.5, 0.25)
        got = ep.nms_sweep(x0, y0, x1, y1, s, iou_threshold=0.5,
                           threshold=0.25, interpret=True)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))

    def test_class_reduce_bit_exact(self):
        rng = np.random.default_rng(1)
        cls = jnp.asarray(rng.normal(size=(123, 20)).astype(np.float32))
        rs, ri = ep.class_reduce_reference(cls)
        ks, ki = ep.class_reduce(cls, interpret=True)
        np.testing.assert_array_equal(np.asarray(rs), np.asarray(ks))
        np.testing.assert_array_equal(np.asarray(ri), np.asarray(ki))

    def test_class_reduce_tie_break_first_max(self):
        cls = jnp.asarray(np.array([[1.0, 3.0, 3.0, 0.0],
                                    [2.0, 2.0, 2.0, 2.0]], np.float32))
        _, ri = ep.class_reduce_reference(cls)
        _, ki = ep.class_reduce(cls, interpret=True)
        np.testing.assert_array_equal(np.asarray(ri), np.asarray(ki))

    def _palette(self):
        pal = np.zeros((256, 4), np.uint8)
        pal[1:, :3] = np.arange(1, 256)[:, None] * np.array([3, 5, 7]) % 256
        pal[1:, 3] = 160
        return pal

    def test_segment_colorize_logits_bit_exact(self):
        rng = np.random.default_rng(2)
        logits = jnp.asarray(rng.normal(size=(33, 41, 21)).astype(np.float32))
        pal = self._palette()
        ref = ep.segment_colorize_reference(logits, pal)
        got = ep.segment_colorize(logits, pal, interpret=True)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))

    def test_segment_colorize_pre_argmaxed_bit_exact(self):
        rng = np.random.default_rng(3)
        ids = jnp.asarray(rng.integers(0, 21, (33, 41)).astype(np.float32))
        pal = self._palette()
        ref = ep.segment_colorize_reference(ids, pal, pre_argmaxed=True)
        got = ep.segment_colorize(ids, pal, pre_argmaxed=True, interpret=True)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))

    def _dgr_inputs(self, r=17, f=130, seed=4):
        rng = np.random.default_rng(seed)
        y = jnp.asarray(rng.integers(-2000, 2000, (r, f)).astype(np.int32))
        xs = jnp.asarray(rng.uniform(1e-3, 1e-2, (r, 1)).astype(np.float32))
        ws = jnp.asarray(rng.uniform(1e-3, 1e-2, (f,)).astype(np.float32))
        return y, xs, ws

    def test_dequant_gelu_requant_f32_bit_exact(self):
        # the reference must itself be jitted: eager XLA contracts the
        # dequant multiply chain differently (1-ulp scale drift), and the
        # production comparison is always jit-vs-jit
        y, xs, ws = self._dgr_inputs()
        ref = jax.jit(functools.partial(ep.dequant_gelu_requant_reference,
                                        out_dtype=jnp.float32))
        rq, rs = ref(y, xs, ws)
        kq, ks = ep.dequant_gelu_requant(y, xs, ws, out_dtype=jnp.float32,
                                         interpret=True)
        np.testing.assert_array_equal(np.asarray(rq), np.asarray(kq))
        np.testing.assert_array_equal(np.asarray(rs), np.asarray(ks))

    def test_dequant_gelu_requant_bf16_close(self):
        # pallas interpret mode evaluates bf16 intermediates in f32, so
        # bf16 can't be asserted bit-exact off-TPU: quantized codes must
        # land within 1 and scales within bf16 epsilon of the reference
        y, xs, ws = self._dgr_inputs(seed=5)
        ref = jax.jit(functools.partial(ep.dequant_gelu_requant_reference,
                                        out_dtype=jnp.bfloat16))
        rq, rs = ref(y, xs, ws)
        kq, ks = ep.dequant_gelu_requant(y, xs, ws, out_dtype=jnp.bfloat16,
                                         interpret=True)
        dq = np.abs(np.asarray(rq, np.int32) - np.asarray(kq, np.int32))
        assert dq.max() <= 1
        np.testing.assert_allclose(np.asarray(rs, np.float32),
                                   np.asarray(ks, np.float32), rtol=1e-2)

    def test_dequant_gelu_requant_zero_row_scale(self):
        # an all-zero row must emit scale 1.0, not 0/127 (div-by-zero in
        # the consumer's dequant otherwise)
        y = jnp.zeros((4, 130), jnp.int32)
        xs = jnp.full((4, 1), 1e-3, jnp.float32)
        ws = jnp.full((130,), 1e-3, jnp.float32)
        q, s = ep.dequant_gelu_requant(y, xs, ws, out_dtype=jnp.float32,
                                       interpret=True)
        assert np.all(np.asarray(q) == 0)
        np.testing.assert_array_equal(np.asarray(s), np.ones((4, 1), np.float32))


class TestMlpMatmul:
    def test_quantized_fused_matches_unfused(self):
        from nnstreamer_tpu.ops import int8

        rng = np.random.default_rng(6)
        x = jnp.asarray(rng.normal(size=(5, 32)).astype(np.float32))
        w1 = jnp.asarray(rng.normal(size=(32, 64)).astype(np.float32) * 0.1)
        w2 = jnp.asarray(rng.normal(size=(64, 16)).astype(np.float32) * 0.1)
        q1, q2 = int8.quantize_weight(w1), int8.quantize_weight(w2)
        fused = jax.jit(int8.mlp_matmul)(x, q1, q2)
        unfused = jax.jit(lambda x: int8.matmul_any(
            jax.nn.gelu(int8.matmul_any(x, q1)), q2))(x)
        np.testing.assert_allclose(np.asarray(fused), np.asarray(unfused),
                                   rtol=2e-2, atol=2e-2)

    def test_unquantized_passthrough_exact(self):
        from nnstreamer_tpu.ops import int8

        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.normal(size=(3, 8)).astype(np.float32))
        w1 = jnp.asarray(rng.normal(size=(8, 12)).astype(np.float32))
        w2 = jnp.asarray(rng.normal(size=(12, 4)).astype(np.float32))
        got = jax.jit(int8.mlp_matmul)(x, w1, w2)
        want = jax.jit(lambda x: jax.nn.gelu(x @ w1) @ w2)(x)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# --------------------------------------------------------------------------- #
# pipeline-level fused vs unfused bit-identity
# --------------------------------------------------------------------------- #

def _run_pair(build):
    """build(auto_fuse) -> (pipeline, sink); returns both runs."""
    pf, sf = build(True)
    pu, su = build(False)
    return pf, sf, pu, su


class TestPipelineFusion:
    def test_transform_chain_fused_bit_identical(self):
        data = [np.linspace(-2, 2, 8, dtype=np.float32).reshape(1, 8)]

        def build(auto_fuse):
            p = Pipeline()
            p.auto_fuse = auto_fuse
            src = p.add_new("appsrc", caps=caps_of("8:1", "float32"),
                            data=data)
            f = p.add_new("tensor_filter", model=lambda x: jnp.tanh(x))
            t1 = p.add_new("tensor_transform", mode="arithmetic",
                           option="mul:3.0,add:0.25")
            t2 = p.add_new("tensor_transform", mode="clamp", option="-0.5:2.5")
            sink = p.add_new("tensor_sink", store=True)
            Pipeline.link(src, f, t1, t2, sink)
            p.run(timeout=60)
            return p, sink

        pf, sf, pu, su = _run_pair(build)
        assert pf._epilogue_count == 2
        assert pu._epilogue_count == 0
        np.testing.assert_array_equal(sf.buffers[0].memories[0].host(),
                                      su.buffers[0].memories[0].host())

    def test_converter_passthrough_fused(self):
        data = [np.ones((1, 4), np.float32) * 7]

        def build(auto_fuse):
            p = Pipeline()
            p.auto_fuse = auto_fuse
            src = p.add_new("appsrc", caps=caps_of("4:1", "float32"),
                            data=data)
            f = p.add_new("tensor_filter", model=lambda x: x * 2 + 1)
            conv = p.add_new("tensor_converter")
            sink = p.add_new("tensor_sink", store=True)
            Pipeline.link(src, f, conv, sink)
            p.run(timeout=60)
            return p, sink

        pf, sf, pu, su = _run_pair(build)
        assert pf._epilogue_count == 1
        assert pu._epilogue_count == 0
        np.testing.assert_array_equal(sf.buffers[0].memories[0].host(),
                                      su.buffers[0].memories[0].host())

    def _ssd_build(self, tmp_path, auto_fuse, async_depth=0):
        from nnstreamer_tpu.models.ssd_mobilenet import write_box_priors

        priors = tmp_path / "priors.txt"
        n = write_box_priors(str(priors), size=96)
        rng = np.random.default_rng(8)
        flat = np.concatenate(
            [rng.normal(size=(1, n * 4)).astype(np.float32),
             rng.normal(size=(1, n * 6)).astype(np.float32) * 4], axis=1)

        def model(x, n=n):
            return (x[:, :n * 4].reshape(1, n, 4),
                    x[:, n * 4:].reshape(1, n, 6))

        p = Pipeline()
        p.auto_fuse = auto_fuse
        src = p.add_new("appsrc", caps=caps_of(f"{n * 10}:1", "float32"),
                        data=[flat])
        f = p.add_new("tensor_filter", model=model)
        kw = {"async_depth": async_depth} if async_depth else {}
        dec = p.add_new("tensor_decoder", mode="bounding_box",
                        option1="mobilenet-ssd", option3=str(priors),
                        option4="96:96", option5="96:96", **kw)
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, f, dec, sink)
        p.run(timeout=120)
        return p, sink

    def test_ssd_decoder_fused_matches_unfused(self, tmp_path):
        pf, sf = self._ssd_build(tmp_path, True)
        pu, su = self._ssd_build(tmp_path, False)
        assert pf._epilogue_count == 1
        assert pu._epilogue_count == 0
        h = su.buffers[0].meta["detections"]
        d = sf.buffers[0].meta["detections"]
        assert len(d) > 0 and len(h) == len(d)
        for a, b in zip(h, d):
            assert a["class"] == b["class"]
            np.testing.assert_allclose(a["box"], b["box"], rtol=1e-4,
                                       atol=1e-5)
            np.testing.assert_allclose(a["score"], b["score"], rtol=1e-4)
        assert sf.buffers[0].memories[0].host().shape == \
            su.buffers[0].memories[0].host().shape

    def test_ssd_decoder_fused_async_depth(self, tmp_path):
        # async submit/complete path with the fused reduce: the tuple
        # token carries the pre-reduced rows through the depth queue
        pf, sf = self._ssd_build(tmp_path, True, async_depth=2)
        pu, su = self._ssd_build(tmp_path, True)
        assert pf._epilogue_count == 1
        np.testing.assert_array_equal(sf.buffers[0].memories[0].host(),
                                      su.buffers[0].memories[0].host())
        assert sf.buffers[0].meta["detections"] == \
            su.buffers[0].meta["detections"]

    def test_image_segment_fused_bit_identical(self):
        h, w, classes = 13, 11, 5
        rng = np.random.default_rng(9)
        logits = rng.normal(size=(1, h, w, classes)).astype(np.float32)

        def build(auto_fuse):
            p = Pipeline()
            p.auto_fuse = auto_fuse
            src = p.add_new("appsrc",
                            caps=caps_of(f"{classes}:{w}:{h}:1", "float32"),
                            data=[logits])
            f = p.add_new("tensor_filter", model=lambda x: x * 1.5)
            dec = p.add_new("tensor_decoder", mode="image_segment",
                            option1="tflite-deeplab")
            sink = p.add_new("tensor_sink", store=True)
            Pipeline.link(src, f, dec, sink)
            p.run(timeout=60)
            return p, sink

        pf, sf, pu, su = _run_pair(build)
        assert pf._epilogue_count == 1
        assert pu._epilogue_count == 0
        fused = sf.buffers[0].memories[0].host()
        plain = su.buffers[0].memories[0].host()
        assert fused.shape == (h, w, 4)
        np.testing.assert_array_equal(fused, plain)

    def test_auto_fuse_off_is_opt_out(self):
        data = [np.ones((1, 4), np.float32)]
        p = Pipeline()
        p.auto_fuse = False
        src = p.add_new("appsrc", caps=caps_of("4:1", "float32"), data=data)
        f = p.add_new("tensor_filter", model=lambda x: x + 1)
        t = p.add_new("tensor_transform", mode="typecast", option="float32")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, f, t, sink)
        p.run(timeout=60)
        assert p._epilogue_count == 0
        assert not t._fused_post

    def test_select_hook_can_veto(self, monkeypatch):
        from nnstreamer_tpu.ops import epilogue as epi

        calls = []

        def veto(filter_label, chain_labels):
            calls.append((filter_label, list(chain_labels)))
            return False

        monkeypatch.setattr(epi, "EPILOGUE_SELECT_HOOK", veto)
        data = [np.ones((1, 4), np.float32)]
        p = Pipeline()
        src = p.add_new("appsrc", caps=caps_of("4:1", "float32"), data=data)
        f = p.add_new("tensor_filter", model=lambda x: x + 1)
        t = p.add_new("tensor_transform", mode="typecast", option="float32")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, f, t, sink)
        p.run(timeout=60)
        assert p._epilogue_count == 0
        assert len(calls) == 1
        assert calls[0][1] == [t.name]

    def test_fusion_stops_at_branching(self):
        data = [np.ones((1, 4), np.float32)]
        p = Pipeline()
        src = p.add_new("appsrc", caps=caps_of("4:1", "float32"), data=data)
        f = p.add_new("tensor_filter", model=lambda x: x + 1)
        t = p.add_new("tensor_transform", mode="typecast", option="float32")
        tee = p.add_new("tee")
        q1 = p.add_new("queue")
        s1 = p.add_new("tensor_sink", store=True)
        q2 = p.add_new("queue")
        s2 = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, f, t, tee)
        Pipeline.link(tee, q1, s1)
        Pipeline.link(tee, q2, s2)
        p.run(timeout=60)
        # the chain ends at the tee: the transform still fuses (it is
        # upstream of the branch point with single pads)
        assert p._epilogue_count == 1
        np.testing.assert_array_equal(s1.buffers[0].memories[0].host(),
                                      s2.buffers[0].memories[0].host())


# --------------------------------------------------------------------------- #
# decoder-level fused contract for the modes without a pipeline harness
# --------------------------------------------------------------------------- #

class TestDecoderReduceModes:
    def _fused_roundtrip(self, make, arrays, cfg):
        """host decode vs epilogue_reduce applied out-of-band (what the
        fused filter jit does) + decode on the pre-reduced buffer."""
        host_out = make().decode(Buffer.of(*arrays), cfg)
        d = make()
        red = d.epilogue_reduce()
        assert red is not None
        rows = jax.jit(red)(tuple(jnp.asarray(a) for a in arrays))
        d._fused_epilogue = True
        fused_out = d.decode(Buffer.of(np.asarray(rows)), cfg)
        return host_out, fused_out

    @staticmethod
    def _same_detections(host_out, fused_out):
        h = host_out.meta["detections"]
        d = fused_out.meta["detections"]
        assert len(h) == len(d) > 0
        for a, b in zip(h, d):
            assert a["class"] == b["class"]
            np.testing.assert_allclose(a["box"], b["box"], rtol=1e-5,
                                       atol=1e-6)
            np.testing.assert_allclose(a["score"], b["score"], rtol=1e-5)

    def test_postprocess_mode(self, tmp_path):
        from nnstreamer_tpu.decoders.base import find_decoder

        labels = tmp_path / "l.txt"
        labels.write_text("person\ncar\n")
        boxes = np.array([[[0.1, 0.1, 0.5, 0.5],
                           [0.6, 0.6, 0.9, 0.9]]], np.float32)
        classes = np.array([[0, 1]], np.float32)
        scores = np.array([[0.9, 0.8]], np.float32)
        count = np.array([2], np.float32)
        cfg = TensorsConfig(TensorsInfo.from_strings(
            "4:2:1,2:1,2:1,1", "float32"))

        def make():
            d = find_decoder("bounding_box")()
            d.init({1: "mobilenet-ssd-postprocess", 2: str(labels),
                    4: "160:120", 5: "300:300"})
            return d

        host_out, fused_out = self._fused_roundtrip(
            make, (boxes, classes, scores, count), cfg)
        self._same_detections(host_out, fused_out)
        np.testing.assert_array_equal(host_out.memories[0].host(),
                                      fused_out.memories[0].host())

    def test_postprocess_count_caps_rows(self, tmp_path):
        from nnstreamer_tpu.decoders.base import find_decoder

        boxes = np.array([[[0.1, 0.1, 0.5, 0.5],
                           [0.6, 0.6, 0.9, 0.9]]], np.float32)
        classes = np.array([[0, 1]], np.float32)
        scores = np.array([[0.9, 0.8]], np.float32)
        count = np.array([1], np.float32)  # second row invalid
        cfg = TensorsConfig(TensorsInfo.from_strings(
            "4:2:1,2:1,2:1,1", "float32"))

        def make():
            d = find_decoder("bounding_box")()
            d.init({1: "mobilenet-ssd-postprocess", 4: "64:64", 5: "64:64"})
            return d

        host_out, fused_out = self._fused_roundtrip(
            make, (boxes, classes, scores, count), cfg)
        assert len(host_out.meta["detections"]) == 1
        self._same_detections(host_out, fused_out)

    def test_ov_mode(self):
        from nnstreamer_tpu.decoders.base import find_decoder

        rng = np.random.default_rng(10)
        rows = np.zeros((1, 8, 7), np.float32)
        rows[0, :, 0] = [0, 0, 0, -1, 0, 0, -1, 0]  # two invalid markers
        rows[0, :, 1] = rng.integers(0, 4, 8)
        rows[0, :, 2] = rng.uniform(0.3, 1.0, 8)
        rows[0, :, 3:] = np.sort(
            rng.uniform(0, 1, (8, 4)).astype(np.float32), axis=1)
        cfg = TensorsConfig(TensorsInfo.from_strings("7:8:1", "float32"))

        def make():
            d = find_decoder("bounding_box")()
            d.init({1: "ov-person-detection", 4: "64:64", 5: "64:64"})
            return d

        host_out, fused_out = self._fused_roundtrip(make, (rows,), cfg)
        self._same_detections(host_out, fused_out)

    def test_snpe_deeplab_pre_argmaxed(self):
        from nnstreamer_tpu.decoders.base import find_decoder

        rng = np.random.default_rng(11)
        ids = rng.integers(0, 21, (1, 9, 7)).astype(np.float32)
        cfg = TensorsConfig(TensorsInfo.from_strings("7:9:1", "float32"))

        def make():
            d = find_decoder("image_segment")()
            d.init({1: "snpe-deeplab"})
            return d

        host_out, fused_out = self._fused_roundtrip(make, (ids,), cfg)
        np.testing.assert_array_equal(host_out.memories[0].host(),
                                      fused_out.memories[0].host())

    def test_snpe_depth_has_no_reduce(self):
        from nnstreamer_tpu.decoders.base import find_decoder

        d = find_decoder("image_segment")()
        d.init({1: "snpe-depth"})
        # data-dependent min/max normalize: host-only, never fused
        assert d.epilogue_reduce() is None


# --------------------------------------------------------------------------- #
# filter-level coalescing + sched composition
# --------------------------------------------------------------------------- #

class TestCoalesce:
    SPEC = ("zoo://mobilenet_v2?width=0.25&size=32&num_classes=16"
            "&dtype=float32")

    def test_epilogue_token_splits_and_joins_coalesce_key(self):
        from nnstreamer_tpu.filters.base import FilterProps
        from nnstreamer_tpu.filters.xla import XLAFilter
        from nnstreamer_tpu.sched.engine import _coalesce_key

        mem = TensorMemory(np.zeros((1, 32, 32, 3), np.float32))
        a, b, c = XLAFilter(), XLAFilter(), XLAFilter()
        for f in (a, b, c):
            f.open(FilterProps(model=self.SPEC))
        try:
            base = a.invoke([mem])[0].host()

            def post(outs):
                return tuple(y * 2.0 for y in outs)

            a.set_fused_epilogue(post, token="t1")
            b.set_fused_epilogue(post, token="t1")
            c.set_fused_epilogue(post, token="t2")
            assert _coalesce_key(a, [mem]) == _coalesce_key(b, [mem])
            assert _coalesce_key(c, [mem]) != _coalesce_key(a, [mem])
            np.testing.assert_allclose(a.invoke([mem])[0].host(), base * 2.0,
                                       rtol=1e-6)
        finally:
            for f in (a, b, c):
                f.close()

    def test_sched_composed_coalesced_epilogue(self):
        from nnstreamer_tpu.models.zoo import ModelBundle
        from nnstreamer_tpu.sched import DeviceEngine

        # one shared bundle: the coalesce token anchors on bundle
        # identity, so both filters must resolve to the same object
        model = ModelBundle(
            "epi_mean",
            lambda x: jnp.asarray(x, jnp.float32).mean(axis=(1, 2, 3)))

        def build(n, scheduler=None, auto_fuse=True):
            p = Pipeline(f"epi{n}", scheduler=scheduler)
            p.auto_fuse = auto_fuse
            src = p.add_new("videotestsrc", width=16, height=16,
                            num_buffers=3, pattern="random", seed=50 + n)
            conv = p.add_new("tensor_converter")
            filt = p.add_new("tensor_filter", framework="xla-tpu",
                             model=model)
            tr = p.add_new("tensor_transform", mode="arithmetic",
                           option="mul:2.0,add:1.0")
            sink = p.add_new("tensor_sink", store=True)
            Pipeline.link(src, conv, filt, tr, sink)
            return p, filt, sink

        def outputs(sink):
            return [np.asarray(b.memories[0].host()) for b in sink.buffers]

        # serial, unfused: the oracle
        serial = []
        for i in range(2):
            p, _, sink = build(i, auto_fuse=False)
            p.run(timeout=120)
            serial.append(outputs(sink))

        eng = DeviceEngine("epi", autostart=True, max_coalesce=4)
        try:
            built = [build(i, scheduler=eng) for i in range(2)]
            for p, _, _ in built:
                p.start()
            for p, _, _ in built:
                assert p.wait_eos(120)
            tokens = [f.fw.coalesce_token for _, f, _ in built]
            assert tokens[0] == tokens[1]
            assert any(isinstance(part, tuple) and len(part) == 2
                       and part[0] == "post" for part in tokens[0])
            for p, _, _ in built:
                assert p._epilogue_count == 1
                p.stop()
            assert eng.stats["items"] == 2 * 3
            for i, (_, _, sink) in enumerate(built):
                got = outputs(sink)
                assert len(got) == len(serial[i]) == 3
                for a, b in zip(got, serial[i]):
                    np.testing.assert_array_equal(a, b)
        finally:
            eng.stop()


# --------------------------------------------------------------------------- #
# profiler-driven selection
# --------------------------------------------------------------------------- #

class TestProfilerSelect:
    def test_no_samples_fuses_unconditionally(self):
        from nnstreamer_tpu.obs.profile import Profiler

        p = Profiler()
        assert p.epilogue_select("f0", ["t0", "d0"]) is True

    def test_cheap_chain_declined_costly_chain_fused(self):
        from nnstreamer_tpu.obs.profile import Profiler

        p = Profiler()
        for dur in (200, 300):
            p._records.append({"kind": "element", "label": "t0",
                               "dur_ns": dur})
        assert p.epilogue_select("f0", ["t0"]) is False
        p._records.append({"kind": "element", "label": "d0",
                           "dur_ns": 50_000})
        assert p.epilogue_select("f0", ["t0", "d0"]) is True

    def test_enable_installs_select_hook(self):
        from nnstreamer_tpu.obs import profile as prof
        from nnstreamer_tpu.ops import epilogue as epi

        prior = epi.EPILOGUE_SELECT_HOOK
        prof.enable()
        try:
            assert epi.EPILOGUE_SELECT_HOOK is not None
            assert epi.EPILOGUE_SELECT_HOOK == prof.profiler().epilogue_select
        finally:
            prof.disable()
        assert epi.EPILOGUE_SELECT_HOOK is None
        assert prior is None or True  # restored to cleared state

    def test_fused_dispatch_label_carries_epilogue_token(self):
        from nnstreamer_tpu.obs import profile as prof

        data = [np.ones((1, 4), np.float32)]
        prof.enable()
        try:
            prof.profiler().reset()
            p = Pipeline()
            src = p.add_new("appsrc", caps=caps_of("4:1", "float32"),
                            data=data)
            f = p.add_new("tensor_filter", model=lambda x: x + 1)
            t = p.add_new("tensor_transform", mode="typecast",
                          option="float32")
            sink = p.add_new("tensor_sink", store=True)
            Pipeline.link(src, f, t, sink)
            p.run(timeout=60)
            assert p._epilogue_count == 1
            labels = [r["label"]
                      for r in prof.profiler().records(kind="dispatch")]
            assert any("+post[" in lb for lb in labels), labels
        finally:
            prof.disable()
