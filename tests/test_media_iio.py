"""Media helper elements + IIO sensor source tests (reference
unittest_src_iio fakes a sysfs tree the same way)."""

import numpy as np
import pytest

from nnstreamer_tpu.graph import Pipeline


class TestImagePath:
    def test_imagefilesrc_pipeline(self, tmp_path):
        from PIL import Image

        for i in range(3):
            arr = np.full((10, 12, 3), i * 40, np.uint8)
            Image.fromarray(arr).save(tmp_path / f"img_{i}.png")
        p = Pipeline()
        src = p.add_new("imagefilesrc", location=str(tmp_path / "*.png"))
        conv = p.add_new("tensor_converter")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, conv, sink)
        p.run(timeout=30)
        assert sink.num_buffers == 3
        assert sink.buffers[1].memories[0].host().shape == (1, 10, 12, 3)
        assert sink.buffers[1].memories[0].host()[0, 0, 0, 0] == 40

    def test_imagedec(self, tmp_path):
        from PIL import Image
        import io

        arr = np.full((6, 8, 3), 99, np.uint8)
        bio = io.BytesIO()
        Image.fromarray(arr).save(bio, format="PNG")
        data = bio.getvalue()
        path = tmp_path / "one.png"
        path.write_bytes(data)
        p = Pipeline()
        src = p.add_new("filesrc", location=str(path), blocksize=1 << 20)
        dec = p.add_new("imagedec")
        conv = p.add_new("tensor_converter")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, dec, conv, sink)
        p.run(timeout=30)
        np.testing.assert_array_equal(sink.buffers[0].memories[0].host()[0], arr)

    def test_videoscale_and_convert(self):
        p = Pipeline()
        src = p.add_new("videotestsrc", width=20, height=10, num_buffers=1)
        scale = p.add_new("videoscale", width=10, height=5)
        conv = p.add_new("videoconvert", format="GRAY8")
        tc = p.add_new("tensor_converter")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, scale, conv, tc, sink)
        p.run(timeout=30)
        assert sink.buffers[0].memories[0].host().shape == (1, 5, 10, 1)


class TestIIO:
    def _fake_device(self, tmp_path, name="accel3d"):
        dev = tmp_path / "iio:device0"
        dev.mkdir()
        (dev / "name").write_text(name + "\n")
        (dev / "in_accel_x_raw").write_text("100\n")
        (dev / "in_accel_y_raw").write_text("-50\n")
        (dev / "in_accel_x_scale").write_text("0.5\n")
        (dev / "in_accel_x_offset").write_text("10\n")
        return tmp_path

    def test_scan_and_convert(self, tmp_path):
        base = self._fake_device(tmp_path)
        p = Pipeline()
        src = p.add_new("tensor_src_iio", base_dir=str(base), device="accel3d",
                        frequency=100, num_buffers=3)
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, sink)
        p.run(timeout=30)
        assert sink.num_buffers == 3
        vals = sink.buffers[0].memories[0].host()
        assert vals.shape == (1, 2)
        assert vals[0, 0] == pytest.approx((100 + 10) * 0.5)  # scale+offset
        assert vals[0, 1] == pytest.approx(-50.0)

    def test_missing_device_fails(self, tmp_path):
        p = Pipeline()
        src = p.add_new("tensor_src_iio", base_dir=str(tmp_path),
                        device="nope", num_buffers=1)
        sink = p.add_new("tensor_sink")
        Pipeline.link(src, sink)
        from nnstreamer_tpu.graph import PipelineError

        with pytest.raises((PipelineError, TimeoutError)):
            p.run(timeout=5)
