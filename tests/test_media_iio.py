"""Media helper elements + IIO sensor source tests (reference
unittest_src_iio fakes a sysfs tree the same way)."""

import numpy as np
import pytest

from nnstreamer_tpu.graph import Pipeline


class TestImagePath:
    def test_imagefilesrc_pipeline(self, tmp_path):
        from PIL import Image

        for i in range(3):
            arr = np.full((10, 12, 3), i * 40, np.uint8)
            Image.fromarray(arr).save(tmp_path / f"img_{i}.png")
        p = Pipeline()
        src = p.add_new("imagefilesrc", location=str(tmp_path / "*.png"))
        conv = p.add_new("tensor_converter")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, conv, sink)
        p.run(timeout=30)
        assert sink.num_buffers == 3
        assert sink.buffers[1].memories[0].host().shape == (1, 10, 12, 3)
        assert sink.buffers[1].memories[0].host()[0, 0, 0, 0] == 40

    def test_imagedec(self, tmp_path):
        from PIL import Image
        import io

        arr = np.full((6, 8, 3), 99, np.uint8)
        bio = io.BytesIO()
        Image.fromarray(arr).save(bio, format="PNG")
        data = bio.getvalue()
        path = tmp_path / "one.png"
        path.write_bytes(data)
        p = Pipeline()
        src = p.add_new("filesrc", location=str(path), blocksize=1 << 20)
        dec = p.add_new("imagedec")
        conv = p.add_new("tensor_converter")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, dec, conv, sink)
        p.run(timeout=30)
        np.testing.assert_array_equal(sink.buffers[0].memories[0].host()[0], arr)

    def test_imagedec_early_embedded_eoi_chunked(self, tmp_path):
        """A JPEG with an embedded-thumbnail-style EOI early in the stream
        (APP1 segment containing \\xff\\xd9) delivered in small chunks:
        the premature marker hit must not kill the pipeline — decode
        retries at the real EOI."""
        from PIL import Image
        import io

        arr = np.full((24, 32, 3), 128, np.uint8)
        bio = io.BytesIO()
        Image.fromarray(arr).save(bio, format="JPEG", quality=95)
        data = bio.getvalue()
        assert data[:2] == b"\xff\xd8"
        # APP1 segment whose payload contains an EOI marker (like an EXIF
        # thumbnail's own terminator)
        payload = b"Exif\x00\x00" + b"\x00" * 10 + b"\xff\xd9" + b"\x00" * 10
        app1 = b"\xff\xe1" + (len(payload) + 2).to_bytes(2, "big") + payload
        path = tmp_path / "thumb.jpg"
        path.write_bytes(data[:2] + app1 + data[2:])
        p = Pipeline()
        src = p.add_new("filesrc", location=str(path), blocksize=16)
        dec = p.add_new("jpegdec")
        conv = p.add_new("tensor_converter")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, dec, conv, sink)
        p.run(timeout=60)
        assert sink.num_buffers == 1
        got = sink.buffers[0].memories[0].host()[0]
        assert got.shape == (24, 32, 3)
        assert abs(int(got.mean()) - 128) < 3  # lossy but close

    def test_imagedec_trailing_padding_after_end_marker(self, tmp_path):
        """Some encoders/cameras append padding after IEND/EOI; the
        completeness heuristic must still decode (marker searched anywhere
        in the stream, not just the tail)."""
        from PIL import Image
        import io

        arr = np.full((6, 8, 3), 50, np.uint8)
        bio = io.BytesIO()
        Image.fromarray(arr).save(bio, format="PNG")
        data = bio.getvalue() + b"\x00" * 300  # padding pushes IEND off the tail
        path = tmp_path / "padded.png"
        path.write_bytes(data)
        p = Pipeline()
        src = p.add_new("filesrc", location=str(path), blocksize=1 << 20)
        dec = p.add_new("imagedec")
        conv = p.add_new("tensor_converter")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, dec, conv, sink)
        p.run(timeout=30)
        assert sink.num_buffers >= 1
        np.testing.assert_array_equal(sink.buffers[0].memories[0].host()[0], arr)

    def test_videoscale_and_convert(self):
        p = Pipeline()
        src = p.add_new("videotestsrc", width=20, height=10, num_buffers=1)
        scale = p.add_new("videoscale", width=10, height=5)
        conv = p.add_new("videoconvert", format="GRAY8")
        tc = p.add_new("tensor_converter")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, scale, conv, tc, sink)
        p.run(timeout=30)
        assert sink.buffers[0].memories[0].host().shape == (1, 5, 10, 1)


class TestIIO:
    def _fake_device(self, tmp_path, name="accel3d"):
        dev = tmp_path / "iio:device0"
        dev.mkdir()
        (dev / "name").write_text(name + "\n")
        (dev / "in_accel_x_raw").write_text("100\n")
        (dev / "in_accel_y_raw").write_text("-50\n")
        (dev / "in_accel_x_scale").write_text("0.5\n")
        (dev / "in_accel_x_offset").write_text("10\n")
        return tmp_path

    def test_scan_and_convert(self, tmp_path):
        base = self._fake_device(tmp_path)
        p = Pipeline()
        src = p.add_new("tensor_src_iio", base_dir=str(base), device="accel3d",
                        frequency=100, num_buffers=3)
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, sink)
        p.run(timeout=30)
        assert sink.num_buffers == 3
        vals = sink.buffers[0].memories[0].host()
        assert vals.shape == (1, 2)
        assert vals[0, 0] == pytest.approx((100 + 10) * 0.5)  # scale+offset
        assert vals[0, 1] == pytest.approx(-50.0)

    def _fake_buffered_device(self, tmp_path, n_scans=4):
        """Fake sysfs tree with scan_elements + packed binary dev node:
        accel_x le:s12/16>>4 (idx 0), accel_y le:u8/8 (idx 1),
        timestamp le:s64/64 (idx 2, 8-byte aligned) → 16-byte scans."""
        import struct

        base = self._fake_device(tmp_path)
        dev = base / "iio:device0"
        scan = dev / "scan_elements"
        scan.mkdir()
        for ch, typ, idx in [("accel_x", "le:s12/16>>4", 0),
                             ("accel_y", "le:u8/8>>0", 1),
                             ("timestamp", "le:s64/64>>0", 2)]:
            (scan / f"in_{ch}_type").write_text(typ + "\n")
            (scan / f"in_{ch}_index").write_text(f"{idx}\n")
            (scan / f"in_{ch}_en").write_text("1\n")
        (dev / "buffer").mkdir()
        (dev / "buffer" / "enable").write_text("0\n")
        (dev / "buffer" / "length").write_text("0\n")
        raw = b""
        for i in range(n_scans):
            x12 = (-5 - i) & 0xFFF        # 12-bit signed, stored <<4
            raw += struct.pack("<H", x12 << 4) + struct.pack("B", 200 + i)
            raw += b"\x00" * 5            # pad to 8-byte ts alignment
            raw += struct.pack("<q", 1000 + i)
        devnode = tmp_path / "devnode.bin"
        devnode.write_bytes(raw)
        return base, devnode

    def test_buffered_capture(self, tmp_path):
        base, devnode = self._fake_buffered_device(tmp_path)
        p = Pipeline()
        src = p.add_new("tensor_src_iio", base_dir=str(base), device="accel3d",
                        mode="buffer", dev_path=str(devnode),
                        frames_per_buffer=2, frequency=100)
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, sink)
        p.run(timeout=30)
        assert sink.num_buffers == 2  # 4 scans / 2 frames-per-buffer
        vals = sink.buffers[0].memories[0].host()
        assert vals.shape == (2, 3)
        # x: (-5 + offset 10) * scale 0.5; y unscaled; ts passthrough
        assert vals[0, 0] == pytest.approx((-5 + 10) * 0.5)
        assert vals[1, 0] == pytest.approx((-6 + 10) * 0.5)
        assert vals[0, 1] == pytest.approx(200.0)
        assert vals[0, 2] == pytest.approx(1000.0)
        # buffer was enabled during capture, disabled on stop
        assert (base / "iio:device0" / "buffer" / "enable").read_text() == "0"

    def test_scan_type_parse_and_layout(self):
        from nnstreamer_tpu.elements.iio import (ScanChannel, parse_scan_type,
                                                 scan_layout)

        assert parse_scan_type("le:s12/16>>4") == (False, True, 12, 16, 4)
        assert parse_scan_type("be:u10/16>>6") == (True, False, 10, 16, 6)
        with pytest.raises(ValueError):
            parse_scan_type("nonsense")
        chans = [ScanChannel("ts", 2, False, True, 64, 64, 0),
                 ScanChannel("x", 0, False, True, 12, 16, 4),
                 ScanChannel("y", 1, False, False, 8, 8, 0)]
        assert scan_layout(chans) == 16
        by_name = {c.name: c for c in chans}
        assert by_name["x"].byte_offset == 0
        assert by_name["y"].byte_offset == 2
        assert by_name["ts"].byte_offset == 8
        # big-endian signed extraction with shift
        ch = ScanChannel("v", 0, True, True, 12, 16, 4)
        assert ch.extract((0xFFB0).to_bytes(2, "big")) == pytest.approx(-5.0)

    def test_missing_device_fails(self, tmp_path):
        p = Pipeline()
        src = p.add_new("tensor_src_iio", base_dir=str(tmp_path),
                        device="nope", num_buffers=1)
        sink = p.add_new("tensor_sink")
        Pipeline.link(src, sink)
        from nnstreamer_tpu.graph import PipelineError

        with pytest.raises((PipelineError, TimeoutError)):
            p.run(timeout=5)
