"""Property-based roundtrip/invariant tests (hypothesis).

The reference leans on exhaustive hand-written gtest cases for its codecs
and parsers; generative testing covers the same ground with adversarial
inputs the hand-written suites miss — every serialization boundary here
must roundtrip losslessly for ANY valid tensor, and every parser must
either parse or raise (never crash or silently mangle).
"""

import numpy as np
import pytest
from hypothesis import given, settings
from hypothesis import strategies as st

from nnstreamer_tpu.core.buffer import Buffer, TensorMemory
from nnstreamer_tpu.core.types import TensorDType, TensorInfo, TensorsConfig, TensorsInfo

DTYPES = ["uint8", "int8", "uint16", "int16", "uint32", "int32",
          "float32", "float64", "int64", "uint64"]


@st.composite
def tensor_arrays(draw, max_rank=4, max_dim=8):
    dtype = draw(st.sampled_from(DTYPES))
    rank = draw(st.integers(1, max_rank))
    shape = tuple(draw(st.integers(1, max_dim)) for _ in range(rank))
    n = int(np.prod(shape))
    if dtype.startswith("float"):
        vals = draw(st.lists(
            st.floats(-1e6, 1e6, allow_nan=False, width=32),
            min_size=n, max_size=n))
        return np.asarray(vals, dtype).reshape(shape)
    info = np.iinfo(dtype)
    vals = draw(st.lists(st.integers(info.min, info.max),
                         min_size=n, max_size=n))
    return np.asarray(vals, dtype).reshape(shape)


class TestFlexMetaRoundtrip:
    @settings(max_examples=40, deadline=None)
    @given(arr=tensor_arrays())
    def test_wrap_unwrap(self, arr):
        from nnstreamer_tpu.core.meta import unwrap_flex, wrap_flex

        info = TensorInfo.from_shape(arr.shape, arr.dtype)
        blob = wrap_flex(arr.tobytes(), info)
        meta, raw = unwrap_flex(blob)
        got = np.frombuffer(raw[:meta.info.size_bytes],
                            arr.dtype).reshape(arr.shape)
        np.testing.assert_array_equal(got, arr)
        assert meta.info.dims == info.dims


class TestSparseRoundtrip:
    @settings(max_examples=30, deadline=None)
    @given(arr=tensor_arrays(max_rank=3), zero_frac=st.floats(0, 1))
    def test_encode_decode(self, arr, zero_frac):
        from nnstreamer_tpu.elements.sparse import sparse_decode, sparse_encode

        mask = np.random.default_rng(0).uniform(size=arr.shape) < zero_frac
        arr = arr.copy()
        arr[mask] = 0
        info = TensorInfo.from_shape(arr.shape, arr.dtype)
        blob = sparse_encode(arr, info)
        back, binfo = sparse_decode(blob)
        np.testing.assert_array_equal(back.reshape(arr.shape), arr)
        assert binfo.dims == info.dims


class TestQueryPayloadRoundtrip:
    @settings(max_examples=25, deadline=None)
    @given(arrs=st.lists(tensor_arrays(max_rank=3, max_dim=6), min_size=1,
                         max_size=4),
           sparse=st.booleans())
    def test_buffer_payload(self, arrs, sparse):
        from nnstreamer_tpu.query.protocol import (
            buffer_to_payload, payload_to_buffer)

        buf = Buffer.of(*arrs, pts=7)
        meta, payload = buffer_to_payload(buf, sparse=sparse)
        out = payload_to_buffer(meta, payload)
        assert out.num_tensors == len(arrs)
        for m, a in zip(out.memories, arrs):
            np.testing.assert_array_equal(m.host().reshape(a.shape), a)


class TestMqttRoundtrips:
    @settings(max_examples=50, deadline=None)
    @given(n=st.integers(0, 268_435_455))
    def test_remaining_length(self, n):
        from nnstreamer_tpu.query import mqtt

        enc = mqtt.encode_remaining_length(n)
        # decode manually (same algorithm the stream parser uses)
        mult, val = 1, 0
        for b in enc:
            val += (b & 0x7F) * mult
            mult *= 128
        assert val == n and len(enc) <= 4

    @settings(max_examples=30, deadline=None)
    @given(topic=st.text(
        alphabet=st.characters(min_codepoint=33, max_codepoint=126),
        min_size=1, max_size=32).filter(lambda t: "#" not in t and "+" not in t),
        payload=st.binary(max_size=2048))
    def test_publish_frame(self, topic, payload):
        from nnstreamer_tpu.query import mqtt

        pkt = mqtt.encode_publish(topic, payload)
        # body offset: fixed header = 1 byte + remaining-length varint
        body_off = 1
        while pkt[body_off] & 0x80:
            body_off += 1
        body_off += 1
        t, p, qos, pid = mqtt.parse_publish(pkt[0] & 0xF, pkt[body_off:])
        assert (t, p, qos) == (topic, payload, 0)

    @settings(max_examples=30, deadline=None)
    @given(num=st.integers(0, 16),
           sizes=st.lists(st.integers(0, 2**40), min_size=0, max_size=16),
           pts=st.one_of(st.none(), st.integers(0, 2**62)),
           caps=st.text(max_size=100).filter(lambda c: "\x00" not in c))
    def test_message_hdr(self, num, sizes, pts, caps):
        from nnstreamer_tpu.query import mqtt

        num = min(num, len(sizes))
        hdr = mqtt.MessageHdr(num_mems=num, size_mems=tuple(sizes[:num]),
                              base_time_epoch=1, sent_time_epoch=2,
                              pts=pts, caps_str=caps)
        back = mqtt.MessageHdr.unpack(hdr.pack())
        assert back.num_mems == num
        assert back.size_mems == tuple(sizes[:num])
        assert back.pts == pts
        # caps travel as a NUL-terminated C string (reference layout);
        # anything under the 511-byte cap survives exactly
        if len(caps.encode()) < 500:
            assert back.caps_str == caps


class TestCapsStringRoundtrip:
    @settings(max_examples=30, deadline=None)
    @given(dims=st.lists(st.lists(st.integers(1, 64), min_size=1, max_size=4),
                         min_size=1, max_size=4),
           types=st.data(),
           rate_n=st.integers(0, 240), rate_d=st.integers(1, 1001))
    def test_tensors_caps(self, dims, types, rate_n, rate_d):
        from fractions import Fraction

        from nnstreamer_tpu.core.types import Caps
        from nnstreamer_tpu.graph.parse import (
            caps_to_gst_string, parse_caps_string)

        dim_s = ",".join(":".join(str(d) for d in t) for t in dims)
        type_s = ",".join(types.draw(st.sampled_from(DTYPES))
                          for _ in dims)
        cfg = TensorsConfig(TensorsInfo.from_strings(dim_s, type_s),
                            Fraction(rate_n, rate_d))
        s = caps_to_gst_string(Caps.tensors(cfg))
        back = parse_caps_string(s).to_config()
        assert back.info.dim_string == dim_s
        assert back.info.type_string == type_s
        assert back.rate == Fraction(rate_n, rate_d)


class TestNmsInvariants:
    @settings(max_examples=30, deadline=None)
    @given(n=st.integers(0, 64), seed=st.integers(0, 2**31))
    def test_nms_output_properties(self, n, seed):
        from nnstreamer_tpu.decoders.util import iou, nms

        rng = np.random.default_rng(seed)
        boxes = np.zeros((n, 6), np.float32)
        if n:
            boxes[:, :2] = rng.uniform(0, 1, (n, 2))
            boxes[:, 2:4] = boxes[:, :2] + rng.uniform(0.01, 0.5, (n, 2))
            boxes[:, 4] = rng.uniform(0, 1, n)
        kept = nms(boxes, 0.5)
        # kept is score-descending
        assert all(kept[i, 4] >= kept[i + 1, 4]
                   for i in range(len(kept) - 1))
        # no two kept boxes overlap above the threshold
        for i in range(len(kept)):
            for j in range(i + 1, len(kept)):
                assert iou(kept[i], kept[j]) <= 0.5 + 1e-6
        # every suppressed box overlaps some higher-scoring kept box
        assert len(kept) <= n
