"""Exhaustive property sweeps for tensor_transform and tensor_if.

Mirrors the reference's unittest_plugins breadth (per-element property
matrices: every typecast dtype pair, every arithmetic op, every dimchg
position pair, every tensor_if operator — gst/nnstreamer/tensor_transform
+ gsttensorif.c), asserted against numpy oracles.
"""

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer
from nnstreamer_tpu.ops.transform_ops import build

_DTYPES = ["uint8", "int8", "uint16", "int16", "uint32", "int32",
           "float32", "float64", "int64", "uint64"]


class TestTypecastSweep:
    @pytest.mark.parametrize("src", _DTYPES)
    @pytest.mark.parametrize("dst", _DTYPES)
    def test_all_dtype_pairs(self, src, dst):
        """Reference SSAT typecast sweep: every (src,dst) tensor_type pair
        must match numpy's astype semantics exactly."""
        rng = np.random.default_rng(hash((src, dst)) % 2**32)
        x = (rng.uniform(0, 100, (3, 4))).astype(src)
        tr = build("typecast", dst)
        got = np.asarray(tr.fn(x))
        np.testing.assert_array_equal(got, x.astype(dst))
        assert got.dtype == np.dtype(dst)


class TestArithmeticSweep:
    @pytest.mark.parametrize("op,expr", [
        ("add:7", lambda x: x + 7),
        ("add:-3.5", lambda x: x + np.float32(-3.5)),
        ("mul:2", lambda x: x * 2),
        ("mul:0.5", lambda x: x * np.float32(0.5)),
        ("div:4", lambda x: x / np.float32(4)),
        ("sub:10", lambda x: x - 10),
    ])
    def test_single_ops_float(self, op, expr):
        x = np.linspace(-5, 5, 12, dtype=np.float32).reshape(3, 4)
        tr = build("arithmetic", f"typecast:float32,{op}")
        np.testing.assert_allclose(np.asarray(tr.fn(x)), expr(x), rtol=1e-6)

    @pytest.mark.parametrize("chain,fn", [
        ("typecast:float32,add:-127.5,div:127.5",
         lambda x: (x.astype(np.float32) - 127.5) / 127.5),
        ("typecast:float32,mul:2,add:1,div:3",
         lambda x: (x.astype(np.float32) * 2 + 1) / 3),
        ("typecast:float64,sub:1,mul:-1",
         lambda x: (x.astype(np.float64) - 1) * -1),
    ])
    def test_chains(self, chain, fn):
        x = np.arange(24, dtype=np.uint8).reshape(2, 3, 4)
        tr = build("arithmetic", chain)
        np.testing.assert_allclose(np.asarray(tr.fn(x)), fn(x), rtol=1e-6)

    def test_per_channel_vector_operands(self):
        # reference arithmetic supports per-channel constant vectors
        x = np.ones((2, 2, 3), np.float32)
        tr = build("arithmetic", "typecast:float32,add:1;2;3")
        got = np.asarray(tr.fn(x))
        np.testing.assert_allclose(got[..., 0], 2)
        np.testing.assert_allclose(got[..., 1], 3)
        np.testing.assert_allclose(got[..., 2], 4)


class TestDimchgSweep:
    @pytest.mark.parametrize("a,b", [(0, 1), (0, 2), (1, 0), (2, 0),
                                     (1, 2), (2, 1)])
    def test_move_positions(self, a, b):
        """dimchg a:b moves reference-dim a to position b (innermost-first
        dim indexing; tensor_transform.h DIMCHG semantics)."""
        x = np.arange(2 * 3 * 4, dtype=np.float32).reshape(4, 3, 2)
        tr = build("dimchg", f"{a}:{b}")
        got = np.asarray(tr.fn(x))
        # oracle: numpy moveaxis in reference dim space (axis = rank-1-idx)
        rank = x.ndim
        na, nb = rank - 1 - a, rank - 1 - b
        np.testing.assert_array_equal(got, np.moveaxis(x, na, nb))

    def test_identity(self):
        x = np.zeros((2, 2), np.float32)
        np.testing.assert_array_equal(np.asarray(build("dimchg", "0:0").fn(x)), x)


class TestTransposeSweep:
    @pytest.mark.parametrize("perm", ["0:1:2", "1:0:2", "2:1:0", "0:2:1",
                                      "2:0:1", "1:2:0"])
    def test_rank3_perms(self, perm):
        x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        tr = build("transpose", perm)
        idx = [int(v) for v in perm.split(":")]
        rank = x.ndim
        np_axes = tuple(rank - 1 - idx[rank - 1 - ax] for ax in range(rank))
        np.testing.assert_array_equal(np.asarray(tr.fn(x)),
                                      np.transpose(x, np_axes))


class TestStandClampSweep:
    def test_stand_default_zero_std(self):
        x = np.full((4, 4), 3.0, np.float32)  # zero variance
        got = np.asarray(build("stand", "default").fn(x))
        assert np.all(np.isfinite(got))

    def test_stand_dc_average(self):
        x = np.arange(8, dtype=np.float32)
        got = np.asarray(build("stand", "dc-average").fn(x))
        np.testing.assert_allclose(got, x - x.mean(), rtol=1e-6)

    @pytest.mark.parametrize("lo,hi", [(0, 1), (-1, 1), (10, 20)])
    def test_clamp_ranges(self, lo, hi):
        x = np.linspace(-50, 50, 21, dtype=np.float32)
        got = np.asarray(build("clamp", f"{lo}:{hi}").fn(x))
        np.testing.assert_allclose(got, np.clip(x, lo, hi))


class TestTensorIfOperatorSweep:
    """All 10 reference operators (gsttensorif.c) through the element."""

    @staticmethod
    def run_if(value: float, operator: str, option: str):
        from fractions import Fraction

        from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
        from nnstreamer_tpu.graph import Pipeline

        p = Pipeline()
        src = p.add_new(
            "appsrc",
            caps=Caps.tensors(TensorsConfig(
                TensorsInfo.from_strings("4:1", "float32"),
                Fraction(30, 1))),
            data=[np.full((1, 4), value, np.float32)])
        cond = p.add_new("tensor_if", compared_value="TENSOR_AVERAGE_VALUE",
                         compared_value_option="0", operator=operator,
                         supplied_value=option, then="PASSTHROUGH",
                         **{"else": "SKIP"})
        then_sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, cond, then_sink)
        p.run(timeout=30)
        return then_sink.num_buffers == 1

    @pytest.mark.parametrize("op,sv,value,expect", [
        ("EQ", "5", 5.0, True), ("EQ", "5", 4.0, False),
        ("NE", "5", 4.0, True), ("NE", "5", 5.0, False),
        ("GT", "5", 6.0, True), ("GT", "5", 5.0, False),
        ("GE", "5", 5.0, True), ("GE", "5", 4.9, False),
        ("LT", "5", 4.0, True), ("LT", "5", 5.0, False),
        ("LE", "5", 5.0, True), ("LE", "5", 5.1, False),
        ("RANGE_INCLUSIVE", "2:8", 2.0, True),
        ("RANGE_INCLUSIVE", "2:8", 9.0, False),
        ("RANGE_EXCLUSIVE", "2:8", 2.0, False),
        ("RANGE_EXCLUSIVE", "2:8", 3.0, True),
        ("NOT_IN_RANGE_INCLUSIVE", "2:8", 9.0, True),
        ("NOT_IN_RANGE_INCLUSIVE", "2:8", 5.0, False),
        ("NOT_IN_RANGE_EXCLUSIVE", "2:8", 2.0, True),
        ("NOT_IN_RANGE_EXCLUSIVE", "2:8", 5.0, False),
    ])
    def test_operator_matrix(self, op, sv, value, expect):
        assert self.run_if(value, op, sv) is expect


class TestMergeSplitAggregatorSweep:
    """Dim sweeps for merge (concat axis modes), split (tensorseg), and
    aggregator (frames_dim) — reference gsttensormerge.h:45-58 linear
    first..fourth, tensor_split tensorseg, tensor_aggregator :178-234."""

    @pytest.mark.parametrize("opt,axis", [
        ("first", 0), ("second", 1), ("third", 2),
    ])
    def test_merge_axes(self, opt, axis):
        from fractions import Fraction

        from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
        from nnstreamer_tpu.graph import Pipeline

        a = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        b = a + 100
        p = Pipeline()
        caps = Caps.tensors(TensorsConfig(
            TensorsInfo.from_strings("4:3:2", "float32"), Fraction(30, 1)))
        s1 = p.add_new("appsrc", caps=caps, data=[a])
        s2 = p.add_new("appsrc", caps=caps, data=[b])
        merge = p.add_new("tensor_merge", mode="linear", option=opt,
                          sync_mode="nosync")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(s1, merge)
        Pipeline.link(s2, merge)
        Pipeline.link(merge, sink)
        p.run(timeout=30)
        got = sink.buffers[0].memories[0].host()
        # reference dim index axis → numpy axis (innermost-first)
        np_axis = a.ndim - 1 - axis
        np.testing.assert_array_equal(got, np.concatenate([a, b], np_axis))

    @pytest.mark.parametrize("seg,nns_axis", [
        ("1,1", 2), ("1,2", 1), ("2,2", 0),
    ])
    def test_split_segments(self, seg, nns_axis):
        from fractions import Fraction

        from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
        from nnstreamer_tpu.graph import Pipeline

        x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
        sizes = [int(v) for v in seg.split(",")]
        p = Pipeline()
        caps = Caps.tensors(TensorsConfig(
            TensorsInfo.from_strings("4:3:2", "float32"), Fraction(30, 1)))
        src = p.add_new("appsrc", caps=caps, data=[x])
        split = p.add_new("tensor_split", tensorseg=seg,
                          option=str(nns_axis))
        sinks = []
        for i in range(len(sizes)):
            s = p.add_new("tensor_sink", store=True)
            sinks.append(s)
            Pipeline.link(split, s)
        Pipeline.link(src, split)
        p.run(timeout=30)
        np_axis = x.ndim - 1 - nns_axis
        off = 0
        for s, size in zip(sinks, sizes):
            got = s.buffers[0].memories[0].host()
            sl = [slice(None)] * x.ndim
            sl[np_axis] = slice(off, off + size)
            np.testing.assert_array_equal(got, x[tuple(sl)])
            off += size

    @pytest.mark.parametrize("frames_dim", [0, 1, 2])
    def test_aggregator_dims(self, frames_dim):
        from fractions import Fraction

        from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
        from nnstreamer_tpu.graph import Pipeline

        frames = [np.full((1, 2, 3), i, np.float32) for i in range(4)]
        p = Pipeline()
        caps = Caps.tensors(TensorsConfig(
            TensorsInfo.from_strings("3:2:1", "float32"), Fraction(30, 1)))
        src = p.add_new("appsrc", caps=caps, data=frames)
        agg = p.add_new("tensor_aggregator", frames_out=2,
                        frames_dim=frames_dim)
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, agg, sink)
        p.run(timeout=30)
        assert sink.num_buffers == 2
        got = sink.buffers[0].memories[0].host()
        np_axis = 3 - 1 - frames_dim
        np.testing.assert_array_equal(
            got, np.concatenate([frames[0], frames[1]], axis=np_axis))
