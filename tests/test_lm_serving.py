"""Continuous-batching LM engine: greedy-exactness vs isolated decode.

Contract (serving/lm_engine.py): every stream's output matches isolated
single-stream generation token-for-token, regardless of batch
composition, admission time, chunk size, or prompt-length bucketing.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import causal_lm
from nnstreamer_tpu.serving import LMEngine, next_pow2_bucket

V, D, H, L, MAXLEN = 97, 32, 4, 2, 64


@pytest.fixture(scope="module")
def params():
    return causal_lm.init_causal_lm(
        jax.random.PRNGKey(7), V, D, H, L, MAXLEN)


def isolated_generate(params, prompt, max_new, eos=None):
    """Single-stream oracle: unpadded prefill + one-at-a-time decode."""
    logits, kc, vc, pos = causal_lm.lm_prefill(
        params, jnp.asarray(np.asarray(prompt, np.int32)[None]),
        H, MAXLEN)
    out = [int(jnp.argmax(logits[0]))]
    while len(out) < max_new and not (eos is not None and out[-1] == eos):
        tok = jnp.asarray([[out[-1]]], jnp.int32)
        logits, kc, vc, pos = causal_lm.lm_decode_step(
            params, tok, kc, vc, pos, H)
        out.append(int(jnp.argmax(logits[0])))
    return out


def prompts_rng(n, lo=1, hi=24, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, V, rng.integers(lo, hi)).astype(np.int32)
            for _ in range(n)]


def test_single_request_matches_isolated(params):
    prompt = prompts_rng(1, lo=5, hi=6)[0]
    eng = LMEngine(params, H, MAXLEN, n_slots=2, chunk=4)
    rid = eng.submit(prompt, max_new=12)
    got = eng.run()[rid]
    assert got == isolated_generate(params, prompt, 12)


def test_more_requests_than_slots_slot_reuse(params):
    prompts = prompts_rng(7, seed=1)
    eng = LMEngine(params, H, MAXLEN, n_slots=3, chunk=4)
    rids = [eng.submit(p, max_new=6 + i % 5) for i, p in enumerate(prompts)]
    res = eng.run()
    assert eng.stats["prefills"] == 7
    for i, (rid, p) in enumerate(zip(rids, prompts)):
        assert res[rid] == isolated_generate(params, p, 6 + i % 5), \
            f"request {i} diverged"


def test_mid_flight_admission(params):
    prompts = prompts_rng(5, seed=2)
    eng = LMEngine(params, H, MAXLEN, n_slots=4, chunk=2)
    rids = [eng.submit(p, max_new=10) for p in prompts[:2]]
    eng.step_iteration()
    eng.step_iteration()
    rids += [eng.submit(p, max_new=10) for p in prompts[2:]]
    res = eng.run()
    for rid, p in zip(rids, prompts):
        assert res[rid] == isolated_generate(params, p, 10)


@pytest.mark.parametrize("chunk", [1, 3, 16])
def test_chunk_size_invariance(params, chunk):
    prompts = prompts_rng(4, seed=3)
    eng = LMEngine(params, H, MAXLEN, n_slots=2, chunk=chunk)
    rids = [eng.submit(p, max_new=9) for p in prompts]
    res = eng.run()
    for rid, p in zip(rids, prompts):
        assert res[rid] == isolated_generate(params, p, 9)


def test_eos_early_stop(params):
    # pick an eos the model actually emits: generate once, then use a
    # token from the middle of that stream as the eos marker
    prompt = prompts_rng(1, lo=8, hi=9, seed=4)[0]
    ref_free = isolated_generate(params, prompt, 20)
    eos = ref_free[len(ref_free) // 2]
    ref = isolated_generate(params, prompt, 20, eos=eos)
    assert ref[-1] == eos and len(ref) < 20
    eng = LMEngine(params, H, MAXLEN, n_slots=2, chunk=4)
    rid = eng.submit(prompt, max_new=20, eos=eos)
    filler = prompts_rng(1, seed=5)[0]
    rid2 = eng.submit(filler, max_new=20)
    res = eng.run()
    assert res[rid] == ref
    assert res[rid2] == isolated_generate(params, filler, 20)
    # capacity invariant even with a mid-chunk eos: every slot-step
    # either produced a kept token or is counted as waste
    st = eng.stats
    assert eng.n_slots * st["decode_steps"] == \
        (st["tokens_out"] - st["prefills"]) + st["wasted_slot_steps"]


def test_max_new_one_retires_at_admission(params):
    prompt = prompts_rng(1, seed=6)[0]
    eng = LMEngine(params, H, MAXLEN, n_slots=1, chunk=4)
    rid = eng.submit(prompt, max_new=1)
    res = eng.run()
    assert res[rid] == isolated_generate(params, prompt, 1)
    assert eng.stats["decode_steps"] == 0


def test_capacity_boundary(params):
    # prompt + max_new - 1 == max_len exactly fills the cache
    t = MAXLEN - 8
    prompt = prompts_rng(1, lo=t, hi=t + 1, seed=7)[0]
    eng = LMEngine(params, H, MAXLEN, n_slots=1, chunk=16)
    rid = eng.submit(prompt, max_new=9)
    got = eng.run()[rid]
    ref = isolated_generate(params, prompt, 9)
    assert got == ref and not any(np.isnan(got))


def test_submit_rejections(params):
    eng = LMEngine(params, H, MAXLEN, n_slots=1)
    with pytest.raises(ValueError, match="capacity"):
        eng.submit(np.zeros(MAXLEN, np.int32), max_new=2)
    with pytest.raises(ValueError, match="empty"):
        eng.submit([], max_new=2)
    with pytest.raises(ValueError, match="max_new"):
        eng.submit([1, 2], max_new=0)


def test_bucketing_is_exact_and_bounded(params):
    # distinct prompt lengths land in few buckets: prefill compiles are
    # bounded by the bucket count, and results stay exact
    assert next_pow2_bucket(1) == 16 and next_pow2_bucket(17) == 32
    prompts = [np.arange(1, n + 1, dtype=np.int32) % V
               for n in (1, 3, 15, 16, 17, 31, 33)]
    eng = LMEngine(params, H, MAXLEN, n_slots=2, chunk=4)
    rids = [eng.submit(p, max_new=5) for p in prompts]
    res = eng.run()
    for rid, p in zip(rids, prompts):
        assert res[rid] == isolated_generate(params, p, 5)


def test_masked_prefill_matches_unpadded(params):
    prompt = prompts_rng(1, lo=11, hi=12, seed=8)[0]
    t = prompt.size
    lg_ref, kc_ref, vc_ref, pos_ref = causal_lm.lm_prefill(
        params, jnp.asarray(prompt[None]), H, MAXLEN)
    padded = np.zeros((1, 16), np.int32)
    padded[0, :t] = prompt
    lg, kc, vc, pos = causal_lm.lm_prefill_masked(
        params, jnp.asarray(padded), jnp.int32(t), H, MAXLEN)
    assert int(pos[0]) == int(pos_ref[0]) == t
    np.testing.assert_allclose(np.asarray(lg), np.asarray(lg_ref),
                               rtol=1e-5, atol=1e-6)
    # cache rows BELOW true_len must match; rows past it are garbage by
    # contract (overwritten before visible)
    np.testing.assert_allclose(np.asarray(kc[:, :t]),
                               np.asarray(kc_ref[:, :t]),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(vc[:, :t]),
                               np.asarray(vc_ref[:, :t]),
                               rtol=1e-5, atol=1e-6)


def test_slot_step_matches_single_stream(params):
    # lm_decode_step_slots == stacked single-stream lm_decode_step
    rng = np.random.default_rng(9)
    S = 3
    states = []
    for s in range(S):
        prompt = rng.integers(0, V, 4 + 3 * s).astype(np.int32)
        lg, kc, vc, pos = causal_lm.lm_prefill(
            params, jnp.asarray(prompt[None]), H, MAXLEN)
        tok = jnp.argmax(lg, -1).astype(jnp.int32)[:, None]
        states.append((tok, kc, vc, pos))
    toks = jnp.stack([s[0] for s in states])
    kcs = jnp.stack([s[1] for s in states])
    vcs = jnp.stack([s[2] for s in states])
    poss = jnp.stack([s[3] for s in states])
    lg_b, kcs2, vcs2, poss2 = causal_lm.lm_decode_step_slots(
        params, toks, kcs, vcs, poss, H)
    for s, (tok, kc, vc, pos) in enumerate(states):
        lg1, kc1, vc1, pos1 = causal_lm.lm_decode_step(
            params, tok, kc, vc, pos, H)
        np.testing.assert_allclose(np.asarray(lg_b[s]), np.asarray(lg1),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(kcs2[s]), np.asarray(kc1),
                                   rtol=1e-5, atol=1e-6)
        assert int(poss2[s, 0]) == int(pos1[0])


def test_engine_exact_under_env_flash_flag(params, monkeypatch):
    # NNS_LM_FLASH=1 must not reroute the masked prefill onto the flash
    # path (which cannot column-mask a padded prompt): admission forces
    # dense and results stay exact
    monkeypatch.setenv("NNS_LM_FLASH", "1")
    prompt = prompts_rng(1, lo=6, hi=7, seed=12)[0]
    eng = LMEngine(params, H, MAXLEN, n_slots=2, chunk=4)
    rid = eng.submit(prompt, max_new=8)
    got = eng.run()[rid]
    monkeypatch.delenv("NNS_LM_FLASH")
    assert got == isolated_generate(params, prompt, 8)


def test_nonpow2_chunk_kept_at_steady_state(params):
    # chunk=6 is not a power of two: full-size chunks must run 6 steps
    # (only TAIL chunks floor to pow2 for executable-cache bounding)
    prompt = prompts_rng(1, lo=4, hi=5, seed=13)[0]
    eng = LMEngine(params, H, MAXLEN, n_slots=1, chunk=6)
    rid = eng.submit(prompt, max_new=14)  # 1 prefill + 13 decode
    got = eng.run()[rid]
    assert got == isolated_generate(params, prompt, 14)
    # 13 remaining -> chunks of 6, 6, then tail 1 (pow2): 3 iterations
    assert eng.stats["decode_steps"] == 13


def test_host_pos_mirror_tracks_device(params):
    prompts = prompts_rng(3, seed=14)
    eng = LMEngine(params, H, MAXLEN, n_slots=2, chunk=4)
    for p in prompts:
        eng.submit(p, max_new=7)
    eng.run()
    np.testing.assert_array_equal(
        np.asarray(eng._pos)[:, 0], np.asarray(eng._pos_host))


def test_gang_mode_static_batching_exact(params):
    # gang=True (the static-batch baseline) admits only into an all-free
    # engine; results stay exact, but later requests wait for the whole
    # first gang, so more decode steps run than in continuous mode
    prompts = prompts_rng(5, seed=11)
    lens = [4, 16, 4, 16, 4]
    cont = LMEngine(params, H, MAXLEN, n_slots=2, chunk=2)
    gang = LMEngine(params, H, MAXLEN, n_slots=2, chunk=2, gang=True)
    rc = [cont.submit(p, max_new=n) for p, n in zip(prompts, lens)]
    rg = [gang.submit(p, max_new=n) for p, n in zip(prompts, lens)]
    res_c, res_g = cont.run(), gang.run()
    for rid_c, rid_g, p, n in zip(rc, rg, prompts, lens):
        ref = isolated_generate(params, p, n)
        assert res_c[rid_c] == ref and res_g[rid_g] == ref
    assert gang.stats["decode_steps"] >= cont.stats["decode_steps"]


def test_paged_kv_same_tokens_as_contiguous(params):
    # the paged cache's engine-level exactness suite is
    # tests/test_kv_paging.py; this pins the serving contract from THIS
    # file's angle — kv_page_size is a scheduling knob, not a numerics
    # knob: same workload, same tokens, bit for bit
    prompts = prompts_rng(4, seed=15)
    cont = LMEngine(params, H, MAXLEN, n_slots=2, chunk=4)
    paged = LMEngine(params, H, MAXLEN, n_slots=2, chunk=4, kv_page_size=8)
    rc = [cont.submit(p, max_new=8) for p in prompts]
    rp = [paged.submit(p, max_new=8) for p in prompts]
    res_c, res_p = cont.run(), paged.run()
    for rid_c, rid_p, p in zip(rc, rp, prompts):
        assert res_p[rid_p] == res_c[rid_c] == isolated_generate(params, p, 8)
    assert paged.kv_stats is not None and cont.kv_stats is None


def test_stats_account_for_waste(params):
    prompts = prompts_rng(2, seed=10)
    eng = LMEngine(params, H, MAXLEN, n_slots=4, chunk=4)
    rids = [eng.submit(p, max_new=3 + 5 * i) for i, p in enumerate(prompts)]
    res = eng.run()
    for rid, p, n in zip(rids, prompts, (3, 8)):
        assert res[rid] == isolated_generate(params, p, n)
    st = eng.stats
    assert st["prefills"] == 2
    assert st["tokens_out"] == 3 + 8
    # 2 empty slots ride every chunk; the short request wastes steps too
    assert st["wasted_slot_steps"] > 0
    assert st["slot_steps"] >= st["tokens_out"] - st["prefills"]
    assert eng.n_slots * st["decode_steps"] == \
        (st["tokens_out"] - st["prefills"]) + st["wasted_slot_steps"]
