"""IIO scan-element spec/extraction sweep.

Reference: per-channel typed scan conversion in tensor_src_iio.c:104-136
and the unittest_src_iio fixture matrix (endianness × sign × bits ×
shift). Pins the bit-exact extraction math and the kernel buffer layout
(natural alignment) rule.
"""

import struct

import numpy as np
import pytest

from nnstreamer_tpu.elements.iio import (
    ScanChannel,
    parse_scan_type,
    scan_layout,
)


@pytest.mark.parametrize("spec,want", [
    ("le:s12/16>>4", (False, True, 12, 16, 4)),
    ("be:u10/16>>6", (True, False, 10, 16, 6)),
    ("le:u8/8", (False, False, 8, 8, 0)),
    ("be:s32/32>>0", (True, True, 32, 32, 0)),
    ("le:s64/64", (False, True, 64, 64, 0)),
])
def test_parse_scan_type(spec, want):
    assert parse_scan_type(spec) == want


@pytest.mark.parametrize("bad", ["", "xx:s12/16", "le:q12/16", "le:s12",
                                 "s12/16>>4", "le:s12/16>>"])
def test_parse_rejects_malformed(bad):
    with pytest.raises(ValueError):
        parse_scan_type(bad)


def _ch(**kw):
    base = dict(name="c", index=0, big_endian=False, signed=True,
                bits=12, storage_bits=16, shift=4)
    base.update(kw)
    return ScanChannel(**base)


class TestExtract:
    def test_le_signed_shifted(self):
        # 12-bit value -5 stored in the high bits of a 16-bit LE word
        raw = struct.pack("<H", ((-5) & 0xFFF) << 4)
        assert _ch().extract(raw) == -5.0

    def test_be_unsigned(self):
        ch = _ch(big_endian=True, signed=False, bits=10, shift=6)
        raw = struct.pack(">H", 700 << 6)
        assert ch.extract(raw) == 700.0

    def test_sign_extension_boundaries(self):
        ch = _ch(shift=0, bits=16, storage_bits=16)
        assert ch.extract(struct.pack("<h", -32768)) == -32768.0
        assert ch.extract(struct.pack("<h", 32767)) == 32767.0

    def test_scale_and_offset_applied(self):
        ch = _ch(shift=0, bits=16, storage_bits=16, scale=0.5, offset=10.0)
        assert ch.extract(struct.pack("<h", 4)) == (4 + 10.0) * 0.5

    def test_garbage_outside_field_masked(self):
        # bits above the 12-bit field (after shift) must be ignored
        ch = _ch(shift=0, bits=12, storage_bits=16, signed=False)
        raw = struct.pack("<H", 0xF000 | 0x0ABC)
        assert ch.extract(raw) == 0x0ABC


class TestLayout:
    def test_natural_alignment_with_padding(self):
        chans = [
            ScanChannel("a", 0, False, False, 8, 8, 0),     # 1 byte @0
            ScanChannel("b", 1, False, True, 16, 16, 0),    # align 2 → @2
            ScanChannel("c", 2, False, True, 32, 32, 0),    # align 4 → @4
        ]
        total = scan_layout(chans)
        assert [c.byte_offset for c in chans] == [0, 2, 4]
        assert total == 8  # padded to the largest storage size

    def test_index_order_not_list_order(self):
        chans = [
            ScanChannel("second", 1, False, False, 16, 16, 0),
            ScanChannel("first", 0, False, False, 16, 16, 0),
        ]
        scan_layout(chans)
        first = next(c for c in chans if c.name == "first")
        second = next(c for c in chans if c.name == "second")
        assert first.byte_offset == 0 and second.byte_offset == 2

    def test_roundtrip_through_packed_scan(self):
        chans = [
            ScanChannel("a", 0, False, True, 12, 16, 4),
            ScanChannel("b", 1, True, False, 10, 16, 6),
            ScanChannel("c", 2, False, True, 32, 32, 0),
        ]
        total = scan_layout(chans)
        buf = bytearray(total)
        buf[0:2] = struct.pack("<H", ((-100) & 0xFFF) << 4)
        buf[2:4] = struct.pack(">H", 513 << 6)
        buf[4:8] = struct.pack("<i", -123456)
        vals = [c.extract(bytes(buf)) for c in chans]
        assert vals == [-100.0, 513.0, -123456.0]
