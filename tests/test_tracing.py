"""obs.tracing: span store semantics (tail retention, trees, sampling),
cross-wire context propagation through the query protocol (ISSUE
satellite: one client→server round trip — including a >CHUNK_SIZE
chunked payload — yields ONE trace whose server-side spans parent onto
the client span, and the disabled path adds no ``trace`` key to wire
meta), serving-engine spans, the ``/debug/*`` exposition endpoints,
and the PipelineTracer report ordering satellite."""

import json
import socket
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline
from nnstreamer_tpu.models import causal_lm
from nnstreamer_tpu.obs import tracing
from nnstreamer_tpu.obs.exporter import start_exporter
from nnstreamer_tpu.obs.tracing import (NOOP_SPAN, SpanStore, ctx_from_wire)
from nnstreamer_tpu.query.protocol import (Cmd, recv_message, send_message)
from nnstreamer_tpu.serving import LMEngine


@pytest.fixture
def tracing_on():
    was = tracing.enabled()
    tracing.store().reset()
    tracing.enable()
    yield tracing.store()
    (tracing.enable if was else tracing.disable)()
    tracing.store().sample_every = 1
    tracing.store().reset()


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# --------------------------------------------------------------------------- #
# SpanStore unit semantics
# --------------------------------------------------------------------------- #

class TestSpanStore:
    def test_disabled_store_returns_shared_noop(self):
        store = SpanStore(enabled=False)
        s = store.start_span("pipeline.buffer")
        assert s is NOOP_SPAN and s.context is None and not s.recording
        s.set_attribute("k", 1)
        s.end()  # all no-ops
        assert store.summaries() == []

    def test_tree_nests_children_under_local_parents(self):
        store = SpanStore(enabled=True)
        root = store.start_span("pipeline.buffer", attrs={"source": "src"})
        child = store.start_span("pipeline.element", parent=root.context,
                                 attrs={"element": "conv"})
        grand = store.start_span("query.request", parent=child.context)
        grand.end()
        child.end()
        root.end()
        tid = root.context.trace_id
        assert child.context.trace_id == tid
        tree = store.tree(tid)
        assert tree["spans"] == 3
        (r,) = tree["tree"]
        assert r["name"] == "pipeline.buffer" and r["parent_id"] is None
        (c,) = r["children"]
        assert c["name"] == "pipeline.element"
        assert c["children"][0]["name"] == "query.request"
        # summaries: one completed trace rooted at pipeline.buffer
        (summ,) = store.summaries()
        assert summ["trace_id"] == tid and summ["completed"]
        assert summ["root"] == "pipeline.buffer"

    def test_remote_parented_spans_surface_as_tree_roots(self):
        store = SpanStore(enabled=True)
        remote = ctx_from_wire({"tid": "aa" * 8, "sid": "bb" * 8})
        s = store.start_span("query.server_handle", parent=remote)
        s.end()
        tree = store.tree("aa" * 8)
        assert tree["tree"][0]["name"] == "query.server_handle"
        assert tree["tree"][0]["parent_id"] == "bb" * 8
        # remote-parented halves never complete locally
        assert store.summaries()[0]["completed"] is False

    def test_min_ms_filter_keeps_only_slow_completed(self):
        store = SpanStore(enabled=True)
        slow = store.start_span("query.request")
        slow.start_ns -= int(50e6)  # pretend it started 50 ms ago
        slow.end()
        fast = store.start_span("query.request")
        fast.end()
        all_traces = store.summaries()
        assert len(all_traces) == 2
        slow_only = store.summaries(min_ms=25.0)
        assert [t["trace_id"] for t in slow_only] == \
            [slow.context.trace_id]

    def test_head_sampling_admits_one_in_n(self):
        store = SpanStore(enabled=True, sample_every=4)
        admitted = sum(store.should_sample() for _ in range(40))
        assert admitted == 10

    def test_slowest_retention_survives_wraparound_concurrent(self):
        """Acceptance criterion: slowest-N retention survives ring
        wraparound under concurrent span recording."""
        store = SpanStore(max_traces=32, keep_slowest=4, enabled=True)
        slow_ids = []
        for i in range(4):
            s = store.start_span("query.request", attrs={"i": i})
            s.start_ns -= int((i + 1) * 1e9)  # 1..4 s — the tail
            s.end()
            slow_ids.append(s.context.trace_id)

        def hammer(n):
            for _ in range(n):
                s = store.start_span("pipeline.buffer")
                store.start_span("pipeline.element",
                                 parent=s.context).end()
                s.end()

        threads = [threading.Thread(target=hammer, args=(200,))
                   for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # 1600 fast traces flooded a 32-slot ring; the 4 slow ones must
        # still be retrievable, marked retained, and ranked slowest-first
        summ = store.summaries()
        assert len(summ) <= store.max_traces + store.keep_slowest
        kept = {t["trace_id"] for t in summ}
        assert set(slow_ids) <= kept
        assert [t["trace_id"] for t in summ[:4]] == slow_ids[::-1]
        assert all(t["slowest_retained"] for t in summ[:4])
        for tid in slow_ids:
            assert store.tree(tid) is not None

    def test_span_context_manager_sets_current_and_flags_error(self):
        store = SpanStore(enabled=True)
        assert tracing.current_context() is None
        with pytest.raises(RuntimeError):
            with store.start_span("serving.request") as s:
                assert tracing.current_context() is s.context
                raise RuntimeError("boom")
        assert tracing.current_context() is None
        assert s.attrs.get("error") is True
        assert s.end_ns is not None


# --------------------------------------------------------------------------- #
# Wire-level propagation (protocol only)
# --------------------------------------------------------------------------- #

class TestWireMeta:
    @staticmethod
    def _pipe():
        return socket.socketpair()

    def test_disabled_adds_no_trace_key(self):
        assert not tracing.enabled()  # suite default
        a, b = self._pipe()
        send_message(a, Cmd.DATA, {"k": 1}, b"x")
        cmd, meta, payload = recv_message(b)
        assert meta == {"k": 1}  # bit-identical meta: no added wire bytes
        a.close(); b.close()

    def test_enabled_without_current_context_adds_no_key(self, tracing_on):
        a, b = self._pipe()
        send_message(a, Cmd.DATA, {"k": 1}, b"x")
        _, meta, _ = recv_message(b)
        assert "trace" not in meta
        a.close(); b.close()

    def test_current_context_rides_wire_and_parses(self, tracing_on):
        a, b = self._pipe()
        with tracing.start_span("query.request") as span:
            send_message(a, Cmd.DATA, {"k": 1}, b"x")
        _, meta, _ = recv_message(b)
        ctx = ctx_from_wire(meta["trace"])
        assert ctx.trace_id == span.context.trace_id
        assert ctx.span_id == span.context.span_id
        a.close(); b.close()

    def test_chunked_transfer_carries_context(self, tracing_on):
        from nnstreamer_tpu.query.protocol import CHUNK_SIZE

        a, b = self._pipe()
        payload = b"\x5a" * (CHUNK_SIZE + 100)
        result = {}

        def rx():
            result["msg"] = recv_message(b)

        t = threading.Thread(target=rx, daemon=True)
        t.start()
        # send on the span's own thread — contextvars are thread-local,
        # exactly as in the client element's chain call
        with tracing.start_span("query.request") as span:
            send_message(a, Cmd.DATA, {"k": 2}, payload)
        t.join(10)
        cmd, meta, got = result["msg"]
        assert cmd is Cmd.DATA and got == payload
        assert ctx_from_wire(meta["trace"]).trace_id == \
            span.context.trace_id
        # the chunked receive recorded a query.recv span in the trace
        names = [s.name for s in
                 tracing.store().spans_of(span.context.trace_id)]
        assert "query.recv" in names
        a.close(); b.close()


# --------------------------------------------------------------------------- #
# End-to-end: client pipeline → wire → server pipeline → back
# --------------------------------------------------------------------------- #

def _roundtrip(dims, n_bufs, payload_elems):
    port = free_port()
    sp = Pipeline("server")
    ssrc = sp.add_new("tensor_query_serversrc", host="127.0.0.1",
                      port=port, id=0, dims=dims, types="float32")
    filt = sp.add_new("tensor_filter", model=lambda x: x * 2)
    ssink = sp.add_new("tensor_query_serversink", id=0)
    Pipeline.link(ssrc, filt, ssink)
    sp.start()
    try:
        time.sleep(0.2)
        caps = Caps.tensors(TensorsConfig(
            TensorsInfo.from_strings(dims, "float32"), 30))
        cp = Pipeline("client")
        src = cp.add_new(
            "appsrc", caps=caps,
            data=[np.full((1, payload_elems), i, np.float32)
                  for i in range(n_bufs)])
        qc = cp.add_new("tensor_query_client", host="127.0.0.1", port=port)
        sink = cp.add_new("tensor_sink", store=True)
        Pipeline.link(src, qc, sink)
        cp.run(timeout=120)
        assert sink.num_buffers == n_bufs
    finally:
        sp.stop()
    return sp, cp


class TestCrossWirePropagation:
    def test_roundtrip_yields_single_trace_with_server_spans(
            self, tracing_on):
        _roundtrip("4:1", 3, 4)
        completed = [t for t in tracing_on.summaries() if t["completed"]]
        assert len(completed) == 3  # one trace per source buffer
        for t in completed:
            assert t["root"] == "pipeline.buffer"
            spans = tracing_on.spans_of(t["trace_id"])
            by_name = {}
            for s in spans:
                by_name.setdefault(s.name, []).append(s)
            # client side: root, element chains, the offload request +
            # sends in both directions; server side: the adopted handler
            # and its pipeline elements — ONE trace id spans it all
            for name in ("pipeline.buffer", "pipeline.element",
                         "query.request", "query.send",
                         "query.server_handle"):
                assert name in by_name, f"missing {name}: {by_name.keys()}"
            (req,) = by_name["query.request"]
            (srv,) = by_name["query.server_handle"]
            # the server span parents onto the CLIENT request span
            assert srv.context.parent_id == req.context.span_id
            assert srv.context.trace_id == req.context.trace_id
            # server pipeline elements hang below the handler span
            # (auto-numbered names: tensor_filter<N> etc.)
            elements = {str(s.attrs.get("element")) for s in
                        by_name["pipeline.element"]}
            assert any(e.startswith("tensor_filter") for e in elements)
            assert any(e.startswith("tensor_query_serversink")
                       for e in elements)
            # full tree is rooted once (everything reachable from the
            # client root — nothing floats)
            tree = tracing_on.tree(t["trace_id"])
            assert len(tree["tree"]) == 1
            assert tree["tree"][0]["name"] == "pipeline.buffer"

    def test_chunked_roundtrip_is_one_trace(self, tracing_on):
        from nnstreamer_tpu.query.protocol import CHUNK_SIZE

        elems = CHUNK_SIZE // 4  # 1 MiB of float32 + flex header → chunked
        _roundtrip(f"{elems}:1", 1, elems)
        completed = [t for t in tracing_on.summaries() if t["completed"]]
        assert len(completed) == 1
        names = {s.name for s in
                 tracing_on.spans_of(completed[0]["trace_id"])}
        # chunked assembly records query.recv on BOTH halves, still in
        # the same single trace
        assert {"pipeline.buffer", "query.request", "query.recv",
                "query.server_handle"} <= names

    def test_disabled_roundtrip_records_nothing(self):
        assert not tracing.enabled()
        tracing.store().reset()
        _roundtrip("4:1", 2, 4)
        assert tracing.store().summaries() == []


# --------------------------------------------------------------------------- #
# Serving engine spans
# --------------------------------------------------------------------------- #

V, D, H, L, MAXLEN = 32, 16, 2, 1, 32


def _engine():
    params = causal_lm.init_causal_lm(
        jax.random.PRNGKey(0), V, D, H, L, MAXLEN)
    return LMEngine(params, H, MAXLEN, n_slots=2, chunk=4)


class TestServingSpans:
    def test_request_span_tree_covers_lifecycle(self, tracing_on):
        eng = _engine()
        eng.submit(np.arange(1, 5, dtype=np.int32), max_new=4)
        eng.run()
        completed = [t for t in tracing_on.summaries() if t["completed"]]
        assert len(completed) == 1
        assert completed[0]["root"] == "serving.request"
        tree = tracing_on.tree(completed[0]["trace_id"])
        (root,) = tree["tree"]
        child_names = {c["name"] for c in root["children"]}
        # first-ever bucket use also records the compile span
        assert {"serving.admission_wait", "serving.prefill",
                "serving.compile", "serving.decode"} <= child_names
        assert root["attrs"]["tokens"] == 4

    def test_submit_joins_callers_current_trace(self, tracing_on):
        eng = _engine()
        with tracing.start_span("query.request") as outer:
            eng.submit(np.arange(1, 5, dtype=np.int32), max_new=2)
        eng.run()
        spans = tracing_on.spans_of(outer.context.trace_id)
        req = [s for s in spans if s.name == "serving.request"]
        assert len(req) == 1
        assert req[0].context.parent_id == outer.context.span_id

    def test_disabled_requests_carry_no_spans(self):
        assert not tracing.enabled()
        eng = _engine()
        eng.submit(np.arange(1, 5, dtype=np.int32), max_new=2)
        req = eng._queue[0]
        assert req.span is None and req.wait_span is None
        eng.run()
        assert tracing.store().summaries() == []


# --------------------------------------------------------------------------- #
# /debug exposition endpoints
# --------------------------------------------------------------------------- #

def _get_json(url):
    return json.loads(urllib.request.urlopen(url, timeout=10).read())


class TestDebugEndpoints:
    def test_traces_tree_and_pipeline_endpoints(self, tracing_on):
        sp, cp = _roundtrip("4:1", 2, 4)
        with start_exporter(port=0, enable=False) as exp:
            base = f"http://{exp.host}:{exp.port}"
            listing = _get_json(f"{base}/debug/traces")
            assert listing["tracing_enabled"] is True
            traces = listing["traces"]
            assert len([t for t in traces if t["completed"]]) == 2
            tid = traces[0]["trace_id"]
            tree = _get_json(f"{base}/debug/traces/{tid}")
            assert tree["trace_id"] == tid and tree["spans"] > 0
            names = set()

            def walk(nodes):
                for n in nodes:
                    names.add(n["name"])
                    walk(n["children"])

            walk(tree["tree"])
            assert "query.request" in names
            # min_ms high-pass filters everything out
            empty = _get_json(f"{base}/debug/traces?min_ms=1e9")
            assert empty["traces"] == []
            # unknown id → 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get_json(f"{base}/debug/traces/nope")
            assert ei.value.code == 404
            # live topology + per-element span stats (pipelines held
            # alive by the locals above — the registry is a WeakSet)
            dbg = _get_json(f"{base}/debug/pipeline")
            pipe_names = {p["name"] for p in dbg["pipelines"]}
            assert {"server", "client"} <= pipe_names
            client = next(p for p in dbg["pipelines"]
                          if p["name"] == "client")
            kinds = {e["kind"] for e in client["elements"]}
            assert "tensor_query_client" in kinds
            assert any(e["links"] for e in client["elements"])
            assert dbg["element_spans"]  # per-element span aggregates
            for st in dbg["element_spans"].values():
                assert st["n"] >= 1 and st["max_us"] >= st["mean_us"] >= 0

    def test_bad_min_ms_is_400(self, tracing_on):
        with start_exporter(port=0, enable=False) as exp:
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get_json(f"http://{exp.host}:{exp.port}"
                          "/debug/traces?min_ms=abc")
            assert ei.value.code == 400


# --------------------------------------------------------------------------- #
# PipelineTracer consumers (report-ordering satellite + span store)
# --------------------------------------------------------------------------- #

class TestPipelineTracer:
    @staticmethod
    def _traced_run(spans=False):
        from nnstreamer_tpu.utils.trace import PipelineTracer

        p = Pipeline()
        src = p.add_new("videotestsrc", width=8, height=8, num_buffers=3)
        conv = p.add_new("tensor_converter")
        slow = p.add_new("tensor_filter",
                         model=lambda x: (time.sleep(0.01), x)[1])
        sink = p.add_new("tensor_sink")
        Pipeline.link(src, conv, slow, sink)
        tracer = PipelineTracer.attach(p, spans=spans)
        p.run(timeout=60)
        return tracer

    def test_report_rows_sorted_by_mean_proctime_desc(self):
        tracer = self._traced_run()
        lines = tracer.report().splitlines()
        assert len(lines) >= 4  # header + 3 non-source elements
        proctimes = [float(ln.split()[2]) for ln in lines[1:]]
        assert proctimes == sorted(proctimes, reverse=True)
        # chain proctime is inclusive of downstream pushes, so the slow
        # filter must rank above the sink it feeds (and its own mean
        # must carry the deliberate 10 ms sleep)
        names = [ln.split()[0] for ln in lines[1:]]
        filt = next(i for i, n in enumerate(names)
                    if n.startswith("tensor_filter"))
        sink = next(i for i, n in enumerate(names)
                    if n.startswith("tensor_sink"))
        assert filt < sink
        # the mean must carry the sleep (well above a no-op chain call)
        assert proctimes[filt] >= 1_000  # us

    def test_span_consumer_uses_private_store(self):
        assert not tracing.enabled()
        tracer = self._traced_run(spans=True)
        report = tracer.span_report()
        assert "tensor_filter" in report
        stats = tracer.span_store.element_stats()
        assert any(k.startswith("tensor_filter") for k in stats)
        # private means private: the global store saw nothing
        assert tracing.store().summaries() == []

    def test_span_report_requires_spans_attach(self):
        tracer = self._traced_run(spans=False)
        with pytest.raises(RuntimeError, match="spans=True"):
            tracer.span_report()


# --------------------------------------------------------------------------- #
# device_trace ↔ trace-id join
# --------------------------------------------------------------------------- #

def test_device_trace_links_xprof_to_trace(tmp_path, tracing_on,
                                           monkeypatch):
    from nnstreamer_tpu.utils import trace as utrace

    calls = {}
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda logdir: calls.setdefault("start", logdir))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.setdefault("stop", True))
    with tracing.start_span("query.request") as outer:
        with utrace.device_trace(str(tmp_path)) as dt:
            pass
    assert calls == {"start": str(tmp_path), "stop": True}
    assert dt.trace_id == outer.context.trace_id
    spans = tracing_on.spans_of(outer.context.trace_id)
    dev = [s for s in spans if s.name == "device.xprof"]
    assert len(dev) == 1
    assert dev[0].attrs["logdir"] == str(tmp_path)
    assert dev[0].context.parent_id == outer.context.span_id
