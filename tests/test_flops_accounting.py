"""FLOPs accounting: analytic closed forms vs XLA cost_analysis.

Pins the empirical premise behind `models/causal_lm.prefill_flops` /
`decode_flops` (and every transformer MFU row in bench.py): XLA's
compiled ``cost_analysis()`` counts a ``lax.scan`` body ONCE regardless
of trip count, so layer-scanned models undercount by ~L. If a jax
upgrade changes that accounting, the L-invariance test here fails and
the analytic forms should be re-validated against the new meaning.
"""

import numpy as np
import pytest

import jax

from nnstreamer_tpu.models import causal_lm
from nnstreamer_tpu.utils import probes

V, D, H, T, B = 512, 128, 4, 128, 2


def _cost_flops(n_layers):
    params = causal_lm.init_causal_lm(
        jax.random.PRNGKey(0), V, D, H, n_layers, T)
    toks = np.zeros((B, T), np.int32)

    def fn(t):
        return causal_lm._lm_prefill(params, t, H, T, flash=False)[0]

    return probes.model_flops(fn, toks)


@pytest.fixture(scope="module")
def cost_by_layers():
    got = {L: _cost_flops(L) for L in (1, 2, 4)}
    if any(v is None for v in got.values()):
        pytest.skip("backend exposes no cost_analysis flops")
    return got


def test_cost_analysis_counts_scan_body_once(cost_by_layers):
    """The wart the analytic forms exist for: reported flops do not grow
    with the scan trip count (so they understate an L-layer model ~Lx)."""
    c1, c2, c4 = (cost_by_layers[k] for k in (1, 2, 4))
    assert c2 < 1.5 * c1, f"L=2 counted {c2 / c1:.2f}x L=1"
    assert c4 < 1.5 * c1, f"L=4 counted {c4 / c1:.2f}x L=1"


def test_analytic_matches_cost_analysis_at_one_layer(cost_by_layers):
    """With no repeated scan body (L=1) the two accountings must agree;
    the analytic form omits LN/softmax/gathers so it sits slightly
    below the XLA count."""
    analytic = causal_lm.prefill_flops(B, T, D, 1, V)
    measured = cost_by_layers[1]
    assert 0.6 * measured < analytic <= 1.1 * measured, \
        f"analytic {analytic:.3e} vs cost_analysis {measured:.3e}"


def test_analytic_scales_linearly_in_layers_and_batch():
    one = causal_lm.prefill_flops(B, T, D, 1, V)
    unembed = B * 2 * D * V
    assert causal_lm.prefill_flops(B, T, D, 8, V) == \
        pytest.approx(8 * (one - unembed) + unembed)
    assert causal_lm.prefill_flops(4 * B, T, D, 1, V) == \
        pytest.approx(4 * one)


def test_decode_flops_matches_single_step_cost_analysis():
    """One decode step at L=1 (no repeated body anywhere): analytic vs
    XLA, same agreement window as prefill."""
    params = causal_lm.init_causal_lm(jax.random.PRNGKey(0), V, D, H, 1, T)
    kc, vc, pos = causal_lm.empty_cache(1, B, H, T, D // H)
    pos0 = 17
    tok = np.zeros((B, 1), np.int32)

    def fn(t, kc, vc):
        return causal_lm._lm_decode_step(
            params, t, kc, vc, np.full((1,), pos0, np.int32), H)[0]

    measured = probes.model_flops(fn, tok, kc, vc)
    if measured is None:
        pytest.skip("backend exposes no cost_analysis flops")
    analytic = causal_lm.decode_flops(B, pos0, 1, D, 1, V)
    assert 0.5 * measured < analytic <= 1.2 * measured, \
        f"analytic {analytic:.3e} vs cost_analysis {measured:.3e}"


def test_decode_flops_attention_term_sums_positions():
    """n_steps from pos0 must equal the sum of single steps (the
    attention term grows with position)."""
    total = causal_lm.decode_flops(B, 10, 5, D, 3, V)
    stepwise = sum(causal_lm.decode_flops(B, 10 + i, 1, D, 3, V)
                   for i in range(5))
    assert total == pytest.approx(stepwise)
