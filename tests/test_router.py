"""query.router tests — endpoint parsing, two-random-choice placement,
session affinity (stability, minimal remap, spill-on-death), graceful
drain, fleet-fed load signals, endpoint-scoped chaos faults with the
latching ``partition`` kind, hedged dispatch (first response wins, the
loser's connection stays in protocol sync), deadline admission at the
router door, and the last-resort fallback when every backend is down.

E2E acceptance: three live backends, a seeded plan partitions one
mid-stream — the pipeline finishes with zero errored buffers, at least
one ``router.failover`` re-dispatch is recorded (event + counter), the
dead backend's breaker opens, and after the net heals routing resumes
onto it. With ``backends=`` unset no router object exists at all (the
zero-overhead contract).
"""

import random
import socket
import time

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline
from nnstreamer_tpu.graph import element as gel
from nnstreamer_tpu.graph.element import FlowReturn
from nnstreamer_tpu.obs import events as obs_events
from nnstreamer_tpu.obs import fleet as obs_fleet
from nnstreamer_tpu.obs import health as obs_health
from nnstreamer_tpu.query import protocol
from nnstreamer_tpu.query import router as qrouter
from nnstreamer_tpu.query.protocol import (
    Cmd,
    buffer_to_payload,
    payload_to_buffer,
)
from nnstreamer_tpu.resilience import chaos, policy


def caps_of(dims, types, rate=30):
    return Caps.tensors(TensorsConfig(
        TensorsInfo.from_strings(dims, types), rate))


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def server_pipeline(port, sid=0):
    """One tensor_query server (x*10 filter). ``sid`` keys the
    serversrc/serversink pairing registry — every concurrently running
    server in one process needs its own id."""
    sp = Pipeline(f"server{sid}")
    ssrc = sp.add_new("tensor_query_serversrc", host="127.0.0.1",
                      port=port, id=sid, dims="4:1", types="float32")
    filt = sp.add_new("tensor_filter", model=lambda x: x * 10)
    ssink = sp.add_new("tensor_query_serversink", id=sid)
    Pipeline.link(ssrc, filt, ssink)
    return sp


@pytest.fixture
def metrics():
    from nnstreamer_tpu.obs import metrics as obs_metrics
    reg = obs_metrics.registry()
    was = reg.is_enabled
    reg.enable()
    yield obs_metrics
    reg._enabled = was


@pytest.fixture
def events():
    ring = obs_events.ring()
    was = ring.is_enabled
    ring.reset()
    yield obs_events
    obs_events.disable()
    ring.reset()
    ring._enabled = was


@pytest.fixture
def health():
    reg = obs_health.registry()
    was = reg.is_enabled
    reg.reset()
    yield obs_health
    reg.reset()
    reg._enabled = was


def events_of(etype):
    return [e for e in obs_events.ring().snapshot() if e["type"] == etype]


def mkset(endpoints, owner, **kw):
    return qrouter.BackendSet(qrouter.parse_endpoints(endpoints),
                              owner=owner, **kw)


# --------------------------------------------------------------------------- #
# Endpoint parsing
# --------------------------------------------------------------------------- #

class TestParseEndpoints:
    def test_string_and_list_forms(self):
        assert qrouter.parse_endpoints("a:1, b:2 ,c:3") == \
            [("a", 1), ("b", 2), ("c", 3)]
        assert qrouter.parse_endpoints(["a:1", "b:2"]) == \
            [("a", 1), ("b", 2)]
        assert qrouter.parse_endpoints("a:1,") == [("a", 1)]

    def test_rejects_malformed(self):
        with pytest.raises(ValueError, match="host:port"):
            qrouter.parse_endpoints("justahost")
        with pytest.raises(ValueError, match="non-integer"):
            qrouter.parse_endpoints("a:http")
        with pytest.raises(ValueError, match="out of range"):
            qrouter.parse_endpoints("a:70000")
        with pytest.raises(ValueError, match="twice"):
            qrouter.parse_endpoints("a:1,a:1")

    def test_backend_set_needs_one(self):
        with pytest.raises(ValueError, match="at least one"):
            qrouter.BackendSet([], owner="empty")


# --------------------------------------------------------------------------- #
# Placement: two-choice, breakers, affinity, drain
# --------------------------------------------------------------------------- #

class TestPlacement:
    EPS = "127.0.0.1:9001,127.0.0.1:9002,127.0.0.1:9003"

    def test_two_choice_never_picks_the_loaded_backend(self):
        # both sampled candidates compare loads, so a backend carrying
        # in-flight work loses every pairing it appears in
        bs = mkset(self.EPS, "p2c", rng=random.Random(5))
        bs.get("127.0.0.1:9001").inflight = 5
        picks = [bs.pick().endpoint for _ in range(50)]
        assert "127.0.0.1:9001" not in picks
        assert set(picks) == {"127.0.0.1:9002", "127.0.0.1:9003"}

    def test_exclude_and_single_candidate(self):
        bs = mkset(self.EPS, "excl", rng=random.Random(0))
        only = bs.pick(exclude=frozenset(
            {"127.0.0.1:9001", "127.0.0.1:9002"}))
        assert only.endpoint == "127.0.0.1:9003"
        assert bs.pick(exclude=frozenset(
            {"127.0.0.1:9001", "127.0.0.1:9002",
             "127.0.0.1:9003"})) is None

    def test_open_breaker_removes_backend_from_placement(self):
        bs = mkset(self.EPS, "brk", breaker_threshold=1,
                   rng=random.Random(1))
        bs.get("127.0.0.1:9001").breaker.record_failure()
        assert all(bs.pick().endpoint != "127.0.0.1:9001"
                   for _ in range(30))
        for ep in ("127.0.0.1:9002", "127.0.0.1:9003"):
            bs.get(ep).breaker.record_failure()
        assert bs.pick() is None  # nothing routable: caller's fallback

    def test_affinity_is_stable_and_spreads_sessions(self):
        bs = mkset(self.EPS, "aff", rng=random.Random(2))
        homes = {f"s{i}": bs.pick(session=f"s{i}").endpoint
                 for i in range(120)}
        for s, home in homes.items():
            assert all(bs.pick(session=s).endpoint == home
                       for _ in range(5))
        assert len(set(homes.values())) == 3  # not all piled on one

    def test_affinity_remap_on_add_is_bounded(self):
        bs = mkset(self.EPS, "remap", rng=random.Random(3))
        before = {f"s{i}": bs.pick(session=f"s{i}").endpoint
                  for i in range(300)}
        bs.add("127.0.0.1:9004")
        after = {s: bs.pick(session=s).endpoint for s in before}
        moved = sum(1 for s in before if before[s] != after[s])
        # consistent hashing: adding 1 of 4 remaps ~1/4 of sessions,
        # never the wholesale reshuffle a modulo hash would cause
        assert 0 < moved < 150
        assert all(after[s] == "127.0.0.1:9004"
                   for s in before if before[s] != after[s])

    def test_affinity_spills_with_event_when_home_dies(self, events):
        # an UNPLANNED death (breaker open) spills loudly — the remote
        # prefix cache is lost; a planned drain remaps silently via the
        # ring rebuild instead (no false alarms on scale-down)
        events.enable()
        bs = mkset(self.EPS, "spill", breaker_threshold=1,
                   rng=random.Random(4))
        sess = next(f"s{i}" for i in range(200)
                    if bs.pick(session=f"s{i}").endpoint
                    == "127.0.0.1:9001")
        bs.get("127.0.0.1:9001").breaker.record_failure()
        got = bs.pick(session=sess)
        assert got is not None and got.endpoint != "127.0.0.1:9001"
        spills = events_of("router.spill")
        assert spills and spills[0]["attrs"]["backend"] == "127.0.0.1:9001"

    def test_drain_and_remove_lifecycle(self, events):
        events.enable()
        bs = mkset(self.EPS, "drain")
        bs.drain("127.0.0.1:9001")
        # idle at drain time: reaped (closed) immediately, never placed
        assert bs.get("127.0.0.1:9001").state == qrouter.CLOSED
        assert all(bs.pick().endpoint != "127.0.0.1:9001"
                   for _ in range(30))
        bs.remove("127.0.0.1:9001")
        assert len(bs) == 2 and bs.get("127.0.0.1:9001") is None
        for et in ("router.drain", "router.backend_closed",
                   "router.backend_remove"):
            assert events_of(et), f"missing {et}"

    def test_duplicate_add_rejected(self):
        bs = mkset(self.EPS, "dup")
        with pytest.raises(ValueError, match="already"):
            bs.add("127.0.0.1:9001")


# --------------------------------------------------------------------------- #
# Fleet-fed placement + routing_view scalars
# --------------------------------------------------------------------------- #

class TestFleetSignals:
    def _doc(self, iid, depth=None, ready=True, seq=1):
        doc = {"instance": iid, "seq": seq, "role": "worker",
               "ready": {"ready": ready}}
        if depth is not None:
            doc["metrics"] = {"nnstpu_serving_queue_depth": {
                "type": "gauge", "help": "",
                "series": [{"labels": {}, "value": float(depth)}]}}
        return doc

    def test_routing_view_scalars_and_tombstones(self):
        agg = obs_fleet.FleetAggregator(ttl_s=30.0, expire_after_s=0.15,
                                        instance="agg-test")
        agg.ingest(self._doc("w1", depth=3.0), via="test")
        agg.ingest(self._doc("w2", ready=False), via="test")
        view = agg.routing_view()
        assert view["w1"]["routable"] and view["w1"]["queue_depth"] == 3.0
        assert not view["w2"]["routable"]  # self-reported not ready
        assert agg.snapshot()["instances"][0]["queue_depth"] == 3.0
        time.sleep(0.2)  # past expire_after_s: both expire
        view = agg.routing_view()
        # expiry leaves tombstones, not silence: "known dead", with a
        # queue depth no placement comparison can ever prefer
        for iid in ("w1", "w2"):
            assert view[iid]["expired"] and not view[iid]["routable"]
            assert view[iid]["queue_depth"] == float("inf")
        assert sorted(agg.snapshot()["expired"]) == ["w1", "w2"]
        agg.ingest(self._doc("w1", depth=0.0, seq=2), via="test")
        view = agg.routing_view()  # a returning instance sheds its stone
        assert view["w1"]["routable"] and "expired" not in view["w1"]
        assert agg.snapshot()["expired"] == ["w2"]

    def test_stale_instance_not_routable_but_present(self):
        agg = obs_fleet.FleetAggregator(ttl_s=0.05, expire_after_s=60.0,
                                        instance="agg-stale")
        agg.ingest(self._doc("w1", depth=1.0), via="test")
        time.sleep(0.1)  # past ttl, before expiry
        view = agg.routing_view()
        assert view["w1"]["stale"] and not view["w1"]["routable"]
        assert "expired" not in view["w1"]

    def test_pick_prefers_the_shallow_fleet_queue(self, monkeypatch):
        agg = obs_fleet.FleetAggregator(ttl_s=30.0, expire_after_s=60.0,
                                        instance="agg-place")
        agg.ingest(self._doc("w1", depth=50.0), via="test")
        agg.ingest(self._doc("w2", depth=0.0), via="test")
        monkeypatch.setattr(obs_fleet, "_AGGREGATOR", agg)
        bs = mkset("127.0.0.1:9101,127.0.0.1:9102", "fleetp",
                   rng=random.Random(6))
        bs.get("127.0.0.1:9101").instance = "w1"
        bs.get("127.0.0.1:9102").instance = "w2"
        assert all(bs.pick().endpoint == "127.0.0.1:9102"
                   for _ in range(20))
        # w2 stops reporting ready: inf load flips the preference
        agg.ingest(self._doc("w2", ready=False, seq=2), via="test")
        assert all(bs.pick().endpoint == "127.0.0.1:9101"
                   for _ in range(20))


# --------------------------------------------------------------------------- #
# Endpoint-scoped chaos + the partition fault
# --------------------------------------------------------------------------- #

class TestChaosEndpoint:
    E = "10.0.0.1:5001"

    def test_endpoint_selector_scopes_the_counter(self):
        plan = chaos.FaultPlan(
            [chaos.Fault(kind="drop", target="send", cmd="DATA",
                         endpoint=self.E, nth=1)], seed=0)
        # traffic to OTHER peers neither fires nor advances the count
        assert plan.decide("send", "DATA", endpoint="10.0.0.2:5001") == []
        assert plan.decide("send", "DATA", endpoint=None) == []
        hits = plan.decide("send", "DATA", endpoint=self.E)
        assert [f.kind for f in hits] == ["drop"]
        assert plan.fired[0]["endpoint"] == self.E

    def test_partition_latches_until_heal(self):
        plan = chaos.FaultPlan(
            [chaos.Fault(kind="partition", target="send", cmd="DATA",
                         endpoint=self.E, nth=2)], seed=0)
        assert plan.decide("send", "DATA", endpoint=self.E) == []  # n=1
        assert plan.decide("send", "DATA", endpoint=self.E) != []  # latch
        for _ in range(5):  # every later matching frame keeps dying
            assert plan.decide("send", "DATA", endpoint=self.E) != []
        assert plan.decide("send", "DATA",
                           endpoint="10.0.0.2:5001") == []  # one side only
        assert len(plan.fired) == 1  # audited once, at the latch
        plan.heal()
        assert plan.decide("send", "DATA", endpoint=self.E) == []

    def test_wire_hook_partition_raises_with_single_event(self, events):
        events.enable()
        plan = chaos.FaultPlan(
            [chaos.Fault(kind="partition", target="send", cmd="DATA",
                         endpoint=self.E, nth=1)], seed=0)
        chaos.install(plan)
        try:
            for _ in range(3):
                with pytest.raises(ConnectionError, match="partition"):
                    chaos._wire_hook("send", Cmd.DATA, {}, b"x", self.E)
            # untargeted traffic flows
            assert chaos._wire_hook("send", Cmd.DATA, {}, b"x",
                                    "10.0.0.2:1") == b"x"
        finally:
            chaos.uninstall()
        assert len(events_of("chaos.inject")) == 1  # latch, not per frame

    def test_from_spec_accepts_endpoint(self):
        plan = chaos.FaultPlan.from_spec({"seed": 1, "faults": [
            {"kind": "partition", "target": "send", "cmd": "DATA",
             "endpoint": self.E, "nth": 1}]})
        assert plan.faults[0].endpoint == self.E


# --------------------------------------------------------------------------- #
# Router dispatch units (no live servers)
# --------------------------------------------------------------------------- #

class TestDispatchUnits:
    def test_expired_deadline_shed_at_the_door(self, events):
        events.enable()
        bs = mkset(f"127.0.0.1:{free_port()}", "shed-unit")
        r = qrouter.QueryRouter(bs, "shed-unit")
        with pytest.raises(qrouter._ShedSignal):
            r.dispatch({}, b"", deadline=policy.Deadline.after_ms(0))
        shed = events_of("resilience.shed")
        assert shed and shed[0]["attrs"]["site"] == "router"

    def test_all_backends_down_raises_router_error(self):
        bs = mkset(f"127.0.0.1:{free_port()},127.0.0.1:{free_port()}",
                   "down-unit", timeout_s=0.3)
        r = qrouter.QueryRouter(
            bs, "down-unit", max_request_retry=2,
            retry_policy=policy.RetryPolicy(base_s=0.001, max_s=0.002))
        with pytest.raises(qrouter.RouterError):
            r.dispatch({}, b"\x00")

    def test_add_refused_while_draining(self):
        bs = mkset(f"127.0.0.1:{free_port()}", "drain-unit")
        r = qrouter.QueryRouter(bs, "drain-unit")
        r.draining = True
        with pytest.raises(RuntimeError, match="draining"):
            r.add_backend("127.0.0.1:9999")
        assert len(r.backends) == 1

    def test_hedge_delay_floors_at_prop_until_enough_samples(self):
        bs = mkset(f"127.0.0.1:{free_port()}", "hd-unit")
        r = qrouter.QueryRouter(bs, "hd-unit", hedge_ms=25.0)
        assert r.hedge_delay_s() == pytest.approx(0.025)
        for _ in range(30):
            r._observe_latency(0.004)
        r._observe_latency(0.9)  # one outlier can't drag P95 that far
        assert r.hedge_delay_s() == pytest.approx(0.025)
        for _ in range(40):
            r._observe_latency(0.2)  # now P95 genuinely above the floor
        assert r.hedge_delay_s() > 0.025

    def test_auto_hedge_arms_from_observed_p95(self, tmp_path):
        """With no manual --hedge-ms, the autotuner hook arms hedging
        once the latency window holds >= 20 samples; tune off (or too
        few samples) keeps the plain single-dispatch path."""
        from nnstreamer_tpu import tune

        bs = mkset(f"127.0.0.1:{free_port()},127.0.0.1:{free_port()}",
                   "ah-unit")
        r = qrouter.QueryRouter(bs, "ah-unit")  # hedge_ms defaults to 0
        calls = {"direct": 0, "hedged": 0}
        be = r.backends.backends()[0]
        be.request = lambda meta, payload, caps: (
            calls.__setitem__("direct", calls["direct"] + 1)
            or ({"ok": 1}, b""))
        r._hedged = lambda *a, **k: (
            calls.__setitem__("hedged", calls["hedged"] + 1)
            or ({"ok": 1}, b""))
        try:
            assert tune.TUNE_HOOK is None
            r._attempt(be, {}, b"", None, None, set())
            assert calls == {"direct": 1, "hedged": 0}  # tune off

            tune.enable(str(tmp_path / "s.json"), fit_from_profiler=False)
            r._attempt(be, {}, b"", None, None, set())
            assert calls == {"direct": 2, "hedged": 0}  # < 20 samples

            for _ in range(25):
                r._observe_latency(0.004)
            r._attempt(be, {}, b"", None, None, set())
            assert calls == {"direct": 2, "hedged": 1}  # armed

            tune.tuner().auto_hedge = False  # explicit opt-out respected
            r._attempt(be, {}, b"", None, None, set())
            assert calls == {"direct": 3, "hedged": 1}
        finally:
            tune.disable(save=False)


# --------------------------------------------------------------------------- #
# Drain-never-dials (client) + zero-overhead contract
# --------------------------------------------------------------------------- #

class TestClientContracts:
    def test_eos_drain_refuses_to_dial(self):
        qc = gel.make_element("tensor_query_client", port=free_port())
        qc._draining = True
        with pytest.raises(ConnectionError, match="draining"):
            qc._connect()

    def test_on_eos_blocks_dials_and_router_growth(self):
        # the old drain/reconnect race: during the EOS drain nothing may
        # open a connection, and the router may not grow membership
        qc = gel.make_element(
            "tensor_query_client",
            backends=f"127.0.0.1:{free_port()}", drain_timeout_s=0.1)
        qc.start()
        try:
            seen = {}

            def spy(timeout=None):
                seen["draining"] = qc._draining
                with pytest.raises(ConnectionError, match="draining"):
                    qc._connect()
                with pytest.raises(RuntimeError, match="draining"):
                    qc.router.add_backend("127.0.0.1:9999")

            qc._drain_pending = spy
            qc.on_eos()
            assert seen["draining"] is True
            assert qc._draining is False  # reset once the drain is over
        finally:
            qc.stop()

    def test_no_backends_means_no_router_object(self):
        # the zero-overhead contract: unset ⇒ chain() pays one is-None
        # check; there is no router to consult, no routed state at all
        qc = gel.make_element("tensor_query_client", port=free_port())
        qc.start()
        try:
            assert qc._router is None and qc.router is None
        finally:
            qc.stop()

    def test_stop_tears_down_router_start_rebuilds(self):
        eps = f"127.0.0.1:{free_port()},127.0.0.1:{free_port()}"
        qc = gel.make_element("tensor_query_client", backends=eps)
        qc.start()
        first = qc.router
        assert first is not None and len(first.backends) == 2
        qc.stop()
        assert qc.router is None
        for be in first.backends.backends():
            assert be.state == qrouter.CLOSED
        qc.start()
        try:
            assert qc.router is not None and qc.router is not first
        finally:
            qc.stop()


# --------------------------------------------------------------------------- #
# E2E: routed offload, failover acceptance, hedging, last resort
# --------------------------------------------------------------------------- #

class TestRoutedEndToEnd:
    def _drive(self, qc, sink, frames, start_offset=0):
        for i, arr in enumerate(frames):
            buf = Buffer.of(arr)
            buf.offset = start_offset + i
            assert qc._chain_entry(qc.sink_pad, buf) == FlowReturn.OK

    def test_routed_offload_spreads_across_backends(self):
        ports = [free_port() for _ in range(2)]
        pipes = [server_pipeline(p, sid=i) for i, p in enumerate(ports)]
        for sp in pipes:
            sp.start()
        qc = gel.make_element(
            "tensor_query_client", timeout_s=2.0,
            backends=",".join(f"127.0.0.1:{p}" for p in ports))
        sink = gel.make_element("tensor_sink", store=True)
        qc.src_pads[0].link(sink.sink_pads[0])
        try:
            time.sleep(0.2)
            sink.start()
            qc.start()
            qc.router.backends._rng = random.Random(7)
            qc.on_caps(qc.sink_pad, caps_of("4:1", "float32"))
            frames = [np.full((1, 4), i, np.float32) for i in range(10)]
            self._drive(qc, sink, frames)
            assert sink.num_buffers == 10
            for i, out in enumerate(sink.buffers):
                np.testing.assert_array_equal(out.memories[0].host(),
                                              frames[i] * 10)
                assert out.offset == i
            snap = qc.router.snapshot()
            served = {b["endpoint"]: b["dispatched"]
                      for b in snap["backends"]}
            assert sum(served.values()) == 10
            assert all(n > 0 for n in served.values())  # genuine spread
        finally:
            qc.stop()
            for sp in pipes:
                sp.stop()

    def test_single_backend_list_routes_fine(self):
        port = free_port()
        sp = server_pipeline(port, sid=0)
        sp.start()
        qc = gel.make_element("tensor_query_client", timeout_s=2.0,
                              backends=[f"127.0.0.1:{port}"])
        sink = gel.make_element("tensor_sink", store=True)
        qc.src_pads[0].link(sink.sink_pads[0])
        try:
            time.sleep(0.2)
            sink.start()
            qc.start()
            qc.on_caps(qc.sink_pad, caps_of("4:1", "float32"))
            frames = [np.full((1, 4), i, np.float32) for i in range(3)]
            self._drive(qc, sink, frames)
            assert sink.num_buffers == 3
            np.testing.assert_array_equal(
                sink.buffers[2].memories[0].host(), frames[2] * 10)
        finally:
            qc.stop()
            sp.stop()

    @pytest.mark.chaos
    def test_partition_failover_breaker_and_recovery(self, events,
                                                     metrics):
        """The acceptance run: 3 backends, a seeded plan partitions one
        mid-stream. Zero errored buffers, every frame delivered with the
        right result, >=1 failover re-dispatch (event + counter), the
        dead backend's breaker opens, and routing resumes onto it after
        the net heals and the breaker's half-open probe succeeds."""
        events.enable()
        ports = [free_port() for _ in range(3)]
        eps = [f"127.0.0.1:{p}" for p in ports]
        pipes = [server_pipeline(p, sid=i) for i, p in enumerate(ports)]
        for sp in pipes:
            sp.start()
        qc = gel.make_element(
            "tensor_query_client", backends=",".join(eps),
            max_request_retry=4, timeout_s=2.0, retry_base_s=0.01,
            retry_max_s=0.05, breaker_threshold=1, breaker_reset_s=0.3)
        sink = gel.make_element("tensor_sink", store=True)
        qc.src_pads[0].link(sink.sink_pads[0])
        fail_before = qrouter._FAILOVER_TOTAL.labels(qc.name).value
        plan = chaos.FaultPlan(
            [chaos.Fault(kind="partition", target="send", cmd="DATA",
                         endpoint=eps[0], nth=1)], seed=11)
        try:
            time.sleep(0.2)
            sink.start()
            qc.start()
            qc.router.backends._rng = random.Random(7)
            qc.on_caps(qc.sink_pad, caps_of("4:1", "float32"))
            frames = [np.full((1, 4), i, np.float32) for i in range(18)]
            self._drive(qc, sink, frames[:6])  # healthy warm-up
            chaos.install(plan)  # eps[0] black-holes from its next DATA
            self._drive(qc, sink, frames[6:12], start_offset=6)
            dead = qc.router.backends.get(eps[0])
            assert plan.fired, "seeded plan never latched the partition"
            assert dead.breaker.state == policy.OPEN
            fovers = events_of("router.failover")
            assert fovers and all(
                e["attrs"]["backend"] != eps[0] for e in fovers)
            assert qrouter._FAILOVER_TOTAL.labels(qc.name).value \
                > fail_before
            served_dead = dead.dispatched
            plan.heal()  # the "restart": the net comes back
            time.sleep(0.35)  # past breaker_reset_s: half-open probe due
            self._drive(qc, sink, frames[12:], start_offset=12)
            assert dead.dispatched > served_dead  # probe landed + closed
            assert sink.num_buffers == 18  # zero errored/lost buffers
            for i, out in enumerate(sink.buffers):
                np.testing.assert_array_equal(out.memories[0].host(),
                                              frames[i] * 10)
                assert out.offset == i
        finally:
            chaos.uninstall()
            qc.stop()
            for sp in pipes:
                sp.stop()

    @pytest.mark.chaos
    def test_hedged_dispatch_first_response_wins(self, events):
        """A delay fault makes one backend the slow primary; the hedge
        fires after the configured floor and the fast peer's response
        wins, while the slow round trip completes in the background and
        leaves its connection in protocol sync."""
        events.enable()
        ports = [free_port() for _ in range(2)]
        eps = [f"127.0.0.1:{p}" for p in ports]
        pipes = [server_pipeline(p, sid=i) for i, p in enumerate(ports)]
        for sp in pipes:
            sp.start()
        bs = mkset(",".join(eps), "hedge-e2e", timeout_s=2.0)
        r = qrouter.QueryRouter(bs, "hedge-e2e", hedge_ms=50.0)
        r.set_caps_provider(lambda: str(caps_of("4:1", "float32")))
        plan = chaos.FaultPlan(
            [chaos.Fault(kind="delay", target="send", cmd="DATA",
                         endpoint=eps[0], p=1.0, delay_s=0.6)], seed=2)
        try:
            time.sleep(0.2)
            slow = bs.get(eps[0])
            meta, payload = buffer_to_payload(
                Buffer.of(np.full((1, 4), 3.0, np.float32)))
            chaos.install(plan)
            t0 = time.monotonic()
            rmeta, rpayload = r._attempt(slow, meta, payload, None,
                                         None, set())
            elapsed = time.monotonic() - t0
            assert elapsed < 0.5  # the 0.6s primary did NOT gate us
            out = payload_to_buffer(rmeta, rpayload)
            np.testing.assert_array_equal(
                out.memories[0].host(), np.full((1, 4), 30.0, np.float32))
            hedges = events_of("resilience.hedge")
            assert hedges and hedges[0]["attrs"]["backend"] == eps[1]
            # let the loser's delayed round trip finish in background...
            t1 = time.monotonic()
            while slow.inflight > 0 and time.monotonic() - t1 < 3.0:
                time.sleep(0.02)
            assert slow.inflight == 0
            chaos.uninstall()
            # ...then prove its connection is still in protocol sync
            rmeta2, rpayload2 = slow.request(meta, payload,
                                             str(caps_of("4:1", "float32")))
            out2 = payload_to_buffer(rmeta2, rpayload2)
            np.testing.assert_array_equal(
                out2.memories[0].host(),
                np.full((1, 4), 30.0, np.float32))
        finally:
            chaos.uninstall()
            r.close()
            for sp in pipes:
                sp.stop()

    def test_live_add_and_drain_reroutes(self, events):
        events.enable()
        ports = [free_port() for _ in range(2)]
        eps = [f"127.0.0.1:{p}" for p in ports]
        pipes = [server_pipeline(p, sid=i) for i, p in enumerate(ports)]
        pipes[0].start()
        bs = mkset(eps[0], "liveadd", timeout_s=2.0)
        r = qrouter.QueryRouter(bs, "liveadd")
        r.set_caps_provider(lambda: str(caps_of("4:1", "float32")))
        try:
            time.sleep(0.2)
            meta, payload = buffer_to_payload(
                Buffer.of(np.full((1, 4), 2.0, np.float32)))
            r.dispatch(meta, payload)
            pipes[1].start()
            time.sleep(0.2)
            r.add_backend(eps[1])  # scale up: placeable immediately
            r.drain_backend(eps[0])  # scale down: idle -> closed now
            assert bs.get(eps[0]).state == qrouter.CLOSED
            for _ in range(3):
                rmeta, rpayload = r.dispatch(meta, payload)
            out = payload_to_buffer(rmeta, rpayload)
            np.testing.assert_array_equal(
                out.memories[0].host(), np.full((1, 4), 20.0, np.float32))
            assert bs.get(eps[1]).dispatched == 3  # all post-drain traffic
            assert events_of("router.backend_add")
            assert events_of("router.drain")
        finally:
            r.close()
            for sp in pipes:
                sp.stop()

    def test_all_backends_down_takes_local_fallback(self, events, health):
        """Last resort: every backend dead routes into the client's
        existing fallback= path — the pipeline COMPLETES and health
        reports DEGRADED, not failed."""
        events.enable()
        health.enable()
        eps = f"127.0.0.1:{free_port()},127.0.0.1:{free_port()}"
        cp = Pipeline("routed-fb")
        frames = [np.full((1, 4), i, np.float32) for i in range(4)]
        src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"),
                         data=frames)
        qc = cp.add_new("tensor_query_client", backends=eps,
                        max_request_retry=2, timeout_s=0.3,
                        retry_base_s=0.001, retry_max_s=0.002,
                        breaker_threshold=1, breaker_reset_s=600.0,
                        fallback="passthrough")
        sink = cp.add_new("tensor_sink", store=True)
        Pipeline.link(src, qc, sink)
        cp.run(timeout=60)  # degradation, not a pipeline error
        assert sink.num_buffers == 4
        for i, out in enumerate(sink.buffers):
            np.testing.assert_array_equal(out.memories[0].host(),
                                          frames[i])
        assert events_of("resilience.fallback")
        snap = obs_health.snapshot()
        comp = next(c for c in snap["components"]
                    if c["name"] == f"query.client:{qc.name}")
        assert comp["status"] == "degraded"
        assert snap["ok"] is True
