"""w8a8 int8 serving path (ops/int8.py + causal_lm.quantize_lm_params).

The reference serves quantized models through TFLite's int8 kernels
(ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc with the
mobilenet_*_quant.tflite test models); the TPU-idiomatic transformer
equivalent is dynamic-activation int8 GEMMs on the MXU's double-rate
path. Three contracts pinned here:

* the quantize/dot/rescale math is exactly the documented scheme
  (numpy integer reference, bit-level);
* quantized logits track the float model (bounded drift);
* the family's exactness-BETWEEN-FORMS contract survives quantization:
  int32 accumulation has no contraction-order drift, so prefill+decode,
  verify windows, vmapped slots, and the full forward agree at float
  roundoff — measured ~1e-7, the same level as the float paths.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import causal_lm
from nnstreamer_tpu.ops import int8 as i8

V, D, H, L, T = 64, 64, 4, 2, 16


@pytest.fixture(scope="module")
def params():
    return causal_lm.init_causal_lm(jax.random.PRNGKey(0), V, D, H, L, T)


@pytest.fixture(scope="module")
def qparams(params):
    return causal_lm.quantize_lm_params(params)


def test_int8_matmul_matches_integer_reference():
    """The documented scheme, replayed in numpy int64: per-output-channel
    weight grid, per-row dynamic activation grid, exact int product,
    outer-product rescale."""
    rng = np.random.default_rng(0)
    x = rng.normal(size=(5, 16)).astype(np.float32)
    w = rng.normal(size=(16, 8)).astype(np.float32)

    y = np.asarray(i8.int8_matmul(jnp.asarray(x), i8.quantize_weight(w)))

    wa = np.max(np.abs(w), axis=0)
    ws = np.where(wa == 0, 1.0, wa / 127.0)
    wq = np.clip(np.round(w / ws), -127, 127).astype(np.int64)
    xa = np.max(np.abs(x), axis=1, keepdims=True)
    xs = np.where(xa == 0, 1.0, xa / 127.0)
    xq = np.clip(np.round(x / xs), -127, 127).astype(np.int64)
    ref = (xq @ wq).astype(np.float32) * xs * ws
    np.testing.assert_allclose(y, ref, rtol=1e-6, atol=1e-7)


def test_quantize_weight_layer_stack_slices():
    """A scanned (L, K, N) stack quantizes to per-layer grids — each
    layer's slice must equal quantizing that layer alone."""
    rng = np.random.default_rng(1)
    w = rng.normal(size=(3, 8, 4)).astype(np.float32)
    stacked = i8.quantize_weight(w)
    for layer in range(3):
        alone = i8.quantize_weight(w[layer])
        np.testing.assert_array_equal(
            np.asarray(stacked[i8.W8A8_TAG][layer]),
            np.asarray(alone[i8.W8A8_TAG]))
        np.testing.assert_allclose(np.asarray(stacked["s"][layer]),
                                   np.asarray(alone["s"]))


def test_zero_rows_and_channels_are_safe():
    x = jnp.zeros((2, 8), jnp.float32)
    w = np.zeros((8, 4), np.float32)
    w[:, 0] = 1.0
    y = np.asarray(i8.int8_matmul(x, i8.quantize_weight(w)))
    assert np.isfinite(y).all() and (y == 0).all()


def test_quantized_logits_track_float(params, qparams):
    """Bounded drift vs the float model: dynamic per-token activation
    grids keep logits within a few percent (measured max ~2.6% of the
    logit scale on these dims)."""
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, V, (2, 10)).astype(np.int32))
    lf = np.asarray(causal_lm.lm_forward(params, toks, H))
    lq = np.asarray(causal_lm.lm_forward(qparams, toks, H))
    scale = np.abs(lf).max()
    assert np.abs(lq - lf).max() < 0.06 * scale
    cos = (lf * lq).sum(-1) / (
        np.linalg.norm(lf, axis=-1) * np.linalg.norm(lq, axis=-1))
    assert cos.min() > 0.995


def test_quantized_prefill_then_decode_matches_quantized_forward(qparams):
    """Exactness-between-forms survives quantization: the int8 GEMMs
    accumulate in exact int32, so the quantized family agrees across
    execution forms at float roundoff — same contract, same tolerance
    as the float tests."""
    rng = np.random.default_rng(2)
    toks = rng.integers(0, V, (2, 10)).astype(np.int32)
    oracle = np.asarray(causal_lm.lm_forward(qparams, jnp.asarray(toks), H))
    P = 4
    logits, k, v, pos = causal_lm.lm_prefill(
        qparams, jnp.asarray(toks[:, :P]), H, T)
    np.testing.assert_allclose(np.asarray(logits), oracle[:, P - 1],
                               rtol=2e-4, atol=2e-5)
    for t in range(P, 10):
        logits, k, v, pos = causal_lm.lm_decode_step(
            qparams, jnp.asarray(toks[:, t:t + 1]), k, v, pos, H)
        np.testing.assert_allclose(
            np.asarray(logits), oracle[:, t], rtol=2e-4, atol=2e-5,
            err_msg=f"quantized step {t} diverged")
    assert int(np.asarray(pos)[0]) == 10


def test_quantized_verify_window_matches_steps(qparams):
    """Speculative-decoding verify windows run the same quantized GEMMs:
    a W=3 window equals 3 single steps bit-for-bit in the int8 products
    (float roundoff overall)."""
    rng = np.random.default_rng(3)
    toks = rng.integers(0, V, (1, 9)).astype(np.int32)
    P = 3
    _, k1, v1, p1 = causal_lm.lm_prefill(
        qparams, jnp.asarray(toks[:, :P]), H, T)
    k2, v2, p2 = k1, v1, p1
    win, kw, vw, pw = causal_lm.lm_verify_window(
        qparams, jnp.asarray(toks[:, P:P + 3]), k1, v1, p1, H)
    for j in range(3):
        step, k2, v2, p2 = causal_lm.lm_decode_step(
            qparams, jnp.asarray(toks[:, P + j:P + j + 1]), k2, v2, p2, H)
        np.testing.assert_allclose(np.asarray(win[:, j]), np.asarray(step),
                                   rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(kw), np.asarray(k2),
                               rtol=1e-5, atol=1e-6)


def test_filter_w8a8_option_serves_lm():
    """custom="quant=w8a8" on the tensor_filter surface: the zoo LM's
    decode step serves int8 end-to-end, logits close to the float
    filter's (and the metadata records the mode)."""
    from nnstreamer_tpu.models.causal_lm import empty_cache
    from nnstreamer_tpu.single import SingleShot

    spec = f"zoo://causal_lm?vocab={V}&dim=32&heads=4&layers=2&max_len=8"
    s_f = SingleShot(model=spec, framework="xla-tpu")
    s_q = SingleShot(model=spec, framework="xla-tpu", custom="quant=w8a8")
    assert s_q.fw._bundle.metadata["quantized"] == "w8a8"

    tok = np.asarray([[3]], np.int32)
    k, v, pos = empty_cache(2, 1, 4, 8, 8)
    lf = np.asarray(s_f.invoke(tok, k, v, pos)[0])
    lq = np.asarray(s_q.invoke(tok, k, v, pos)[0])
    assert lf.shape == lq.shape
    assert np.abs(lq - lf).max() < 0.06 * max(np.abs(lf).max(), 1e-6)


def test_w8a8_rejects_non_lm_bundle():
    from nnstreamer_tpu.models.quantize import quantize_bundle_w8a8
    from nnstreamer_tpu.models.zoo import get_model

    b = get_model("zoo://mobilenet_v2?width=0.25&size=32&num_classes=16"
                  "&dtype=float32")
    with pytest.raises(ValueError, match="w8a8"):
        quantize_bundle_w8a8(b)


@pytest.mark.parametrize("n_model", [2, 4])
def test_tp_decode_quantized_matches_single_device(qparams, n_model):
    """Distributed int8 decode: head-sharded TP generate over a w8a8
    tree equals the single-device quantized decode loop token-for-token.
    The design makes this EXACT, not approximate: column-sharded int8
    weights keep their single-device codes/grids, activations quantize
    on pmax-global grids, and row-sharded partials are summed in exact
    int32 before one global rescale (parallel/tp_decode.py
    _restructure_w8a8 + ops/int8.quant_act_global)."""
    from jax.sharding import Mesh

    from nnstreamer_tpu.parallel.tp_decode import (
        make_tp_generate, tp_shard_cache, tp_shard_params)

    if len(jax.devices()) < n_model:
        pytest.skip("needs virtual multi-device CPU")
    mesh = Mesh(np.array(jax.devices()[:n_model]), ("model",))
    rng = np.random.default_rng(6)
    prompt = rng.integers(0, V, (2, 7)).astype(np.int32)
    n_steps = 8  # pos 7 + 8 steps = 15 <= max_len 16

    logits, kc, vc, pos = causal_lm.lm_prefill(
        qparams, jnp.asarray(prompt), H, T)
    first = jnp.argmax(logits, -1)[:, None].astype(jnp.int32)
    want, tok = [], first
    kc1, vc1, p1 = kc, vc, pos
    for _ in range(n_steps):
        lg, kc1, vc1, p1 = causal_lm.lm_decode_step(
            qparams, tok, kc1, vc1, p1, H)
        tok = jnp.argmax(lg, -1)[:, None].astype(jnp.int32)
        want.append(np.asarray(tok[:, 0]))
    want = np.stack(want, 1)

    tp = tp_shard_params(qparams, H, mesh)
    kc_tp, vc_tp = tp_shard_cache(kc, vc, L, 2, H, mesh)
    gen = make_tp_generate(H, T, mesh)
    got = np.asarray(gen(tp, first, kc_tp, vc_tp, pos, n_steps))
    np.testing.assert_array_equal(got, want)


def test_tp_shard_params_quantized_layout():
    """Sliced int8 payloads/scales must equal the single-device codes'
    slices (grid preservation is the whole design)."""
    from jax.sharding import Mesh

    from nnstreamer_tpu.ops.int8 import W8A8_TAG
    from nnstreamer_tpu.parallel.tp_decode import tp_shard_params

    if len(jax.devices()) < 2:
        pytest.skip("needs virtual multi-device CPU")
    p = causal_lm.init_causal_lm(jax.random.PRNGKey(5), V, D, H, 1, 8)
    qp = causal_lm.quantize_lm_params(p)
    mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
    tp = tp_shard_params(qp, H, mesh)

    qw = np.asarray(qp["wqkv"][W8A8_TAG])   # (1, D, 3D)
    wq0 = np.asarray(tp["wq"][W8A8_TAG])[0, 0]   # device 0: (D, hn*hd)
    np.testing.assert_array_equal(wq0, qw[0, :, :D // 2])
    np.testing.assert_array_equal(
        np.asarray(tp["wo_s"]), np.asarray(qp["wo"]["s"]))
    assert wq0.dtype == np.int8


def test_serving_engine_runs_quantized(qparams):
    """The continuous-batching engine consumes a quantized tree through
    the same slot primitives (stack_shape introspection instead of
    .shape) — greedy output must equal the engine-free quantized
    generation path."""
    from nnstreamer_tpu.serving.lm_engine import LMEngine

    rng = np.random.default_rng(4)
    prompts = [rng.integers(0, V, (n,)).astype(np.int32) for n in (3, 5)]
    gen = 4

    eng = LMEngine(qparams, H, T, n_slots=2, chunk=2)
    rids = [eng.submit(p, max_new=gen) for p in prompts]
    res = eng.run()

    for rid, p in zip(rids, prompts):
        logits, k, v, pos = causal_lm.lm_prefill(
            qparams, jnp.asarray(p[None]), H, T)
        want = [int(np.asarray(jnp.argmax(logits, -1))[0])]
        while len(want) < gen:
            logits, k, v, pos = causal_lm.lm_decode_step(
                qparams, jnp.asarray([[want[-1]]], dtype=jnp.int32),
                k, v, pos, H)
            want.append(int(np.asarray(jnp.argmax(logits, -1))[0]))
        assert res[rid] == want
