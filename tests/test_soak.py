"""Soak/volume tests (reference unittest_sink scale: thousands of buffers
through long-lived pipelines; asserts sustained operation, ordering, and
bounded decoder queues rather than just smoke)."""

import time

import numpy as np

from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline


def test_two_thousand_frames_sustained():
    n = 2000
    p = Pipeline()
    src = p.add_new("videotestsrc", width=16, height=16, num_buffers=n,
                    pattern="random")
    conv = p.add_new("tensor_converter")
    filt = p.add_new("tensor_filter",
                     model="zoo://scaler?dims=3:16:16:1&types=uint8&scale=2")
    dec = p.add_new("tensor_decoder", mode="direct_video", async_depth=32)
    count = [0]
    last_pts = [-1]
    ok = [True]

    sink = p.add_new("tensor_sink")

    def on_data(buf):
        count[0] += 1
        if buf.pts is not None:
            ok[0] &= buf.pts >= last_pts[0]
            last_pts[0] = buf.pts

    sink.new_data = on_data
    Pipeline.link(src, conv, filt, dec, sink)
    t0 = time.monotonic()
    p.run(timeout=300)
    dt = time.monotonic() - t0
    assert count[0] == n
    assert ok[0], "PTS order violated"
    assert dt < 120, f"2000 tiny frames took {dt:.0f}s"
    # decoder drained fully
    assert p.get_by_name(dec.name) is dec
    assert len(dec._pending) == 0


def test_long_lived_queue_backpressure():
    """queue with max-size bounds memory while a slow sink drains."""
    n = 400
    p = Pipeline()
    caps = Caps.tensors(TensorsConfig(
        TensorsInfo.from_strings("16:1", "float32"), 0))
    src = p.add_new("appsrc", caps=caps,
                    data=(np.full((1, 16), i, np.float32) for i in range(n)))
    q = p.add_new("queue", max_size_buffers=8)
    seen = []

    sink = p.add_new("tensor_sink")
    sink.new_data = lambda b: (seen.append(int(b.memories[0].host()[0, 0])),
                               time.sleep(0.001))
    Pipeline.link(src, q, sink)
    p.run(timeout=120)
    assert seen == list(range(n))


def test_adaptive_batch_soak_order_and_count():
    """1000 frames through batch→filter→unbatch: nothing dropped,
    nothing reordered, partial tail flushed."""
    import numpy as np

    from nnstreamer_tpu.core import Caps
    from nnstreamer_tpu.core.types import TensorsConfig, TensorsInfo
    from nnstreamer_tpu.graph import Pipeline

    n = 1000
    p = Pipeline()
    src = p.add_new("appsrc", caps=Caps.tensors(TensorsConfig(
        TensorsInfo.from_strings("4:1", "float32"), 0)),
        data=(np.full((1, 4), i, np.float32) for i in range(n)))
    bat = p.add_new("tensor_batch", max_batch=16, budget_ms=50.0)
    filt = p.add_new("tensor_filter", framework="xla-tpu",
                     model="zoo://scaler?scale=3&dims=4:16&types=float32")
    unb = p.add_new("tensor_unbatch")
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, bat, filt, unb, sink)
    p.run(timeout=300)
    assert sink.num_buffers == n
    vals = [int(b.memories[0].host()[0, 0]) for b in sink.buffers]
    assert vals == [3 * i for i in range(n)]


def test_pipelined_offload_soak():
    """500 frames through the pipelined query path: complete and in order."""
    import socket
    import time

    import numpy as np

    from nnstreamer_tpu.core import Caps
    from nnstreamer_tpu.core.types import TensorsConfig, TensorsInfo
    from nnstreamer_tpu.graph import Pipeline

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    sp = Pipeline("server")
    ssrc = sp.add_new("tensor_query_serversrc", host="127.0.0.1",
                      port=port, id=9, dims="4:1", types="float32")
    filt = sp.add_new("tensor_filter", model=lambda x: x + 1)
    ssink = sp.add_new("tensor_query_serversink", id=9, async_depth=32)
    Pipeline.link(ssrc, filt, ssink)
    sp.start()
    try:
        time.sleep(0.2)
        n = 500
        cp = Pipeline("client")
        src = cp.add_new("appsrc", caps=Caps.tensors(TensorsConfig(
            TensorsInfo.from_strings("4:1", "float32"), 0)),
            data=(np.full((1, 4), i, np.float32) for i in range(n)))
        qc = cp.add_new("tensor_query_client", host="127.0.0.1",
                        port=port, async_depth=32)
        sink = cp.add_new("tensor_sink", store=True)
        Pipeline.link(src, qc, sink)
        cp.run(timeout=300)
        assert sink.num_buffers == n
        for i in (0, n // 2, n - 1):
            np.testing.assert_array_equal(
                sink.buffers[i].memories[0].host(),
                np.full((1, 4), i + 1, np.float32))
    finally:
        sp.stop()
