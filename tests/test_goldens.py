"""Bit-exact golden decoder tests (VERDICT r2 missing #4).

Reference model: golden-compare SSAT tests
(tests/nnstreamer_decoder_boundingbox/runTest.sh — decode frozen inputs,
byte-compare rendered output). Frozen inputs + expected outputs live in
tests/goldens/goldens.npz (generated once by tests/goldens/generate.py);
every decode here must reproduce the stored bytes EXACTLY — a silent
draw/NMS/palette/scaling change fails the suite.

The device submit/complete paths are separately asserted equal to the host
path (test_model_pipelines.py), so these goldens pin both.
"""

import os

import numpy as np
import pytest

HERE = os.path.join(os.path.dirname(os.path.abspath(__file__)), "goldens")
NPZ = os.path.join(HERE, "goldens.npz")

import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from goldens.generate import build_cases, decode_case  # noqa: E402


@pytest.fixture(scope="module")
def goldens():
    assert os.path.isfile(NPZ), \
        "tests/goldens/goldens.npz missing — run tests/goldens/generate.py"
    return np.load(NPZ)


_CASES = build_cases()


@pytest.mark.parametrize("case", _CASES, ids=[c[0] for c in _CASES])
def test_decoder_bit_exact(case, goldens):
    name, mode, options, arrays, config = case
    # frozen inputs must equal the committed ones (generator drift guard)
    for i, a in enumerate(arrays):
        np.testing.assert_array_equal(
            a, goldens[f"{name}__in{i}"],
            err_msg=f"{name}: generated input {i} drifted — generate.py is "
                    "no longer deterministic")
    decoded = decode_case(mode, options, arrays, config)
    got = decoded.memories[0].host()
    want = goldens[f"{name}__out"]
    assert got.dtype == want.dtype and got.shape == want.shape, \
        f"{name}: output {got.dtype}{got.shape} != golden {want.dtype}{want.shape}"
    np.testing.assert_array_equal(
        got, want, err_msg=f"{name}: decode output no longer bit-exact")


def test_goldens_cover_all_visual_decoders():
    """Every draw/palette-producing decoder mode has at least one golden."""
    modes = {c[1] for c in _CASES}
    assert {"bounding_box", "image_segment", "pose_estimation",
            "image_labeling", "font", "direct_video"} <= modes
