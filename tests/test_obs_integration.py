"""End-to-end observability: run a real element chain, a query
server+client pair, and an LMEngine workload with metrics enabled,
then scrape the live ``/metrics`` endpoint and assert at least one
populated series from each of the three instrumented layers
(the ISSUE acceptance criterion)."""

import re
import socket
import time
import urllib.request

import numpy as np
import pytest

import jax

from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline
from nnstreamer_tpu.models import causal_lm
from nnstreamer_tpu.obs import metrics as obs_metrics
from nnstreamer_tpu.obs.exporter import start_exporter
from nnstreamer_tpu.serving import LMEngine

V, D, H, L, MAXLEN = 32, 16, 2, 1, 32

#: exposition line: comment, or  name{labels} value  /  name value
_LINE_RE = re.compile(
    r"^(?:#.*|[A-Za-z_:][A-Za-z0-9_:]*(?:\{[^{}]*\})? [0-9+\-.eEinf]+)$")


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


@pytest.fixture
def metrics_on():
    was = obs_metrics.enabled()
    obs_metrics.enable()
    yield obs_metrics.registry()
    (obs_metrics.enable if was else obs_metrics.disable)()


def _run_element_chain():
    p = Pipeline()
    src = p.add_new("videotestsrc", width=8, height=8, num_buffers=3)
    conv = p.add_new("tensor_converter")
    filt = p.add_new("tensor_filter", model=lambda x: x)
    sink = p.add_new("tensor_sink")
    Pipeline.link(src, conv, filt, sink)
    p.run(timeout=60)


def _run_query_roundtrip():
    port = free_port()
    sp = Pipeline("server")
    ssrc = sp.add_new("tensor_query_serversrc", host="127.0.0.1",
                      port=port, id=0, dims="4:1", types="float32")
    filt = sp.add_new("tensor_filter", model=lambda x: x * 2)
    ssink = sp.add_new("tensor_query_serversink", id=0)
    Pipeline.link(ssrc, filt, ssink)
    sp.start()
    try:
        time.sleep(0.2)
        caps = Caps.tensors(TensorsConfig(
            TensorsInfo.from_strings("4:1", "float32"), 30))
        cp = Pipeline("client")
        src = cp.add_new("appsrc", caps=caps,
                         data=[np.full((1, 4), i, np.float32)
                               for i in range(3)])
        qc = cp.add_new("tensor_query_client", host="127.0.0.1", port=port)
        sink = cp.add_new("tensor_sink", store=True)
        Pipeline.link(src, qc, sink)
        cp.run(timeout=60)
        assert sink.num_buffers == 3
    finally:
        sp.stop()


def _run_engine_workload():
    params = causal_lm.init_causal_lm(
        jax.random.PRNGKey(0), V, D, H, L, MAXLEN)
    eng = LMEngine(params, H, MAXLEN, n_slots=2, chunk=4)
    rids = [eng.submit(np.arange(1, 5 + i, dtype=np.int32), max_new=4)
            for i in range(2)]
    res = eng.run()
    assert all(len(res[r]) == 4 for r in rids)


def _series(text, family):
    """Sample lines of `family` (incl. _bucket/_sum/_count children)."""
    return [ln for ln in text.splitlines()
            if ln.startswith(family) and not ln.startswith("#")]


def test_all_three_layers_visible_in_one_scrape(metrics_on):
    _run_element_chain()
    _run_query_roundtrip()
    _run_engine_workload()

    with start_exporter(port=0) as exp:
        text = urllib.request.urlopen(exp.url, timeout=10).read().decode()

    # every non-empty line is valid exposition syntax
    for ln in text.splitlines():
        assert _LINE_RE.match(ln), f"malformed exposition line: {ln!r}"

    # pipeline layer: per-element buffer counts + proctime histogram
    assert _series(text, "nnstpu_pipeline_buffers_total")
    assert _series(text, "nnstpu_pipeline_proctime_seconds_bucket")

    # query layer: messages by direction/cmd and an RTT histogram
    msgs = _series(text, "nnstpu_query_messages_total")
    assert any('direction="sent"' in ln for ln in msgs)
    assert any('direction="recv"' in ln for ln in msgs)
    assert _series(text, "nnstpu_query_bytes_total")
    assert _series(text, "nnstpu_query_roundtrip_seconds_count")

    # serving layer: stream lifecycle, TTFT, token throughput
    streams = _series(text, "nnstpu_serving_streams_total")
    assert any('event="admitted"' in ln for ln in streams)
    assert any('event="completed"' in ln for ln in streams)
    assert _series(text, "nnstpu_serving_ttft_seconds_count")
    assert _series(text, "nnstpu_serving_tokens_total")


def test_engine_slot_gauges_live_and_release(metrics_on):
    _run_engine_workload()
    snap = obs_metrics.registry().snapshot()
    slots = {tuple(s["labels"].items()): s["value"]
             for s in snap["nnstpu_serving_active_slots"]["series"]}
    # workload has drained; the weakref gauge reads 0 (or the engine is
    # already collected and the callback degrades to 0) — never raises
    assert slots[(("engine", "lm"),)] == 0
    prefills = snap["nnstpu_serving_prefills_total"]["series"]
    assert sum(s["value"] for s in prefills) >= 2


def test_query_inflight_gauge_registered(metrics_on):
    _run_query_roundtrip()
    snap = obs_metrics.registry().snapshot()
    depth = snap["nnstpu_query_inflight_depth"]["series"]
    assert all(s["value"] == 0 for s in depth)  # all drained at EOS
    rec = snap["nnstpu_query_reconnects_total"]["series"]
    assert sum(s["value"] for s in rec) >= 1
