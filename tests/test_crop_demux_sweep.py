"""tensor_crop / tensor_demux / tensor_split edge-case sweeps.

Reference model: gst/nnstreamer/elements/gsttensor_crop.c (clipping,
multi-region, zero-region frames), tensor_demux tensorpick variants, and
tensor_split tensorseg slicing (tests/nnstreamer_demux, nnstreamer_split
SSAT groups).
"""

import numpy as np
import pytest

from nnstreamer_tpu.core import Caps
from nnstreamer_tpu.core.buffer import Buffer
from nnstreamer_tpu.core.types import TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline

MS = 1_000_000


def caps_of(dims, types):
    return Caps.tensors(TensorsConfig(TensorsInfo.from_strings(dims, types)))


def run_crop(img, boxes_per_frame):
    p = Pipeline()
    h, w, c = img.shape
    n = len(boxes_per_frame)
    raw = p.add_new("appsrc", caps=caps_of(f"{c}:{w}:{h}:1", "uint8"),
                    data=[Buffer.of(img[None], pts=i * 33 * MS,
                                    duration=33 * MS) for i in range(n)])
    info = p.add_new(
        "appsrc", caps=caps_of("4:4", "int32"),
        data=[Buffer.of(np.asarray(b, np.int32), pts=i * 33 * MS,
                        duration=33 * MS)
              for i, b in enumerate(boxes_per_frame)])
    crop = p.add_new("tensor_crop")
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(raw, crop)     # raw pad
    Pipeline.link(info, crop)    # info pad
    Pipeline.link(crop, sink)
    p.run(timeout=60)
    return sink


@pytest.fixture(scope="module")
def img():
    rng = np.random.default_rng(0)
    return rng.integers(0, 255, (16, 20, 3)).astype(np.uint8)


class TestCrop:
    def test_multi_region_values(self, img):
        boxes = [[[2, 3, 5, 4], [0, 0, 20, 16]]]
        sink = run_crop(img, boxes)
        assert sink.num_buffers == 1
        mems = sink.buffers[0].memories
        assert len(mems) == 2
        np.testing.assert_array_equal(mems[0].host(), img[3:7, 2:7])
        np.testing.assert_array_equal(mems[1].host(), img)

    def test_out_of_bounds_boxes_clipped(self, img):
        sink = run_crop(img, [[[18, 14, 10, 10]]])
        got = sink.buffers[0].memories[0].host()
        np.testing.assert_array_equal(got, img[14:16, 18:20])

    def test_per_frame_region_counts_vary(self, img):
        sink = run_crop(img, [[[0, 0, 4, 4]],
                              [[0, 0, 4, 4], [4, 4, 4, 4], [8, 8, 4, 4]]])
        assert sink.num_buffers == 2
        assert len(sink.buffers[0].memories) == 1
        assert len(sink.buffers[1].memories) == 3


class TestDemuxPicks:
    def _run(self, tensorpick, n_pads):
        p = Pipeline()
        frames = [Buffer.from_arrays(
            [np.full((2,), 10 * t + i, np.float32) for i in range(4)],
            pts=t * 33 * MS) for t in range(3)]
        src = p.add_new("appsrc",
                        caps=caps_of("2,2,2,2", ",".join(["float32"] * 4)),
                        data=frames)
        demux = p.add_new("tensor_demux", tensorpick=tensorpick)
        sinks = [p.add_new("tensor_sink", store=True) for _ in range(n_pads)]
        Pipeline.link(src, demux)
        for s in sinks:
            Pipeline.link(demux, s)
        p.run(timeout=60)
        return sinks

    def test_single_picks(self):
        sinks = self._run("0,2", 2)
        for t in range(3):
            assert sinks[0].buffers[t].memories[0].host()[0] == 10 * t
            assert sinks[1].buffers[t].memories[0].host()[0] == 10 * t + 2

    def test_grouped_pick_emits_multi_tensor(self):
        sinks = self._run("0:1,3", 2)
        b = sinks[0].buffers[0]
        assert b.num_tensors == 2
        assert b.memories[1].host()[0] == 1
        assert sinks[1].buffers[0].memories[0].host()[0] == 3

    def test_no_pick_fans_out_all(self):
        sinks = self._run(None, 4)
        assert all(s.num_buffers == 3 for s in sinks)


class TestSplitSegs:
    def test_tensorseg_slices(self):
        p = Pipeline()
        arr = np.arange(12, dtype=np.float32).reshape(1, 12)
        src = p.add_new("appsrc", caps=caps_of("12:1", "float32"),
                        data=[arr] * 2)
        split = p.add_new("tensor_split", tensorseg="3,4,5")
        sinks = [p.add_new("tensor_sink", store=True) for _ in range(3)]
        Pipeline.link(src, split)
        for s in sinks:
            Pipeline.link(split, s)
        p.run(timeout=60)
        np.testing.assert_array_equal(sinks[0].buffers[0].memories[0].host(),
                                      arr[:, :3])
        np.testing.assert_array_equal(sinks[1].buffers[0].memories[0].host(),
                                      arr[:, 3:7])
        np.testing.assert_array_equal(sinks[2].buffers[0].memories[0].host(),
                                      arr[:, 7:])
