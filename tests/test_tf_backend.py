"""framework=tensorflow: the reference's frozen GraphDef models served verbatim.

Reference: ext/nnstreamer/tensor_filter/tensor_filter_tensorflow.cc and
tests/nnstreamer_filter_tensorflow/runTest.sh — mnist.pb (9.raw → argmax 9)
and conv_actions_frozen.pb (yes.wav through a DT_STRING input → argmax 2).
"""

import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from nnstreamer_tpu.graph.parse import parse_pipeline  # noqa: E402

MODELS = "/root/reference/tests/test_models/models"
DATA = "/root/reference/tests/test_models/data"

needs_ref = pytest.mark.skipif(
    not os.path.isfile(os.path.join(MODELS, "mnist.pb")),
    reason="reference test models not mounted")

# runTest.sh:78, verbatim apart from mounted paths
MNIST = (
    "filesrc location={data} ! application/octet-stream ! "
    "tensor_converter input-dim=784:1 input-type=uint8 ! "
    "tensor_transform mode=arithmetic option=typecast:float32,add:-127.5,div:127.5 ! "
    "tensor_filter framework=tensorflow model={model} "
    "input=784:1 inputtype=float32 inputname=input "
    "output=10:1 outputtype=float32 outputname=softmax ! "
    "filesink location={out}"
)

# runTest.sh:98, verbatim apart from mounted paths
SPEECH = (
    "filesrc location={data} blocksize=-1 ! application/octet-stream ! "
    "tensor_converter input-dim=1:16022 input-type=int16 ! "
    "tensor_filter framework=tensorflow model={model} "
    "input=1:16022 inputtype=int16 inputname=wav_data "
    "output=12:1 outputtype=float32 outputname=labels_softmax ! "
    "filesink location={out}"
)


@needs_ref
def test_reference_mnist_pb_golden(tmp_path):
    out = tmp_path / "tensorfilter.out.1.log"
    p = parse_pipeline(MNIST.format(
        data=os.path.join(DATA, "9.raw"),
        model=os.path.join(MODELS, "mnist.pb"), out=out))
    p.run(timeout=120)
    scores = np.frombuffer(out.read_bytes(), np.float32)
    assert scores.size == 10
    assert int(scores.argmax()) == 9  # checkLabel.py semantics


@needs_ref
def test_reference_speech_pb_string_input_golden(tmp_path):
    """conv_actions_frozen.pb has a DT_STRING input (wav_data); the raw
    int16 buffer is fed as one scalar string — answer index 2 ('yes')."""
    out = tmp_path / "tensorfilter.out.3.log"
    p = parse_pipeline(SPEECH.format(
        data=os.path.join(DATA, "yes.wav"),
        model=os.path.join(MODELS, "conv_actions_frozen.pb"), out=out))
    p.run(timeout=120)
    scores = np.frombuffer(out.read_bytes(), np.float32)
    assert scores.size == 12
    assert int(scores.argmax()) == 2


@needs_ref
def test_reference_combination_string(tmp_path):
    """runTest.sh:83 verbatim — input-combination=1 picks the mnist tensor
    out of the mux, output-combination=i0,o0 re-emits the video tensor
    alongside the result; demux splits them back."""
    golden = tmp_path / "combi.dummy.golden"
    combi_in = tmp_path / "tensorfilter.combi.in.log"
    out = tmp_path / "tensorfilter.out.1.log"
    s = (
        "videotestsrc pattern=13 num-buffers=1 ! videoconvert ! "
        "video/x-raw,width=640,height=480,framerate=30/1 ! tensor_converter ! "
        "tee name=t "
        f"t. ! queue ! filesink location={golden} buffer-mode=unbuffered sync=false async=false "
        "t. ! queue ! mux.sink_0 "
        f"filesrc location={os.path.join(DATA, '9.raw')} ! application/octet-stream ! "
        "tensor_converter input-dim=784:1 input-type=uint8 ! "
        "tensor_transform mode=arithmetic option=typecast:float32,add:-127.5,div:127.5 ! "
        "mux.sink_1 tensor_mux name=mux ! "
        f"tensor_filter framework=tensorflow model={os.path.join(MODELS, 'mnist.pb')} "
        "input=784:1 inputtype=float32 inputname=input "
        "output=10:1 outputtype=float32 outputname=softmax "
        "input-combination=1 output-combination=i0,o0 ! "
        "tensor_demux name=demux "
        f"demux.src_0 ! queue ! filesink location={combi_in} buffer-mode=unbuffered sync=false async=false "
        f"demux.src_1 ! queue ! filesink location={out} buffer-mode=unbuffered sync=false async=false"
    )
    parse_pipeline(s).run(timeout=120)
    # callCompareTest: the video tensor must pass through byte-exact
    assert golden.read_bytes() == combi_in.read_bytes()
    assert len(golden.read_bytes()) == 640 * 480 * 3
    scores = np.frombuffer(out.read_bytes(), np.float32)
    assert scores.size == 10 and int(scores.argmax()) == 9


@needs_ref
def test_pb_extension_auto_detect(tmp_path):
    """framework=auto resolves .pb → tensorflow via the priority table."""
    out = tmp_path / "o.log"
    s = MNIST.format(
        data=os.path.join(DATA, "9.raw"),
        model=os.path.join(MODELS, "mnist.pb"),
        out=out).replace("framework=tensorflow ", "")
    parse_pipeline(s).run(timeout=120)
    assert int(np.frombuffer(out.read_bytes(), np.float32).argmax()) == 9


@needs_ref
def test_missing_names_clear_error(tmp_path):
    s = MNIST.format(
        data=os.path.join(DATA, "9.raw"),
        model=os.path.join(MODELS, "mnist.pb"),
        out=tmp_path / "o.log").replace("inputname=input ", "")
    with pytest.raises(Exception, match="name"):
        parse_pipeline(s).run(timeout=60)


@needs_ref
def test_wrong_op_name_clear_error(tmp_path):
    s = MNIST.format(
        data=os.path.join(DATA, "9.raw"),
        model=os.path.join(MODELS, "mnist.pb"),
        out=tmp_path / "o.log").replace("inputname=input ", "inputname=nonesuch ")
    with pytest.raises(Exception, match="nonesuch"):
        parse_pipeline(s).run(timeout=60)


@needs_ref
def test_wrong_dtype_clear_error(tmp_path):
    s = MNIST.format(
        data=os.path.join(DATA, "9.raw"),
        model=os.path.join(MODELS, "mnist.pb"),
        out=tmp_path / "o.log").replace(
        "inputtype=float32", "inputtype=int32").replace(
        "typecast:float32", "typecast:int32")
    with pytest.raises(Exception, match="int32|float32"):
        parse_pipeline(s).run(timeout=60)


@needs_ref
def test_wrong_output_dims_clear_error(tmp_path):
    """runTest 3F_n analog: output=5:1 against a 10-element graph output."""
    s = MNIST.format(
        data=os.path.join(DATA, "9.raw"),
        model=os.path.join(MODELS, "mnist.pb"),
        out=tmp_path / "o.log").replace("output=10:1 ", "output=5:1 ")
    with pytest.raises(Exception, match="output"):
        parse_pipeline(s).run(timeout=60)


@needs_ref
def test_not_a_graphdef_clear_error(tmp_path):
    bad = tmp_path / "model.pb"
    bad.write_bytes(b"\xff\xfe not a protobuf")
    s = MNIST.format(
        data=os.path.join(DATA, "9.raw"), model=bad, out=tmp_path / "o.log")
    with pytest.raises(Exception, match="GraphDef"):
        parse_pipeline(s).run(timeout=60)
