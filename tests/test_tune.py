"""tune/ tests — store roundtrip and merge semantics, cost-model
determinism, the tuner's resolution order (store → model → bounded
sweep → default), the zero-overhead-when-off contract, and fleet
federation of tuned configs (push doc, tuned_view merge, push-ack
adoption including the real HTTP exporter loop)."""

import json
import urllib.request

import pytest

from nnstreamer_tpu import tune
from nnstreamer_tpu.obs import fleet as obs_fleet
from nnstreamer_tpu.obs import health as obs_health
from nnstreamer_tpu.obs.exporter import start_exporter
from nnstreamer_tpu.obs.fleet import FleetAggregator, FleetPusher, build_push
from nnstreamer_tpu.obs.metrics import MetricsRegistry
from nnstreamer_tpu.obs.tracing import SpanStore
from nnstreamer_tpu.tune.model import CostModel
from nnstreamer_tpu.tune.store import MAX_PUSH_ENTRIES, TuneStore
from nnstreamer_tpu.tune.tuner import Tuner, shape_sig


@pytest.fixture
def tune_off_after():
    """Whatever a test installs on the module hooks, put it back."""
    yield tune
    tune.disable(save=False)
    obs_fleet.TUNE_PUSH_HOOK = None
    obs_fleet.TUNE_ADOPT_HOOK = None


def worker_push(instance, seq=1, tune_doc=None):
    """A synthetic worker push built through the real build_push path
    (private registries), with an optional tune slice attached."""
    doc = build_push(instance, "worker", seq, interval_s=2.0,
                     registry=MetricsRegistry(enabled=True),
                     health_registry=obs_health.HealthRegistry(),
                     span_store=SpanStore())
    if tune_doc is not None:
        doc["tune"] = tune_doc
    return doc


def _samples(device="cpu", label="f", rows=((1e6, 1e4, 50.0),
                                            (2e6, 2e4, 95.0),
                                            (4e6, 4e4, 190.0))):
    """Profiler-shaped sample rows: cost grows with flops+bytes so the
    fit is well-posed (positive coefficients)."""
    return [{"label": label, "device": device, "flops": f, "bytes": b,
             "mean_device_us": c} for f, b, c in rows]


# --------------------------------------------------------------------------- #
# Store
# --------------------------------------------------------------------------- #

class TestStore:
    def test_roundtrip(self, tmp_path):
        p = str(tmp_path / "t.json")
        s = TuneStore(p)
        s.put("cpu", "flash", "b8.l2048", "flash_blocks",
              [512, 1024], "sweep", cost_us=42.5)
        s.put("cpu", "lm", "s4.l256", "lm_chunk", 16, "model")
        assert s.dirty
        assert s.save() == p
        assert not s.dirty

        s2 = TuneStore(p)
        rec = s2.get("cpu", "flash", "b8.l2048", "flash_blocks")
        assert rec["value"] == [512, 1024]
        assert rec["source"] == "sweep"
        assert rec["cost_us"] == 42.5
        assert s2.get("cpu", "lm", "s4.l256", "lm_chunk")["value"] == 16
        assert not s2.dirty

    def test_unsupported_version_raises(self, tmp_path):
        p = tmp_path / "bad.json"
        p.write_text(json.dumps({"version": 99, "entries": {}}))
        with pytest.raises(ValueError, match="version"):
            TuneStore(str(p))

    def test_merge_adopts_absent_and_lower_cost_only(self):
        s = TuneStore()
        s.put("cpu", "flash", "sig", "k", 512, "sweep", cost_us=10.0)
        doc = {"version": 1, "entries": {
            # absent locally -> adopted
            "cpu|lm|sig|chunk": {"value": 16, "source": "sweep",
                                 "cost_us": 5.0, "ts": 1.0},
            # worse measured cost -> kept out
            "cpu|flash|sig|k": {"value": 128, "source": "sweep",
                                "cost_us": 50.0, "ts": 2.0}}}
        assert s.merge_doc(doc) == 1
        assert s.get("cpu", "lm", "sig", "chunk")["source"] == "fleet"
        assert s.get("cpu", "flash", "sig", "k")["value"] == 512

        # strictly lower measured cost -> replaces the local sweep
        better = {"version": 1, "entries": {
            "cpu|flash|sig|k": {"value": 256, "cost_us": 4.0, "ts": 3.0}}}
        assert s.merge_doc(better) == 1
        rec = s.get("cpu", "flash", "sig", "k")
        assert rec["value"] == 256 and rec["source"] == "fleet"

        # unmeasured remote never displaces a measured local
        unmeasured = {"version": 1, "entries": {
            "cpu|flash|sig|k": {"value": 64, "ts": 9.0}}}
        assert s.merge_doc(unmeasured) == 0
        assert s.merge_doc("junk") == 0
        assert s.merge_doc({"entries": "junk"}) == 0

    def test_push_doc_caps_entries_newest_first(self):
        s = TuneStore()
        for i in range(MAX_PUSH_ENTRIES + 10):
            rec = s.put("cpu", "l", f"s{i}", "k", i, "sweep")
            rec["ts"] = float(i)  # deterministic ordering
        doc = s.to_doc()
        assert len(doc["entries"]) == MAX_PUSH_ENTRIES
        # the oldest 10 fell off, the newest survived
        assert "cpu|l|s0|k" not in doc["entries"]
        assert f"cpu|l|s{MAX_PUSH_ENTRIES + 9}|k" in doc["entries"]


def test_shape_sig():
    assert shape_sig(("b", 8), ("l", 2048)) == "b8.l2048"
    assert shape_sig(("rung", 64)) == "rung64"


# --------------------------------------------------------------------------- #
# Cost model
# --------------------------------------------------------------------------- #

class TestCostModel:
    def test_fit_is_deterministic(self):
        rows = _samples()
        m1, m2 = CostModel(), CostModel()
        assert m1.fit(rows) == 1
        assert m2.fit(list(rows)) == 1
        assert m1.covers("cpu", "f")
        for fl, by in ((1e6, 1e4), (3e6, 3e4), (8e6, 8e4)):
            assert m1.predict("cpu", "f", fl, by) \
                == m2.predict("cpu", "f", fl, by)

    def test_negative_coefficient_means_no_coverage(self):
        # more work measured as FASTER: samples do not span the
        # feature — ranking on this fit would invert candidate order
        rows = _samples(rows=((1e6, 0.0, 100.0), (2e6, 0.0, 50.0),
                              (4e6, 0.0, 25.0)))
        m = CostModel()
        assert m.fit(rows) == 0
        assert not m.covers("cpu", "f")
        assert m.predict("cpu", "f", 1e6, 0.0) is None

    def test_too_few_samples_means_no_coverage(self):
        m = CostModel()
        assert m.fit(_samples(rows=((1e6, 1e4, 50.0),))) == 0
        assert not m.covers("cpu", "f")


# --------------------------------------------------------------------------- #
# Tuner resolution order
# --------------------------------------------------------------------------- #

class TestTunerResolution:
    def test_model_pick_deterministic_across_instances(self):
        """Same samples + same candidates → same config across two
        independent tuners — and the second ask on either is a store
        hit."""
        rows = _samples()

        def features(cand):
            # candidate = multiplier on traffic; flops fixed
            return (1e6, 1e4 * cand)

        picks = []
        for _ in range(2):
            tn = Tuner(store=TuneStore())
            tn.fit(rows)
            v = tn.pick("k", "cpu", "f", "sig", candidates=(4, 2, 1, 8),
                        default=4, features=features)
            picks.append(v)
            assert tn.stats["model_picks"] == 1
            # second ask: resolved from the store, model not consulted
            assert tn.pick("k", "cpu", "f", "sig", candidates=(4, 2, 1, 8),
                           default=4, features=features) == v
            assert tn.stats["store_hits"] == 1
        assert picks[0] == picks[1] == 1  # least traffic wins

    def test_sweep_is_bounded_and_cached(self):
        calls = []

        def measure(cand):
            calls.append(cand)
            return float(cand)  # smaller candidate = faster

        tn = Tuner(store=TuneStore(), max_trials=4, measure_repeats=1)
        v = tn.pick("k", "cpu", "f", "sig",
                    candidates=(9, 3, 7, 5, 2, 1, 8, 6, 4, 10),
                    default=9, measure=measure)
        assert v == 3  # best of the FIRST max_trials candidates only
        assert len(calls) == 4
        assert tn.stats["trials"] == 4
        rec = tn.store.get("cpu", "f", "sig", "k")
        assert rec["source"] == "sweep" and rec["cost_us"] == 3e6

        # warm ask: store hit, zero further measurement
        assert tn.pick("k", "cpu", "f", "sig", candidates=(9, 3),
                       default=9, measure=measure) == 3
        assert len(calls) == 4
        assert tn.stats["sweeps"] == 1

    def test_sweep_total_failure_falls_back_to_default(self):
        def broken(cand):
            raise RuntimeError("no device")

        tn = Tuner(store=TuneStore(), measure_repeats=1)
        assert tn.pick("k", "cpu", "f", "sig", candidates=(1, 2),
                       default=7, measure=broken) == 7
        assert tn.stats["defaults"] == 1
        assert tn.store.get("cpu", "f", "sig", "k") is None  # may retry

    def test_measured_tie_breaks_by_candidate_order(self):
        tn = Tuner(store=TuneStore(), measure_repeats=1)
        v = tn.pick("k", "cpu", "f", "sig", candidates=(5, 3, 8),
                    default=8, measure=lambda c: 1.0)
        assert v == 5

    def test_observe_persists_like_a_sweep(self):
        tn = Tuner(store=TuneStore())
        tn.observe("lm_spec_draft", "cpu", "serving.lm", "s4", 6)
        assert tn.pick("lm_spec_draft", "cpu", "serving.lm", "s4",
                       candidates=(), default=4) == 6
        assert tn.stats["store_hits"] == 1


# --------------------------------------------------------------------------- #
# Zero overhead when off
# --------------------------------------------------------------------------- #

class TestTuneOff:
    def test_flash_blocks_default_without_hook(self, tune_off_after):
        """TUNE_HOOK is None → the flash call site returns its hand-set
        blocks without measuring, building arrays, or touching a store."""
        from nnstreamer_tpu.ops.pallas import flash_attention as fa

        assert tune.TUNE_HOOK is None
        # None operands prove the gate short-circuits before any shape
        # inspection — the hook check is the FIRST thing in the helper
        assert fa._tuned_blocks(None, None, None, False, True) \
            == fa._DEFAULT_BLOCKS

    def test_push_doc_unchanged_without_hook(self, tune_off_after):
        assert obs_fleet.TUNE_PUSH_HOOK is None
        assert worker_push("w1:1").get("tune") is None

    def test_enable_disable_lifecycle(self, tmp_path, tune_off_after):
        p = str(tmp_path / "store.json")
        tn = tune.enable(p, fit_from_profiler=False)
        assert tune.enabled() and tune.tuner() is tn
        assert tune.enable(p) is tn  # idempotent
        assert obs_fleet.TUNE_PUSH_HOOK == tn.push_doc
        assert obs_fleet.TUNE_ADOPT_HOOK == tn.adopt
        tn.store.put("cpu", "f", "sig", "k", 1, "sweep")
        tune.disable()
        assert not tune.enabled()
        assert obs_fleet.TUNE_PUSH_HOOK is None
        assert obs_fleet.TUNE_ADOPT_HOOK is None
        # disable persisted the dirty store
        assert TuneStore(p).get("cpu", "f", "sig", "k")["value"] == 1


# --------------------------------------------------------------------------- #
# Fleet federation
# --------------------------------------------------------------------------- #

class TestFleetFederation:
    def test_push_doc_carries_store(self, tune_off_after):
        tn = Tuner(store=TuneStore())
        tn.store.put("cpu", "flash", "sig", "k", [512, 1024], "sweep",
                     cost_us=10.0)
        obs_fleet.TUNE_PUSH_HOOK = tn.push_doc
        doc = worker_push("w1:1")
        assert doc["tune"]["entries"]["cpu|flash|sig|k"]["value"] \
            == [512, 1024]

    def test_tuned_view_merges_lowest_cost(self):
        agg = FleetAggregator(span_store=SpanStore())
        agg.ingest(worker_push("w1:1", tune_doc={"version": 1, "entries": {
            "cpu|f|s|k": {"value": 512, "cost_us": 20.0, "ts": 1.0},
            "cpu|f|s|k2": {"value": 1, "ts": 1.0}}}))
        agg.ingest(worker_push("w2:1", tune_doc={"version": 1, "entries": {
            "cpu|f|s|k": {"value": 256, "cost_us": 5.0, "ts": 0.5},
            "cpu|f|s|k2": {"value": 2, "ts": 2.0}}}))
        view = agg.tuned_view()
        # measured: lowest cost wins regardless of age
        assert view["entries"]["cpu|f|s|k"]["value"] == 256
        # both unmeasured: newest ts wins
        assert view["entries"]["cpu|f|s|k2"]["value"] == 2

    def test_tuned_view_none_before_any_tune_push(self):
        agg = FleetAggregator(span_store=SpanStore())
        agg.ingest(worker_push("w1:1"))
        assert agg.tuned_view() is None

    def test_adoption_skips_the_sweep(self, tune_off_after):
        """A fresh instance that adopted the fleet's config must answer
        from the store — its measure closure never runs."""
        agg = FleetAggregator(span_store=SpanStore())
        agg.ingest(worker_push("w1:1", tune_doc={"version": 1, "entries": {
            "cpu|f|sig|k": {"value": 3, "cost_us": 2.0, "ts": 1.0}}}))
        fresh = Tuner(store=TuneStore())
        assert fresh.adopt(agg.tuned_view()) == 1
        assert fresh.stats["adopted"] == 1

        def never(cand):
            raise AssertionError("sweep ran despite fleet adoption")

        assert fresh.pick("k", "cpu", "f", "sig", candidates=(1, 2, 3),
                          default=1, measure=never) == 3

    def test_push_ack_adoption_over_http(self, tune_off_after):
        """The real loop: aggregator already knows a tuned config, a
        fresh worker's FIRST push-ack delivers it into the worker's
        store via TUNE_ADOPT_HOOK."""
        agg = obs_fleet.enable_aggregator(ttl_s=30.0)
        try:
            agg.ingest(worker_push("w1:1", tune_doc={
                "version": 1, "entries": {
                    "cpu|flash|sig|k": {"value": [512, 1024],
                                        "cost_us": 7.0, "ts": 1.0}}}))
            fresh = Tuner(store=TuneStore())
            obs_fleet.TUNE_PUSH_HOOK = fresh.push_doc
            obs_fleet.TUNE_ADOPT_HOOK = fresh.adopt
            with start_exporter(port=0,
                                registry=MetricsRegistry(enabled=True)) as exp:
                psh = FleetPusher(
                    url=f"http://127.0.0.1:{exp.port}", interval_s=3600,
                    instance="w2:1",
                    registry=MetricsRegistry(enabled=True),
                    health_registry=obs_health.HealthRegistry(),
                    span_store=SpanStore())
                try:
                    assert psh.push_now() is True
                finally:
                    psh.close()
            rec = fresh.store.get("cpu", "flash", "sig", "k")
            assert rec is not None
            assert rec["value"] == [512, 1024] and rec["source"] == "fleet"
        finally:
            obs_fleet.disable_aggregator()

    def test_debug_tune_route(self, tune_off_after, tmp_path):
        tn = tune.enable(str(tmp_path / "s.json"), fit_from_profiler=False)
        tn.store.put("cpu", "f", "sig", "k", 1, "sweep", cost_us=3.0)
        with start_exporter(port=0,
                            registry=MetricsRegistry(enabled=True)) as exp:
            url = f"http://127.0.0.1:{exp.port}/debug/tune"
            with urllib.request.urlopen(url, timeout=5) as r:
                body = json.loads(r.read())
        assert body["enabled"] is True
        assert "cpu|f|sig|k" in body["local"]["entries"]
