"""Tensor-parallel KV-cache decode (parallel/tp_decode.py).

Exactness vs the single-device decode loop on the virtual 8-device CPU
mesh: greedy tokens must match token-for-token (logits only to float
tolerance — psum reduction order differs from a fused matmul), with the
cache prefilled on one device and resharded head-major.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from nnstreamer_tpu.models import causal_lm
from nnstreamer_tpu.parallel.tp_decode import (
    make_tp_generate, tp_shard_cache, tp_shard_params)

V, D, H, L, MAXLEN = 89, 64, 8, 3, 96


@pytest.fixture(scope="module")
def params():
    return causal_lm.init_causal_lm(
        jax.random.PRNGKey(11), V, D, H, L, MAXLEN)


def _single_device_generate(params, prompt, n_steps):
    logits, kc, vc, pos = causal_lm.lm_prefill(
        params, jnp.asarray(prompt), H, MAXLEN)
    first = jnp.argmax(logits, -1)[:, None].astype(jnp.int32)
    toks, tok = [], first
    for _ in range(n_steps):
        lg, kc, vc, pos = causal_lm.lm_decode_step(
            params, tok, kc, vc, pos, H)
        tok = jnp.argmax(lg, -1)[:, None].astype(jnp.int32)
        toks.append(np.asarray(tok[:, 0]))
    return first, np.stack(toks, 1)  # (B, n_steps)


@pytest.mark.parametrize("n_model", [2, 4, 8])
def test_tp_decode_matches_single_device(params, n_model):
    if len(jax.devices()) < n_model:
        pytest.skip("needs virtual multi-device CPU")
    mesh = Mesh(np.array(jax.devices()[:n_model]), ("model",))
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, V, (2, 13)).astype(np.int32)
    first, want = _single_device_generate(params, prompt, 20)

    logits, kc, vc, pos = causal_lm.lm_prefill(
        params, jnp.asarray(prompt), H, MAXLEN)
    tp = tp_shard_params(params, H, mesh)
    kc_tp, vc_tp = tp_shard_cache(kc, vc, L, 2, H, mesh)
    gen = make_tp_generate(H, MAXLEN, mesh)
    got = np.asarray(gen(tp, first, kc_tp, vc_tp, pos, 20))
    np.testing.assert_array_equal(got, want)


def test_tp_requires_divisible_heads(params):
    if len(jax.devices()) < 3:
        pytest.skip("needs virtual multi-device CPU")
    mesh = Mesh(np.array(jax.devices()[:3]), ("model",))
    with pytest.raises(ValueError):
        tp_shard_params(params, H, mesh)  # 8 % 3 != 0


def test_tp_generate_is_one_executable_per_length(params):
    if len(jax.devices()) < 2:
        pytest.skip("needs virtual multi-device CPU")
    mesh = Mesh(np.array(jax.devices()[:2]), ("model",))
    prompt = np.arange(6, dtype=np.int32)[None]
    logits, kc, vc, pos = causal_lm.lm_prefill(
        params, jnp.asarray(prompt), H, MAXLEN)
    first = jnp.argmax(logits, -1)[:, None].astype(jnp.int32)
    tp = tp_shard_params(params, H, mesh)
    gen = make_tp_generate(H, MAXLEN, mesh)
    outs = []
    for _ in range(2):  # second call hits the compiled cache
        kc_tp, vc_tp = tp_shard_cache(kc, vc, L, 1, H, mesh)
        outs.append(np.asarray(gen(tp, first, kc_tp, vc_tp, pos, 8)))
    np.testing.assert_array_equal(outs[0], outs[1])
    assert len(gen.compiled) == 1  # one executable per distinct n_steps
    with pytest.raises(ValueError):  # overflow is loud, not NaN-argmax
        gen(tp, first, kc_tp, vc_tp, pos, MAXLEN + 1)
