"""nnstreamer_tpu.sched — multi-tenant device dispatch engine.

Covers the ISSUE-11 acceptance pins: weighted-DRR fairness and the hard
starvation bound (fake clock, no sleeps), coalesced outputs bit-identical
to direct invokes, per-tenant deadline shedding riding resilience
accounting, the zero-overhead-when-off contract on the graph hot path,
the bounded bucket ladder in filters/xla.py, and the 8-concurrent-
pipelines E2E whose outputs must match serial runs exactly.
"""

import numpy as np
import pytest

from nnstreamer_tpu import sched
from nnstreamer_tpu.core.buffer import TensorMemory
from nnstreamer_tpu.graph import Pipeline
from nnstreamer_tpu.sched import SHED, DeviceEngine


class FakeClock:
    """Injectable monotonic-seconds source (no sleeping in fairness
    tests)."""

    def __init__(self) -> None:
        self.t = 0.0

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class TagFilter:
    """Minimal filter double: distinct instances never coalesce with
    each other (the coalesce key includes id(filt))."""

    def __init__(self, name="f", log=None):
        self.name = name
        self.log = log if log is not None else []

    def invoke(self, inputs):
        self.log.append(self.name)
        return [inputs[0].host() * 2]


def _mem():
    return TensorMemory(np.ones((2, 2), np.float32))


@pytest.fixture
def metrics_on():
    """Counters are the registry's cheap no-op while collection is off;
    these tests assert on values, so turn it on and restore after."""
    from nnstreamer_tpu.obs import metrics

    reg = metrics.registry()
    was = reg.is_enabled
    reg.enable()
    yield reg
    if not was:
        reg.disable()


# -- fairness ---------------------------------------------------------------- #

def test_drr_service_tracks_weights():
    clock = FakeClock()
    eng = DeviceEngine("t", autostart=False, clock=clock, max_coalesce=1)
    a = eng.register("a", weight=3.0)
    b = eng.register("b", weight=1.0)
    fa, fb = TagFilter("a"), TagFilter("b")
    for _ in range(40):
        a.submit(fa, [_mem()])
        b.submit(fb, [_mem()])
    for _ in range(40):
        assert eng.step()
    total = a.stats["completed"] + b.stats["completed"]
    assert total == 40
    # weight 3:1 → a gets ~30 of the first 40 services
    assert 26 <= a.stats["completed"] <= 34


def test_equal_weights_alternate():
    clock = FakeClock()
    eng = DeviceEngine("t", autostart=False, clock=clock, max_coalesce=1)
    a = eng.register("a")
    b = eng.register("b")
    order = []
    fa, fb = TagFilter("a", order), TagFilter("b", order)
    for _ in range(6):
        a.submit(fa, [_mem()])
        b.submit(fb, [_mem()])
    for _ in range(12):
        eng.step()
    # round-robin cursor: neither tenant serves 3+ in a row
    for i in range(len(order) - 2):
        assert len(set(order[i:i + 3])) > 1
    assert order.count("a") == order.count("b") == 6


def test_priority_class_served_first():
    clock = FakeClock()
    eng = DeviceEngine("t", autostart=False, clock=clock, max_coalesce=1)
    low = eng.register("low", priority=0)
    high = eng.register("high", priority=1)
    order = []
    fl, fh = TagFilter("low", order), TagFilter("high", order)
    for _ in range(3):
        low.submit(fl, [_mem()])
        high.submit(fh, [_mem()])
    for _ in range(6):
        eng.step()
    # inside the starvation bound, the higher class drains completely
    # before the lower one sees the device
    assert order == ["high"] * 3 + ["low"] * 3


def test_starvation_bound_forces_service():
    clock = FakeClock()
    eng = DeviceEngine("t", autostart=False, clock=clock,
                       max_coalesce=1, starve_ms=100.0)
    low = eng.register("low", priority=0)
    high = eng.register("high", priority=1)
    order = []
    fl, fh = TagFilter("low", order), TagFilter("high", order)
    low.submit(fl, [_mem()])
    for _ in range(8):
        high.submit(fh, [_mem()])
    for _ in range(3):
        eng.step()
    assert order == ["high"] * 3  # low bypassed while inside the bound
    clock.advance(0.15)  # past starve_ms
    eng.step()
    assert order[-1] == "low"
    assert eng.stats["starvation_reliefs"] >= 1
    assert low.stats["completed"] == 1


def test_starved_tenant_wait_never_exceeds_bound_plus_service():
    """The acceptance pin: with continuous competing load, no tenant's
    dispatch wait exceeds the fairness bound by more than one service
    round."""
    clock = FakeClock()
    eng = DeviceEngine("t", autostart=False, clock=clock,
                       max_coalesce=1, starve_ms=50.0)
    heavy = eng.register("heavy", weight=100.0)
    meek = eng.register("meek", weight=0.01)
    fh, fm = TagFilter("heavy"), TagFilter("meek")
    for _ in range(200):
        heavy.submit(fh, [_mem()])
    meek.submit(fm, [_mem()])
    while meek.stats["completed"] == 0:
        eng.step()
        clock.advance(0.01)  # 10ms per service round
    # bound: starve_ms plus one relief round-robin lap (|tenants| = 2)
    assert meek.waits[-1] <= 0.05 + 2 * 0.01 + 1e-6


# -- coalescing --------------------------------------------------------------- #

class CoalesceFilter:
    """Counts invocation modes; invoke_coalesced mirrors XLAFilter's
    contract (per-group output lists, order-aligned)."""

    def __init__(self):
        self.serial = 0
        self.coalesced = 0

    def invoke(self, inputs):
        self.serial += 1
        return [inputs[0].host() + 1]

    def invoke_coalesced(self, groups):
        self.coalesced += 1
        return [[g[0].host() + 1] for g in groups]


def test_same_key_heads_coalesce_across_tenants():
    clock = FakeClock()
    eng = DeviceEngine("t", autostart=False, clock=clock, max_coalesce=8)
    filt = CoalesceFilter()
    futs = [eng.register(f"t{i}").submit(filt, [_mem()]) for i in range(4)]
    assert eng.step()
    assert filt.coalesced == 1 and filt.serial == 0
    for f in futs:
        np.testing.assert_array_equal(
            np.asarray(f.result(1.0)[0]), np.full((2, 2), 2, np.float32))
    assert eng.coalesce_stats()["max"] == 4


def test_coalesce_failure_falls_back_to_serial():
    class Broken(CoalesceFilter):
        def invoke_coalesced(self, groups):
            raise RuntimeError("not coalescible after all")

    clock = FakeClock()
    eng = DeviceEngine("t", autostart=False, clock=clock)
    filt = Broken()
    futs = [eng.register(f"t{i}").submit(filt, [_mem()]) for i in range(3)]
    eng.step()
    assert eng.stats["coalesce_fallbacks"] == 1
    assert filt.serial == 3
    for f in futs:
        assert f.result(1.0)[0].shape == (2, 2)


def test_xla_coalesced_bit_identical_to_direct_invoke():
    """invoke_coalesced concatenates groups into ONE dispatch; every
    scattered row must equal the direct per-item invoke exactly."""
    import jax.numpy as jnp

    from nnstreamer_tpu.filters.base import FilterProps
    from nnstreamer_tpu.filters.xla import XLAFilter

    rng = np.random.default_rng(11)
    w = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))

    def model(x):
        return jnp.tanh(x @ w)

    f = XLAFilter()
    f.open(FilterProps(model=model))
    items = [[TensorMemory(rng.normal(size=(4, 16)).astype(np.float32))]
             for _ in range(5)]
    direct = [np.asarray(f.invoke(g)[0].host()) for g in items]
    together = f.invoke_coalesced(items)
    assert len(together) == len(items)
    for got, want in zip(together, direct):
        np.testing.assert_array_equal(np.asarray(got[0].host()), want)


def test_xla_coalesced_bucketed_bit_identical():
    import jax.numpy as jnp

    from nnstreamer_tpu.filters.base import FilterProps
    from nnstreamer_tpu.filters.xla import XLAFilter

    f = XLAFilter()
    f.open(FilterProps(model=lambda x: jnp.asarray(x) * 3.0,
                       custom="bucket=4"))
    rng = np.random.default_rng(3)
    groups = [[TensorMemory(rng.normal(size=(2, 2)).astype(np.float32))
               for _ in range(k)] for k in (1, 3, 2)]
    direct = [np.asarray(f.invoke(g)[0].host()) for g in groups]
    together = f.invoke_coalesced(groups)
    for got, want in zip(together, direct):
        np.testing.assert_array_equal(np.asarray(got[0].host()), want)


@pytest.mark.filterwarnings("ignore:Some donated buffers were not usable")
def test_xla_coalesced_donating_bit_identical():
    """donate=True routes the concatenated scratch buffer through the
    donating jit twin — outputs must be bit-identical to the
    non-donating coalesce AND to the direct per-group invoke (donation
    changes buffer ownership, never arithmetic)."""
    import jax.numpy as jnp

    from nnstreamer_tpu.filters.base import FilterProps
    from nnstreamer_tpu.filters.xla import XLAFilter

    rng = np.random.default_rng(17)
    w = jnp.asarray(rng.normal(size=(16, 8)).astype(np.float32))

    def model(x):
        return jnp.tanh(x @ w)

    f = XLAFilter()
    f.open(FilterProps(model=model))
    assert f.supports_donate_coalesce

    def groups():
        g = np.random.default_rng(23)
        return [[TensorMemory(g.normal(size=(4, 16)).astype(np.float32))]
                for _ in range(5)]

    direct = [np.asarray(f.invoke(g)[0].host()) for g in groups()]
    plain = f.invoke_coalesced(groups())
    donated = f.invoke_coalesced(groups(), donate=True)
    assert len(plain) == len(donated) == len(direct)
    for got_d, got_p, want in zip(donated, plain, direct):
        np.testing.assert_array_equal(np.asarray(got_p[0].host()), want)
        np.testing.assert_array_equal(np.asarray(got_d[0].host()), want)


def test_engine_donates_through_coalesce_gate():
    """The engine's batched dispatch passes donate=True only to filters
    that advertise supports_donate_coalesce — legacy coalescible
    filters keep the old call shape (no TypeError → no silent
    permanent serial fallback)."""
    class Donatable(CoalesceFilter):
        supports_donate_coalesce = True

        def __init__(self):
            super().__init__()
            self.donate_flags = []

        def invoke_coalesced(self, groups, donate=False):
            self.donate_flags.append(donate)
            return super().invoke_coalesced(groups)

    clock = FakeClock()
    eng = DeviceEngine("t", autostart=False, clock=clock)
    filt = Donatable()
    futs = [eng.register(f"t{i}").submit(filt, [_mem()]) for i in range(3)]
    assert eng.step()
    assert filt.donate_flags == [True]
    for f in futs:
        assert f.result(1.0)[0].shape == (2, 2)

    legacy = CoalesceFilter()  # no donate kwarg at all
    futs = [eng.register(f"u{i}").submit(legacy, [_mem()]) for i in range(2)]
    assert eng.step()
    assert legacy.coalesced == 1 and legacy.serial == 0
    for f in futs:
        assert f.result(1.0)[0].shape == (2, 2)


# -- bounded bucket ladder (filters/xla.py bugfix) --------------------------- #

def test_bucket_ladder_capped_and_chunked(metrics_on):
    """More tensors than bucket_max used to compile a fresh unbounded
    shape; now the invoke chunks at the cap and stays correct."""
    import jax.numpy as jnp

    from nnstreamer_tpu.filters.base import FilterProps
    from nnstreamer_tpu.filters.xla import XLAFilter
    from nnstreamer_tpu.sched import telemetry as tel

    f = XLAFilter()
    f.open(FilterProps(model=lambda x: jnp.asarray(x) + 1.0,
                       custom="bucket=2,bucket_max=4"))
    assert f._bucket_max == 4
    before = tel.BUCKET_TOTAL.labels("miss")._value
    inputs = [TensorMemory(np.full((3,), i, np.float32))
              for i in range(11)]  # 11 > cap of 4 → 3 chunks
    outs = f.invoke(inputs)
    got = np.asarray(outs[0].host())
    assert got.shape == (11, 3)
    np.testing.assert_array_equal(
        got, np.stack([np.full((3,), i + 1.0, np.float32)
                       for i in range(11)]))
    assert tel.BUCKET_TOTAL.labels("miss")._value == before + 1


def test_bucket_default_cap_is_8x():
    import jax.numpy as jnp

    from nnstreamer_tpu.filters.base import FilterProps
    from nnstreamer_tpu.filters.xla import XLAFilter

    f = XLAFilter()
    f.open(FilterProps(model=lambda x: jnp.asarray(x),
                       custom="bucket=4"))
    assert f._bucket_max == 32


# -- deadlines ---------------------------------------------------------------- #

class StubDeadline:
    def __init__(self, expired=False):
        self._expired = expired

    def expired(self):
        return self._expired


def test_expired_at_submit_sheds_immediately():
    clock = FakeClock()
    eng = DeviceEngine("t", autostart=False, clock=clock)
    t = eng.register("a")
    fut = t.submit(TagFilter(), [_mem()], deadline=StubDeadline(True))
    assert fut.result(0.1) is SHED
    assert t.stats["shed"] == 1 and eng.stats["shed"] == 1
    assert t.pending() == 0


def test_expired_in_queue_sheds_before_dispatch():
    clock = FakeClock()
    eng = DeviceEngine("t", autostart=False, clock=clock)
    t = eng.register("a")
    filt = TagFilter()
    dead = StubDeadline(False)
    fut = t.submit(filt, [_mem()], deadline=dead)
    dead._expired = True  # expires while queued
    assert eng.step() is False  # shed, nothing dispatched
    assert fut.result(0.1) is SHED
    assert filt.log == []
    assert t.stats["shed"] == 1


def test_tenant_default_deadline_applies():
    eng = DeviceEngine("t", autostart=False)
    t = eng.register("a", deadline_ms=0.0)  # everything is already late
    fut = t.submit(TagFilter(), [_mem()])
    assert fut.result(0.1) is SHED


def test_shed_rides_resilience_accounting(metrics_on):
    eng = DeviceEngine("t", autostart=False)
    t = eng.register("a")
    fam = metrics_on.counter(
        "nnstpu_resilience_shed_total",
        "work shed by deadline/overload policies", ("site",))
    before = fam.labels("sched")._value
    t.submit(TagFilter(), [_mem()], deadline=StubDeadline(True))
    assert fam.labels("sched")._value == before + 1


# -- tenant lifecycle --------------------------------------------------------- #

def test_duplicate_tenant_name_rejected():
    eng = DeviceEngine("t", autostart=False)
    eng.register("a")
    with pytest.raises(ValueError, match="duplicate"):
        eng.register("a")


def test_deregister_resolves_leftovers_to_shed():
    eng = DeviceEngine("t", autostart=False)
    t = eng.register("a")
    fut = t.submit(TagFilter(), [_mem()])
    eng.deregister(t)
    assert fut.result(0.1) is SHED
    assert eng.tenants() == []


def test_preset_overrides_registration():
    eng = DeviceEngine("t", autostart=False)
    eng.preset("cam", weight=4.0, priority=2)
    t = eng.register("cam", weight=1.0)
    assert t.weight == 4.0 and t.priority == 2
    # suffixed pipeline tenants inherit the base-name preset
    t2 = eng.register("cam#1")
    assert t2.weight == 4.0


def test_opaque_call_runs_under_fair_share():
    eng = DeviceEngine("t", autostart=True)
    try:
        t = eng.register("srv")
        assert t.call(lambda: 41 + 1) == 42
        assert t.stats["completed"] == 1
    finally:
        eng.stop()


def test_dispatch_error_propagates_to_future():
    class Boom:
        def invoke(self, inputs):
            raise RuntimeError("device on fire")

    eng = DeviceEngine("t", autostart=False)
    t = eng.register("a")
    fut = t.submit(Boom(), [_mem()])
    eng.step()
    with pytest.raises(RuntimeError, match="device on fire"):
        fut.result(0.1)
    assert t.stats["errors"] == 1


# -- zero-overhead-when-off contract ------------------------------------------ #

def test_no_scheduler_means_no_hook_and_no_wrapper():
    from nnstreamer_tpu.graph import pipeline as gp

    assert gp.SCHED_PIPELINE_HOOK is None
    assert sched.installed() is None
    p = Pipeline()
    src = p.add_new("videotestsrc", width=32, height=32, num_buffers=2)
    conv = p.add_new("tensor_converter")
    filt = p.add_new("tensor_filter", model=lambda x: x.mean(axis=(1, 2, 3)))
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, conv, filt, sink)
    p.run(timeout=120)
    # the chain never grew a scheduler wrapper: the gate attribute
    # stayed None the whole run and no engine ever existed
    assert all(el._sched_exec is None for el in p.elements.values())
    assert p._sched_engine is None
    assert sink.num_buffers == 2


def test_install_uninstall_default_engine():
    from nnstreamer_tpu.graph import pipeline as gp

    eng = sched.install("dflt", max_coalesce=4)
    try:
        assert sched.installed() is eng
        assert sched.install() is eng  # idempotent
        assert gp.SCHED_PIPELINE_HOOK is not None
        p = Pipeline("hookpipe")
        src = p.add_new("videotestsrc", width=32, height=32, num_buffers=2)
        conv = p.add_new("tensor_converter")
        filt = p.add_new("tensor_filter",
                         model=lambda x: x.mean(axis=(1, 2, 3)))
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, conv, filt, sink)
        p.run(timeout=120)
        assert sink.num_buffers == 2
        assert eng.stats["items"] >= 2  # invokes went through the engine
        assert filt._sched_exec is None  # stop() detached
    finally:
        sched.uninstall()
    assert sched.installed() is None
    assert gp.SCHED_PIPELINE_HOOK is None


# -- E2E: 8 concurrent pipelines, one engine ---------------------------------- #

def _build(model, n, scheduler=None, buffers=4):
    p = Pipeline(f"pipe{n}", scheduler=scheduler)
    src = p.add_new("videotestsrc", width=32, height=32,
                    num_buffers=buffers, pattern="random", seed=100 + n)
    conv = p.add_new("tensor_converter")
    filt = p.add_new("tensor_filter", framework="xla-tpu", model=model)
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, conv, filt, sink)
    return p, sink


def _outputs(sink):
    return [np.asarray(b.memories[0].host()) for b in sink.buffers]


def test_eight_pipelines_multiplex_identical_to_serial():
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    w = jnp.asarray(rng.normal(size=(3, 7)).astype(np.float32))

    def model(x):
        return jnp.tanh(jnp.asarray(x, jnp.float32) @ w)

    serial = []
    for i in range(8):
        p, sink = _build(model, i)
        p.run(timeout=120)
        serial.append(_outputs(sink))

    eng = DeviceEngine("e2e", autostart=True, max_coalesce=8)
    try:
        built = [_build(model, i, scheduler=eng) for i in range(8)]
        for p, _ in built:
            p.start()
        for p, _ in built:
            assert p.wait_eos(120)
        for p, _ in built:
            p.stop()
        assert len(eng.tenants()) == 0  # every stop() detached cleanly
        assert eng.stats["items"] == 8 * 4
        for i, (_, sink) in enumerate(built):
            got = _outputs(sink)
            assert len(got) == len(serial[i]) == 4
            for a, b in zip(got, serial[i]):
                np.testing.assert_array_equal(a, b)
    finally:
        eng.stop()


def test_coalesce_key_shared_across_xla_filter_instances():
    # the zoo memoizes equal specs, so two filters over one spec publish
    # the same coalesce_token — N pipelines share device batches; any
    # result-affecting config difference splits the key again
    from nnstreamer_tpu.filters.base import FilterProps
    from nnstreamer_tpu.filters.xla import XLAFilter
    from nnstreamer_tpu.sched.engine import _coalesce_key

    spec = ("zoo://mobilenet_v2?width=0.25&size=32&num_classes=16"
            "&dtype=float32")
    mem = TensorMemory(np.zeros((1, 32, 32, 3), np.float32))
    a, b, c = XLAFilter(), XLAFilter(), XLAFilter()
    a.open(FilterProps(model=spec))
    b.open(FilterProps(model=spec))
    c.open(FilterProps(model=spec, custom="precision=bf16"))
    try:
        assert _coalesce_key(a, [mem]) == _coalesce_key(b, [mem])
        assert _coalesce_key(c, [mem]) != _coalesce_key(a, [mem])
        other = TensorMemory(np.zeros((2, 32, 32, 3), np.float32))
        assert _coalesce_key(a, [other]) != _coalesce_key(a, [mem])
    finally:
        for f in (a, b, c):
            f.close()
