"""tensor_filter inputlayout/outputlayout/inputranks property parity.

Reference surface: tensor_filter_common.c:891-992 (PROP_INPUTLAYOUT /
PROP_OUTPUTLAYOUT accept none/any/NHWC/NCHW per tensor; PROP_INPUTRANKS /
PROP_OUTPUTRANKS are readable rank lists). On the XLA backend a declared
NCHW stream is permuted to the model's native NHWC INSIDE the compiled
program (and back for outputs) — a fused transpose, not a host copy.
"""

import numpy as np
import pytest

from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline


def caps_of(dims, types, rate=30):
    return Caps.tensors(
        TensorsConfig(TensorsInfo.from_strings(dims, types), rate))


def test_nchw_stream_into_nhwc_model():
    """Channel-first frames (1,3,4,5) reach an NHWC channel-reduce model;
    result must equal reducing the original's axis 1."""
    x = np.arange(60, dtype=np.float32).reshape(1, 3, 4, 5)
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps_of("5:4:3:1", "float32"), data=[x])
    filt = p.add_new("tensor_filter", framework="xla-tpu",
                     model=lambda a: a.sum(axis=3), inputlayout="NCHW")
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, filt, sink)
    p.run(timeout=60)
    out = sink.buffers[0].memories[0].host()
    np.testing.assert_allclose(out, x.sum(axis=1))


def test_nchw_roundtrip_identity_preserves_layout():
    """inputlayout+outputlayout NCHW: data comes back exactly, and the
    negotiated output caps stay channel-first."""
    x = np.random.default_rng(0).standard_normal((1, 3, 4, 5)).astype(
        np.float32)
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps_of("5:4:3:1", "float32"), data=[x])
    filt = p.add_new("tensor_filter", framework="xla-tpu",
                     model=lambda a: a * 1.0,
                     inputlayout="NCHW", outputlayout="NCHW")
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, filt, sink)
    p.run(timeout=60)
    out = sink.buffers[0].memories[0].host()
    assert out.shape == (1, 3, 4, 5)
    np.testing.assert_allclose(out, x)


def test_nchw_model_info_reported_in_stream_layout():
    """A bundle with NHWC in_info declared NCHW must negotiate
    channel-first caps (dims permuted) — the is_compatible check passes
    for a channel-first stream."""
    from nnstreamer_tpu.models.zoo import ModelBundle

    bundle = ModelBundle(
        "idconv", lambda x: x,
        in_info=TensorsInfo.from_strings("3:8:8:1", "float32"),   # NHWC
        out_info=TensorsInfo.from_strings("3:8:8:1", "float32"))
    x = np.zeros((1, 3, 8, 8), np.float32)
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps_of("8:8:3:1", "float32"), data=[x])
    filt = p.add_new("tensor_filter", framework="xla-tpu", model=bundle,
                     inputlayout="NCHW", outputlayout="NCHW")
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, filt, sink)
    filt._open_fw()
    # backend reports the bundle's NHWC info permuted to the declared
    # channel-first stream layout — that's what caps negotiation compares
    assert filt.fw.get_model_info()[0][0].dim_string == "8:8:3:1"
    assert filt.inputranks == "4"
    p.run(timeout=60)
    assert sink.buffers[0].memories[0].host().shape == (1, 3, 8, 8)


def test_inputranks_outputranks_readable_props():
    from nnstreamer_tpu.elements.filter import TensorFilter

    filt = TensorFilter(framework="xla-tpu",
                        model=lambda a: (a.sum(axis=3), a[:, 0, 0, 0]))
    assert filt.inputranks == ""          # backend not opened yet
    filt._open_fw()
    filt.fw.set_input_info(TensorsInfo.from_strings("5:4:3:1", "float32"))
    assert filt.inputranks == "4"
    assert filt.outputranks == "3,1"
    filt.stop()


def test_unknown_layout_value_rejected():
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps_of("4:1", "float32"),
                    data=[np.zeros((1, 4), np.float32)])
    filt = p.add_new("tensor_filter", framework="xla-tpu",
                     model=lambda a: a, inputlayout="NHCW")
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, filt, sink)
    with pytest.raises(Exception, match="layout"):
        p.run(timeout=60)


def test_non_rank4_tensors_pass_through_unchanged():
    """Layout only applies to rank-4 tensors (the reference's scope);
    a rank-2 stream with inputlayout=NCHW is untouched."""
    x = np.arange(8, dtype=np.float32).reshape(2, 4)
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps_of("4:2", "float32"), data=[x])
    filt = p.add_new("tensor_filter", framework="xla-tpu",
                     model=lambda a: a + 1, inputlayout="NCHW")
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, filt, sink)
    p.run(timeout=60)
    np.testing.assert_allclose(sink.buffers[0].memories[0].host(), x + 1)


def test_fused_transform_runs_before_layout_permute():
    """inputlayout describes the stream ENTERING the filter — i.e. the
    fused transform's output. With auto_fuse, the transform must run
    before the NCHW permute or fused vs unfused results diverge."""
    x = np.random.default_rng(1).standard_normal((2, 3, 4, 5)).astype(
        np.float32)

    def run(fuse):
        p = Pipeline()
        p.auto_fuse = fuse
        src = p.add_new("appsrc", caps=caps_of("5:4:3:2", "float32"),
                        data=[x])
        tr = p.add_new("tensor_transform", mode="transpose",
                       option="1:0:2:3")
        filt = p.add_new("tensor_filter", framework="xla-tpu",
                         model=lambda a: a * 1.0,
                         inputlayout="NCHW", outputlayout="NCHW")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, tr, filt, sink)
        p.run(timeout=60)
        return sink.buffers[0].memories[0].host()

    fused, unfused = run(True), run(False)
    assert fused.shape == unfused.shape
    np.testing.assert_allclose(fused, unfused)


def test_nchw_rejected_on_backend_without_layout_support(tmp_path):
    """A backend that would silently ignore the declared layout must be
    rejected at open, not run unpermuted data."""
    from nnstreamer_tpu.codegen import generate
    from nnstreamer_tpu.elements.filter import TensorFilter

    (path,) = generate("layoutless", "py", str(tmp_path))
    filt = TensorFilter(framework="python3", model=path, inputlayout="NCHW")
    with pytest.raises(ValueError, match="NCHW layout"):
        filt._open_fw()
