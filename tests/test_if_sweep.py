"""tensor_if full operator/action sweep.

Mirrors the reference's unittest_if discipline
(/root/reference/tests/unittest_if, gsttensorif.c operator table): every
operator exercised against values below/at/inside/above the comparison
points, both branch actions, TENSORPICK narrowing, and error paths.
"""

import numpy as np
import pytest

from nnstreamer_tpu.core.types import TensorsConfig, TensorsInfo
from nnstreamer_tpu.core import Caps
from nnstreamer_tpu.graph import Pipeline


def caps_of(dims, types):
    return Caps.tensors(TensorsConfig(TensorsInfo.from_strings(dims, types)))


def run_if(values, **if_props):
    """Push scalar frames through tensor_if; return the values that passed
    the then-branch."""
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps_of("1", "float32"),
                    data=[np.full(1, v, np.float32) for v in values])
    tif = p.add_new("tensor_if", compared_value="TENSOR_AVERAGE_VALUE",
                    compared_value_option="0", **if_props)
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, tif, sink)
    p.run(timeout=30)
    return [float(b.memories[0].host()[0]) for b in sink.buffers]


VALUES = [2.0, 5.0, 6.0, 7.0, 9.0]

#: operator → (supplied_value, expected survivors of VALUES)
CASES = {
    "EQ": ("5", [5.0]),
    "NE": ("5", [2.0, 6.0, 7.0, 9.0]),
    "GT": ("5", [6.0, 7.0, 9.0]),
    "GE": ("5", [5.0, 6.0, 7.0, 9.0]),
    "LT": ("5", [2.0]),
    "LE": ("5", [2.0, 5.0]),
    "RANGE_INCLUSIVE": ("5:7", [5.0, 6.0, 7.0]),
    "RANGE_EXCLUSIVE": ("5:7", [6.0]),
    "NOT_IN_RANGE_INCLUSIVE": ("5:7", [2.0, 9.0]),
    "NOT_IN_RANGE_EXCLUSIVE": ("5:7", [2.0, 5.0, 7.0, 9.0]),
}


@pytest.mark.parametrize("op", sorted(CASES))
def test_operator(op):
    supplied, want = CASES[op]
    got = run_if(VALUES, operator=op, supplied_value=supplied,
                 then="PASSTHROUGH")
    assert got == want, f"{op} supplied={supplied}"


@pytest.mark.parametrize("op", sorted(CASES))
def test_operator_else_branch_complement(op):
    """then=SKIP + else=PASSTHROUGH yields exactly the complement set."""
    supplied, want = CASES[op]
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps_of("1", "float32"),
                    data=[np.full(1, v, np.float32) for v in VALUES])
    tif = p.add_new("tensor_if", compared_value="TENSOR_AVERAGE_VALUE",
                    compared_value_option="0", operator=op,
                    supplied_value=supplied, then="SKIP")
    tif.set_properties(**{"else": "PASSTHROUGH"})
    tif.add_src_pad("src_else")
    s_then = p.add_new("tensor_sink", store=True)
    s_else = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, tif)
    tif.src_pads[0].link(s_then.sink_pad)
    tif.src_pads[1].link(s_else.sink_pad)
    p.run(timeout=30)
    assert s_then.num_buffers == 0  # SKIP drops the then-branch
    got_else = [float(b.memories[0].host()[0]) for b in s_else.buffers]
    assert got_else == [v for v in VALUES if v not in want]


def test_tensorpick_then_action():
    """TENSORPICK narrows the frame to the chosen tensors on the branch."""
    frames = [[np.full(2, v, np.float32), np.full(3, -v, np.float32)]
              for v in [1.0, 9.0]]
    from nnstreamer_tpu.core.buffer import Buffer

    p2 = Pipeline()
    src = p2.add_new("appsrc", caps=caps_of("2,3", "float32,float32"),
                     data=[Buffer.from_arrays(f) for f in frames])
    tif = p2.add_new("tensor_if", compared_value="TENSOR_AVERAGE_VALUE",
                     compared_value_option="0", operator="GT",
                     supplied_value="5", then="TENSORPICK", then_option="1")
    sink = p2.add_new("tensor_sink", store=True)
    Pipeline.link(src, tif, sink)
    p2.run(timeout=30)
    assert sink.num_buffers == 1
    buf = sink.buffers[0]
    assert buf.num_tensors == 1
    np.testing.assert_array_equal(buf.memories[0].host(),
                                  np.full(3, -9.0, np.float32))


def test_a_value_multidim_coordinate():
    """A_VALUE with innermost-first coords picks one element of tensor 0."""
    arr0 = np.zeros((2, 3), np.float32)   # dims "3:2"
    arr0[1, 2] = 8.0                      # coords innermost-first: 2:1
    arr1 = np.zeros((2, 3), np.float32)
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps_of("3:2", "float32"),
                    data=[arr0, arr1])
    tif = p.add_new("tensor_if", compared_value="A_VALUE",
                    compared_value_option="2:1:0", operator="GT",
                    supplied_value="5")
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, tif, sink)
    p.run(timeout=30)
    assert sink.num_buffers == 1
    np.testing.assert_array_equal(sink.buffers[0].memories[0].host(), arr0)


@pytest.mark.parametrize("bad", [
    dict(operator="BOGUS", supplied_value="5"),
    dict(operator="GT", supplied_value="not-a-number"),
    dict(compared_value="NOPE", operator="GT", supplied_value="5"),
])
def test_invalid_config_fails(bad):
    from nnstreamer_tpu.graph.pipeline import PipelineError

    p = Pipeline()
    src = p.add_new("appsrc", caps=caps_of("1", "float32"),
                    data=[np.zeros(1, np.float32)])
    props = dict(compared_value="TENSOR_AVERAGE_VALUE",
                 compared_value_option="0")
    props.update(bad)
    tif = p.add_new("tensor_if", **props)
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, tif, sink)
    with pytest.raises((PipelineError, ValueError, KeyError)):
        p.run(timeout=30)
