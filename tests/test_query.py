"""Query/offload layer tests — localhost server+client pipelines
(reference tests/nnstreamer_query/runTest.sh pattern: both ends in one test
host, plus protocol unit tests)."""

import socket
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline
from nnstreamer_tpu.query import DiscoveryBroker, discover, register_node
from nnstreamer_tpu.query.protocol import (
    Cmd,
    buffer_to_payload,
    pack_message,
    payload_to_buffer,
)


def caps_of(dims, types, rate=30):
    return Caps.tensors(TensorsConfig(TensorsInfo.from_strings(dims, types), rate))


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestProtocol:
    def test_buffer_payload_roundtrip(self):
        buf = Buffer.of(np.arange(6, dtype=np.float32).reshape(2, 3),
                        np.ones((4,), np.uint8), pts=123, duration=7)
        meta, payload = buffer_to_payload(buf)
        out = payload_to_buffer(meta, payload)
        assert out.pts == 123 and out.duration == 7
        np.testing.assert_array_equal(out.memories[0].host(),
                                      buf.memories[0].host())
        np.testing.assert_array_equal(out.memories[1].host(),
                                      buf.memories[1].host())

    def test_sparse_payload(self):
        dense = np.zeros((8, 8), np.float32)
        dense[2, 3] = 9.0
        buf = Buffer.of(dense)
        meta, payload = buffer_to_payload(buf, sparse=True)
        dense_meta, dense_payload = buffer_to_payload(buf, sparse=False)
        assert len(payload) < len(dense_payload)
        out = payload_to_buffer(meta, payload)
        np.testing.assert_array_equal(out.memories[0].host(), dense)

    def test_bad_magic_rejected(self):
        import struct
        from nnstreamer_tpu.query.protocol import QueryProtocolError, recv_message

        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack("<IBIQ", 0xDEAD, 1, 0, 0))
            with pytest.raises(QueryProtocolError, match="magic"):
                recv_message(b)
        finally:
            a.close()
            b.close()


class TestQueryOffload:
    def _server_pipeline(self, port):
        sp = Pipeline("server")
        ssrc = sp.add_new("tensor_query_serversrc", host="127.0.0.1",
                          port=port, id=0, dims="4:1", types="float32")
        filt = sp.add_new("tensor_filter", model=lambda x: x * 10)
        ssink = sp.add_new("tensor_query_serversink", id=0)
        Pipeline.link(ssrc, filt, ssink)
        return sp

    def test_offload_roundtrip(self):
        port = free_port()
        sp = self._server_pipeline(port)
        sp.start()
        try:
            time.sleep(0.2)
            cp = Pipeline("client")
            src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"),
                             data=[np.full((1, 4), i, np.float32)
                                   for i in range(5)])
            qc = cp.add_new("tensor_query_client", host="127.0.0.1", port=port)
            sink = cp.add_new("tensor_sink", store=True)
            Pipeline.link(src, qc, sink)
            cp.run(timeout=60)
            assert sink.num_buffers == 5
            np.testing.assert_array_equal(sink.buffers[3].memories[0].host(),
                                          np.full((1, 4), 30.0, np.float32))
            # timestamps preserved across the wire
            assert sink.buffers[3].offset == 3
        finally:
            sp.stop()

    def test_sparse_link(self):
        port = free_port()
        sp = self._server_pipeline(port)
        sp.start()
        try:
            time.sleep(0.2)
            cp = Pipeline("client")
            data = np.zeros((1, 4), np.float32)
            data[0, 1] = 2.0
            src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"),
                             data=[data])
            qc = cp.add_new("tensor_query_client", host="127.0.0.1",
                            port=port, sparse=True)
            sink = cp.add_new("tensor_sink", store=True)
            Pipeline.link(src, qc, sink)
            cp.run(timeout=60)
            np.testing.assert_array_equal(sink.buffers[0].memories[0].host(),
                                          data * 10)
        finally:
            sp.stop()

    def test_client_retry_then_fail(self):
        port = free_port()  # nothing listening
        cp = Pipeline("client")
        src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"),
                         data=[np.zeros((1, 4), np.float32)])
        qc = cp.add_new("tensor_query_client", host="127.0.0.1", port=port,
                        max_request_retry=2, timeout_s=1.0)
        sink = cp.add_new("tensor_sink")
        Pipeline.link(src, qc, sink)
        from nnstreamer_tpu.graph import PipelineError

        with pytest.raises(PipelineError, match="failed after retries"):
            cp.run(timeout=60)


class TestHybridDiscovery:
    def test_register_discover(self):
        broker = DiscoveryBroker(port=0).start()
        try:
            assert register_node("object_detection", "127.0.0.1", 5001,
                                 broker_port=broker.port)
            nodes = discover("object_detection", broker_port=broker.port)
            assert nodes == [("127.0.0.1", 5001)]
            assert discover("missing", broker_port=broker.port) == []
        finally:
            broker.stop()

    def test_client_via_broker(self):
        broker = DiscoveryBroker(port=0).start()
        port = free_port()
        sp = Pipeline("server")
        ssrc = sp.add_new("tensor_query_serversrc", host="127.0.0.1",
                          port=port, id=0, dims="2:1", types="float32")
        filt = sp.add_new("tensor_filter", model=lambda x: x + 1)
        ssink = sp.add_new("tensor_query_serversink", id=0)
        Pipeline.link(ssrc, filt, ssink)
        sp.start()
        try:
            time.sleep(0.2)
            register_node("addone", "127.0.0.1", port, broker_port=broker.port)
            cp = Pipeline("client")
            src = cp.add_new("appsrc", caps=caps_of("2:1", "float32"),
                             data=[np.zeros((1, 2), np.float32)])
            qc = cp.add_new("tensor_query_client", operation="addone",
                            broker_port=broker.port)
            sink = cp.add_new("tensor_sink", store=True)
            Pipeline.link(src, qc, sink)
            cp.run(timeout=60)
            np.testing.assert_array_equal(sink.buffers[0].memories[0].host(),
                                          np.ones((1, 2), np.float32))
        finally:
            sp.stop()
            broker.stop()


class TestMultiProcess:
    def test_server_in_separate_process(self, tmp_path):
        """True cross-process offload (reference runs server & client as
        separate gst-launch processes)."""
        import subprocess
        import sys

        port = free_port()
        server_code = f"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import sys
sys.path.insert(0, {repr(str(tmp_path.parent))})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from nnstreamer_tpu.graph import Pipeline
p = Pipeline()
ssrc = p.add_new("tensor_query_serversrc", host="127.0.0.1", port={port},
                 id=0, dims="3:1", types="float32")
f = p.add_new("tensor_filter", model=lambda x: -x)
ssink = p.add_new("tensor_query_serversink", id=0)
Pipeline.link(ssrc, f, ssink)
p.start()
print("READY", flush=True)
import time
time.sleep(60)  # lifetime window; the test terminates us once done
p.stop()
"""
        import os

        env = dict(os.environ)
        env["PYTHONPATH"] = "/root/repo"
        proc = subprocess.Popen([sys.executable, "-u", "-c", server_code],
                                stdout=subprocess.PIPE, env=env, text=True)
        try:
            line = proc.stdout.readline()
            assert "READY" in line
            cp = Pipeline("client")
            src = cp.add_new("appsrc", caps=caps_of("3:1", "float32"),
                             data=[np.full((1, 3), 4.0, np.float32)])
            qc = cp.add_new("tensor_query_client", host="127.0.0.1", port=port)
            sink = cp.add_new("tensor_sink", store=True)
            Pipeline.link(src, qc, sink)
            cp.run(timeout=60)
            np.testing.assert_array_equal(sink.buffers[0].memories[0].host(),
                                          np.full((1, 3), -4.0, np.float32))
        finally:
            proc.terminate()
            proc.wait(timeout=10)


class TestGrpc:
    def test_push_sink_to_server_src(self):
        pytest.importorskip("grpc")
        sp = Pipeline("grpc-server")
        gsrc = sp.add_new("tensor_grpc_src", port=0, server=True)
        ssink = sp.add_new("tensor_sink", store=True)
        Pipeline.link(gsrc, ssink)
        sp.start()
        try:
            time.sleep(0.3)
            port = gsrc.bound_port
            cp = Pipeline("grpc-client")
            src = cp.add_new("appsrc", caps=caps_of("3:1", "float32"),
                             data=[np.full((1, 3), i, np.float32)
                                   for i in range(4)])
            gsink = cp.add_new("tensor_grpc_sink", port=port, server=False)
            Pipeline.link(src, gsink)
            cp.run(timeout=30)
            deadline = time.monotonic() + 10
            while ssink.num_buffers < 4 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert ssink.num_buffers == 4
            np.testing.assert_array_equal(
                ssink.buffers[2].memories[0].host(),
                np.full((1, 3), 2.0, np.float32))
        finally:
            sp.stop()


class TestPubSub:
    def test_mqtt_style_pubsub(self):
        from nnstreamer_tpu.query.pubsub import PubSubBroker

        broker = PubSubBroker(port=0).start()
        try:
            rp = Pipeline("subscriber")
            msrc = rp.add_new("mqttsrc", port=broker.port, sub_topic="cam0")
            rsink = rp.add_new("tensor_sink", store=True)
            Pipeline.link(msrc, rsink)
            rp.start()
            time.sleep(0.3)
            tp = Pipeline("publisher")
            src = tp.add_new("appsrc", caps=caps_of("2:1", "float32"),
                             data=[np.full((1, 2), i, np.float32)
                                   for i in range(3)])
            msink = tp.add_new("mqttsink", port=broker.port, pub_topic="cam0")
            Pipeline.link(src, msink)
            tp.run(timeout=30)
            deadline = time.monotonic() + 10
            while rsink.num_buffers < 3 and time.monotonic() < deadline:
                time.sleep(0.05)
            rp.stop()
            assert rsink.num_buffers == 3
            assert "mqtt_latency_us" in rsink.buffers[0].meta
        finally:
            broker.stop()


class TestGrpcIdlVariants:
    @pytest.mark.parametrize("idl", ["protobuf", "flatbuf"])
    def test_push_roundtrip(self, idl):
        """gRPC transport with the reference's two IDL message formats
        (nnstreamer_grpc_protobuf.cc / nnstreamer_grpc_flatbuf.cc +
        nnstreamer.fbs/.proto)."""
        pytest.importorskip("grpc")
        if idl == "flatbuf":
            pytest.importorskip("flatbuffers")
        rp = Pipeline("receiver")
        gsrc = rp.add_new("tensor_grpc_src", port=0, idl=idl)
        rsink = rp.add_new("tensor_sink", store=True)
        Pipeline.link(gsrc, rsink)
        rp.start()
        try:
            deadline = time.monotonic() + 5
            while not hasattr(gsrc, "bound_port") \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            port = gsrc.bound_port
            tp = Pipeline("tx")
            arrs = [np.full((1, 3), i, np.float32) for i in range(3)]
            src = tp.add_new("appsrc", caps=caps_of("3:1", "float32"),
                             data=arrs)
            gsink = tp.add_new("tensor_grpc_sink", port=port, idl=idl)
            Pipeline.link(src, gsink)
            tp.run(timeout=30)
            deadline = time.monotonic() + 10
            while rsink.num_buffers < 3 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert rsink.num_buffers == 3
            got = sorted(float(b.memories[0].host().reshape(-1)[0])
                         for b in rsink.buffers)
            assert got == [0.0, 1.0, 2.0]
        finally:
            rp.stop()


class TestChunkedTransfer:
    """Chunked DATA framing (reference TRANSFER_START/DATA/END,
    tensor_query_common.h:42-68) + per-chunk timeouts + fault injection."""

    @staticmethod
    def _pipe():
        a, b = socket.socketpair()
        return a, b

    def test_large_payload_streams_in_chunks(self):
        from nnstreamer_tpu.query.protocol import (
            CHUNK_SIZE, recv_message, send_message)

        a, b = self._pipe()
        payload = bytes(np.random.default_rng(0).bytes(3 * CHUNK_SIZE + 17))
        t = threading.Thread(
            target=send_message, args=(a, Cmd.DATA, {"k": 1}, payload),
            daemon=True)
        t.start()
        cmd, meta, got = recv_message(b)
        assert cmd is Cmd.DATA and meta == {"k": 1}
        assert got == payload
        t.join(5)
        a.close(); b.close()

    def test_small_payload_single_message(self):
        from nnstreamer_tpu.query.protocol import recv_message, send_message

        a, b = self._pipe()
        send_message(a, Cmd.RESULT, {"x": 2}, b"tiny")
        cmd, meta, got = recv_message(b)
        assert (cmd, meta, got) == (Cmd.RESULT, {"x": 2}, b"tiny")
        a.close(); b.close()

    def test_chunk_timeout_detects_stalled_sender(self):
        from nnstreamer_tpu.query.protocol import (
            QueryProtocolError, pack_message, recv_message)

        a, b = self._pipe()
        # CHUNK_START promising data, then silence: per-chunk timeout must
        # fire instead of hanging for the whole payload
        a.sendall(pack_message(Cmd.CHUNK_START,
                               {"chunked_cmd": int(Cmd.DATA),
                                "chunked_total": 5 * 1024 * 1024}))
        t0 = time.monotonic()
        with pytest.raises(QueryProtocolError, match="chunk timeout"):
            recv_message(b, chunk_timeout=0.3)
        assert time.monotonic() - t0 < 5
        a.close(); b.close()

    def test_truncated_frame_rejected(self):
        from nnstreamer_tpu.query.protocol import recv_message

        a, b = self._pipe()
        full = pack_message(Cmd.DATA, {"sizes": [999]}, b"x" * 10)
        a.sendall(full[: len(full) // 2])
        a.close()  # peer dies mid-frame
        with pytest.raises(ConnectionError):
            recv_message(b)
        b.close()

    def test_chunk_out_of_bounds_rejected(self):
        from nnstreamer_tpu.query.protocol import (
            QueryProtocolError, pack_message, recv_message)

        a, b = self._pipe()
        a.sendall(pack_message(Cmd.CHUNK_START,
                               {"chunked_cmd": int(Cmd.DATA),
                                "chunked_total": 10}))
        a.sendall(pack_message(Cmd.CHUNK_DATA, {"off": 8}, b"xxxx"))
        with pytest.raises(QueryProtocolError, match="out of order"):
            recv_message(b, chunk_timeout=2.0)
        a.close(); b.close()

    def test_duplicate_chunk_rejected(self):
        """A duplicated/overlapping chunk must not let a hole pass the
        completeness check (byte counters alone would be fooled)."""
        from nnstreamer_tpu.query.protocol import (
            QueryProtocolError, pack_message, recv_message)

        a, b = self._pipe()
        a.sendall(pack_message(Cmd.CHUNK_START,
                               {"chunked_cmd": int(Cmd.DATA),
                                "chunked_total": 8}))
        a.sendall(pack_message(Cmd.CHUNK_DATA, {"off": 0}, b"1234"))
        a.sendall(pack_message(Cmd.CHUNK_DATA, {"off": 0}, b"1234"))
        a.sendall(pack_message(Cmd.CHUNK_END, {}))
        with pytest.raises(QueryProtocolError, match="out of order"):
            recv_message(b, chunk_timeout=2.0)
        a.close(); b.close()

    def test_null_chunk_meta_rejected(self):
        """{"chunked_total": null} decodes to None; int(None) raises
        TypeError, which must surface as QueryProtocolError — a bad peer
        never crashes the receive loop with a raw TypeError."""
        from nnstreamer_tpu.query.protocol import (
            QueryProtocolError, pack_message, recv_message)

        a, b = self._pipe()
        a.sendall(pack_message(Cmd.CHUNK_START,
                               {"chunked_cmd": int(Cmd.DATA),
                                "chunked_total": None}))
        with pytest.raises(QueryProtocolError, match="bad CHUNK_START"):
            recv_message(b, chunk_timeout=2.0)
        a.close(); b.close()

    def test_incomplete_chunked_transfer_rejected(self):
        from nnstreamer_tpu.query.protocol import (
            QueryProtocolError, pack_message, recv_message)

        a, b = self._pipe()
        a.sendall(pack_message(Cmd.CHUNK_START,
                               {"chunked_cmd": int(Cmd.DATA),
                                "chunked_total": 8}))
        a.sendall(pack_message(Cmd.CHUNK_DATA, {"off": 0}, b"1234"))
        a.sendall(pack_message(Cmd.CHUNK_END, {}))
        with pytest.raises(QueryProtocolError, match="incomplete"):
            recv_message(b, chunk_timeout=2.0)
        a.close(); b.close()


class TestFaultInjection:
    """Server/client resilience (reference runTest.sh kills background
    pipelines mid-stream; unittest_query asserts error paths)."""

    def test_server_survives_garbage_and_truncated_clients(self):
        """A malformed client must not take the server down; the next
        well-behaved client still gets service."""
        sp = Pipeline("server")
        ssrc = sp.add_new("tensor_query_serversrc", port=0, id=0,
                          dims="2:1", types="float32")
        ssink = sp.add_new("tensor_query_serversink", id=0)
        Pipeline.link(ssrc, ssink)
        sp.start()
        try:
            deadline = time.monotonic() + 5
            while not hasattr(ssrc, "bound_port") \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            port = ssrc.bound_port
            # 1: pure garbage bytes
            g = socket.create_connection(("127.0.0.1", port), 5)
            g.sendall(b"\xde\xad\xbe\xef" * 8)
            g.close()
            # 2: valid header then truncated body + hard close
            t = socket.create_connection(("127.0.0.1", port), 5)
            full = pack_message(Cmd.DATA, {"sizes": [100]}, b"y" * 100)
            t.sendall(full[:20])
            t.close()
            time.sleep(0.2)
            # 3: real client pipeline still gets echo service
            cp = Pipeline("client")
            arrs = [np.full((1, 2), i, np.float32) for i in range(2)]
            src = cp.add_new("appsrc", caps=caps_of("2:1", "float32"),
                             data=arrs)
            qc = cp.add_new("tensor_query_client", port=port)
            sink = cp.add_new("tensor_sink", store=True)
            Pipeline.link(src, qc, sink)
            cp.run(timeout=30)
            assert sink.num_buffers == 2
        finally:
            sp.stop()

    def test_client_error_on_server_killed_mid_stream(self):
        """Server dies between frames → client either recovers by retry
        (reconnect) or surfaces a pipeline error — never hangs."""
        from nnstreamer_tpu.graph import PipelineError

        sp = Pipeline("server")
        ssrc = sp.add_new("tensor_query_serversrc", port=0, id=0,
                          dims="2:1", types="float32")
        ssink = sp.add_new("tensor_query_serversink", id=0)
        Pipeline.link(ssrc, ssink)
        sp.start()
        deadline = time.monotonic() + 5
        while not hasattr(ssrc, "bound_port") and time.monotonic() < deadline:
            time.sleep(0.05)
        port = ssrc.bound_port

        killed = threading.Event()

        def frames():
            yield np.full((1, 2), 0, np.float32)
            sp.stop()  # hard kill between frames
            killed.set()
            yield np.full((1, 2), 1, np.float32)
            yield np.full((1, 2), 2, np.float32)

        cp = Pipeline("client")
        src = cp.add_new("appsrc", caps=caps_of("2:1", "float32"),
                         data=frames())
        qc = cp.add_new("tensor_query_client", port=port,
                        max_request_retry=2)
        sink = cp.add_new("tensor_sink", store=True)
        Pipeline.link(src, qc, sink)
        t0 = time.monotonic()
        try:
            cp.run(timeout=60)
        except PipelineError:
            pass  # surfacing the failure is acceptable; hanging is not
        assert killed.is_set()
        assert time.monotonic() - t0 < 60
        assert sink.num_buffers >= 1  # pre-kill frame was served


class TestTwoInterpreterQuery:
    def test_cross_process_offload(self, tmp_path):
        """True two-interpreter test (reference runs server & client as
        separate gst-launch processes, tests/nnstreamer_query/runTest.sh:41-80):
        the server pipeline lives in a SEPARATE python process; this process
        runs the client pipeline against it."""
        import os
        import subprocess
        import sys

        port_file = tmp_path / "port.txt"
        code = f"""
import os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
from nnstreamer_tpu.graph import Pipeline
p = Pipeline("server")
ssrc = p.add_new("tensor_query_serversrc", port=0, id=0, dims="2:1", types="float32")
filt = p.add_new("tensor_filter", framework="xla-tpu", model="zoo://scaler?dims=2:1&types=float32&scale=3")
ssink = p.add_new("tensor_query_serversink", id=0)
Pipeline.link(ssrc, filt, ssink)
p.start()
deadline = time.monotonic() + 10
while not hasattr(ssrc, "bound_port") and time.monotonic() < deadline:
    time.sleep(0.05)
open({str(port_file)!r}, "w").write(str(ssrc.bound_port))
time.sleep(30)
"""
        srv = subprocess.Popen([sys.executable, "-c", code],
                               stdout=subprocess.PIPE,
                               stderr=subprocess.PIPE)
        try:
            deadline = time.monotonic() + 60
            while not port_file.exists() and time.monotonic() < deadline:
                if srv.poll() is not None:
                    raise AssertionError(
                        "server process died: "
                        + srv.stderr.read().decode()[-2000:])
                time.sleep(0.1)
            port = int(port_file.read_text())

            cp = Pipeline("client")
            arrs = [np.full((1, 2), float(i), np.float32) for i in range(3)]
            src = cp.add_new("appsrc", caps=caps_of("2:1", "float32"),
                             data=arrs)
            qc = cp.add_new("tensor_query_client", port=port)
            sink = cp.add_new("tensor_sink", store=True)
            Pipeline.link(src, qc, sink)
            cp.run(timeout=60)
            assert sink.num_buffers == 3
            for i, b in enumerate(sink.buffers):
                np.testing.assert_allclose(b.memories[0].host(),
                                           np.full((1, 2), i * 3.0))
        finally:
            srv.kill()
            srv.wait(timeout=10)


class TestPipelinedOffload:
    """async_depth on tensor_query_client/serversink: pipelined offload
    (TPU-first RTT hiding; default depth=1 keeps reference-sync semantics)."""

    def _server(self, port, depth=8):
        sp = Pipeline("server")
        ssrc = sp.add_new("tensor_query_serversrc", host="127.0.0.1",
                          port=port, id=0, dims="4:1", types="float32")
        filt = sp.add_new("tensor_filter", model=lambda x: x * 10)
        ssink = sp.add_new("tensor_query_serversink", id=0,
                           async_depth=depth)
        Pipeline.link(ssrc, filt, ssink)
        return sp

    def test_pipelined_roundtrip_order_and_values(self):
        port = free_port()
        sp = self._server(port)
        sp.start()
        try:
            time.sleep(0.2)
            n = 40
            cp = Pipeline("client")
            src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"),
                             data=[np.full((1, 4), i, np.float32)
                                   for i in range(n)])
            qc = cp.add_new("tensor_query_client", host="127.0.0.1",
                            port=port, async_depth=8)
            sink = cp.add_new("tensor_sink", store=True)
            Pipeline.link(src, qc, sink)
            cp.run(timeout=120)
            assert sink.num_buffers == n  # EOS drained every in-flight frame
            for i, b in enumerate(sink.buffers):
                np.testing.assert_array_equal(
                    b.memories[0].host(),
                    np.full((1, 4), i * 10, np.float32))
                assert b.offset == i  # timestamps restored in order
        finally:
            sp.stop()

    def test_pipelined_faster_than_sync_with_slow_server(self):
        """A server with per-frame latency must overlap across the window."""
        port = free_port()
        sp = Pipeline("server")
        ssrc = sp.add_new("tensor_query_serversrc", host="127.0.0.1",
                          port=port, id=0, dims="4:1", types="float32")

        from nnstreamer_tpu.filters.custom import register_custom_easy

        def slow(x):
            time.sleep(0.05)
            return x

        register_custom_easy("qtest_slow_echo", slow,
                             ("4:1", "float32"), ("4:1", "float32"))
        filt = sp.add_new("tensor_filter", framework="custom-easy",
                          model="qtest_slow_echo")
        ssink = sp.add_new("tensor_query_serversink", id=0, async_depth=16)
        Pipeline.link(ssrc, filt, ssink)
        sp.start()
        try:
            time.sleep(0.2)
            n = 20
            cp = Pipeline("client")
            src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"),
                             data=[np.zeros((1, 4), np.float32)] * n)
            qc = cp.add_new("tensor_query_client", host="127.0.0.1",
                            port=port, async_depth=16)
            sink = cp.add_new("tensor_sink", store=True)
            Pipeline.link(src, qc, sink)
            t0 = time.monotonic()
            cp.run(timeout=120)
            wall = time.monotonic() - t0
            assert sink.num_buffers == n
            # the server filter itself is serial (20 × 50 ms ≥ 1 s), but
            # client-side send/receive overlap must not ADD per-frame
            # round trips on top; sync mode costs ≥ n × (invoke + 2 RTT)
            assert wall < n * 0.05 * 2.5, f"no overlap: {wall:.2f}s"
        finally:
            sp.stop()

    def test_reader_failure_surfaces_on_bus(self):
        from nnstreamer_tpu.graph.pipeline import PipelineError

        port = free_port()
        sp = self._server(port)
        sp.start()
        time.sleep(0.2)

        killed = {}

        def gen():
            for i in range(100):
                if i == 25 and not killed:
                    killed["yes"] = True
                    sp.stop()  # kill server with frames in flight
                    time.sleep(0.3)
                yield np.zeros((1, 4), np.float32)

        cp = Pipeline("client")
        src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"),
                         data=gen())
        qc = cp.add_new("tensor_query_client", host="127.0.0.1", port=port,
                        async_depth=8)
        sink = cp.add_new("tensor_sink", store=True)
        Pipeline.link(src, qc, sink)
        with pytest.raises((PipelineError, TimeoutError)):
            cp.run(timeout=30)

    def test_pipelined_reconnects_after_server_restart(self):
        """A cleanly closed connection between streams must reconnect on
        the next frame (reader exits cleanly, next chain redials)."""
        port = free_port()
        sp1 = self._server(port)
        sp1.start()
        time.sleep(0.2)
        cp = Pipeline("client")
        src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"))
        qc = cp.add_new("tensor_query_client", host="127.0.0.1", port=port,
                        async_depth=4, max_request_retry=10)
        sink = cp.add_new("tensor_sink", store=True)
        Pipeline.link(src, qc, sink)
        cp.start()
        try:
            src.push_buffer(np.full((1, 4), 1, np.float32))
            deadline = time.monotonic() + 30
            while sink.num_buffers < 1 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert sink.num_buffers == 1
            sp1.stop()          # server goes away between frames
            time.sleep(0.3)
            sp2 = self._server(port)
            sp2.start()
            time.sleep(0.3)
            try:
                src.push_buffer(np.full((1, 4), 2, np.float32))
                src.end_of_stream()
                assert cp.wait_eos(30)
                assert sink.num_buffers == 2
                np.testing.assert_array_equal(
                    sink.buffers[1].memories[0].host(),
                    np.full((1, 4), 20, np.float32))
            finally:
                sp2.stop()
        finally:
            cp.stop()
            sp1.stop()


class TestLintRegressions:
    """Focused regressions for the true positives nnslint surfaced
    (see docs/analysis.md): the INFO_DENY dispatch gap, thread-leak
    joins, and the _peer_of never-raise boundary."""

    def _serve(self, port):
        sp = Pipeline("server")
        ssrc = sp.add_new("tensor_query_serversrc", host="127.0.0.1",
                          port=port, id=90, dims="4:1", types="float32")
        filt = sp.add_new("tensor_filter", model=lambda x: x)
        ssink = sp.add_new("tensor_query_serversink", id=90)
        Pipeline.link(ssrc, filt, ssink)
        sp.start()
        time.sleep(0.2)
        return sp

    def test_server_denies_caps_mismatch_with_info_deny(self):
        from nnstreamer_tpu.query.protocol import recv_message, send_message

        port = free_port()
        sp = self._serve(port)
        try:
            # wrong media type: explicit INFO_DENY naming the mismatch,
            # not a generic error after the first DATA frame
            with socket.create_connection(("127.0.0.1", port), 5) as s:
                send_message(s, Cmd.INFO_REQ, {"caps": "video/x-raw(w=4)"})
                cmd, meta, _ = recv_message(s)
                assert cmd is Cmd.INFO_DENY
                assert "caps mismatch" in meta["error"]
            # compatible (and unknown) caps still approve
            for caps in ("other/tensors(dims=4:1)", ""):
                with socket.create_connection(("127.0.0.1", port), 5) as s:
                    send_message(s, Cmd.INFO_REQ, {"caps": caps})
                    cmd, meta, _ = recv_message(s)
                    assert cmd is Cmd.INFO_APPROVE, caps
        finally:
            sp.stop()

    def test_client_surfaces_deny_reason(self):
        from nnstreamer_tpu.query.client import TensorQueryClient

        port = free_port()
        sp = self._serve(port)
        try:
            qc = TensorQueryClient(host="127.0.0.1", port=port,
                                   timeout_s=2.0)
            qc.sink_pad.caps = Caps("video/x-raw", {"w": 4})
            with pytest.raises(ConnectionError, match="caps mismatch"):
                qc._connect()
        finally:
            sp.stop()

    def test_server_stop_joins_all_workers(self):
        port = free_port()
        sp = self._serve(port)
        with socket.create_connection(("127.0.0.1", port), 5):
            time.sleep(0.3)  # let the accept loop spawn the conn worker
        sp.stop()
        leaked = [t.name for t in threading.enumerate()
                  if t.name.startswith("qsrv-")]
        assert leaked == []

    def test_discovery_broker_stop_joins_thread(self):
        broker = DiscoveryBroker(port=0).start()
        worker = broker._thread
        assert worker is not None and worker.is_alive()
        broker.stop()
        assert broker._thread is None
        assert not worker.is_alive()
        # the joined listener releases the port for an immediate rebind
        broker2 = DiscoveryBroker(port=broker.port).start()
        broker2.stop()

    def test_peer_of_never_raises(self):
        from nnstreamer_tpu.query.protocol import _peer_of

        class WeirdSock:
            def getpeername(self):
                raise RuntimeError("driver bug")  # outside OSError

        class TupleLess:
            def getpeername(self):
                return 7  # peer[0] raises TypeError

        s = socket.socket()
        s.close()
        assert _peer_of(s) is None            # OSError path
        assert _peer_of(WeirdSock()) is None  # arbitrary exception
        assert _peer_of(TupleLess()) is None
