"""Query/offload layer tests — localhost server+client pipelines
(reference tests/nnstreamer_query/runTest.sh pattern: both ends in one test
host, plus protocol unit tests)."""

import socket
import threading
import time

import numpy as np
import pytest

from nnstreamer_tpu.core import Buffer, Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.graph import Pipeline
from nnstreamer_tpu.query import DiscoveryBroker, discover, register_node
from nnstreamer_tpu.query.protocol import (
    Cmd,
    buffer_to_payload,
    pack_message,
    payload_to_buffer,
)


def caps_of(dims, types, rate=30):
    return Caps.tensors(TensorsConfig(TensorsInfo.from_strings(dims, types), rate))


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class TestProtocol:
    def test_buffer_payload_roundtrip(self):
        buf = Buffer.of(np.arange(6, dtype=np.float32).reshape(2, 3),
                        np.ones((4,), np.uint8), pts=123, duration=7)
        meta, payload = buffer_to_payload(buf)
        out = payload_to_buffer(meta, payload)
        assert out.pts == 123 and out.duration == 7
        np.testing.assert_array_equal(out.memories[0].host(),
                                      buf.memories[0].host())
        np.testing.assert_array_equal(out.memories[1].host(),
                                      buf.memories[1].host())

    def test_sparse_payload(self):
        dense = np.zeros((8, 8), np.float32)
        dense[2, 3] = 9.0
        buf = Buffer.of(dense)
        meta, payload = buffer_to_payload(buf, sparse=True)
        dense_meta, dense_payload = buffer_to_payload(buf, sparse=False)
        assert len(payload) < len(dense_payload)
        out = payload_to_buffer(meta, payload)
        np.testing.assert_array_equal(out.memories[0].host(), dense)

    def test_bad_magic_rejected(self):
        import struct
        from nnstreamer_tpu.query.protocol import QueryProtocolError, recv_message

        a, b = socket.socketpair()
        try:
            a.sendall(struct.pack("<IBIQ", 0xDEAD, 1, 0, 0))
            with pytest.raises(QueryProtocolError, match="magic"):
                recv_message(b)
        finally:
            a.close()
            b.close()


class TestQueryOffload:
    def _server_pipeline(self, port):
        sp = Pipeline("server")
        ssrc = sp.add_new("tensor_query_serversrc", host="127.0.0.1",
                          port=port, id=0, dims="4:1", types="float32")
        filt = sp.add_new("tensor_filter", model=lambda x: x * 10)
        ssink = sp.add_new("tensor_query_serversink", id=0)
        Pipeline.link(ssrc, filt, ssink)
        return sp

    def test_offload_roundtrip(self):
        port = free_port()
        sp = self._server_pipeline(port)
        sp.start()
        try:
            time.sleep(0.2)
            cp = Pipeline("client")
            src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"),
                             data=[np.full((1, 4), i, np.float32)
                                   for i in range(5)])
            qc = cp.add_new("tensor_query_client", host="127.0.0.1", port=port)
            sink = cp.add_new("tensor_sink", store=True)
            Pipeline.link(src, qc, sink)
            cp.run(timeout=60)
            assert sink.num_buffers == 5
            np.testing.assert_array_equal(sink.buffers[3].memories[0].host(),
                                          np.full((1, 4), 30.0, np.float32))
            # timestamps preserved across the wire
            assert sink.buffers[3].offset == 3
        finally:
            sp.stop()

    def test_sparse_link(self):
        port = free_port()
        sp = self._server_pipeline(port)
        sp.start()
        try:
            time.sleep(0.2)
            cp = Pipeline("client")
            data = np.zeros((1, 4), np.float32)
            data[0, 1] = 2.0
            src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"),
                             data=[data])
            qc = cp.add_new("tensor_query_client", host="127.0.0.1",
                            port=port, sparse=True)
            sink = cp.add_new("tensor_sink", store=True)
            Pipeline.link(src, qc, sink)
            cp.run(timeout=60)
            np.testing.assert_array_equal(sink.buffers[0].memories[0].host(),
                                          data * 10)
        finally:
            sp.stop()

    def test_client_retry_then_fail(self):
        port = free_port()  # nothing listening
        cp = Pipeline("client")
        src = cp.add_new("appsrc", caps=caps_of("4:1", "float32"),
                         data=[np.zeros((1, 4), np.float32)])
        qc = cp.add_new("tensor_query_client", host="127.0.0.1", port=port,
                        max_request_retry=2, timeout_s=1.0)
        sink = cp.add_new("tensor_sink")
        Pipeline.link(src, qc, sink)
        from nnstreamer_tpu.graph import PipelineError

        with pytest.raises(PipelineError, match="failed after retries"):
            cp.run(timeout=60)


class TestHybridDiscovery:
    def test_register_discover(self):
        broker = DiscoveryBroker(port=0).start()
        try:
            assert register_node("object_detection", "127.0.0.1", 5001,
                                 broker_port=broker.port)
            nodes = discover("object_detection", broker_port=broker.port)
            assert nodes == [("127.0.0.1", 5001)]
            assert discover("missing", broker_port=broker.port) == []
        finally:
            broker.stop()

    def test_client_via_broker(self):
        broker = DiscoveryBroker(port=0).start()
        port = free_port()
        sp = Pipeline("server")
        ssrc = sp.add_new("tensor_query_serversrc", host="127.0.0.1",
                          port=port, id=0, dims="2:1", types="float32")
        filt = sp.add_new("tensor_filter", model=lambda x: x + 1)
        ssink = sp.add_new("tensor_query_serversink", id=0)
        Pipeline.link(ssrc, filt, ssink)
        sp.start()
        try:
            time.sleep(0.2)
            register_node("addone", "127.0.0.1", port, broker_port=broker.port)
            cp = Pipeline("client")
            src = cp.add_new("appsrc", caps=caps_of("2:1", "float32"),
                             data=[np.zeros((1, 2), np.float32)])
            qc = cp.add_new("tensor_query_client", operation="addone",
                            broker_port=broker.port)
            sink = cp.add_new("tensor_sink", store=True)
            Pipeline.link(src, qc, sink)
            cp.run(timeout=60)
            np.testing.assert_array_equal(sink.buffers[0].memories[0].host(),
                                          np.ones((1, 2), np.float32))
        finally:
            sp.stop()
            broker.stop()


class TestMultiProcess:
    def test_server_in_separate_process(self, tmp_path):
        """True cross-process offload (reference runs server & client as
        separate gst-launch processes)."""
        import subprocess
        import sys

        port = free_port()
        server_code = f"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import sys
sys.path.insert(0, {repr(str(tmp_path.parent))})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from nnstreamer_tpu.graph import Pipeline
p = Pipeline()
ssrc = p.add_new("tensor_query_serversrc", host="127.0.0.1", port={port},
                 id=0, dims="3:1", types="float32")
f = p.add_new("tensor_filter", model=lambda x: -x)
ssink = p.add_new("tensor_query_serversink", id=0)
Pipeline.link(ssrc, f, ssink)
p.start()
print("READY", flush=True)
import time
time.sleep(20)
p.stop()
"""
        import os

        env = dict(os.environ)
        env["PYTHONPATH"] = "/root/repo"
        proc = subprocess.Popen([sys.executable, "-u", "-c", server_code],
                                stdout=subprocess.PIPE, env=env, text=True)
        try:
            line = proc.stdout.readline()
            assert "READY" in line
            cp = Pipeline("client")
            src = cp.add_new("appsrc", caps=caps_of("3:1", "float32"),
                             data=[np.full((1, 3), 4.0, np.float32)])
            qc = cp.add_new("tensor_query_client", host="127.0.0.1", port=port)
            sink = cp.add_new("tensor_sink", store=True)
            Pipeline.link(src, qc, sink)
            cp.run(timeout=60)
            np.testing.assert_array_equal(sink.buffers[0].memories[0].host(),
                                          np.full((1, 3), -4.0, np.float32))
        finally:
            proc.terminate()
            proc.wait(timeout=10)


class TestGrpc:
    def test_push_sink_to_server_src(self):
        pytest.importorskip("grpc")
        sp = Pipeline("grpc-server")
        gsrc = sp.add_new("tensor_grpc_src", port=0, server=True)
        ssink = sp.add_new("tensor_sink", store=True)
        Pipeline.link(gsrc, ssink)
        sp.start()
        try:
            time.sleep(0.3)
            port = gsrc.bound_port
            cp = Pipeline("grpc-client")
            src = cp.add_new("appsrc", caps=caps_of("3:1", "float32"),
                             data=[np.full((1, 3), i, np.float32)
                                   for i in range(4)])
            gsink = cp.add_new("tensor_grpc_sink", port=port, server=False)
            Pipeline.link(src, gsink)
            cp.run(timeout=30)
            deadline = time.monotonic() + 10
            while ssink.num_buffers < 4 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert ssink.num_buffers == 4
            np.testing.assert_array_equal(
                ssink.buffers[2].memories[0].host(),
                np.full((1, 3), 2.0, np.float32))
        finally:
            sp.stop()


class TestPubSub:
    def test_mqtt_style_pubsub(self):
        from nnstreamer_tpu.query.pubsub import PubSubBroker

        broker = PubSubBroker(port=0).start()
        try:
            rp = Pipeline("subscriber")
            msrc = rp.add_new("mqttsrc", port=broker.port, sub_topic="cam0")
            rsink = rp.add_new("tensor_sink", store=True)
            Pipeline.link(msrc, rsink)
            rp.start()
            time.sleep(0.3)
            tp = Pipeline("publisher")
            src = tp.add_new("appsrc", caps=caps_of("2:1", "float32"),
                             data=[np.full((1, 2), i, np.float32)
                                   for i in range(3)])
            msink = tp.add_new("mqttsink", port=broker.port, pub_topic="cam0")
            Pipeline.link(src, msink)
            tp.run(timeout=30)
            deadline = time.monotonic() + 10
            while rsink.num_buffers < 3 and time.monotonic() < deadline:
                time.sleep(0.05)
            rp.stop()
            assert rsink.num_buffers == 3
            assert rsink.buffers[0].meta["mqtt_latency_ns"] >= 0
        finally:
            broker.stop()
