"""TFLite importer: serve the reference's own .tflite model files.

Golden parity with the reference's tflite pipelines
(tests/nnstreamer_filter_tensorflow2_lite/runTest.sh:69-76: orange.png
through mobilenet quant must classify as "orange"; add.tflite adds 2.0):
the flatbuffer is parsed from scratch and lowered to XLA
(models/tflite_import.py), no TFLite runtime involved.
"""

import os

import numpy as np
import pytest

from nnstreamer_tpu.filters.base import detect_framework, find_filter
from nnstreamer_tpu.graph import Pipeline
from nnstreamer_tpu.models.tflite_import import load_tflite, parse_tflite

MODELS = "/root/reference/tests/test_models/models"
DATA = "/root/reference/tests/test_models/data"
LABELS = "/root/reference/tests/test_models/labels/labels.txt"

needs_ref = pytest.mark.skipif(
    not os.path.isdir(MODELS), reason="reference test models not mounted")


@needs_ref
def test_parse_add_tflite_structure():
    m = parse_tflite(os.path.join(MODELS, "add.tflite"))
    assert [op.op for op in m.operators] == ["ADD"]
    assert len(m.inputs) == 1 and len(m.outputs) == 1
    assert m.tensors[m.inputs[0]].np_dtype == np.float32


@needs_ref
def test_add_tflite_adds_two():
    import jax

    bundle = load_tflite(os.path.join(MODELS, "add.tflite"))
    (out,) = jax.jit(bundle.fn())(np.array([1.5], np.float32))
    assert np.allclose(np.asarray(out), [3.5])


@needs_ref
def test_mobilenet_quant_io_contract_matches_reference_caps():
    bundle = load_tflite(
        os.path.join(MODELS, "mobilenet_v2_1.0_224_quant.tflite"))
    # the caps the reference tflite subplugin reports via getModelInfo
    assert bundle.in_info[0].dim_string == "3:224:224:1"
    assert str(bundle.in_info[0].dtype) == "uint8"
    assert bundle.out_info[0].dim_string == "1001:1"
    assert str(bundle.out_info[0].dtype) == "uint8"


@needs_ref
def test_mobilenet_quant_classifies_orange_e2e():
    """The reference's golden tflite pipeline, unmodified semantics:
    orange.png -> converter -> tensor_filter framework=tensorflow-lite
    model=mobilenet_v2_1.0_224_quant.tflite -> image_labeling -> "orange"."""
    p = Pipeline()
    src = p.add_new("imagefilesrc",
                    location=os.path.join(DATA, "orange.png"))
    conv = p.add_new("tensor_converter")
    filt = p.add_new(
        "tensor_filter", framework="tensorflow-lite",
        model=os.path.join(MODELS, "mobilenet_v2_1.0_224_quant.tflite"))
    dec = p.add_new("tensor_decoder", mode="image_labeling", option1=LABELS)
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, conv, filt, dec, sink)
    p.run(timeout=300)
    assert sink.num_buffers == 1
    label = bytes(sink.buffers[0].memories[0].host()).decode().strip("\x00")
    assert label == "orange"


@needs_ref
def test_mobilenet_quant_orange_margin():
    """Top-1 well separated (reference interpreter gives ~0.93 softmax;
    dequantized-float + fake-quant execution must keep a clear margin)."""
    import jax

    bundle = load_tflite(
        os.path.join(MODELS, "mobilenet_v2_1.0_224_quant.tflite"))
    img = np.fromfile(os.path.join(DATA, "orange.raw"),
                      np.uint8).reshape(1, 224, 224, 3)
    (out,) = jax.jit(bundle.fn())(img)
    scores = np.asarray(out).reshape(-1)
    labels = open(LABELS).read().splitlines()
    top = int(scores.argmax())
    assert labels[top] == "orange"
    second = int(np.argsort(scores)[-2])
    assert int(scores[top]) - int(scores[second]) >= 20


@needs_ref
def test_deeplab_tflite_runs_full_resolution():
    import jax

    bundle = load_tflite(
        os.path.join(MODELS, "deeplabv3_257_mv_gpu.tflite"))
    assert bundle.in_info[0].shape == (1, 257, 257, 3)
    x = np.zeros((1, 257, 257, 3), np.float32)
    (out,) = jax.jit(bundle.fn())(x)
    assert out.shape == (1, 257, 257, 21)
    assert out.dtype == np.float32


@needs_ref
def test_tflite_extension_autodetects_xla():
    path = os.path.join(MODELS, "add.tflite")
    assert detect_framework(path) == "xla-tpu"
    # reference framework names route to the same backend
    for alias in ("tensorflow-lite", "tensorflow2-lite", "tflite"):
        assert find_filter(alias) is not None


def test_corrupt_tflite_rejected(tmp_path):
    bad = tmp_path / "bad.tflite"
    bad.write_bytes(b"NOTAFLATBUFFERATALL")
    with pytest.raises(ValueError, match="TFL"):
        parse_tflite(str(bad))


def test_truncated_tflite_rejected(tmp_path):
    bad = tmp_path / "tiny.tflite"
    bad.write_bytes(b"\x00")
    with pytest.raises(ValueError):
        parse_tflite(str(bad))


@needs_ref
def test_singleshot_serves_tflite():
    """Reference C-API analog: SingleShot invoke on a .tflite file
    (tensor_filter_single semantics, no pipeline)."""
    from nnstreamer_tpu.single import SingleShot

    s = SingleShot(framework="tensorflow-lite",
                   model=os.path.join(
                       MODELS, "mobilenet_v2_1.0_224_quant.tflite"))
    img = np.fromfile(os.path.join(DATA, "orange.raw"),
                      np.uint8).reshape(1, 224, 224, 3)
    (out,) = s.invoke(img)
    labels = open(LABELS).read().splitlines()
    assert labels[int(np.asarray(out).reshape(-1).argmax())] == "orange"


def test_per_channel_quantized_io_clear_error(tmp_path):
    """Graph I/O (de/re)quantization is per-tensor only; a per-channel
    I/O tensor must fail with a descriptive error, not a trace-time crash."""
    import sys as _sys

    _sys.path.insert(0, os.path.dirname(__file__))
    from test_tflite_ops import UINT8, build_tflite

    blob = build_tflite(
        tensors=[
            {"shape": (1, 2, 2, 2), "type": UINT8, "data": None,
             "quant": (np.array([0.1, 0.2], np.float32),
                       np.array([0, 0], np.int64), 3)},
            {"shape": (1, 2, 2, 2), "type": UINT8, "data": None,
             "quant": (0.1, 0)},
        ],
        operators=[{"code": 0, "inputs": [0, 1], "outputs": [1],
                    "options": None}],
        inputs=[0], outputs=[1])
    # an ADD with itself is irrelevant; the I/O quant check fires first —
    # at LOAD time (load_tflite is the documented compatibility test)
    path = tmp_path / "pc_io.tflite"
    path.write_bytes(blob)
    with pytest.raises(NotImplementedError, match="per-channel"):
        load_tflite(str(path))
