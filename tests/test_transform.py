"""tensor_transform op tests (mirrors reference unittest_plugins transform
coverage incl. orc kernel semantics — here XLA)."""

import numpy as np
import pytest

from nnstreamer_tpu.ops import transform_ops as T
from nnstreamer_tpu.core import TensorDType, TensorInfo


def apply(tr, x):
    import jax

    return np.asarray(jax.jit(tr.fn)(x))


class TestTypecast:
    def test_u8_to_f32(self):
        tr = T.build("typecast", "float32")
        x = np.array([0, 128, 255], np.uint8)
        y = apply(tr, x)
        assert y.dtype == np.float32
        np.testing.assert_array_equal(y, [0.0, 128.0, 255.0])

    def test_out_info(self):
        tr = T.build("typecast", "int16")
        info = tr.out_info(TensorInfo.from_strings("4:4", "float32"))
        assert info.dtype is TensorDType.INT16
        assert info.dims == (4, 4)


class TestArithmetic:
    def test_mobilenet_normalize(self):
        # the canonical reference chain: typecast + normalize to [-1,1]
        tr = T.build("arithmetic", "typecast:float32,add:-127.5,div:127.5")
        x = np.array([0, 127.5, 255], np.float32).astype(np.uint8)
        y = apply(tr, np.array([0, 128, 255], np.uint8))
        np.testing.assert_allclose(y, [(v - 127.5) / 127.5 for v in [0, 128, 255]],
                                   rtol=1e-6)

    def test_chain_order(self):
        tr = T.build("arithmetic", "typecast:float32,mul:2.0,add:1.0")
        y = apply(tr, np.array([1.0, 2.0], np.float32))
        np.testing.assert_array_equal(y, [3.0, 5.0])

    def test_per_channel_values(self):
        tr = T.build("arithmetic", "typecast:float32,add:1;10;100")
        x = np.zeros((2, 3), np.float32)
        y = apply(tr, x)
        np.testing.assert_array_equal(y[0], [1, 10, 100])

    def test_bad_op(self):
        with pytest.raises(ValueError):
            T.build("arithmetic", "pow:2")


class TestTranspose:
    def test_hwc_to_chw(self):
        # reference option "1:2:0:3" maps [C:W:H:N] -> [W:H:C:N]
        tr = T.build("transpose", "1:2:0:3")
        x = np.arange(24, dtype=np.float32).reshape(1, 2, 3, 4)  # N,H,W,C
        y = apply(tr, x)
        # out nns dims: (W,H,C,N) -> row-major (N,C,H,W)
        np.testing.assert_array_equal(y, np.transpose(x, (0, 3, 1, 2)))

    def test_out_info(self):
        tr = T.build("transpose", "1:2:0:3")
        info = tr.out_info(TensorInfo.from_strings("3:20:10:1", "uint8"))
        assert info.dims == (20, 10, 3, 1)

    def test_invalid_perm(self):
        with pytest.raises(ValueError):
            T.build("transpose", "0:0:1:2")


class TestDimchg:
    def test_chw_from_hwc(self):
        # reference dimchg 0:2 : innermost dim (channels) → position 2
        tr = T.build("dimchg", "0:2")
        x = np.arange(24, dtype=np.float32).reshape(1, 2, 3, 4)
        y = apply(tr, x)
        assert y.shape == (1, 4, 2, 3)
        info = tr.out_info(TensorInfo.from_strings("4:3:2:1", "float32"))
        assert info.dims == (3, 2, 4, 1)


class TestStand:
    def test_default(self):
        tr = T.build("stand", "default")
        x = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
        y = apply(tr, x)
        np.testing.assert_allclose(y.mean(), 0, atol=1e-6)
        np.testing.assert_allclose(y.std(), 1, atol=1e-4)

    def test_dc_average(self):
        tr = T.build("stand", "dc-average")
        x = np.array([1.0, 3.0], np.float32)
        y = apply(tr, x)
        np.testing.assert_allclose(y, [-1.0, 1.0])

    def test_per_channel(self):
        tr = T.build("stand", "default:per-channel")
        x = np.random.default_rng(0).normal(5, 3, (8, 4)).astype(np.float32)
        y = apply(tr, x)
        np.testing.assert_allclose(y.mean(axis=0), 0, atol=1e-4)


class TestClamp:
    def test_clamp(self):
        tr = T.build("clamp", "0:1")
        y = apply(tr, np.array([-5.0, 0.5, 7.0], np.float32))
        np.testing.assert_array_equal(y, [0.0, 0.5, 1.0])

    def test_bad_range(self):
        with pytest.raises(ValueError):
            T.build("clamp", "1:0")


class TestCompose:
    def test_fused_chain(self):
        chain = T.compose([T.build("typecast", "float32"),
                           T.build("arithmetic", "mul:3.0"),
                           T.build("clamp", "0:100")])
        y = apply(chain, np.array([1, 50], np.uint8))
        np.testing.assert_array_equal(y, [3.0, 100.0])


class TestTransformElement:
    def test_in_pipeline_device_resident(self):
        from nnstreamer_tpu.graph import Pipeline
        from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo

        p = Pipeline()
        src = p.add_new(
            "appsrc",
            caps=Caps.tensors(TensorsConfig(TensorsInfo.from_strings("4", "uint8"), 30)),
            data=[np.array([0, 50, 100, 200], np.uint8)])
        t = p.add_new("tensor_transform", mode="arithmetic",
                      option="typecast:float32,add:-127.5,div:127.5")
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, t, sink)
        p.run(timeout=20)
        out = sink.buffers[0]
        assert out.memories[0].is_device  # stayed on device
        assert out.config.info[0].dtype is TensorDType.FLOAT32
        np.testing.assert_allclose(
            out.memories[0].host(),
            (np.array([0, 50, 100, 200], np.float32) - 127.5) / 127.5)

    def test_transform_chain_fused(self):
        from nnstreamer_tpu.graph import Pipeline
        from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo

        p = Pipeline()
        src = p.add_new(
            "appsrc",
            caps=Caps.tensors(TensorsConfig(TensorsInfo.from_strings("2:2", "float32"), 0)),
            data=[np.ones((2, 2), np.float32)])
        t = p.add_new("tensor_transform",
                      transform_chain=[("arithmetic", "mul:4.0"),
                                       ("transpose", "1:0"),
                                       ("clamp", "0:3")])
        sink = p.add_new("tensor_sink", store=True)
        Pipeline.link(src, t, sink)
        p.run(timeout=20)
        np.testing.assert_array_equal(sink.buffers[0].memories[0].host(),
                                      np.full((2, 2), 3.0, np.float32))
