"""tensor_converter media-type matrices (reference tensor_converter.c
parsers: video :1385, audio :1480, text :1564, octet :1634 + SSAT
nnstreamer_converter groups)."""

from fractions import Fraction

import numpy as np
import pytest

from nnstreamer_tpu.core import Caps, TensorsConfig, TensorsInfo
from nnstreamer_tpu.core.types import AUDIO_FORMATS, VIDEO_FORMATS
from nnstreamer_tpu.graph import Pipeline


def run_conv(caps, data, **conv_props):
    p = Pipeline()
    src = p.add_new("appsrc", caps=caps, data=data)
    conv = p.add_new("tensor_converter", **conv_props)
    sink = p.add_new("tensor_sink", store=True)
    Pipeline.link(src, conv, sink)
    p.run(timeout=30)
    return sink


class TestVideoFormatMatrix:
    @pytest.mark.parametrize("fmt", sorted(VIDEO_FORMATS))
    def test_every_video_format(self, fmt):
        ch, dt = VIDEO_FORMATS[fmt]
        w, h = 6, 4
        frame = (np.arange(h * w * ch) % 251).astype(dt).reshape(h, w, ch)
        caps = Caps("video/x-raw", {"format": fmt, "width": w, "height": h,
                                    "framerate": Fraction(30, 1)})
        sink = run_conv(caps, [frame])
        out = sink.buffers[0].memories[0].host()
        # 3/1-channel paths emit (H,W,C); stride-padded 4-channel paths go
        # through the padding-removal reshape and emit (1,H,W,C) — both
        # carry dims C:W:H
        np.testing.assert_array_equal(out.reshape(h, w, ch), frame)
        cfg = sink.sink_pad.caps.to_config()
        # dims innermost-first: C:W:H (batch handled by frames-per-tensor)
        assert cfg.info[0].dims[0] == ch
        assert cfg.info[0].dtype.np_dtype == dt

    def test_frames_per_tensor_video(self):
        w, h = 4, 4
        frames = [np.full((h, w, 3), i, np.uint8) for i in range(6)]
        caps = Caps("video/x-raw", {"format": "RGB", "width": w, "height": h,
                                    "framerate": Fraction(30, 1)})
        sink = run_conv(caps, frames, frames_per_tensor=3)
        assert sink.num_buffers == 2
        got = sink.buffers[0].memories[0].host()
        assert got.shape == (3, h, w, 3)
        for i in range(3):
            np.testing.assert_array_equal(got[i], frames[i])


class TestAudioFormatMatrix:
    @pytest.mark.parametrize("fmt", sorted(AUDIO_FORMATS))
    def test_every_audio_format(self, fmt):
        dt = AUDIO_FORMATS[fmt]
        samples = np.arange(32, dtype=dt).reshape(32, 1)
        caps = Caps("audio/x-raw", {"format": fmt, "rate": 16000,
                                    "channels": 1})
        sink = run_conv(caps, [samples])
        out = sink.buffers[0].memories[0].host()
        np.testing.assert_array_equal(out.reshape(-1), samples.reshape(-1))
        assert out.dtype == dt

    def test_stereo_channels(self):
        samples = np.arange(16, dtype=np.int16).reshape(8, 2)
        caps = Caps("audio/x-raw", {"format": "S16LE", "rate": 8000,
                                    "channels": 2})
        sink = run_conv(caps, [samples])
        cfg = sink.sink_pad.caps.to_config()
        assert cfg.info[0].dims[0] == 2  # channels innermost


class TestTextAndOctet:
    def test_text_fixed_size_padding(self):
        caps = Caps("text/x-raw", {"format": "utf8"})
        sink = run_conv(caps, [np.frombuffer(b"hi", np.uint8)],
                        input_dim="8")
        out = sink.buffers[0].memories[0].host()
        assert out.size == 8  # zero-padded to the fixed text size
        assert bytes(out.reshape(-1)[:2].tobytes()) == b"hi"

    def test_octet_typed_reinterpret(self):
        payload = np.frombuffer(np.arange(6, dtype=np.float32).tobytes(),
                                np.uint8)
        caps = Caps("application/octet-stream")
        sink = run_conv(caps, [payload], input_dim="3:2",
                        input_type="float32")
        out = sink.buffers[0].memories[0].host()
        np.testing.assert_array_equal(out.reshape(-1),
                                      np.arange(6, dtype=np.float32))
