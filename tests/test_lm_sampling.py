"""On-device sampling (serving/sampling.py + LMEngine sampled decode).

Contracts pinned here:
- defaults are greedy and bit-identical to isolated greedy generation;
- top_k=1 degenerates to greedy at any temperature;
- sampled streams are reproducible (seeded) and independent of batch
  composition / chunking (the fold_in(seed, consumed) key schedule);
- the sampler's keep-sets honor top-k and nucleus cuts, and its draw
  frequencies track the softmax distribution.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from nnstreamer_tpu.models import causal_lm
from nnstreamer_tpu.serving import LMEngine
from nnstreamer_tpu.serving import sampling

V, D, H, L, MAXLEN = 97, 32, 4, 2, 64


@pytest.fixture(scope="module")
def params():
    return causal_lm.init_causal_lm(
        jax.random.PRNGKey(7), V, D, H, L, MAXLEN)


def prompts_rng(n, lo=1, hi=24, seed=0):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, V, rng.integers(lo, hi)).astype(np.int32)
            for _ in range(n)]


def solo_run(params, prompt, max_new, **kw):
    """Isolated-run oracle: a 1-slot engine (chunk=1, exact bucketing is
    irrelevant to the contract — sampling keys depend only on consumed
    count and seed, which this also exercises)."""
    eng = LMEngine(params, H, MAXLEN, n_slots=1, chunk=1)
    rid = eng.submit(prompt, max_new, **kw)
    return eng.run()[rid]


# -- sampler unit behavior (synthetic logits) ----------------------------- #

def _draws(logits_row, n, temperature=1.0, top_k=0, top_p=1.0, seed=3):
    row = jnp.asarray(logits_row, jnp.float32)
    keys = jax.random.split(jax.random.PRNGKey(seed), n)
    toks = jax.vmap(lambda k: sampling.sample_row(
        row, k, jnp.float32(temperature), jnp.int32(top_k),
        jnp.float32(top_p)))(keys)
    return np.asarray(toks)


def test_topk_draws_stay_in_topk_set():
    logits = np.array([5.0, 4.0, 3.0, 2.0, 1.0, 0.0])
    toks = _draws(logits, 300, temperature=2.0, top_k=3)
    assert set(toks.tolist()) <= {0, 1, 2}
    assert len(set(toks.tolist())) > 1  # actually sampling, not argmax


def test_topp_keeps_minimal_prefix():
    # probs ~ [0.5, 0.3, 0.15, 0.05]; top_p=0.7 keeps {0, 1} only
    probs = np.array([0.5, 0.3, 0.15, 0.05])
    logits = np.log(probs)
    toks = _draws(logits, 300, temperature=1.0, top_p=0.7)
    assert set(toks.tolist()) <= {0, 1}
    assert len(set(toks.tolist())) == 2


def test_temperature_zero_is_argmax_and_frequencies_track_softmax():
    logits = np.array([1.0, 2.0, 0.5, 1.5])
    assert (_draws(logits, 50, temperature=0.0) == 1).all()
    toks = _draws(logits, 4000, temperature=1.0, seed=11)
    freq = np.bincount(toks, minlength=4) / 4000.0
    want = np.exp(logits) / np.exp(logits).sum()
    assert np.abs(freq - want).max() < 0.05


def test_disabled_filters_match_plain_softmax_sampling():
    # top_k=0 / top_p=1 must not perturb the categorical draw
    logits = np.array([0.3, -1.2, 2.0, 0.0, 1.1])
    a = _draws(logits, 64, temperature=1.3, top_k=0, top_p=1.0, seed=5)
    key = jax.random.PRNGKey(5)
    b = np.asarray(jax.vmap(lambda k: jax.random.categorical(
        k, jnp.asarray(logits / 1.3, jnp.float32)))(
            jax.random.split(key, 64)))
    assert (a == b).all()


def test_disabled_topp_keeps_saturated_tail_drawable():
    # peaked distribution over a big vocab: the float32 cumsum hits 1.0
    # after a couple of entries; disabled top_p must still keep the
    # sub-1e-7 tail bit-identical to a plain categorical draw
    logits = np.full(4096, -20.0)
    logits[:2] = [10.0, 0.0]
    a = _draws(logits, 256, temperature=1.0, top_k=0, top_p=1.0, seed=13)
    b = np.asarray(jax.vmap(lambda k: jax.random.categorical(
        k, jnp.asarray(logits, jnp.float32)))(
            jax.random.split(jax.random.PRNGKey(13), 256)))
    assert (a == b).all()


# -- engine-level contracts ---------------------------------------------- #

def test_default_submit_is_greedy_unchanged(params):
    prompt = prompts_rng(1, lo=5, hi=6)[0]
    eng = LMEngine(params, H, MAXLEN, n_slots=2, chunk=4)
    rid = eng.submit(prompt, max_new=12)
    got = eng.run()[rid]
    # greedy oracle: unpadded prefill + step-at-a-time argmax
    logits, kc, vc, pos = causal_lm.lm_prefill(
        params, jnp.asarray(prompt[None]), H, MAXLEN)
    out = [int(jnp.argmax(logits[0]))]
    while len(out) < 12:
        tok = jnp.asarray([[out[-1]]], jnp.int32)
        logits, kc, vc, pos = causal_lm.lm_decode_step(
            params, tok, kc, vc, pos, H)
        out.append(int(jnp.argmax(logits[0])))
    assert got == out


def test_topk1_equals_greedy_any_temperature(params):
    prompt = prompts_rng(1, lo=8, hi=9, seed=2)[0]
    greedy = solo_run(params, prompt, 10)
    hot = solo_run(params, prompt, 10, temperature=5.0, top_k=1, seed=9)
    assert hot == greedy


def test_sampled_reproducible_and_seed_sensitive(params):
    prompt = prompts_rng(1, lo=6, hi=7, seed=3)[0]
    a = solo_run(params, prompt, 16, temperature=1.0, seed=41)
    b = solo_run(params, prompt, 16, temperature=1.0, seed=41)
    c = solo_run(params, prompt, 16, temperature=1.0, seed=42)
    assert a == b
    assert a != c  # 16 draws over V=97 colliding fully is ~impossible


def test_batched_sampling_matches_isolated(params):
    """The exactness contract extended to sampled decoding: output
    depends only on (request, seed), not slots/admission/chunking."""
    prompts = prompts_rng(6, seed=4)
    modes = [dict(temperature=1.0, seed=10),
             dict(),  # greedy in the same batch
             dict(temperature=0.7, top_k=8, seed=11),
             dict(temperature=1.3, top_p=0.9, seed=12),
             dict(temperature=0.9, top_k=20, top_p=0.8, seed=13),
             dict(temperature=2.0, seed=10)]
    eng = LMEngine(params, H, MAXLEN, n_slots=3, chunk=5)
    rids = [eng.submit(p, max_new=7 + i, **m)
            for i, (p, m) in enumerate(zip(prompts, modes))]
    res = eng.run()
    for i, (rid, p, m) in enumerate(zip(rids, prompts, modes)):
        assert res[rid] == solo_run(params, p, 7 + i, **m), f"req {i}"


def test_sampled_eos_stops_stream(params):
    prompt = prompts_rng(1, lo=6, hi=7, seed=8)[0]
    ref = solo_run(params, prompt, 24, temperature=1.1, seed=3)
    eos = ref[len(ref) // 2]  # a token the sampled stream will emit
    got = solo_run(params, prompt, 24, eos=eos, temperature=1.1, seed=3)
    assert got == ref[:ref.index(eos) + 1]
